"""Rollout flight recorder (tpu_cc_manager/obs/flight.py) + cross-
process trace stitching.

The acceptance bars (ISSUE 12):

- a kill-at-a-crash-point rollout followed by ``--resume`` yields ONE
  flight-recorder timeline from which ``ctl rollout-timeline``
  reconstructs every wave/window/node event exactly once, with zero
  torn JSONL lines;
- a single trace id links the orchestrator's rollout span to a node
  agent's reconcile span (real fake-pool agents, real watch loops);
- ``ctl status`` surfaces the last-reconcile trace id as a TRACE
  column.

The crash/resume suite is chaos-marked and prints the OBS_SUMMARY line
hack/chaos_soak.sh scrapes (events written/replayed, torn lines).
"""

from __future__ import annotations

import json
import os
import threading

import pytest

from tpu_cc_manager.ccmanager import rollout_state
from tpu_cc_manager.ccmanager.rolling import RollingReconfigurator
from tpu_cc_manager.faults.plan import OrchestratorKilled
from tpu_cc_manager.kubeclient.api import node_labels
from tpu_cc_manager.kubeclient.fake import FakeKube
from tpu_cc_manager.labels import CC_MODE_LABEL, CC_MODE_STATE_LABEL
from tpu_cc_manager.obs import flight as flight_mod
from tpu_cc_manager.utils.metrics import MetricsRegistry

POOL = "pool=tpu"
NS = "tpu-operator"


class Clock:
    def __init__(self, t: float = 1000.0) -> None:
        self.t = t

    def __call__(self) -> float:
        return self.t

    def advance(self, s: float) -> None:
        self.t += s


def add_pool(fake, n=4, slice_map=None):
    for i in range(n):
        labels = {"pool": "tpu"}
        if slice_map and i in slice_map:
            labels["cloud.google.com/tpu-slice-id"] = slice_map[i]
        fake.add_node(f"node-{i}", labels)


def agent_simulator(fake, fail_nodes=()):
    in_flight = set()

    def reactor(name, node):
        desired = node_labels(node).get(CC_MODE_LABEL)
        state = node_labels(node).get(CC_MODE_STATE_LABEL)
        if desired and state != desired and name not in in_flight:
            in_flight.add(name)

            def fire():
                target = "failed" if name in fail_nodes else desired
                in_flight.discard(name)
                fake.set_node_label(name, CC_MODE_STATE_LABEL, target)

            t = threading.Timer(0.03, fire)
            t.daemon = True
            t.start()

    fake.add_patch_reactor(reactor)


def make_lease(fake, holder, clk, metrics=None, duration_s=30.0):
    return rollout_state.RolloutLease(
        fake, holder=holder, namespace=NS, duration_s=duration_s,
        metrics=metrics or MetricsRegistry(), wall=clk, clock=clk,
    )


def make_roller(fake, **kw):
    kw.setdefault("node_timeout_s", 5)
    kw.setdefault("poll_interval_s", 0.02)
    kw.setdefault("metrics", MetricsRegistry())
    return RollingReconfigurator(fake, POOL, **kw)


# ---------------------------------------------------------------------------
# Recorder mechanics
# ---------------------------------------------------------------------------


def test_recorder_appends_flushed_jsonl_and_reads_back(tmp_path):
    path = str(tmp_path / "f.jsonl")
    fr = flight_mod.FlightRecorder(path, generation=3, trace_id="abc")
    fr.record("plan", mode="on", groups=2)
    fr.record("window-open", wave=0, window=0)
    events, torn = flight_mod.read_events(path)
    assert torn == 0
    assert [e["event"] for e in events] == ["plan", "window-open"]
    assert events[0]["gen"] == 3
    assert events[0]["trace_id"] == "abc"
    assert [e["seq"] for e in events] == [1, 2]


def test_torn_tail_is_tolerated_and_counted(tmp_path):
    path = str(tmp_path / "f.jsonl")
    fr = flight_mod.FlightRecorder(path)
    fr.record("plan", mode="on")
    fr.record("complete", ok=True)
    # A SIGKILL mid-write tears the final line.
    with open(path, "a", encoding="utf-8") as f:
        f.write('{"event": "window-open", "truncat')
    events, torn = flight_mod.read_events(path)
    assert [e["event"] for e in events] == ["plan", "complete"]
    assert torn == 1


def test_successor_continues_the_sequence(tmp_path):
    path = str(tmp_path / "f.jsonl")
    a = flight_mod.FlightRecorder(path)
    a.record("plan")
    a.record("halt", reason="x")
    b = flight_mod.FlightRecorder(path)  # the resumed orchestrator
    b.record("resume")
    events, _ = flight_mod.read_events(path)
    assert [e["seq"] for e in events] == [1, 2, 3]


def test_missing_file_is_an_empty_timeline(tmp_path):
    events, torn = flight_mod.read_events(str(tmp_path / "nope.jsonl"))
    assert events == [] and torn == 0


def test_write_failure_degrades_without_raising(tmp_path):
    fr = flight_mod.FlightRecorder(str(tmp_path / "dir-not-file"))
    os.makedirs(str(tmp_path / "dir-not-file"))
    fr.record("plan")  # open() fails; must not raise
    assert fr.events_written == 0


def test_reconstruct_sorts_mixed_int_and_string_wave_ids():
    """A surge (wave="surge") or adoption (wave="adopt") rollout also
    emits numeric waves; the reconstruction must render, not TypeError
    on int-vs-str comparison."""
    events = [
        {"event": "window-open", "wave": "surge", "window": 0,
         "groups": ["g0"]},
        {"event": "window-close", "wave": "surge", "window": 0,
         "seconds": 1.0},
        {"event": "window-open", "wave": 0, "window": 0, "groups": ["g1"]},
        {"event": "window-close", "wave": 0, "window": 0, "seconds": 0.5},
        {"event": "window-open", "wave": "adopt", "window": 0,
         "groups": ["g2"]},
    ]
    rec = flight_mod.reconstruct(events)
    waves = [w["wave"] for w in rec["windows"]]
    assert waves == [0, "adopt", "surge"]  # numeric first, then named
    # The human renderer survives the same mix.
    assert "surge" in flight_mod.render_timeline(events)


def test_redrive_of_failed_node_is_not_a_duplicate():
    """The designed resume path re-drives a FAILED group after the
    operator re-runs the rollout; the later real terminal supersedes
    (flagged `redriven`), while a second real drive of a CONVERGED node
    stays a forbidden duplicate."""
    redrive = [
        {"event": "node-failed", "node": "n0", "state": "timeout"},
        {"event": "node-converged", "node": "n0", "state": "on"},
    ]
    rec = flight_mod.reconstruct(redrive)
    assert rec["duplicate_node_events"] == []
    assert rec["nodes"]["n0"]["outcome"] == "node-converged"
    assert rec["nodes"]["n0"]["redriven"] is True
    double_bounce = [
        {"event": "node-converged", "node": "n0", "state": "on"},
        {"event": "node-converged", "node": "n0", "state": "on"},
    ]
    rec = flight_mod.reconstruct(double_bounce)
    assert len(rec["duplicate_node_events"]) == 1


def test_snapshot_serves_from_memory_and_counts_prior_events(tmp_path):
    path = str(tmp_path / "f.jsonl")
    a = flight_mod.FlightRecorder(path)
    a.record("plan", mode="on")
    b = flight_mod.FlightRecorder(path)  # successor loads the file once
    b.record("resume")
    snap = b.snapshot()
    assert snap["events_in_file"] == 2
    assert [e["event"] for e in snap["recent"]] == ["plan", "resume"]
    # The snapshot is served from memory: deleting the file under the
    # recorder does not blank a live /rolloutz poll.
    os.unlink(path)
    assert [e["event"] for e in b.snapshot()["recent"]] == [
        "plan", "resume",
    ]


def test_flight_path_for_is_deterministic(monkeypatch, tmp_path):
    monkeypatch.setenv("CC_FLIGHT_DIR", str(tmp_path))
    p1 = flight_mod.flight_path_for("pool=tpu")
    p2 = flight_mod.flight_path_for("pool=tpu")
    assert p1 == p2 and p1.startswith(str(tmp_path))


# ---------------------------------------------------------------------------
# A full rollout writes a reconstructable timeline
# ---------------------------------------------------------------------------


def test_rollout_timeline_covers_every_decision(tmp_path, fake_kube):
    add_pool(fake_kube, 4, slice_map={0: "s1", 1: "s1"})
    agent_simulator(fake_kube)
    flight = flight_mod.FlightRecorder(str(tmp_path / "f.jsonl"))
    roller = make_roller(fake_kube, flight=flight)
    result = roller.rollout("on")
    assert result.ok
    events, torn = flight_mod.read_events(flight.path)
    assert torn == 0
    names = [e["event"] for e in events]
    assert names[0] == "plan"
    assert names[-1] == "complete"
    assert "window-open" in names and "window-close" in names
    # 3 groups (s1 pair + 2 singles) = one desired patch per node.
    desired = [e for e in events if e["event"] == "node-desired-patch"]
    assert sorted(e["node"] for e in desired) == [
        f"node-{i}" for i in range(4)
    ]
    rec = flight_mod.reconstruct(events)
    assert rec["plan"]["mode"] == "on"
    assert set(rec["nodes"]) == {f"node-{i}" for i in range(4)}
    assert all(
        n["outcome"] == "node-converged" for n in rec["nodes"].values()
    )
    assert rec["duplicate_node_events"] == []
    # Every event shares the rollout's trace id.
    assert len({e["trace_id"] for e in events}) == 1


def test_failed_group_and_halt_are_in_the_timeline(tmp_path, fake_kube):
    add_pool(fake_kube, 3)
    agent_simulator(fake_kube, fail_nodes={"node-1"})
    flight = flight_mod.FlightRecorder(str(tmp_path / "f.jsonl"))
    roller = make_roller(fake_kube, flight=flight)
    result = roller.rollout("on")
    assert not result.ok
    events, _ = flight_mod.read_events(flight.path)
    rec = flight_mod.reconstruct(events)
    assert rec["nodes"]["node-1"]["outcome"] == "node-failed"
    assert any(h["reason"] == "group-failed" for h in rec["halts"])


# ---------------------------------------------------------------------------
# The acceptance bar: kill + --resume = ONE exactly-once timeline
# ---------------------------------------------------------------------------


def _run_crash_resume_with_flight(kill_at: int, flight_path: str):
    fake = FakeKube()
    add_pool(fake, 4, slice_map={0: "s1", 1: "s1"})
    agent_simulator(fake)
    clk = Clock()
    metrics = MetricsRegistry()
    hook_calls = {"n": 0}

    def killer(point):
        if hook_calls["n"] == kill_at:
            raise OrchestratorKilled(point, hook_calls["n"])
        hook_calls["n"] += 1

    lease_a = make_lease(fake, "orch-a", clk, metrics=metrics)
    lease_a.acquire()
    flight_a = flight_mod.FlightRecorder(
        flight_path, generation=lease_a.generation
    )
    roller_a = make_roller(
        fake, lease=lease_a, crash_hook=killer, flight=flight_a,
    )
    killed = False
    try:
        result = roller_a.rollout("on")
    except OrchestratorKilled:
        killed = True
        clk.advance(31)
        lease_b = make_lease(fake, "orch-b", clk, metrics=metrics)
        record = lease_b.acquire()
        assert record is not None
        # The successor opens the SAME file — one timeline spans the
        # crash (this is exactly what ctl's selector-derived default
        # path does).
        flight_b = flight_mod.FlightRecorder(
            flight_path, generation=lease_b.generation
        )
        roller_b = make_roller(
            fake, lease=lease_b, resume_record=record, metrics=metrics,
            flight=flight_b,
        )
        result = roller_b.rollout(record.mode)
    return killed, result, fake


@pytest.mark.chaos
def test_kill_resume_yields_one_exactly_once_timeline(tmp_path):
    """Kill the orchestrator at EVERY crash point in turn; the combined
    (pre-kill + post-resume) timeline must reconstruct every node's
    outcome exactly once, with zero torn lines and zero real duplicate
    drives, at every kill point."""
    exhausted = False
    total_events = 0
    resumes = 0
    for kill_at in range(32):
        flight_path = str(tmp_path / f"kill{kill_at}.jsonl")
        killed, result, fake = _run_crash_resume_with_flight(
            kill_at, flight_path
        )
        assert result.ok, f"kill_at={kill_at}"
        events, torn = flight_mod.read_events(flight_path)
        total_events += len(events)
        assert torn == 0, f"kill_at={kill_at}: torn lines in the timeline"
        rec = flight_mod.reconstruct(events)
        assert set(rec["nodes"]) == {f"node-{i}" for i in range(4)}, (
            f"kill_at={kill_at}: reconstruction lost a node"
        )
        assert rec["duplicate_node_events"] == [], (
            f"kill_at={kill_at}: node driven twice"
        )
        for name, n in rec["nodes"].items():
            assert n["outcome"] == "node-converged", (
                f"kill_at={kill_at}: {name} -> {n}"
            )
        if killed:
            resumes += 1
            assert rec["resumes"] == 1, f"kill_at={kill_at}"
            assert len(rec["generations"]) == 2, f"kill_at={kill_at}"
        else:
            exhausted = True
            break
    assert exhausted, "never exhausted the crash points; raise the range"
    print("OBS_SUMMARY " + json.dumps({
        "kill_points": kill_at, "resumes": resumes,
        "events_written": total_events, "torn_lines": 0,
    }))


@pytest.mark.chaos
def test_ctl_rollout_timeline_renders_the_crash_spanning_file(
    tmp_path, capsys
):
    flight_path = str(tmp_path / "f.jsonl")
    killed, result, fake = _run_crash_resume_with_flight(4, flight_path)
    assert killed and result.ok
    from tpu_cc_manager import ctl

    class Args:
        flight_file = flight_path
        selector = None
        as_json = False
        trace = False
        spans = None

    assert ctl.cmd_rollout_timeline(fake, Args()) == 0
    out = capsys.readouterr().out
    assert "reconstruction:" in out
    assert "resumes=1" in out
    for i in range(4):
        assert f"node-{i}" in out
    assert "WARNING" not in out  # no torn lines, no duplicates

    Args.as_json = True
    assert ctl.cmd_rollout_timeline(fake, Args()) == 0
    payload = json.loads(capsys.readouterr().out)
    assert payload["torn_lines"] == 0
    assert len(payload["reconstruction"]["nodes"]) == 4


def test_ctl_rollout_timeline_requires_a_source(fake_kube):
    from tpu_cc_manager import ctl

    class Args:
        flight_file = None
        selector = None

    with pytest.raises(ValueError):
        ctl.cmd_rollout_timeline(fake_kube, Args())


# ---------------------------------------------------------------------------
# Cross-process trace stitching: one causal tree, orchestrator -> agent
# ---------------------------------------------------------------------------


def test_rollout_trace_parents_agent_reconcile_spans(tmp_path):
    """The other acceptance bar: across a REAL fake-pool rollout (real
    CCManager watch loops, real drain/reset pipeline), the orchestrator
    trace id appears as the trace of — and the orchestrator span as the
    parent of — each node agent's reconcile root span; the agent
    republishes the trace id to its node annotation; and `ctl status`
    surfaces it as the TRACE column."""
    from tpu_cc_manager import ctl
    from tpu_cc_manager import labels as labels_mod
    from tpu_cc_manager.kubeclient.api import node_annotations
    from tpu_cc_manager.serve.harness import POOL_SELECTOR, ServeHarness
    from tpu_cc_manager.utils import retry as retry_mod

    harness = ServeHarness(n_nodes=2, tmp_dir=str(tmp_path))
    harness.build()
    try:
        flight = flight_mod.FlightRecorder(str(tmp_path / "f.jsonl"))
        roller = RollingReconfigurator(
            harness.kube, POOL_SELECTOR, node_timeout_s=30,
            poll_interval_s=0.02, flight=flight,
        )
        result = roller.rollout("on")
        assert result.ok
        trace_id = flight.trace_id
        assert trace_id

        # The rollout's desired patches stamped <trace>.<span> on the
        # nodes; the root span's identity is recoverable from them.
        stamped = {
            name: node_labels(harness.kube.get_node(name)).get(
                labels_mod.ROLLOUT_TRACE_LABEL
            )
            for name in harness.nodes
        }
        assert all(stamped.values())
        assert all(v.split(".")[0] == trace_id for v in stamped.values())
        rollout_span_ids = {v.split(".")[1] for v in stamped.values()}

        def stitched() -> bool:
            for mgr in harness.agents:
                spans = [
                    s for s in mgr.journal.spans()
                    if s["name"] == "reconcile"
                    and s["trace_id"] == trace_id
                ]
                if not spans:
                    return False
            return True

        assert retry_mod.poll_until(stitched, 10.0, 0.05), (
            "agent reconcile spans never joined the rollout trace"
        )
        for mgr in harness.agents:
            spans = [
                s for s in mgr.journal.spans()
                if s["name"] == "reconcile" and s["trace_id"] == trace_id
            ]
            # The reconcile root's parent IS the orchestrator span that
            # wrote the desired patch — one causal tree.
            assert all(
                s["parent_id"] in rollout_span_ids for s in spans
            ), spans

        # Last-reconcile trace id republished to the node annotation.
        def annotated() -> bool:
            return all(
                node_annotations(harness.kube.get_node(name)).get(
                    labels_mod.TRACE_ID_ANNOTATION
                ) == trace_id
                for name in harness.nodes
            )

        assert retry_mod.poll_until(annotated, 10.0, 0.05)

        # ctl status surfaces it as the TRACE column.
        class Args:
            selector = POOL_SELECTOR
            lease_namespace = None

        import io
        from contextlib import redirect_stdout

        buf = io.StringIO()
        with redirect_stdout(buf):
            assert ctl.cmd_status(harness.kube, Args()) == 0
        out = buf.getvalue()
        assert "TRACE" in out.splitlines()[0]
        assert trace_id in out
    finally:
        harness.shutdown()


def test_unstitched_reconcile_keeps_its_own_root_trace(fake_kube):
    """A reconcile NOT driven by a rollout (no stamped label) must mint
    its own root trace — stitching is opt-in per patch, never sticky
    across pools."""
    from tpu_cc_manager.obs import trace as trace_mod

    assert trace_mod.parse_parent(None) is None
    assert trace_mod.parse_parent("garbled") is None
    assert trace_mod.parse_parent("a.b.c") is None
    assert trace_mod.parse_parent("abc.def") == ("abc", "def")
    with trace_mod.root_span("reconcile") as sp:
        assert sp.parent_id is None
    with trace_mod.root_span("reconcile", parent=("t1", "s1")) as sp:
        assert sp.trace_id == "t1"
        assert sp.parent_id == "s1"
