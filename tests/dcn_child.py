"""Child process for the real 2-process ``jax.distributed`` test.

Run as ``python tests/dcn_child.py <coordinator_port> <process_id>``. Each
of the two processes brings 2 virtual CPU devices, so the pair forms a
4-device global mesh with ``dcn=2`` crossing the process boundary — the
same topology shape as two TPU slices over DCN (BASELINE.json configs[4]),
executed with a REAL coordinator handshake instead of a single-process
virtual mesh (VERDICT r3 item 3).

Success protocol: print ``DCN_CHILD_OK`` and exit 0.
"""

import os
import sys


def main() -> int:
    port, pid = sys.argv[1], sys.argv[2]
    # Env must be set before jax imports; this child is a fresh interpreter.
    os.environ.pop("PALLAS_AXON_POOL_IPS", None)  # never touch the TPU tunnel
    os.environ["JAX_PLATFORMS"] = "cpu"
    os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"
    os.environ["JAX_NUM_PROCESSES"] = "2"
    os.environ["JAX_PROCESS_ID"] = pid
    os.environ["JAX_COORDINATOR_ADDRESS"] = f"127.0.0.1:{port}"

    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import NamedSharding, PartitionSpec as P

    jax.config.update("jax_platforms", "cpu")

    from tpu_cc_manager.parallel.distributed import bootstrap, verify_dcn_mesh
    from tpu_cc_manager.parallel.mesh import MeshSpec, make_mesh

    info = bootstrap(timeout_s=90)
    assert info["initialized"] is True and info["processes"] == 2, info
    assert jax.process_count() == 2
    assert jax.local_device_count() == 2
    assert jax.device_count() == 4

    mesh = make_mesh(MeshSpec(dcn=2, dp=2))
    # The dcn axis must actually cross processes: each dcn row's devices
    # belong to one process.
    dcn_procs = [
        {d.process_index for d in mesh.devices[i].flatten()}
        for i in range(mesh.shape["dcn"])
    ]
    assert dcn_procs == [{0}, {1}], dcn_procs
    assert verify_dcn_mesh(mesh)

    # One cross-process train step: global batch sharded over the data
    # axes, replicated params, gradient reduction crossing the process
    # boundary. Both processes must read back identical results.
    xs = np.arange(8, dtype=np.float32).reshape(8, 1) / 8.0
    ys = 3.0 * xs[:, 0] + 1.0
    data_sh = NamedSharding(mesh, P(("dcn", "dp", "fsdp")))
    rep = NamedSharding(mesh, P())
    xg = jax.make_array_from_callback(xs.shape, data_sh, lambda idx: xs[idx])
    yg = jax.make_array_from_callback(ys.shape, data_sh, lambda idx: ys[idx])
    w0 = jax.make_array_from_callback((), rep, lambda idx: np.float32(0.0))
    b0 = jax.make_array_from_callback((), rep, lambda idx: np.float32(0.0))

    def loss_fn(w, b, xb, yb):
        pred = xb[:, 0] * w + b
        return jnp.mean((pred - yb) ** 2)

    @jax.jit
    def step(w, b, xb, yb):
        loss, (gw, gb) = jax.value_and_grad(loss_fn, argnums=(0, 1))(
            w, b, xb, yb
        )
        return loss, w - 0.5 * gw, b - 0.5 * gb

    loss0, w, b = step(w0, b0, xg, yg)
    loss1, w, b = step(w, b, xg, yg)
    l0, l1 = float(loss0), float(loss1)
    assert np.isfinite(l0) and np.isfinite(l1)
    assert l1 < l0, (l0, l1)  # the cross-process gradient actually applied

    jax.distributed.shutdown()
    print(f"DCN_CHILD_OK pid={pid} losses={l0:.4f}->{l1:.4f}", flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
