"""Fast tier-1 wiring of hack/check_metrics_lint.py: a live registry
render (exercised through the real phase/finish path, hostile label
values included) must pass the Prometheus exposition lint, and the lint
itself must catch each class of regression it exists for."""

from __future__ import annotations

import os
import sys

sys.path.insert(
    0, os.path.join(os.path.dirname(os.path.dirname(__file__)), "hack")
)
import check_metrics_lint  # noqa: E402

from tpu_cc_manager.utils.metrics import MetricsRegistry  # noqa: E402


def test_live_registry_render_passes_lint():
    registry = MetricsRegistry()
    for mode in ("on", "slice", 'odd"mode\nwith\\escapes'):
        m = registry.start(mode)
        for phase in ("drain", "stage", "reset", "wait_ready", "attest"):
            with m.phase(phase):
                pass
        m.finish("ok")
    m = registry.start("off")
    m.result = "failed"
    m.finish("failed")
    registry.record_failure("attestation-failed")
    registry.record_failure('hostile"reason\nhere')
    # Pipelined-transition families: the overlap gauge (auto-fed from
    # finish()) plus explicit smoke fast-path outcomes, hostile included.
    registry.set_phase_overlap_seconds(3.25)
    registry.record_smoke_fastpath("hit")
    registry.record_smoke_fastpath('odd"outcome')
    # Live serving telemetry (tpu_cc_serve_*; serve/ + obs/slo.py),
    # hostile node names included — the per-node histogram must escape
    # like everything else.
    registry.observe_serve_request("serve-node-0", 0.042)
    registry.observe_serve_request('odd"node', 3.0)
    registry.set_serve_queue_depth("serve-node-0", 5)
    registry.set_serve_inflight("serve-node-0", 2)
    registry.record_serve_outcome("serve-node-0", "completed", 3)
    registry.record_serve_outcome("serve-node-0", "bounced")
    registry.record_serve_outcome("serve-node-0", "shed", 4)
    registry.record_serve_lost(2)
    registry.record_serve_deadline_miss("serve-node-0", 6)
    registry.set_serve_offered_rps(640.5)
    registry.record_slo_pause()
    registry.record_slo_pause()
    registry.set_serve_goodput(123.4)
    registry.set_serve_slo(30.0, 0.08, 1.5)
    registry.set_serve_slo(300.0, None, 0.0)  # empty window: burn only
    # Zero-bounce flip families (serve/ handoff + prestage), hostile
    # outcome included.
    registry.record_serve_handoff("accepted", 7)
    registry.record_serve_handoff("fallback")
    registry.record_serve_handoff('odd"outcome\nhere')
    registry.set_spare_prestage_seconds(31.299)
    # Capacity-ledger families (fleet gateway inputs), hostile node
    # included; hbm util is clamped into [0, 1].
    registry.set_serve_hbm_bw_util("serve-node-0", 0.73)
    registry.set_serve_hbm_bw_util('odd"node', 1.7)
    registry.set_prestage_in_progress(True)
    # Fail-slow vetting families (obs/failslow.py), hostile node and
    # verdict labels included.
    registry.set_failslow_suspect("serve-node-0", True)
    registry.set_failslow_suspect('odd"node\nname', False)
    registry.set_failslow_deviation("serve-node-0", 3.4142)
    registry.record_failslow_verdict("serve-node-0", "confirmed")
    registry.record_failslow_verdict("serve-node-0", "confirmed")
    registry.record_failslow_verdict("serve-node-0", "cleared")
    registry.record_failslow_verdict('odd"node', 'odd"verdict')
    problems = check_metrics_lint.lint(registry.render_prometheus())
    assert problems == [], problems
    text = registry.render_prometheus()
    assert "tpu_cc_phase_overlap_seconds" in text
    assert 'tpu_cc_smoke_fastpath_total{outcome="hit"} 1' in text
    assert (
        'tpu_cc_serve_request_seconds_bucket{node="serve-node-0",le="0.05"} 1'
        in text
    )
    assert 'tpu_cc_serve_request_seconds_count{node="serve-node-0"} 1' in text
    assert 'tpu_cc_serve_queue_depth{node="serve-node-0"} 5' in text
    assert 'tpu_cc_serve_inflight{node="serve-node-0"} 2' in text
    assert (
        'tpu_cc_serve_requests_total{node="serve-node-0",outcome="completed"} 3'
        in text
    )
    assert "tpu_cc_serve_lost_total 2" in text
    assert (
        'tpu_cc_serve_requests_total{node="serve-node-0",outcome="shed"} 4'
        in text
    )
    assert 'tpu_cc_serve_deadline_miss_total{node="serve-node-0"} 6' in text
    assert "tpu_cc_serve_offered_rps 640.500" in text
    assert "tpu_cc_rollout_slo_pauses_total 2" in text
    assert "tpu_cc_serve_goodput_rps 123.400" in text
    assert 'tpu_cc_serve_slo_p99_seconds{window="30"} 0.080000' in text
    assert 'tpu_cc_serve_error_budget_burn{window="30"} 1.500000' in text
    assert 'tpu_cc_serve_handoffs_total{outcome="accepted"} 7' in text
    assert 'tpu_cc_serve_handoffs_total{outcome="fallback"} 1' in text
    assert "tpu_cc_spare_prestage_seconds 31.299" in text
    # The empty window exports burn (0) but NO invented p99 sample.
    assert 'tpu_cc_serve_error_budget_burn{window="300"} 0.000000' in text
    assert 'tpu_cc_serve_slo_p99_seconds{window="300"}' not in text
    assert 'tpu_cc_hbm_bw_util{node="serve-node-0"} 0.730000' in text
    assert 'tpu_cc_hbm_bw_util{node="odd\\"node"} 1' in text  # clamped
    assert "tpu_cc_prestage_in_progress 1" in text
    assert 'tpu_cc_failslow_suspect{node="serve-node-0"} 1' in text
    assert 'tpu_cc_failslow_suspect{node="odd\\"node\\nname"} 0' in text
    assert 'tpu_cc_failslow_deviation{node="serve-node-0"} 3.414' in text
    assert (
        'tpu_cc_failslow_verdicts_total{node="serve-node-0",verdict="confirmed"} 2'
        in text
    )
    assert (
        'tpu_cc_failslow_verdicts_total{node="serve-node-0",verdict="cleared"} 1'
        in text
    )


def test_fleet_merged_exposition_passes_lint():
    """The gateway's MERGED exposition (two full seeded agents plus a
    partial one, federated in-process) must pass the same lint the
    per-agent render does — HELP/TYPE once per family, buckets still
    cumulative after summation, fleet families declared."""
    text = check_metrics_lint._seeded_fleet_text()
    problems = check_metrics_lint.lint(text)
    assert problems == [], problems
    assert "tpu_cc_fleet_nodes 3" in text
    assert "tpu_cc_fleet_nodes_stale 0" in text
    assert "tpu_cc_fleet_headroom_nodes" in text
    assert "tpu_cc_fleet_scrape_errors_total 0" in text
    assert "tpu_cc_fleet_serve_p99_seconds" in text
    # Per-node series survive federation label-preserving...
    assert 'tpu_cc_serve_queue_depth{node="fleet-node-2"}' in text
    # ...and identical series from the two identical seeded agents sum:
    # each agent observed serve-node-0 twice, so the fleet count is 4.
    assert 'tpu_cc_serve_request_seconds_count{node="serve-node-0"} 4' in text


def test_lint_main_fleet_mode():
    """`check_metrics_lint.py --fleet` lints the merged exposition."""
    assert check_metrics_lint.main(["--fleet"]) == 0


def test_empty_registry_render_passes_lint():
    problems = check_metrics_lint.lint(MetricsRegistry().render_prometheus())
    assert problems == [], problems


def test_lint_catches_missing_help_and_type():
    problems = check_metrics_lint.lint('x{a="b"} 1\n')
    assert any("no # HELP" in p for p in problems)
    assert any("no # TYPE" in p for p in problems)


def test_lint_catches_type_after_sample():
    text = "# HELP x h\nx 1\n# TYPE x gauge\n"
    problems = check_metrics_lint.lint(text)
    assert any("after its first sample" in p for p in problems)


def test_lint_catches_illegal_escape_and_raw_garbage():
    text = '# HELP g h\n# TYPE g gauge\ng{v="a\\q"} 1\n'
    assert any("escape" in p for p in check_metrics_lint.lint(text))
    assert check_metrics_lint.lint("!!! not exposition\n")


def test_lint_catches_non_cumulative_buckets():
    text = (
        "# HELP h x\n# TYPE h histogram\n"
        'h_bucket{le="1"} 5\nh_bucket{le="2"} 3\n'
        'h_bucket{le="+Inf"} 9\nh_sum 1\nh_count 9\n'
    )
    problems = check_metrics_lint.lint(text)
    assert any("cumulative" in p for p in problems), problems


def test_lint_catches_missing_inf_bucket_and_count_mismatch():
    no_inf = (
        "# HELP h x\n# TYPE h histogram\n"
        'h_bucket{le="1"} 5\nh_bucket{le="2"} 6\n'
    )
    assert any("+Inf" in p for p in check_metrics_lint.lint(no_inf))
    mismatch = (
        "# HELP h x\n# TYPE h histogram\n"
        'h_bucket{le="1"} 5\nh_bucket{le="+Inf"} 9\nh_count 8\nh_sum 0\n'
    )
    assert any("_count" in p for p in check_metrics_lint.lint(mismatch))


def test_lint_main_selftest_mode():
    """The CLI default (no args) lints a seeded live registry and exits 0."""
    assert check_metrics_lint.main([]) == 0
