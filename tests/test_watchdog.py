"""Runtime-health watchdog: tier export, demote/restore hysteresis, busy
standdown — and the tpuvm backend's probe-tier selection."""

from __future__ import annotations

import pytest

from tpu_cc_manager.ccmanager.watchdog import RuntimeHealthWatchdog
from tpu_cc_manager.labels import (
    CC_MODE_STATE_LABEL,
    CC_READY_STATE_LABEL,
)
from tpu_cc_manager.kubeclient.api import node_labels
from tpu_cc_manager.utils.metrics import MetricsRegistry

NODE = "node-0"


@pytest.fixture()
def rig(fake_kube, fake_tpu):
    fake_kube.add_node(NODE, {
        CC_MODE_STATE_LABEL: "on", CC_READY_STATE_LABEL: "true",
    })
    events = []
    registry = MetricsRegistry()
    watchdog = RuntimeHealthWatchdog(
        fake_kube, fake_tpu, NODE,
        demote_after=3, restore_after=2,
        emit_event=lambda t, r, m: events.append((t, r, m)),
        metrics=registry,
    )
    return watchdog, fake_kube, fake_tpu, events, registry


def ready(fake_kube):
    return node_labels(fake_kube.get_node(NODE)).get(CC_READY_STATE_LABEL)


def test_healthy_ticks_touch_nothing(rig):
    watchdog, kube, _, events, registry = rig
    for _ in range(5):
        probe = watchdog.tick()
        assert probe.healthy
    assert ready(kube) == "true"
    assert events == []
    assert registry.health_tier() == ("probe-cmd", 3)


def test_sustained_degradation_demotes_then_recovers(rig):
    watchdog, kube, tpu, events, registry = rig
    tpu.healthy = False
    # Hysteresis: two unhealthy probes are not enough.
    watchdog.tick(); watchdog.tick()
    assert ready(kube) == "true" and not watchdog.degraded
    watchdog.tick()  # third consecutive -> demote
    assert watchdog.degraded
    assert ready(kube) == "false"
    # mode.state untouched: the mode is still committed.
    assert node_labels(kube.get_node(NODE))[CC_MODE_STATE_LABEL] == "on"
    assert events[-1][1] == "CCRuntimeUnhealthy"
    assert registry.failure_totals().get("runtime-unhealthy") == 1

    tpu.healthy = True
    watchdog.tick()
    assert ready(kube) == "false"  # one healthy probe is not recovery
    watchdog.tick()  # second consecutive -> restore
    assert not watchdog.degraded
    # Restored from the CURRENT mode.state, not a cached value.
    assert ready(kube) == "true"
    assert events[-1][1] == "CCRuntimeRecovered"


def test_restore_derives_ready_from_current_state(rig):
    """If the mode.state changed while degraded (e.g. an operator drove a
    reconcile), recovery restores THAT state's ready value."""
    watchdog, kube, tpu, _, _ = rig
    tpu.healthy = False
    for _ in range(3):
        watchdog.tick()
    kube.set_node_label(NODE, CC_MODE_STATE_LABEL, "devtools")
    tpu.healthy = True
    watchdog.tick(); watchdog.tick()
    assert ready(kube) == "debug"


def test_flapping_probe_never_demotes(rig):
    watchdog, kube, tpu, events, _ = rig
    for i in range(12):
        tpu.healthy = i % 2 == 0  # alternate: never 3 consecutive failures
        watchdog.tick()
    assert ready(kube) == "true"
    assert events == []


def test_busy_standdown_skips_the_probe(rig):
    watchdog, kube, tpu, events, _ = rig
    watchdog.is_busy = lambda: True
    tpu.healthy = False
    for _ in range(10):
        assert watchdog.tick() is None
    assert ready(kube) == "true" and not watchdog.degraded


def test_probe_exception_counts_as_unhealthy(rig):
    watchdog, kube, tpu, events, registry = rig
    tpu.fail["probe"] = -1  # probe raises TpuError forever
    for _ in range(3):
        probe = watchdog.tick()
        assert probe is not None and not probe.healthy and probe.tier == "none"
    assert watchdog.degraded
    assert ready(kube) == "false"


def test_demote_survives_apiserver_flake_and_retries_next_tick(rig):
    """A patch failure during demote must not wedge the state machine:
    the watchdog stays un-degraded and the next tick retries."""
    from tpu_cc_manager.kubeclient.api import KubeApiError

    watchdog, kube, tpu, events, _ = rig
    watchdog.retry_policy.max_attempts = 1
    tpu.healthy = False
    real_patch = kube.patch_node_labels
    kube.patch_node_labels = lambda *a, **k: (_ for _ in ()).throw(
        KubeApiError(503, "down")
    )
    for _ in range(3):
        watchdog.tick()
    assert not watchdog.degraded
    kube.patch_node_labels = real_patch
    watchdog.tick()  # still unhealthy; demote retries and lands
    assert watchdog.degraded and ready(kube) == "false"


class TestTpuVmProbeTiers:
    """Tier selection: health port > probe cmd > systemd > device node,
    strongest AVAILABLE wins; the tier rides the HealthProbe so the
    watchdog can export it."""

    def make_backend(self, tmp_path, **kwargs):
        from tpu_cc_manager.tpudev.tpuvm import TpuVmBackend

        devdir = tmp_path / "dev"
        devdir.mkdir(exist_ok=True)
        (devdir / "accel0").touch()
        kwargs.setdefault("state_dir", str(tmp_path / "state"))
        kwargs.setdefault("reset_cmd", ["true"])
        kwargs.setdefault("show_cmd", [])
        kwargs.setdefault("metadata_url", "http://127.0.0.1:1")
        kwargs.setdefault("device_glob", str(devdir / "accel*"))
        kwargs.setdefault("health_port", 0)
        return TpuVmBackend(**kwargs)

    def test_health_port_is_the_strongest_tier(self, tmp_path):
        import socket

        srv = socket.socket()
        try:
            srv.bind(("127.0.0.1", 0))
            srv.listen(8)  # several probes connect without being accepted
            backend = self.make_backend(
                tmp_path, health_port=srv.getsockname()[1]
            )
            probe = backend.probe_runtime_health()
            assert (probe.tier, probe.healthy) == ("health-port", True)
            # A configured probe command still runs as the app-level second
            # opinion: a kernel-backlog TCP accept must not mask a wedge
            # the command catches — both must pass.
            backend.health_probe_cmd = ["false"]
            probe = backend.probe_runtime_health()
            assert (probe.tier, probe.healthy) == ("health-port", False)
            backend.health_probe_cmd = ["true"]
            probe = backend.probe_runtime_health()
            assert (probe.tier, probe.healthy) == ("health-port", True)
        finally:
            srv.close()
        backend.health_probe_cmd = None
        probe = backend.probe_runtime_health()
        assert (probe.tier, probe.healthy) == ("health-port", False)

    def test_probe_cmd_tier(self, tmp_path):
        backend = self.make_backend(tmp_path, health_probe_cmd=["true"])
        probe = backend.probe_runtime_health()
        assert (probe.tier, probe.healthy) == ("probe-cmd", True)
        backend.health_probe_cmd = ["false"]
        assert backend.probe_runtime_health().healthy is False

    def test_systemd_tier(self, tmp_path):
        show = tmp_path / "show.txt"
        show.write_text("ActiveState=active\nActiveEnterTimestampMonotonic=1\n")
        backend = self.make_backend(tmp_path, show_cmd=["cat", str(show)])
        backend.stamp_cache_ttl_s = 0.0
        probe = backend.probe_runtime_health()
        assert (probe.tier, probe.healthy) == ("systemd", True)
        show.write_text("ActiveState=failed\nActiveEnterTimestampMonotonic=1\n")
        probe = backend.probe_runtime_health()
        assert (probe.tier, probe.healthy) == ("systemd", False)

    def test_device_node_is_the_weakest_fallback(self, tmp_path):
        backend = self.make_backend(tmp_path)  # no port, no cmd, no systemd
        probe = backend.probe_runtime_health()
        assert (probe.tier, probe.healthy) == ("device-node", True)
        assert probe.strength == 1  # exported rank: bottom tier
        backend.device_glob = str(tmp_path / "nope*")
        backend.vfio_glob = str(tmp_path / "nope*")
        assert backend.probe_runtime_health().healthy is False


def test_re_demotes_after_reconcile_rewrote_ready(rig):
    """A reconcile that rewrites ready=true while the runtime is STILL
    unhealthy must not stick: the watchdog re-asserts not-ready on the
    next sustained-unhealthy tick (no in-memory latch), without emitting
    a second transition event."""
    watchdog, kube, tpu, events, _ = rig
    tpu.healthy = False
    for _ in range(3):
        watchdog.tick()
    assert ready(kube) == "false"
    n_events = len(events)
    # A reconcile (e.g. label edit) rewrites the ready label...
    kube.set_node_label(NODE, CC_READY_STATE_LABEL, "true")
    # ...but the runtime is still wedged: next tick re-demotes.
    watchdog.tick()
    assert ready(kube) == "false"
    assert len(events) == n_events  # transition event not re-emitted


def test_unanswered_health_port_falls_through_not_fail_closed(tmp_path):
    """The manifest defaults CC_RUNTIME_HEALTH_PORT on; a runtime build
    with no liveness port must read as tier-unavailable (fall through to
    the next tier), not fleet-wide unhealthy. Once the port HAS answered,
    refusal means the runtime is down."""
    import socket

    from tpu_cc_manager.tpudev.tpuvm import TpuVmBackend

    devdir = tmp_path / "dev"
    devdir.mkdir()
    (devdir / "accel0").touch()
    # Grab a port nothing listens on.
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    dead_port = s.getsockname()[1]
    s.close()
    backend = TpuVmBackend(
        state_dir=str(tmp_path / "state"), reset_cmd=["true"], show_cmd=[],
        metadata_url="http://127.0.0.1:1",
        device_glob=str(devdir / "accel*"),
        health_port=dead_port, health_probe_cmd=["true"],
    )
    probe = backend.probe_runtime_health()
    assert (probe.tier, probe.healthy) == ("probe-cmd", True)
    # Same backend with no weaker tiers at all: device-node fallback.
    backend.health_probe_cmd = None
    probe = backend.probe_runtime_health()
    assert (probe.tier, probe.healthy) == ("device-node", True)
    # After the port answers once, refusal fails closed at the port tier.
    srv = socket.socket()
    try:
        srv.bind(("127.0.0.1", 0))
        srv.listen(4)
        backend.health_port = srv.getsockname()[1]
        assert backend.probe_runtime_health().tier == "health-port"
    finally:
        srv.close()
    probe = backend.probe_runtime_health()
    assert (probe.tier, probe.healthy) == ("health-port", False)
