"""cclint (tpu_cc_manager/lint/): each checker catches its seeded
known-bad fixture — including the v2 flow-aware rules (journal
typestate on the CFG, fenced-write taint, interprocedural guarded-by,
crash-point coverage) — the annotation escapes work, the baseline
machinery grandfathers and hard-errors staleness, the whole package is
clean modulo the committed baseline, and the CC_LOCKCHECK runtime
wrapper catches a deliberately inverted lock pair. Pure-AST on tiny
fixture strings plus one parse of the package — tier-1 time is
marginal, keep this cheap."""

from __future__ import annotations

import json
import os
import threading

import pytest

from tpu_cc_manager.lint import base, baseline as baseline_mod
from tpu_cc_manager.lint import (
    crash,
    crashpoints,
    fenced,
    journal,
    locks,
    surface,
    waits,
)
from tpu_cc_manager.utils import locks as locks_rt

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def ctx_of(tmp_path, files: dict[str, str]) -> base.LintContext:
    ctx = base.LintContext(root=str(tmp_path))
    for relpath, src in files.items():
        full = tmp_path / relpath
        full.parent.mkdir(parents=True, exist_ok=True)
        full.write_text(src)
        if relpath.endswith(".py"):
            sf = base.SourceFile(str(tmp_path), relpath)
            if relpath.startswith("tests/"):
                ctx.test_files.append(sf)
            else:
                ctx.files.append(sf)
    return ctx


# ---------------------------------------------------------------------------
# checker 1: lock discipline
# ---------------------------------------------------------------------------

LOCKS_BAD = '''
class C:
    def __init__(self):
        import threading
        self._lock = threading.Lock()
        self._shared = 0  # cclint: guarded-by(_lock)

    def bad(self):
        self._shared += 1

    def good(self):
        with self._lock:
            self._shared += 1

    def helper(self):  # cclint: requires(_lock)
        return self._shared

    def closure_leak(self):
        with self._lock:
            def later():
                return self._shared
            return later

    def waived(self):
        return self._shared  # cclint: unlocked-ok(snapshot read for logs)
'''


def test_locks_checker_catches_unguarded_access(tmp_path):
    findings = locks.check(ctx_of(tmp_path, {"m.py": LOCKS_BAD}))
    by_symbol = {f.symbol for f in findings}
    assert "C.bad" in by_symbol
    # A closure defined under `with` runs later — lexical hold must not
    # leak into it.
    assert "C.closure_leak" in by_symbol
    # Locked access, requires()-annotated helper, and the explicit waiver
    # are all clean.
    assert "C.good" not in by_symbol
    assert "C.helper" not in by_symbol
    assert "C.waived" not in by_symbol


LOCKS_INTERPROC = '''
import threading

class C:
    def __init__(self):
        self._lock = threading.Lock()
        self._shared = 0  # cclint: guarded-by(_lock)

    def locked_caller(self):
        with self._lock:
            self._mutate()

    def lockfree_caller(self):
        self._mutate()

    def _mutate(self):
        self._shared += 1

    def _always_locked(self):
        self._shared -= 1

    def only_locked_caller(self):
        with self._lock:
            self._always_locked()

    def helper_needs(self):  # cclint: requires(_lock)
        return self._shared

    def bad_requires_call(self):
        return self.helper_needs()

    def good_requires_call(self):
        with self._lock:
            return self.helper_needs()

    def thread_target(self):
        return threading.Thread(target=self.helper_needs)
'''


def test_locks_helper_judged_by_caller_lock_context(tmp_path):
    """The ISSUE fixture: a helper mutating a guarded field lock-free via
    two call paths — one locked, one not — is a finding naming the
    lock-free path, while a helper whose every caller holds the lock is
    proven clean with no annotation."""
    findings = locks.check(ctx_of(tmp_path, {"m.py": LOCKS_INTERPROC}))
    mutate = [f for f in findings if f.symbol == "C._mutate"]
    assert len(mutate) == 1
    assert "lockfree_caller" in mutate[0].message
    assert not any(f.symbol == "C._always_locked" for f in findings)


def test_locks_requires_is_verified_at_call_sites(tmp_path):
    findings = locks.check(ctx_of(tmp_path, {"m.py": LOCKS_INTERPROC}))
    by = {(f.symbol, f.detail) for f in findings}
    assert ("C.bad_requires_call", "call-helper_needs") in by
    assert ("C.thread_target", "ref-helper_needs") in by
    assert not any(s == "C.good_requires_call" for (s, _) in by)


def test_locks_thread_target_escaping_from_init_is_flagged(tmp_path):
    """__init__ is exempt for field ACCESSES (single-threaded
    construction) but not for escapes: a thread built in __init__
    targeting a requires() method runs it holding nothing."""
    src = '''
import threading

class C:
    def __init__(self):
        self._lock = threading.Lock()
        self._n = 0  # cclint: guarded-by(_lock)
        self._seed()  # direct construction-time call: exempt
        self._t = threading.Thread(target=self._run)

    def _seed(self):  # cclint: requires(_lock)
        self._n = 1

    def _run(self):  # cclint: requires(_lock)
        return self._n
'''
    findings = locks.check(ctx_of(tmp_path, {"m.py": src}))
    assert [(f.symbol, f.detail) for f in findings] == [
        ("C.__init__", "ref-_run")
    ]


# ---------------------------------------------------------------------------
# checker 2: no ad-hoc waits
# ---------------------------------------------------------------------------

WAITS_BAD = '''
import time
from time import sleep as zzz

def poller():
    time.sleep(1.0)

def aliased():
    zzz(0.1)

def reference_only(cb=time.sleep):
    return cb
'''


def test_waits_checker_catches_time_sleep(tmp_path):
    findings = waits.check(ctx_of(tmp_path, {"m.py": WAITS_BAD}))
    symbols = {f.symbol for f in findings}
    assert symbols == {"poller", "aliased"}  # a bare reference is not a call


def test_waits_checker_allows_retry_and_faults(tmp_path):
    files = {
        "tpu_cc_manager/utils/retry.py": "import time\ntime.sleep(1)\n",
        "tpu_cc_manager/faults/kube.py": "import time\ntime.sleep(1)\n",
    }
    assert waits.check(ctx_of(tmp_path, files)) == []


def test_waits_checker_covers_tests_with_waiver(tmp_path):
    files = {
        "tests/test_x.py": (
            "import time\n"
            "def test_flaky():\n"
            "    time.sleep(0.5)\n"
            "def test_deliberate():\n"
            "    # cclint: test-sleep-ok(the real-clock TTL must lapse)\n"
            "    time.sleep(0.5)\n"
        ),
    }
    findings = waits.check(ctx_of(tmp_path, files))
    assert [f.symbol for f in findings] == ["test_flaky"]
    assert "flake factory" in findings[0].message


def test_waits_waiver_does_not_bleed_onto_the_next_sleep(tmp_path):
    """A waiver trailing one sleep's line must not cover the sleep on
    the following line — the line-above lookup only honors pure comment
    lines."""
    files = {
        "tests/test_x.py": (
            "import time\n"
            "def test_two():\n"
            "    time.sleep(1)  # cclint: test-sleep-ok(the first one)\n"
            "    time.sleep(2)\n"
        ),
    }
    findings = waits.check(ctx_of(tmp_path, files))
    assert [f.line for f in findings] == [4]


def test_waits_waiver_is_not_honored_in_package_code(tmp_path):
    files = {
        "tpu_cc_manager/mod.py": (
            "import time\n"
            "def f():\n"
            "    # cclint: test-sleep-ok(nope)\n"
            "    time.sleep(0.5)\n"
        ),
    }
    findings = waits.check(ctx_of(tmp_path, files))
    assert [f.symbol for f in findings] == ["f"]


# ---------------------------------------------------------------------------
# checker 3: crash stays a crash
# ---------------------------------------------------------------------------

CRASH_BAD = '''
def swallow():
    try:
        work()
    except BaseException:
        log()

def bare_swallow():
    try:
        work()
    except:
        pass

def reraises():
    try:
        work()
    except BaseException as e:
        note(e)
        raise

def nested_raise_does_not_count():
    try:
        work()
    except BaseException:
        def later():
            raise RuntimeError("not on the handler level")
        keep(later)

def trampoline():
    try:
        work()
    except BaseException as e:  # cclint: crash-ok(re-raised at join)
        store(e)

def plain_exception_is_fine():
    try:
        work()
    except Exception:
        pass
'''


def test_crash_checker_requires_reraise(tmp_path):
    findings = crash.check(ctx_of(tmp_path, {"m.py": CRASH_BAD}))
    symbols = sorted(f.symbol for f in findings)
    assert symbols == ["bare_swallow", "nested_raise_does_not_count", "swallow"]


# ---------------------------------------------------------------------------
# checker 4: journal typestate (begin-dominates-reset, close-at-exit)
# ---------------------------------------------------------------------------

JOURNAL_BAD = '''
class Rogue:
    def zap(self):
        self.backend.reset(self.chips)

    def bounce(self):
        self.backend.restart_runtime()

    def unrelated(self):
        self.cursor.reset(token)
'''


def test_journal_checker_catches_unjournaled_reset(tmp_path):
    findings = journal.check(
        ctx_of(tmp_path, {"tpu_cc_manager/ccmanager/rogue.py": JOURNAL_BAD})
    )
    details = sorted(f.detail for f in findings)
    assert details == ["reset", "restart_runtime"]  # not the cursor.reset


def test_journal_checker_skips_device_layer(tmp_path):
    findings = journal.check(
        ctx_of(tmp_path, {"tpu_cc_manager/tpudev/impl.py": JOURNAL_BAD})
    )
    assert findings == []


JOURNAL_BRANCH_BAD = '''
class M:
    def flip(self, fast):
        if fast:
            txn = self.intents.begin("transition")
        else:
            txn = None  # one branch reaches the reset UNJOURNALED
        self.backend.reset(self.chips)
        self.intents.commit(txn)
'''


def test_journal_branch_without_begin_is_a_finding(tmp_path):
    """The dominance proof, not a call-site grep: a begin on ONE branch
    does not dominate the reset."""
    findings = journal.check(
        ctx_of(tmp_path, {"tpu_cc_manager/ccmanager/m.py": JOURNAL_BRANCH_BAD})
    )
    assert [f.detail for f in findings] == ["reset"]
    assert "dominated" in findings[0].message


JOURNAL_INTERPROC_OK = '''
class M:
    def _begin(self):
        return self.intents.begin("transition")

    def outer_pipelined(self):
        txn = self._begin()
        self._reset_bracketed(txn=txn)

    def outer_serial(self):
        self._reset_bracketed()

    def _reset_bracketed(self, txn=None):
        if txn is None:
            txn = self._begin()
        self.backend.reset(self.chips)
        self.intents.commit(txn)
'''


def test_journal_interprocedural_token_proves_the_bracket(tmp_path):
    """The real pipeline's shape: the token begun in the caller (or the
    if-None fallback) proves the callee's reset on BOTH call paths —
    with no allowlist entry."""
    findings = journal.check(
        ctx_of(tmp_path, {"tpu_cc_manager/ccmanager/m.py": JOURNAL_INTERPROC_OK})
    )
    assert findings == []


def test_journal_interprocedural_unproven_caller_is_a_finding(tmp_path):
    """Same callee, but one caller hands over a token it never began:
    the merge degrades and the reset is no longer proven."""
    bad = JOURNAL_INTERPROC_OK.replace(
        "        txn = self._begin()\n        self._reset_bracketed(txn=txn)",
        "        txn = object()\n        self._reset_bracketed(txn=txn)",
    )
    findings = journal.check(
        ctx_of(tmp_path, {"tpu_cc_manager/ccmanager/m.py": bad})
    )
    assert [f.detail for f in findings] == ["reset"]


JOURNAL_OPEN_EXIT = '''
class M:
    def flip(self, ok):
        txn = self.intents.begin("transition")
        self.backend.reset(self.chips)
        if ok:
            self.intents.commit(txn)
        return ok
'''


def test_journal_open_intent_at_exit_is_a_finding(tmp_path):
    findings = journal.check(
        ctx_of(tmp_path, {"tpu_cc_manager/ccmanager/m.py": JOURNAL_OPEN_EXIT})
    )
    assert [f.detail for f in findings] == ["open-txn"]
    assert "non-crash exit" in findings[0].message


def test_journal_open_exit_waiver(tmp_path):
    waived = JOURNAL_OPEN_EXIT.replace(
        'txn = self.intents.begin("transition")',
        'txn = self.intents.begin("transition")  '
        "# cclint: intent-open-ok(replay owns it)",
    )
    findings = journal.check(
        ctx_of(tmp_path, {"tpu_cc_manager/ccmanager/m.py": waived})
    )
    assert findings == []


def test_journal_close_in_finally_covers_returns(tmp_path):
    src = '''
class M:
    def flip(self):
        txn = self.intents.begin("transition")
        try:
            self.backend.reset(self.chips)
            return True
        finally:
            self.intents.abort(txn)
'''
    findings = journal.check(
        ctx_of(tmp_path, {"tpu_cc_manager/ccmanager/m.py": src})
    )
    assert findings == []


def test_journal_drain_token_does_not_prove_hardware(tmp_path):
    """Replay of a drain intent readmits components — it does not
    resolve a reset. An open drain-bracket token must not satisfy the
    dominance proof."""
    src = '''
class M:
    def flip(self):
        dtxn = self.intents.begin("drain")
        self.backend.reset(self.chips)
        self.intents.commit(dtxn)
'''
    findings = journal.check(
        ctx_of(tmp_path, {"tpu_cc_manager/ccmanager/m.py": src})
    )
    assert [f.detail for f in findings] == ["reset"]


def test_journal_closure_and_module_level_resets_are_flagged(tmp_path):
    """v1 parity: a hardware call the flow engine cannot place on a CFG
    (a closure that runs later, module level) degrades to a finding,
    never to silent cleanliness."""
    src = '''
backend.reset([])

class M:
    def flip(self):
        txn = self.intents.begin("transition")
        def later():
            self.backend.reset(self.chips)
        self.retry(later)
        self.intents.commit(txn)
'''
    findings = journal.check(
        ctx_of(tmp_path, {"tpu_cc_manager/ccmanager/m.py": src})
    )
    assert sorted(f.symbol for f in findings) == ["<module>", "M.flip.later"]
    assert all("cannot prove" in f.message for f in findings)


def test_journal_ok_line_waiver(tmp_path):
    waived = JOURNAL_BAD.replace(
        "self.backend.reset(self.chips)",
        "self.backend.reset(self.chips)  # cclint: journal-ok(fixture)",
    )
    findings = journal.check(
        ctx_of(tmp_path, {"tpu_cc_manager/ccmanager/rogue.py": waived})
    )
    assert [f.detail for f in findings] == ["restart_runtime"]


# ---------------------------------------------------------------------------
# checker 6: fenced-write taint
# ---------------------------------------------------------------------------

FENCED_BRACKET = '''
def cmd_rollout(api, args):
    lease = RolloutLease(api, holder="me")
    record = lease.acquire()
    api.patch_node_labels("n0", {"k": "v"})  # RAW write inside the bracket
    fenced = FencedKube(api, lease)
    fenced.patch_node_labels("n0", {"k": "v"})  # fenced: fine
    lease.release()
    api.patch_node_labels("n0", {"k": "v"})  # after release: fine
'''


def test_fenced_raw_write_inside_bracket(tmp_path):
    findings = fenced.check(
        ctx_of(tmp_path, {"tpu_cc_manager/ctl.py": FENCED_BRACKET})
    )
    assert len(findings) == 1
    assert findings[0].line == 5
    assert "raw-client write" in findings[0].message


FENCED_HELPER = '''
def _retag(client, name):
    client.patch_node_labels(name, {"k": "v"})

def cmd_rollout(api, args):
    lease = RolloutLease(api, holder="me")
    lease.acquire()
    _retag(api, "n0")  # raw client handed to a writing helper
    lease.release()
'''


def test_fenced_write_through_helper_inside_bracket(tmp_path):
    findings = fenced.check(
        ctx_of(tmp_path, {"tpu_cc_manager/ctl.py": FENCED_HELPER})
    )
    assert [f.detail for f in findings] == ["_retag"]
    assert "writes through that parameter" in findings[0].message


FENCED_CLASS = '''
class Roller:
    def __init__(self, api, lease=None):
        if lease is not None:
            api = FencedKube(api, lease)
        self.api = api
        self._stash = api

    def good(self):
        self.api.patch_node_labels("n", {"k": "v"})

    def bad(self):
        self._stash.patch_node_labels("n", {"k": "v"})
'''


def test_fenced_self_fencing_class_stashed_client(tmp_path):
    findings = fenced.check(
        ctx_of(tmp_path, {"tpu_cc_manager/ccmanager/roll.py": FENCED_CLASS})
    )
    assert [f.symbol for f in findings] == ["Roller.bad"]


def test_fenced_lease_handoff_to_self_fencing_class_is_sanctioned(tmp_path):
    files = {
        "tpu_cc_manager/ccmanager/roll.py": FENCED_CLASS,
        "tpu_cc_manager/ctl.py": '''
def cmd_rollout(api, args):
    lease = RolloutLease(api, holder="me")
    lease.acquire()
    roller = Roller(api, lease=lease)  # sanctioned: client + lease
    lease.release()
''',
    }
    findings = fenced.check(ctx_of(tmp_path, files))
    # Only the fixture class's own stashed-client bug remains.
    assert [f.symbol for f in findings] == ["Roller.bad"]


def test_fenced_closure_write_inside_bracket(tmp_path):
    """A callback defined between acquire and release most plausibly
    runs inside the bracket: its raw-client writes are findings too."""
    src = '''
def cmd_rollout(api, args):
    lease = RolloutLease(api, holder="me")
    lease.acquire()
    def on_halt():
        api.patch_node_labels("n0", {"k": "v"})
    register(on_halt)
    lease.release()
'''
    findings = fenced.check(ctx_of(tmp_path, {"tpu_cc_manager/ctl.py": src}))
    assert [f.detail for f in findings] == ["patch_node_labels"]


# ---------------------------------------------------------------------------
# checker 7: crash-point coverage
# ---------------------------------------------------------------------------

CRASHPOINT_PKG = '''
class Roller:
    def _crash_point(self, point):
        pass

    def drive(self):
        self._crash_point("window-start")
        self._crash_point("lonely-point")
'''

CRASHPOINT_TEST = '''
MY_CRASH_POINTS = ["window-start", "retired-point"]
'''


def test_crashpoints_orphaned_and_stale(tmp_path):
    files = {
        "tpu_cc_manager/ccmanager/roll.py": CRASHPOINT_PKG,
        "tests/test_roll.py": CRASHPOINT_TEST,
    }
    findings = crashpoints.check(ctx_of(tmp_path, files))
    by = {(f.symbol, f.detail) for f in findings}
    # A package point no test names fails the build...
    assert ("orphaned-point", "lonely-point") in by
    # ...and a point only tests still claim is stale.
    assert ("stale-point", "retired-point") in by
    # The covered point is clean in both directions.
    assert not any(d == "window-start" for (_, d) in by)


def test_crashpoints_phase_marks_covered_by_constant_name(tmp_path):
    files = {
        "tpu_cc_manager/ccmanager/ij.py": 'PHASE_RESET = "reset"\n',
        "tpu_cc_manager/ccmanager/m.py": (
            "from tpu_cc_manager.ccmanager import ij\n"
            "def go(j, txn):\n"
            "    j.intents.mark(txn, ij.PHASE_RESET)\n"
        ),
        "tests/test_m.py": "def test():\n    assert ij.PHASE_RESET\n",
    }
    assert crashpoints.check(ctx_of(tmp_path, files)) == []


def test_crashpoints_uncovered_phase_mark_is_orphaned(tmp_path):
    files = {
        "tpu_cc_manager/ccmanager/ij.py": 'PHASE_RESET = "reset"\n',
        "tpu_cc_manager/ccmanager/m.py": (
            "from tpu_cc_manager.ccmanager import ij\n"
            "def go(j, txn):\n"
            "    j.intents.mark(txn, ij.PHASE_RESET)\n"
        ),
        "tests/test_m.py": "def test():\n    pass\n",
    }
    findings = crashpoints.check(ctx_of(tmp_path, files))
    assert [(f.symbol, f.detail) for f in findings] == [
        ("orphaned-point", "reset")
    ]


def test_crashpoints_waiver(tmp_path):
    pkg = CRASHPOINT_PKG.replace(
        'self._crash_point("lonely-point")',
        'self._crash_point("lonely-point")  # cclint: crash-point-ok(fixture)',
    )
    files = {
        "tpu_cc_manager/ccmanager/roll.py": pkg,
        "tests/test_roll.py": 'MY_CRASH_POINTS = ["window-start"]\n',
    }
    assert crashpoints.check(ctx_of(tmp_path, files)) == []


def test_repo_crash_points_match_the_declared_suite_list():
    """The package↔suite↔lint triangle on the REAL repo: the canonical
    rolling.CRASH_POINTS tuple, the literals the kill-at suite declares,
    and what the coverage checker extracts must all agree."""
    from tpu_cc_manager.ccmanager import rolling as rolling_mod

    ctx = base.build_context(REPO)
    phase_consts = crashpoints._phase_constants(ctx.files)
    points = crashpoints._package_points(ctx.files, phase_consts)
    assert set(rolling_mod.CRASH_POINTS) <= set(points)


# ---------------------------------------------------------------------------
# checker 5: contract-surface drift
# ---------------------------------------------------------------------------


def test_surface_checker_env_and_label_drift(tmp_path):
    files = {
        "tpu_cc_manager/mod.py": (
            'import os\n'
            'A = os.environ.get("CC_DOCUMENTED", "")\n'
            'B = os.environ.get("CC_UNDOCUMENTED", "")\n'
            'KEY = "cloud.google.com/tpu-cc.rogue-key"\n'
        ),
        "tpu_cc_manager/labels.py": 'OK = "cloud.google.com/tpu-cc.fine"\n',
        "docs/operations.md": "| `CC_DOCUMENTED` | on | documented |\n",
        "deployments/manifests/daemonset.yaml": (
            "env:\n"
            "  - name: CC_DOCUMENTED\n"
            "  - name: CC_PHANTOM\n"
        ),
    }
    findings = surface.check(ctx_of(tmp_path, files))
    by = {(f.symbol, f.detail) for f in findings}
    assert ("env-undocumented", "CC_UNDOCUMENTED") in by
    assert ("env-unread", "CC_PHANTOM") in by
    assert ("label-literal", "cloud.google.com/tpu-cc.rogue-key") in by
    # labels.py itself and the documented env are clean.
    assert ("env-undocumented", "CC_DOCUMENTED") not in by
    assert not any(d == "cloud.google.com/tpu-cc.fine" for (_, d) in by)


def test_surface_checker_exempts_docstrings(tmp_path):
    files = {
        "tpu_cc_manager/mod.py": (
            '"""Doc naming cloud.google.com/tpu-cc.mode is fine."""\n'
            "X = 1\n"
        ),
    }
    assert surface.check(ctx_of(tmp_path, files)) == []


# ---------------------------------------------------------------------------
# baseline machinery
# ---------------------------------------------------------------------------


def test_baseline_split_and_stale(tmp_path):
    f1 = base.Finding("waits", "a.py", 3, "m", "f")
    f2 = base.Finding("waits", "b.py", 9, "m", "g")
    known = {f1.fingerprint: "reason", "waits:gone.py:h": "stale"}
    new, old, stale = baseline_mod.split([f1, f2], known)
    assert [f.fingerprint for f in new] == [f2.fingerprint]
    assert [f.fingerprint for f in old] == [f1.fingerprint]
    assert stale == ["waits:gone.py:h"]


def test_baseline_roundtrip(tmp_path):
    f = base.Finding("crash", "x.py", 1, "m", "fn")
    path = str(tmp_path / "b.json")
    baseline_mod.save(str(tmp_path), [f], path)
    loaded = baseline_mod.load(str(tmp_path), path)
    assert f.fingerprint in loaded
    data = json.loads((tmp_path / "b.json").read_text())
    assert data["entries"][0]["reason"].startswith("TODO")


def test_write_baseline_preserves_reasons_and_prunes_fixed(tmp_path):
    """Regeneration is not a bare skeleton: entries that survive keep
    their hand-written reasons, and entries whose violations are gone
    are pruned."""
    keep = base.Finding("waits", "a.py", 3, "m", "f")
    gone = base.Finding("waits", "b.py", 9, "m", "g")
    path = str(tmp_path / "b.json")
    baseline_mod.save(str(tmp_path), [keep, gone], path)
    data = json.loads((tmp_path / "b.json").read_text())
    for e in data["entries"]:
        e["reason"] = f"hand-written for {e['fingerprint']}"
    (tmp_path / "b.json").write_text(json.dumps(data))
    # The `gone` violation is fixed; regenerate.
    baseline_mod.save(str(tmp_path), [keep], path)
    loaded = baseline_mod.load(str(tmp_path), path)
    assert loaded == {
        keep.fingerprint: f"hand-written for {keep.fingerprint}"
    }


def test_stale_baseline_entry_is_a_hard_error(tmp_path):
    """The driver exits non-zero on a stale entry even with zero
    findings — fixed violations must shed their grandfathering in the
    same change."""
    from tpu_cc_manager.lint.__main__ import main

    root = tmp_path / "emptyrepo"
    root.mkdir()
    bl = tmp_path / "stale.json"
    bl.write_text(json.dumps({
        "entries": [{"fingerprint": "waits:gone.py:f", "reason": "old"}],
    }))
    rc = main([
        "--root", str(root), "--baseline", str(bl), "--skip-expo",
    ])
    assert rc == 1
    bl.write_text(json.dumps({"entries": []}))
    assert main(
        ["--root", str(root), "--baseline", str(bl), "--skip-expo"]
    ) == 0


# ---------------------------------------------------------------------------
# the repo itself is clean modulo the committed baseline
# ---------------------------------------------------------------------------


def test_whole_package_clean_modulo_baseline():
    from tpu_cc_manager.lint.__main__ import run

    findings = run(REPO, skip_expo=True)
    known = baseline_mod.load(REPO)
    new, _, stale = baseline_mod.split(findings, known)
    assert new == [], [f.to_dict() for f in new]
    assert stale == [], f"stale baseline entries: {stale}"


# ---------------------------------------------------------------------------
# CC_LOCKCHECK runtime lock-order checker
# ---------------------------------------------------------------------------


def test_lockcheck_catches_inverted_pair(monkeypatch):
    monkeypatch.setenv(locks_rt.LOCKCHECK_ENV, "1")
    a = locks_rt.CheckedLock("test.A")
    b = locks_rt.CheckedLock("test.B")
    try:
        with a:
            with b:
                pass
        # The inversion is caught on the FIRST inverted acquisition, on
        # the same thread, without needing the deadlock interleaving.
        with pytest.raises(locks_rt.LockOrderError, match="inversion"):
            with b:
                with a:
                    pass
    finally:
        locks_rt.GRAPH.reset()


def test_lockcheck_rlock_reentry_is_not_an_inversion(monkeypatch):
    monkeypatch.setenv(locks_rt.LOCKCHECK_ENV, "1")
    r = locks_rt.CheckedLock("test.R", reentrant=True)
    try:
        with r:
            with r:  # re-entrant: no self-edge, no error
                pass
    finally:
        locks_rt.GRAPH.reset()


def test_lockcheck_nonreentrant_self_reacquire_is_reported(monkeypatch):
    """Re-acquiring a plain (non-reentrant) checked lock on the same
    thread is a guaranteed self-deadlock: the checker reports it instead
    of hanging the suite."""
    monkeypatch.setenv(locks_rt.LOCKCHECK_ENV, "1")
    lock = locks_rt.CheckedLock("test.self")
    try:
        with lock:
            with pytest.raises(locks_rt.LockOrderError, match="self-deadlock"):
                lock.acquire()
    finally:
        locks_rt.GRAPH.reset()


def test_lockcheck_cross_thread_inversion(monkeypatch):
    """The realistic shape: thread 1 takes A→B, thread 2 takes B→A."""
    monkeypatch.setenv(locks_rt.LOCKCHECK_ENV, "1")
    a = locks_rt.CheckedLock("test.X")
    b = locks_rt.CheckedLock("test.Y")
    caught: list[BaseException] = []

    def t1():
        with a:
            with b:
                pass

    def t2():
        try:
            with b:
                with a:
                    pass
        except locks_rt.LockOrderError as e:
            caught.append(e)

    try:
        th1 = threading.Thread(target=t1)
        th1.start()
        th1.join()
        th2 = threading.Thread(target=t2)
        th2.start()
        th2.join()
        assert caught, "cross-thread inversion was not detected"
    finally:
        locks_rt.GRAPH.reset()


def test_make_lock_is_plain_without_env(monkeypatch):
    monkeypatch.delenv(locks_rt.LOCKCHECK_ENV, raising=False)
    lock = locks_rt.make_lock("prod")
    assert not isinstance(lock, locks_rt.CheckedLock)
