"""cclint (tpu_cc_manager/lint/): each checker catches its seeded
known-bad fixture, the annotation escapes work, the baseline machinery
grandfathers and flags staleness, the whole package is clean modulo the
committed baseline, and the CC_LOCKCHECK runtime wrapper catches a
deliberately inverted lock pair. Pure-AST on tiny fixture strings plus
one parse of the package — tier-1 time is marginal, keep this cheap."""

from __future__ import annotations

import json
import os
import threading

import pytest

from tpu_cc_manager.lint import base, baseline as baseline_mod
from tpu_cc_manager.lint import crash, journal, locks, surface, waits
from tpu_cc_manager.utils import locks as locks_rt

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def ctx_of(tmp_path, files: dict[str, str]) -> base.LintContext:
    ctx = base.LintContext(root=str(tmp_path))
    for relpath, src in files.items():
        full = tmp_path / relpath
        full.parent.mkdir(parents=True, exist_ok=True)
        full.write_text(src)
        if relpath.endswith(".py"):
            ctx.files.append(base.SourceFile(str(tmp_path), relpath))
    return ctx


# ---------------------------------------------------------------------------
# checker 1: lock discipline
# ---------------------------------------------------------------------------

LOCKS_BAD = '''
class C:
    def __init__(self):
        import threading
        self._lock = threading.Lock()
        self._shared = 0  # cclint: guarded-by(_lock)

    def bad(self):
        self._shared += 1

    def good(self):
        with self._lock:
            self._shared += 1

    def helper(self):  # cclint: requires(_lock)
        return self._shared

    def closure_leak(self):
        with self._lock:
            def later():
                return self._shared
            return later

    def waived(self):
        return self._shared  # cclint: unlocked-ok(snapshot read for logs)
'''


def test_locks_checker_catches_unguarded_access(tmp_path):
    findings = locks.check(ctx_of(tmp_path, {"m.py": LOCKS_BAD}))
    by_symbol = {f.symbol for f in findings}
    assert "C.bad" in by_symbol
    # A closure defined under `with` runs later — lexical hold must not
    # leak into it.
    assert "C.closure_leak" in by_symbol
    # Locked access, requires()-annotated helper, and the explicit waiver
    # are all clean.
    assert "C.good" not in by_symbol
    assert "C.helper" not in by_symbol
    assert "C.waived" not in by_symbol


# ---------------------------------------------------------------------------
# checker 2: no ad-hoc waits
# ---------------------------------------------------------------------------

WAITS_BAD = '''
import time
from time import sleep as zzz

def poller():
    time.sleep(1.0)

def aliased():
    zzz(0.1)

def reference_only(cb=time.sleep):
    return cb
'''


def test_waits_checker_catches_time_sleep(tmp_path):
    findings = waits.check(ctx_of(tmp_path, {"m.py": WAITS_BAD}))
    symbols = {f.symbol for f in findings}
    assert symbols == {"poller", "aliased"}  # a bare reference is not a call


def test_waits_checker_allows_retry_and_faults(tmp_path):
    files = {
        "tpu_cc_manager/utils/retry.py": "import time\ntime.sleep(1)\n",
        "tpu_cc_manager/faults/kube.py": "import time\ntime.sleep(1)\n",
    }
    assert waits.check(ctx_of(tmp_path, files)) == []


# ---------------------------------------------------------------------------
# checker 3: crash stays a crash
# ---------------------------------------------------------------------------

CRASH_BAD = '''
def swallow():
    try:
        work()
    except BaseException:
        log()

def bare_swallow():
    try:
        work()
    except:
        pass

def reraises():
    try:
        work()
    except BaseException as e:
        note(e)
        raise

def nested_raise_does_not_count():
    try:
        work()
    except BaseException:
        def later():
            raise RuntimeError("not on the handler level")
        keep(later)

def trampoline():
    try:
        work()
    except BaseException as e:  # cclint: crash-ok(re-raised at join)
        store(e)

def plain_exception_is_fine():
    try:
        work()
    except Exception:
        pass
'''


def test_crash_checker_requires_reraise(tmp_path):
    findings = crash.check(ctx_of(tmp_path, {"m.py": CRASH_BAD}))
    symbols = sorted(f.symbol for f in findings)
    assert symbols == ["bare_swallow", "nested_raise_does_not_count", "swallow"]


# ---------------------------------------------------------------------------
# checker 4: journal-before-reset
# ---------------------------------------------------------------------------

JOURNAL_BAD = '''
class Rogue:
    def zap(self):
        self.backend.reset(self.chips)

    def bounce(self):
        self.backend.restart_runtime()

    def unrelated(self):
        self.cursor.reset(token)
'''


def test_journal_checker_catches_unallowlisted_reset(tmp_path):
    findings = journal.check(
        ctx_of(tmp_path, {"tpu_cc_manager/ccmanager/rogue.py": JOURNAL_BAD})
    )
    details = sorted(f.detail for f in findings)
    assert details == ["reset", "restart_runtime"]  # not the cursor.reset


def test_journal_checker_skips_device_layer(tmp_path):
    findings = journal.check(
        ctx_of(tmp_path, {"tpu_cc_manager/tpudev/impl.py": JOURNAL_BAD})
    )
    assert findings == []


# ---------------------------------------------------------------------------
# checker 5: contract-surface drift
# ---------------------------------------------------------------------------


def test_surface_checker_env_and_label_drift(tmp_path):
    files = {
        "tpu_cc_manager/mod.py": (
            'import os\n'
            'A = os.environ.get("CC_DOCUMENTED", "")\n'
            'B = os.environ.get("CC_UNDOCUMENTED", "")\n'
            'KEY = "cloud.google.com/tpu-cc.rogue-key"\n'
        ),
        "tpu_cc_manager/labels.py": 'OK = "cloud.google.com/tpu-cc.fine"\n',
        "docs/operations.md": "| `CC_DOCUMENTED` | on | documented |\n",
        "deployments/manifests/daemonset.yaml": (
            "env:\n"
            "  - name: CC_DOCUMENTED\n"
            "  - name: CC_PHANTOM\n"
        ),
    }
    findings = surface.check(ctx_of(tmp_path, files))
    by = {(f.symbol, f.detail) for f in findings}
    assert ("env-undocumented", "CC_UNDOCUMENTED") in by
    assert ("env-unread", "CC_PHANTOM") in by
    assert ("label-literal", "cloud.google.com/tpu-cc.rogue-key") in by
    # labels.py itself and the documented env are clean.
    assert ("env-undocumented", "CC_DOCUMENTED") not in by
    assert not any(d == "cloud.google.com/tpu-cc.fine" for (_, d) in by)


def test_surface_checker_exempts_docstrings(tmp_path):
    files = {
        "tpu_cc_manager/mod.py": (
            '"""Doc naming cloud.google.com/tpu-cc.mode is fine."""\n'
            "X = 1\n"
        ),
    }
    assert surface.check(ctx_of(tmp_path, files)) == []


# ---------------------------------------------------------------------------
# baseline machinery
# ---------------------------------------------------------------------------


def test_baseline_split_and_stale(tmp_path):
    f1 = base.Finding("waits", "a.py", 3, "m", "f")
    f2 = base.Finding("waits", "b.py", 9, "m", "g")
    known = {f1.fingerprint: "reason", "waits:gone.py:h": "stale"}
    new, old, stale = baseline_mod.split([f1, f2], known)
    assert [f.fingerprint for f in new] == [f2.fingerprint]
    assert [f.fingerprint for f in old] == [f1.fingerprint]
    assert stale == ["waits:gone.py:h"]


def test_baseline_roundtrip(tmp_path):
    f = base.Finding("crash", "x.py", 1, "m", "fn")
    path = str(tmp_path / "b.json")
    baseline_mod.save(str(tmp_path), [f], path)
    loaded = baseline_mod.load(str(tmp_path), path)
    assert f.fingerprint in loaded
    data = json.loads((tmp_path / "b.json").read_text())
    assert data["entries"][0]["reason"].startswith("TODO")


# ---------------------------------------------------------------------------
# the repo itself is clean modulo the committed baseline
# ---------------------------------------------------------------------------


def test_whole_package_clean_modulo_baseline():
    from tpu_cc_manager.lint.__main__ import run

    findings = run(REPO, skip_expo=True)
    known = baseline_mod.load(REPO)
    new, _, stale = baseline_mod.split(findings, known)
    assert new == [], [f.to_dict() for f in new]
    assert stale == [], f"stale baseline entries: {stale}"


# ---------------------------------------------------------------------------
# CC_LOCKCHECK runtime lock-order checker
# ---------------------------------------------------------------------------


def test_lockcheck_catches_inverted_pair(monkeypatch):
    monkeypatch.setenv(locks_rt.LOCKCHECK_ENV, "1")
    a = locks_rt.CheckedLock("test.A")
    b = locks_rt.CheckedLock("test.B")
    try:
        with a:
            with b:
                pass
        # The inversion is caught on the FIRST inverted acquisition, on
        # the same thread, without needing the deadlock interleaving.
        with pytest.raises(locks_rt.LockOrderError, match="inversion"):
            with b:
                with a:
                    pass
    finally:
        locks_rt.GRAPH.reset()


def test_lockcheck_rlock_reentry_is_not_an_inversion(monkeypatch):
    monkeypatch.setenv(locks_rt.LOCKCHECK_ENV, "1")
    r = locks_rt.CheckedLock("test.R", reentrant=True)
    try:
        with r:
            with r:  # re-entrant: no self-edge, no error
                pass
    finally:
        locks_rt.GRAPH.reset()


def test_lockcheck_nonreentrant_self_reacquire_is_reported(monkeypatch):
    """Re-acquiring a plain (non-reentrant) checked lock on the same
    thread is a guaranteed self-deadlock: the checker reports it instead
    of hanging the suite."""
    monkeypatch.setenv(locks_rt.LOCKCHECK_ENV, "1")
    lock = locks_rt.CheckedLock("test.self")
    try:
        with lock:
            with pytest.raises(locks_rt.LockOrderError, match="self-deadlock"):
                lock.acquire()
    finally:
        locks_rt.GRAPH.reset()


def test_lockcheck_cross_thread_inversion(monkeypatch):
    """The realistic shape: thread 1 takes A→B, thread 2 takes B→A."""
    monkeypatch.setenv(locks_rt.LOCKCHECK_ENV, "1")
    a = locks_rt.CheckedLock("test.X")
    b = locks_rt.CheckedLock("test.Y")
    caught: list[BaseException] = []

    def t1():
        with a:
            with b:
                pass

    def t2():
        try:
            with b:
                with a:
                    pass
        except locks_rt.LockOrderError as e:
            caught.append(e)

    try:
        th1 = threading.Thread(target=t1)
        th1.start()
        th1.join()
        th2 = threading.Thread(target=t2)
        th2.start()
        th2.join()
        assert caught, "cross-thread inversion was not detected"
    finally:
        locks_rt.GRAPH.reset()


def test_make_lock_is_plain_without_env(monkeypatch):
    monkeypatch.delenv(locks_rt.LOCKCHECK_ENV, raising=False)
    lock = locks_rt.make_lock("prod")
    assert not isinstance(lock, locks_rt.CheckedLock)
