"""Skew-proof fencing (ccmanager/rollout_state.py, ISSUE 18).

Federated regions run on different wall clocks. The regional lease's
expiry stamp is written by the HOLDER's clock and judged by the
CONTENDER's, so a ±N s skew can fabricate expiry on a healthy holder or
keep a dead one "live". With ``max_clock_skew_s > 0`` the lease treats
renewTime + leaseTransitions as an opaque change-token and confirms
holder death by observing the token frozen for one lease duration of
LOCAL monotonic time — no cross-clock comparison decides a takeover.

The bars here:

- a seeded property test: the acquire verdict (takeover vs held) is a
  function of the holder's ACTUAL liveness only — identical under every
  sampled skew in ±120 s;
- the frozen-clock regression: a stale holder self-fences from its own
  monotonic clock alone, before any apiserver round trip;
- a future-stamped dead holder (skewed-ahead remote clock) is observed
  and taken over instead of being trusted as live forever;
- a third-party takeover mid-observation surfaces as LeaseHeld naming
  the live writer.
"""

import random

import pytest

from tpu_cc_manager.ccmanager import rollout_state
from tpu_cc_manager.ccmanager.rollout_state import (
    LeaseHeld,
    RolloutLease,
    RolloutFenced,
)
from tpu_cc_manager.kubeclient.fake import FakeKube
from tpu_cc_manager.utils import retry as retry_mod
from tpu_cc_manager.utils.metrics import MetricsRegistry

NS = "tpu-operator"
LEASE = "tpu-cc-rollout"
BASE = 1_700_000_000.0
DURATION = 30.0
MAX_SKEW = 150.0


class Clock:
    def __init__(self, t: float = 0.0) -> None:
        self.t = t

    def __call__(self) -> float:
        return self.t

    def advance(self, s: float) -> None:
        self.t += s


def stamp_holder(fake, holder_wall, duration=DURATION):
    """A previous holder's lease, stamped by THAT holder's wall clock."""
    lease = RolloutLease(
        fake, holder="holder-a", namespace=NS, name=LEASE,
        duration_s=duration, metrics=MetricsRegistry(),
        wall=holder_wall, clock=Clock(),
    )
    lease.acquire()
    return lease


def contender(fake, wall, clock, max_skew=MAX_SKEW):
    return RolloutLease(
        fake, holder="holder-b", namespace=NS, name=LEASE,
        duration_s=DURATION, metrics=MetricsRegistry(),
        wall=wall, clock=clock, max_clock_skew_s=max_skew,
    )


def acquire_verdict(monkeypatch, skew_holder, skew_contender, alive):
    """Run one takeover attempt and classify its outcome. The holder's
    stamp and the contender's wall disagree by the two skews; the
    holder's ACTUAL liveness is simulated by (not) advancing the opaque
    token while the contender observes."""
    fake = FakeKube()
    stamp_holder(fake, lambda: BASE + skew_holder)

    # Enough LOCAL elapsed time that the wall verdict reads "suspect"
    # (expired or future-stamped) under every skew in the sampled band —
    # the regime where only the observation window decides.
    elapsed = DURATION + 2 * 120.0 + 60.0
    clk = Clock()
    renew_seq = {"n": 0}

    def observing_wait(delay_s, stop=None):
        clk.advance(delay_s)
        if alive:
            lease = fake.get_lease(NS, LEASE)
            renew_seq["n"] += 1
            lease["spec"]["renewTime"] = f"1970-01-01T00:00:{renew_seq['n']:02d}Z"
            fake.update_lease(NS, LEASE, lease)
        return False

    monkeypatch.setattr(retry_mod, "wait", observing_wait)
    b = contender(fake, lambda: BASE + elapsed + skew_contender, clk)
    try:
        b.acquire()
    except LeaseHeld:
        return "held"
    return "takeover"


def test_fencing_verdict_is_skew_invariant(monkeypatch):
    """Property: under ±120 s of injected skew on either side, the
    verdict matches the zero-skew verdict for both a dead and a live
    holder — fencing never depends on whose wall clock is right."""
    for seed in range(5):
        rng = random.Random(20260807 + seed)
        for alive in (False, True):
            baseline = acquire_verdict(monkeypatch, 0.0, 0.0, alive)
            assert baseline == ("held" if alive else "takeover")
            for _ in range(6):
                sh = rng.uniform(-120.0, 120.0)
                sc = rng.uniform(-120.0, 120.0)
                verdict = acquire_verdict(monkeypatch, sh, sc, alive)
                assert verdict == baseline, (
                    f"seed={seed} skew_holder={sh:.1f} "
                    f"skew_contender={sc:.1f} alive={alive}: "
                    f"{verdict} != {baseline}"
                )


class CountingKube:
    """Pass-through wrapper that counts every API round trip."""

    def __init__(self, inner):
        self._inner = inner
        self.calls = 0

    def __getattr__(self, name):
        attr = getattr(self._inner, name)
        if not callable(attr):
            return attr

        def counted(*a, **kw):
            self.calls += 1
            return attr(*a, **kw)

        return counted


def test_stale_holder_self_fences_with_zero_api_calls():
    """The frozen-clock regression: an orchestrator that slept past its
    own lease duration must fence itself from LOCAL monotonic time
    alone — before any apiserver round trip could confirm a successor."""
    counting = CountingKube(FakeKube())
    clk = Clock()
    lease = RolloutLease(
        counting, holder="orch", namespace=NS, name=LEASE,
        duration_s=DURATION, metrics=MetricsRegistry(),
        wall=lambda: BASE, clock=clk,
    )
    lease.acquire()
    assert lease.valid
    calls_after_acquire = counting.calls

    clk.advance(DURATION + 1.0)
    with pytest.raises(RolloutFenced):
        lease.check()
    assert lease.lost
    assert counting.calls == calls_after_acquire


def test_future_stamped_dead_holder_is_observed_and_taken_over(monkeypatch):
    """A dead holder whose last stamp came from a clock 100 s AHEAD of
    ours looks perpetually live to wall math. The legacy (skew-unaware)
    lease waits for our clock to catch up; the skew-aware one observes
    the frozen token for one duration and takes over."""
    fake = FakeKube()
    stamp_holder(fake, lambda: BASE + 100.0)

    legacy = contender(fake, lambda: BASE, Clock(), max_skew=0.0)
    with pytest.raises(LeaseHeld):
        legacy.acquire()

    clk = Clock()
    monkeypatch.setattr(
        retry_mod, "wait", lambda s, stop=None: clk.advance(s)
    )
    aware = contender(fake, lambda: BASE, clk)
    aware.acquire()  # frozen token for a full duration: holder dead
    assert fake.get_lease(NS, LEASE)["spec"]["holderIdentity"] == "holder-b"


def test_third_party_takeover_mid_observation_raises_held(monkeypatch):
    """Any token change during the observation window proves a live
    writer — including a THIRD contender's takeover, which must surface
    as LeaseHeld naming the new holder, not as our own takeover."""
    fake = FakeKube()
    stamp_holder(fake, lambda: BASE - 500.0)  # long-expired stamp

    clk = Clock()
    fired = {"done": False}

    def interloping_wait(delay_s, stop=None):
        clk.advance(delay_s)
        if not fired["done"]:
            fired["done"] = True
            lease = fake.get_lease(NS, LEASE)
            lease["spec"]["holderIdentity"] = "holder-c"
            lease["spec"]["renewTime"] = "1970-01-01T00:00:59Z"
            lease["spec"]["leaseTransitions"] = 9
            fake.update_lease(NS, LEASE, lease)
        return False

    monkeypatch.setattr(retry_mod, "wait", interloping_wait)
    b = contender(fake, lambda: BASE, clk)
    with pytest.raises(LeaseHeld, match="holder-c"):
        b.acquire()
