"""Pipelined node transitions (ccmanager/manager.py): stage-during-drain,
per-chip parallel reset, readmit-overlapped-smoke, and the attestation-
digest smoke fast path.

Three families, matching the guarantees the pipeline must NOT trade away:

- **ordering**: reset never runs while any drained component's pods are
  still on the node (the strict-eviction guarantee, checked as a seeded
  concurrency property), and re-admission never starts before the
  hardware verifiably holds the committed mode;
- **crash safety**: kill-at-every-crash-point in the style of
  tests/test_rollout_resume.py — a modeled SIGKILL at each overlap
  boundary, then a fresh agent replaying the intent journal; every chip
  is reset exactly once across the crash, never twice;
- **fast path**: CC_SMOKE_DIGEST_FAST_PATH skips the full smoke ONLY on
  an unchanged verified digest; a changed digest (or no record, or the
  env off) always falls through to the full smoke.
"""

from __future__ import annotations

import dataclasses
import json
import os
import random
import threading
import time

import pytest

from tpu_cc_manager.ccmanager.intent_journal import IntentJournal
from tpu_cc_manager.ccmanager.manager import CCManager
from tpu_cc_manager.drain.pause import is_paused
from tpu_cc_manager.kubeclient.api import node_labels
from tpu_cc_manager.labels import (
    CC_MODE_STATE_LABEL,
    DRAIN_COMPONENT_LABELS,
    MODE_OFF,
    MODE_ON,
)
from tpu_cc_manager.obs.journal import Journal
from tpu_cc_manager.tpudev.fake import FakeTpuBackend, sign_fake_quote
from tpu_cc_manager.utils.metrics import MetricsRegistry
from tpu_cc_manager.utils import retry as retry_mod

NODE = "pipe-node-0"
NS = "tpu-operator"
DP_LABEL = "google.com/tpu.deploy.device-plugin"
DP_APP = DRAIN_COMPONENT_LABELS[DP_LABEL]


class AgentKilled(BaseException):
    """Models a SIGKILL landing inside the agent (BaseException so the
    manager's except-Exception failure handler cannot run 'cleanup' a
    real SIGKILL would never run)."""


def make_manager(kube, backend, **kw):
    kw.setdefault("evict_components", True)
    kw.setdefault("smoke_workload", "none")
    kw.setdefault("metrics", MetricsRegistry())
    kw.setdefault("journal", Journal(trace_file=""))
    kw.setdefault("eviction_timeout_s", 5)
    kw.setdefault("eviction_poll_interval_s", 0.01)
    return CCManager(
        api=kube, backend=backend, node_name=NODE,
        operator_namespace=NS, **kw,
    )


def add_drainable_node(kube, pod_delete_delay_s: float = 0.0):
    """One node with a drainable component whose pod the emulated operator
    controller deletes (after a delay) once the pause label lands."""
    kube.add_node(NODE, {DP_LABEL: "true"})
    kube.add_pod(NS, "dp-0", NODE, labels={"app": DP_APP})

    def reactor(name, patched):
        if is_paused(node_labels(patched).get(DP_LABEL)):
            if pod_delete_delay_s > 0:
                t = threading.Timer(
                    pod_delete_delay_s, kube.delete_pods_matching,
                    (NS, f"app={DP_APP}"),
                )
                t.daemon = True
                t.start()
            else:
                kube.delete_pods_matching(NS, f"app={DP_APP}")

    kube.add_patch_reactor(reactor)


def reset_ops(backend):
    return [op for op, _ in backend.op_log if op == "reset"]


def chip_reset_counts(backend):
    counts: dict[int, int] = {}
    for op, payload in backend.op_log:
        if op == "reset":
            for idx in payload:
                counts[idx] = counts.get(idx, 0) + 1
        elif op == "reset.chip":
            # per-chip entries ride inside a whole-set reset() call; the
            # whole-set entry already counted them.
            pass
    return counts


# ---------------------------------------------------------------------------
# Ordering: stage-during-drain never resets under undrained components
# ---------------------------------------------------------------------------


def test_stage_overlaps_drain_but_reset_waits(fake_kube):
    """The stage op lands while the drain is still waiting on pods, and
    the reset only runs after every component pod left the node."""
    add_drainable_node(fake_kube, pod_delete_delay_s=0.15)
    backend = FakeTpuBackend()
    observed = {}
    real_reset = backend.reset

    def observing_reset(chips):
        observed["pods_at_reset"] = len(fake_kube.list_pods(
            NS, label_selector=f"app={DP_APP}",
            field_selector=f"spec.nodeName={NODE}",
        ))
        observed["label_at_reset"] = node_labels(
            fake_kube.get_node(NODE)
        ).get(DP_LABEL)
        real_reset(chips)

    backend.reset = observing_reset
    mgr = make_manager(fake_kube, backend)
    t0 = time.monotonic()
    assert mgr.set_cc_mode(MODE_ON) is True
    elapsed = time.monotonic() - t0
    # The stage ran while the (0.15 s) pod wait was still in flight: the
    # reconcile paid one drain, not drain + stage serialized... the real
    # assertion is ordering, but the overlap shows up as stage finishing
    # before the drain's pod deletion could have.
    ops = [op for op, _ in backend.op_log]
    assert ops.index("stage") < ops.index("reset")
    assert observed["pods_at_reset"] == 0, "reset ran under undrained pods"
    assert is_paused(observed["label_at_reset"]), (
        "reset must run inside the pause bracket"
    )
    assert elapsed < 5, "pipeline must not serialize pathologically"


def test_reset_never_under_undrained_components_property(fake_kube):
    """Seeded concurrency property: across randomized pod-termination
    delays and per-chip reset timings, the reset NEVER observes a
    component pod still on the node, and never a component label outside
    its paused state (the strict-eviction guarantee, pipelined or not)."""
    rng = random.Random(1234)
    for round_no in range(12):
        from tpu_cc_manager.kubeclient.fake import FakeKube

        kube = FakeKube()
        add_drainable_node(kube, pod_delete_delay_s=rng.uniform(0, 0.05))
        backend = FakeTpuBackend(
            reset_latency_s=[rng.uniform(0, 0.01) for _ in range(4)],
            reset_parallelism_override=rng.choice([1, 2, 4]),
        )
        violations = []
        real_reset = backend.reset

        def checking_reset(chips, kube=kube, backend=backend,
                           violations=violations):
            pods = kube.list_pods(
                NS, label_selector=f"app={DP_APP}",
                field_selector=f"spec.nodeName={NODE}",
            )
            if pods:
                violations.append(f"{len(pods)} pod(s) at reset")
            label = node_labels(kube.get_node(NODE)).get(DP_LABEL)
            if not is_paused(label):
                violations.append(f"component label {label!r} not paused")
            real_reset(chips)

        backend.reset = checking_reset
        mgr = make_manager(kube, backend)
        assert mgr.set_cc_mode(MODE_ON) is True, f"round {round_no} failed"
        assert not violations, f"round {round_no}: {violations}"


def test_readmit_overlaps_smoke(fake_kube):
    """Re-admission runs WHILE the smoke workload executes: a smoke
    runner that blocks until the component label is unpaused can only
    complete if the readmit was kicked off concurrently."""
    add_drainable_node(fake_kube)
    backend = FakeTpuBackend()
    state = {}

    def blocking_smoke(workload):
        deadline = time.monotonic() + 5.0
        while time.monotonic() < deadline:
            if node_labels(fake_kube.get_node(NODE)).get(DP_LABEL) == "true":
                state["unpaused_during_smoke"] = True
                # Safe-to-release check: at readmit time every chip must
                # already hold the committed mode.
                state["committed_at_readmit"] = dict(backend.committed)
                return {"ok": True}
            # cclint: test-sleep-ok(bounded poll that must snapshot committed-state at the observation instant)
            time.sleep(0.005)
        state["unpaused_during_smoke"] = False
        return {"ok": True}

    mgr = make_manager(
        fake_kube, backend, smoke_workload="matmul",
        smoke_runner=blocking_smoke,
    )
    assert mgr.set_cc_mode(MODE_ON) is True
    assert state["unpaused_during_smoke"] is True, (
        "readmit never ran during the smoke — the overlap is gone"
    )
    assert all(
        v == MODE_ON for v in state["committed_at_readmit"].values()
    ), "readmit released the pause bracket before the mode was committed"


def test_smoke_failure_still_readmits_and_fails(fake_kube):
    """The overlapped readmit does not change failure semantics: a failed
    smoke labels the node failed AND components are restored."""
    add_drainable_node(fake_kube)
    backend = FakeTpuBackend()

    def failing_smoke(workload):
        raise RuntimeError("numerics mismatch")

    mgr = make_manager(
        fake_kube, backend, smoke_workload="matmul",
        smoke_runner=failing_smoke,
    )
    assert mgr.set_cc_mode(MODE_ON) is False
    labels = node_labels(fake_kube.get_node(NODE))
    assert labels[DP_LABEL] == "true"
    assert labels[CC_MODE_STATE_LABEL] == "failed"


def test_strict_drain_timeout_rolls_back_overlapped_stage(fake_kube):
    """Strict eviction + pipelining: the overlapped stage is rolled back
    when the drain times out — staged.json empty, intent aborted, no
    reset, components re-admitted."""
    fake_kube.add_node(NODE, {DP_LABEL: "true"})
    fake_kube.add_pod(NS, "stuck", NODE, labels={"app": DP_APP})  # never drains
    backend = FakeTpuBackend()
    mgr = make_manager(
        fake_kube, backend, strict_eviction=True, eviction_timeout_s=0.05,
    )
    assert mgr.set_cc_mode(MODE_ON) is False
    assert backend.staged == {}, "overlapped stage must be rolled back"
    assert not reset_ops(backend)
    labels = node_labels(fake_kube.get_node(NODE))
    assert labels[DP_LABEL] == "true"
    assert labels[CC_MODE_STATE_LABEL] == "failed"


def test_overlap_metric_exported(fake_kube):
    """tpu_cc_phase_overlap_seconds reports the saving once phases
    actually overlap (drain 0.15 s ∥ stage here)."""
    add_drainable_node(fake_kube, pod_delete_delay_s=0.15)
    backend = FakeTpuBackend()
    orig_stage = backend.stage_cc_mode

    def slow_stage(chips, mode):
        # cclint: test-sleep-ok(simulated stage latency — the overlap under test)
        time.sleep(0.1)
        orig_stage(chips, mode)

    backend.stage_cc_mode = slow_stage
    registry = MetricsRegistry()
    mgr = make_manager(fake_kube, backend, metrics=registry)
    assert mgr.set_cc_mode(MODE_ON) is True
    text = registry.render_prometheus()
    assert "tpu_cc_phase_overlap_seconds" in text
    value = float([
        line for line in text.splitlines()
        if line.startswith("tpu_cc_phase_overlap_seconds")
    ][0].split()[-1])
    assert value > 0.05, f"expected real overlap, got {value}"


def test_pipeline_disabled_restores_serial_order(fake_kube):
    """CC_PIPELINE_TRANSITIONS=0 (the safety valve): stage strictly after
    the drain completes, readmit strictly after the smoke."""
    add_drainable_node(fake_kube, pod_delete_delay_s=0.05)
    backend = FakeTpuBackend()
    events = []
    orig_stage = backend.stage_cc_mode

    def logging_stage(chips, mode):
        events.append(("stage_at", len(fake_kube.list_pods(
            NS, label_selector=f"app={DP_APP}",
            field_selector=f"spec.nodeName={NODE}",
        ))))
        orig_stage(chips, mode)

    backend.stage_cc_mode = logging_stage
    mgr = make_manager(fake_kube, backend, pipeline_transitions=False)
    assert mgr.set_cc_mode(MODE_ON) is True
    assert events == [("stage_at", 0)], (
        "serial mode must stage only after the drain emptied the node"
    )


# ---------------------------------------------------------------------------
# Kill-at-every-crash-point: exactly one reset per chip, no unsafe readmit
# ---------------------------------------------------------------------------


def _arm_kill(backend, op_name, when="before"):
    """Replace backend.<op_name> so the NEXT call raises AgentKilled —
    before or after the real op runs."""
    real = getattr(backend, op_name)
    armed = {"live": True}

    def killer(*args, **kwargs):
        if not armed["live"]:
            return real(*args, **kwargs)
        armed["live"] = False
        if when == "after":
            real(*args, **kwargs)
        raise AgentKilled()

    setattr(backend, op_name, killer)
    return armed


# (Test-local kill specs, not orchestrator crash-point names: the
# cclint crash-point checker reserves *CRASH_POINTS* list names for
# declarations of package point literals.)
PIPELINE_KILL_SPECS = [
    # (name, op to kill in, before/after the real op)
    ("during-overlapped-stage", "stage_cc_mode", "before"),
    ("after-stage-before-reset", "stage_cc_mode", "after"),
    ("before-device-reset", "reset", "before"),
    ("after-device-reset", "reset", "after"),
    ("during-wait-ready", "wait_ready", "before"),
]


@pytest.mark.parametrize("name,op,when", PIPELINE_KILL_SPECS)
def test_kill_at_crash_point_exactly_one_reset(tmp_path, name, op, when):
    """A modeled SIGKILL at each pipeline crash point, then a fresh agent
    replaying the intent journal: the successor converges to the desired
    mode, every chip reset EXACTLY once across the crash, and no readmit
    ever released the pause bracket while the hardware was mid-flip."""
    from tpu_cc_manager.kubeclient.fake import FakeKube

    kube = FakeKube()
    add_drainable_node(kube)
    backend = FakeTpuBackend()
    journal1 = IntentJournal.from_state_dir(str(tmp_path))

    # Every unpause write is checked against hardware truth at that
    # instant: either the chips all hold the final mode, or nothing
    # disruptive ever ran (pre-reset rollback).
    unsafe_readmits = []

    def readmit_guard(node_name, patched):
        if node_labels(patched).get(DP_LABEL) == "true":
            committed = dict(backend.committed)
            resets = reset_ops(backend)
            safe = (
                all(v == MODE_ON for v in committed.values())
                or not resets
            )
            if not safe:
                unsafe_readmits.append((committed, resets))

    kube.add_patch_reactor(readmit_guard)

    mgr1 = make_manager(
        kube, backend, intent_journal=journal1, state_dir=str(tmp_path),
    )
    _arm_kill(backend, op, when)
    with pytest.raises(AgentKilled):
        mgr1.set_cc_mode(MODE_ON)
    # mgr1 is dead. Crash truth: at most one reset so far.
    resets_after_crash = len(reset_ops(backend))
    assert resets_after_crash <= 1

    # ---- restart: fresh journal handle, journal replay, reconcile -----
    journal2 = IntentJournal.from_state_dir(str(tmp_path))
    mgr2 = make_manager(
        kube, backend, intent_journal=journal2, state_dir=str(tmp_path),
    )
    mgr2.recover_from_journal()
    assert mgr2.set_cc_mode(MODE_ON) is True, f"crash point {name}"

    counts = chip_reset_counts(backend)
    assert counts and all(c == 1 for c in counts.values()), (
        f"crash point {name}: per-chip reset counts {counts} != 1"
    )
    assert not journal2.open_intents("transition")
    assert not journal2.open_intents("drain")
    labels = node_labels(kube.get_node(NODE))
    assert labels[CC_MODE_STATE_LABEL] == MODE_ON
    assert labels[DP_LABEL] == "true", "components must end re-admitted"
    assert not unsafe_readmits, (
        f"crash point {name}: readmit released the pause bracket "
        f"mid-flip: {unsafe_readmits}"
    )


def test_kill_mid_parallel_per_chip_reset(tmp_path):
    """A kill landing inside the per-chip reset pool (one chip's worker
    dies before its work): the survivors' chips committed, the killed
    chip stays staged, and the successor's re-apply resets the REMAINING
    work without double-resetting any committed chip... the fake promotes
    per chip, so the property is: after recovery every chip holds the
    mode and no chip saw more than 2 reset.chip events with at most one
    effective commit."""
    from tpu_cc_manager.kubeclient.fake import FakeKube

    kube = FakeKube()
    add_drainable_node(kube)
    backend = FakeTpuBackend(
        reset_latency_s=[0.0, 0.0, 0.0, 0.0], reset_parallelism_override=1,
    )
    journal1 = IntentJournal.from_state_dir(str(tmp_path))
    # Kill chip 2's worker before it runs: serial pool (parallelism 1)
    # makes the cut deterministic — chips 0,1 committed, 2,3 not.
    backend.fail["reset.chip2"] = 1
    real_fail = backend._maybe_fail

    def kill_fail(op):
        if op == "reset.chip2" and backend.fail.get(op):
            backend.fail[op] = 0
            raise AgentKilled()
        real_fail(op)

    backend._maybe_fail = kill_fail
    mgr1 = make_manager(
        kube, backend, intent_journal=journal1, state_dir=str(tmp_path),
    )
    with pytest.raises(AgentKilled):
        mgr1.set_cc_mode(MODE_ON)
    committed_mid = dict(backend.committed)
    assert committed_mid[0] == MODE_ON and committed_mid[1] == MODE_ON
    # Chip 2's worker died before its commit. (Chip 3's already-queued
    # worker may still have run — the in-process kill model cannot stop
    # the pool's other threads the way a real SIGKILL would; the
    # invariant under test is that the KILLED chip never half-commits.)
    assert committed_mid[2] == MODE_OFF
    # The journal holds the open reset-phase intent.
    assert journal1.open_intents("transition")[0]["phase"] == "reset"

    journal2 = IntentJournal.from_state_dir(str(tmp_path))
    mgr2 = make_manager(
        kube, backend, intent_journal=journal2, state_dir=str(tmp_path),
    )
    mgr2.recover_from_journal()
    assert mgr2.set_cc_mode(MODE_ON) is True
    assert all(v == MODE_ON for v in backend.committed.values())
    assert not journal2.open_intents("transition")
    labels = node_labels(kube.get_node(NODE))
    assert labels[CC_MODE_STATE_LABEL] == MODE_ON


# ---------------------------------------------------------------------------
# Attestation-digest smoke fast path
# ---------------------------------------------------------------------------


def smoke_counter():
    calls = []

    def runner(workload):
        calls.append(workload)
        return {"ok": True}

    return calls, runner


def test_digest_fastpath_skips_smoke_on_unchanged_digest(fake_kube, tmp_path):
    fake_kube.add_node(NODE)
    backend = FakeTpuBackend()
    calls, runner = smoke_counter()
    registry = MetricsRegistry()
    mgr = make_manager(
        fake_kube, backend, evict_components=False,
        smoke_workload="matmul", smoke_runner=runner,
        smoke_digest_fastpath=True, state_dir=str(tmp_path),
        metrics=registry,
    )
    # First flip: no record -> full smoke ("cold"), digest persisted.
    assert mgr.set_cc_mode(MODE_ON) is True
    assert calls == ["matmul"]
    record = json.loads(
        (tmp_path / "verified_digest.json").read_text()
    )
    assert record["mode"] == MODE_ON and record["digest"]
    # Bounce through off (full smoke — no quote for mode off), then back
    # on: unchanged digest -> attest-only verify, smoke SKIPPED.
    assert mgr.set_cc_mode(MODE_OFF) is True
    assert mgr.set_cc_mode(MODE_ON) is True
    assert calls == ["matmul", "matmul"], (
        f"expected the second 'on' flip to skip the smoke, got {calls}"
    )
    totals = registry.smoke_fastpath_totals()
    assert totals.get("cold") == 1 and totals.get("hit") == 1
    text = registry.render_prometheus()
    assert 'tpu_cc_smoke_fastpath_total{outcome="hit"} 1' in text


def test_digest_fastpath_changed_digest_runs_full_smoke(fake_kube, tmp_path):
    """A CHANGED runtime digest (runtime update between flips) must always
    fall through to the full smoke — and re-persist the new digest."""
    fake_kube.add_node(NODE)
    backend = FakeTpuBackend()
    calls, runner = smoke_counter()
    registry = MetricsRegistry()
    mgr = make_manager(
        fake_kube, backend, evict_components=False,
        smoke_workload="matmul", smoke_runner=runner,
        smoke_digest_fastpath=True, state_dir=str(tmp_path),
        metrics=registry,
    )
    assert mgr.set_cc_mode(MODE_ON) is True
    old_digest = json.loads(
        (tmp_path / "verified_digest.json").read_text()
    )["digest"]
    # The runtime updates underneath (libtpu roll): the fake's measured
    # digest changes, re-signed so attestation still verifies.
    real_attest = backend.fetch_attestation

    def updated_runtime_attest(nonce):
        quote = real_attest(nonce)
        measurements = dict(quote.measurements)
        measurements["runtime_digest"] = "updated-runtime-build"
        return dataclasses.replace(
            quote,
            measurements=measurements,
            signature=sign_fake_quote(
                quote.slice_id, nonce, quote.mode, measurements
            ),
        )

    backend.fetch_attestation = updated_runtime_attest
    assert mgr.set_cc_mode(MODE_OFF) is True
    assert mgr.set_cc_mode(MODE_ON) is True
    # off-flip smoke + the changed-digest on-flip smoke: NO skip.
    assert calls == ["matmul", "matmul", "matmul"]
    assert registry.smoke_fastpath_totals().get("miss") == 1
    new_digest = json.loads(
        (tmp_path / "verified_digest.json").read_text()
    )["digest"]
    assert new_digest != old_digest, "full smoke must re-persist the digest"
    # And the NEXT flip on the updated runtime hits.
    assert mgr.set_cc_mode(MODE_OFF) is True
    assert mgr.set_cc_mode(MODE_ON) is True
    assert registry.smoke_fastpath_totals().get("hit") == 1


def test_digest_fastpath_off_by_default(fake_kube, tmp_path):
    fake_kube.add_node(NODE)
    backend = FakeTpuBackend()
    calls, runner = smoke_counter()
    mgr = make_manager(
        fake_kube, backend, evict_components=False,
        smoke_workload="matmul", smoke_runner=runner,
        state_dir=str(tmp_path),
    )
    assert mgr.smoke_digest_fastpath is False
    assert mgr.set_cc_mode(MODE_ON) is True
    assert mgr.set_cc_mode(MODE_OFF) is True
    assert mgr.set_cc_mode(MODE_ON) is True
    # Every flip ran its smoke; the persisted digest (written regardless,
    # so enabling the env later hits immediately) skipped nothing.
    assert calls == ["matmul"] * 3


def test_failed_smoke_does_not_persist_digest(fake_kube, tmp_path):
    fake_kube.add_node(NODE)
    backend = FakeTpuBackend()

    def failing(workload):
        raise RuntimeError("bad numerics")

    mgr = make_manager(
        fake_kube, backend, evict_components=False,
        smoke_workload="matmul", smoke_runner=failing,
        smoke_digest_fastpath=True, state_dir=str(tmp_path),
    )
    assert mgr.set_cc_mode(MODE_ON) is False
    assert not os.path.exists(tmp_path / "verified_digest.json"), (
        "a failed smoke must never mint a verified digest"
    )


def test_digest_fastpath_garbled_record_falls_through(fake_kube, tmp_path):
    fake_kube.add_node(NODE)
    backend = FakeTpuBackend()
    calls, runner = smoke_counter()
    (tmp_path / "verified_digest.json").write_text("not json{")
    mgr = make_manager(
        fake_kube, backend, evict_components=False,
        smoke_workload="matmul", smoke_runner=runner,
        smoke_digest_fastpath=True, state_dir=str(tmp_path),
    )
    assert mgr.set_cc_mode(MODE_ON) is True
    assert calls == ["matmul"], "garbled record must mean full smoke"


def test_attest_prep_overlaps_wait_ready(fake_kube):
    """prepare_attestation (the tpuvm measured-file hash warm-up) is
    invoked while wait_ready is still polling."""
    add_drainable_node(fake_kube)
    backend = FakeTpuBackend(boot_latency_s=0.1)
    state = {}
    real_wait = backend.wait_ready

    def prep():
        # Runs on the prep worker concurrently with tracking_wait: it
        # must OBSERVE the boot wait in flight (0.1 s window) — a serial
        # prep (before or after wait_ready) never sees waiting=True.
        state["prep_during_boot"] = retry_mod.poll_until(
            lambda: bool(state.get("waiting")), 2.0, 0.005
        )

    def tracking_wait(chips, timeout_s):
        state["waiting"] = True
        real_wait(chips, timeout_s)
        state["waiting"] = False

    backend.prepare_attestation = prep
    backend.wait_ready = tracking_wait
    mgr = make_manager(fake_kube, backend)
    assert mgr.set_cc_mode(MODE_ON) is True
    assert state.get("prep_during_boot") is True, (
        "attestation prep must run during the boot wait"
    )
