"""The validation-faithful mock apiserver (hack/mock_apiserver.py).

VERDICT r4 missing #2: no kind/kubectl exists in this image, so the
claims "our label writes survive apiserver validation" and "the DaemonSet
RBAC covers every verb the agent uses" are enforced by the mock the demos
and these tests run against — the real RestKube client over real HTTP,
with the real ClusterRole manifest as the authz source of truth.
"""

from __future__ import annotations

import json
import os
import sys
import threading
import time
from http.server import ThreadingHTTPServer

import pytest

from _hypothesis_compat import given, st

from tpu_cc_manager.kubeclient.api import KubeApiError, node_annotations, node_labels
from tpu_cc_manager.kubeclient.rest import ClusterConfig, RestKube
from tpu_cc_manager.utils import retry as retry_mod

sys.path.insert(
    0, os.path.join(os.path.dirname(os.path.dirname(__file__)), "hack")
)
import mock_apiserver  # noqa: E402


@pytest.fixture(scope="module")
def server():
    mock_apiserver.add_node("val-node-0")
    srv = ThreadingHTTPServer(("127.0.0.1", 0), mock_apiserver.Handler)
    t = threading.Thread(target=srv.serve_forever, daemon=True)
    t.start()
    yield srv
    srv.shutdown()


@pytest.fixture()
def client(server):
    kube = RestKube(
        ClusterConfig(server=f"http://127.0.0.1:{server.server_port}")
    )
    kube.retry_attempts = 1  # validation rejections must surface, not retry
    return kube


NODE = "val-node-0"


def test_grants_come_from_the_real_cluster_role_manifest():
    """The mock's authz set IS the DaemonSet ClusterRole: editing the
    manifest without the agent (or vice versa) fails the demos."""
    assert mock_apiserver.GRANTS == {
        ("get", "nodes"), ("list", "nodes"), ("watch", "nodes"),
        ("patch", "nodes"), ("list", "pods"), ("create", "events"),
        ("get", "leases"), ("create", "leases"), ("update", "leases"),
        ("delete", "leases"),
    }


def test_valid_label_patch_passes(client):
    client.patch_node_labels(NODE, {"cloud.google.com/tpu-cc.mode": "on"})
    labels = node_labels(client.get_node(NODE))
    assert labels["cloud.google.com/tpu-cc.mode"] == "on"


def test_invalid_label_value_is_422(client):
    with pytest.raises(KubeApiError) as exc:
        client.patch_node_labels(NODE, {"k": "not ok!"})
    assert exc.value.status == 422
    with pytest.raises(KubeApiError) as exc:
        client.patch_node_labels(NODE, {"k": "x" * 64})
    assert exc.value.status == 422
    with pytest.raises(KubeApiError) as exc:
        client.patch_node_labels(NODE, {"k": "-edge-"})
    assert exc.value.status == 422
    # Trailing newline: Python's $-anchored match would admit it; the real
    # apiserver does not. fullmatch keeps the mock faithful.
    assert mock_apiserver.validate_label_patch({"k": "on\n"}) is not None
    assert mock_apiserver.validate_label_patch({"k\n": "v"}) is not None


def test_invalid_label_key_is_422(client):
    with pytest.raises(KubeApiError) as exc:
        client.patch_node_labels(NODE, {"Bad_Prefix!/name": "v"})
    assert exc.value.status == 422
    with pytest.raises(KubeApiError) as exc:
        client.patch_node_labels(NODE, {"prefix/" + "n" * 64: "v"})
    assert exc.value.status == 422


def test_annotation_patch_roundtrip_and_size_cap(client):
    client.patch_node_annotations(NODE, {"cloud.google.com/tpu-cc.quote": "{}"})
    anns = node_annotations(client.get_node(NODE))
    assert anns["cloud.google.com/tpu-cc.quote"] == "{}"
    # Values may be arbitrary text (unlike labels) — but bounded in total.
    with pytest.raises(KubeApiError) as exc:
        client.patch_node_annotations(NODE, {"big": "x" * (257 * 1024)})
    assert exc.value.status == 422
    # Deletion via None merge-patch semantics.
    client.patch_node_annotations(
        NODE, {"cloud.google.com/tpu-cc.quote": None}
    )
    assert "cloud.google.com/tpu-cc.quote" not in node_annotations(
        client.get_node(NODE)
    )


def test_ungranted_verb_is_403(client, monkeypatch):
    """An agent regression that grows an apiserver call outside the
    ClusterRole's grants breaks loudly, as on a real cluster."""
    monkeypatch.setattr(
        mock_apiserver, "GRANTS",
        mock_apiserver.GRANTS - {("patch", "nodes")},
    )
    with pytest.raises(KubeApiError) as exc:
        client.patch_node_labels(NODE, {"k": "v"})
    assert exc.value.status == 403
    # list pods remains granted.
    client.list_pods("tpu-operator")


def test_everything_the_agent_writes_passes_validation():
    """The union of label values the agent can emit — mode/state/ready
    values, failure reasons, pause values, drain-cycle tokens, quote
    digest labels — passes the apiserver's validation rules."""
    from tpu_cc_manager.ccmanager.multislice import quote_label_patch
    from tpu_cc_manager.drain import handshake
    from tpu_cc_manager.drain.pause import pause_value
    from tpu_cc_manager.tpudev.fake import FakeTpuBackend

    patches: list[dict] = [
        {"cloud.google.com/tpu-cc.mode.state": s}
        for s in ("on", "off", "devtools", "slice", "failed", "resetting")
    ]
    patches.append(
        {handshake.DRAIN_REQUESTED_LABEL:
         handshake.request_value(handshake.new_cycle_token())}
    )
    patches.append(
        {handshake.subscriber_label("My Job/π"):
         handshake.ack_value(handshake.new_cycle_token())}
    )
    patches.append({"google.com/tpu.deploy.device-plugin":
                    pause_value("true")})
    quote = FakeTpuBackend(initial_mode="on").fetch_attestation("n0nce")
    patches.append({
        k: v for k, v in quote_label_patch(quote).items() if v is not None
    })
    for patch in patches:
        assert mock_apiserver.validate_label_patch(patch) is None, patch


@given(st.text(max_size=120))
def test_label_safe_always_passes_apiserver_validation(raw):
    """labels.label_safe is the client-side sanitizer; the mock's
    validator is the server's rule. Property: anything label_safe emits,
    the apiserver accepts — the two can never drift apart silently."""
    from tpu_cc_manager.labels import label_safe

    assert mock_apiserver.validate_label_patch({"k": label_safe(raw)}) is None


def test_watch_carries_bookmark_events(server, client):
    """The manager's BOOKMARK branch (ccmanager/manager.py watch loop) is
    exercised over real HTTP: the mock, like a real apiserver, sends
    metadata-only BOOKMARK frames to watchers that asked via
    allowWatchBookmarks=true (which RestKube.watch_nodes always does)."""
    # The module-scope fixture starts only the HTTP server; run the
    # writer thread and inject the ticker's sentinel directly instead of
    # waiting out a wall-clock interval.
    threading.Thread(target=mock_apiserver._watch_writer, daemon=True).start()

    seen = {}

    def consume():
        for ev in client.watch_nodes(NODE, timeout_seconds=5):
            if ev.type == "BOOKMARK":
                seen["event"] = ev
                return

    t = threading.Thread(target=consume, daemon=True)
    t.start()
    def pump() -> bool:
        mock_apiserver._event_queue.put((mock_apiserver._BOOKMARK, b""))
        return "event" in seen

    retry_mod.poll_until(pump, 5.0, 0.1)
    t.join(timeout=5)
    assert "event" in seen, "no BOOKMARK event reached the watch client"
    ev = seen["event"]
    # Bookmarks are metadata-only: a fresh resourceVersion, no labels —
    # exactly the shape the manager's branch exists to not misread.
    md = ev.object.get("metadata", {})
    assert md.get("resourceVersion")
    assert "labels" not in md


def test_watch_without_optin_gets_no_bookmarks(server):
    """The gating half of the contract: a watcher that did NOT send
    allowWatchBookmarks=true (RestKube always does, so go below it to
    raw HTTP) must never receive BOOKMARK frames, no matter how many the
    ticker broadcasts."""
    import json as _json
    import urllib.request

    threading.Thread(target=mock_apiserver._watch_writer, daemon=True).start()

    url = (
        f"http://127.0.0.1:{server.server_port}/api/v1/nodes"
        f"?watch=true&fieldSelector=metadata.name={NODE}&timeoutSeconds=2"
    )
    types = []

    def consume():
        with urllib.request.urlopen(url, timeout=5) as resp:
            for line in resp:
                line = line.strip()
                if line:
                    types.append(_json.loads(line)["type"])

    t = threading.Thread(target=consume, daemon=True)
    t.start()
    for _ in range(10):
        mock_apiserver._event_queue.put((mock_apiserver._BOOKMARK, b""))
        # cclint: test-sleep-ok(paced pumping for a NEGATIVE assertion — no bookmark may reach the client)
        time.sleep(0.05)
    t.join(timeout=10)
    assert types and "BOOKMARK" not in types, types


def test_compacted_watch_resume_is_410(server, client):
    """The manager's 410-resync path gets its wire-level answer: after
    /_ctl/compact, a watch resuming from a genuinely stale resourceVersion
    is refused with HTTP 410 (KubeApiError.status == 410 — exactly what
    watch_and_apply catches to re-GET and resync), while a fresh watch
    (no resourceVersion) still opens. resourceVersion="0" is the
    documented exception: real apiservers define it as "any version /
    serve from cache" and never 410 it (ADVICE.md round 5), so the mock
    must not either."""
    import urllib.request

    # Advance the server's rv past 1 so "1" is genuinely stale once the
    # compaction floor rises to the current rv.
    client.patch_node_labels(NODE, {"compaction-test": "bump"})
    url = f"http://127.0.0.1:{server.server_port}/_ctl/compact"
    req = urllib.request.Request(url, data=b"{}", method="POST")
    with urllib.request.urlopen(req, timeout=5) as resp:
        floor = json.loads(resp.read())["compacted_below"]
    assert floor > 1

    try:
        with pytest.raises(KubeApiError) as exc:
            next(iter(client.watch_nodes(NODE, resource_version="1",
                                         timeout_seconds=2)))
        assert exc.value.status == 410

        # rv="0" means "any version" on a real apiserver — it must open
        # (replaying current state as ADDED), compaction notwithstanding.
        ev = next(iter(client.watch_nodes(NODE, resource_version="0",
                                          timeout_seconds=2)))
        assert ev.type == "ADDED"

        # No resourceVersion → fresh watch, replays current state as
        # ADDED.
        ev = next(iter(client.watch_nodes(NODE, timeout_seconds=2)))
        assert ev.type == "ADDED"
    finally:
        # The module-scope server is shared; don't leave the floor up for
        # whichever test runs next.
        mock_apiserver.compacted_below[0] = 0


def test_node_patch_with_stale_resource_version_is_409(client):
    """Satellite (ISSUE 4): update verbs honor optimistic concurrency —
    a PATCH naming a stale metadata.resourceVersion gets 409 Conflict
    exactly as a real apiserver answers, instead of last-write-wins."""
    current = client.get_node(NODE)["metadata"]["resourceVersion"]
    # A conditional patch at the CURRENT rv lands...
    client._request_json(
        "PATCH", f"/api/v1/nodes/{NODE}",
        body={"metadata": {"resourceVersion": current,
                           "labels": {"occ-test": "v1"}}},
        content_type="application/merge-patch+json",
    )
    # ...which bumps the rv, so re-sending the SAME rv now conflicts.
    with pytest.raises(KubeApiError) as exc:
        client._request_json(
            "PATCH", f"/api/v1/nodes/{NODE}",
            body={"metadata": {"resourceVersion": current,
                               "labels": {"occ-test": "v2"}}},
            content_type="application/merge-patch+json",
        )
    assert exc.value.status == 409
    assert node_labels(client.get_node(NODE))["occ-test"] == "v1"


def test_lease_lifecycle_over_http(client):
    """RestKube's coordination.k8s.io verbs against the mock: create,
    get, CAS update (stale rv -> 409), delete — the wire surface the
    rollout lease (ccmanager/rollout_state.py) runs on."""
    ns = "tpu-operator"
    created = client.create_lease(ns, "occ-lease", {
        "holderIdentity": "orch-a", "leaseDurationSeconds": 15,
        "leaseTransitions": 1,
    })
    assert created["spec"]["holderIdentity"] == "orch-a"
    with pytest.raises(KubeApiError) as exc:
        client.create_lease(ns, "occ-lease", {"holderIdentity": "orch-b"})
    assert exc.value.status == 409

    fresh = client.get_lease(ns, "occ-lease")
    stale_rv = fresh["metadata"]["resourceVersion"]
    fresh["spec"]["holderIdentity"] = "orch-a"
    fresh["spec"]["leaseTransitions"] = 2
    fresh["metadata"].setdefault("annotations", {})[
        "cloud.google.com/tpu-cc.rollout-record"
    ] = "{}"
    updated = client.update_lease(ns, "occ-lease", fresh)
    assert updated["spec"]["leaseTransitions"] == 2
    assert updated["metadata"]["annotations"]

    stale = {
        "metadata": {"resourceVersion": stale_rv},
        "spec": {"holderIdentity": "orch-b"},
    }
    with pytest.raises(KubeApiError) as exc:
        client.update_lease(ns, "occ-lease", stale)
    assert exc.value.status == 409
    assert client.get_lease(ns, "occ-lease")["spec"][
        "holderIdentity"
    ] == "orch-a"

    client.delete_lease(ns, "occ-lease")
    with pytest.raises(KubeApiError) as exc:
        client.get_lease(ns, "occ-lease")
    assert exc.value.status == 404


def test_chunked_list_pagination_over_http(client):
    """Satellite (ISSUE 6): the mock pages big listings through the real
    limit/continue protocol, so the informer's chunked initial sync
    (list_nodes_chunked -> RestKube.list_nodes_page) is exercised over
    real HTTP instead of only against FakeKube."""
    from tpu_cc_manager.kubeclient.api import list_nodes_chunked

    for i in range(7):
        mock_apiserver.add_node(f"page-node-{i}")
    try:
        page = client.list_nodes_page(limit=3)
        assert len(page["items"]) == 3
        token = page["metadata"]["continue"]
        assert token

        # Walking every page yields exactly the unchunked listing, plus
        # the listing's resourceVersion for a follow-up watch.
        items, rv = list_nodes_chunked(client, limit=3)
        names = [n["metadata"]["name"] for n in items]
        assert names == sorted(
            n["metadata"]["name"] for n in client.list_nodes()
        )
        assert rv and rv.isdigit()

        # An unparseable continue token answers 410 Expired — the
        # "restart your listing" signal the informer's relist path rides.
        with pytest.raises(KubeApiError) as exc:
            client.list_nodes_page(limit=3, continue_token="bogus!")
        assert exc.value.status == 410
    finally:
        with mock_apiserver.lock:
            for i in range(7):
                mock_apiserver.nodes.pop(f"page-node-{i}", None)


def test_selector_watch_synthesizes_deleted_on_label_change(server, client):
    """A selector-scoped watcher (the informer cache's watch) must see a
    node whose labels STOP matching as DELETED — the rule a real
    apiserver applies, and what keeps the cache from serving nodes that
    left the pool."""
    threading.Thread(target=mock_apiserver._watch_writer, daemon=True).start()
    mock_apiserver.add_node("pool-watch-node")
    seen: list = []
    done = threading.Event()

    def consume():
        try:
            for ev in client.watch_nodes_pool(
                "watch-pool=a", timeout_seconds=5
            ):
                seen.append((ev.type, ev.object["metadata"]["name"]))
                if ev.type == "DELETED":
                    done.set()
                    return
        except KubeApiError:
            pass

    try:
        client.patch_node_labels("pool-watch-node", {"watch-pool": "a"})
        t = threading.Thread(target=consume, daemon=True)
        t.start()
        assert retry_mod.poll_until(
            lambda: any(n == "pool-watch-node" for _, n in seen), 5.0, 0.05
        ), f"never saw the node: {seen}"
        # Leaving the selector arrives as DELETED, not MODIFIED.
        client.patch_node_labels("pool-watch-node", {"watch-pool": "b"})
        assert done.wait(5.0), f"no DELETED event: {seen}"
        assert ("DELETED", "pool-watch-node") in seen
    finally:
        with mock_apiserver.lock:
            mock_apiserver.nodes.pop("pool-watch-node", None)


def test_request_counters_served_at_ctl_endpoint(server, client):
    """Satellite (ISSUE 6): the mock counts requests per verb and serves
    them at POST /_ctl/requests, so the scale harness and demos can read
    the apiserver-side QPS an orchestrator generated."""
    import urllib.request

    def counters():
        req = urllib.request.Request(
            f"http://127.0.0.1:{server.server_port}/_ctl/requests",
            data=b"{}", method="POST",
        )
        with urllib.request.urlopen(req, timeout=5) as resp:
            return json.loads(resp.read())["requests"]

    before = counters()
    client.get_node(NODE)
    client.list_nodes()
    after = counters()
    assert after.get("get", 0) == before.get("get", 0) + 1
    assert after.get("list", 0) == before.get("list", 0) + 1
