"""Device layer: fake backend semantics, tpuvm backend with injected
environment, attestation verification."""

import os

import pytest

from tpu_cc_manager.labels import MODE_OFF, MODE_ON
from tpu_cc_manager.tpudev import load_backend
from tpu_cc_manager.tpudev.attestation import (
    AttestationError,
    fresh_nonce,
    verify_quote,
)
from tpu_cc_manager.tpudev.contract import TpuError
from tpu_cc_manager.tpudev.fake import FakeTpuBackend
from tpu_cc_manager.tpudev.tpuvm import TpuVmBackend, parse_accelerator_type


class TestFakeBackend:
    def test_stage_then_reset_commits(self, fake_tpu):
        topo = fake_tpu.discover()
        chips = topo.chips
        fake_tpu.stage_cc_mode(chips, MODE_ON)
        # Staged but not committed yet.
        assert all(fake_tpu.query_cc_mode(c) == MODE_OFF for c in chips)
        fake_tpu.reset(chips)
        fake_tpu.wait_ready(chips, timeout_s=1)
        assert all(fake_tpu.query_cc_mode(c) == MODE_ON for c in chips)

    def test_fault_injection(self, fake_tpu):
        fake_tpu.fail_next("reset")
        with pytest.raises(TpuError):
            fake_tpu.reset(fake_tpu.discover().chips)
        fake_tpu.reset(fake_tpu.discover().chips)  # next call succeeds

    def test_attestation_roundtrip(self, fake_tpu):
        topo = fake_tpu.discover()
        fake_tpu.stage_cc_mode(topo.chips, MODE_ON)
        fake_tpu.reset(topo.chips)
        nonce = fresh_nonce()
        quote = fake_tpu.fetch_attestation(nonce)
        assert verify_quote(quote, nonce, MODE_ON, topo.slice_id) == []

    def test_attestation_rejects_tampering(self, fake_tpu):
        import dataclasses

        nonce = fresh_nonce()
        quote = fake_tpu.fetch_attestation(nonce)
        bad = dataclasses.replace(quote, signature="0" * 64)
        with pytest.raises(AttestationError):
            verify_quote(bad, nonce, MODE_OFF)

    def test_attestation_rejects_stale_nonce(self, fake_tpu):
        quote = fake_tpu.fetch_attestation("nonce-a")
        with pytest.raises(AttestationError):
            verify_quote(quote, "nonce-b", MODE_OFF)

    def test_devtools_policy_logs_instead_of_raising(self, fake_tpu):
        quote = fake_tpu.fetch_attestation("nonce-a")
        problems = verify_quote(quote, "nonce-b", MODE_OFF, debug_policy=True)
        assert problems  # reported, not raised


class TestTpuVmBackend:
    @pytest.fixture()
    def backend(self, tmp_path, monkeypatch):
        monkeypatch.setenv("TPU_ACCELERATOR_TYPE", "v5p-8")
        monkeypatch.setenv("TPU_WORKER_ID", "0")
        monkeypatch.delenv("TPU_SLICE_ID", raising=False)
        # Fabricate device nodes.
        devdir = tmp_path / "dev"
        devdir.mkdir()
        for i in range(4):
            (devdir / f"accel{i}").touch()
        return TpuVmBackend(
            state_dir=str(tmp_path / "state"),
            reset_cmd=["true"],
            metadata_url="http://127.0.0.1:1",  # unreachable -> env fallbacks
            device_glob=str(devdir / "accel*"),
        )

    def test_discover(self, backend):
        topo = backend.discover()
        assert topo.accelerator_type == "v5p-8"
        assert len(topo.chips) == 4
        assert topo.num_hosts == 1
        assert topo.host_index == 0

    def test_stage_reset_query_roundtrip(self, backend):
        topo = backend.discover()
        assert backend.query_cc_mode(topo.chips[0]) == MODE_OFF
        backend.stage_cc_mode(topo.chips, MODE_ON)
        assert backend.query_cc_mode(topo.chips[0]) == MODE_OFF  # not committed
        backend.reset(topo.chips)
        assert all(backend.query_cc_mode(c) == MODE_ON for c in topo.chips)
        backend.wait_ready(topo.chips, timeout_s=1)

    def test_reset_command_failure(self, backend):
        backend.reset_cmd = ["false"]
        topo = backend.discover()
        backend.stage_cc_mode(topo.chips, MODE_ON)
        with pytest.raises(TpuError):
            backend.reset(topo.chips)
        # Crash-safety: the failed reset must NOT look committed — the chip
        # reports an in-between state so idempotency checks re-apply.
        assert backend.query_cc_mode(topo.chips[0]) == "resetting"
        # Retry succeeds and commits.
        backend.reset_cmd = ["true"]
        backend.stage_cc_mode(topo.chips, MODE_ON)
        backend.reset(topo.chips)
        assert backend.query_cc_mode(topo.chips[0]) == MODE_ON

    def test_state_survives_restart(self, backend, tmp_path):
        topo = backend.discover()
        backend.stage_cc_mode(topo.chips, MODE_ON)
        backend.reset(topo.chips)
        reborn = TpuVmBackend(
            state_dir=backend.state_dir,
            reset_cmd=["true"],
            metadata_url="http://127.0.0.1:1",
            device_glob=backend.device_glob,
        )
        assert all(reborn.query_cc_mode(c) == MODE_ON for c in topo.chips)

    def test_attestation_needs_metadata_server(self, backend):
        with pytest.raises(TpuError):
            backend.fetch_attestation("n")


@pytest.mark.parametrize(
    "accel,gen,chips,hosts",
    [
        ("v5e-1", "v5e", 1, 1),
        ("v5e-8", "v5e", 8, 1),
        ("v5p-8", "v5p", 4, 1),
        ("v5p-32", "v5p", 16, 4),
        ("v5p-64", "v5p", 32, 8),
        ("v4-16", "v4", 8, 2),
        ("v6e-16", "v6e", 16, 2),
    ],
)
def test_parse_accelerator_type(accel, gen, chips, hosts):
    assert parse_accelerator_type(accel) == (gen, chips, hosts)


def test_parse_accelerator_type_garbage():
    with pytest.raises(TpuError):
        parse_accelerator_type("not-a-number-x")


def test_load_backend_factory(tmp_path):
    assert isinstance(load_backend("fake"), FakeTpuBackend)
    assert isinstance(load_backend("tpuvm", state_dir=str(tmp_path)), TpuVmBackend)
    with pytest.raises(ValueError):
        load_backend("gpu")
