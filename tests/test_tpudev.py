"""Device layer: fake backend semantics, tpuvm backend with injected
environment, attestation verification."""

import os

import pytest

from tpu_cc_manager.labels import MODE_OFF, MODE_ON
from tpu_cc_manager.tpudev import load_backend
from tpu_cc_manager.tpudev.attestation import (
    AttestationError,
    fresh_nonce,
    verify_quote,
)
from tpu_cc_manager.tpudev.contract import AttestationQuote, TpuError
from tpu_cc_manager.tpudev.fake import FakeTpuBackend
from tpu_cc_manager.tpudev.tpuvm import TpuVmBackend, parse_accelerator_type


class TestFakeBackend:
    def test_stage_then_reset_commits(self, fake_tpu):
        topo = fake_tpu.discover()
        chips = topo.chips
        fake_tpu.stage_cc_mode(chips, MODE_ON)
        # Staged but not committed yet.
        assert all(fake_tpu.query_cc_mode(c) == MODE_OFF for c in chips)
        fake_tpu.reset(chips)
        fake_tpu.wait_ready(chips, timeout_s=1)
        assert all(fake_tpu.query_cc_mode(c) == MODE_ON for c in chips)

    def test_fault_injection(self, fake_tpu):
        fake_tpu.fail_next("reset")
        with pytest.raises(TpuError):
            fake_tpu.reset(fake_tpu.discover().chips)
        fake_tpu.reset(fake_tpu.discover().chips)  # next call succeeds

    def test_attestation_roundtrip(self, fake_tpu):
        topo = fake_tpu.discover()
        fake_tpu.stage_cc_mode(topo.chips, MODE_ON)
        fake_tpu.reset(topo.chips)
        nonce = fresh_nonce()
        quote = fake_tpu.fetch_attestation(nonce)
        assert verify_quote(quote, nonce, MODE_ON, topo.slice_id, allow_fake=True) == []

    def test_attestation_rejects_tampering(self, fake_tpu):
        import dataclasses

        nonce = fresh_nonce()
        quote = fake_tpu.fetch_attestation(nonce)
        bad = dataclasses.replace(quote, signature="0" * 64)
        with pytest.raises(AttestationError):
            verify_quote(bad, nonce, MODE_OFF, allow_fake=True)

    def test_attestation_rejects_stale_nonce(self, fake_tpu):
        quote = fake_tpu.fetch_attestation("nonce-a")
        with pytest.raises(AttestationError):
            verify_quote(quote, "nonce-b", MODE_OFF, allow_fake=True)

    def test_devtools_policy_logs_instead_of_raising(self, fake_tpu):
        quote = fake_tpu.fetch_attestation("nonce-a")
        problems = verify_quote(quote, "nonce-b", MODE_OFF, debug_policy=True, allow_fake=True)
        assert problems  # reported, not raised


class TestTpuVmBackend:
    @pytest.fixture()
    def backend(self, tmp_path, monkeypatch):
        monkeypatch.setenv("TPU_ACCELERATOR_TYPE", "v5p-8")
        monkeypatch.setenv("TPU_WORKER_ID", "0")
        monkeypatch.delenv("TPU_SLICE_ID", raising=False)
        # Fabricate device nodes.
        devdir = tmp_path / "dev"
        devdir.mkdir()
        for i in range(4):
            (devdir / f"accel{i}").touch()
        return TpuVmBackend(
            state_dir=str(tmp_path / "state"),
            reset_cmd=["true"],
            show_cmd=[],  # no systemd on the test box; truth checks off
            metadata_url="http://127.0.0.1:1",  # unreachable -> env fallbacks
            device_glob=str(devdir / "accel*"),
        )

    def test_discover(self, backend):
        topo = backend.discover()
        assert topo.accelerator_type == "v5p-8"
        assert len(topo.chips) == 4
        assert topo.num_hosts == 1
        assert topo.host_index == 0

    def test_stage_reset_query_roundtrip(self, backend):
        topo = backend.discover()
        assert backend.query_cc_mode(topo.chips[0]) == MODE_OFF
        backend.stage_cc_mode(topo.chips, MODE_ON)
        assert backend.query_cc_mode(topo.chips[0]) == MODE_OFF  # not committed
        backend.reset(topo.chips)
        assert all(backend.query_cc_mode(c) == MODE_ON for c in topo.chips)
        backend.wait_ready(topo.chips, timeout_s=1)

    def test_reset_command_failure(self, backend):
        backend.reset_cmd = ["false"]
        topo = backend.discover()
        backend.stage_cc_mode(topo.chips, MODE_ON)
        with pytest.raises(TpuError):
            backend.reset(topo.chips)
        # Crash-safety: the failed reset must NOT look committed — the chip
        # reports an in-between state so idempotency checks re-apply.
        assert backend.query_cc_mode(topo.chips[0]) == "resetting"
        # Retry succeeds and commits.
        backend.reset_cmd = ["true"]
        backend.stage_cc_mode(topo.chips, MODE_ON)
        backend.reset(topo.chips)
        assert backend.query_cc_mode(topo.chips[0]) == MODE_ON

    def test_state_survives_restart(self, backend, tmp_path):
        topo = backend.discover()
        backend.stage_cc_mode(topo.chips, MODE_ON)
        backend.reset(topo.chips)
        reborn = TpuVmBackend(
            state_dir=backend.state_dir,
            reset_cmd=["true"],
            metadata_url="http://127.0.0.1:1",
            device_glob=backend.device_glob,
        )
        assert all(reborn.query_cc_mode(c) == MODE_ON for c in topo.chips)

    def test_attestation_needs_metadata_server(self, backend):
        with pytest.raises(TpuError):
            backend.fetch_attestation("n")


class TestRuntimeTruth:
    """The systemd cross-checks that keep the backend honest: a reset that
    didn't actually bounce the runtime must not commit, and a runtime that
    restarted outside the manager must stop reporting the committed mode
    (VERDICT round-2 item 3; the reference's device layer reads truth back
    from the hardware, main.py:519-528)."""

    @pytest.fixture()
    def rig(self, tmp_path, monkeypatch):
        monkeypatch.setenv("TPU_ACCELERATOR_TYPE", "v5p-8")
        monkeypatch.setenv("TPU_WORKER_ID", "0")
        monkeypatch.delenv("TPU_SLICE_ID", raising=False)
        devdir = tmp_path / "dev"
        devdir.mkdir()
        for i in range(4):
            (devdir / f"accel{i}").touch()
        show_file = tmp_path / "show.txt"

        def set_runtime(state: str, ts: int) -> None:
            show_file.write_text(
                f"ActiveState={state}\nActiveEnterTimestampMonotonic={ts}\n"
            )

        set_runtime("active", 1000)
        backend = TpuVmBackend(
            state_dir=str(tmp_path / "state"),
            reset_cmd=["true"],  # default: exits 0 WITHOUT bumping the stamp
            show_cmd=["cat", str(show_file)],
            metadata_url="http://127.0.0.1:1",
            device_glob=str(devdir / "accel*"),
        )
        # These tests rewrite the show output mid-flow; the short-TTL memo
        # (an optimization for per-chip sweeps) would serve stale stamps.
        backend.stamp_cache_ttl_s = 0.0
        return backend, set_runtime, show_file

    def bounce_cmd(self, show_file, ts: int) -> list[str]:
        """A reset command that actually 'restarts' the runtime by bumping
        the activation stamp."""
        return [
            "sh", "-c",
            "printf 'ActiveState=active\\nActiveEnterTimestampMonotonic=%d\\n'"
            " > %s" % (ts, show_file),
        ]

    def test_reset_that_does_not_restart_is_not_committed(self, rig):
        backend, _, show_file = rig
        topo = backend.discover()
        backend.stage_cc_mode(topo.chips, MODE_ON)
        with pytest.raises(TpuError, match="did not restart"):
            backend.reset(topo.chips)
        # Not committed: the chips report an in-between state that fails
        # every idempotency check.
        assert backend.query_cc_mode(topo.chips[0]) == "resetting"
        # A retry whose reset really bounces the runtime commits.
        backend.reset_cmd = self.bounce_cmd(show_file, 2000)
        backend.stage_cc_mode(topo.chips, MODE_ON)
        backend.reset(topo.chips)
        assert all(backend.query_cc_mode(c) == MODE_ON for c in topo.chips)

    def test_external_restart_surfaces_as_resetting(self, rig):
        backend, set_runtime, show_file = rig
        topo = backend.discover()
        backend.reset_cmd = self.bounce_cmd(show_file, 2000)
        backend.stage_cc_mode(topo.chips, MODE_ON)
        backend.reset(topo.chips)
        assert backend.query_cc_mode(topo.chips[0]) == MODE_ON
        # Someone restarts the runtime behind the manager's back.
        set_runtime("active", 5000)
        assert backend.query_cc_mode(topo.chips[0]) == "resetting"

    def test_health_probe_requires_active_runtime(self, rig):
        backend, set_runtime, _ = rig
        topo = backend.discover()
        assert backend._probe_healthy(topo.chips) is True
        set_runtime("inactive", 1000)
        assert backend._probe_healthy(topo.chips) is False
        with pytest.raises(TpuError):
            backend.wait_ready(topo.chips, timeout_s=0.05)

    def test_state_only_show_output_disables_cross_check(self, rig, tmp_path):
        """A show_cmd that yields ActiveState but no usable activation
        timestamp must read as probe-unavailable — NOT as ts=0, which would
        fail every restart cross-check and brick the node."""
        backend, _, show_file = rig
        show_file.write_text("ActiveState=active\n")  # no timestamp property
        topo = backend.discover()
        backend.stage_cc_mode(topo.chips, MODE_ON)
        backend.reset(topo.chips)  # must NOT raise "did not restart"
        assert all(backend.query_cc_mode(c) == MODE_ON for c in topo.chips)

    def test_health_port_probe(self, rig):
        import socket

        backend, _, _ = rig
        topo = backend.discover()
        srv = socket.socket()
        try:
            srv.bind(("127.0.0.1", 0))
            srv.listen(1)
            backend.health_port = srv.getsockname()[1]
            assert backend._probe_healthy(topo.chips) is True
        finally:
            srv.close()
        assert backend._probe_healthy(topo.chips) is False


class TestRuntimeIdentity:
    """The attested runtime digest measures the runtime — its library,
    unit and config files — not the manager's own state (VERDICT r3 weak
    #2: a digest of committed.json compared manager beliefs, so a silently
    swapped runtime produced an identical digest)."""

    def make_backend(self, tmp_path, name: str, measure_dir) -> TpuVmBackend:
        return TpuVmBackend(
            state_dir=str(tmp_path / f"state-{name}"),
            reset_cmd=["true"],
            show_cmd=[],
            metadata_url="http://127.0.0.1:1",
            device_glob=str(tmp_path / "nodev*"),
            measure_globs=[str(measure_dir / "*.so"),
                           str(measure_dir / "*.service")],
            tsm_root="",
        )

    def test_digest_changes_when_runtime_changes(self, tmp_path):
        mdir = tmp_path / "runtime"
        mdir.mkdir()
        (mdir / "libtpu.so").write_bytes(b"libtpu v1")
        (mdir / "tpu-runtime.service").write_text("ExecStart=/run-v1")
        backend = self.make_backend(tmp_path, "a", mdir)
        d1 = backend._runtime_digest()
        # Swapping the runtime binary provably changes the digest.
        (mdir / "libtpu.so").write_bytes(b"libtpu v2 (swapped)")
        assert backend._runtime_digest() != d1
        d2 = backend._runtime_digest()
        # So does a unit-file (config) edit.
        (mdir / "tpu-runtime.service").write_text("ExecStart=/run-v2 --debug")
        assert backend._runtime_digest() not in (d1, d2)

    def test_digest_ignores_manager_state(self, tmp_path, monkeypatch):
        """Mode transitions rewrite committed.json; the runtime digest must
        not move with it (cc_mode is its own measurement)."""
        monkeypatch.setenv("TPU_ACCELERATOR_TYPE", "v5p-8")
        mdir = tmp_path / "runtime"
        mdir.mkdir()
        (mdir / "libtpu.so").write_bytes(b"libtpu v1")
        devdir = tmp_path / "dev"
        devdir.mkdir()
        for i in range(4):
            (devdir / f"accel{i}").touch()
        backend = self.make_backend(tmp_path, "a", mdir)
        backend.device_glob = str(devdir / "accel*")
        d1 = backend._runtime_digest()
        topo = backend.discover()
        backend.stage_cc_mode(topo.chips, MODE_ON)
        backend.reset(topo.chips)
        assert backend._runtime_digest() == d1

    def test_digest_equal_across_same_runtime_hosts(self, tmp_path):
        """Two hosts with identical runtime files but different state dirs
        (and histories) produce EQUAL digests — the multislice pool
        equality check depends on this."""
        mdir = tmp_path / "runtime"
        mdir.mkdir()
        (mdir / "libtpu.so").write_bytes(b"libtpu v1")
        a = self.make_backend(tmp_path, "a", mdir)
        b = self.make_backend(tmp_path, "b", mdir)
        # Host b has a different manager history.
        b._write_state("committed.json", {"*": "on"})
        assert a._runtime_digest() == b._runtime_digest()

    def test_tsm_report_binds_nonce(self, tmp_path):
        """Seeded configfs-tsm tree: the backend writes the nonce-derived
        challenge to inblob and returns the provider's outblob; the
        verifier checks the challenge is embedded in the signed report
        (report_data) and rejects a wrong-nonce replay."""
        import base64
        import hashlib

        # Real TEEs copy inblob verbatim into the signed report_data; the
        # seeded outblob mimics that layout (header + challenge + sig).
        challenge = hashlib.sha256(b"tpu-cc-manager/nonce-1").digest()
        seeded_outblob = b"SNP-REPORT-HDR" + challenge + b"-SIGNATURE"

        tsm = tmp_path / "tsm" / "report"
        seed = tsm / "tpu-cc-manager"
        seed.mkdir(parents=True)
        (seed / "outblob").write_bytes(seeded_outblob)
        (seed / "provider").write_text("sev_guest\n")
        backend = TpuVmBackend(
            state_dir=str(tmp_path / "state"),
            reset_cmd=["true"], show_cmd=[],
            metadata_url="http://127.0.0.1:1",
            measure_globs=[], tsm_root=str(tsm),
        )
        report = backend._tsm_report("nonce-1")
        assert report is not None
        assert report["provider"] == "sev_guest"
        assert base64.b64decode(report["outblob_b64"]) == seeded_outblob
        # The challenge actually written to inblob is nonce-derived.
        assert (seed / "inblob").read_bytes() == challenge

        from tpu_cc_manager.tpudev.attestation import _check_tsm_binding

        quote = AttestationQuote(
            slice_id="s", nonce="nonce-1", mode=MODE_ON,
            measurements={"tsm_provider": "sev_guest"},
            signature="x", platform="tpuvm",
            host_evidence={"tsm_outblob_b64": report["outblob_b64"]},
        )
        assert _check_tsm_binding(quote, "nonce-1") == []
        # The same outblob replayed under a different nonce fails: the
        # challenge inside the signed blob no longer matches (and a
        # producer cannot fix that without the TEE re-signing).
        assert _check_tsm_binding(quote, "nonce-2")

    def test_devtools_commits_debug_runtime_env(self, tmp_path, monkeypatch):
        """devtools is backend-visible: the committed runtime env carries
        debug/trace flags, and because the env file is measured, a devtools
        runtime attests a DIFFERENT digest than a production-CC runtime
        (labels.py mode table; VERDICT r3 item 8)."""
        monkeypatch.setenv("TPU_ACCELERATOR_TYPE", "v5p-8")
        devdir = tmp_path / "dev"
        devdir.mkdir()
        for i in range(4):
            (devdir / f"accel{i}").touch()
        env_file = tmp_path / "etc" / "tpu-runtime.env"
        backend = TpuVmBackend(
            state_dir=str(tmp_path / "state"),
            reset_cmd=["true"], show_cmd=[],
            metadata_url="http://127.0.0.1:1",
            device_glob=str(devdir / "accel*"),
            measure_globs=[str(env_file)], tsm_root="",
            runtime_env_file=str(env_file),
        )
        topo = backend.discover()
        backend.stage_cc_mode(topo.chips, "devtools")
        backend.reset(topo.chips)
        content = env_file.read_text()
        assert "TPU_CC_MODE=devtools" in content
        assert "TPU_MIN_LOG_LEVEL=0" in content
        devtools_digest = backend._runtime_digest()

        backend.stage_cc_mode(topo.chips, MODE_ON)
        backend.reset(topo.chips)
        content = env_file.read_text()
        assert "TPU_CC_MODE=on" in content
        assert "TPU_MIN_LOG_LEVEL" not in content  # debug flags are devtools-only
        assert backend._runtime_digest() != devtools_digest

    def test_runtime_env_write_failure_fails_reset(self, tmp_path, monkeypatch):
        """A mode whose runtime config didn't land must not commit."""
        monkeypatch.setenv("TPU_ACCELERATOR_TYPE", "v5p-8")
        devdir = tmp_path / "dev"
        devdir.mkdir()
        (devdir / "accel0").touch()
        blocker = tmp_path / "notadir"
        blocker.touch()  # parent "directory" is a file -> write fails
        backend = TpuVmBackend(
            state_dir=str(tmp_path / "state"),
            reset_cmd=["true"], show_cmd=[],
            metadata_url="http://127.0.0.1:1",
            device_glob=str(devdir / "accel*"),
            measure_globs=[], tsm_root="",
            runtime_env_file=str(blocker / "env"),
        )
        topo = backend.discover()
        backend.stage_cc_mode(topo.chips, MODE_ON)
        with pytest.raises(TpuError):
            backend.reset(topo.chips)
        assert backend.query_cc_mode(topo.chips[0]) == "resetting"

    def test_mixed_mode_staging_refuses_runtime_env(self, tmp_path, monkeypatch):
        """Chips staged to different modes must fail the reset loudly: the
        runtime env is host-global, so silently writing one mode (r4's
        behavior was a silent 'off') would commit — and then attest — a
        runtime config that doesn't match what half the chips staged
        (VERDICT r4 weak #6)."""
        monkeypatch.setenv("TPU_ACCELERATOR_TYPE", "v5p-8")
        devdir = tmp_path / "dev"
        devdir.mkdir()
        for i in range(4):
            (devdir / f"accel{i}").touch()
        env_file = tmp_path / "etc" / "tpu-runtime.env"
        backend = TpuVmBackend(
            state_dir=str(tmp_path / "state"),
            reset_cmd=["true"], show_cmd=[],
            metadata_url="http://127.0.0.1:1",
            device_glob=str(devdir / "accel*"),
            measure_globs=[], tsm_root="",
            runtime_env_file=str(env_file),
        )
        topo = backend.discover()
        backend.stage_cc_mode(topo.chips[:2], MODE_ON)
        backend.stage_cc_mode(topo.chips[2:], MODE_OFF)
        with pytest.raises(TpuError, match="mixed modes"):
            backend.reset(topo.chips)
        assert not env_file.exists()  # nothing half-written
        # Pending markers stay: the reconcile sees 'resetting' and retries.
        assert backend.query_cc_mode(topo.chips[0]) == "resetting"

    def test_fake_backend_mirrors_devtools_env(self):
        backend = FakeTpuBackend()
        topo = backend.discover()
        backend.stage_cc_mode(topo.chips, "devtools")
        backend.reset(topo.chips)
        assert backend.runtime_env.get("TPU_CC_MODE") == "devtools"
        assert backend.runtime_env.get("TPU_MIN_LOG_LEVEL") == "0"
        backend.stage_cc_mode(topo.chips, MODE_ON)
        backend.reset(topo.chips)
        assert backend.runtime_env.get("TPU_CC_MODE") == "on"
        assert "TPU_MIN_LOG_LEVEL" not in backend.runtime_env

    def test_tsm_claim_without_report_fails(self):
        from tpu_cc_manager.tpudev.attestation import _check_tsm_binding

        quote = AttestationQuote(
            slice_id="s", nonce="n", mode=MODE_ON,
            measurements={"tsm_provider": "tdx_guest"},
            signature="x", platform="tpuvm",
        )
        problems = _check_tsm_binding(quote, "n")
        assert any("no guest report" in p for p in problems)

    def test_tsm_unavailable_is_not_required(self):
        from tpu_cc_manager.tpudev.attestation import _check_tsm_binding

        quote = AttestationQuote(
            slice_id="s", nonce="n", mode=MODE_ON,
            measurements={"tsm_provider": "none"},
            signature="x", platform="tpuvm",
        )
        assert _check_tsm_binding(quote, "n") == []


class TestHostWrap:
    def test_identity_without_host_root(self, monkeypatch):
        from tpu_cc_manager.tpudev.tpuvm import host_wrap

        monkeypatch.delenv("CC_RUNTIME_SHOW_CMD", raising=False)
        monkeypatch.delenv("CC_HOST_ROOT", raising=False)
        assert host_wrap(["systemctl", "show", "x"]) == ["systemctl", "show", "x"]

    def test_wrap_executes_inside_host_root(self, tmp_path):
        """Functional check of the chroot wrapper (the test runs as root on
        this image): a command resolves against the fake host rootfs, with
        stdout captured by the outer subprocess as the backend expects."""
        import os
        import subprocess

        from tpu_cc_manager.tpudev.tpuvm import host_wrap

        if os.geteuid() != 0:
            pytest.skip("chroot requires root")
        # Minimal fake host rootfs: busybox-style /bin/sh via the static sh
        # is overkill — copy the system's sh + needed libs is fragile, so
        # use a statically-linked helper we already build: native/rmutil/rm
        # is static. Simpler still: chroot to the REAL root ('/') — a
        # no-op boundary that still exercises the wrapper plumbing.
        cmd = host_wrap(["echo", "host-hello"], host_root="/")
        out = subprocess.run(cmd, capture_output=True, timeout=10, text=True)
        assert out.returncode == 0
        assert out.stdout.strip() == "host-hello"


@pytest.mark.parametrize(
    "accel,gen,chips,hosts",
    [
        ("v5e-1", "v5e", 1, 1),
        ("v5e-8", "v5e", 8, 1),
        ("v5p-8", "v5p", 4, 1),
        ("v5p-32", "v5p", 16, 4),
        ("v5p-64", "v5p", 32, 8),
        ("v4-16", "v4", 8, 2),
        ("v6e-16", "v6e", 16, 2),
    ],
)
def test_parse_accelerator_type(accel, gen, chips, hosts):
    assert parse_accelerator_type(accel) == (gen, chips, hosts)


def test_parse_accelerator_type_garbage():
    with pytest.raises(TpuError):
        parse_accelerator_type("not-a-number-x")


def test_load_backend_factory(tmp_path):
    assert isinstance(load_backend("fake"), FakeTpuBackend)
    assert isinstance(load_backend("tpuvm", state_dir=str(tmp_path)), TpuVmBackend)
    with pytest.raises(ValueError):
        load_backend("gpu")


# ---------------------------------------------------------------------------
# Fuzzing the accelerator-type parser: whatever the metadata server or env
# hands us, the parser either returns a sane topology or raises TpuError —
# never an unhandled ValueError/ZeroDivisionError mid-discovery.
# ---------------------------------------------------------------------------

from _hypothesis_compat import given, st


@given(st.text(max_size=24))
def test_parse_accelerator_type_total(accel):
    try:
        gen, chips, hosts = parse_accelerator_type(accel)
    except TpuError:
        return  # the one sanctioned failure mode
    assert chips >= 1
    assert hosts >= 1
    assert isinstance(gen, str)
    # Chips never exceed per-host capacity times hosts.
    assert chips <= hosts * 8


@given(st.sampled_from(["v4", "v5e", "v5p", "v6e"]),
       st.integers(min_value=1, max_value=512))
def test_parse_accelerator_type_known_generations(gen, cores):
    got_gen, chips, hosts = parse_accelerator_type(f"{gen}-{cores}")
    assert got_gen == gen
    assert 1 <= chips
    assert 1 <= hosts
    assert chips <= hosts * (8 if gen in ("v5e", "v6e") else 4)


class TestRuntimeEnvDigest:
    """The daemonset stages CC_RUNTIME_ENV_FILE in the state dir and puts
    it on the measured-path list, so ``on`` vs ``devtools`` — which commit
    different runtime env content (devtools adds debug/trace flags) —
    provably attest DIFFERENT runtime digests (VERDICT #4)."""

    def make_backend(self, tmp_path, monkeypatch):
        monkeypatch.setenv("TPU_ACCELERATOR_TYPE", "v5p-8")
        monkeypatch.setenv("TPU_WORKER_ID", "0")
        monkeypatch.delenv("TPU_SLICE_ID", raising=False)
        devdir = tmp_path / "dev"
        devdir.mkdir()
        for i in range(4):
            (devdir / f"accel{i}").touch()
        state = tmp_path / "state"
        env_file = state / "tpu-runtime.env"
        return TpuVmBackend(
            state_dir=str(state),
            reset_cmd=["true"],
            show_cmd=[],
            metadata_url="http://127.0.0.1:1",
            device_glob=str(devdir / "accel*"),
            # The env file is measured alongside a runtime library, exactly
            # like the daemonset's CC_RUNTIME_MEASURE_PATHS wiring.
            measure_globs=[str(state / "tpu-runtime.env")],
            tsm_root="",
            runtime_env_file=str(env_file),
        )

    def commit(self, backend, mode):
        topo = backend.discover()
        backend.stage_cc_mode(topo.chips, mode)
        backend.reset(topo.chips)
        return backend._runtime_digest()

    def test_on_vs_devtools_attest_different_digests(self, tmp_path, monkeypatch):
        from tpu_cc_manager.labels import MODE_DEVTOOLS

        backend = self.make_backend(tmp_path, monkeypatch)
        d_on = self.commit(backend, MODE_ON)
        d_devtools = self.commit(backend, MODE_DEVTOOLS)
        assert d_on != d_devtools
        # The difference is the committed env content: devtools carries the
        # debug flags, on does not.
        env = (tmp_path / "state" / "tpu-runtime.env").read_text()
        assert "TPU_CC_MODE=devtools" in env
        assert "TPU_MIN_LOG_LEVEL=0" in env
        # And the same mode commits reproduce the same digest.
        assert self.commit(backend, MODE_ON) == d_on

    def test_env_write_failure_fails_the_reset(self, tmp_path, monkeypatch):
        """A mode whose runtime config didn't land must not commit: pending
        markers stay and query reports 'resetting' (crash-as-retry)."""
        backend = self.make_backend(tmp_path, monkeypatch)
        topo = backend.discover()
        # Unwritable env path: a DIRECTORY where the file should go.
        backend.runtime_env_file = str(tmp_path / "state")
        backend.stage_cc_mode(topo.chips, MODE_ON)
        with pytest.raises(TpuError):
            backend.reset(topo.chips)
        assert backend.query_cc_mode(topo.chips[0]) == "resetting"


class TestDeviceCmdBreaker:
    """The device-command circuit breaker fails fast mid-ladder: a circuit
    opened by attempt 1 must stop attempt 2 from running another (up to
    120 s) command against the known-bad path."""

    def make_backend(self, tmp_path, monkeypatch):
        monkeypatch.setenv("TPU_ACCELERATOR_TYPE", "v5p-8")
        monkeypatch.setenv("TPU_WORKER_ID", "0")
        monkeypatch.delenv("TPU_SLICE_ID", raising=False)
        devdir = tmp_path / "dev"
        devdir.mkdir()
        (devdir / "accel0").touch()
        return TpuVmBackend(
            state_dir=str(tmp_path / "state"),
            reset_cmd=["false"],
            show_cmd=[],
            metadata_url="http://127.0.0.1:1",
            device_glob=str(devdir / "accel*"),
        )

    def test_circuit_opened_mid_ladder_stops_the_retry(self, tmp_path, monkeypatch):
        from tpu_cc_manager.utils import retry as retry_mod
        from tpu_cc_manager.utils.metrics import MetricsRegistry

        backend = self.make_backend(tmp_path, monkeypatch)
        backend.retry_policy.sleep = lambda s: None
        backend.breaker = retry_mod.CircuitBreaker(
            "device-cmd", failure_threshold=1, recovery_time_s=60.0,
            metrics=MetricsRegistry(),
        )
        runs = {"n": 0}
        real_run = __import__("subprocess").run

        def counting_run(*a, **k):
            runs["n"] += 1
            return real_run(*a, **k)

        monkeypatch.setattr("subprocess.run", counting_run)
        topo = backend.discover()
        backend.stage_cc_mode(topo.chips, MODE_ON)
        with pytest.raises(TpuError, match="unavailable|circuit"):
            backend.reset(topo.chips)
        # Attempt 1 ran and opened the circuit; attempt 2 was rejected
        # before spawning a process.
        assert runs["n"] == 1
        # Crash-as-retry: pending markers stayed behind.
        assert backend.query_cc_mode(topo.chips[0]) == "resetting"


class TestPerChipReset:
    """Per-chip parallel reset (the pipelined transition's 30 s-floor
    attack): the fake's independently configurable per-chip delays make
    the speedup measurable deterministically, and the tpuvm per-chip
    command path preserves the pending/staged crash ordering."""

    def test_fake_per_chip_parallel_wall_time(self):
        backend = FakeTpuBackend(
            reset_latency_s=[0.15, 0.15, 0.15, 0.15],
            reset_parallelism_override=4,
        )
        topo = backend.discover()
        backend.stage_cc_mode(topo.chips, MODE_ON)
        import time as _time

        t0 = _time.monotonic()
        backend.reset(topo.chips)
        wall = _time.monotonic() - t0
        # 4 × 0.15 s of work in a 4-wide pool: one chip's latency of wall
        # time, far under the 0.6 s serial sum.
        assert wall < 0.45, f"parallel reset took {wall:.3f}s"
        assert all(backend.query_cc_mode(c) == MODE_ON for c in topo.chips)
        assert [op for op, _ in backend.op_log].count("reset.chip") == 4

    def test_fake_per_chip_serial_with_parallelism_one(self):
        backend = FakeTpuBackend(
            reset_latency_s=[0.05, 0.05, 0.05, 0.05],
            reset_parallelism_override=1,
        )
        topo = backend.discover()
        backend.stage_cc_mode(topo.chips, MODE_ON)
        import time as _time

        t0 = _time.monotonic()
        backend.reset(topo.chips)
        wall = _time.monotonic() - t0
        assert wall >= 0.2, f"serial walk must pay the sum, got {wall:.3f}s"

    def test_fake_per_chip_boot_delays_independent(self):
        """Per-chip wait_ready delays configurable independently of the
        reset delays (ISSUE 8 satellite): one slow-booting chip owns the
        wait_ready tail."""
        backend = FakeTpuBackend(
            reset_latency_s=[0.0, 0.0, 0.0, 0.0],
            boot_latency_s=[0.0, 0.0, 0.0, 0.2],
            reset_parallelism_override=4,
        )
        topo = backend.discover()
        backend.stage_cc_mode(topo.chips, MODE_ON)
        backend.reset(topo.chips)
        import time as _time

        t0 = _time.monotonic()
        backend.wait_ready(topo.chips, timeout_s=2)
        wall = _time.monotonic() - t0
        assert 0.15 <= wall < 1.0

    def test_fake_per_chip_failure_keeps_unreset_chips_staged(self):
        backend = FakeTpuBackend(
            reset_latency_s=[0.0] * 4, reset_parallelism_override=1,
        )
        backend.fail_next("reset.chip2")
        topo = backend.discover()
        backend.stage_cc_mode(topo.chips, MODE_ON)
        with pytest.raises(TpuError):
            backend.reset(topo.chips)
        # Chip 2 never committed; its staged entry survives for the retry.
        assert backend.committed[2] == MODE_OFF
        assert backend.staged.get(2) == MODE_ON
        # The retry converges.
        backend.reset(topo.chips)
        assert all(backend.query_cc_mode(c) == MODE_ON for c in topo.chips)

    @pytest.fixture()
    def vm_backend(self, tmp_path, monkeypatch):
        monkeypatch.setenv("TPU_ACCELERATOR_TYPE", "v5p-8")
        monkeypatch.setenv("TPU_WORKER_ID", "0")
        monkeypatch.delenv("TPU_SLICE_ID", raising=False)
        devdir = tmp_path / "dev"
        devdir.mkdir()
        for i in range(4):
            (devdir / f"accel{i}").touch()
        marker_dir = tmp_path / "markers"
        marker_dir.mkdir()
        import sys as _sys

        return TpuVmBackend(
            state_dir=str(tmp_path / "state"),
            reset_cmd=["true"],
            show_cmd=[],
            metadata_url="http://127.0.0.1:1",
            device_glob=str(devdir / "accel*"),
            per_chip_reset_cmd=[
                _sys.executable, "-c",
                "import sys; open(sys.argv[1] + '/chip' + sys.argv[2], 'w')"
                ".write(open(sys.argv[3]).read())",
                str(marker_dir), "{index}",
                str(tmp_path / "state" / "pending.json"),
            ],
        ), marker_dir

    def test_tpuvm_per_chip_commands_run_per_chip(self, vm_backend):
        backend, marker_dir = vm_backend
        topo = backend.discover()
        backend.stage_cc_mode(topo.chips, MODE_ON)
        backend.reset(topo.chips)
        # One command per chip ran, with {index} substituted.
        markers = sorted(os.listdir(marker_dir))
        assert markers == ["chip0", "chip1", "chip2", "chip3"]
        # Crash ordering: every chip's command saw the PENDING markers
        # already durable (the command copies pending.json's content).
        import json as _json

        for marker in markers:
            pending_seen = _json.loads((marker_dir / marker).read_text())
            assert set(pending_seen) == {"0", "1", "2", "3"}
            assert set(pending_seen.values()) == {MODE_ON}
        # Committed promoted, pending cleared.
        assert all(backend.query_cc_mode(c) == MODE_ON for c in topo.chips)

    def test_tpuvm_per_chip_command_failure_keeps_resetting(self, vm_backend):
        backend, _ = vm_backend
        import sys as _sys

        backend.per_chip_reset_cmd = [
            _sys.executable, "-c",
            "import sys; sys.exit(1 if sys.argv[1] == '2' else 0)",
            "{index}",
        ]
        backend.retry_policy.max_attempts = 1  # no classified retry here
        topo = backend.discover()
        backend.stage_cc_mode(topo.chips, MODE_ON)
        with pytest.raises(TpuError, match="chip"):
            backend.reset(topo.chips)
        # Pending markers stayed: every chip reads 'resetting' and the
        # reconcile's crash-as-retry re-applies.
        assert backend.query_cc_mode(topo.chips[0]) == "resetting"

    def test_tpuvm_prepare_attestation_warms_hash_cache(self, tmp_path):
        measured = tmp_path / "libtpu.so"
        measured.write_bytes(b"fake-libtpu" * 64)
        backend = TpuVmBackend(
            state_dir=str(tmp_path / "state"),
            reset_cmd=["true"],
            show_cmd=[],
            metadata_url="http://127.0.0.1:1",
            measure_globs=[str(measured)],
        )
        assert backend._file_hash_cache == {}
        backend.prepare_attestation()  # overlapped with wait_ready by the manager
        assert str(measured) in backend._file_hash_cache

    def test_tpuvm_per_chip_refuses_host_global_runtime_env(
        self, vm_backend, tmp_path
    ):
        """CC_RESET_PER_CHIP_CMD + CC_RUNTIME_ENV_FILE are incompatible by
        construction (host-global mode env needs a host-global restart):
        reset() refuses loudly BEFORE minting any 'resetting' markers."""
        backend, _ = vm_backend
        backend.runtime_env_file = str(tmp_path / "tpu-runtime.env")
        topo = backend.discover()
        backend.stage_cc_mode(topo.chips, MODE_ON)
        with pytest.raises(TpuError, match="incompatible"):
            backend.reset(topo.chips)
        # No pending markers: the misconfiguration is stable, not a crash.
        assert backend.query_cc_mode(topo.chips[0]) == MODE_OFF
