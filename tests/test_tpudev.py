"""Device layer: fake backend semantics, tpuvm backend with injected
environment, attestation verification."""

import os

import pytest

from tpu_cc_manager.labels import MODE_OFF, MODE_ON
from tpu_cc_manager.tpudev import load_backend
from tpu_cc_manager.tpudev.attestation import (
    AttestationError,
    fresh_nonce,
    verify_quote,
)
from tpu_cc_manager.tpudev.contract import TpuError
from tpu_cc_manager.tpudev.fake import FakeTpuBackend
from tpu_cc_manager.tpudev.tpuvm import TpuVmBackend, parse_accelerator_type


class TestFakeBackend:
    def test_stage_then_reset_commits(self, fake_tpu):
        topo = fake_tpu.discover()
        chips = topo.chips
        fake_tpu.stage_cc_mode(chips, MODE_ON)
        # Staged but not committed yet.
        assert all(fake_tpu.query_cc_mode(c) == MODE_OFF for c in chips)
        fake_tpu.reset(chips)
        fake_tpu.wait_ready(chips, timeout_s=1)
        assert all(fake_tpu.query_cc_mode(c) == MODE_ON for c in chips)

    def test_fault_injection(self, fake_tpu):
        fake_tpu.fail_next("reset")
        with pytest.raises(TpuError):
            fake_tpu.reset(fake_tpu.discover().chips)
        fake_tpu.reset(fake_tpu.discover().chips)  # next call succeeds

    def test_attestation_roundtrip(self, fake_tpu):
        topo = fake_tpu.discover()
        fake_tpu.stage_cc_mode(topo.chips, MODE_ON)
        fake_tpu.reset(topo.chips)
        nonce = fresh_nonce()
        quote = fake_tpu.fetch_attestation(nonce)
        assert verify_quote(quote, nonce, MODE_ON, topo.slice_id, allow_fake=True) == []

    def test_attestation_rejects_tampering(self, fake_tpu):
        import dataclasses

        nonce = fresh_nonce()
        quote = fake_tpu.fetch_attestation(nonce)
        bad = dataclasses.replace(quote, signature="0" * 64)
        with pytest.raises(AttestationError):
            verify_quote(bad, nonce, MODE_OFF, allow_fake=True)

    def test_attestation_rejects_stale_nonce(self, fake_tpu):
        quote = fake_tpu.fetch_attestation("nonce-a")
        with pytest.raises(AttestationError):
            verify_quote(quote, "nonce-b", MODE_OFF, allow_fake=True)

    def test_devtools_policy_logs_instead_of_raising(self, fake_tpu):
        quote = fake_tpu.fetch_attestation("nonce-a")
        problems = verify_quote(quote, "nonce-b", MODE_OFF, debug_policy=True, allow_fake=True)
        assert problems  # reported, not raised


class TestTpuVmBackend:
    @pytest.fixture()
    def backend(self, tmp_path, monkeypatch):
        monkeypatch.setenv("TPU_ACCELERATOR_TYPE", "v5p-8")
        monkeypatch.setenv("TPU_WORKER_ID", "0")
        monkeypatch.delenv("TPU_SLICE_ID", raising=False)
        # Fabricate device nodes.
        devdir = tmp_path / "dev"
        devdir.mkdir()
        for i in range(4):
            (devdir / f"accel{i}").touch()
        return TpuVmBackend(
            state_dir=str(tmp_path / "state"),
            reset_cmd=["true"],
            show_cmd=[],  # no systemd on the test box; truth checks off
            metadata_url="http://127.0.0.1:1",  # unreachable -> env fallbacks
            device_glob=str(devdir / "accel*"),
        )

    def test_discover(self, backend):
        topo = backend.discover()
        assert topo.accelerator_type == "v5p-8"
        assert len(topo.chips) == 4
        assert topo.num_hosts == 1
        assert topo.host_index == 0

    def test_stage_reset_query_roundtrip(self, backend):
        topo = backend.discover()
        assert backend.query_cc_mode(topo.chips[0]) == MODE_OFF
        backend.stage_cc_mode(topo.chips, MODE_ON)
        assert backend.query_cc_mode(topo.chips[0]) == MODE_OFF  # not committed
        backend.reset(topo.chips)
        assert all(backend.query_cc_mode(c) == MODE_ON for c in topo.chips)
        backend.wait_ready(topo.chips, timeout_s=1)

    def test_reset_command_failure(self, backend):
        backend.reset_cmd = ["false"]
        topo = backend.discover()
        backend.stage_cc_mode(topo.chips, MODE_ON)
        with pytest.raises(TpuError):
            backend.reset(topo.chips)
        # Crash-safety: the failed reset must NOT look committed — the chip
        # reports an in-between state so idempotency checks re-apply.
        assert backend.query_cc_mode(topo.chips[0]) == "resetting"
        # Retry succeeds and commits.
        backend.reset_cmd = ["true"]
        backend.stage_cc_mode(topo.chips, MODE_ON)
        backend.reset(topo.chips)
        assert backend.query_cc_mode(topo.chips[0]) == MODE_ON

    def test_state_survives_restart(self, backend, tmp_path):
        topo = backend.discover()
        backend.stage_cc_mode(topo.chips, MODE_ON)
        backend.reset(topo.chips)
        reborn = TpuVmBackend(
            state_dir=backend.state_dir,
            reset_cmd=["true"],
            metadata_url="http://127.0.0.1:1",
            device_glob=backend.device_glob,
        )
        assert all(reborn.query_cc_mode(c) == MODE_ON for c in topo.chips)

    def test_attestation_needs_metadata_server(self, backend):
        with pytest.raises(TpuError):
            backend.fetch_attestation("n")


class TestRuntimeTruth:
    """The systemd cross-checks that keep the backend honest: a reset that
    didn't actually bounce the runtime must not commit, and a runtime that
    restarted outside the manager must stop reporting the committed mode
    (VERDICT round-2 item 3; the reference's device layer reads truth back
    from the hardware, main.py:519-528)."""

    @pytest.fixture()
    def rig(self, tmp_path, monkeypatch):
        monkeypatch.setenv("TPU_ACCELERATOR_TYPE", "v5p-8")
        monkeypatch.setenv("TPU_WORKER_ID", "0")
        monkeypatch.delenv("TPU_SLICE_ID", raising=False)
        devdir = tmp_path / "dev"
        devdir.mkdir()
        for i in range(4):
            (devdir / f"accel{i}").touch()
        show_file = tmp_path / "show.txt"

        def set_runtime(state: str, ts: int) -> None:
            show_file.write_text(
                f"ActiveState={state}\nActiveEnterTimestampMonotonic={ts}\n"
            )

        set_runtime("active", 1000)
        backend = TpuVmBackend(
            state_dir=str(tmp_path / "state"),
            reset_cmd=["true"],  # default: exits 0 WITHOUT bumping the stamp
            show_cmd=["cat", str(show_file)],
            metadata_url="http://127.0.0.1:1",
            device_glob=str(devdir / "accel*"),
        )
        # These tests rewrite the show output mid-flow; the short-TTL memo
        # (an optimization for per-chip sweeps) would serve stale stamps.
        backend.stamp_cache_ttl_s = 0.0
        return backend, set_runtime, show_file

    def bounce_cmd(self, show_file, ts: int) -> list[str]:
        """A reset command that actually 'restarts' the runtime by bumping
        the activation stamp."""
        return [
            "sh", "-c",
            "printf 'ActiveState=active\\nActiveEnterTimestampMonotonic=%d\\n'"
            " > %s" % (ts, show_file),
        ]

    def test_reset_that_does_not_restart_is_not_committed(self, rig):
        backend, _, show_file = rig
        topo = backend.discover()
        backend.stage_cc_mode(topo.chips, MODE_ON)
        with pytest.raises(TpuError, match="did not restart"):
            backend.reset(topo.chips)
        # Not committed: the chips report an in-between state that fails
        # every idempotency check.
        assert backend.query_cc_mode(topo.chips[0]) == "resetting"
        # A retry whose reset really bounces the runtime commits.
        backend.reset_cmd = self.bounce_cmd(show_file, 2000)
        backend.stage_cc_mode(topo.chips, MODE_ON)
        backend.reset(topo.chips)
        assert all(backend.query_cc_mode(c) == MODE_ON for c in topo.chips)

    def test_external_restart_surfaces_as_resetting(self, rig):
        backend, set_runtime, show_file = rig
        topo = backend.discover()
        backend.reset_cmd = self.bounce_cmd(show_file, 2000)
        backend.stage_cc_mode(topo.chips, MODE_ON)
        backend.reset(topo.chips)
        assert backend.query_cc_mode(topo.chips[0]) == MODE_ON
        # Someone restarts the runtime behind the manager's back.
        set_runtime("active", 5000)
        assert backend.query_cc_mode(topo.chips[0]) == "resetting"

    def test_health_probe_requires_active_runtime(self, rig):
        backend, set_runtime, _ = rig
        topo = backend.discover()
        assert backend._probe_healthy(topo.chips) is True
        set_runtime("inactive", 1000)
        assert backend._probe_healthy(topo.chips) is False
        with pytest.raises(TpuError):
            backend.wait_ready(topo.chips, timeout_s=0.05)

    def test_state_only_show_output_disables_cross_check(self, rig, tmp_path):
        """A show_cmd that yields ActiveState but no usable activation
        timestamp must read as probe-unavailable — NOT as ts=0, which would
        fail every restart cross-check and brick the node."""
        backend, _, show_file = rig
        show_file.write_text("ActiveState=active\n")  # no timestamp property
        topo = backend.discover()
        backend.stage_cc_mode(topo.chips, MODE_ON)
        backend.reset(topo.chips)  # must NOT raise "did not restart"
        assert all(backend.query_cc_mode(c) == MODE_ON for c in topo.chips)

    def test_health_port_probe(self, rig):
        import socket

        backend, _, _ = rig
        topo = backend.discover()
        srv = socket.socket()
        try:
            srv.bind(("127.0.0.1", 0))
            srv.listen(1)
            backend.health_port = srv.getsockname()[1]
            assert backend._probe_healthy(topo.chips) is True
        finally:
            srv.close()
        assert backend._probe_healthy(topo.chips) is False


class TestHostWrap:
    def test_identity_without_host_root(self, monkeypatch):
        from tpu_cc_manager.tpudev.tpuvm import host_wrap

        monkeypatch.delenv("CC_RUNTIME_SHOW_CMD", raising=False)
        monkeypatch.delenv("CC_HOST_ROOT", raising=False)
        assert host_wrap(["systemctl", "show", "x"]) == ["systemctl", "show", "x"]

    def test_wrap_executes_inside_host_root(self, tmp_path):
        """Functional check of the chroot wrapper (the test runs as root on
        this image): a command resolves against the fake host rootfs, with
        stdout captured by the outer subprocess as the backend expects."""
        import os
        import subprocess

        from tpu_cc_manager.tpudev.tpuvm import host_wrap

        if os.geteuid() != 0:
            pytest.skip("chroot requires root")
        # Minimal fake host rootfs: busybox-style /bin/sh via the static sh
        # is overkill — copy the system's sh + needed libs is fragile, so
        # use a statically-linked helper we already build: native/rmutil/rm
        # is static. Simpler still: chroot to the REAL root ('/') — a
        # no-op boundary that still exercises the wrapper plumbing.
        cmd = host_wrap(["echo", "host-hello"], host_root="/")
        out = subprocess.run(cmd, capture_output=True, timeout=10, text=True)
        assert out.returncode == 0
        assert out.stdout.strip() == "host-hello"


@pytest.mark.parametrize(
    "accel,gen,chips,hosts",
    [
        ("v5e-1", "v5e", 1, 1),
        ("v5e-8", "v5e", 8, 1),
        ("v5p-8", "v5p", 4, 1),
        ("v5p-32", "v5p", 16, 4),
        ("v5p-64", "v5p", 32, 8),
        ("v4-16", "v4", 8, 2),
        ("v6e-16", "v6e", 16, 2),
    ],
)
def test_parse_accelerator_type(accel, gen, chips, hosts):
    assert parse_accelerator_type(accel) == (gen, chips, hosts)


def test_parse_accelerator_type_garbage():
    with pytest.raises(TpuError):
        parse_accelerator_type("not-a-number-x")


def test_load_backend_factory(tmp_path):
    assert isinstance(load_backend("fake"), FakeTpuBackend)
    assert isinstance(load_backend("tpuvm", state_dir=str(tmp_path)), TpuVmBackend)
    with pytest.raises(ValueError):
        load_backend("gpu")
