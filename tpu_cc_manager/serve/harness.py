"""Serving-under-the-flip harness: REAL agents, real rollout, live traffic.

Wires together, against one in-memory apiserver fake:

- a pool of REAL node agents (:class:`CCManager` ``watch_and_apply``
  loops, fake TPU backends, component pods + the emulated operator
  controller reacting to pause labels) — the same full reconcile
  pipeline every other bench drives;
- one :class:`~tpu_cc_manager.serve.server.NodeServer` per node,
  registered on the drain handshake;
- a :class:`~tpu_cc_manager.serve.driver.TrafficDriver` sustaining
  batched traffic across the pool;
- a REAL rolling CC flip (``ccmanager/rolling.py`` — the orchestrator
  ``ctl rollout`` drives) running mid-traffic.

The report is the ROADMAP item 3 artifact: p50/p99 and error rate
*during* the rollout vs steady state, and requests lost per node
bounced (the zero-loss claim).
"""

from __future__ import annotations

import logging
import os
import threading
import time

from tpu_cc_manager.ccmanager.manager import CCManager
from tpu_cc_manager.ccmanager.metrics_server import start_metrics_server
from tpu_cc_manager.ccmanager.remediation import RemediationLadder
from tpu_cc_manager.ccmanager.rolling import RollingReconfigurator
from tpu_cc_manager.drain.sim import add_drainable_node
from tpu_cc_manager.kubeclient.api import node_labels
from tpu_cc_manager.kubeclient.fake import FakeKube
from tpu_cc_manager.labels import (
    CC_MODE_STATE_LABEL,
    MODE_OFF,
    SLICE_ID_LABEL,
)
from tpu_cc_manager.obs import failslow as failslow_mod
from tpu_cc_manager.obs.flight import FlightRecorder
from tpu_cc_manager.obs.journal import Journal
from tpu_cc_manager.obs.slo import SloEvaluator
from tpu_cc_manager.serve.driver import TrafficDriver
from tpu_cc_manager.serve.server import NodeServer, SimulatedExecutor
from tpu_cc_manager.tpudev.fake import FakeTpuBackend
from tpu_cc_manager.utils import retry as retry_mod
from tpu_cc_manager.utils.metrics import MetricsRegistry

log = logging.getLogger(__name__)

NS = "tpu-operator"
POOL_LABEL = "pool"
POOL_VALUE = "tpu-serve"
POOL_SELECTOR = f"{POOL_LABEL}={POOL_VALUE}"


def add_serving_node(
    kube: FakeKube, name: str, pod_delete_delay_s: float = 0.0
) -> None:
    """One drainable node with the serving pool label — the SAME
    emulated operator controller the main bench drives
    (drain/sim.py), so SERVE and BENCH artifacts can never measure
    diverging drain emulations."""
    add_drainable_node(
        kube, name, NS, pod_delete_delay_s=pod_delete_delay_s,
        extra_labels={POOL_LABEL: POOL_VALUE},
    )


class ServeHarness:
    """Build the pool, run traffic, flip it, report what users saw."""

    def __init__(
        self,
        n_nodes: int = 3,
        tmp_dir: str = "/tmp/tpu-cc-serve",
        executor_factory=None,
        drain_ack_timeout_s: float = 10.0,
        pod_delete_delay_s: float = 0.0,
        checkpoint_full_s: float = 0.1,
        reset_latency_s: float = 0.0,
        boot_latency_s: float = 0.0,
        driver_kwargs: dict | None = None,
        metrics_port: int | None = None,
        slo_windows_s: tuple[float, ...] = (5.0, 30.0),
        slo_error_budget: float = 1e-3,
        handoff: bool = False,
        failslow: bool = False,
        failslow_kwargs: dict | None = None,
        failslow_probation_s: float = 2.0,
    ) -> None:
        self.n_nodes = n_nodes
        self.nodes = [f"serve-node-{i}" for i in range(n_nodes)]
        self.tmp_dir = tmp_dir
        # ONE shared registry + SLO evaluator for the serving layer
        # (the per-agent registries below stay per-agent on purpose —
        # each models a separate node process): every server's gauges
        # and the driver's histogram/SLO land here, and metrics_port
        # (0 = ephemeral) serves it live at /metrics + /rolloutz —
        # scrapeable DURING the flip, which is the whole point.
        self.metrics = MetricsRegistry()
        self.slo = SloEvaluator(
            windows_s=slo_windows_s, error_budget=slo_error_budget,
        )
        self.metrics_port = metrics_port
        self.metrics_server = None
        self.flight = FlightRecorder(os.path.join(tmp_dir, "flight.jsonl"))
        self.executor_factory = (
            executor_factory if executor_factory is not None
            else SimulatedExecutor
        )
        self.drain_ack_timeout_s = drain_ack_timeout_s
        self.pod_delete_delay_s = pod_delete_delay_s
        self.checkpoint_full_s = checkpoint_full_s
        self.reset_latency_s = reset_latency_s
        self.boot_latency_s = boot_latency_s
        self.driver_kwargs = driver_kwargs or {}
        # Serving-state handoff (SERVE_r03): a draining server's parked
        # requests migrate straight to an accepting peer inside the ack
        # window instead of requeueing into the driver queue. Off by
        # default so SERVE_r01/r02 measurements keep their shape.
        self.handoff = handoff
        self.kube = FakeKube()
        self.backends: dict[str, FakeTpuBackend] = {}
        self.agents: list[CCManager] = []
        self.servers: dict[str, NodeServer] = {}
        self.driver: TrafficDriver | None = None
        self._agent_threads: list[threading.Thread] = []
        self._agent_stop = threading.Event()
        # Fail-slow plane (GRAY_r01): the peer-relative vetter judging
        # every completion, one remediation ladder per node for
        # containment (runtime-restart -> quarantine reason=fail-slow),
        # and a vet-loop thread pacing the windows and acting verdicts
        # at window cadence. A concurrent rollout journals the same
        # verdicts into its record (crash-resume) and replays them
        # through the same callable — both paths funnel through
        # _failslow_act, whose per-id dedup keeps one verdict from ever
        # escalating twice.
        self.failslow = failslow
        self.failslow_kwargs = failslow_kwargs or {}
        self.failslow_probation_s = failslow_probation_s
        self.failslow_vetter: failslow_mod.FailslowVetter | None = None
        self.ladders: dict[str, RemediationLadder] = {}
        self._failslow_acted: set[str] = set()
        self._suspects_published: set[str] = set()
        self._vet_stop = threading.Event()
        self._vet_thread: threading.Thread | None = None

    # -- pool construction -------------------------------------------------

    def build(self) -> None:
        os.makedirs(self.tmp_dir, exist_ok=True)
        for i, name in enumerate(self.nodes):
            add_serving_node(self.kube, name, self.pod_delete_delay_s)
            backend = FakeTpuBackend(
                num_chips=2,
                accelerator_type="v5p-8",
                slice_id=f"serve-slice-{i}",
                reset_latency_s=self.reset_latency_s,
                boot_latency_s=self.boot_latency_s,
            )
            self.backends[name] = backend
            mgr = CCManager(
                api=self.kube,
                backend=backend,
                node_name=name,
                default_mode=MODE_OFF,
                operator_namespace=NS,
                evict_components=True,
                smoke_workload="none",
                metrics=MetricsRegistry(),
                journal=Journal(trace_file=""),
                eviction_timeout_s=30,
                eviction_poll_interval_s=0.02,
                drain_ack_timeout_s=self.drain_ack_timeout_s,
                watch_timeout_s=1,
                reconnect_delay_s=0.0,
                readiness_file=f"{self.tmp_dir}/ready-{name}",
            )
            self.agents.append(mgr)
            t = threading.Thread(
                target=mgr.watch_and_apply, args=(self._agent_stop,),
                daemon=True, name=f"agent-{name}",
            )
            self._agent_threads.append(t)
        for t in self._agent_threads:
            t.start()
        if not self._await_settled():
            raise RuntimeError("serving pool agents never settled")
        # Forwarding closures break the server↔driver construction cycle
        # (nothing fires before run() starts the servers, by which time
        # the driver exists).
        if self.failslow:
            kwargs = dict(self.failslow_kwargs)
            kwargs.setdefault("metrics", self.metrics)
            self.failslow_vetter = failslow_mod.FailslowVetter.from_env(
                **kwargs
            )
            self.ladders = {
                name: RemediationLadder(
                    self.kube, name, backend=self.backends[name],
                    probation_s=self.failslow_probation_s,
                    metrics=self.metrics,
                )
                for name in self.nodes
            }
        self.servers = {
            name: NodeServer(
                self.kube, name,
                on_complete=lambda n, r, u: self._on_complete(n, r, u),
                on_requeue=lambda n, rs: self.driver.on_requeue(n, rs),
                on_shed=lambda n, rs: self.driver.on_shed(n, rs),
                on_handoff=(
                    (lambda n, rs: self.driver.on_handoff(n, rs))
                    if self.handoff else None
                ),
                executor=self.executor_factory(),
                checkpoint_full_s=self.checkpoint_full_s,
                metrics=self.metrics,
            )
            for name in self.nodes
        }
        self.driver = TrafficDriver(
            self.servers, metrics=self.metrics, slo=self.slo,
            **self.driver_kwargs,
        )
        if self.metrics_port is not None:
            self.metrics_server = start_metrics_server(
                self.metrics_port, self.metrics,
                bind="127.0.0.1", flight=self.flight,
            )

    def _await_settled(self, timeout_s: float = 30.0) -> bool:
        def settled() -> bool:
            for name in self.nodes:
                labels = node_labels(self.kube.get_node(name))
                if labels.get(CC_MODE_STATE_LABEL) != MODE_OFF:
                    return False
                if not labels.get(SLICE_ID_LABEL):
                    return False
            return True

        return retry_mod.poll_until(settled, timeout_s, 0.05)

    # -- fail-slow plane ---------------------------------------------------

    def _on_complete(self, node, req, util) -> None:
        """Driver completion callback, teed into the fail-slow vetter:
        every finished request's SERVICE time (dispatch to completion)
        is one peer-relative sample for the node that served it. NOT
        end-to-end latency: the driver's pending queue is shared, so
        under overload its wait inflates every node's arrival-to-done
        latency together and the peer ratio compresses toward 1 —
        exactly when a browned-out node is eating the fleet's headroom.
        Service time stays a property of the node alone."""
        self.driver.on_complete(node, req, util)
        if (
            self.failslow_vetter is not None
            and req.completed_at is not None
        ):
            t0 = (
                req.started_at
                if req.started_at is not None else req.submitted_at
            )
            self.failslow_vetter.observe(
                node, max(0.0, req.completed_at - t0)
            )

    def _failslow_act(self, node: str, entry: dict) -> None:
        """Containment for ONE fail-slow verdict — the callable the
        rolling orchestrator invokes behind its ``failslow-vetted``
        crash point, and the vet loop invokes between rollouts.
        Idempotent per verdict id (the rolling journal may replay an
        act after a mid-act SIGKILL): a replayed id is a no-op, so a
        node can never be double-escalated for one verdict."""
        key = str(entry.get("id", ""))
        if key and key in self._failslow_acted:
            return
        ladder = self.ladders.get(node)
        if ladder is None:
            return
        if entry.get("verdict") == failslow_mod.VERDICT_CONFIRMED:
            step = ladder.note_failslow(entry.get("deviation"))
            log.warning(
                "fail-slow containment: node %s verdict %s "
                "(deviation %.2fx) -> %s",
                node, key or "?", float(entry.get("deviation") or 0.0),
                step,
            )
        else:
            ladder.note_failslow_recovered()
            log.info(
                "fail-slow cleared: node %s verdict %s (peer-relative "
                "stats recovered)", node, key or "?",
            )
        if key:
            self._failslow_acted.add(key)

    def _vet_once(self) -> None:
        """One vetting window: judge, publish the suspect set to the
        driver (de-weighting) and the node labels (ctl status SUSPECT
        column), then — only while no rollout owns the journal — act
        any verdicts the orchestrator has not already acted."""
        vetter = self.failslow_vetter
        vetter.vet()
        suspects = vetter.suspects()
        if self.driver is not None:
            self.driver.set_suspects(suspects)
        added = suspects - self._suspects_published
        removed = self._suspects_published - suspects
        if added or removed:
            failslow_mod.publish_suspect_labels(
                self.kube, sorted(added), sorted(removed)
            )
            self._suspects_published = set(suspects)
        # Containment latency is the vet loop's job: verdicts are acted
        # HERE, at window cadence, not deferred to the next rollout
        # window boundary. The rolling orchestrator journals the same
        # verdicts into its record (crash-resume) and replays them
        # through this same callable — the per-id dedup makes whichever
        # path runs second a no-op, so the two consumers can never
        # double-escalate one verdict.
        for entry in vetter.concluded():
            self._failslow_act(str(entry.get("node")), entry)
        # Probation feed: a quarantined node that is no longer suspect
        # accrues healthy probes, so the lift (reason=fail-slow release)
        # happens on recovery without a separate watchdog in the
        # harness.
        for name, ladder in self.ladders.items():
            if ladder.quarantined and name not in suspects:
                ladder.note_probe(True)

    def _vet_loop(self) -> None:
        while not self._vet_stop.wait(self.failslow_vetter.window_s):
            try:
                self._vet_once()
            except Exception:  # noqa: BLE001 - vetting never kills traffic
                log.warning(
                    "fail-slow vet pass failed; continuing", exc_info=True
                )

    def _start_vetting(self) -> None:
        if self.failslow_vetter is None or self._vet_thread is not None:
            return
        self._vet_stop.clear()
        self._vet_thread = threading.Thread(
            target=self._vet_loop, daemon=True, name="failslow-vet",
        )
        self._vet_thread.start()

    def _stop_vetting(self) -> None:
        if self._vet_thread is None:
            return
        self._vet_stop.set()
        self._vet_thread.join(timeout=10)
        self._vet_thread = None

    def set_brownout(self, node: str, token_rate_factor: float) -> None:
        """Degrade (or restore, factor 1.0) one node's executor token
        rate AND its fake TPU latency walls — the seeded gray-failure
        injection: the node keeps completing requests and passing
        probes, just slower."""
        server = self.servers.get(node)
        if server is not None and hasattr(server.executor, "set_brownout"):
            server.executor.set_brownout(token_rate_factor)
        backend = self.backends.get(node)
        if backend is not None:
            backend.set_brownout(token_rate_factor)

    # -- run ---------------------------------------------------------------

    def run(
        self,
        traffic_s: float = 6.0,
        rollout_mode: str | None = "on",
        warmup_frac: float = 0.25,
        max_unavailable: int = 1,
        rollout_timeout_s: float = 60.0,
        rollout_hook=None,
        slo_max_burn_rate: float | None = None,
        slo_p99_target_ms: float | None = None,
        slo_window_s: float | None = None,
        slo_max_pause_s: float = 60.0,
        roller_kwargs: dict | None = None,
    ) -> dict:
        """Sustain traffic for ``traffic_s`` (plus however long the flip
        needs), run the rolling CC flip after ``warmup_frac`` of it, and
        report. The steady-state buckets are the pre-flip warmup and the
        post-flip tail. ``rollout_hook`` is passed to the orchestrator's
        named crash points ("window-start"/"mid-window"/...) — the
        mid-flip scrape tests hang their assertions there, so "scraped
        during the flip" is true by construction, not by sleep-timing.

        ``rollout_mode=None`` runs traffic with NO flip (the rate
        sweep's steady measurement). ``slo_max_burn_rate`` /
        ``slo_p99_target_ms`` arm the orchestrator's wave-boundary SLO
        gate with THIS harness's live evaluator — the in-process form of
        the latency-gated rollout (``ctl rollout --slo-source`` is the
        remote one)."""
        assert self.driver is not None, "call build() first"
        for server in self.servers.values():
            server.start()
        self.driver.start()
        self._start_vetting()
        result = None
        t_roll_0 = t_roll_1 = None
        try:
            if rollout_mode is None:
                retry_mod.wait(traffic_s, None)
            else:
                retry_mod.wait(traffic_s * warmup_frac, None)
                slo_gate = None
                slo_config = None
                if slo_max_burn_rate is not None or slo_p99_target_ms is not None:
                    from tpu_cc_manager.ccmanager.rolling import SloGateConfig

                    burn = (
                        slo_max_burn_rate
                        if slo_max_burn_rate is not None else 1.0
                    )
                    target_s = (
                        slo_p99_target_ms / 1e3
                        if slo_p99_target_ms is not None else None
                    )
                    slo_config = SloGateConfig(
                        max_burn_rate=burn,
                        p99_target_ms=slo_p99_target_ms,
                        window_s=slo_window_s,
                        max_pause_s=slo_max_pause_s,
                    )

                    def slo_gate() -> bool:
                        return self.slo.breached(
                            max_burn_rate=burn,
                            window_s=slo_window_s,
                            p99_target_s=target_s,
                        )

                extra = dict(roller_kwargs or {})
                if self.failslow_vetter is not None:
                    # The orchestrator owns verdict acting during the
                    # flip: journaled in the record, acted behind the
                    # failslow-vetted crash point — _failslow_act's
                    # per-id dedup keeps a replay harmless.
                    extra.setdefault("failslow_vetter", self.failslow_vetter)
                    extra.setdefault("failslow_act", self._failslow_act)
                roller = RollingReconfigurator(
                    self.kube, POOL_SELECTOR,
                    max_unavailable=max_unavailable,
                    node_timeout_s=rollout_timeout_s,
                    poll_interval_s=0.02,
                    crash_hook=rollout_hook,
                    flight=self.flight,
                    metrics=self.metrics,
                    slo_gate=slo_gate,
                    slo_config=slo_config,
                    # Extra orchestrator knobs (BENCH_r09 passes
                    # continuous_prestage + headroom_gate here).
                    **extra,
                )
                t_roll_0 = time.monotonic()
                result = roller.rollout(rollout_mode)
                t_roll_1 = time.monotonic()
                # Post-flip steady tail: the rest of the traffic budget,
                # at least a second so the tail bucket has data.
                tail = max(1.0, traffic_s * (1.0 - warmup_frac))
                retry_mod.wait(tail, None)
        finally:
            self.driver.stop()
        # Everything still in the system must complete: the zero-loss
        # claim is checked AFTER the grace drain, not before.
        self.driver.drain_outstanding(grace_s=15.0)
        if rollout_mode is None:
            return self.driver.report()
        bounced = sum(
            1 for name in self.nodes
            if node_labels(self.kube.get_node(name)).get(
                CC_MODE_STATE_LABEL
            ) == rollout_mode
        )
        report = self.driver.report(
            rollout_window=(t_roll_0, t_roll_1), nodes_bounced=bounced,
        )
        report["rollout_ok"] = bool(result.ok)
        report["rollout_wall_s"] = round(t_roll_1 - t_roll_0, 3)
        report["rollout_summary"] = result.summary()
        report["rollout_slo_pauses"] = self.metrics.rollout_totals()[
            "slo_pauses"
        ]
        report["drains"] = {
            name: {
                "drains": s.drains,
                "resumes": s.resumes,
                "last_checkpoint_s": (
                    round(s.last_checkpoint_s, 4)
                    if s.last_checkpoint_s is not None else None
                ),
                "last_checkpoint_deadline_s": s.last_checkpoint_deadline_s,
                # Both per-LAST-drain, so the pair stays comparable;
                # the cumulative migration count rides separately.
                "requeued": s.last_checkpoint_requeued,
                "handed_off": s.last_handoff_accepted,
                "handed_off_total": s.handoffs_accepted,
            }
            for name, s in self.servers.items()
        }
        return report

    def metrics_address(self) -> str | None:
        """host:port of the live serve /metrics endpoint (None when
        metrics_port was not given)."""
        if self.metrics_server is None:
            return None
        host, port = self.metrics_server.server_address[:2]
        return f"{host}:{port}"

    def shutdown(self) -> None:
        self._stop_vetting()
        for server in self.servers.values():
            server.stop()
        self._agent_stop.set()
        for t in self._agent_threads:
            t.join(timeout=10)
        if self.metrics_server is not None:
            self.metrics_server.shutdown()
