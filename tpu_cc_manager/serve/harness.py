"""Serving-under-the-flip harness: REAL agents, real rollout, live traffic.

Wires together, against one in-memory apiserver fake:

- a pool of REAL node agents (:class:`CCManager` ``watch_and_apply``
  loops, fake TPU backends, component pods + the emulated operator
  controller reacting to pause labels) — the same full reconcile
  pipeline every other bench drives;
- one :class:`~tpu_cc_manager.serve.server.NodeServer` per node,
  registered on the drain handshake;
- a :class:`~tpu_cc_manager.serve.driver.TrafficDriver` sustaining
  batched traffic across the pool;
- a REAL rolling CC flip (``ccmanager/rolling.py`` — the orchestrator
  ``ctl rollout`` drives) running mid-traffic.

The report is the ROADMAP item 3 artifact: p50/p99 and error rate
*during* the rollout vs steady state, and requests lost per node
bounced (the zero-loss claim).
"""

from __future__ import annotations

import logging
import os
import threading
import time

from tpu_cc_manager.ccmanager.manager import CCManager
from tpu_cc_manager.ccmanager.metrics_server import start_metrics_server
from tpu_cc_manager.ccmanager.rolling import RollingReconfigurator
from tpu_cc_manager.drain.sim import add_drainable_node
from tpu_cc_manager.kubeclient.api import node_labels
from tpu_cc_manager.kubeclient.fake import FakeKube
from tpu_cc_manager.labels import (
    CC_MODE_STATE_LABEL,
    MODE_OFF,
    SLICE_ID_LABEL,
)
from tpu_cc_manager.obs.flight import FlightRecorder
from tpu_cc_manager.obs.journal import Journal
from tpu_cc_manager.obs.slo import SloEvaluator
from tpu_cc_manager.serve.driver import TrafficDriver
from tpu_cc_manager.serve.server import NodeServer, SimulatedExecutor
from tpu_cc_manager.tpudev.fake import FakeTpuBackend
from tpu_cc_manager.utils import retry as retry_mod
from tpu_cc_manager.utils.metrics import MetricsRegistry

log = logging.getLogger(__name__)

NS = "tpu-operator"
POOL_LABEL = "pool"
POOL_VALUE = "tpu-serve"
POOL_SELECTOR = f"{POOL_LABEL}={POOL_VALUE}"


def add_serving_node(
    kube: FakeKube, name: str, pod_delete_delay_s: float = 0.0
) -> None:
    """One drainable node with the serving pool label — the SAME
    emulated operator controller the main bench drives
    (drain/sim.py), so SERVE and BENCH artifacts can never measure
    diverging drain emulations."""
    add_drainable_node(
        kube, name, NS, pod_delete_delay_s=pod_delete_delay_s,
        extra_labels={POOL_LABEL: POOL_VALUE},
    )


class ServeHarness:
    """Build the pool, run traffic, flip it, report what users saw."""

    def __init__(
        self,
        n_nodes: int = 3,
        tmp_dir: str = "/tmp/tpu-cc-serve",
        executor_factory=None,
        drain_ack_timeout_s: float = 10.0,
        pod_delete_delay_s: float = 0.0,
        checkpoint_full_s: float = 0.1,
        reset_latency_s: float = 0.0,
        boot_latency_s: float = 0.0,
        driver_kwargs: dict | None = None,
        metrics_port: int | None = None,
        slo_windows_s: tuple[float, ...] = (5.0, 30.0),
        slo_error_budget: float = 1e-3,
        handoff: bool = False,
    ) -> None:
        self.n_nodes = n_nodes
        self.nodes = [f"serve-node-{i}" for i in range(n_nodes)]
        self.tmp_dir = tmp_dir
        # ONE shared registry + SLO evaluator for the serving layer
        # (the per-agent registries below stay per-agent on purpose —
        # each models a separate node process): every server's gauges
        # and the driver's histogram/SLO land here, and metrics_port
        # (0 = ephemeral) serves it live at /metrics + /rolloutz —
        # scrapeable DURING the flip, which is the whole point.
        self.metrics = MetricsRegistry()
        self.slo = SloEvaluator(
            windows_s=slo_windows_s, error_budget=slo_error_budget,
        )
        self.metrics_port = metrics_port
        self.metrics_server = None
        self.flight = FlightRecorder(os.path.join(tmp_dir, "flight.jsonl"))
        self.executor_factory = (
            executor_factory if executor_factory is not None
            else SimulatedExecutor
        )
        self.drain_ack_timeout_s = drain_ack_timeout_s
        self.pod_delete_delay_s = pod_delete_delay_s
        self.checkpoint_full_s = checkpoint_full_s
        self.reset_latency_s = reset_latency_s
        self.boot_latency_s = boot_latency_s
        self.driver_kwargs = driver_kwargs or {}
        # Serving-state handoff (SERVE_r03): a draining server's parked
        # requests migrate straight to an accepting peer inside the ack
        # window instead of requeueing into the driver queue. Off by
        # default so SERVE_r01/r02 measurements keep their shape.
        self.handoff = handoff
        self.kube = FakeKube()
        self.backends: dict[str, FakeTpuBackend] = {}
        self.agents: list[CCManager] = []
        self.servers: dict[str, NodeServer] = {}
        self.driver: TrafficDriver | None = None
        self._agent_threads: list[threading.Thread] = []
        self._agent_stop = threading.Event()

    # -- pool construction -------------------------------------------------

    def build(self) -> None:
        os.makedirs(self.tmp_dir, exist_ok=True)
        for i, name in enumerate(self.nodes):
            add_serving_node(self.kube, name, self.pod_delete_delay_s)
            backend = FakeTpuBackend(
                num_chips=2,
                accelerator_type="v5p-8",
                slice_id=f"serve-slice-{i}",
                reset_latency_s=self.reset_latency_s,
                boot_latency_s=self.boot_latency_s,
            )
            self.backends[name] = backend
            mgr = CCManager(
                api=self.kube,
                backend=backend,
                node_name=name,
                default_mode=MODE_OFF,
                operator_namespace=NS,
                evict_components=True,
                smoke_workload="none",
                metrics=MetricsRegistry(),
                journal=Journal(trace_file=""),
                eviction_timeout_s=30,
                eviction_poll_interval_s=0.02,
                drain_ack_timeout_s=self.drain_ack_timeout_s,
                watch_timeout_s=1,
                reconnect_delay_s=0.0,
                readiness_file=f"{self.tmp_dir}/ready-{name}",
            )
            self.agents.append(mgr)
            t = threading.Thread(
                target=mgr.watch_and_apply, args=(self._agent_stop,),
                daemon=True, name=f"agent-{name}",
            )
            self._agent_threads.append(t)
        for t in self._agent_threads:
            t.start()
        if not self._await_settled():
            raise RuntimeError("serving pool agents never settled")
        # Forwarding closures break the server↔driver construction cycle
        # (nothing fires before run() starts the servers, by which time
        # the driver exists).
        self.servers = {
            name: NodeServer(
                self.kube, name,
                on_complete=lambda n, r, u: self.driver.on_complete(n, r, u),
                on_requeue=lambda n, rs: self.driver.on_requeue(n, rs),
                on_shed=lambda n, rs: self.driver.on_shed(n, rs),
                on_handoff=(
                    (lambda n, rs: self.driver.on_handoff(n, rs))
                    if self.handoff else None
                ),
                executor=self.executor_factory(),
                checkpoint_full_s=self.checkpoint_full_s,
                metrics=self.metrics,
            )
            for name in self.nodes
        }
        self.driver = TrafficDriver(
            self.servers, metrics=self.metrics, slo=self.slo,
            **self.driver_kwargs,
        )
        if self.metrics_port is not None:
            self.metrics_server = start_metrics_server(
                self.metrics_port, self.metrics,
                bind="127.0.0.1", flight=self.flight,
            )

    def _await_settled(self, timeout_s: float = 30.0) -> bool:
        def settled() -> bool:
            for name in self.nodes:
                labels = node_labels(self.kube.get_node(name))
                if labels.get(CC_MODE_STATE_LABEL) != MODE_OFF:
                    return False
                if not labels.get(SLICE_ID_LABEL):
                    return False
            return True

        return retry_mod.poll_until(settled, timeout_s, 0.05)

    # -- run ---------------------------------------------------------------

    def run(
        self,
        traffic_s: float = 6.0,
        rollout_mode: str | None = "on",
        warmup_frac: float = 0.25,
        max_unavailable: int = 1,
        rollout_timeout_s: float = 60.0,
        rollout_hook=None,
        slo_max_burn_rate: float | None = None,
        slo_p99_target_ms: float | None = None,
        slo_window_s: float | None = None,
        slo_max_pause_s: float = 60.0,
        roller_kwargs: dict | None = None,
    ) -> dict:
        """Sustain traffic for ``traffic_s`` (plus however long the flip
        needs), run the rolling CC flip after ``warmup_frac`` of it, and
        report. The steady-state buckets are the pre-flip warmup and the
        post-flip tail. ``rollout_hook`` is passed to the orchestrator's
        named crash points ("window-start"/"mid-window"/...) — the
        mid-flip scrape tests hang their assertions there, so "scraped
        during the flip" is true by construction, not by sleep-timing.

        ``rollout_mode=None`` runs traffic with NO flip (the rate
        sweep's steady measurement). ``slo_max_burn_rate`` /
        ``slo_p99_target_ms`` arm the orchestrator's wave-boundary SLO
        gate with THIS harness's live evaluator — the in-process form of
        the latency-gated rollout (``ctl rollout --slo-source`` is the
        remote one)."""
        assert self.driver is not None, "call build() first"
        for server in self.servers.values():
            server.start()
        self.driver.start()
        result = None
        t_roll_0 = t_roll_1 = None
        try:
            if rollout_mode is None:
                retry_mod.wait(traffic_s, None)
            else:
                retry_mod.wait(traffic_s * warmup_frac, None)
                slo_gate = None
                slo_config = None
                if slo_max_burn_rate is not None or slo_p99_target_ms is not None:
                    from tpu_cc_manager.ccmanager.rolling import SloGateConfig

                    burn = (
                        slo_max_burn_rate
                        if slo_max_burn_rate is not None else 1.0
                    )
                    target_s = (
                        slo_p99_target_ms / 1e3
                        if slo_p99_target_ms is not None else None
                    )
                    slo_config = SloGateConfig(
                        max_burn_rate=burn,
                        p99_target_ms=slo_p99_target_ms,
                        window_s=slo_window_s,
                        max_pause_s=slo_max_pause_s,
                    )

                    def slo_gate() -> bool:
                        return self.slo.breached(
                            max_burn_rate=burn,
                            window_s=slo_window_s,
                            p99_target_s=target_s,
                        )

                roller = RollingReconfigurator(
                    self.kube, POOL_SELECTOR,
                    max_unavailable=max_unavailable,
                    node_timeout_s=rollout_timeout_s,
                    poll_interval_s=0.02,
                    crash_hook=rollout_hook,
                    flight=self.flight,
                    metrics=self.metrics,
                    slo_gate=slo_gate,
                    slo_config=slo_config,
                    # Extra orchestrator knobs (BENCH_r09 passes
                    # continuous_prestage + headroom_gate here).
                    **(roller_kwargs or {}),
                )
                t_roll_0 = time.monotonic()
                result = roller.rollout(rollout_mode)
                t_roll_1 = time.monotonic()
                # Post-flip steady tail: the rest of the traffic budget,
                # at least a second so the tail bucket has data.
                tail = max(1.0, traffic_s * (1.0 - warmup_frac))
                retry_mod.wait(tail, None)
        finally:
            self.driver.stop()
        # Everything still in the system must complete: the zero-loss
        # claim is checked AFTER the grace drain, not before.
        self.driver.drain_outstanding(grace_s=15.0)
        if rollout_mode is None:
            return self.driver.report()
        bounced = sum(
            1 for name in self.nodes
            if node_labels(self.kube.get_node(name)).get(
                CC_MODE_STATE_LABEL
            ) == rollout_mode
        )
        report = self.driver.report(
            rollout_window=(t_roll_0, t_roll_1), nodes_bounced=bounced,
        )
        report["rollout_ok"] = bool(result.ok)
        report["rollout_wall_s"] = round(t_roll_1 - t_roll_0, 3)
        report["rollout_summary"] = result.summary()
        report["rollout_slo_pauses"] = self.metrics.rollout_totals()[
            "slo_pauses"
        ]
        report["drains"] = {
            name: {
                "drains": s.drains,
                "resumes": s.resumes,
                "last_checkpoint_s": (
                    round(s.last_checkpoint_s, 4)
                    if s.last_checkpoint_s is not None else None
                ),
                "last_checkpoint_deadline_s": s.last_checkpoint_deadline_s,
                # Both per-LAST-drain, so the pair stays comparable;
                # the cumulative migration count rides separately.
                "requeued": s.last_checkpoint_requeued,
                "handed_off": s.last_handoff_accepted,
                "handed_off_total": s.handoffs_accepted,
            }
            for name, s in self.servers.items()
        }
        return report

    def metrics_address(self) -> str | None:
        """host:port of the live serve /metrics endpoint (None when
        metrics_port was not given)."""
        if self.metrics_server is None:
            return None
        host, port = self.metrics_server.server_address[:2]
        return f"{host}:{port}"

    def shutdown(self) -> None:
        for server in self.servers.values():
            server.stop()
        self._agent_stop.set()
        for t in self._agent_threads:
            t.join(timeout=10)
        if self.metrics_server is not None:
            self.metrics_server.shutdown()
