"""Traffic driver: sustained batched inference across a pool under flip.

Closed-loop driver with a per-node batch ladder:

- keeps each accepting server's pipe ~``pipe_depth`` batches deep,
  routing around draining/bounced nodes (their requests come back via
  checkpoint-and-requeue and are re-dispatched with progress intact);
- adapts each node's batch size from its reported ``hbm_bw_util``:
  below ``util_ceiling`` there is headroom → step the batch up ONE rung;
  above it step down. One rung at a time, and a ceiling strictly below
  1.0, because the utilization read is a useful-traffic LOWER bound
  (smoke/llama_infer.py — the padded+masked KV stream makes the
  marginal-cost model worst-case): the ladder's headroom read is
  deliberately conservative, never optimistic;
- stamps every request at creation and never restamps: reported latency
  is end-to-end what a user saw, checkpoint bounces included.

The report splits completions into steady-state vs a caller-marked
rollout window and carries the headline the harness commits:
``requests_lost_per_node_bounced`` (target: zero — a request is lost
only if it never completed after traffic stopped and the grace drain
expired).
"""

from __future__ import annotations

import logging
import threading
import time

from tpu_cc_manager.obs import slo as slo_mod
from tpu_cc_manager.serve.server import NodeServer, Request
from tpu_cc_manager.utils import locks as locks_mod
from tpu_cc_manager.utils import metrics as metrics_mod
from tpu_cc_manager.utils import retry as retry_mod

log = logging.getLogger(__name__)


def _percentile(sorted_vals: list[float], q: float) -> float | None:
    if not sorted_vals:
        return None
    idx = min(len(sorted_vals) - 1, max(0, round(q * (len(sorted_vals) - 1))))
    return sorted_vals[idx]


class TrafficDriver:
    def __init__(
        self,
        servers: dict[str, NodeServer],
        request_tokens: int = 8,
        initial_batch: int = 2,
        min_batch: int = 1,
        max_batch: int = 16,
        util_ceiling: float = 0.9,
        ladder_interval_s: float = 0.25,
        submit_interval_s: float = 0.01,
        pipe_depth: int = 2,
        metrics: metrics_mod.MetricsRegistry | None = None,
        slo: slo_mod.SloEvaluator | None = None,
    ) -> None:
        self.servers = servers
        # Live serving telemetry: completions feed the per-node latency
        # histogram + outcome counters (tpu_cc_serve_*) and the SLO
        # evaluator; the ladder tick exports the windowed p99 /
        # burn-rate / goodput gauges, so a scrape DURING a flip reads
        # the live SLO — the contract the latency-gated rollout polls.
        self.metrics = metrics
        self.slo = slo
        self.request_tokens = request_tokens
        self.min_batch = min_batch
        self.max_batch = max_batch
        self.util_ceiling = util_ceiling
        self.ladder_interval_s = ladder_interval_s
        self.submit_interval_s = submit_interval_s
        self.pipe_depth = pipe_depth
        self._lock = locks_mod.make_lock("serve.driver")
        self._pending: list[Request] = []  # cclint: guarded-by(_lock)
        self._completed: list[Request] = []  # cclint: guarded-by(_lock)
        self._outstanding: dict[str, int] = {  # cclint: guarded-by(_lock)
            name: 0 for name in servers
        }
        self._batch: dict[str, int] = {  # cclint: guarded-by(_lock)
            name: initial_batch for name in servers
        }
        self._next_id = 0  # cclint: guarded-by(_lock)
        self._requeues = 0  # cclint: guarded-by(_lock)
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None

    # -- server callbacks --------------------------------------------------

    def on_complete(self, node: str, req: Request, util: float) -> None:
        with self._lock:
            self._completed.append(req)
            self._outstanding[node] = max(0, self._outstanding[node] - 1)
        if req.completed_at is not None:
            lat = max(0.0, req.completed_at - req.submitted_at)
            if self.metrics is not None:
                self.metrics.observe_serve_request(node, lat)
                self.metrics.record_serve_outcome(node, "completed")
            if self.slo is not None:
                self.slo.observe(lat, ok=True)

    def on_requeue(self, node: str, reqs: list[Request]) -> None:
        """Checkpointed requests coming back from a draining server:
        front of the queue (oldest first) so the bounce delay they
        already paid is not compounded by re-queueing behind fresh
        traffic."""
        with self._lock:
            self._requeues += len(reqs)
            self._outstanding[node] = max(
                0, self._outstanding[node] - len(reqs)
            )
            self._pending[:0] = reqs
        if self.metrics is not None:
            self.metrics.record_serve_outcome(node, "bounced", len(reqs))

    # -- driving loop ------------------------------------------------------

    def start(self) -> None:
        self._thread = threading.Thread(
            target=self._run, daemon=True, name="serve-driver"
        )
        self._thread.start()

    def stop(self, timeout_s: float = 10.0) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=timeout_s)

    def _run(self) -> None:
        last_ladder = time.monotonic()
        while not self._stop.is_set():
            now = time.monotonic()
            if now - last_ladder >= self.ladder_interval_s:
                self._ladder_step()
                last_ladder = now
            self._dispatch_round(top_up=True)
            retry_mod.wait(self.submit_interval_s, self._stop)

    def _dispatch_round(self, top_up: bool) -> None:
        """Fill each accepting server's pipe to ``pipe_depth`` batches.
        ``top_up`` mints fresh requests when the pending queue runs dry
        (closed-loop traffic); the drain pass after stop() leaves it off
        so only in-system requests finish."""
        for name, server in self.servers.items():
            if not server.accepting():
                continue
            with self._lock:
                bsz = self._batch[name]
                if self._outstanding[name] >= self.pipe_depth * bsz:
                    continue
                if top_up:
                    now = time.monotonic()
                    while len(self._pending) < bsz:
                        self._next_id += 1
                        self._pending.append(Request(
                            req_id=self._next_id,
                            decode_tokens=self.request_tokens,
                            submitted_at=now,
                        ))
                batch = self._pending[:bsz]
                if not batch:
                    continue
                del self._pending[:len(batch)]
                self._outstanding[name] += len(batch)
            if not server.submit(batch):
                # Lost the race with a drain: keep the requests, let the
                # next round route them to an accepting server.
                with self._lock:
                    self._outstanding[name] = max(
                        0, self._outstanding[name] - len(batch)
                    )
                    self._pending[:0] = batch
                if self.metrics is not None:
                    self.metrics.record_serve_outcome(
                        name, "requeued", len(batch)
                    )

    def _export_slo(self) -> None:
        """Export the live windowed SLO readout + goodput gauges —
        piggybacked on the ladder tick so the gauges stay fresh at the
        ladder's cadence without a dedicated timer thread."""
        if self.slo is None:
            return
        snap = self.slo.snapshot()
        for w in snap["windows"]:
            if self.metrics is not None:
                self.metrics.set_serve_slo(
                    w["window_s"], w["p99_s"], w["burn_rate"]
                )
        if self.metrics is not None and snap["windows"]:
            self.metrics.set_serve_goodput(
                snap["windows"][0]["goodput_rps"]
            )

    def _ladder_step(self) -> None:
        """One conservative rung per interval, per node, off the last
        reported ``hbm_bw_util``: the read is a lower bound, so the
        ceiling sits below 1.0 and the ladder never jumps rungs."""
        self._export_slo()
        for name, server in self.servers.items():
            util = server.last_hbm_bw_util
            if util is None:
                continue
            with self._lock:
                if util < self.util_ceiling and self._batch[name] < self.max_batch:
                    self._batch[name] += 1
                elif util > self.util_ceiling and self._batch[name] > self.min_batch:
                    self._batch[name] -= 1

    def drain_outstanding(self, grace_s: float = 10.0) -> None:
        """After stop(): keep dispatching ONLY in-system requests until
        everything completed or the grace expires (whatever remains is
        counted lost — the harness's zero-loss claim hinges here)."""

        def settled() -> bool:
            self._dispatch_round(top_up=False)
            with self._lock:
                return (
                    not self._pending
                    and all(v == 0 for v in self._outstanding.values())
                )

        retry_mod.poll_until(settled, grace_s, 0.02)
        with self._lock:
            lost = len(self._pending) + sum(self._outstanding.values())
        if lost:
            # Each lost request is a counted SLO error AND a counter
            # bump — the zero-loss contract's violation is visible both
            # in the burn-rate gauge and in tpu_cc_serve_lost_total.
            if self.metrics is not None:
                self.metrics.record_serve_lost(lost)
            if self.slo is not None:
                for _ in range(lost):
                    self.slo.observe_error()
        self._export_slo()

    # -- reporting ---------------------------------------------------------

    def snapshot_batches(self) -> dict[str, int]:
        with self._lock:
            return dict(self._batch)

    def report(
        self,
        rollout_window: tuple[float, float] | None = None,
        nodes_bounced: int = 0,
    ) -> dict:
        """Latency/loss summary. ``rollout_window`` is (start, end) on
        the driver's monotonic clock; the during-rollout bucket is every
        request whose in-system interval [submitted_at, completed_at]
        OVERLAPS the window — exactly the requests a user had in flight
        while the pool flipped. (Bucketing by completion time alone
        would park a request bounced by the LAST node's drain — which
        completes just after the rollout returns — in the steady bucket,
        inflating steady p99 and understating the disruption the
        artifact headlines.)"""
        with self._lock:
            completed = list(self._completed)
            in_system = len(self._pending) + sum(
                self._outstanding.values()
            )
            requeues = self._requeues
            issued = self._next_id
        lat_all, lat_roll, lat_steady = [], [], []
        for r in completed:
            if r.completed_at is None:
                continue
            lat = r.completed_at - r.submitted_at
            lat_all.append(lat)
            if rollout_window and (
                r.completed_at >= rollout_window[0]
                and r.submitted_at <= rollout_window[1]
            ):
                lat_roll.append(lat)
            else:
                lat_steady.append(lat)
        lat_all.sort(); lat_roll.sort(); lat_steady.sort()
        lost = in_system  # after drain_outstanding: nothing should remain

        def stats(vals: list[float]) -> dict:
            return {
                "count": len(vals),
                "p50_ms": round(1e3 * _percentile(vals, 0.50), 2) if vals else None,
                "p99_ms": round(1e3 * _percentile(vals, 0.99), 2) if vals else None,
                "max_ms": round(1e3 * vals[-1], 2) if vals else None,
            }

        denom = len(completed) + lost
        return {
            "requests_issued": issued,
            "requests_completed": len(completed),
            "requests_lost": lost,
            "requests_requeued": requeues,
            "error_rate": round(lost / denom, 6) if denom else 0.0,
            "nodes_bounced": nodes_bounced,
            "requests_lost_per_node_bounced": (
                round(lost / nodes_bounced, 6) if nodes_bounced else lost
            ),
            "latency": stats(lat_all),
            "latency_during_rollout": stats(lat_roll),
            "latency_steady_state": stats(lat_steady),
            "batch_ladder": self.snapshot_batches(),
            "slo": self.slo.snapshot() if self.slo is not None else None,
        }
