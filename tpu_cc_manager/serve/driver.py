"""Traffic driver: sustained batched inference across a pool under flip.

Two traffic modes share one dispatch/accounting core:

**Closed loop** (default, SERVE_r01): keeps each accepting server's pipe
~``pipe_depth`` batches deep, minting requests as the pipes drain. The
load adapts to the pool — which is exactly why a closed-loop driver can
never observe queueing collapse: when nodes drain it backs off, the
classic coordinated-omission trap.

**Open loop** (``schedule=``, SERVE_r02): a rate-driven arrival process
(:class:`PoissonSchedule` / :class:`RampSchedule`, seeded rng) submits
on schedule regardless of pipe depth — millions of real users do not
slow down because a pool is flipping. Every request is stamped at its
SCHEDULED arrival time and never restamped, so reported latency includes
all queue wait (no coordinated omission), and carries a ``deadline_s``
budget: servers shed at intake when the deadline budget is provably
spent (admission control, serve/server.py), the driver sheds requests
that die of old age in its own queue, and a completion past the deadline
counts as a deadline miss. Goodput = completed WITHIN deadline.

Both modes:

- route around draining/bounced nodes (their requests come back via
  checkpoint-and-requeue and are re-dispatched with progress intact);
- adapt each node's batch size from its reported ``hbm_bw_util``:
  below ``util_ceiling`` there is headroom → step the batch up ONE rung;
  above it step down. One rung at a time, and a ceiling strictly below
  1.0, because the utilization read is a useful-traffic LOWER bound
  (smoke/llama_infer.py — the padded+masked KV stream makes the
  marginal-cost model worst-case): the ladder's headroom read is
  deliberately conservative, never optimistic.

The report splits completions into steady-state vs a caller-marked
rollout window (membership by OVERLAP of the in-system interval with the
window — shed and deadline-miss counts use the same rule, so the
during-rollout shed rate is not polluted by steady-state arrivals) and
carries the headline the harness commits:
``requests_lost_per_node_bounced`` (target: zero — a request is lost
only if it never completed after traffic stopped and the grace drain
expired; a SHED request is an explicit, counted refusal, never lost).
Conservation holds by construction: issued = completed + shed + lost.
"""

from __future__ import annotations

import logging
import random
import threading
import time

from tpu_cc_manager.obs import slo as slo_mod
from tpu_cc_manager.obs.slo import percentile as _percentile
from tpu_cc_manager.serve.server import NodeServer, Request
from tpu_cc_manager.utils import locks as locks_mod
from tpu_cc_manager.utils import metrics as metrics_mod
from tpu_cc_manager.utils import retry as retry_mod

log = logging.getLogger(__name__)

#: Pseudo-node label for requests shed by the DRIVER's own queue (their
#: deadline expired before any server had pipe room); server-side sheds
#: carry the real node name.
DRIVER_SHED_NODE = "driver"


class PoissonSchedule:
    """Open-loop Poisson arrivals at a constant ``rate_rps``. Seeded:
    the same seed yields the same arrival schedule, independent of how
    fast the pool absorbs it (the whole point of open loop)."""

    def __init__(self, rate_rps: float, seed: int = 0) -> None:
        if rate_rps <= 0:
            raise ValueError("rate_rps must be > 0")
        self.rate_rps = float(rate_rps)
        self._rng = random.Random(seed)

    def rate_at(self, t_s: float) -> float:
        return self.rate_rps

    def next_interarrival_s(self, t_s: float) -> float:
        return self._rng.expovariate(self.rate_rps)


class RampSchedule:
    """Open-loop arrivals ramping linearly from ``rate0_rps`` to
    ``rate1_rps`` over ``duration_s`` (holding ``rate1_rps`` after) — a
    time-varying Poisson process, seeded like :class:`PoissonSchedule`.
    The shape that walks a pool INTO overload instead of teleporting it
    there."""

    def __init__(
        self, rate0_rps: float, rate1_rps: float, duration_s: float,
        seed: int = 0,
    ) -> None:
        if rate0_rps <= 0 or rate1_rps <= 0:
            raise ValueError("rates must be > 0")
        self.rate0_rps = float(rate0_rps)
        self.rate1_rps = float(rate1_rps)
        self.duration_s = max(0.0, float(duration_s))
        self._rng = random.Random(seed)

    def rate_at(self, t_s: float) -> float:
        if self.duration_s <= 0 or t_s >= self.duration_s:
            return self.rate1_rps
        frac = max(0.0, t_s) / self.duration_s
        return self.rate0_rps + (self.rate1_rps - self.rate0_rps) * frac

    def next_interarrival_s(self, t_s: float) -> float:
        return self._rng.expovariate(self.rate_at(t_s))


class TrafficDriver:
    def __init__(
        self,
        servers: dict[str, NodeServer],
        request_tokens: int = 8,
        initial_batch: int = 2,
        min_batch: int = 1,
        max_batch: int = 16,
        util_ceiling: float = 0.9,
        ladder_interval_s: float = 0.25,
        submit_interval_s: float = 0.01,
        pipe_depth: int = 2,
        metrics: metrics_mod.MetricsRegistry | None = None,
        slo: slo_mod.SloEvaluator | None = None,
        schedule=None,
        deadline_s: float | None = None,
        clock=time.monotonic,
    ) -> None:
        self.servers = servers
        # Open-loop mode: a rate-driven arrival process (PoissonSchedule
        # / RampSchedule) decides when requests enter the system; the
        # pool's absorption rate decides nothing. ``deadline_s`` is each
        # request's completion budget (admission control + deadline-miss
        # accounting hang off it); ``clock`` is injectable for
        # deterministic tests, but the servers stamp on the same clock —
        # a non-default clock must be passed to every NodeServer too
        # (server.py ``clock=``), or admission/latency math would mix
        # time domains.
        self.schedule = schedule
        self.deadline_s = deadline_s
        self.clock = clock
        # Live serving telemetry: completions feed the per-node latency
        # histogram + outcome counters (tpu_cc_serve_*) and the SLO
        # evaluator; the ladder tick exports the windowed p99 /
        # burn-rate / goodput gauges, so a scrape DURING a flip reads
        # the live SLO — the contract the latency-gated rollout polls.
        self.metrics = metrics
        self.slo = slo
        self.request_tokens = request_tokens
        self.min_batch = min_batch
        self.max_batch = max_batch
        self.util_ceiling = util_ceiling
        self.ladder_interval_s = ladder_interval_s
        self.submit_interval_s = submit_interval_s
        self.pipe_depth = pipe_depth
        self._lock = locks_mod.make_lock("serve.driver")
        self._pending: list[Request] = []  # cclint: guarded-by(_lock)
        self._completed: list[Request] = []  # cclint: guarded-by(_lock)
        self._outstanding: dict[str, int] = {  # cclint: guarded-by(_lock)
            name: 0 for name in servers
        }
        self._batch: dict[str, int] = {  # cclint: guarded-by(_lock)
            name: initial_batch for name in servers
        }
        self._next_id = 0  # cclint: guarded-by(_lock)
        self._requeues = 0  # cclint: guarded-by(_lock)
        self._handoffs_accepted = 0  # cclint: guarded-by(_lock)
        self._handoffs_fallback = 0  # cclint: guarded-by(_lock)
        self._shed: list[Request] = []  # cclint: guarded-by(_lock)
        self._offered = 0  # cclint: guarded-by(_lock)
        self._offered_at_tick = 0  # cclint: guarded-by(_lock)
        self._offered_tick_t: float | None = None  # cclint: guarded-by(_lock)
        self._next_arrival_t: float | None = None  # cclint: guarded-by(_lock)
        self._open_loop_t0: float | None = None  # cclint: guarded-by(_lock)
        self._traffic_stopped_t: float | None = None  # cclint: guarded-by(_lock)
        # Fail-slow de-weighting (obs/failslow.py): nodes under
        # peer-relative suspicion are capped at min_batch IN FLIGHT —
        # their trickle is bounded by their own service rate, not a
        # share of the offered load — which holds the tail while the
        # verdict is still out yet keeps vetting fed so recovery stays
        # observable. Ignored when EVERY accepting node is suspect:
        # de-weighting the whole pool would just shed it.
        self._suspects: frozenset[str] = frozenset()  # cclint: guarded-by(_lock)
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None

    def set_suspects(self, names) -> None:
        """Replace the fail-slow suspect set the dispatcher de-weights
        (the vetting loop publishes :meth:`FailslowVetter.suspects`
        here each window)."""
        with self._lock:
            self._suspects = frozenset(names)

    # -- server callbacks --------------------------------------------------

    def on_complete(self, node: str, req: Request, util: float) -> None:
        # A completion past the request's deadline is a counted miss: the
        # request was ACCEPTED (admission control judged it feasible) and
        # the pool still blew its budget — the SLO violation the gate and
        # the error budget exist to see. Sheds are the separate,
        # deliberate refusal; misses are the broken promise.
        missed = (
            req.deadline_at is not None
            and req.completed_at is not None
            and req.completed_at > req.deadline_at
        )
        with self._lock:
            self._completed.append(req)
            self._outstanding[node] = max(0, self._outstanding[node] - 1)
        if req.completed_at is not None:
            lat = max(0.0, req.completed_at - req.submitted_at)
            if self.metrics is not None:
                self.metrics.observe_serve_request(node, lat)
                self.metrics.record_serve_outcome(node, "completed")
                if missed:
                    self.metrics.record_serve_deadline_miss(node)
            if self.slo is not None:
                self.slo.observe(lat, ok=not missed)

    def on_shed(self, node: str, reqs: list[Request]) -> None:
        """Requests refused at a server's intake (deadline budget
        provably spent): out of the system, counted ``outcome=shed`` —
        never lost, never an accepted-request SLO error. The error
        budget governs the promise made to ADMITTED requests; shedding
        is the mechanism that keeps that promise keepable past the
        knee."""
        now = self.clock()
        with self._lock:
            for r in reqs:
                r.shed_at = now
            self._shed.extend(reqs)
            if node in self._outstanding:
                self._outstanding[node] = max(
                    0, self._outstanding[node] - len(reqs)
                )
        if self.metrics is not None:
            self.metrics.record_serve_outcome(node, "shed", len(reqs))

    def on_handoff(self, node: str, reqs: list[Request]) -> tuple[int, int]:
        """Serving-state handoff sink (SERVE_r03): a draining server's
        parked in-flight + queued requests, re-dispatched DIRECTLY to
        accepting peers instead of requeueing into the driver's queue —
        called synchronously from the drain bracket, so the migration
        lands inside the ack window. Requests are chunked by each
        peer's current batch-ladder rung and offered round-robin;
        whatever finds no accepting peer (every peer draining, or a
        submit losing its own drain race) falls back to the plain
        :meth:`on_requeue` — today's behavior, so conservation
        (issued = completed + shed + lost) holds by construction.
        Returns ``(migrated, fallback)`` counts: a request the peer's
        admission control SHED at intake is neither — it left the
        system as a counted shed, not a migration (counting it
        accepted would inflate the zero-bounce evidence).

        A migrated request keeps its original ``submitted_at`` (latency
        stays stamped at arrival), carries its ``tokens_done`` progress,
        and pays the state-transfer restore at the receiving executor
        (``restore_pending`` → ``resume_from_progress``)."""
        queue = list(reqs)
        accepted_total = 0
        fallback: list[Request] = []
        # Snapshot targets + rungs under the lock; submit OUTSIDE it —
        # a peer's intake may synchronously shed into on_shed, which
        # takes this same (non-reentrant) lock.
        with self._lock:
            rungs = dict(self._batch)
        peers = [
            (name, server) for name, server in self.servers.items()
            if name != node and server.accepting()
        ]
        while queue and peers:
            still_accepting = []
            for pname, server in peers:
                if not queue:
                    break
                chunk = queue[: max(1, rungs.get(pname, 1))]
                for r in chunk:
                    # Progress-carrying requests owe a restore at the
                    # new executor; fresh (queued, zero-progress) ones
                    # have no state to transfer.
                    r.handoffs += 1
                    r.restore_pending = r.tokens_done > 0
                with self._lock:
                    self._outstanding[node] = max(
                        0, self._outstanding[node] - len(chunk)
                    )
                    self._outstanding[pname] = (
                        self._outstanding.get(pname, 0) + len(chunk)
                    )
                # front=True: migrated requests are the oldest in-flight
                # work in the system; they resume ahead of the peer's
                # queued fresh traffic (its executing batch still
                # finishes first).
                if server.submit(chunk, front=True):
                    del queue[: len(chunk)]
                    # The peer's intake may have SHED part of the chunk
                    # (on_shed stamps shed_at synchronously inside
                    # submit): those left the system as counted sheds,
                    # not migrations — excluded from the accepted count
                    # and their handoff marks reverted.
                    for r in chunk:
                        if r.shed_at is not None:
                            r.handoffs -= 1
                            r.restore_pending = False
                        else:
                            accepted_total += 1
                    still_accepting.append((pname, server))
                else:
                    # Lost the race with the peer's own drain: undo the
                    # outstanding transfer and stop offering to it.
                    with self._lock:
                        self._outstanding[pname] = max(
                            0, self._outstanding[pname] - len(chunk)
                        )
                        self._outstanding[node] += len(chunk)
                    for r in chunk:
                        r.handoffs -= 1
                        r.restore_pending = False
            peers = still_accepting
        fallback = queue
        with self._lock:
            self._handoffs_accepted += accepted_total
            self._handoffs_fallback += len(fallback)
        if self.metrics is not None:
            if accepted_total:
                self.metrics.record_serve_handoff("accepted", accepted_total)
            if fallback:
                self.metrics.record_serve_handoff("fallback", len(fallback))
        if fallback:
            for r in fallback:
                # The durable checkpoint the draining node charges
                # covers exactly these — they survive in the driver's
                # queue on the written copy alone.
                r.checkpoints += 1
            self.on_requeue(node, fallback)
        return accepted_total, len(fallback)

    def on_requeue(self, node: str, reqs: list[Request]) -> None:
        """Checkpointed requests coming back from a draining server:
        front of the queue (oldest first) so the bounce delay they
        already paid is not compounded by re-queueing behind fresh
        traffic."""
        with self._lock:
            self._requeues += len(reqs)
            self._outstanding[node] = max(
                0, self._outstanding[node] - len(reqs)
            )
            self._pending[:0] = reqs
        if self.metrics is not None:
            self.metrics.record_serve_outcome(node, "bounced", len(reqs))

    # -- driving loop ------------------------------------------------------

    def start(self) -> None:
        self._thread = threading.Thread(
            target=self._run, daemon=True, name="serve-driver"
        )
        self._thread.start()

    def stop(self, timeout_s: float = 10.0) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=timeout_s)

    def _run(self) -> None:
        open_loop = self.schedule is not None
        if open_loop:
            t0 = self.clock()
            with self._lock:
                self._open_loop_t0 = t0
                self._next_arrival_t = t0 + self.schedule.next_interarrival_s(0.0)
        last_ladder = self.clock()
        while not self._stop.is_set():
            now = self.clock()
            if now - last_ladder >= self.ladder_interval_s:
                self._ladder_step()
                last_ladder = now
            if open_loop:
                self._mint_arrivals(now)
            # Open loop never mints from the dispatch path: arrivals are
            # the schedule's decision alone, regardless of pipe depth.
            self._dispatch_round(top_up=not open_loop)
            retry_mod.wait(self.submit_interval_s, self._stop)
        with self._lock:
            self._traffic_stopped_t = self.clock()

    def _mint_arrivals(self, now: float) -> None:
        """Submit every arrival the schedule placed at or before ``now``
        into the pending queue — stamped at its SCHEDULED arrival time
        (not the dispatch loop's wake-up), so a laggy driver thread
        cannot hide queue wait from the latency it reports (the
        coordinated-omission fix, applied to the driver itself too)."""
        with self._lock:
            t0 = self._open_loop_t0 if self._open_loop_t0 is not None else now
            while (
                self._next_arrival_t is not None
                and self._next_arrival_t <= now
            ):
                t = self._next_arrival_t
                self._next_id += 1
                self._offered += 1
                self._pending.append(Request(
                    req_id=self._next_id,
                    decode_tokens=self.request_tokens,
                    submitted_at=t,
                    deadline_at=(
                        t + self.deadline_s
                        if self.deadline_s is not None else None
                    ),
                ))
                self._next_arrival_t = t + max(
                    1e-6, self.schedule.next_interarrival_s(t - t0)
                )

    def _shed_expired_pending(self, now: float) -> None:
        """Driver-side load shedding: a request whose deadline expired
        while it waited in the DRIVER's queue (every server's intake was
        full or draining) is shed here — its budget is spent, submitting
        it would only be refused at intake one hop later. Keeps the
        open-loop pending queue bounded by the deadline instead of
        growing without limit past the knee."""
        with self._lock:
            # The pending queue is NEAR-deadline-ordered: arrivals append
            # in schedule order (deadline = arrival + constant) and
            # requeues go to the front carrying older arrivals, so
            # expired requests form a prefix in the common case and the
            # scan stops at the first live one instead of walking the
            # whole overload backlog every dispatch round. Interleaved
            # requeue groups from CONCURRENT node drains can hide an
            # expired request behind a younger live one — such a
            # straggler is still shed, one hop later at server intake
            # (attributed to that node instead of "driver"); conservation
            # is unaffected either way.
            n = 0
            for r in self._pending:
                if r.deadline_at is not None and r.deadline_at <= now:
                    n += 1
                else:
                    break
            if not n:
                return
            expired = self._pending[:n]
            del self._pending[:n]
        self.on_shed(DRIVER_SHED_NODE, expired)

    def _dispatch_round(self, top_up: bool) -> None:
        """Fill each accepting server's pipe to ``pipe_depth`` batches.
        ``top_up`` mints fresh requests when the pending queue runs dry
        (closed-loop traffic); open-loop dispatch (and the drain pass
        after stop()) leaves it off so only scheduled/in-system requests
        flow."""
        if self.deadline_s is not None:
            self._shed_expired_pending(self.clock())
        with self._lock:
            suspects = self._suspects
        accepting = [n for n, s in self.servers.items() if s.accepting()]
        if suspects and accepting and all(n in suspects for n in accepting):
            suspects = frozenset()
        # Suspects draw their (one-in-flight) trickle first: with fleet
        # headroom the healthy nodes would otherwise drain the pending
        # queue every round and starve the suspect of the very samples
        # vetting needs to clear it. The CAP is the de-weight — a
        # suspect can never hold more than min_batch requests — so
        # going first costs the tail at most min_batch slow slots.
        ordered = sorted(
            self.servers.items(), key=lambda kv: kv[0] not in suspects
        )
        for name, server in ordered:
            if not server.accepting():
                continue
            with self._lock:
                if name in suspects:
                    bsz = self.min_batch
                    cap = self.min_batch
                else:
                    bsz = self._batch[name]
                    cap = self.pipe_depth * bsz
                if self._outstanding[name] >= cap:
                    continue
                if top_up:
                    now = self.clock()
                    while len(self._pending) < bsz:
                        self._next_id += 1
                        self._pending.append(Request(
                            req_id=self._next_id,
                            decode_tokens=self.request_tokens,
                            submitted_at=now,
                        ))
                batch = self._pending[:bsz]
                if not batch:
                    continue
                del self._pending[:len(batch)]
                self._outstanding[name] += len(batch)
            if not server.submit(batch):
                # Lost the race with a drain: keep the requests, let the
                # next round route them to an accepting server.
                with self._lock:
                    self._outstanding[name] = max(
                        0, self._outstanding[name] - len(batch)
                    )
                    self._pending[:0] = batch
                if self.metrics is not None:
                    self.metrics.record_serve_outcome(
                        name, "requeued", len(batch)
                    )

    def _export_slo(self) -> None:
        """Export the live windowed SLO readout + goodput gauges —
        piggybacked on the ladder tick so the gauges stay fresh at the
        ladder's cadence without a dedicated timer thread."""
        if self.slo is None:
            return
        snap = self.slo.snapshot()
        for w in snap["windows"]:
            if self.metrics is not None:
                self.metrics.set_serve_slo(
                    w["window_s"], w["p99_s"], w["burn_rate"]
                )
        if self.metrics is not None and snap["windows"]:
            self.metrics.set_serve_goodput(
                snap["windows"][0]["goodput_rps"]
            )

    def _export_offered(self) -> None:
        """Open-loop only: export the offered (scheduled) arrival rate
        since the last export — the load the pool was ASKED to absorb,
        which goodput is judged against. Divided by the MEASURED elapsed
        time, not the nominal ladder interval: under overload the
        dispatch loop runs late, and nominal division would overstate
        the very number operators compare goodput against."""
        if self.metrics is None or self.schedule is None:
            return
        now = self.clock()
        with self._lock:
            delta = self._offered - self._offered_at_tick
            self._offered_at_tick = self._offered
            last_t = self._offered_tick_t
            self._offered_tick_t = now
        if last_t is None:
            return  # first tick: no window to rate over yet
        elapsed = now - last_t
        if elapsed > 0:
            self.metrics.set_serve_offered_rps(delta / elapsed)

    def _ladder_step(self) -> None:
        """One conservative rung per interval, per node, off the last
        reported ``hbm_bw_util``: the read is a lower bound, so the
        ceiling sits below 1.0 and the ladder never jumps rungs."""
        self._export_slo()
        self._export_offered()
        for name, server in self.servers.items():
            util = server.last_hbm_bw_util
            if util is None:
                continue
            if self.metrics is not None:
                # Export the ladder's own signal: the fleet capacity
                # ledger (obs/fleet.py) judges per-node headroom off it.
                self.metrics.set_serve_hbm_bw_util(name, util)
            with self._lock:
                if util < self.util_ceiling and self._batch[name] < self.max_batch:
                    self._batch[name] += 1
                elif util > self.util_ceiling and self._batch[name] > self.min_batch:
                    self._batch[name] -= 1

    def drain_outstanding(self, grace_s: float = 10.0) -> None:
        """After stop(): keep dispatching ONLY in-system requests until
        everything completed or the grace expires (whatever remains is
        counted lost — the harness's zero-loss claim hinges here)."""

        def settled() -> bool:
            self._dispatch_round(top_up=False)
            with self._lock:
                return (
                    not self._pending
                    and all(v == 0 for v in self._outstanding.values())
                )

        retry_mod.poll_until(settled, grace_s, 0.02)
        with self._lock:
            lost = len(self._pending) + sum(self._outstanding.values())
        if lost:
            # Each lost request is a counted SLO error AND a counter
            # bump — the zero-loss contract's violation is visible both
            # in the burn-rate gauge and in tpu_cc_serve_lost_total.
            if self.metrics is not None:
                self.metrics.record_serve_lost(lost)
            if self.slo is not None:
                for _ in range(lost):
                    self.slo.observe_error()
        self._export_slo()

    # -- reporting ---------------------------------------------------------

    def snapshot_batches(self) -> dict[str, int]:
        with self._lock:
            return dict(self._batch)

    def report(
        self,
        rollout_window: tuple[float, float] | None = None,
        nodes_bounced: int = 0,
    ) -> dict:
        """Latency/loss summary. ``rollout_window`` is (start, end) on
        the driver's monotonic clock; the during-rollout bucket is every
        request whose in-system interval [submitted_at, completed_at]
        OVERLAPS the window — exactly the requests a user had in flight
        while the pool flipped. (Bucketing by completion time alone
        would park a request bounced by the LAST node's drain — which
        completes just after the rollout returns — in the steady bucket,
        inflating steady p99 and understating the disruption the
        artifact headlines.)"""
        with self._lock:
            completed = list(self._completed)
            shed = list(self._shed)
            in_system = len(self._pending) + sum(
                self._outstanding.values()
            )
            requeues = self._requeues
            handoffs_accepted = self._handoffs_accepted
            handoffs_fallback = self._handoffs_fallback
            issued = self._next_id
            open_loop_t0 = self._open_loop_t0
            traffic_stopped_t = self._traffic_stopped_t

        def in_window(start: float, end: float) -> bool:
            """Membership-by-overlap of an in-system interval with the
            rollout window — the shared rule for latency, shed AND
            deadline-miss bucketing (a request shed while the pool
            flipped belongs to the disruption it headlines, wherever
            its arrival landed)."""
            return bool(rollout_window) and (
                end >= rollout_window[0] and start <= rollout_window[1]
            )

        lat_all, lat_roll, lat_steady = [], [], []
        qd_all: list[float] = []
        misses = miss_roll = miss_steady = 0
        within_deadline = 0
        for r in completed:
            if r.completed_at is None:
                continue
            lat = r.completed_at - r.submitted_at
            lat_all.append(lat)
            rolled = in_window(r.submitted_at, r.completed_at)
            (lat_roll if rolled else lat_steady).append(lat)
            if r.started_at is not None:
                qd_all.append(max(0.0, r.started_at - r.submitted_at))
            if r.deadline_at is not None:
                if r.completed_at > r.deadline_at:
                    misses += 1
                    if rolled:
                        miss_roll += 1
                    else:
                        miss_steady += 1
                else:
                    within_deadline += 1
            else:
                within_deadline += 1
        shed_roll = sum(
            1 for r in shed
            if r.shed_at is not None and in_window(r.submitted_at, r.shed_at)
        )
        lat_all.sort(); lat_roll.sort(); lat_steady.sort(); qd_all.sort()
        lost = in_system  # after drain_outstanding: nothing should remain

        def stats(vals: list[float]) -> dict:
            return {
                "count": len(vals),
                "p50_ms": round(1e3 * _percentile(vals, 0.50), 2) if vals else None,
                "p99_ms": round(1e3 * _percentile(vals, 0.99), 2) if vals else None,
                "max_ms": round(1e3 * vals[-1], 2) if vals else None,
            }

        denom = len(completed) + lost
        # Offered rate: the schedule's arrivals over the open-loop
        # traffic window (None for closed-loop runs, where "offered" is
        # whatever the pool absorbed — the number means nothing).
        offered_rps = None
        if open_loop_t0 is not None:
            t1 = traffic_stopped_t if traffic_stopped_t is not None else self.clock()
            span = max(1e-9, t1 - open_loop_t0)
            offered_rps = round(issued / span, 3)
        goodput_rps = (
            round(within_deadline / max(
                1e-9,
                (traffic_stopped_t if traffic_stopped_t is not None
                 else self.clock()) - open_loop_t0,
            ), 3)
            if open_loop_t0 is not None else None
        )
        return {
            "requests_issued": issued,
            "requests_completed": len(completed),
            "requests_lost": lost,
            "requests_requeued": requeues,
            # Serving-state handoff: parked requests a draining node
            # migrated straight to an accepting peer (accepted) vs ones
            # that found no peer and fell back to the local requeue
            # (fallback, a subset of requests_requeued).
            "handoffs": {
                "accepted": handoffs_accepted,
                "fallback": handoffs_fallback,
            },
            "requests_shed": len(shed),
            "shed_rate": round(len(shed) / issued, 6) if issued else 0.0,
            "deadline_misses": misses,
            "completed_within_deadline": within_deadline,
            # issued = completed + shed + lost, by construction; exported
            # so every artifact (and the property tests) can assert it.
            "conserved": issued == len(completed) + len(shed) + lost,
            "offered_rps": offered_rps,
            "goodput_rps": goodput_rps,
            "error_rate": round(lost / denom, 6) if denom else 0.0,
            "nodes_bounced": nodes_bounced,
            "requests_lost_per_node_bounced": (
                round(lost / nodes_bounced, 6) if nodes_bounced else lost
            ),
            "latency": stats(lat_all),
            "latency_during_rollout": stats(lat_roll),
            "latency_steady_state": stats(lat_steady),
            "queue_delay": stats(qd_all),
            "shed_during_rollout": shed_roll,
            "shed_steady_state": len(shed) - shed_roll,
            "deadline_miss_during_rollout": miss_roll,
            "deadline_miss_steady_state": miss_steady,
            "batch_ladder": self.snapshot_batches(),
            "slo": self.slo.snapshot() if self.slo is not None else None,
        }
