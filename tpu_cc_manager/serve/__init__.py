"""Continuous synthetic serving: measure what a *user* sees while a pool
flips CC mode under load (ROADMAP item 3).

Every bench before this package measured node-seconds; nothing measured
user-visible disruption. The pieces here close that gap:

- :class:`~tpu_cc_manager.serve.server.NodeServer` — a per-node batched
  inference server that subscribes to the drain handshake
  (``drain/handshake.py``): when its node's manager requests a drain the
  server checkpoints in-flight requests (sized to the
  ``drain.deadline-s`` hint when one is published) and hands them back
  to the driver instead of dying with them.
- :class:`~tpu_cc_manager.serve.driver.TrafficDriver` — sustains batched
  requests across the pool, routes around draining nodes, and adapts
  its per-node batch ladder from the reported ``hbm_bw_util`` headroom
  (a conservative, lower-bound read — see ``smoke/llama_infer.py``).
- :class:`~tpu_cc_manager.serve.harness.ServeHarness` — wires a fake
  pool of REAL node agents (CCManager watch loops), the servers and the
  driver together, runs a real rolling CC flip mid-traffic, and reports
  p50/p99 latency + error rate during the rollout vs steady state, plus
  requests lost per node bounced (target: zero).

The layer is live-observable, not just report-observable: servers and
driver export the ``tpu_cc_serve_*`` metric families through one shared
``utils/metrics.py`` registry (latency histogram, queue depth,
in-flight, outcome/loss counters, goodput) and feed an
``obs/slo.py`` :class:`~tpu_cc_manager.obs.slo.SloEvaluator` whose
windowed p99 / error-budget burn readout is both exported as gauges and
pollable in-process — the contract a latency-gated rollout reads at
wave boundaries (ROADMAP item 1).
"""

from tpu_cc_manager.serve.driver import TrafficDriver
from tpu_cc_manager.serve.harness import ServeHarness
from tpu_cc_manager.serve.server import NodeServer, Request, SimulatedExecutor

__all__ = [
    "NodeServer",
    "Request",
    "ServeHarness",
    "SimulatedExecutor",
    "TrafficDriver",
]
