"""Continuous synthetic serving: measure what a *user* sees while a pool
flips CC mode under load (ROADMAP item 3).

Every bench before this package measured node-seconds; nothing measured
user-visible disruption. The pieces here close that gap:

- :class:`~tpu_cc_manager.serve.server.NodeServer` — a per-node batched
  inference server that subscribes to the drain handshake
  (``drain/handshake.py``): when its node's manager requests a drain the
  server checkpoints in-flight requests (sized to the
  ``drain.deadline-s`` hint when one is published) and hands them back
  to the driver instead of dying with them.
- :class:`~tpu_cc_manager.serve.driver.TrafficDriver` — sustains batched
  requests across the pool, routes around draining nodes, and adapts
  its per-node batch ladder from the reported ``hbm_bw_util`` headroom
  (a conservative, lower-bound read — see ``smoke/llama_infer.py``).
- :class:`~tpu_cc_manager.serve.harness.ServeHarness` — wires a fake
  pool of REAL node agents (CCManager watch loops), the servers and the
  driver together, runs a real rolling CC flip mid-traffic, and reports
  p50/p99 latency + error rate during the rollout vs steady state, plus
  requests lost per node bounced (target: zero).

The driver has two traffic modes: the closed-loop ladder above
(SERVE_r01) and an **open-loop** rate-driven mode
(:class:`~tpu_cc_manager.serve.driver.PoissonSchedule` /
:class:`~tpu_cc_manager.serve.driver.RampSchedule`, SERVE_r02) that
submits on schedule regardless of pipe depth, attaches per-request
deadlines, and lets the server's admission control shed at intake —
the overload-honest half: goodput = completed-within-deadline, and
``serve/sweep.py`` finds the knee of a rate sweep.

The layer is live-observable, not just report-observable: servers and
driver export the ``tpu_cc_serve_*`` metric families through one shared
``utils/metrics.py`` registry (latency histogram, queue depth,
in-flight, outcome/shed/loss counters, offered rate, goodput) and feed
an ``obs/slo.py`` :class:`~tpu_cc_manager.obs.slo.SloEvaluator` whose
windowed p99 / error-budget burn readout is both exported as gauges and
pollable in-process — the contract the latency-gated rollout
(``ccmanager/rolling.py`` ``slo_gate``) polls at wave boundaries.
"""

from tpu_cc_manager.serve.driver import (
    PoissonSchedule,
    RampSchedule,
    TrafficDriver,
)
from tpu_cc_manager.serve.harness import ServeHarness
from tpu_cc_manager.serve.server import NodeServer, Request, SimulatedExecutor

__all__ = [
    "NodeServer",
    "PoissonSchedule",
    "RampSchedule",
    "Request",
    "ServeHarness",
    "SimulatedExecutor",
    "TrafficDriver",
]
