"""Open-loop rate sweep: find the knee, prove shedding holds goodput.

A closed-loop driver can never see queueing collapse (it backs off when
the pool slows — coordinated omission by construction). This module
drives the serving stack the way millions of users do: a seeded Poisson
arrival process at a FIXED offered rate, per-request deadlines,
admission control shedding at intake — and measures, per rate:

- **offered vs goodput**: goodput = completed WITHIN deadline. Below
  the knee goodput tracks offered load; past it, shedding holds goodput
  near the knee instead of letting queueing collapse take it to zero.
- **shed rate** and **deadline misses** (the deliberate refusal vs the
  broken promise — conserved against issued, never "lost").
- **queue-delay percentiles**: time from scheduled arrival to first
  executor dispatch — the number that explodes past the knee.

:func:`run_rate_point` is deliberately lightweight (a bare FakeKube,
NodeServers and an open-loop TrafficDriver — no agents, no rollout) so
a sweep of N rates costs N × traffic_s. The full rolling-flip-at-the-
knee measurement composes it with :class:`ServeHarness`
(hack/serve_bench.py --sweep → SERVE_r02.json).

:func:`find_knee` is a pure function of the sweep rows, property-tested
in tests/test_serve.py: the knee is the LAST rate where goodput tracks
offered load and queue-delay p99 stays bounded.
"""

from __future__ import annotations

import logging

from tpu_cc_manager.kubeclient.fake import FakeKube
from tpu_cc_manager.serve.driver import PoissonSchedule, TrafficDriver
from tpu_cc_manager.serve.server import NodeServer, SimulatedExecutor
from tpu_cc_manager.utils import retry as retry_mod

log = logging.getLogger(__name__)

#: Default knee criteria: goodput must stay within this fraction of the
#: offered load ("tracks"), and queue-delay p99 must stay under the
#: request deadline ("bounded" — a p99 past the deadline means the
#: typical tail request was already dead on dispatch). 0.95 sits above
#: Poisson measurement noise at sweep sample sizes but below the first
#: real divergence: a rate completing only 90% of its offered load is
#: already past the knee, not at it.
DEFAULT_TRACK_FRAC = 0.95

#: SERVE_r02's headline bar: past the knee, shedding must hold goodput
#: within this fraction OF THE KNEE'S goodput (collapse would take it
#: toward zero).
DEFAULT_HOLD_FRAC = 0.80


def run_rate_point(
    rate_rps: float,
    n_nodes: int = 3,
    traffic_s: float = 2.5,
    deadline_s: float = 0.5,
    request_tokens: int = 8,
    batch: int = 8,
    seed: int = 0,
    executor_factory=None,
    drain_grace_s: float = 10.0,
) -> dict:
    """One open-loop measurement at a fixed offered rate: a bare pool
    (no agents, no flip), seeded Poisson arrivals, admission control on.
    The batch ladder is pinned (min=max=``batch``) so every rate is
    measured against the same per-node capacity — a sweep compares
    rates, not ladder trajectories. Returns one JSON-able row."""
    factory = executor_factory if executor_factory is not None else SimulatedExecutor
    kube = FakeKube()
    servers: dict[str, NodeServer] = {}
    for i in range(n_nodes):
        name = f"sweep-node-{i}"
        kube.add_node(name)
        servers[name] = NodeServer(
            kube, name,
            on_complete=lambda n, r, u: driver.on_complete(n, r, u),
            on_requeue=lambda n, rs: driver.on_requeue(n, rs),
            on_shed=lambda n, rs: driver.on_shed(n, rs),
            executor=factory(),
            poll_interval_s=5.0,  # no drain in a rate point; quiet poller
        )
    driver = TrafficDriver(
        servers,
        request_tokens=request_tokens,
        initial_batch=batch, min_batch=batch, max_batch=batch,
        schedule=PoissonSchedule(rate_rps, seed=seed),
        deadline_s=deadline_s,
        submit_interval_s=0.002,
    )
    for server in servers.values():
        server.start()
    driver.start()
    try:
        retry_mod.wait(traffic_s, None)
    finally:
        driver.stop()
    driver.drain_outstanding(grace_s=drain_grace_s)
    report = driver.report()
    for server in servers.values():
        server.stop()
    qd = report["queue_delay"]
    return {
        "rate_rps": rate_rps,
        "traffic_s": traffic_s,
        "deadline_ms": round(1e3 * deadline_s, 1),
        "nodes": n_nodes,
        "batch": batch,
        "seed": seed,
        "offered_rps": report["offered_rps"],
        "goodput_rps": report["goodput_rps"],
        "issued": report["requests_issued"],
        "completed": report["requests_completed"],
        "completed_within_deadline": report["completed_within_deadline"],
        "shed": report["requests_shed"],
        "shed_rate": report["shed_rate"],
        "deadline_misses": report["deadline_misses"],
        "lost": report["requests_lost"],
        "conserved": report["conserved"],
        "queue_delay_p50_ms": qd["p50_ms"],
        "queue_delay_p99_ms": qd["p99_ms"],
        "latency_p99_ms": report["latency"]["p99_ms"],
        # A rate point is healthy when nothing leaked: every issued
        # request either completed or was explicitly shed.
        "ok": bool(report["conserved"] and report["requests_lost"] == 0),
    }


def find_knee(
    rows: list[dict],
    track_frac: float = DEFAULT_TRACK_FRAC,
    queue_p99_bound_ms: float | None = None,
) -> dict | None:
    """The knee of a sweep: the LAST (highest-rate) row where goodput
    still tracks the offered load (``goodput >= track_frac * offered``)
    and queue-delay p99 stays bounded (default bound: the row's own
    deadline — a tail request queued past its deadline was dead on
    dispatch). Pure function of the rows; None when no row qualifies
    (every measured rate was already past the knee)."""
    knee = None
    for row in sorted(rows, key=lambda r: r["rate_rps"]):
        offered = row.get("offered_rps") or 0.0
        goodput = row.get("goodput_rps") or 0.0
        if offered <= 0:
            continue
        bound = queue_p99_bound_ms
        if bound is None:
            bound = row.get("deadline_ms")
        p99 = row.get("queue_delay_p99_ms")
        bounded = bound is None or p99 is None or p99 <= bound
        if goodput >= track_frac * offered and bounded:
            knee = row
    return knee


def knee_slack_nodes(
    knee_rps: float, offered_rps: float, n_nodes: int
) -> int:
    """How many whole nodes of capacity the offered load leaves free
    under the knee. The knee is the fleet's proven serving capacity, so
    one node is worth ``knee_rps / n_nodes`` of it; the slack is the
    unused capacity expressed in those units, floored (a fractional
    node cannot absorb a whole node's traffic during its prestage).
    Pure and fail-closed: nonsensical inputs (no nodes, no knee,
    offered at/above knee) yield 0."""
    if n_nodes <= 0 or knee_rps <= 0:
        return 0
    per_node = knee_rps / n_nodes
    slack = (knee_rps - max(0.0, offered_rps)) / per_node
    return max(0, int(slack))


def prestage_allowance(
    knee_rps: float,
    offered_rps: float,
    n_nodes: int,
    reserve_nodes: int = 1,
) -> int:
    """The capacity ledger's concurrency budget: how many nodes may be
    in prestage transition at once. The ISSUE-19 rule is "prestage only
    while offered load leaves >= 1 node of slack" — so the allowance is
    the knee slack MINUS a reserved node kept free for the wave itself
    (the draining window's traffic has to land somewhere). At 80 % of
    knee on 10 nodes: slack 2, allowance 1."""
    slack = knee_slack_nodes(knee_rps, offered_rps, n_nodes)
    return max(0, slack - max(0, int(reserve_nodes)))


def goodput_holds_past_knee(
    rows: list[dict], knee: dict, hold_frac: float = DEFAULT_HOLD_FRAC
) -> bool:
    """SERVE_r02's overload claim: at every measured rate PAST the knee,
    shedding held goodput within ``1 - hold_frac`` of the knee's goodput
    instead of collapsing. Vacuously true when the sweep never went past
    the knee (the caller should sweep further)."""
    knee_goodput = knee.get("goodput_rps") or 0.0
    if knee_goodput <= 0:
        return False
    past = [r for r in rows if r["rate_rps"] > knee["rate_rps"]]
    return all(
        (r.get("goodput_rps") or 0.0) >= hold_frac * knee_goodput
        for r in past
    )
