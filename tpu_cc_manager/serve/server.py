"""Per-node synthetic inference server riding the drain handshake.

The serving contract under a CC flip: a request accepted by a node is
NEVER lost. The server subscribes to the node's drain protocol
(:class:`~tpu_cc_manager.drain.handshake.DrainSubscriber`); when the
manager requests a drain the server

1. stops accepting new batches (the driver routes around it),
2. lets the in-flight batch park — the executor checkpoints each
   sequence's partial decode state at the next token boundary instead of
   running the batch to completion,
3. charges one simulated durable-checkpoint write, sized to whatever a
   published ``drain.deadline-s`` hint's budget share the park wait left
   over (a preemption fast-drain's hard window must bound the whole
   bracket, not truncate it — normal drains pay the full write), and
4. hands every unfinished request to the driver's ``on_handoff`` sink
   (when wired — SERVE_r03's zero-bounce path): the sink re-dispatches
   them DIRECTLY to an accepting peer, still inside the ack window, and
   whatever finds no accepting peer falls back to the plain requeue —
   so conservation (issued = completed + shed + lost) holds by
   construction whichever path each request takes. Without a sink,
   requeues everything to the driver — progress (``tokens_done``)
   preserved, so the retry only pays the remaining tokens — before the
   subscriber acks the cycle (a batch that outruns the park budget is
   the one exception: it requeues the moment it parks, which under
   deadline pressure may land just after the ack — conserved either
   way). The durable-checkpoint write is charged only for requests that
   did NOT migrate live: a handed-off request's decode state transfers
   with it and is paid as a restore at the receiving executor
   (:meth:`SimulatedExecutor.resume_from_progress`), not as a durable
   write here.

The executor is a latency/bandwidth model by default
(:class:`SimulatedExecutor`, calibratable from a real llama smoke
result); the protocol half — intake, drain, checkpoint, requeue — is the
real code path the report measures.
"""

from __future__ import annotations

import dataclasses
import logging
import threading
import time

from tpu_cc_manager.drain import handshake
from tpu_cc_manager.kubeclient.api import KubeApi
from tpu_cc_manager.utils import locks as locks_mod
from tpu_cc_manager.utils import metrics as metrics_mod
from tpu_cc_manager.utils import retry as retry_mod

log = logging.getLogger(__name__)

STATE_ACCEPTING = "accepting"
STATE_DRAINING = "draining"

#: Fraction of a published drain deadline the checkpoint bracket may
#: spend: the rest of the window belongs to the manager's own eviction
#: and (on a preemption) the handoff publish.
DEFAULT_CHECKPOINT_BUDGET_FRACTION = 0.5

#: Restoring a handed-off request's checkpointed decode state on the
#: receiving node re-ingests its ``tokens_done`` context at prefill
#: speed, which is roughly an order of magnitude faster than decode
#: (weights stream once for the whole re-ingest instead of once per
#: token) — so the restore charge is this fraction of the decode-side
#: per-token rate. Deliberately not a calibration parameter:
#: ``from_smoke_result`` stays untouched.
RESTORE_PREFILL_FRAC = 0.1


@dataclasses.dataclass
class Request:
    """One synthetic inference request: ``decode_tokens`` of work, with
    checkpointable progress. ``submitted_at`` is stamped when the request
    enters the system (driver clock) — for open-loop traffic that is the
    SCHEDULED arrival time, so reported latency includes every second of
    queue wait (no coordinated omission) — and a checkpoint-and-requeue
    bounce does NOT restamp it, so reported latency is what the user saw.
    ``deadline_at`` (open-loop mode) is the absolute completion deadline:
    admission control sheds the request once the deadline budget is
    provably spent, and a completion past it counts as a deadline miss.
    ``started_at`` is the FIRST executor dispatch (bounces keep it), so
    ``started_at - submitted_at`` is the queue delay the sweep reports."""

    req_id: int
    decode_tokens: int
    submitted_at: float
    tokens_done: int = 0
    attempts: int = 0
    checkpoints: int = 0
    completed_at: float | None = None
    deadline_at: float | None = None
    started_at: float | None = None
    shed_at: float | None = None
    # Serving-state handoff (SERVE_r03): how many times this request
    # migrated from a draining node to an accepting peer, and whether
    # its checkpointed decode state still awaits the restore charge at
    # the next executor dispatch (cleared by resume_from_progress).
    handoffs: int = 0
    restore_pending: bool = False

    def remaining(self) -> int:
        return max(0, self.decode_tokens - self.tokens_done)


class SimulatedExecutor:
    """Latency + bandwidth model of one batched decode step.

    Wall time: ``base_s`` dispatch overhead + ``per_token_s`` per decode
    step (steps run batch-parallel, so the batch pays the LONGEST
    remaining sequence, not the sum). Interruptible at token boundaries:
    a set ``interrupt`` event parks the batch with each sequence's
    ``tokens_done`` advanced to the boundary — the checkpointable state
    the drain protocol preserves.

    ``hbm_bw_util``: mirrors the llama smoke accounting shape — one
    weight stream shared by the whole batch plus one full-allocated KV
    stream per sequence (``weight_frac + batch * kv_frac``, capped at
    1.0). Like the real number it is a useful-traffic LOWER bound (see
    smoke/llama_infer.py), which is why the driver's ladder treats its
    headroom read as conservative and keeps a ceiling below 1.0.
    """

    def __init__(
        self,
        base_s: float = 0.002,
        per_token_s: float = 0.002,
        weight_frac: float = 0.30,
        kv_frac: float = 0.05,
    ) -> None:
        self.base_s = base_s
        self.per_token_s = per_token_s
        self.weight_frac = weight_frac
        self.kv_frac = kv_frac
        # Brownout (gray failure, faults/plan.py seed_brownout): > 1
        # while the node's token rate is degraded. Scales base_s AND
        # per_token_s in place so estimate_s, the admission math that
        # reads per_token_s, and the execute charge all slow down
        # together — the node stays honest about its own degradation,
        # it just IS slower.
        self.brownout_factor = 1.0

    def set_brownout(self, factor: float) -> None:
        """Arm (factor > 1) or clear (factor = 1) a degraded token
        rate: every dispatch and decode step runs ``factor`` times
        slower while the executor keeps succeeding — the seeded gray
        failure the fail-slow detector must catch peer-relatively,
        because nothing on this node ever errors."""
        f = max(1.0, float(factor))
        if self.brownout_factor != 1.0:
            # Restore the nominal rate before re-scaling.
            self.per_token_s /= self.brownout_factor
            self.base_s /= self.brownout_factor
        self.per_token_s *= f
        self.base_s *= f
        self.brownout_factor = f

    @classmethod
    def from_smoke_result(cls, smoke: dict) -> "SimulatedExecutor":
        """Calibrate the model from a real llama smoke artifact: measured
        ``ms_per_token`` becomes the per-step latency and the measured
        ``hbm_bw_util`` at the smoke's batch anchors the bandwidth model
        (weight stream modeled as the batch-independent part)."""
        ex = cls()
        ms = smoke.get("ms_per_token")
        if ms:
            ex.per_token_s = max(1e-4, float(ms) / 1e3)
        util = smoke.get("hbm_bw_util")
        batch = smoke.get("batch") or 1
        if util:
            # Split the measured point: weights amortize across the
            # batch, KV does not — the same shape the accounting models.
            ex.weight_frac = 0.5 * float(util)
            ex.kv_frac = max(1e-3, 0.5 * float(util) / max(1, int(batch)))
        return ex

    def hbm_bw_util(self, batch_size: int) -> float:
        return min(1.0, self.weight_frac + batch_size * self.kv_frac)

    def estimate_s(self, tokens: int) -> float:
        """Predicted wall time for ``tokens`` of batch-parallel decode —
        the calibrated per-token rate admission control multiplies queue
        depth by (serve/server.py intake). The same model ``execute``
        charges, so the estimate and the charge cannot drift."""
        return self.base_s + self.per_token_s * max(0, tokens)

    def resume_from_progress(
        self, batch: list[Request], stop: threading.Event,
    ) -> float:
        """Charge the one-time restore of checkpointed decode state for
        requests handed off from a draining peer: one dispatch overhead
        plus a prefill-speed re-ingest of the LONGEST checkpointed
        context in the batch (restores run batch-parallel like decode).
        Requests without ``restore_pending`` cost nothing — the method
        is a no-op outside the handoff path, so closed-loop/requeue
        behavior is byte-identical to before. Returns the seconds
        charged and clears the flags."""
        tokens = max(
            (r.tokens_done for r in batch if r.restore_pending), default=0
        )
        restored = [r for r in batch if r.restore_pending]
        if not restored:
            return 0.0
        cost = self.base_s + RESTORE_PREFILL_FRAC * self.per_token_s * tokens
        retry_mod.wait(cost, stop)
        for r in restored:
            r.restore_pending = False
        return cost

    def execute(
        self, batch: list[Request], interrupt: threading.Event,
        stop: threading.Event,
    ) -> float:
        """Run the batch to completion or to the interrupt boundary;
        returns the modeled ``hbm_bw_util`` for this batch size."""
        retry_mod.wait(self.base_s, stop)
        steps = max((r.remaining() for r in batch), default=0)
        for _ in range(steps):
            if interrupt.is_set() or stop.is_set():
                break
            retry_mod.wait(self.per_token_s, stop)
            for r in batch:
                if r.remaining() > 0:
                    r.tokens_done += 1
        return self.hbm_bw_util(len(batch))


class NodeServer:
    """One node's serving loop + its side of the drain handshake."""

    def __init__(
        self,
        api: KubeApi,
        node_name: str,
        on_complete,
        on_requeue,
        on_shed=None,
        on_handoff=None,
        executor: SimulatedExecutor | None = None,
        job_name: str = "serve",
        poll_interval_s: float = 0.05,
        checkpoint_full_s: float = 0.2,
        checkpoint_budget_fraction: float = DEFAULT_CHECKPOINT_BUDGET_FRACTION,
        restore_s: float = 0.0,
        metrics: metrics_mod.MetricsRegistry | None = None,
        clock=time.monotonic,
    ) -> None:
        self.api = api
        self.node_name = node_name
        # Live telemetry (tpu_cc_serve_* families): queue depth and
        # in-flight gauges plus the bounced counter come from the
        # server — it is the only component that knows both. None =
        # unexported (unit tests); the harness passes ONE shared
        # registry across servers + driver so /metrics shows the pool.
        self.metrics = metrics
        self.executor = executor if executor is not None else SimulatedExecutor()
        self._on_complete = on_complete  # (node_name, Request, util)
        self._on_requeue = on_requeue    # (node_name, list[Request])
        # Admission control / load shedding (open-loop overload): when a
        # submitted request carries a deadline, intake estimates the
        # queue delay ahead of it (queue depth x the executor's
        # calibrated per-token rate) and sheds it if it provably cannot
        # complete in time — admitting it would burn capacity on a
        # guaranteed deadline miss and drag every request queued behind
        # it past ITS deadline too. Shed requests go to this callback
        # (counted outcome=shed by the driver; never lost).
        self._on_shed = on_shed          # (node_name, list[Request])
        # Serving-state handoff (SERVE_r03): the drain bracket hands its
        # parked in-flight + queued requests to this driver-side sink
        # instead of requeueing them locally; the sink re-dispatches
        # them to an accepting peer INSIDE the ack window and returns
        # how many a peer accepted (it requeues the rest itself — the
        # no-accepting-peer fallback IS today's local requeue, so
        # conservation holds whichever path each request takes). None =
        # the pre-handoff behavior, unchanged.
        self._on_handoff = on_handoff    # (node_name, list[Request]) -> int
        self.checkpoint_full_s = checkpoint_full_s
        self.checkpoint_budget_fraction = checkpoint_budget_fraction
        self.restore_s = restore_s
        # Must share the driver's time domain: request stamps
        # (submitted_at/deadline_at from the driver, started_at/
        # completed_at from here) are compared against each other by the
        # admission check and the latency report.
        self.clock = clock
        self._lock = locks_mod.make_lock("serve.server")
        self._state = STATE_ACCEPTING  # cclint: guarded-by(_lock)
        self._queue: list[list[Request]] = []  # cclint: guarded-by(_lock)
        self._inflight: list[Request] = []  # cclint: guarded-by(_lock)
        # In-flight partials parked by the worker WHILE a drain bracket is
        # collecting (the bracket requeues them inside the ack window);
        # once the bracket stops collecting, the worker requeues directly
        # so nothing can strand here between drains.
        self._parked: list[Request] = []  # cclint: guarded-by(_lock)
        self._drain_collecting = False  # cclint: guarded-by(_lock)
        self._work = threading.Event()
        self._idle = threading.Event()
        self._idle.set()
        self._drain_break = threading.Event()
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        self.subscriber = handshake.DrainSubscriber(
            api, node_name, job_name,
            on_drain=self._on_drain, on_resume=self._on_resume,
            poll_interval_s=poll_interval_s,
        )
        # Observability for the harness report / tests (single-writer
        # fields: the subscriber thread writes, readers tolerate lag).
        self.drains = 0
        self.resumes = 0
        self.last_checkpoint_s: float | None = None
        self.last_checkpoint_deadline_s: float | None = None
        self.last_checkpoint_requeued = 0
        self.last_handoff_accepted = 0
        self.handoffs_accepted = 0
        self.last_hbm_bw_util: float | None = None

    # -- lifecycle ---------------------------------------------------------

    def start(self) -> None:
        # Register synchronously BEFORE traffic starts so a drain
        # requested in the first poll interval still awaits this server.
        self.subscriber.register()
        self.subscriber.start()
        self._thread = threading.Thread(
            target=self._worker, daemon=True,
            name=f"serve-{self.node_name}",
        )
        self._thread.start()

    def stop(self, timeout_s: float = 10.0) -> None:
        self._stop.set()
        self._work.set()
        if self._thread is not None:
            self._thread.join(timeout=timeout_s)
        self.subscriber.stop(timeout_s=timeout_s)

    # -- intake ------------------------------------------------------------

    def accepting(self) -> bool:
        with self._lock:
            return self._state == STATE_ACCEPTING

    def _export_gauges(self) -> None:
        """Push the queue-depth / in-flight gauges (tpu_cc_serve_*) —
        called at every transition that changes either, so a mid-flip
        scrape sees the live pipeline, not an end-of-run summary."""
        if self.metrics is None:
            return
        with self._lock:
            depth = sum(len(b) for b in self._queue)
            inflight = len(self._inflight)
        self.metrics.set_serve_queue_depth(self.node_name, depth)
        self.metrics.set_serve_inflight(self.node_name, inflight)

    def _queue_delay_estimate_s(self) -> float:  # cclint: requires(_lock)
        """Predicted wait before a newly-accepted batch starts executing:
        every queued batch's modeled wall time (batch-parallel, so each
        pays its LONGEST remaining sequence) plus whatever the in-flight
        batch still owes. Uses the executor's calibrated per-token rate
        (``estimate_s``) — the same model that charges the work — so the
        admission decision is as honest as the simulation itself."""
        est = 0.0
        for b in self._queue:
            est += self.executor.estimate_s(
                max((r.remaining() for r in b), default=0)
            )
        if self._inflight:
            # tokens_done advances live at each boundary, so this reads
            # the true remaining work, not the batch's original size.
            est += self.executor.per_token_s * max(
                (r.remaining() for r in self._inflight), default=0
            )
        return est

    def queue_delay_estimate_s(self) -> float:
        with self._lock:
            return self._queue_delay_estimate_s()

    def submit(self, batch: list[Request], front: bool = False) -> bool:
        """Accept one batch for execution; False while draining/drained
        (the driver keeps the requests and routes them elsewhere).
        ``front`` queues the batch AHEAD of waiting work — the handoff
        sink uses it because migrated requests are the oldest in-flight
        work in the system and re-queueing them behind the peer's fresh
        pipe would compound the bounce delay they already paid.

        Admission control: requests carrying a deadline are shed at
        intake when the estimated queue delay plus their own service
        time already overruns the deadline budget — handed to
        ``on_shed`` (outcome=shed), never queued to miss. Requests
        without a deadline are always admitted (closed-loop traffic is
        unchanged)."""
        if not batch:
            return True
        now = self.clock()
        shed: list[Request] = []
        with self._lock:
            if self._state != STATE_ACCEPTING or self._stop.is_set():
                return False
            est = self._queue_delay_estimate_s()
            accepted: list[Request] = []
            for r in batch:
                # No shed sink = no shedding: a deadline-carrying request
                # submitted without an on_shed callback must be admitted,
                # not silently dropped.
                if self._on_shed is not None and r.deadline_at is not None and (
                    now + est + self.executor.estimate_s(r.remaining())
                    > r.deadline_at
                ):
                    shed.append(r)
                    continue
                r.attempts += 1
                accepted.append(r)
            if accepted:
                if front:
                    self._queue.insert(0, accepted)
                else:
                    self._queue.append(accepted)
                self._work.set()
        self._export_gauges()
        if shed and self._on_shed is not None:
            self._on_shed(self.node_name, shed)
        return True

    # -- serving loop ------------------------------------------------------

    def _worker(self) -> None:
        while not self._stop.is_set():
            if not self._work.wait(timeout=0.2):
                continue
            batch = None
            with self._lock:
                if self._queue and self._state == STATE_ACCEPTING:
                    batch = self._queue.pop(0)
                    self._inflight = list(batch)
                    self._idle.clear()
                if not self._queue:
                    self._work.clear()
            if batch is None:
                continue
            self._export_gauges()
            dispatch_t = self.clock()
            for r in batch:
                if r.started_at is None:
                    # First dispatch only: a bounced request keeps its
                    # original start, so queue delay measures the wait
                    # before ANY service, not the latest hop's.
                    r.started_at = dispatch_t
            # Handed-off requests pay their state-transfer restore here,
            # at the receiving executor (no-op for everything else).
            self.executor.resume_from_progress(batch, self._stop)
            util = self.executor.execute(batch, self._drain_break, self._stop)
            now = self.clock()
            with self._lock:
                self._inflight = []
                done = [r for r in batch if r.remaining() == 0]
                partial = [r for r in batch if r.remaining() > 0]
                for r in partial:
                    r.checkpoints += 1
                if partial and self._drain_collecting:
                    # A drain bracket is waiting on us: hand the parked
                    # partials to IT (under this same lock, before _idle
                    # is set) so they are requeued — and counted — inside
                    # the ack window.
                    self._parked.extend(partial)
                    partial = []
                self._idle.set()
            self._export_gauges()
            self.last_hbm_bw_util = util
            for r in done:
                r.completed_at = now
                self._on_complete(self.node_name, r, util)
            if partial:
                # No bracket collecting (normal interrupt-free stop, or a
                # batch that outran the drain's park budget): requeue
                # directly so nothing can strand in the parked list —
                # checkpointed progress rides back to the driver either
                # way, nothing dies with the node.
                self._on_requeue(self.node_name, partial)

    # -- drain handshake ---------------------------------------------------

    def _on_drain(self) -> None:
        """Checkpoint-and-drain, run on the subscriber thread BEFORE the
        ack is published — the manager's bounded ack wait covers exactly
        this bracket: park the in-flight batch (bounded), hand everything
        unfinished to the peer-migration sink (or checkpoint + requeue it
        locally without one), then let the ack go out. The park
        wait and the checkpoint write share ONE budget (the hint's
        fraction): each bounded separately could consume 2× the share of
        a hard window that also has to fit the manager's eviction and
        handoff. A batch that outruns the park budget is still conserved
        — the worker requeues it directly the moment it parks (the
        checkpoint then lands after the ack, the one compromise deadline
        pressure can force)."""
        t0 = time.monotonic()
        with self._lock:
            self._state = STATE_DRAINING
            self._drain_collecting = True
            pending: list[Request] = [
                r for b in self._queue for r in b
            ]
            self._queue.clear()
        self._drain_break.set()
        deadline = self.subscriber.drain_deadline_s
        budget = (
            deadline * self.checkpoint_budget_fraction
            if deadline else None
        )
        # Let the in-flight batch park at its token boundary (the
        # executor breaks within one per-token step).
        self._idle.wait(timeout=budget if budget is not None else 5.0)
        with self._lock:
            parked = self._parked[:]
            self._parked.clear()
            # From here the worker requeues any late partials directly —
            # nothing can strand in the parked list between drains.
            self._drain_collecting = False
        to_requeue = pending + parked
        # Serving-state handoff: migrate the parked batch + queued
        # requests to an accepting peer FIRST, still inside the ack
        # window — a live migration carries the decode state with the
        # request (the restore is charged at the receiving executor),
        # so migrated requests skip the durable write entirely. The
        # sink requeues whatever found no accepting peer itself; the
        # durable-checkpoint charge below then covers exactly that
        # remainder (its progress survives only in the written copy),
        # and the ack still waits out the write as before.
        accepted = 0
        fallback = 0
        if self._on_handoff is not None and to_requeue:
            # The sink owns every request from here (migrated ones may
            # already be EXECUTING on a peer — this thread must not
            # touch them again); it requeues the fallback remainder
            # itself and stamps those requests' checkpoint counts.
            accepted, fallback = self._on_handoff(self.node_name, to_requeue)
            to_requeue = []
        self.last_handoff_accepted = accepted
        self.handoffs_accepted += accepted
        # Simulated durable checkpoint write: the full write when no
        # deadline pressure; under a hint, whatever of the budget the
        # park wait left over — the hint exists so jobs can fit the
        # window instead of starting a write the kill would truncate
        # (drain/handshake.py). Skipped when nothing took the local
        # requeue path (migrated requests carry their state with them;
        # peer-shed ones left the system) — only fallback requests
        # depend on the durable copy.
        if self._on_handoff is not None and fallback == 0:
            ckpt_s = 0.0
        elif budget is not None:
            remaining = max(0.0, budget - (time.monotonic() - t0))
            ckpt_s = min(self.checkpoint_full_s, remaining)
        else:
            ckpt_s = self.checkpoint_full_s
        retry_mod.wait(ckpt_s, self._stop)
        if self._on_handoff is None:
            for r in pending:
                r.checkpoints += 1
        # else: the sink stamped the fallback requests' checkpoint
        # counts itself; migrated requests may already be executing on a
        # peer and must not be touched from this thread.
        self.last_checkpoint_s = time.monotonic() - t0
        self.last_checkpoint_deadline_s = deadline
        # Requests that took the LOCAL requeue path this drain (the
        # durable write covers exactly these; migrated/shed ones do not
        # count — see last_handoff_accepted for the migrations).
        self.last_checkpoint_requeued = len(to_requeue) + fallback
        self.drains += 1
        self._export_gauges()
        if to_requeue:
            self._on_requeue(self.node_name, to_requeue)
        log.info(
            "server %s drained: %d requeued (%d handed off), checkpoint "
            "%.3fs (hint=%s)",
            self.node_name, self.last_checkpoint_requeued, accepted,
            self.last_checkpoint_s, deadline,
        )

    def _on_resume(self) -> None:
        """The drain request cleared (node re-admitted, post-flip):
        restore and reopen intake."""
        if self.restore_s:
            retry_mod.wait(self.restore_s, self._stop)
        self._drain_break.clear()
        with self._lock:
            self._state = STATE_ACCEPTING
        self.resumes += 1
        log.info("server %s resumed intake", self.node_name)
