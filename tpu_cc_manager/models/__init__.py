"""Flax model definitions for the smoke workloads.

No reference counterpart (the reference has no models at all, SURVEY.md §2);
these exist to satisfy BASELINE.json's validation ladder: Llama-2-7B /
Llama-3-8B inference (configs[2], [4]) and ResNet-50 training (configs[3]).
Written TPU-first: bf16 compute with f32 accumulation, static shapes,
`lax.scan` over layers, shard-annotated parameters.
"""

from tpu_cc_manager.models.llama import LlamaConfig, LlamaModel
from tpu_cc_manager.models.resnet import ResNet50

__all__ = ["LlamaConfig", "LlamaModel", "ResNet50"]
