"""Llama-family decoder, written TPU-first in flax.linen.

Used by the inference smoke workload (BASELINE.json configs[2]: Llama-2-7B
on v5p-8; configs[4]: Llama-3-8B DP over DCN). Design notes for the MXU/XLA:

- bf16 activations, f32 parameters and f32 for RoPE phases, softmax and
  logits — the standard TPU numerics recipe;
- one ``nn.scan`` over identical decoder blocks: one compile of one block
  regardless of depth, layer-stacked parameters (leading 'layers' axis), and
  the natural place to hang ``nn.remat`` for HBM-bound training;
- grouped-query attention (Llama-2-70B / Llama-3 style) expressed as einsum
  over a (kv_head, group) split so XLA keeps a single large contraction;
- static-shape KV cache for decode: fixed (max_len) buffers updated with
  ``lax.dynamic_update_slice_in_dim`` and masked by position — no dynamic
  shapes, so the decode step compiles exactly once;
- named sharding axes ('embed', 'heads', 'kv_heads', 'mlp', 'vocab',
  'layers') via ``nn.with_logical_partitioning``, mapped onto mesh axes by
  parallel/sharding.py.
"""

from __future__ import annotations

import dataclasses
from typing import Any

import flax.linen as nn
import jax
import jax.numpy as jnp
from jax import lax


@dataclasses.dataclass(frozen=True)
class LlamaConfig:
    vocab_size: int = 32000
    dim: int = 4096
    n_layers: int = 32
    n_heads: int = 32
    n_kv_heads: int = 32
    hidden_dim: int = 11008
    max_seq_len: int = 2048
    rope_theta: float = 10000.0
    # Llama-3.1-style frequency scaling for long context, as a hashable
    # tuple (factor, low_freq_factor, high_freq_factor, original_max_len);
    # None = unscaled RoPE (Llama-2/3.0).
    rope_scaling: tuple[float, float, float, int] | None = None
    norm_eps: float = 1e-5
    dtype: Any = jnp.bfloat16
    # Parameter storage dtype. f32 for training (optimizer-grade master
    # weights); bf16 for inference, where decode is HBM-bandwidth-bound on
    # reading the weights each step — bf16 params double tokens/s and halve
    # the footprint (what fits a 7B model on one chip).
    param_dtype: Any = jnp.float32
    remat: bool = False
    # Use the pallas flash-attention kernel (ops/flash_attention.py) on the
    # no-cache (training/prefill) path; the cached decode path always uses
    # the einsum attention (its working set is already small). None (the
    # default) resolves to True on TPU — the kernel (forward AND flash
    # backward, O(S·D) memory) is the production path — and False elsewhere
    # (on CPU pallas runs in interpreter mode, which is for correctness
    # tests, not speed).
    use_flash: bool | None = None
    # Long-context sequence/context parallelism: when a mesh is given, the
    # no-cache (training/prefill) attention runs as ring attention
    # (ops/ring_attention.py) with the sequence sharded over ``ring_axis``
    # — K/V shards stream around the ICI ring with ppermute, so no device
    # ever holds full K/V. The mesh is static module metadata (hashable),
    # like the dtypes.
    ring_mesh: Any = None
    ring_axis: str = "sp"

    @property
    def head_dim(self) -> int:
        return self.dim // self.n_heads

    # ---- standard family members --------------------------------------

    @classmethod
    def llama2_7b(cls, **kw) -> "LlamaConfig":
        return cls(**{**dict(vocab_size=32000, dim=4096, n_layers=32, n_heads=32,
                             n_kv_heads=32, hidden_dim=11008, max_seq_len=4096), **kw})

    @classmethod
    def llama3_8b(cls, **kw) -> "LlamaConfig":
        return cls(**{**dict(vocab_size=128256, dim=4096, n_layers=32, n_heads=32,
                             n_kv_heads=8, hidden_dim=14336, max_seq_len=8192,
                             rope_theta=500000.0), **kw})

    @classmethod
    def llama3_1_8b(cls, **kw) -> "LlamaConfig":
        """Llama-3.1-8B: the 3.0 geometry with 128k context via the
        llama3 rope-scaling recipe (factor 8 over the 8192 base)."""
        return cls.llama3_8b(**{**dict(max_seq_len=131072,
                                       rope_scaling=(8.0, 1.0, 4.0, 8192)), **kw})

    @classmethod
    def llama3_2_1b(cls, **kw) -> "LlamaConfig":
        """Llama-3.2-1B geometry (~1.2B params): bf16 fits any TPU chip
        with room for cache and activations."""
        return cls(**{**dict(vocab_size=128256, dim=2048, n_layers=16,
                             n_heads=32, n_kv_heads=8, hidden_dim=8192,
                             max_seq_len=131072, rope_theta=500000.0,
                             rope_scaling=(32.0, 1.0, 4.0, 8192)), **kw})

    @classmethod
    def llama3_2_3b(cls, **kw) -> "LlamaConfig":
        """Llama-3.2-3B geometry (~3.2B params): the largest family member
        that fits single-chip v5e (16 GB HBM) in bf16 with real headroom —
        ~6.4 GB of weights leaves ~9 GB for KV cache + activations.
        (Llama-2-7B bf16 is ~13.5 GB of weights alone: it loads on v5e
        only with a sliver of cache headroom, and Llama-3-8B's 128k vocab
        pushes past 16 GB — the single-chip ceiling BASELINE configs[2]
        runs into; multi-chip tp is the path for those.)"""
        return cls(**{**dict(vocab_size=128256, dim=3072, n_layers=28,
                             n_heads=24, n_kv_heads=8, hidden_dim=8192,
                             max_seq_len=131072, rope_theta=500000.0,
                             rope_scaling=(32.0, 1.0, 4.0, 8192)), **kw})

    @classmethod
    def tiny(cls, **kw) -> "LlamaConfig":
        """CI/test config: ~1M params, same code paths."""
        return cls(**{**dict(vocab_size=256, dim=64, n_layers=2, n_heads=4,
                             n_kv_heads=2, hidden_dim=128, max_seq_len=128), **kw})

    @classmethod
    def smoke_500m(cls, **kw) -> "LlamaConfig":
        """Single-chip smoke config (~400M params): big enough to exercise
        the MXU seriously, small enough to init fast on any chip."""
        return cls(**{**dict(vocab_size=32000, dim=1024, n_layers=16, n_heads=16,
                             n_kv_heads=8, hidden_dim=4096, max_seq_len=2048), **kw})

    def resolved_use_flash(self) -> bool:
        """The single resolution point for the use_flash default (None →
        flash on TPU, einsum elsewhere). The model forward and the smoke's
        flash-consistency oracle (smoke/llama_infer.py) must agree on this,
        or the oracle checks a path the model doesn't run."""
        if self.use_flash is not None:
            return self.use_flash
        return jax.default_backend() == "tpu"

    def param_count(self) -> int:
        head = self.head_dim
        attn = self.dim * (self.n_heads * head) * 2 + self.dim * (
            self.n_kv_heads * head
        ) * 2
        mlp = 3 * self.dim * self.hidden_dim
        per_layer = attn + mlp + 2 * self.dim
        return self.vocab_size * self.dim * 2 + per_layer * self.n_layers + self.dim


class RMSNorm(nn.Module):
    eps: float
    dtype: Any
    param_dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x):
        scale = self.param(
            "scale",
            nn.with_logical_partitioning(nn.initializers.ones, ("embed",)),
            (x.shape[-1],),
            self.param_dtype,
        )
        x32 = x.astype(jnp.float32)
        normed = x32 * lax.rsqrt(jnp.mean(x32 * x32, axis=-1, keepdims=True) + self.eps)
        return (normed * scale.astype(jnp.float32)).astype(self.dtype)


def rope_frequencies(
    head_dim: int,
    max_len: int,
    theta: float,
    scaling: tuple[float, float, float, int] | None = None,
) -> jnp.ndarray:
    """(max_len, head_dim//2) rotation phases, f32.

    ``scaling`` applies the Llama-3.1 long-context recipe: wavelengths far
    beyond the original training context are divided by ``factor``, short
    wavelengths are kept, and the band in between is smoothly interpolated.
    """
    inv_freq = 1.0 / (theta ** (jnp.arange(0, head_dim, 2, dtype=jnp.float32) / head_dim))
    if scaling is not None:
        factor, low_ff, high_ff, original_max = scaling
        wavelen = 2.0 * jnp.pi / inv_freq
        low_wavelen = original_max / low_ff
        high_wavelen = original_max / high_ff
        smooth = (original_max / wavelen - low_ff) / (high_ff - low_ff)
        interpolated = (1.0 - smooth) * inv_freq / factor + smooth * inv_freq
        inv_freq = jnp.where(
            wavelen > low_wavelen,
            inv_freq / factor,
            jnp.where(wavelen < high_wavelen, inv_freq, interpolated),
        )
    pos = jnp.arange(max_len, dtype=jnp.float32)
    return jnp.outer(pos, inv_freq)


def apply_rope(x: jnp.ndarray, phases: jnp.ndarray) -> jnp.ndarray:
    """x: (B, S, H, D); phases: (S, D/2). Rotation in f32, cast back."""
    x32 = x.astype(jnp.float32)
    x1, x2 = jnp.split(x32, 2, axis=-1)
    cos = jnp.cos(phases)[None, :, None, :]
    sin = jnp.sin(phases)[None, :, None, :]
    out = jnp.concatenate([x1 * cos - x2 * sin, x2 * cos + x1 * sin], axis=-1)
    return out.astype(x.dtype)


def _dense(features: int, axes: tuple[str, str], cfg: "LlamaConfig", name: str):
    return nn.Dense(
        features,
        use_bias=False,
        dtype=cfg.dtype,
        param_dtype=cfg.param_dtype,
        kernel_init=nn.with_logical_partitioning(
            nn.initializers.normal(stddev=0.02), axes
        ),
        name=name,
    )


class Attention(nn.Module):
    cfg: LlamaConfig

    @nn.compact
    def __call__(self, x, phases, mask, layer_cache=None, position=None):
        cfg = self.cfg
        B, S, _ = x.shape
        H, KV, D = cfg.n_heads, cfg.n_kv_heads, cfg.head_dim

        q = _dense(H * D, ("embed", "heads"), cfg, "wq")(x).reshape(B, S, H, D)
        k = _dense(KV * D, ("embed", "kv_heads"), cfg, "wk")(x).reshape(B, S, KV, D)
        v = _dense(KV * D, ("embed", "kv_heads"), cfg, "wv")(x).reshape(B, S, KV, D)

        q = apply_rope(q, phases)
        k = apply_rope(k, phases)

        if layer_cache is not None:
            # Static-shape decode: write this step's K/V at `position` into
            # the (B, max_len, KV, D) buffers, then attend over the buffers.
            k_buf, v_buf = layer_cache
            k_buf = lax.dynamic_update_slice_in_dim(
                k_buf, k.astype(k_buf.dtype), position, axis=1
            )
            v_buf = lax.dynamic_update_slice_in_dim(
                v_buf, v.astype(v_buf.dtype), position, axis=1
            )
            k, v = k_buf, v_buf
            layer_cache = (k_buf, v_buf)

        if layer_cache is None and (
            cfg.ring_mesh is not None or cfg.resolved_use_flash()
        ):
            # Kernel layout is (B, heads, S, D).
            qf = q.transpose(0, 2, 1, 3)
            kf = k.transpose(0, 2, 1, 3)
            vf = v.transpose(0, 2, 1, 3)
            if cfg.ring_mesh is not None:
                from tpu_cc_manager.ops.ring_attention import (
                    ring_attention_in_jit,
                )

                # Sequence-parallel long-context path: K/V shards stream
                # around the ring KV-head-shaped (GQA grouping happens
                # inside the kernel — no H/KV-fold traffic inflation).
                out = ring_attention_in_jit(
                    qf, kf, vf, cfg.ring_mesh, cfg.ring_axis
                )
            else:
                from tpu_cc_manager.ops.flash_attention import flash_attention

                # The pallas kernel wants equal head counts: GQA via
                # kv-head repetition.
                kf = jnp.repeat(kf, H // KV, axis=1)
                vf = jnp.repeat(vf, H // KV, axis=1)
                out = flash_attention(qf, kf, vf)
            out = out.transpose(0, 2, 1, 3).reshape(B, S, H * D).astype(cfg.dtype)
            return _dense(cfg.dim, ("heads", "embed"), cfg, "wo")(out), None

        # GQA: fold heads into (kv groups, group size) so the contraction
        # stays one big einsum on the MXU.
        G = H // KV
        qg = q.reshape(B, S, KV, G, D)
        scores = jnp.einsum(
            "bskgd,btkd->bkgst", qg, k, preferred_element_type=jnp.float32
        ) / jnp.sqrt(jnp.float32(D))
        scores = scores + mask  # additive causal mask, broadcast to (B,KV,G,S,T)
        probs = jax.nn.softmax(scores, axis=-1).astype(cfg.dtype)
        out = jnp.einsum("bkgst,btkd->bskgd", probs, v)
        out = out.reshape(B, S, H * D)
        return _dense(cfg.dim, ("heads", "embed"), cfg, "wo")(out), layer_cache


class MLP(nn.Module):
    cfg: LlamaConfig

    @nn.compact
    def __call__(self, x):
        cfg = self.cfg
        gate = _dense(cfg.hidden_dim, ("embed", "mlp"), cfg, "w_gate")(x)
        up = _dense(cfg.hidden_dim, ("embed", "mlp"), cfg, "w_up")(x)
        return _dense(cfg.dim, ("mlp", "embed"), cfg, "w_down")(
            nn.silu(gate) * up
        )


class DecoderBlock(nn.Module):
    """Scanned unit: carry is (activations, phases, mask, position) —
    invariant in shape; per-layer KV cache rides the scan's xs/ys."""

    cfg: LlamaConfig

    @nn.compact
    def __call__(self, carry, layer_cache):
        x, phases, mask, position = carry
        h, layer_cache = Attention(self.cfg, name="attn")(
            RMSNorm(self.cfg.norm_eps, self.cfg.dtype, self.cfg.param_dtype, name="attn_norm")(x),
            phases, mask, layer_cache, position,
        )
        x = x + h
        x = x + MLP(self.cfg, name="mlp")(
            RMSNorm(self.cfg.norm_eps, self.cfg.dtype, self.cfg.param_dtype, name="mlp_norm")(x)
        )
        return (x, phases, mask, position), layer_cache


class LlamaModel(nn.Module):
    """Decoder-only transformer; __call__ covers both training (full
    sequence, cache=None) and decode (S=1 with a KV cache)."""

    cfg: LlamaConfig

    @nn.compact
    def __call__(self, tokens, cache=None, position=None):
        cfg = self.cfg
        B, S = tokens.shape
        embed = self.param(
            "embedding",
            nn.with_logical_partitioning(
                nn.initializers.normal(stddev=0.02), ("vocab", "embed")
            ),
            (cfg.vocab_size, cfg.dim),
            cfg.param_dtype,
        )
        x = embed[tokens].astype(cfg.dtype)

        all_phases = rope_frequencies(
            cfg.head_dim, cfg.max_seq_len, cfg.rope_theta, cfg.rope_scaling
        )
        if cache is not None:
            T = cache[0].shape[2]  # cache: (k, v) each (L, B, T, KV, D)
            phases = lax.dynamic_slice_in_dim(all_phases, position, S, axis=0)
            t = jnp.arange(T)
            # Causal over absolute positions: query i (at position+i) sees
            # cache slots <= position+i. Covers decode (S=1) and multi-token
            # prefill with one formula.
            q_pos = position + jnp.arange(S)
            mask = jnp.where(
                t[None, None, None, None, :] <= q_pos[None, None, None, :, None],
                0.0,
                -jnp.inf,
            )
        else:
            phases = all_phases[:S]
            t = jnp.arange(S)
            mask = jnp.where(t[None, :] <= t[:, None], 0.0, -jnp.inf)[
                None, None, None, :, :
            ]

        block_cls = DecoderBlock
        if cfg.remat:
            block_cls = nn.remat(DecoderBlock, prevent_cse=False)
        scan_block = nn.scan(
            block_cls,
            variable_axes={"params": 0},
            split_rngs={"params": True},
            length=cfg.n_layers,
            in_axes=0,
            out_axes=0,
            metadata_params={nn.PARTITION_NAME: "layers"},
        )
        carry = (x, phases, mask, position)
        xs = None if cache is None else cache
        (x, _, _, _), new_cache = scan_block(cfg, name="blocks")(carry, xs)

        x = RMSNorm(cfg.norm_eps, cfg.dtype, cfg.param_dtype, name="final_norm")(x)
        lm_head = self.param(
            "lm_head",
            nn.with_logical_partitioning(
                nn.initializers.normal(stddev=0.02), ("embed", "vocab")
            ),
            (cfg.dim, cfg.vocab_size),
            cfg.param_dtype,
        )
        # bf16 params keep bf16 operands (MXU-native, half the bandwidth)
        # with f32 accumulation; f32 master weights keep the full-f32
        # contraction of the training recipe.
        mm_dtype = cfg.dtype if cfg.param_dtype == cfg.dtype else jnp.float32
        logits = jnp.einsum(
            "bsd,dv->bsv",
            x.astype(mm_dtype),
            lm_head.astype(mm_dtype),
            preferred_element_type=jnp.float32,
        )
        return logits, new_cache

    # ---- cache helpers ----------------------------------------------------

    def init_cache(self, batch: int, max_len: int | None = None):
        cfg = self.cfg
        max_len = max_len or cfg.max_seq_len
        shape = (cfg.n_layers, batch, max_len, cfg.n_kv_heads, cfg.head_dim)
        return (jnp.zeros(shape, cfg.dtype), jnp.zeros(shape, cfg.dtype))
