"""ResNet-50 in flax.linen, TPU-first.

Used by the training smoke workload (BASELINE.json configs[3]: rolling CC
reconfig under live ResNet-50 training on v5p-32). Conventions:

- NHWC layout (XLA:TPU's native conv layout — channels-last feeds the MXU
  as a matmul over (spatial, C_in) x (C_in, C_out));
- bf16 activations, f32 parameters and f32 batch-norm statistics;
- BatchNorm in inference or train mode via the ``train`` flag, with batch
  stats carried in the standard flax 'batch_stats' collection.
"""

from __future__ import annotations

from functools import partial
from typing import Any, Sequence

import flax.linen as nn
import jax.numpy as jnp


class BottleneckBlock(nn.Module):
    filters: int
    strides: int
    dtype: Any

    @nn.compact
    def __call__(self, x, train: bool):
        conv = partial(nn.Conv, use_bias=False, dtype=self.dtype, param_dtype=jnp.float32)
        norm = partial(
            nn.BatchNorm,
            use_running_average=not train,
            momentum=0.9,
            epsilon=1e-5,
            dtype=self.dtype,
            param_dtype=jnp.float32,
        )
        residual = x
        y = conv(self.filters, (1, 1), name="conv1")(x)
        y = nn.relu(norm(name="bn1")(y))
        y = conv(self.filters, (3, 3), strides=(self.strides, self.strides), name="conv2")(y)
        y = nn.relu(norm(name="bn2")(y))
        y = conv(self.filters * 4, (1, 1), name="conv3")(y)
        y = norm(name="bn3", scale_init=nn.initializers.zeros)(y)
        if residual.shape != y.shape:
            residual = conv(
                self.filters * 4, (1, 1), strides=(self.strides, self.strides),
                name="proj",
            )(residual)
            residual = norm(name="proj_bn")(residual)
        return nn.relu(residual + y)


class ResNet(nn.Module):
    stage_sizes: Sequence[int]
    num_classes: int = 1000
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, x, train: bool = True):
        x = x.astype(self.dtype)
        x = nn.Conv(64, (7, 7), strides=(2, 2), padding=[(3, 3), (3, 3)],
                    use_bias=False, dtype=self.dtype, param_dtype=jnp.float32,
                    name="stem_conv")(x)
        x = nn.BatchNorm(use_running_average=not train, momentum=0.9, epsilon=1e-5,
                         dtype=self.dtype, param_dtype=jnp.float32, name="stem_bn")(x)
        x = nn.relu(x)
        x = nn.max_pool(x, (3, 3), strides=(2, 2), padding=((1, 1), (1, 1)))
        for stage, n_blocks in enumerate(self.stage_sizes):
            for block in range(n_blocks):
                strides = 2 if stage > 0 and block == 0 else 1
                x = BottleneckBlock(
                    filters=64 * 2**stage,
                    strides=strides,
                    dtype=self.dtype,
                    name=f"stage{stage}_block{block}",
                )(x, train)
        x = jnp.mean(x, axis=(1, 2))  # global average pool
        x = nn.Dense(self.num_classes, dtype=jnp.float32, param_dtype=jnp.float32,
                     name="classifier")(x)
        return x


def ResNet50(num_classes: int = 1000, dtype: Any = jnp.bfloat16) -> ResNet:
    return ResNet(stage_sizes=(3, 4, 6, 3), num_classes=num_classes, dtype=dtype)


def ResNetTiny(num_classes: int = 10, dtype: Any = jnp.bfloat16) -> ResNet:
    """CI/test config: same code paths, 2 stages of 1 block."""
    return ResNet(stage_sizes=(1, 1), num_classes=num_classes, dtype=dtype)
