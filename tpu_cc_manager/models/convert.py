"""Hugging Face → flax weight conversion for the Llama family.

BASELINE.json configs[2]/[4] name real checkpoints (Llama-2-7B,
Llama-3-8B); the smoke workloads run with random weights for speed, but an
operator pointing the verify phase at a real model needs its weights in our
parameter layout. This converts a ``transformers`` Llama state dict into
the layer-stacked pytree produced by ``models/llama.py`` (one leading
'layers' axis from ``nn.scan`` — SURVEY.md has no counterpart; the
reference manages no model weights at all).

Conventions handled:
- torch ``nn.Linear`` stores (out, in); flax ``Dense`` kernels are
  (in, out) → transpose every projection;
- HF's rotary convention is rotate-half, matching ``apply_rope``'s
  split-in-half layout, so Q/K need no permutation;
- per-layer tensors are stacked on axis 0 to match the scan layout.

Gated on ``transformers``/``torch`` being importable; pure-numpy state
dicts work without either.
"""

from __future__ import annotations

from typing import Any, Mapping

import numpy as np

from tpu_cc_manager.models.llama import LlamaConfig


def _to_numpy(t: Any) -> np.ndarray:
    if isinstance(t, np.ndarray):
        return t
    # torch.Tensor (cpu) — avoid importing torch just for the isinstance.
    detach = getattr(t, "detach", None)
    if detach is not None:
        t = detach()
        if hasattr(t, "float"):
            t = t.float()
        return t.cpu().numpy()
    return np.asarray(t)


def _rope_scaling_from_hf(hf_config: Any) -> tuple[float, float, float, int] | None:
    """Map HF ``rope_scaling`` to our tuple; reject types we'd silently get
    wrong (linear/yarn/dynamic) rather than produce diverging numerics."""
    rs = getattr(hf_config, "rope_scaling", None)
    if not rs:
        return None
    rope_type = rs.get("rope_type") or rs.get("type")
    if rope_type == "default":
        return None
    if rope_type != "llama3":
        raise NotImplementedError(
            f"rope_scaling type {rope_type!r} is not supported "
            "(supported: llama3); refusing to convert with wrong RoPE"
        )
    return (
        float(rs["factor"]),
        float(rs["low_freq_factor"]),
        float(rs["high_freq_factor"]),
        int(rs["original_max_position_embeddings"]),
    )


def config_from_hf(hf_config: Any) -> LlamaConfig:
    """Map a ``transformers.LlamaConfig`` onto ours."""
    return LlamaConfig(
        rope_scaling=_rope_scaling_from_hf(hf_config),
        vocab_size=hf_config.vocab_size,
        dim=hf_config.hidden_size,
        n_layers=hf_config.num_hidden_layers,
        n_heads=hf_config.num_attention_heads,
        n_kv_heads=getattr(hf_config, "num_key_value_heads", None)
        or hf_config.num_attention_heads,
        hidden_dim=hf_config.intermediate_size,
        max_seq_len=hf_config.max_position_embeddings,
        rope_theta=getattr(hf_config, "rope_theta", 10000.0),
        norm_eps=hf_config.rms_norm_eps,
    )


def hf_state_dict_to_params(
    state_dict: Mapping[str, Any], cfg: LlamaConfig
) -> dict:
    """Convert an HF ``LlamaForCausalLM`` state dict to our params pytree.

    Accepts torch tensors or numpy arrays. Returns ``{"params": {...}}``
    ready for ``LlamaModel(cfg).apply``.
    """
    sd = {k: _to_numpy(v) for k, v in state_dict.items()}
    L = cfg.n_layers
    # Store in the config's parameter dtype: the bf16 inference path's
    # footprint/bandwidth win must survive real-checkpoint loading, not
    # just random init. (ml_dtypes, pulled in by jax, teaches numpy about
    # bfloat16.)
    import jax.numpy as jnp

    dtype = jnp.dtype(cfg.param_dtype)

    def proj(i: int, name: str) -> np.ndarray:
        return sd[f"model.layers.{i}.{name}.weight"].T.astype(dtype)

    def stack(name: str) -> np.ndarray:
        return np.stack([proj(i, name) for i in range(L)], axis=0)

    def stack_norm(name: str) -> np.ndarray:
        return np.stack(
            [
                sd[f"model.layers.{i}.{name}.weight"].astype(dtype)
                for i in range(L)
            ],
            axis=0,
        )

    embed = sd["model.embed_tokens.weight"].astype(dtype)
    # Tied embeddings (Llama-3.2 style) fall back to the input embedding.
    lm_head = sd.get("lm_head.weight", sd["model.embed_tokens.weight"])
    params = {
        "embedding": embed,
        "lm_head": lm_head.T.astype(dtype),
        "final_norm": {"scale": sd["model.norm.weight"].astype(dtype)},
        "blocks": {
            "attn": {
                "wq": {"kernel": stack("self_attn.q_proj")},
                "wk": {"kernel": stack("self_attn.k_proj")},
                "wv": {"kernel": stack("self_attn.v_proj")},
                "wo": {"kernel": stack("self_attn.o_proj")},
            },
            "attn_norm": {"scale": stack_norm("input_layernorm")},
            "mlp_norm": {"scale": stack_norm("post_attention_layernorm")},
            "mlp": {
                "w_gate": {"kernel": stack("mlp.gate_proj")},
                "w_up": {"kernel": stack("mlp.up_proj")},
                "w_down": {"kernel": stack("mlp.down_proj")},
            },
        },
    }
    return {"params": params}


def load_hf_llama(model_name_or_path: str):
    """Load an HF Llama checkpoint → (LlamaConfig, variables pytree).

    Requires ``transformers`` + ``torch``; heavyweight, call from tooling
    (e.g. a checkpoint-conversion job), not from the reconcile loop.
    """
    from transformers import AutoConfig, AutoModelForCausalLM

    hf_config = AutoConfig.from_pretrained(model_name_or_path)
    cfg = config_from_hf(hf_config)
    model = AutoModelForCausalLM.from_pretrained(model_name_or_path)
    return cfg, hf_state_dict_to_params(model.state_dict(), cfg)
