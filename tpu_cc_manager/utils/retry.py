"""Unified retry/backoff policy, deadline-bounded polling, circuit breakers.

Before this module every transient-failure path rolled its own loop: the
REST client doubled a delay with no jitter and ignored ``Retry-After``
(kubeclient/rest.py), the slice barrier and the tpuvm backend open-coded
poll/sleep loops, the manager's watch reconnect slept a fixed 5 s. A
thundering herd of node agents retrying in lockstep against a flapping
apiserver is exactly the failure mode a CC control plane must survive, so
the policy lives in ONE place with the three properties the ad-hoc loops
lacked:

- **full jitter** (AWS-style: ``uniform(0, min(cap, base·2^n))``) via an
  *injected* rng, so a pool of agents desynchronizes and tests/chaos runs
  are reproducible with a seeded rng;
- **Retry-After honoring**: a 429/503 that names its own backoff is obeyed
  (never undershot by jitter);
- **classification + budgets**: the caller says what is transient vs
  permanent (a 404 never improves; a connection reset usually does) and may
  cap the whole operation with a deadline so retries cannot eat a
  reconcile's latency SLO.

Every retry is observable: counted in
``tpu_cc_retries_total{op,reason}`` (utils/metrics.py) and annotated on
the current obs span so /tracez shows which phase burned time retrying.

:class:`CircuitBreaker` protects the two remote dependencies — the
apiserver (kubeclient/rest.py) and the host device-command path
(tpudev/tpuvm.py) — from retry storms: after ``failure_threshold``
consecutive transient failures the circuit opens and calls fail fast until
a recovery window passes; the first call after the window (half-open)
probes, and its outcome decides closed vs re-open.
"""

from __future__ import annotations

import email.utils
import logging
import random
import threading
import time
from dataclasses import dataclass, field
from typing import Callable, NamedTuple

from tpu_cc_manager.utils import locks as locks_mod

log = logging.getLogger(__name__)


class Classification(NamedTuple):
    """A classifier's verdict on one failure."""

    transient: bool
    reason: str = "error"
    # Server-directed minimum backoff (e.g. a 429's Retry-After), seconds.
    retry_after_s: float | None = None


#: Convenience verdicts for classifiers.
PERMANENT = Classification(False, "permanent")


def parse_retry_after(value: str | None) -> float | None:
    """Parse an HTTP ``Retry-After`` header: delta-seconds or HTTP-date.

    Returns seconds (clamped to >= 0) or None when absent/unparseable — an
    unparseable header must degrade to policy backoff, never crash the
    retry path that is already handling a failure.
    """
    if not value:
        return None
    value = value.strip()
    try:
        return max(0.0, float(value))
    except ValueError:
        pass
    try:
        dt = email.utils.parsedate_to_datetime(value)
    except (TypeError, ValueError):
        return None
    if dt is None:
        return None
    if dt.tzinfo is None:
        import datetime as _dt

        dt = dt.replace(tzinfo=_dt.timezone.utc)
    return max(0.0, dt.timestamp() - time.time())


def _default_metrics():
    # Imported lazily: metrics -> obs is a heavier import chain than most
    # retry.py consumers need at module-import time, and keeping retry.py
    # import-light avoids cycles (kubeclient.api may import retry).
    from tpu_cc_manager.utils import metrics as metrics_mod

    return metrics_mod.REGISTRY


def _annotate_span(op: str, reason: str, attempt: int, delay: float) -> None:
    """Record the retry on the current obs span (bounded), so /tracez
    answers "where did the reconcile's time go" when the answer is
    "re-asking a flaky apiserver"."""
    try:
        from tpu_cc_manager.obs import trace as obs_trace

        sp = obs_trace.current_span()
        if sp is None:
            return
        retries = sp.attributes.setdefault("retries", [])
        if len(retries) < 32:  # a span must not grow unboundedly
            retries.append(
                {
                    "op": op,
                    "reason": reason,
                    "attempt": attempt,
                    "delay_s": round(delay, 3),
                }
            )
    except Exception:  # noqa: BLE001 - observability must never fail a retry
        pass


@dataclass
class RetryPolicy:
    """Exponential backoff with full jitter, classification and budgets.

    ``rng``/``sleep``/``clock`` are injectable so tests and the chaos
    harness get reproducible schedules and zero wall-clock cost.
    """

    max_attempts: int = 3
    base_delay_s: float = 0.5
    max_delay_s: float = 30.0
    # Ceiling on a server-directed Retry-After: honored as a floor below
    # this, clamped above it — a misconfigured proxy saying "come back in
    # an hour" must not park a control-plane thread for an hour.
    retry_after_cap_s: float = 120.0
    # Whole-operation budget (first attempt to last), seconds; None = no cap.
    deadline_s: float | None = None
    jitter: bool = True
    rng: random.Random = field(default_factory=random.Random)
    sleep: Callable[[float], None] = time.sleep
    clock: Callable[[], float] = time.monotonic
    metrics: object | None = None

    def backoff_cap(self, attempt: int) -> float:
        """The un-jittered delay ceiling for retry number ``attempt`` (0-based)."""
        return min(self.max_delay_s, self.base_delay_s * (2.0 ** attempt))

    def delay_for(self, attempt: int, retry_after_s: float | None = None) -> float:
        """Sleep before retry ``attempt``: full jitter under the exponential
        cap, but never less than a server-directed Retry-After (itself
        clamped to ``retry_after_cap_s``)."""
        cap = self.backoff_cap(attempt)
        delay = self.rng.uniform(0.0, cap) if self.jitter else cap
        if retry_after_s is not None:
            delay = max(delay, min(retry_after_s, self.retry_after_cap_s))
        return delay

    def _record(self, op: str, reason: str, attempt: int, delay: float) -> None:
        metrics = self.metrics if self.metrics is not None else _default_metrics()
        try:
            metrics.record_retry(op, reason)
        except Exception:  # noqa: BLE001 - a metrics bug must not break retries
            pass
        _annotate_span(op, reason, attempt, delay)

    def call(
        self,
        fn: Callable[[], object],
        *,
        op: str,
        classify: Callable[[BaseException], Classification | None],
        max_attempts: int | None = None,
    ):
        """Run ``fn`` with classified retries.

        ``classify(exc)`` returns a :class:`Classification`; a permanent (or
        None) verdict re-raises immediately. The LAST failure always
        re-raises the original exception — callers keep their existing
        exception contracts (KubeApiError, TpuError, …).
        """
        attempts = max(1, max_attempts if max_attempts is not None else self.max_attempts)
        deadline = (
            self.clock() + self.deadline_s if self.deadline_s is not None else None
        )
        for attempt in range(attempts):
            try:
                return fn()
            except BaseException as e:  # noqa: BLE001 - classifier decides
                verdict = classify(e)
                if verdict is None or not verdict.transient:
                    raise
                if attempt == attempts - 1:
                    raise
                delay = self.delay_for(attempt, verdict.retry_after_s)
                if deadline is not None and self.clock() + delay > deadline:
                    log.warning(
                        "retry budget exhausted for %s after %d attempt(s) "
                        "(deadline %.1fs): %s",
                        op, attempt + 1, self.deadline_s, e,
                    )
                    raise
                log.warning(
                    "transient failure in %s (attempt %d/%d, reason=%s): %s — "
                    "retrying in %.2fs",
                    op, attempt + 1, attempts, verdict.reason, e, delay,
                )
                self._record(op, verdict.reason, attempt + 1, delay)
                self.sleep(delay)
        raise AssertionError("unreachable")  # loop always returns or raises


def poll_until(
    predicate: Callable[[], bool],
    timeout_s: float,
    interval_s: float,
    *,
    sleep: Callable[[float], None] = time.sleep,
    clock: Callable[[], float] = time.monotonic,
) -> bool:
    """Deadline-bounded polling: the one shape every "wait for X" loop in
    the control plane shares (slice barrier, drain pod-wait, runtime
    wait-ready, rollout await). Calls ``predicate`` immediately, then every
    ``interval_s`` until it returns truthy (-> True) or the deadline passes
    (-> False). Never sleeps past the deadline.
    """
    deadline = clock() + timeout_s
    while True:
        if predicate():
            return True
        remaining = deadline - clock()
        if remaining <= 0:
            return False
        sleep(min(interval_s, remaining))


def wait(delay_s: float, stop: "threading.Event | None" = None) -> bool:
    """The one sanctioned bare wait outside this module (cclint's
    ``waits`` checker forbids direct ``time.sleep`` elsewhere): sleep
    ``delay_s``, stop-aware when the caller has a stop event. Returns
    True when ``stop`` was set during the wait — the caller should wind
    down instead of continuing its loop."""
    delay_s = max(0.0, delay_s)
    if stop is not None:
        return stop.wait(delay_s)
    time.sleep(delay_s)
    return False


# ---------------------------------------------------------------------------
# Circuit breaker
# ---------------------------------------------------------------------------

BREAKER_CLOSED = "closed"
BREAKER_OPEN = "open"
BREAKER_HALF_OPEN = "half_open"


class CircuitOpenError(Exception):
    """The breaker is open: the dependency failed repeatedly and the
    recovery window has not passed — fail fast instead of piling on."""

    def __init__(self, name: str, retry_in_s: float):
        super().__init__(
            f"circuit {name!r} open; next probe allowed in {retry_in_s:.1f}s"
        )
        self.name = name
        self.retry_in_s = retry_in_s


class CircuitBreaker:
    """Closed / open / half-open breaker, thread-safe.

    Callers bracket the protected call:

        breaker.before_call()            # raises CircuitOpenError when open
        try:    result = do_the_call()
        except TransientThing:  breaker.record_failure(); raise
        else:   breaker.record_success()

    Only *transient* failures should be recorded — a 404 says nothing about
    the dependency's health. State changes are exported via
    ``metrics.set_breaker_state`` (``tpu_cc_breaker_state{path}``).
    """

    def __init__(
        self,
        name: str,
        failure_threshold: int = 10,
        recovery_time_s: float = 30.0,
        clock: Callable[[], float] = time.monotonic,
        metrics: object | None = None,
    ) -> None:
        self.name = name
        self.failure_threshold = max(1, failure_threshold)
        self.recovery_time_s = recovery_time_s
        self.clock = clock
        self._metrics = metrics
        self._lock = locks_mod.make_lock(f"retry.breaker.{name}")
        self._state = BREAKER_CLOSED
        self._consecutive_failures = 0
        self._opened_at = 0.0
        self._probe_in_flight = False
        self._probe_started_at = 0.0
        self._export()

    @property
    def state(self) -> str:
        with self._lock:
            self._maybe_half_open_locked()
            return self._state

    def _export(self) -> None:
        metrics = self._metrics if self._metrics is not None else _default_metrics()
        try:
            metrics.set_breaker_state(self.name, self._state)
        except Exception:  # noqa: BLE001 - metrics must never break the breaker
            pass

    def _maybe_half_open_locked(self) -> None:
        if (
            self._state == BREAKER_OPEN
            and self.clock() - self._opened_at >= self.recovery_time_s
        ):
            self._state = BREAKER_HALF_OPEN
            self._probe_in_flight = False
            self._export()

    def before_call(self) -> None:
        """Gate a call: raises :class:`CircuitOpenError` when the circuit is
        open (or half-open with the single probe already in flight)."""
        with self._lock:
            self._maybe_half_open_locked()
            if self._state == BREAKER_OPEN:
                raise CircuitOpenError(
                    self.name,
                    max(0.0, self._opened_at + self.recovery_time_s - self.clock()),
                )
            if self._state == BREAKER_HALF_OPEN:
                # A probe whose outcome was never recorded (caller died, or
                # failed with an exception its classifier had no verdict
                # for) must not wedge the breaker half-open forever: the
                # probe slot is a LEASE that expires after the recovery
                # window, after which the next caller takes over as probe.
                if (
                    self._probe_in_flight
                    and self.clock() - self._probe_started_at
                    < self.recovery_time_s
                ):
                    raise CircuitOpenError(
                        self.name,
                        max(
                            0.0,
                            self._probe_started_at
                            + self.recovery_time_s
                            - self.clock(),
                        ),
                    )
                self._probe_in_flight = True  # this caller IS the probe
                self._probe_started_at = self.clock()

    def record_success(self) -> None:
        with self._lock:
            changed = self._state != BREAKER_CLOSED
            self._state = BREAKER_CLOSED
            self._consecutive_failures = 0
            self._probe_in_flight = False
            if changed:
                log.info("circuit %s closed (dependency recovered)", self.name)
                self._export()

    def record_permanent(self) -> None:
        """The call failed for a reason that says nothing about the
        dependency's health (bad input, missing binary): release a held
        half-open probe slot without moving the state machine, so the next
        caller can probe instead of waiting out the lease."""
        with self._lock:
            self._probe_in_flight = False

    def record_failure(self) -> None:
        with self._lock:
            self._consecutive_failures += 1
            if self._state == BREAKER_HALF_OPEN or (
                self._state == BREAKER_CLOSED
                and self._consecutive_failures >= self.failure_threshold
            ):
                self._state = BREAKER_OPEN
                self._opened_at = self.clock()
                self._probe_in_flight = False
                log.warning(
                    "circuit %s OPEN after %d consecutive transient failure(s); "
                    "failing fast for %.0fs",
                    self.name, self._consecutive_failures, self.recovery_time_s,
                )
                self._export()
