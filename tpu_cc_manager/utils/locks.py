"""Opt-in runtime lock-order checker (``CC_LOCKCHECK=1``).

The static side of the lock contract (cclint's ``locks`` checker) proves
annotated fields are only touched under their lock; it cannot prove the
locks themselves are acquired in a consistent ORDER. A deadlock needs
two threads taking two locks in opposite orders — rare, timing-dependent,
and invisible to tests that happen not to interleave. This module makes
the inversion itself the failure, deterministically:

- Threaded modules create locks through :func:`make_lock` /
  :func:`make_rlock` with a stable name. With ``CC_LOCKCHECK`` unset
  (production) that returns a plain ``threading.Lock`` — zero overhead.
- With ``CC_LOCKCHECK=1`` (the chaos suites set it) every acquisition
  records the per-thread held stack and adds held→acquired edges to a
  process-wide order graph. An acquisition whose edge would close a
  cycle raises :class:`LockOrderError` **immediately, on the first
  inverted pair** — no deadlock, no timing, just the two chains that
  disagree.

Re-entrant acquisition (RLock) adds no self-edge. The checker's own
internal lock is a leaf by construction (nothing is acquired inside it).
"""

from __future__ import annotations

import os
import threading

LOCKCHECK_ENV = "CC_LOCKCHECK"


class LockOrderError(BaseException):
    """Two locks were acquired in opposite orders by (possibly) different
    threads — a deadlock waiting for the right interleaving.

    Derives from ``BaseException`` (like the chaos harness's modeled
    SIGKILL) on purpose: the agent is full of broad ``except Exception``
    resilience paths ("never fails a reconcile"), and an inversion report
    swallowed-and-retried by one of them would defeat the checker. A
    BaseException escapes them all and fails the suite deterministically."""


def lockcheck_enabled() -> bool:
    return os.environ.get(LOCKCHECK_ENV, "").lower() in ("1", "true", "yes")


class _OrderGraph:
    """Process-wide directed graph of observed lock orderings.

    Edge A→B = "A was held while B was acquired". Adding an edge that
    makes B reach A (a cycle) is the inversion; the error message carries
    both chains.
    """

    def __init__(self) -> None:
        self._mu = threading.Lock()
        # lock name -> set of names acquired while it was held.
        self._edges: dict[str, set[str]] = {}  # cclint: guarded-by(_mu)
        self._held = threading.local()

    def held_stack(self) -> "list[CheckedLock]":
        stack = getattr(self._held, "stack", None)
        if stack is None:
            stack = []
            self._held.stack = stack
        return stack

    def _path_locked(self, src: str, dst: str) -> list[str] | None:  # cclint: requires(_mu)
        """A directed path src→…→dst in the edge graph, or None."""
        stack = [(src, [src])]
        seen = {src}
        while stack:
            node, path = stack.pop()
            if node == dst:
                return path
            for nxt in sorted(self._edges.get(node, ())):
                if nxt not in seen:
                    seen.add(nxt)
                    stack.append((nxt, path + [nxt]))
        return None

    def note_acquire(self, lock: "CheckedLock") -> None:
        """Record edges held→lock; raise on a cycle-forming inversion.

        The path check and edge insertion happen under ONE critical
        section: two threads racing the actual deadlock interleaving
        (T1 holds A acquiring B, T2 holds B acquiring A) must not both
        snapshot an edge set that contains neither edge — whichever
        thread inserts second sees the first thread's edge and raises.
        """
        name = lock.name
        stack = self.held_stack()
        with self._mu:
            for held in stack:
                if held is lock:
                    if lock.reentrant:
                        continue  # re-entrant (RLock) re-acquisition
                    raise LockOrderError(
                        f"self-deadlock: re-acquiring non-reentrant lock "
                        f"{name!r} on the same thread"
                    )
                if held.name == name:
                    # A DIFFERENT instance sharing the name (per-node
                    # backends in a fleet test): a name-keyed graph
                    # cannot represent cross-instance order without a
                    # false self-cycle, so no edge is recorded.
                    continue
                # Would held→name close a cycle? Only if name already
                # reaches held.
                path = self._path_locked(name, held.name)
                if path is not None:
                    raise LockOrderError(
                        f"lock order inversion: acquiring {name!r} while "
                        f"holding {held.name!r}, but the order "
                        f"{' -> '.join(path)} was already observed — "
                        "two threads taking these in opposite orders will "
                        "deadlock"
                    )
                self._edges.setdefault(held.name, set()).add(name)
        stack.append(lock)

    def note_release(self, lock: "CheckedLock") -> None:
        stack = self.held_stack()
        # Remove the LAST occurrence (re-entrant releases unwind inner
        # first).
        for i in range(len(stack) - 1, -1, -1):
            if stack[i] is lock:
                del stack[i]
                return

    def reset(self) -> None:
        """Tests only: drop all observed orderings."""
        with self._mu:
            self._edges.clear()


#: Process-wide graph shared by every checked lock.
GRAPH = _OrderGraph()


class CheckedLock:
    """A ``threading.Lock``/``RLock`` wrapper that reports acquisitions
    to the order graph. Context-manager and acquire/release compatible."""

    def __init__(self, name: str, reentrant: bool = False) -> None:
        self.name = name
        self.reentrant = reentrant
        self._inner = threading.RLock() if reentrant else threading.Lock()

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        # Order is recorded BEFORE blocking: the inversion must surface
        # even when (especially when) the acquisition would deadlock.
        GRAPH.note_acquire(self)
        got = self._inner.acquire(blocking, timeout)
        if not got:
            GRAPH.note_release(self)
        return got

    def release(self) -> None:
        self._inner.release()
        GRAPH.note_release(self)

    def __enter__(self) -> "CheckedLock":
        self.acquire()
        return self

    def __exit__(self, *exc) -> None:
        self.release()

    def locked(self) -> bool:
        inner_locked = getattr(self._inner, "locked", None)
        return bool(inner_locked()) if inner_locked is not None else False


def make_lock(name: str) -> "threading.Lock | CheckedLock":
    """A mutex for ``name`` — plain ``threading.Lock`` normally, a
    :class:`CheckedLock` under ``CC_LOCKCHECK=1``."""
    if lockcheck_enabled():
        return CheckedLock(name)
    return threading.Lock()


def make_rlock(name: str) -> "threading.RLock | CheckedLock":
    """Re-entrant variant of :func:`make_lock`."""
    if lockcheck_enabled():
        return CheckedLock(name, reentrant=True)
    return threading.RLock()
