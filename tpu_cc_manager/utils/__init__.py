"""Shared utilities: structured logging and phase-latency metrics."""
