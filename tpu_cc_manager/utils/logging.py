"""Logging setup.

Reference analogue: main.py:53-58 (stdout logging with asctime/name/level).
Improvement: optional JSON log lines (one object per line) so GKE's logging
agent ingests structured fields without a parser config. Every line emitted
inside a trace (obs/trace.py) carries ``trace_id``/``span_id``, so log
search correlates a drain handshake with the reset/attest it triggered.
"""

from __future__ import annotations

import json
import logging
import sys
import time

from tpu_cc_manager.obs import trace as obs_trace


class JsonFormatter(logging.Formatter):
    """Render each record as a single JSON object on one line."""

    def format(self, record: logging.LogRecord) -> str:
        out = {
            "ts": round(time.time(), 3),
            "severity": record.levelname,
            "logger": record.name,
            "message": record.getMessage(),
        }
        # format() runs on the emitting thread, so the contextvar still
        # names the span the log call happened under.
        span = obs_trace.current_span()
        if span is not None:
            out["trace_id"] = span.trace_id
            out["span_id"] = span.span_id
        if record.exc_info:
            out["exc"] = self.formatException(record.exc_info)
        extra = getattr(record, "fields", None)
        if isinstance(extra, dict):
            out.update(extra)
        return json.dumps(out)


def setup_logging(debug: bool = False, json_lines: bool = False) -> None:
    """Configure root logging to stdout; idempotent."""
    root = logging.getLogger()
    root.setLevel(logging.DEBUG if debug else logging.INFO)
    for h in list(root.handlers):
        root.removeHandler(h)
    handler = logging.StreamHandler(sys.stdout)
    if json_lines:
        handler.setFormatter(JsonFormatter())
    else:
        handler.setFormatter(
            logging.Formatter("%(asctime)s - %(name)s - %(levelname)s - %(message)s")
        )
    root.addHandler(handler)
