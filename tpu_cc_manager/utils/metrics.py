"""Phase-latency metrics.

The reference has no instrumentation beyond log lines (SURVEY.md §5), but the
north-star metric for this build is a latency — per-node drain→CC-on→ready
< 90 s (BASELINE.md) — so every reconcile phase is timed here and the timings
are exported both as structured log lines and programmatically (bench.py and
the Prometheus text endpoint read them).

Each phase is also traced: :meth:`ReconcileMetrics.phase` opens a span
(obs/trace.py) named after the phase, so the phase record, the log line,
and the journal entry all carry the reconcile's ``trace_id``. Latencies
accumulate into fixed-bucket histograms (``tpu_cc_phase_seconds_bucket``)
rather than only sum/count pairs, because the <90 s SLO is a tail question
— a mean cannot say whether one in ten drains blows the budget.
"""

from __future__ import annotations

import contextlib
import logging
import time
from dataclasses import dataclass, field

from tpu_cc_manager.obs import trace as obs_trace
from tpu_cc_manager.utils import locks as locks_mod

log = logging.getLogger(__name__)

# Canonical phase names, in pipeline order.
PHASE_DRAIN = "drain"
PHASE_STAGE = "stage"
PHASE_BARRIER = "barrier"
PHASE_RESET = "reset"
PHASE_WAIT_READY = "wait_ready"
PHASE_ATTEST = "attest"
PHASE_SMOKE = "smoke"
PHASE_READMIT = "readmit"

# Fixed histogram buckets (seconds), chosen around the <90 s SLO: fine
# resolution under a second for the control-plane-only phases, then the
# decision points an operator actually asks about (30 s reset, 60 s, the
# 90 s budget itself, and the 300 s timeouts). +Inf is implicit.
HISTOGRAM_BUCKETS = (
    0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 20.0, 30.0, 45.0,
    60.0, 90.0, 120.0, 300.0,
)

# Serving request-latency buckets (seconds): inference latencies live
# orders of magnitude below the reconcile phases — ms-scale resolution
# at the bottom, the checkpoint-bounce tail (~100-500 ms in SERVE_r01)
# in the middle, and multi-second outliers at the top. Fixed like
# HISTOGRAM_BUCKETS so fleet-wide aggregation never mixes bucket sets.
SERVE_HISTOGRAM_BUCKETS = (
    0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0,
)


def _escape_label_value(value: str) -> str:
    """Prometheus text-exposition label-value escaping: backslash, double
    quote, and newline must be escaped or a hostile/odd mode or phase
    string corrupts the whole scrape."""
    return (
        str(value)
        .replace("\\", r"\\")
        .replace('"', r"\"")
        .replace("\n", r"\n")
    )


def _labels(**kv: str) -> str:
    """Render a label set with escaped values, keys in given order."""
    return (
        "{"
        + ",".join(f'{k}="{_escape_label_value(v)}"' for k, v in kv.items())
        + "}"
    )


def _bucket_le(bound: float) -> str:
    return "%g" % bound


@dataclass
class PhaseRecord:
    name: str
    start: float
    end: float = 0.0
    ok: bool = True
    # Correlation with the reconcile's span tree (obs/trace.py); set by
    # ReconcileMetrics.phase from the span it opens.
    trace_id: str | None = None
    span_id: str | None = None

    @property
    def seconds(self) -> float:
        return max(0.0, self.end - self.start)


@dataclass
class ReconcileMetrics:
    """Timings for one reconcile (one desired-mode application)."""

    mode: str
    start: float = field(default_factory=time.monotonic)
    end: float = 0.0
    phases: list[PhaseRecord] = field(default_factory=list)
    result: str = "pending"  # pending | ok | failed | noop
    trace_id: str | None = None
    # Set by MetricsRegistry.start(); finish() folds this reconcile into the
    # registry's cumulative counters (which survive the bounded history).
    registry: "MetricsRegistry | None" = field(
        default=None, repr=False, compare=False
    )

    @contextlib.contextmanager
    def phase(self, name: str):
        rec = PhaseRecord(name=name, start=time.monotonic())
        with obs_trace.span(name, phase=name, mode=self.mode) as sp:
            rec.trace_id, rec.span_id = sp.trace_id, sp.span_id
            if self.trace_id is None:
                self.trace_id = sp.trace_id
            try:
                yield rec
            except BaseException:
                rec.ok = False
                raise
            finally:
                rec.end = time.monotonic()
                sp.set_attribute("ok", rec.ok)
                self.phases.append(rec)
                log.info(
                    "phase %s finished in %.2fs (ok=%s)",
                    name,
                    rec.seconds,
                    rec.ok,
                    extra={"fields": {"phase": name, "seconds": round(rec.seconds, 3), "ok": rec.ok}},
                )

    def finish(self, result: str) -> None:
        self.end = time.monotonic()
        self.result = result
        if self.registry is not None:
            self.registry._accumulate(self)
        log.info(
            "reconcile mode=%s result=%s total=%.2fs phases=%s",
            self.mode,
            result,
            self.total_seconds,
            {p.name: round(p.seconds, 2) for p in self.phases},
            extra={
                "fields": {
                    "mode": self.mode,
                    "result": result,
                    "total_seconds": round(self.total_seconds, 3),
                }
            },
        )

    @property
    def total_seconds(self) -> float:
        end = self.end if self.end else time.monotonic()
        return max(0.0, end - self.start)

    def phase_seconds(self, name: str) -> float:
        return sum(p.seconds for p in self.phases if p.name == name)


class MetricsRegistry:
    """Process-wide registry of reconcile metrics (thread-safe).

    Backs the Prometheus text endpoint and bench.py. The reference exposes no
    metrics endpoint (SURVEY.md §5) — this is a deliberate addition.
    """

    def __init__(self) -> None:
        self._lock = locks_mod.make_lock("metrics.registry")
        self._history: list[ReconcileMetrics] = []  # cclint: guarded-by(_lock)
        # Cumulative counters (unbounded lifetime, unlike the history): a
        # scraper that misses a reconcile still sees its latency in the
        # totals — last-reconcile gauges alone lose data between scrapes.
        self._result_totals: dict[str, int] = {}  # cclint: guarded-by(_lock)
        self._phase_totals: dict[tuple[str, str], list[float]] = {}  # cclint: guarded-by(_lock)
        # (mode, phase) -> per-bucket cumulative-style counts; index i is
        # observations <= HISTOGRAM_BUCKETS[i], the final slot is +Inf.
        self._phase_hist: dict[tuple[str, str], list[int]] = {}  # cclint: guarded-by(_lock)
        # Machine-readable failure reasons (CCManager._failure_reason and
        # the pre-apply failure paths), keyed exactly as the failed.reason
        # node label is.
        self._failure_totals: dict[str, int] = {}  # cclint: guarded-by(_lock)
        # (op, reason) -> retries through the shared policy (utils/retry.py).
        self._retry_totals: dict[tuple[str, str], int] = {}  # cclint: guarded-by(_lock)
        # Circuit breaker states by path name ("apiserver", "device-cmd").
        self._breaker_states: dict[str, str] = {}  # cclint: guarded-by(_lock)
        # Runtime-health watchdog: active probe tier + last probe verdict.
        self._health_tier: tuple[str, int] | None = None  # cclint: guarded-by(_lock)
        self._runtime_healthy: bool | None = None  # cclint: guarded-by(_lock)
        # Failure containment (ccmanager/remediation.py): whether this node
        # is quarantined, ladder actions by (step, outcome), and how many
        # slice barriers were aborted with a fencing generation.
        self._quarantined: bool | None = None  # cclint: guarded-by(_lock)
        self._remediation_totals: dict[tuple[str, str], int] = {}  # cclint: guarded-by(_lock)
        self._barrier_fenced_total = 0  # cclint: guarded-by(_lock)
        # Crash-safe rollout orchestration (ccmanager/rollout_state.py):
        # resumes from a persisted record, lease acquisitions/takeovers,
        # and writes refused because the lease was lost (fencing).
        self._rollout_resumes_total = 0  # cclint: guarded-by(_lock)
        self._rollout_lease_transitions_total = 0  # cclint: guarded-by(_lock)
        self._rollout_fenced_writes_total = 0  # cclint: guarded-by(_lock)
        # Federated rollouts (ccmanager/federation.py): parent-record
        # syncs by outcome (ok / fenced), hierarchical fences by reason
        # (parent-generation / parent-aborted), and the global budget
        # spend size this shard last observed on the parent.
        self._federation_sync_totals: dict[str, int] = {}  # cclint: guarded-by(_lock)
        self._federation_fence_totals: dict[str, int] = {}  # cclint: guarded-by(_lock)
        self._federation_budget_spent: int | None = None  # cclint: guarded-by(_lock)
        # Parent-plane partition tolerance: how long the current parent
        # blackout has lasted (0 when connected), this shard's escrowed
        # budget slice, and how much dark spend is pending reconciliation.
        self._federation_offline_seconds: float | None = None  # cclint: guarded-by(_lock)
        self._federation_escrow_reserved: int | None = None  # cclint: guarded-by(_lock)
        self._federation_escrow_spent: int | None = None  # cclint: guarded-by(_lock)
        # Apiserver-outage autonomy (ccmanager/intent_journal.py): live
        # connectivity, how long the current outage has lasted, intent-
        # journal replays by outcome, and deferred label patches.
        self._apiserver_connected: bool | None = None  # cclint: guarded-by(_lock)
        self._offline_seconds: float | None = None  # cclint: guarded-by(_lock)
        self._journal_replay_totals: dict[str, int] = {}  # cclint: guarded-by(_lock)
        self._deferred_patch_total = 0  # cclint: guarded-by(_lock)
        # Fleet churn (preemption fast-drain + autoscaler interplay):
        # preemption notices handled by outcome (handoff / clean /
        # resumed / handoff-failed), mid-rollout node adoptions, and how
        # long the last fast drain took against its hard deadline.
        self._preemption_totals: dict[str, int] = {}  # cclint: guarded-by(_lock)
        self._node_adoptions_total = 0  # cclint: guarded-by(_lock)
        self._fast_drain_seconds: float | None = None  # cclint: guarded-by(_lock)
        # Pipelined transitions (ccmanager/manager.py): how many seconds
        # the most recent reconcile saved by overlapping phases (sum of
        # phase latencies minus reconcile wall time, floored at 0), and
        # smoke fast-path decisions by outcome (hit = smoke skipped on an
        # unchanged verified digest, miss = digest changed so the full
        # smoke ran, cold = no verified digest on record yet).
        self._phase_overlap_seconds: float | None = None  # cclint: guarded-by(_lock)
        self._smoke_fastpath_totals: dict[str, int] = {}  # cclint: guarded-by(_lock)
        # Client-side apiserver request accounting by verb (get / list /
        # watch / patch / create / update / delete): every HTTP round
        # trip RestKube performs, retries included. The fleet-scale
        # question this answers: is this process O(changes) against the
        # apiserver (watch-driven informer cache) or O(pool) (re-listing
        # per decision)?
        self._apiserver_request_totals: dict[str, int] = {}  # cclint: guarded-by(_lock)
        # Live serving telemetry (tpu_cc_serve_* families; serve/ +
        # obs/slo.py): per-node request-latency histogram (+_sum), queue
        # depth and in-flight gauges, request outcomes
        # (completed/bounced/requeued), requests lost (the zero-loss
        # headline), goodput, and the windowed SLO readout the
        # latency-gated rollout will poll.
        self._serve_hist: dict[str, list[int]] = {}  # cclint: guarded-by(_lock)
        self._serve_hist_sum: dict[str, float] = {}  # cclint: guarded-by(_lock)
        self._serve_queue_depth: dict[str, int] = {}  # cclint: guarded-by(_lock)
        self._serve_inflight: dict[str, int] = {}  # cclint: guarded-by(_lock)
        # Capacity-ledger inputs (obs/fleet.py headroom): per-node HBM
        # bandwidth utilization (the serve driver's ladder signal) and
        # whether a spare pre-stage is in flight on this agent — both
        # read by the fleet gateway to judge per-node serving headroom.
        self._serve_hbm_bw_util: dict[str, float] = {}  # cclint: guarded-by(_lock)
        self._prestage_in_progress: bool | None = None  # cclint: guarded-by(_lock)
        self._serve_outcome_totals: dict[tuple[str, str], int] = {}  # cclint: guarded-by(_lock)
        self._serve_lost_total = 0  # cclint: guarded-by(_lock)
        self._serve_deadline_miss_totals: dict[str, int] = {}  # cclint: guarded-by(_lock)
        self._serve_offered_rps: float | None = None  # cclint: guarded-by(_lock)
        self._rollout_slo_pauses_total = 0  # cclint: guarded-by(_lock)
        self._serve_goodput: float | None = None  # cclint: guarded-by(_lock)
        # window_s -> (p99_s or None, burn_rate)
        self._serve_slo: dict[float, tuple[float | None, float]] = {}  # cclint: guarded-by(_lock)
        # Zero-bounce flips (serve/ handoff + ccmanager prestage): parked
        # requests migrated to a peer at drain time by outcome (accepted
        # = a peer took them inside the ack window; fallback = no
        # accepting peer, local requeue), and how long the most recent
        # spare pre-staging (annotation-driven full flip + warmup ahead
        # of the rollout wave) took.
        self._serve_handoff_totals: dict[str, int] = {}  # cclint: guarded-by(_lock)
        self._spare_prestage_seconds: float | None = None  # cclint: guarded-by(_lock)
        # Continuous prestage (ccmanager/rolling.py capacity ledger,
        # record v7): how many regular nodes are in prestage transition
        # right now (reserved or armed — held costs nothing), the
        # allowance the headroom gate last granted, and lifecycle
        # outcomes (reserved/armed/held/converged/invalidated/degraded/
        # paused/aborted/failed) as a labeled counter.
        self._prestage_reserved: int | None = None  # cclint: guarded-by(_lock)
        self._prestage_headroom_nodes: int | None = None  # cclint: guarded-by(_lock)
        self._prestage_totals: dict[str, int] = {}  # cclint: guarded-by(_lock)
        # Fail-slow vetting (tpu_cc_failslow_* families; obs/failslow.py):
        # per-node suspicion flag and last peer-relative deviation ratio
        # (node window median / fleet median — 1.0 is "moving with the
        # fleet"), plus concluded verdicts (confirmed/cleared) as a
        # labeled counter. The gray-failure readout: a node can be deep
        # in suspicion here while every watchdog probe stays green.
        self._failslow_suspect: dict[str, bool] = {}  # cclint: guarded-by(_lock)
        self._failslow_deviation: dict[str, float] = {}  # cclint: guarded-by(_lock)
        self._failslow_verdict_totals: dict[tuple[str, str], int] = {}  # cclint: guarded-by(_lock)

    def start(self, mode: str) -> ReconcileMetrics:
        m = ReconcileMetrics(mode=mode, registry=self)
        with self._lock:
            self._history.append(m)
            # Bound memory: keep the last 256 reconciles.
            if len(self._history) > 256:
                del self._history[: len(self._history) - 256]
        return m

    def observe_phase(self, mode: str, phase: str, seconds: float) -> None:
        """Fold one phase latency into the cumulative histogram."""
        with self._lock:
            hist = self._phase_hist.setdefault(
                (mode, phase), [0] * (len(HISTOGRAM_BUCKETS) + 1)
            )
            for i, bound in enumerate(HISTOGRAM_BUCKETS):
                if seconds <= bound:
                    hist[i] += 1
            hist[-1] += 1  # +Inf

    def record_failure(self, reason: str) -> None:
        """Count a failed reconcile by machine-readable reason (the same
        string the failed.reason node label carries)."""
        with self._lock:
            self._failure_totals[reason] = self._failure_totals.get(reason, 0) + 1

    def record_retry(self, op: str, reason: str) -> None:
        """Count one retry through the shared policy (utils/retry.py)."""
        with self._lock:
            key = (op, reason)
            self._retry_totals[key] = self._retry_totals.get(key, 0) + 1

    def retry_totals(self) -> dict[tuple[str, str], int]:
        with self._lock:
            return dict(self._retry_totals)

    def set_breaker_state(self, name: str, state: str) -> None:
        with self._lock:
            self._breaker_states[name] = state

    def breaker_states(self) -> dict[str, str]:
        with self._lock:
            return dict(self._breaker_states)

    def set_health_tier(self, tier: str, strength: int, healthy: bool) -> None:
        """Record the runtime-health watchdog's active probe tier (strength
        is the tier's rank — device-node existence being the weakest) and
        the latest probe verdict."""
        with self._lock:
            self._health_tier = (tier, strength)
            self._runtime_healthy = healthy

    def health_tier(self) -> tuple[str, int] | None:
        with self._lock:
            return self._health_tier

    def set_quarantined(self, quarantined: bool) -> None:
        """Record this node's quarantine state (remediation ladder)."""
        with self._lock:
            self._quarantined = bool(quarantined)

    def record_remediation_step(self, step: str, outcome: str) -> None:
        """Count one remediation-ladder action by step and outcome
        (``ok`` / ``failed`` / ``escalated``)."""
        with self._lock:
            key = (step, outcome)
            self._remediation_totals[key] = (
                self._remediation_totals.get(key, 0) + 1
            )

    def remediation_totals(self) -> dict[tuple[str, str], int]:
        with self._lock:
            return dict(self._remediation_totals)

    def record_barrier_fenced(self) -> None:
        """Count one slice-barrier fence event (a barrier round aborted
        with a new fencing generation so peers fail fast)."""
        with self._lock:
            self._barrier_fenced_total += 1

    def record_rollout_resume(self) -> None:
        """Count one rollout resumed from a persisted record (a successor
        picking up a dead orchestrator's checkpoint)."""
        with self._lock:
            self._rollout_resumes_total += 1

    def record_lease_transition(self) -> None:
        """Count one rollout-lease acquisition/takeover (the fencing
        token increments with each)."""
        with self._lock:
            self._rollout_lease_transitions_total += 1

    def record_fenced_write(self) -> None:
        """Count one write REFUSED because the rollout lease was lost
        (a stale orchestrator's patch stopped by the fence)."""
        with self._lock:
            self._rollout_fenced_writes_total += 1

    def record_federation_sync(self, outcome: str) -> None:
        """Count one regional shard's wave-boundary exchange with the
        federated parent record by outcome (``ok`` / ``fenced``)."""
        with self._lock:
            self._federation_sync_totals[outcome] = (
                self._federation_sync_totals.get(outcome, 0) + 1
            )

    def record_federation_fence(self, reason: str) -> None:
        """Count one hierarchical fence refusal by reason
        (``parent-generation`` after a force-abort bumped the parent,
        ``parent-aborted`` when the whole federation was discarded)."""
        with self._lock:
            self._federation_fence_totals[reason] = (
                self._federation_fence_totals.get(reason, 0) + 1
            )

    def set_federation_budget_spent(self, count: int) -> None:
        """Record the GLOBAL failure-budget spend size (distinct node
        names charged across every region) this shard last read off the
        parent record."""
        with self._lock:
            self._federation_budget_spent = max(0, int(count))

    def set_federation_offline_seconds(self, seconds: float) -> None:
        """Record how long the current PARENT-plane blackout has lasted
        for this regional shard (0 when the last parent sync landed)."""
        with self._lock:
            self._federation_offline_seconds = max(0.0, float(seconds))

    def set_federation_escrow(self, reserved: int, spent: int) -> None:
        """Record this shard's escrow ledger: the budget slice reserved
        on the parent for autonomous degraded-mode spending, and how
        many dark charges are pending reconciliation against it (0 once
        a reconnect sync union-merges them into the global ledger)."""
        with self._lock:
            self._federation_escrow_reserved = max(0, int(reserved))
            self._federation_escrow_spent = max(0, int(spent))

    def set_apiserver_connected(self, connected: bool) -> None:
        """Record whether the last apiserver interaction succeeded (the
        disconnected-mode ladder's outward signal)."""
        with self._lock:
            self._apiserver_connected = bool(connected)

    def set_offline_seconds(self, seconds: float) -> None:
        """Record how long the current total apiserver outage has lasted
        (0 when connected)."""
        with self._lock:
            self._offline_seconds = max(0.0, seconds)

    def record_journal_replay(self, outcome: str) -> None:
        """Count one intent-journal replay resolution by outcome
        (``completed`` / ``rolled-back`` / ``clean`` / ``failed-closed``)."""
        with self._lock:
            self._journal_replay_totals[outcome] = (
                self._journal_replay_totals.get(outcome, 0) + 1
            )

    def record_deferred_patch(self) -> None:
        """Count one node-label write deferred into the intent journal
        because the apiserver was unreachable."""
        with self._lock:
            self._deferred_patch_total += 1

    def journal_replay_totals(self) -> dict[str, int]:
        with self._lock:
            return dict(self._journal_replay_totals)

    def record_preemption(self, outcome: str) -> None:
        """Count one handled preemption notice by outcome: ``handoff``
        (mid-flip, handoff record published for the replacement),
        ``clean`` (no transition in flight), ``handoff-failed`` (the
        publish itself failed before the kill), ``resumed`` (this agent
        consumed a predecessor's handoff and completed the flip)."""
        with self._lock:
            self._preemption_totals[outcome] = (
                self._preemption_totals.get(outcome, 0) + 1
            )

    def preemption_totals(self) -> dict[str, int]:
        with self._lock:
            return dict(self._preemption_totals)

    def record_node_adoption(self, count: int = 1) -> None:
        """Count nodes created mid-rollout (autoscaler scale-up) that the
        orchestrator adopted into a trailing wave."""
        with self._lock:
            self._node_adoptions_total += count

    def node_adoptions_total(self) -> int:
        with self._lock:
            return self._node_adoptions_total

    def set_fast_drain_seconds(self, seconds: float) -> None:
        """Record how long the most recent preemption fast-drain took
        (checkpoint handshake + compressed eviction, against the hard
        termination deadline)."""
        with self._lock:
            self._fast_drain_seconds = max(0.0, seconds)

    def set_phase_overlap_seconds(self, seconds: float) -> None:
        """Record how many seconds the most recent reconcile saved by
        running phases concurrently (pipelined transitions): the sum of
        its phase latencies minus its wall time, floored at 0."""
        with self._lock:
            self._phase_overlap_seconds = max(0.0, seconds)

    def record_smoke_fastpath(self, outcome: str) -> None:
        """Count one attestation-digest smoke fast-path decision by
        outcome (``hit`` / ``miss`` / ``cold``; ccmanager/manager.py,
        CC_SMOKE_DIGEST_FAST_PATH)."""
        with self._lock:
            self._smoke_fastpath_totals[outcome] = (
                self._smoke_fastpath_totals.get(outcome, 0) + 1
            )

    def smoke_fastpath_totals(self) -> dict[str, int]:
        with self._lock:
            return dict(self._smoke_fastpath_totals)

    def record_apiserver_request(self, verb: str) -> None:
        """Count one apiserver HTTP round trip by verb (kubeclient)."""
        with self._lock:
            self._apiserver_request_totals[verb] = (
                self._apiserver_request_totals.get(verb, 0) + 1
            )

    def apiserver_request_totals(self) -> dict[str, int]:
        with self._lock:
            return dict(self._apiserver_request_totals)

    # -- live serving telemetry (serve/, obs/slo.py) -----------------------

    def observe_serve_request(self, node: str, seconds: float) -> None:
        """Fold one completed request's end-to-end latency (bounces
        included — the latency the user saw) into the per-node serve
        histogram."""
        with self._lock:
            hist = self._serve_hist.setdefault(
                node, [0] * (len(SERVE_HISTOGRAM_BUCKETS) + 1)
            )
            for i, bound in enumerate(SERVE_HISTOGRAM_BUCKETS):
                if seconds <= bound:
                    hist[i] += 1
            hist[-1] += 1  # +Inf
            self._serve_hist_sum[node] = (
                self._serve_hist_sum.get(node, 0.0) + max(0.0, seconds)
            )

    def set_serve_queue_depth(self, node: str, depth: int) -> None:
        """Requests queued (accepted, not yet executing) on a node."""
        with self._lock:
            self._serve_queue_depth[node] = max(0, int(depth))

    def set_serve_inflight(self, node: str, inflight: int) -> None:
        """Requests in the executing batch on a node."""
        with self._lock:
            self._serve_inflight[node] = max(0, int(inflight))

    def set_serve_hbm_bw_util(self, node: str, util: float) -> None:
        """Last observed HBM bandwidth utilization (0..1) on a node —
        the serve driver's batch-ladder signal, exported so the fleet
        capacity ledger can judge headroom against its ceiling."""
        with self._lock:
            self._serve_hbm_bw_util[node] = min(1.0, max(0.0, float(util)))

    def set_prestage_in_progress(self, in_progress: bool) -> None:
        """Whether a spare pre-stage (annotation-driven full flip +
        warmup ahead of a rollout wave) is currently running on this
        agent. A prestaging node is warming, not serving headroom."""
        with self._lock:
            self._prestage_in_progress = bool(in_progress)

    def record_serve_outcome(
        self, node: str, outcome: str, count: int = 1
    ) -> None:
        """Count request dispositions per node: ``completed`` (finished
        and returned), ``bounced`` (checkpoint-and-requeued by a drain
        bracket, progress intact), ``requeued`` (returned unsubmitted
        after losing the submit race with a drain)."""
        with self._lock:
            key = (node, outcome)
            self._serve_outcome_totals[key] = (
                self._serve_outcome_totals.get(key, 0) + count
            )

    def record_serve_lost(self, count: int = 1) -> None:
        """Count requests that never completed after traffic stopped
        and the grace drain expired — the zero-loss headline's counter
        (not per-node: a lost request by definition has no owner)."""
        with self._lock:
            self._serve_lost_total += count

    def record_serve_deadline_miss(self, node: str, count: int = 1) -> None:
        """Count ACCEPTED requests that completed past their deadline —
        the broken promise, separate from ``outcome=shed`` (the counted,
        deliberate refusal at intake)."""
        with self._lock:
            self._serve_deadline_miss_totals[node] = (
                self._serve_deadline_miss_totals.get(node, 0) + count
            )

    def set_serve_offered_rps(self, rps: float) -> None:
        """Open-loop offered (scheduled) arrival rate — the load the
        pool was asked to absorb, independent of what it completed."""
        with self._lock:
            self._serve_offered_rps = max(0.0, rps)

    def record_serve_handoff(self, outcome: str, count: int = 1) -> None:
        """Count parked requests a draining node's drain bracket handed
        to the driver's migration sink, by outcome: ``accepted`` (an
        accepting peer took them inside the ack window — the zero-bounce
        path) or ``fallback`` (no accepting peer; requeued locally,
        today's behavior)."""
        with self._lock:
            self._serve_handoff_totals[outcome] = (
                self._serve_handoff_totals.get(outcome, 0) + count
            )

    def serve_handoff_totals(self) -> dict[str, int]:
        with self._lock:
            return dict(self._serve_handoff_totals)

    def set_spare_prestage_seconds(self, seconds: float) -> None:
        """Record how long the most recent spare pre-staging took — the
        annotation-driven full flip + compile warmup a surge spare runs
        BEFORE the rollout wave that needs it opens (ccmanager/manager.py
        prestage; the wave then converges in ~drain+readmit time)."""
        with self._lock:
            self._spare_prestage_seconds = max(0.0, seconds)

    def record_slo_pause(self) -> None:
        """Count one SLO-gate pause of a rolling rollout's next wave
        (ccmanager/rolling.py wave boundaries)."""
        with self._lock:
            self._rollout_slo_pauses_total += 1

    def set_prestage_reserved(self, count: int) -> None:
        """Gauge: capacity-ledger entries currently in prestage
        TRANSITION (reserved or armed — a held entry's node is serving
        at the target mode and costs no headroom), maintained by the
        rolling orchestrator's continuous-prestage pass."""
        with self._lock:
            self._prestage_reserved = max(0, int(count))

    def set_prestage_headroom_nodes(self, count: int) -> None:
        """Gauge: the prestage allowance the headroom gate last granted
        — whole nodes of slack under the serving knee, capped at
        max_unavailable (serve.sweep.knee_slack_nodes). Zero while the
        gate fails closed or offered load fills the knee."""
        with self._lock:
            self._prestage_headroom_nodes = max(0, int(count))

    def record_prestage(self, outcome: str) -> None:
        """Count one continuous-prestage lifecycle step by outcome:
        ``reserved``/``armed``/``held`` (the happy path), ``converged``
        (charge settled at the flip window), ``invalidated`` (stale
        plan digest), ``degraded`` (prestage-path failure downgraded
        the node to the full flip), ``paused`` (SLO burn skipped a
        top-up) and ``aborted``/``failed`` (terminal drains)."""
        with self._lock:
            self._prestage_totals[outcome] = (
                self._prestage_totals.get(outcome, 0) + 1
            )

    def prestage_totals(self) -> dict[str, int]:
        with self._lock:
            return dict(self._prestage_totals)

    def set_serve_goodput(self, rps: float) -> None:
        """Completed-requests-per-second over the SLO window."""
        with self._lock:
            self._serve_goodput = max(0.0, rps)

    def set_serve_slo(
        self, window_s: float, p99_s: float | None, burn_rate: float
    ) -> None:
        """Record one SLO window's readout (obs/slo.py): rolling p99
        (None while the window is empty — no sample beats a fake one)
        and error-budget burn rate."""
        with self._lock:
            self._serve_slo[float(window_s)] = (p99_s, burn_rate)

    def set_failslow_suspect(self, node: str, suspect: bool) -> None:
        """Whether peer-relative fail-slow vetting (obs/failslow.py)
        currently suspects this node of a gray failure (>= 1 strike or
        confirmed). 0/1 gauge per node."""
        with self._lock:
            self._failslow_suspect[node] = bool(suspect)

    def set_failslow_deviation(self, node: str, deviation: float) -> None:
        """Last vetting window's peer-relative deviation ratio for this
        node (window median / fleet median-of-medians): 1.0 moves with
        the fleet, the confirm threshold defaults to 2.0."""
        with self._lock:
            self._failslow_deviation[node] = max(0.0, float(deviation))

    def record_failslow_verdict(self, node: str, verdict: str) -> None:
        """Count one concluded fail-slow verdict for a node:
        ``confirmed`` (sustained deviation beyond the threshold for
        min_windows consecutive windows — feeds the remediation ladder)
        or ``cleared`` (recovered below the clear threshold for
        clear_windows consecutive windows — suspicion lifted)."""
        with self._lock:
            key = (node, verdict)
            self._failslow_verdict_totals[key] = (
                self._failslow_verdict_totals.get(key, 0) + 1
            )

    def failslow_totals(self) -> dict:
        with self._lock:
            return {
                "suspects": dict(self._failslow_suspect),
                "deviation": dict(self._failslow_deviation),
                "verdicts": dict(self._failslow_verdict_totals),
            }

    def serve_totals(self) -> dict:
        with self._lock:
            return {
                "outcomes": dict(self._serve_outcome_totals),
                "lost": self._serve_lost_total,
                "deadline_misses": dict(self._serve_deadline_miss_totals),
                "offered_rps": self._serve_offered_rps,
                "queue_depth": dict(self._serve_queue_depth),
                "inflight": dict(self._serve_inflight),
                "goodput_rps": self._serve_goodput,
                "slo": dict(self._serve_slo),
                "handoffs": dict(self._serve_handoff_totals),
            }

    def rollout_totals(self) -> dict[str, int]:
        with self._lock:
            return {
                "resumes": self._rollout_resumes_total,
                "lease_transitions": self._rollout_lease_transitions_total,
                "fenced_writes": self._rollout_fenced_writes_total,
                "slo_pauses": self._rollout_slo_pauses_total,
            }

    def _accumulate(self, m: ReconcileMetrics) -> None:
        with self._lock:
            self._result_totals[m.result] = self._result_totals.get(m.result, 0) + 1
            for p in m.phases:
                tot = self._phase_totals.setdefault((m.mode, p.name), [0.0, 0])
                tot[0] += p.seconds
                tot[1] += 1
        for p in m.phases:
            self.observe_phase(m.mode, p.name, p.seconds)
        if m.phases:
            # Pipelined transitions: phases that ran concurrently sum to
            # more than the reconcile's wall time; the difference is the
            # overlap the pipeline saved (0 when fully serialized).
            self.set_phase_overlap_seconds(
                sum(p.seconds for p in m.phases) - m.total_seconds
            )

    def result_totals(self) -> dict[str, int]:
        with self._lock:
            return dict(self._result_totals)

    def failure_totals(self) -> dict[str, int]:
        with self._lock:
            return dict(self._failure_totals)

    @property
    def history(self) -> list[ReconcileMetrics]:
        with self._lock:
            return list(self._history)

    def last(self) -> ReconcileMetrics | None:
        with self._lock:
            return self._history[-1] if self._history else None

    def render_prometheus(self) -> str:
        """Render the registry in Prometheus text exposition format."""
        lines = [
            "# HELP tpu_cc_reconcile_seconds Total seconds for the most recent reconcile.",
            "# TYPE tpu_cc_reconcile_seconds gauge",
        ]
        last = self.last()
        if last is not None:
            lines.append(
                "tpu_cc_reconcile_seconds%s %.3f"
                % (_labels(mode=last.mode, result=last.result), last.total_seconds)
            )
            lines.append("# HELP tpu_cc_last_phase_seconds Seconds per phase of the most recent reconcile.")
            lines.append("# TYPE tpu_cc_last_phase_seconds gauge")
            for p in last.phases:
                lines.append(
                    "tpu_cc_last_phase_seconds%s %.3f"
                    % (
                        _labels(mode=last.mode, phase=p.name, ok=str(p.ok).lower()),
                        p.seconds,
                    )
                )
        lines.append("# HELP tpu_cc_reconciles_total Reconciles since process start.")
        lines.append("# TYPE tpu_cc_reconciles_total counter")
        with self._lock:
            result_totals = dict(self._result_totals)
            phase_totals = {k: list(v) for k, v in self._phase_totals.items()}
            phase_hist = {k: list(v) for k, v in self._phase_hist.items()}
            failure_totals = dict(self._failure_totals)
            retry_totals = dict(self._retry_totals)
            breaker_states = dict(self._breaker_states)
            health_tier = self._health_tier
            runtime_healthy = self._runtime_healthy
            quarantined = self._quarantined
            remediation_totals = dict(self._remediation_totals)
            barrier_fenced_total = self._barrier_fenced_total
            rollout_resumes = self._rollout_resumes_total
            rollout_transitions = self._rollout_lease_transitions_total
            rollout_fenced = self._rollout_fenced_writes_total
            federation_syncs = dict(self._federation_sync_totals)
            federation_fences = dict(self._federation_fence_totals)
            federation_budget_spent = self._federation_budget_spent
            federation_offline_seconds = self._federation_offline_seconds
            federation_escrow_reserved = self._federation_escrow_reserved
            federation_escrow_spent = self._federation_escrow_spent
            apiserver_connected = self._apiserver_connected
            offline_seconds = self._offline_seconds
            journal_replays = dict(self._journal_replay_totals)
            deferred_patches = self._deferred_patch_total
            apiserver_requests = dict(self._apiserver_request_totals)
            preemption_totals = dict(self._preemption_totals)
            node_adoptions = self._node_adoptions_total
            fast_drain_seconds = self._fast_drain_seconds
            phase_overlap_seconds = self._phase_overlap_seconds
            smoke_fastpath_totals = dict(self._smoke_fastpath_totals)
            serve_hist = {k: list(v) for k, v in self._serve_hist.items()}
            serve_hist_sum = dict(self._serve_hist_sum)
            serve_queue_depth = dict(self._serve_queue_depth)
            serve_inflight = dict(self._serve_inflight)
            serve_hbm_bw_util = dict(self._serve_hbm_bw_util)
            prestage_in_progress = self._prestage_in_progress
            serve_outcomes = dict(self._serve_outcome_totals)
            serve_lost = self._serve_lost_total
            serve_deadline_misses = dict(self._serve_deadline_miss_totals)
            serve_offered = self._serve_offered_rps
            rollout_slo_pauses = self._rollout_slo_pauses_total
            serve_goodput = self._serve_goodput
            serve_slo = dict(self._serve_slo)
            serve_handoffs = dict(self._serve_handoff_totals)
            spare_prestage_seconds = self._spare_prestage_seconds
            prestage_reserved = self._prestage_reserved
            prestage_headroom = self._prestage_headroom_nodes
            prestage_totals = dict(self._prestage_totals)
            failslow_suspect = dict(self._failslow_suspect)
            failslow_deviation = dict(self._failslow_deviation)
            failslow_verdicts = dict(self._failslow_verdict_totals)
        for result in ("ok", "failed", "noop"):
            lines.append(
                "tpu_cc_reconciles_total%s %d"
                % (_labels(result=result), result_totals.get(result, 0))
            )
        lines.append(
            "# HELP tpu_cc_failures_total Failed reconciles by machine-"
            "readable reason (the failed.reason node label)."
        )
        lines.append("# TYPE tpu_cc_failures_total counter")
        for reason in sorted(failure_totals):
            lines.append(
                "tpu_cc_failures_total%s %d"
                % (_labels(reason=reason), failure_totals[reason])
            )
        if retry_totals:
            lines.append(
                "# HELP tpu_cc_retries_total Retries through the shared "
                "backoff policy (utils/retry.py), by operation and reason."
            )
            lines.append("# TYPE tpu_cc_retries_total counter")
            for (op, reason), count in sorted(retry_totals.items()):
                lines.append(
                    "tpu_cc_retries_total%s %d"
                    % (_labels(op=op, reason=reason), count)
                )
        if breaker_states:
            lines.append(
                "# HELP tpu_cc_breaker_state Circuit breaker state per "
                "dependency path (0=closed, 1=half_open, 2=open)."
            )
            lines.append("# TYPE tpu_cc_breaker_state gauge")
            state_value = {"closed": 0, "half_open": 1, "open": 2}
            for name in sorted(breaker_states):
                lines.append(
                    "tpu_cc_breaker_state%s %d"
                    % (
                        _labels(path=name),
                        state_value.get(breaker_states[name], 2),
                    )
                )
        if health_tier is not None:
            tier, strength = health_tier
            lines.append(
                "# HELP tpu_cc_health_probe_tier Active runtime-health probe "
                "tier; the value is the tier's strength rank (higher = "
                "stronger signal; 1 = bare device-node existence)."
            )
            lines.append("# TYPE tpu_cc_health_probe_tier gauge")
            lines.append(
                "tpu_cc_health_probe_tier%s %d" % (_labels(tier=tier), strength)
            )
        if runtime_healthy is not None:
            lines.append(
                "# HELP tpu_cc_runtime_healthy Last watchdog probe verdict "
                "(1 = healthy)."
            )
            lines.append("# TYPE tpu_cc_runtime_healthy gauge")
            lines.append(
                "tpu_cc_runtime_healthy %d" % (1 if runtime_healthy else 0)
            )
        if quarantined is not None:
            lines.append(
                "# HELP tpu_cc_quarantined Whether this node is quarantined "
                "by the remediation ladder (1 = quarantined)."
            )
            lines.append("# TYPE tpu_cc_quarantined gauge")
            lines.append("tpu_cc_quarantined %d" % (1 if quarantined else 0))
        if remediation_totals:
            lines.append(
                "# HELP tpu_cc_remediation_step_total Remediation-ladder "
                "actions by step and outcome (ccmanager/remediation.py)."
            )
            lines.append("# TYPE tpu_cc_remediation_step_total counter")
            for (step, outcome), count in sorted(remediation_totals.items()):
                lines.append(
                    "tpu_cc_remediation_step_total%s %d"
                    % (_labels(step=step, outcome=outcome), count)
                )
        if barrier_fenced_total:
            lines.append(
                "# HELP tpu_cc_barrier_fenced_total Slice barrier rounds "
                "aborted with a fencing generation (peers fail fast "
                "instead of burning the barrier deadline)."
            )
            lines.append("# TYPE tpu_cc_barrier_fenced_total counter")
            lines.append(
                "tpu_cc_barrier_fenced_total %d" % barrier_fenced_total
            )
        if rollout_resumes or rollout_transitions or rollout_fenced:
            lines.append(
                "# HELP tpu_cc_rollout_resumes_total Pool rollouts resumed "
                "from a persisted record (a successor picking up a dead "
                "orchestrator's checkpoint)."
            )
            lines.append("# TYPE tpu_cc_rollout_resumes_total counter")
            lines.append(
                "tpu_cc_rollout_resumes_total %d" % rollout_resumes
            )
            lines.append(
                "# HELP tpu_cc_rollout_lease_transitions_total Rollout-"
                "lease acquisitions/takeovers (the fencing token "
                "increments with each)."
            )
            lines.append("# TYPE tpu_cc_rollout_lease_transitions_total counter")
            lines.append(
                "tpu_cc_rollout_lease_transitions_total %d"
                % rollout_transitions
            )
            lines.append(
                "# HELP tpu_cc_rollout_fenced_writes_total Writes refused "
                "because the rollout lease was lost (stale orchestrator "
                "stopped by the fence)."
            )
            lines.append("# TYPE tpu_cc_rollout_fenced_writes_total counter")
            lines.append(
                "tpu_cc_rollout_fenced_writes_total %d" % rollout_fenced
            )
        if federation_syncs:
            lines.append(
                "# HELP tpu_cc_federation_syncs_total Regional shard "
                "exchanges with the federated parent record by outcome "
                "(ok / fenced; ccmanager/federation.py)."
            )
            lines.append("# TYPE tpu_cc_federation_syncs_total counter")
            for outcome in sorted(federation_syncs):
                lines.append(
                    "tpu_cc_federation_syncs_total%s %d"
                    % (_labels(outcome=outcome), federation_syncs[outcome])
                )
        if federation_fences:
            lines.append(
                "# HELP tpu_cc_federation_fences_total Hierarchical fence "
                "refusals by reason (parent-generation after a force-"
                "abort, parent-aborted when the federation was discarded)."
            )
            lines.append("# TYPE tpu_cc_federation_fences_total counter")
            for reason in sorted(federation_fences):
                lines.append(
                    "tpu_cc_federation_fences_total%s %d"
                    % (_labels(reason=reason), federation_fences[reason])
                )
        if federation_budget_spent is not None:
            lines.append(
                "# HELP tpu_cc_federation_budget_spent Global failure-"
                "budget spend (distinct node names charged across every "
                "region) this shard last read off the parent record."
            )
            lines.append("# TYPE tpu_cc_federation_budget_spent gauge")
            lines.append(
                "tpu_cc_federation_budget_spent %d" % federation_budget_spent
            )
        if federation_offline_seconds is not None:
            lines.append(
                "# HELP tpu_cc_federation_offline_seconds How long the "
                "current PARENT-plane blackout has lasted for this "
                "regional shard (0 when the last parent sync landed; "
                "degraded mode engages past CC_FEDERATION_OFFLINE_GRACE_S)."
            )
            lines.append("# TYPE tpu_cc_federation_offline_seconds gauge")
            lines.append(
                "tpu_cc_federation_offline_seconds %.3f"
                % federation_offline_seconds
            )
        if federation_escrow_reserved is not None:
            lines.append(
                "# HELP tpu_cc_federation_escrow_reserved This shard's "
                "escrowed slice of the global failure budget — what it "
                "may charge autonomously while the parent plane is dark."
            )
            lines.append("# TYPE tpu_cc_federation_escrow_reserved gauge")
            lines.append(
                "tpu_cc_federation_escrow_reserved %d"
                % federation_escrow_reserved
            )
        if federation_escrow_spent is not None:
            lines.append(
                "# HELP tpu_cc_federation_escrow_spent Dark charges "
                "pending reconciliation against the escrowed slice (0 "
                "once a reconnect sync union-merges them into the global "
                "ledger)."
            )
            lines.append("# TYPE tpu_cc_federation_escrow_spent gauge")
            lines.append(
                "tpu_cc_federation_escrow_spent %d" % federation_escrow_spent
            )
        if apiserver_connected is not None:
            lines.append(
                "# HELP tpu_cc_apiserver_connected Whether the last "
                "apiserver interaction succeeded (0 = total outage; the "
                "disconnected-mode ladder is engaged once the outage "
                "outlasts CC_OFFLINE_GRACE_S)."
            )
            lines.append("# TYPE tpu_cc_apiserver_connected gauge")
            lines.append(
                "tpu_cc_apiserver_connected %d"
                % (1 if apiserver_connected else 0)
            )
        if offline_seconds is not None:
            lines.append(
                "# HELP tpu_cc_offline_seconds How long the current total "
                "apiserver outage has lasted (0 when connected)."
            )
            lines.append("# TYPE tpu_cc_offline_seconds gauge")
            lines.append("tpu_cc_offline_seconds %.3f" % offline_seconds)
        if journal_replays:
            lines.append(
                "# HELP tpu_cc_journal_replays_total Intent-journal replay "
                "resolutions by outcome (completed / rolled-back / clean / "
                "failed-closed; ccmanager/intent_journal.py)."
            )
            lines.append("# TYPE tpu_cc_journal_replays_total counter")
            for outcome in sorted(journal_replays):
                lines.append(
                    "tpu_cc_journal_replays_total%s %d"
                    % (_labels(outcome=outcome), journal_replays[outcome])
                )
        if deferred_patches:
            lines.append(
                "# HELP tpu_cc_journal_deferred_patches_total Node-label "
                "writes deferred into the intent journal while the "
                "apiserver was unreachable (flushed on reconnect)."
            )
            lines.append(
                "# TYPE tpu_cc_journal_deferred_patches_total counter"
            )
            lines.append(
                "tpu_cc_journal_deferred_patches_total %d" % deferred_patches
            )
        if preemption_totals:
            lines.append(
                "# HELP tpu_cc_preemptions_total Platform preemption "
                "notices handled, by outcome (handoff / clean / resumed / "
                "handoff-failed; docs/operations.md \"Preemption, "
                "autoscaler & surge\")."
            )
            lines.append("# TYPE tpu_cc_preemptions_total counter")
            for outcome in sorted(preemption_totals):
                lines.append(
                    "tpu_cc_preemptions_total%s %d"
                    % (_labels(outcome=outcome), preemption_totals[outcome])
                )
        if node_adoptions:
            lines.append(
                "# HELP tpu_cc_node_adoptions_total Nodes created mid-"
                "rollout (autoscaler scale-up) adopted into a trailing "
                "rollout wave."
            )
            lines.append("# TYPE tpu_cc_node_adoptions_total counter")
            lines.append(
                "tpu_cc_node_adoptions_total %d" % node_adoptions
            )
        if fast_drain_seconds is not None:
            lines.append(
                "# HELP tpu_cc_fast_drain_seconds Duration of the most "
                "recent preemption fast-drain (checkpoint handshake + "
                "compressed eviction) against the hard termination "
                "deadline."
            )
            lines.append("# TYPE tpu_cc_fast_drain_seconds gauge")
            lines.append(
                "tpu_cc_fast_drain_seconds %.3f" % fast_drain_seconds
            )
        if phase_overlap_seconds is not None:
            lines.append(
                "# HELP tpu_cc_phase_overlap_seconds Seconds the most "
                "recent reconcile saved by overlapping phases (sum of "
                "phase latencies minus wall time; pipelined transitions)."
            )
            lines.append("# TYPE tpu_cc_phase_overlap_seconds gauge")
            lines.append(
                "tpu_cc_phase_overlap_seconds %.3f" % phase_overlap_seconds
            )
        if smoke_fastpath_totals:
            lines.append(
                "# HELP tpu_cc_smoke_fastpath_total Attestation-digest "
                "smoke fast-path decisions by outcome (hit = smoke "
                "skipped on an unchanged verified digest, miss = digest "
                "changed so the full smoke ran, cold = no digest on "
                "record; CC_SMOKE_DIGEST_FAST_PATH)."
            )
            lines.append("# TYPE tpu_cc_smoke_fastpath_total counter")
            for outcome in sorted(smoke_fastpath_totals):
                lines.append(
                    "tpu_cc_smoke_fastpath_total%s %d"
                    % (_labels(outcome=outcome), smoke_fastpath_totals[outcome])
                )
        if apiserver_requests:
            lines.append(
                "# HELP tpu_cc_apiserver_requests_total Apiserver HTTP "
                "round trips by verb (kubeclient; retries included — the "
                "QPS the server actually absorbs)."
            )
            lines.append("# TYPE tpu_cc_apiserver_requests_total counter")
            for verb in sorted(apiserver_requests):
                lines.append(
                    "tpu_cc_apiserver_requests_total%s %d"
                    % (_labels(verb=verb), apiserver_requests[verb])
                )
        if serve_hist:
            lines.append(
                "# HELP tpu_cc_serve_request_seconds End-to-end serving "
                "request latency per node (submission to completion, "
                "checkpoint bounces included — what the user saw)."
            )
            lines.append("# TYPE tpu_cc_serve_request_seconds histogram")
            for node in sorted(serve_hist):
                hist = serve_hist[node]
                for i, bound in enumerate(SERVE_HISTOGRAM_BUCKETS):
                    lines.append(
                        "tpu_cc_serve_request_seconds_bucket%s %d"
                        % (_labels(node=node, le=_bucket_le(bound)), hist[i])
                    )
                lines.append(
                    "tpu_cc_serve_request_seconds_bucket%s %d"
                    % (_labels(node=node, le="+Inf"), hist[-1])
                )
                lines.append(
                    "tpu_cc_serve_request_seconds_sum%s %.6f"
                    % (_labels(node=node), serve_hist_sum.get(node, 0.0))
                )
                lines.append(
                    "tpu_cc_serve_request_seconds_count%s %d"
                    % (_labels(node=node), hist[-1])
                )
        if serve_queue_depth:
            lines.append(
                "# HELP tpu_cc_serve_queue_depth Requests accepted but "
                "not yet executing on a node."
            )
            lines.append("# TYPE tpu_cc_serve_queue_depth gauge")
            for node in sorted(serve_queue_depth):
                lines.append(
                    "tpu_cc_serve_queue_depth%s %d"
                    % (_labels(node=node), serve_queue_depth[node])
                )
        if serve_inflight:
            lines.append(
                "# HELP tpu_cc_serve_inflight Requests in the executing "
                "batch on a node."
            )
            lines.append("# TYPE tpu_cc_serve_inflight gauge")
            for node in sorted(serve_inflight):
                lines.append(
                    "tpu_cc_serve_inflight%s %d"
                    % (_labels(node=node), serve_inflight[node])
                )
        if serve_hbm_bw_util:
            lines.append(
                "# HELP tpu_cc_hbm_bw_util Last observed HBM bandwidth "
                "utilization (0..1) per node — the serve driver's batch-"
                "ladder signal; the fleet capacity ledger judges headroom "
                "against its ceiling."
            )
            lines.append("# TYPE tpu_cc_hbm_bw_util gauge")
            for node in sorted(serve_hbm_bw_util):
                lines.append(
                    "tpu_cc_hbm_bw_util%s %.6f"
                    % (_labels(node=node), serve_hbm_bw_util[node])
                )
        if prestage_in_progress is not None:
            lines.append(
                "# HELP tpu_cc_prestage_in_progress Whether a spare pre-"
                "stage (annotation-driven flip + warmup ahead of a rollout "
                "wave) is running on this agent (1) or not (0) — a "
                "prestaging node is warming, not serving headroom."
            )
            lines.append("# TYPE tpu_cc_prestage_in_progress gauge")
            lines.append(
                "tpu_cc_prestage_in_progress %d"
                % (1 if prestage_in_progress else 0)
            )
        if serve_outcomes:
            lines.append(
                "# HELP tpu_cc_serve_requests_total Serving request "
                "dispositions per node: completed, bounced (checkpoint-"
                "and-requeued by a drain with progress intact), requeued "
                "(returned unsubmitted after losing the submit race)."
            )
            lines.append("# TYPE tpu_cc_serve_requests_total counter")
            for (node, outcome), count in sorted(serve_outcomes.items()):
                lines.append(
                    "tpu_cc_serve_requests_total%s %d"
                    % (_labels(node=node, outcome=outcome), count)
                )
        if serve_lost:
            lines.append(
                "# HELP tpu_cc_serve_lost_total Requests that never "
                "completed after traffic stopped and the grace drain "
                "expired (the zero-loss serving contract's violation "
                "counter)."
            )
            lines.append("# TYPE tpu_cc_serve_lost_total counter")
            lines.append("tpu_cc_serve_lost_total %d" % serve_lost)
        if serve_deadline_misses:
            lines.append(
                "# HELP tpu_cc_serve_deadline_miss_total Accepted "
                "requests that completed past their deadline, per node "
                "(separate from outcome=shed — the deliberate refusal at "
                "intake; a miss is the broken promise)."
            )
            lines.append("# TYPE tpu_cc_serve_deadline_miss_total counter")
            for node in sorted(serve_deadline_misses):
                lines.append(
                    "tpu_cc_serve_deadline_miss_total%s %d"
                    % (_labels(node=node), serve_deadline_misses[node])
                )
        if serve_offered is not None:
            lines.append(
                "# HELP tpu_cc_serve_offered_rps Open-loop offered "
                "(scheduled) arrival rate — the load the pool was asked "
                "to absorb, which goodput is judged against."
            )
            lines.append("# TYPE tpu_cc_serve_offered_rps gauge")
            lines.append("tpu_cc_serve_offered_rps %.3f" % serve_offered)
        if serve_handoffs:
            lines.append(
                "# HELP tpu_cc_serve_handoffs_total Parked in-flight "
                "requests a draining node handed to the driver's "
                "migration sink, by outcome (accepted = re-dispatched "
                "to an accepting peer inside the ack window; fallback = "
                "no accepting peer, local requeue)."
            )
            lines.append("# TYPE tpu_cc_serve_handoffs_total counter")
            for outcome in sorted(serve_handoffs):
                lines.append(
                    "tpu_cc_serve_handoffs_total%s %d"
                    % (_labels(outcome=outcome), serve_handoffs[outcome])
                )
        if spare_prestage_seconds is not None:
            lines.append(
                "# HELP tpu_cc_spare_prestage_seconds Duration of the "
                "most recent spare pre-staging (annotation-driven full "
                "flip + compile warmup run ahead of the rollout wave "
                "that needs the spare)."
            )
            lines.append("# TYPE tpu_cc_spare_prestage_seconds gauge")
            lines.append(
                "tpu_cc_spare_prestage_seconds %.3f" % spare_prestage_seconds
            )
        if rollout_slo_pauses:
            lines.append(
                "# HELP tpu_cc_rollout_slo_pauses_total Rollout waves "
                "paused by the SLO gate at a wave boundary (error-budget "
                "burn or p99 above target; ccmanager/rolling.py)."
            )
            lines.append("# TYPE tpu_cc_rollout_slo_pauses_total counter")
            lines.append(
                "tpu_cc_rollout_slo_pauses_total %d" % rollout_slo_pauses
            )
        if prestage_reserved is not None:
            lines.append(
                "# HELP tpu_cc_prestage_reserved Capacity-ledger entries "
                "currently in prestage transition (reserved or armed; a "
                "held entry's node serves at target mode and costs no "
                "headroom) — ccmanager/rolling.py continuous prestage."
            )
            lines.append("# TYPE tpu_cc_prestage_reserved gauge")
            lines.append("tpu_cc_prestage_reserved %d" % prestage_reserved)
        if prestage_headroom is not None:
            lines.append(
                "# HELP tpu_cc_prestage_headroom_nodes Prestage allowance "
                "the headroom gate last granted: whole nodes of slack "
                "under the serving knee, capped at max_unavailable "
                "(serve/sweep.py knee_slack_nodes)."
            )
            lines.append("# TYPE tpu_cc_prestage_headroom_nodes gauge")
            lines.append(
                "tpu_cc_prestage_headroom_nodes %d" % prestage_headroom
            )
        if prestage_totals:
            lines.append(
                "# HELP tpu_cc_prestage_total Continuous-prestage "
                "lifecycle steps by outcome (reserved/armed/held/"
                "converged/invalidated/degraded/paused/aborted) — the "
                "ledger balances when charges equal releases."
            )
            lines.append("# TYPE tpu_cc_prestage_total counter")
            for outcome in sorted(prestage_totals):
                lines.append(
                    "tpu_cc_prestage_total%s %d"
                    % (_labels(outcome=outcome), prestage_totals[outcome])
                )
        if serve_goodput is not None:
            lines.append(
                "# HELP tpu_cc_serve_goodput_rps Completed requests per "
                "second over the SLO window."
            )
            lines.append("# TYPE tpu_cc_serve_goodput_rps gauge")
            lines.append("tpu_cc_serve_goodput_rps %.3f" % serve_goodput)
        if serve_slo:
            lines.append(
                "# HELP tpu_cc_serve_slo_p99_seconds Rolling-window p99 "
                "request latency (obs/slo.py; absent while the window "
                "is empty)."
            )
            lines.append("# TYPE tpu_cc_serve_slo_p99_seconds gauge")
            p99_lines = [
                "tpu_cc_serve_slo_p99_seconds%s %.6f"
                % (_labels(window=_bucket_le(w)), p99)
                for w, (p99, _burn) in sorted(serve_slo.items())
                if p99 is not None
            ]
            lines.extend(p99_lines)
            lines.append(
                "# HELP tpu_cc_serve_error_budget_burn Error-budget burn "
                "rate over the rolling window (error rate / budget; 1.0 "
                "= spending exactly as provisioned — the halt signal a "
                "latency-gated rollout polls)."
            )
            lines.append("# TYPE tpu_cc_serve_error_budget_burn gauge")
            for w, (_p99, burn) in sorted(serve_slo.items()):
                lines.append(
                    "tpu_cc_serve_error_budget_burn%s %.6f"
                    % (_labels(window=_bucket_le(w)), burn)
                )
        if failslow_suspect:
            lines.append(
                "# HELP tpu_cc_failslow_suspect Whether peer-relative "
                "fail-slow vetting currently suspects this node of a "
                "gray failure (obs/failslow.py; 1 = >= 1 strike or "
                "confirmed — the watchdog probe can be green "
                "throughout)."
            )
            lines.append("# TYPE tpu_cc_failslow_suspect gauge")
            for node in sorted(failslow_suspect):
                lines.append(
                    "tpu_cc_failslow_suspect%s %d"
                    % (_labels(node=node), 1 if failslow_suspect[node] else 0)
                )
        if failslow_deviation:
            lines.append(
                "# HELP tpu_cc_failslow_deviation Last vetting window's "
                "peer-relative deviation ratio per node (window median "
                "/ fleet median-of-medians; 1.0 = moving with the "
                "fleet, confirm threshold defaults to 2.0)."
            )
            lines.append("# TYPE tpu_cc_failslow_deviation gauge")
            for node in sorted(failslow_deviation):
                lines.append(
                    "tpu_cc_failslow_deviation%s %.4f"
                    % (_labels(node=node), failslow_deviation[node])
                )
        if failslow_verdicts:
            lines.append(
                "# HELP tpu_cc_failslow_verdicts_total Concluded "
                "fail-slow verdicts by node and verdict (confirmed = "
                "sustained deviation, feeds the remediation ladder; "
                "cleared = recovered below the clear threshold)."
            )
            lines.append("# TYPE tpu_cc_failslow_verdicts_total counter")
            for (node, verdict), count in sorted(failslow_verdicts.items()):
                lines.append(
                    "tpu_cc_failslow_verdicts_total%s %d"
                    % (_labels(node=node, verdict=verdict), count)
                )
        # The cumulative per-phase sums/counts are served exclusively as
        # the histogram's _sum/_count series below — separate
        # tpu_cc_phase_seconds_total/_runs_total counters would duplicate
        # them AND collide with the histogram family name under
        # OpenMetrics (where a counter named X_total belongs to family X).
        lines.append(
            "# HELP tpu_cc_phase_seconds Per-phase latency histogram "
            "(fixed buckets around the 90 s SLO)."
        )
        lines.append("# TYPE tpu_cc_phase_seconds histogram")
        for (mode, phase), hist in sorted(phase_hist.items()):
            total_s = phase_totals.get((mode, phase), [0.0, 0])[0]
            for i, bound in enumerate(HISTOGRAM_BUCKETS):
                lines.append(
                    "tpu_cc_phase_seconds_bucket%s %d"
                    % (
                        _labels(mode=mode, phase=phase, le=_bucket_le(bound)),
                        hist[i],
                    )
                )
            lines.append(
                "tpu_cc_phase_seconds_bucket%s %d"
                % (_labels(mode=mode, phase=phase, le="+Inf"), hist[-1])
            )
            lines.append(
                "tpu_cc_phase_seconds_sum%s %.3f"
                % (_labels(mode=mode, phase=phase), total_s)
            )
            lines.append(
                "tpu_cc_phase_seconds_count%s %d"
                % (_labels(mode=mode, phase=phase), hist[-1])
            )
        return "\n".join(lines) + "\n"


REGISTRY = MetricsRegistry()
