"""Phase-latency metrics.

The reference has no instrumentation beyond log lines (SURVEY.md §5), but the
north-star metric for this build is a latency — per-node drain→CC-on→ready
< 90 s (BASELINE.md) — so every reconcile phase is timed here and the timings
are exported both as structured log lines and programmatically (bench.py and
the Prometheus text endpoint read them).
"""

from __future__ import annotations

import contextlib
import logging
import threading
import time
from dataclasses import dataclass, field

log = logging.getLogger(__name__)

# Canonical phase names, in pipeline order.
PHASE_DRAIN = "drain"
PHASE_STAGE = "stage"
PHASE_BARRIER = "barrier"
PHASE_RESET = "reset"
PHASE_WAIT_READY = "wait_ready"
PHASE_ATTEST = "attest"
PHASE_SMOKE = "smoke"
PHASE_READMIT = "readmit"


@dataclass
class PhaseRecord:
    name: str
    start: float
    end: float = 0.0
    ok: bool = True

    @property
    def seconds(self) -> float:
        return max(0.0, self.end - self.start)


@dataclass
class ReconcileMetrics:
    """Timings for one reconcile (one desired-mode application)."""

    mode: str
    start: float = field(default_factory=time.monotonic)
    end: float = 0.0
    phases: list[PhaseRecord] = field(default_factory=list)
    result: str = "pending"  # pending | ok | failed | noop
    # Set by MetricsRegistry.start(); finish() folds this reconcile into the
    # registry's cumulative counters (which survive the bounded history).
    registry: "MetricsRegistry | None" = field(
        default=None, repr=False, compare=False
    )

    @contextlib.contextmanager
    def phase(self, name: str):
        rec = PhaseRecord(name=name, start=time.monotonic())
        try:
            yield rec
        except BaseException:
            rec.ok = False
            raise
        finally:
            rec.end = time.monotonic()
            self.phases.append(rec)
            log.info(
                "phase %s finished in %.2fs (ok=%s)",
                name,
                rec.seconds,
                rec.ok,
                extra={"fields": {"phase": name, "seconds": round(rec.seconds, 3), "ok": rec.ok}},
            )

    def finish(self, result: str) -> None:
        self.end = time.monotonic()
        self.result = result
        if self.registry is not None:
            self.registry._accumulate(self)
        log.info(
            "reconcile mode=%s result=%s total=%.2fs phases=%s",
            self.mode,
            result,
            self.total_seconds,
            {p.name: round(p.seconds, 2) for p in self.phases},
            extra={
                "fields": {
                    "mode": self.mode,
                    "result": result,
                    "total_seconds": round(self.total_seconds, 3),
                }
            },
        )

    @property
    def total_seconds(self) -> float:
        end = self.end if self.end else time.monotonic()
        return max(0.0, end - self.start)

    def phase_seconds(self, name: str) -> float:
        return sum(p.seconds for p in self.phases if p.name == name)


class MetricsRegistry:
    """Process-wide registry of reconcile metrics (thread-safe).

    Backs the Prometheus text endpoint and bench.py. The reference exposes no
    metrics endpoint (SURVEY.md §5) — this is a deliberate addition.
    """

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._history: list[ReconcileMetrics] = []
        # Cumulative counters (unbounded lifetime, unlike the history): a
        # scraper that misses a reconcile still sees its latency in the
        # totals — last-reconcile gauges alone lose data between scrapes.
        self._result_totals: dict[str, int] = {}
        self._phase_totals: dict[tuple[str, str], list[float]] = {}

    def start(self, mode: str) -> ReconcileMetrics:
        m = ReconcileMetrics(mode=mode, registry=self)
        with self._lock:
            self._history.append(m)
            # Bound memory: keep the last 256 reconciles.
            if len(self._history) > 256:
                del self._history[: len(self._history) - 256]
        return m

    def _accumulate(self, m: ReconcileMetrics) -> None:
        with self._lock:
            self._result_totals[m.result] = self._result_totals.get(m.result, 0) + 1
            for p in m.phases:
                tot = self._phase_totals.setdefault((m.mode, p.name), [0.0, 0])
                tot[0] += p.seconds
                tot[1] += 1

    @property
    def history(self) -> list[ReconcileMetrics]:
        with self._lock:
            return list(self._history)

    def last(self) -> ReconcileMetrics | None:
        with self._lock:
            return self._history[-1] if self._history else None

    def render_prometheus(self) -> str:
        """Render the registry in Prometheus text exposition format."""
        lines = [
            "# HELP tpu_cc_reconcile_seconds Total seconds for the most recent reconcile.",
            "# TYPE tpu_cc_reconcile_seconds gauge",
        ]
        last = self.last()
        if last is not None:
            lines.append(
                'tpu_cc_reconcile_seconds{mode="%s",result="%s"} %.3f'
                % (last.mode, last.result, last.total_seconds)
            )
            lines.append("# HELP tpu_cc_phase_seconds Seconds per phase of the most recent reconcile.")
            lines.append("# TYPE tpu_cc_phase_seconds gauge")
            for p in last.phases:
                lines.append(
                    'tpu_cc_phase_seconds{mode="%s",phase="%s",ok="%s"} %.3f'
                    % (last.mode, p.name, str(p.ok).lower(), p.seconds)
                )
        lines.append("# HELP tpu_cc_reconciles_total Reconciles since process start.")
        lines.append("# TYPE tpu_cc_reconciles_total counter")
        with self._lock:
            result_totals = dict(self._result_totals)
            phase_totals = {k: list(v) for k, v in self._phase_totals.items()}
        for result in ("ok", "failed", "noop"):
            lines.append(
                'tpu_cc_reconciles_total{result="%s"} %d'
                % (result, result_totals.get(result, 0))
            )
        lines.append(
            "# HELP tpu_cc_phase_seconds_total Cumulative seconds spent per "
            "phase since process start."
        )
        lines.append("# TYPE tpu_cc_phase_seconds_total counter")
        lines.append(
            "# HELP tpu_cc_phase_runs_total Cumulative phase executions "
            "since process start."
        )
        lines.append("# TYPE tpu_cc_phase_runs_total counter")
        for (mode, phase), (seconds, count) in sorted(phase_totals.items()):
            lines.append(
                'tpu_cc_phase_seconds_total{mode="%s",phase="%s"} %.3f'
                % (mode, phase, seconds)
            )
            lines.append(
                'tpu_cc_phase_runs_total{mode="%s",phase="%s"} %d'
                % (mode, phase, count)
            )
        return "\n".join(lines) + "\n"


REGISTRY = MetricsRegistry()
