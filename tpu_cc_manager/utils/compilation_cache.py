"""Persistent XLA compilation cache for smoke workloads and the bench.

The end-to-end verify latency (the <90 s north-star, BASELINE.md) is
dominated by XLA's first compile of the smoke workload — tens of seconds on
a cold process. XLA's persistent compilation cache turns every run after the
first into a disk hit, so a node's verify phase after a CC bounce costs
milliseconds of compile instead of tens of seconds. The reference has no
analogue (its verify is a register read, SURVEY.md §3.2 phase 4); this is
the TPU-native cost of upgrading verification to a real numerical workload,
and the cache is how we keep it under the latency target.

Must be called BEFORE jax is first imported by the process (env-var config
is read at import). Opt out with TPU_CC_NO_COMPILATION_CACHE=1.
"""

from __future__ import annotations

import os
import pathlib

_ENV_DIR = "JAX_COMPILATION_CACHE_DIR"


def candidate_cache_dirs() -> list[str]:
    """Preference order: TPU_CC_CACHE_DIR override, repo-local dir, tmpdir.

    Repo-local keeps the cache on the image's writable layer next to the
    code that produced it; the tmpdir fallback matters in the distroless
    image where the site-packages tree is root-owned and the agent runs as
    nonroot (a silent no-cache there would re-pay the full XLA compile on
    every post-bounce verify)."""
    import tempfile

    repo_root = pathlib.Path(__file__).resolve().parents[2]
    candidates = [
        str(repo_root / ".jax_cache"),
        os.path.join(tempfile.gettempdir(), "tpu-cc-jax-cache"),
    ]
    override = os.environ.get("TPU_CC_CACHE_DIR")
    if override:
        # Preferred, not exclusive: an unwritable override (e.g. a hostPath
        # the kubelet created root-owned while we run nonroot) must fall
        # through to tmpdir rather than silently disabling the cache.
        candidates.insert(0, override)
    return candidates


def enable(cache_dir: str | None = None) -> str | None:
    """Point XLA's persistent cache at the first writable candidate dir.

    Returns the directory in use, or None when disabled/unwritable. Safe to
    call multiple times; an existing JAX_COMPILATION_CACHE_DIR wins.
    """
    if os.environ.get("TPU_CC_NO_COMPILATION_CACHE") == "1":
        return None
    candidates = [os.environ[_ENV_DIR]] if os.environ.get(_ENV_DIR) else []
    if cache_dir:
        candidates.append(cache_dir)
    candidates.extend(candidate_cache_dirs())
    path = None
    for candidate in candidates:
        try:
            pathlib.Path(candidate).mkdir(parents=True, exist_ok=True)
            if os.access(candidate, os.W_OK):
                path = candidate
                break
        except OSError:
            continue
    if path is None:
        return None
    os.environ[_ENV_DIR] = path
    # Cache every executable: the smoke models compile few, large programs,
    # so entry-count blowup is not a concern and misses are expensive.
    os.environ.setdefault("JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS", "0")
    os.environ.setdefault("JAX_PERSISTENT_CACHE_MIN_ENTRY_SIZE_BYTES", "-1")
    return path
