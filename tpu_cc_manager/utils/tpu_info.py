"""TPU generation detection + public peak-FLOPs table for MFU accounting.

Shared by the smoke workloads so every reported MFU uses the same
denominator. Peak numbers are the public bf16 figures per chip.
"""

from __future__ import annotations

import os

# Public peak dense bf16 TFLOP/s per chip.
PEAK_BF16_TFLOPS = {
    "v4": 275.0,
    "v5e": 197.0,
    "v5p": 459.0,
    "v6e": 918.0,
}

# Public peak HBM bandwidth GB/s per chip. Decode-style workloads are
# bandwidth-bound (every weight is read once per token), so their honest
# utilization metric is HBM-BW, not MFU.
PEAK_HBM_GBPS = {
    "v4": 1228.0,
    "v5e": 819.0,
    "v5p": 2765.0,
    "v6e": 1640.0,
}


def _normalize(gen: str) -> str | None:
    """Canonicalize a generation string ('v5litepod' → 'v5e', 'tpuv6lite'
    → 'v6e'), mirroring tpudev/tpuvm.py's accelerator-type parsing."""
    gen = gen.lower().replace("tpu", "").replace(" ", "")
    if gen.startswith("v5lite"):
        return "v5e"
    if gen.startswith("v6lite"):
        return "v6e"
    for name in PEAK_BF16_TFLOPS:
        if gen.startswith(name):
            return name
    return None


def tpu_generation() -> str | None:
    """Best-effort TPU generation: env override, else device_kind."""
    gen = os.environ.get("PALLAS_AXON_TPU_GEN") or os.environ.get(
        "TPU_ACCELERATOR_TYPE", ""
    ).split("-")[0]
    if gen:
        return _normalize(gen)
    try:
        import jax

        kind = jax.devices()[0].device_kind
    except Exception:  # noqa: BLE001 - detection is best-effort
        return None
    return _normalize(kind)


def generation_for(backend: str) -> str | None:
    """Chip generation when running on TPU, else None (smoke-result field:
    the bench artifact must carry its own denominator — a TFLOP/s number is
    only evidence next to the chip it ran on)."""
    return tpu_generation() if backend == "tpu" else None


def peak_flops_per_chip(default_tflops: float = 197.0) -> float:
    """Peak bf16 FLOP/s for MFU math; conservative default when unknown."""
    gen = tpu_generation()
    return PEAK_BF16_TFLOPS.get(gen, default_tflops) * 1e12


def peak_hbm_bytes_per_chip(default_gbps: float = 819.0) -> float:
    """Peak HBM bytes/s for bandwidth-utilization math."""
    gen = tpu_generation()
    return PEAK_HBM_GBPS.get(gen, default_gbps) * 1e9
