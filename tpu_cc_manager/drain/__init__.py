"""Drain / re-admit orchestration over the label pause protocol.

Reference analogue: gpu_operator_eviction.py (SURVEY.md §2 #8, #9). Split in
three: :mod:`pause` is the pure label algebra (unit-testable with no cluster),
:mod:`evict` performs the drain/re-admit against a KubeApi, :mod:`state`
reports actual state back through node labels.
"""

from tpu_cc_manager.drain.evict import (
    evict_components,
    fetch_component_labels,
    readmit_components,
)
from tpu_cc_manager.drain.pause import pause_value, unpause_value
from tpu_cc_manager.drain.state import set_cc_state_label

__all__ = [
    "evict_components",
    "fetch_component_labels",
    "readmit_components",
    "pause_value",
    "unpause_value",
    "set_cc_state_label",
]
