"""Pause/unpause label algebra — pure functions, no I/O.

Ported exactly from the reference's protocol (gpu_operator_eviction.py:43-95,
SURVEY.md §5 "label state machine"), because the external controller that
reacts to these labels (the TPU operator, analogue of the GPU operator)
defines them as its API:

    'true'      -> PAUSED_VALUE                  (pause)
    custom 'v'  -> 'v' + PAUSED_SUFFIX           (pause, preserving the value)
    'false'/''  -> unchanged                     (component user-disabled)
    paused      -> unchanged                     (idempotent)

and unpausing inverts exactly.
"""

from __future__ import annotations

from tpu_cc_manager.labels import PAUSED_SUFFIX, PAUSED_VALUE


def is_paused(value: str | None) -> bool:
    return value is not None and (
        value == PAUSED_VALUE or value.endswith(PAUSED_SUFFIX)
    )


def pause_value(value: str | None) -> str | None:
    """New label value when pausing, or None if the label must not change."""
    if value is None or value in ("", "false"):
        return None
    if is_paused(value):
        return None
    if value == "true":
        return PAUSED_VALUE
    return value + PAUSED_SUFFIX


def unpause_value(value: str | None) -> str | None:
    """New label value when unpausing, or None if the label must not change."""
    if value is None or not is_paused(value):
        return None
    if value == PAUSED_VALUE:
        return "true"
    return value[: -len(PAUSED_SUFFIX)]
