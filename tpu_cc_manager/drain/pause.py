"""Pause/unpause label algebra — pure functions, no I/O.

Ported exactly from the reference's protocol (gpu_operator_eviction.py:43-95,
SURVEY.md §5 "label state machine"), because the external controller that
reacts to these labels (the TPU operator, analogue of the GPU operator)
defines them as its API:

    'true'      -> PAUSED_VALUE                  (pause)
    custom 'v'  -> 'v' + PAUSED_SUFFIX           (pause, preserving the value)
    'false'/''  -> unchanged                     (component user-disabled)
    paused      -> unchanged                     (idempotent)

and unpausing inverts exactly.
"""

from __future__ import annotations

from tpu_cc_manager.labels import PAUSED_SUFFIX, PAUSED_VALUE

# k8s label values are capped at 63 characters; appending the 30-char
# suffix to a custom value longer than this would make the whole drain
# merge-patch 422 on a real apiserver — blocking the CC transition over
# one label.
MAX_LABEL_LEN = 63
_MAX_CUSTOM = MAX_LABEL_LEN - len(PAUSED_SUFFIX)


def is_paused(value: str | None) -> bool:
    return value is not None and (
        value == PAUSED_VALUE or value.endswith(PAUSED_SUFFIX)
    )


def pause_value(value: str | None) -> str | None:
    """New label value when pausing, or None if the label must not change.

    Custom values too long to carry the suffix within the 63-char label
    limit are truncated to fit: the suffix (the external operator's API —
    it is what triggers the pod deletion) is never compromised, the drain
    proceeds, and the untruncated original is restored on re-admit from
    the remembered pre-drain labels (drain/evict.py). Only a crash
    between pause and re-admit restores the truncated form. If the cut
    point exposes an embedded copy of the suffix, it is stripped too —
    the paused value must carry EXACTLY one suffix, or unpausing would
    peel a single layer and leave a value that still reads as paused."""
    if value is None or value in ("", "false"):
        return None
    if is_paused(value):
        return None
    if value == "true":
        return PAUSED_VALUE
    prefix = value[:_MAX_CUSTOM]
    while prefix.endswith(PAUSED_SUFFIX):
        prefix = prefix[: -len(PAUSED_SUFFIX)]
    return prefix + PAUSED_SUFFIX


def unpause_value(value: str | None) -> str | None:
    """New label value when unpausing, or None if the label must not change."""
    if value is None or not is_paused(value):
        return None
    if value == PAUSED_VALUE:
        return "true"
    return value[: -len(PAUSED_SUFFIX)]
