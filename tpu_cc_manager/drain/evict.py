"""Drain and re-admit TPU operator components via the pause protocol.

Reference analogue: gpu_operator_eviction.py:98-259 (SURVEY.md §2 #8). The
shape is the same — read the component deploy labels, rewrite them to their
paused values in one node patch, poll until each component's pods are gone
from this node, and invert afterwards — with two deliberate changes:

- label writes are a merge-patch of metadata.labels only (not the reference's
  racy full-object read-modify-write, SURVEY.md §8.3);
- the timeout policy is explicit: ``proceed_on_timeout=True`` preserves the
  reference's "don't fail — continue anyway" behavior
  (gpu_operator_eviction.py:205-207) but callers can demand strictness.
"""

from __future__ import annotations

import logging
import time

from tpu_cc_manager.drain import handshake
from tpu_cc_manager.drain.pause import is_paused, pause_value, unpause_value
from tpu_cc_manager.kubeclient.api import KubeApi, node_labels
from tpu_cc_manager.labels import DRAIN_COMPONENT_LABELS
from tpu_cc_manager.obs import trace as obs_trace
from tpu_cc_manager.utils import retry as retry_mod

log = logging.getLogger(__name__)

# Reference values: 300 s wait, 2 s poll (gpu_operator_eviction.py:136, :200).
DEFAULT_EVICTION_TIMEOUT_S = 300.0
DEFAULT_POLL_INTERVAL_S = 2.0

# Preemption fast-drain: the hard deadline a GCE spot/preemptible VM gets
# between the preemption notice and the kill — the whole drain (workload
# checkpoint handshake + component eviction) must fit inside it, which is
# why the fast path compresses rather than reuses the 300 s budget above.
DEFAULT_PREEMPTION_DEADLINE_S = 30.0
FAST_DRAIN_POLL_INTERVAL_S = 0.5
# Fraction of the deadline reserved for the workload checkpoint handshake
# (checkpoint-before-pause, benched at 0.55 s for the real llama job);
# the rest is the compressed pod-eviction wait.
FAST_DRAIN_ACK_FRACTION = 0.5
# Tail of the deadline the pod-eviction wait may NOT consume: the caller
# still has to publish the handoff record (and fence the slice) before
# the kill lands, and a wedged pod waiting out the whole window would
# cost exactly the publish that matters more than a clean drain.
FAST_DRAIN_PUBLISH_RESERVE_FRACTION = 0.15
FAST_DRAIN_PUBLISH_RESERVE_MAX_S = 5.0


class EvictionTimeout(Exception):
    """Raised (only when proceed_on_timeout=False) if pods outlive the wait.

    Carries the pre-drain label values so the caller can still re-admit
    the components it paused."""

    def __init__(self, msg: str, original: dict[str, str]):
        super().__init__(msg)
        self.original = original


def fetch_component_labels(api: KubeApi, node_name: str) -> dict[str, str]:
    """Current values of the drain-component labels on the node.

    Reference: fetch_current_component_labels (gpu_operator_eviction.py:98).
    Only labels actually present on the node are returned.
    """
    labels = node_labels(api.get_node(node_name))
    return {k: labels[k] for k in DRAIN_COMPONENT_LABELS if k in labels}


def evict_components(
    api: KubeApi,
    node_name: str,
    namespace: str,
    timeout_s: float = DEFAULT_EVICTION_TIMEOUT_S,
    poll_interval_s: float = DEFAULT_POLL_INTERVAL_S,
    proceed_on_timeout: bool = True,
    workload_ack_timeout_s: float = 0.0,
) -> dict[str, str]:
    """Pause every drainable component and wait for its pods to leave the node.

    When ``workload_ack_timeout_s`` > 0, the workload drain handshake runs
    FIRST: the drain request label goes up and registered training jobs get
    that long to checkpoint and ack before any component is paused
    (drain/handshake.py). The wait is bounded and lenient — a wedged job
    cannot veto a security transition — matching the reference's
    lenient-drain policy (gpu_operator_eviction.py:205-207).

    Returns the original label values (pass them to ``readmit_components``).
    Reference: evict_gpu_operator_components (gpu_operator_eviction.py:131-214).
    """
    cycle = None
    if workload_ack_timeout_s > 0:
        cycle = handshake.request_drain(api, node_name)
    try:
        return _evict_components_inner(
            api, node_name, namespace, timeout_s, poll_interval_s,
            proceed_on_timeout, workload_ack_timeout_s, cycle,
        )
    except Exception:
        # The drain-request label is up but this drain is being abandoned
        # (transport error mid-pause, strict eviction timeout, …): clear it
        # best-effort so subscribers don't stay parked until some later
        # reconcile happens to reach readmit_components.
        if cycle is not None:
            handshake.clear_drain_request(api, node_name)
        raise


def _evict_components_inner(
    api: KubeApi,
    node_name: str,
    namespace: str,
    timeout_s: float,
    poll_interval_s: float,
    proceed_on_timeout: bool,
    workload_ack_timeout_s: float,
    cycle,
) -> dict[str, str]:
    if cycle is not None and cycle.subscribers:
        # Its own span: the handshake is the part of the drain window a
        # slow-checkpointing training job owns, and the first question
        # after a blown budget is "handshake or pod eviction?".
        with obs_trace.span(
            "drain.handshake", node=node_name,
            subscribers=len(cycle.subscribers),
        ):
            handshake.await_workload_acks(
                api, node_name,
                timeout_s=workload_ack_timeout_s,
                poll_interval_s=poll_interval_s,
                token=cycle.token,
            )
    with obs_trace.span("drain.pause_components", node=node_name) as sp:
        original = fetch_component_labels(api, node_name)
        patch = {}
        for key, value in original.items():
            paused = pause_value(value)
            if paused is not None:
                patch[key] = paused
        sp.set_attribute("paused", sorted(patch))
        if patch:
            log.info("pausing components on %s: %s", node_name, sorted(patch))
            api.patch_node_labels(node_name, patch)
        else:
            log.info("no components to pause on %s", node_name)

    # Wait for the operator controller to delete each paused component's
    # pods. Components already paused by a previous (crashed) run must be
    # waited on too — their pods may still be terminating — hence "paused
    # now", not "paused by us".
    paused_now = sorted(
        key
        for key, value in {**original, **patch}.items()
        if is_paused(value)
    )
    if not paused_now:
        return original
    deadline = time.monotonic() + timeout_s
    with obs_trace.span(
        "drain.await_pods", node=node_name, components=len(paused_now)
    ) as sp:
        timed_out = []
        for key in paused_now:
            app = DRAIN_COMPONENT_LABELS[key]
            remaining = {"pods": 0}

            def component_gone(app=app, remaining=remaining) -> bool:
                pods = api.list_pods(
                    namespace,
                    label_selector=f"app={app}",
                    field_selector=f"spec.nodeName={node_name}",
                )
                remaining["pods"] = len(pods)
                return not pods

            # One shared deadline across all components (unchanged policy);
            # the per-component wait is whatever budget is left.
            if retry_mod.poll_until(
                component_gone,
                max(0.0, deadline - time.monotonic()),
                poll_interval_s,
            ):
                log.info("component %s drained from %s", app, node_name)
                continue
            msg = (
                f"timed out waiting for {remaining['pods']} pod(s) of "
                f"component {app} to leave node {node_name}"
            )
            if proceed_on_timeout:
                # Reference behavior: warn and continue to the hardware
                # phase anyway (gpu_operator_eviction.py:205-207).
                log.warning("%s — continuing anyway", msg)
                timed_out.append(app)
                continue
            raise EvictionTimeout(msg, original)
        if timed_out:
            sp.set_attribute("timed_out", timed_out)
    return original


def fast_drain_components(
    api: KubeApi,
    node_name: str,
    namespace: str,
    deadline_s: float = DEFAULT_PREEMPTION_DEADLINE_S,
    poll_interval_s: float = FAST_DRAIN_POLL_INTERVAL_S,
    workload_ack_timeout_s: float | None = None,
) -> dict[str, str]:
    """Preemption fast-drain: the SAME pause-label algebra as
    :func:`evict_components`, compressed into the platform's hard
    termination deadline.

    Ordering is the point: the workload checkpoint handshake runs FIRST
    (checkpoint-before-pause — the training job's unsaved state is the
    only thing on this node that cannot be recreated), then the
    components are paused and their pods waited on with whatever budget
    remains. The wait ALWAYS proceeds on timeout — the VM dies at the
    deadline whether or not eviction finished, and the caller still has
    the handoff record to publish.

    Deliberately never re-admits and never withdraws the drain-request
    label: this node is dying, and the replacement node's crash-recovery
    readmit (manager._readmit_leftover_paused) restores both from the
    labels the fast drain leaves behind. Returns the pre-drain label
    values like evict_components (callers that survive the notice — a
    cancelled preemption — can readmit with them)."""
    deadline = time.monotonic() + max(0.0, deadline_s)
    if workload_ack_timeout_s is None:
        workload_ack_timeout_s = deadline_s * FAST_DRAIN_ACK_FRACTION
    with obs_trace.span(
        "drain.fast", node=node_name, deadline_s=deadline_s,
    ) as sp:
        cycle = handshake.request_drain(
            api, node_name, deadline_s=deadline_s
        )
        if cycle.subscribers and workload_ack_timeout_s > 0:
            with obs_trace.span(
                "drain.handshake", node=node_name,
                subscribers=len(cycle.subscribers), fast=True,
            ):
                handshake.await_workload_acks(
                    api, node_name,
                    timeout_s=min(
                        workload_ack_timeout_s,
                        max(0.0, deadline - time.monotonic()),
                    ),
                    poll_interval_s=poll_interval_s,
                    token=cycle.token,
                )
        # The eviction wait stops short of the deadline: the tail is the
        # caller's handoff-publish (and slice-fence) window, which a
        # wedged pod must not be allowed to consume.
        publish_reserve_s = min(
            FAST_DRAIN_PUBLISH_RESERVE_MAX_S,
            deadline_s * FAST_DRAIN_PUBLISH_RESERVE_FRACTION,
        )
        original = _evict_components_inner(
            api, node_name, namespace,
            timeout_s=max(
                0.0, deadline - publish_reserve_s - time.monotonic()
            ),
            poll_interval_s=poll_interval_s,
            # The kill lands at the deadline regardless; failing here
            # would only cost the caller its handoff publish window.
            proceed_on_timeout=True,
            workload_ack_timeout_s=0.0,  # already awaited, compressed
            cycle=None,
        )
        sp.set_attribute(
            "seconds", round(deadline_s - (deadline - time.monotonic()), 3)
        )
        return original


def readmit_components(api: KubeApi, node_name: str, original: dict[str, str]) -> None:
    """Restore the pre-drain label values, unpausing what we paused.

    Reference: reschedule_gpu_operator_components
    (gpu_operator_eviction.py:217-259). Reads the node again and only
    unpauses labels that are still in a paused state, so a concurrent
    user edit (e.g. disabling a component mid-drain) wins.
    """
    with obs_trace.span("readmit.unpause", node=node_name):
        _readmit_components(api, node_name, original)


def _readmit_components(
    api: KubeApi, node_name: str, original: dict[str, str]
) -> None:
    labels = node_labels(api.get_node(node_name))
    current = {k: labels[k] for k in DRAIN_COMPONENT_LABELS if k in labels}
    patch: dict[str, str | None] = {}
    # Withdraw the drain request in the same patch, so subscribers watching
    # the label may resume as soon as components return — but only when one
    # was actually published (the handshake is off by default, and the
    # common path must not pay an extra write per reconcile).
    if handshake.DRAIN_REQUESTED_LABEL in labels:
        patch[handshake.DRAIN_REQUESTED_LABEL] = None
    # A fast drain publishes a deadline hint next to the request; when a
    # crash-recovery readmit (or a cancelled preemption) unwinds it, the
    # stale hint must not survive into the next normal drain cycle.
    if handshake.DRAIN_DEADLINE_LABEL in labels:
        patch[handshake.DRAIN_DEADLINE_LABEL] = None
    for key in DRAIN_COMPONENT_LABELS:
        restored = unpause_value(current.get(key))
        if restored is not None:
            # The unpaused current value is the truth. The remembered
            # original is only consulted when it is itself unpaused (it can
            # legitimately be a paused value after a crash-recovery run, and
            # writing that back would strand the component).
            remembered = original.get(key)
            patch[key] = (
                remembered
                if remembered is not None and not is_paused(remembered)
                else restored
            )
    components = sorted(k for k in patch if k != handshake.DRAIN_REQUESTED_LABEL)
    if components:
        log.info("unpausing components on %s: %s", node_name, components)
    else:
        log.info("no components to unpause on %s", node_name)
    if patch:
        api.patch_node_labels(node_name, patch)
