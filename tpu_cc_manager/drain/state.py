"""Actual-state reporting through node labels.

Reference: set_cc_state_label (gpu_operator_eviction.py:262-295) — writes
``cc.mode.state`` and the derived ``cc.ready.state`` in one call. Here both
labels land in a single merge-patch (the reference does a full-object RMW
patch per label write; SURVEY.md §8.3).
"""

from __future__ import annotations

import logging

from tpu_cc_manager.kubeclient.api import KubeApi
from tpu_cc_manager.labels import (
    CC_FAILED_REASON_LABEL,
    CC_MODE_STATE_LABEL,
    CC_READY_STATE_LABEL,
    STATE_FAILED,
    label_safe,
    ready_state_for,
)

log = logging.getLogger(__name__)


def state_label_patch(state: str, reason: str | None = None) -> dict:
    """The merge-patch reporting an actual state (mode.state, the derived
    ready.state, and the failed reason — cleared by any non-failed state).
    Exposed separately from :func:`set_cc_state_label` so the manager's
    disconnected mode can journal exactly this patch for a deferred flush
    when the apiserver is unreachable (ccmanager/intent_journal.py)."""
    return {
        CC_MODE_STATE_LABEL: state,
        CC_READY_STATE_LABEL: ready_state_for(state),
        CC_FAILED_REASON_LABEL: (
            label_safe(reason) if state == STATE_FAILED and reason else None
        ),
    }


def set_cc_state_label(
    api: KubeApi, node_name: str, state: str, reason: str | None = None
) -> None:
    """Report actual state; on ``failed`` also publish a machine-readable
    reason label, cleared again by any non-failed state. One merge-patch."""
    patch = state_label_patch(state, reason)
    log.info(
        "reporting state on %s: %s=%s %s=%s%s",
        node_name, CC_MODE_STATE_LABEL, state,
        CC_READY_STATE_LABEL, patch[CC_READY_STATE_LABEL],
        f" reason={reason}" if reason else "",
    )
    api.patch_node_labels(node_name, patch)
