"""Actual-state reporting through node labels.

Reference: set_cc_state_label (gpu_operator_eviction.py:262-295) — writes
``cc.mode.state`` and the derived ``cc.ready.state`` in one call. Here both
labels land in a single merge-patch (the reference does a full-object RMW
patch per label write; SURVEY.md §8.3).
"""

from __future__ import annotations

import logging

from tpu_cc_manager.kubeclient.api import KubeApi
from tpu_cc_manager.labels import (
    CC_MODE_STATE_LABEL,
    CC_READY_STATE_LABEL,
    ready_state_for,
)

log = logging.getLogger(__name__)


def set_cc_state_label(api: KubeApi, node_name: str, state: str) -> None:
    ready = ready_state_for(state)
    log.info(
        "reporting state on %s: %s=%s %s=%s",
        node_name, CC_MODE_STATE_LABEL, state, CC_READY_STATE_LABEL, ready,
    )
    api.patch_node_labels(
        node_name,
        {CC_MODE_STATE_LABEL: state, CC_READY_STATE_LABEL: ready},
    )
