"""Emulated operator controller for fake pools.

One drainable node = component labels + one pod per drain component +
a patch reactor that deletes a component's pods (after an optional
grace delay — pods have termination grace periods on a real cluster)
once its pause label lands. This is the external behavior the drain
protocol relies on (SURVEY.md §5), shared by every fake-pool scenario —
bench.py's measurement kube and the serving harness
(serve/harness.py) — so the emulation cannot diverge between the
artifacts they produce.
"""

from __future__ import annotations

import threading

from tpu_cc_manager.drain.pause import is_paused
from tpu_cc_manager.kubeclient.api import node_labels
from tpu_cc_manager.labels import DRAIN_COMPONENT_LABELS


def add_drainable_node(
    kube,
    node_name: str,
    namespace: str,
    pod_delete_delay_s: float = 0.0,
    extra_labels: dict[str, str] | None = None,
) -> None:
    labels = dict(extra_labels or {})
    labels.update({key: "true" for key in DRAIN_COMPONENT_LABELS})
    kube.add_node(node_name, labels)
    for key, app in DRAIN_COMPONENT_LABELS.items():
        kube.add_pod(namespace, f"{app}-{node_name}", node_name,
                     labels={"app": app})

    def reactor(patched_name, patched):
        if patched_name != node_name:
            return
        for key, app in DRAIN_COMPONENT_LABELS.items():
            if is_paused(node_labels(patched).get(key)):
                if pod_delete_delay_s > 0:
                    timer = threading.Timer(
                        pod_delete_delay_s,
                        kube.delete_pod, (namespace, f"{app}-{node_name}"),
                    )
                    # Daemonize so a pending timer can't outlive its
                    # scenario (delaying exit or firing into the fake
                    # after the measurement window).
                    timer.daemon = True
                    timer.start()
                else:
                    kube.delete_pod(namespace, f"{app}-{node_name}")

    kube.add_patch_reactor(reactor)
