"""Workload drain handshake: let live training jobs checkpoint before the
node's TPU runtime is bounced.

No reference counterpart (the reference's drain only pauses operator
components and waits for THEIR pods, gpu_operator_eviction.py:185-207; the
workloads a reset disrupts are invisible to its protocol). On TPUs the gap
bites harder: a CC transition restarts the runtime under every pod on the
host, so a live training job loses unsaved state unless it snapshots first
(BASELINE.json configs[3] — rolling reconfig under live ResNet-50 training).

Protocol, carried on node labels like everything else in this system:

1. A training job registers a subscriber label
   ``drain-subscriber.tpu-cc.gke.io/<job> = active`` on its node
   (:class:`DrainSubscriber`, typically from a sidecar thread).
2. The manager, before pausing components, sets
   ``cloud.google.com/tpu-cc.drain = requested-<cycle token>`` and resets
   every subscriber label to ``active`` in the same patch, then re-reads
   the subscriber set (so a job registering concurrently is still
   awaited).
3. The subscriber sees the request, runs its ``on_drain`` callback
   (checkpoint via :class:`~tpu_cc_manager.parallel.checkpoint
   .TrainCheckpointer`), then flips its label to ``acked-<cycle token>``.
   Acks are cycle-scoped: an in-flight ack patch from a previous cycle
   carries the previous token and can never satisfy this cycle's wait.
4. The manager waits — bounded, CC_DRAIN_ACK_TIMEOUT_S — for every
   subscriber to ack, then proceeds with the normal component drain.
   Timeout proceeds with a warning (the reference's lenient-drain policy,
   SURVEY.md §8.5): a wedged job must not be able to veto a security
   transition forever.
5. After re-admission the drain request label is cleared; subscribers see
   that and may resume (restore + continue, or simply let the pod restart
   and restore on boot).
"""

from __future__ import annotations

import logging
import secrets
import threading
import time
from typing import Callable, NamedTuple

from tpu_cc_manager.kubeclient.api import KubeApi, KubeApiError, node_labels
from tpu_cc_manager import labels as labels_mod
from tpu_cc_manager.labels import label_safe
from tpu_cc_manager.utils import retry as retry_mod

log = logging.getLogger(__name__)

# Wire names centralized in labels.py (cclint surface contract).
DRAIN_REQUESTED_LABEL = labels_mod.DRAIN_REQUESTED_LABEL
DRAIN_REQUESTED = "requested"  # value prefix: "requested-<cycle token>"
# Optional deadline hint published WITH a drain request (whole seconds):
# a preemption fast-drain carries its hard termination deadline here so a
# subscriber's checkpoint callback can choose a partial/incremental
# checkpoint that actually fits the window instead of starting a full one
# the kill will truncate. Absent on a normal (300 s budget) drain.
DRAIN_DEADLINE_LABEL = labels_mod.DRAIN_DEADLINE_LABEL
SUBSCRIBER_PREFIX = labels_mod.DRAIN_SUBSCRIBER_PREFIX
ACTIVE = "active"
ACKED = "acked"  # value prefix: "acked-<cycle token>"

DEFAULT_ACK_POLL_INTERVAL_S = 2.0
# When no drain is requested, subscribers poll this many times slower —
# fleet-wide the idle GET load is N jobs × poll rate, and the only thing an
# idle poll can discover is a new request, which tolerates seconds of lag
# (the manager's ack wait is bounded in tens of seconds).
IDLE_POLL_MULTIPLIER = 5


def new_cycle_token() -> str:
    """A fresh per-drain-cycle token (label-value-safe hex)."""
    return secrets.token_hex(4)


def request_value(token: str) -> str:
    """Drain-request label value carrying the cycle token."""
    return f"{DRAIN_REQUESTED}-{token}" if token else DRAIN_REQUESTED


def ack_value(token: str) -> str:
    """The only subscriber value that satisfies cycle ``token``'s wait.

    Cycle-scoped so an in-flight ack patch from the PREVIOUS cycle landing
    after this cycle's reset can never read as a fresh checkpoint (the r4
    stale-ack race): the old ack carries the old token.
    """
    return f"{ACKED}-{token}" if token else ACKED


def request_token(value: str | None) -> str | None:
    """Cycle token of a drain-request label value; None when no drain is
    requested. A bare legacy ``requested`` value maps to token ''; any
    other value that is not ``requested-<token>`` is NOT a drain request
    (a malformed value must not yield a garbage token that subscribers
    would checkpoint against)."""
    if value is None:
        return None
    if value == DRAIN_REQUESTED:
        return ""
    if value.startswith(DRAIN_REQUESTED + "-"):
        return value[len(DRAIN_REQUESTED) + 1:]
    return None


class DrainCycle(NamedTuple):
    """One published drain request: its token and the subscribers to await."""

    token: str
    subscribers: list[str]


def subscriber_label(job_name: str) -> str:
    return SUBSCRIBER_PREFIX + label_safe(job_name)


def subscriber_labels_of(labels: dict[str, str]) -> dict[str, str]:
    """The subscriber entries among a node's labels."""
    return {
        k: v for k, v in labels.items() if k.startswith(SUBSCRIBER_PREFIX)
    }


# ---------------------------------------------------------------------------
# Manager side
# ---------------------------------------------------------------------------


def request_drain(
    api: KubeApi, node_name: str, deadline_s: float | None = None
) -> DrainCycle:
    """Publish the drain request (with a fresh cycle token) and reset every
    known subscriber to ``active``, in one merge-patch.

    ``deadline_s`` (preemption fast-drain) additionally publishes the
    hard termination deadline as a whole-seconds label hint for
    subscribers; a normal drain clears any stale hint in the same patch.

    Returns the cycle token plus the subscriber keys that must ack it. The
    subscriber set is re-read AFTER the patch (the server's view), so a job
    registering between our read and our patch is still awaited — and the
    cycle token means a stale ack can never satisfy the wait regardless of
    when it lands.
    """
    token = new_cycle_token()
    subscribers = subscriber_labels_of(node_labels(api.get_node(node_name)))
    patch: dict[str, str | None] = {
        DRAIN_REQUESTED_LABEL: request_value(token),
        DRAIN_DEADLINE_LABEL: (
            str(max(1, int(round(deadline_s)))) if deadline_s else None
        ),
    }
    patch.update({k: ACTIVE for k in subscribers})
    api.patch_node_labels(node_name, patch)
    try:
        subscribers = subscriber_labels_of(
            node_labels(api.get_node(node_name))
        )
    except KubeApiError as e:
        # The request IS published; a transient re-read failure must not
        # abandon the cycle. Fall back to the pre-patch set.
        log.warning(
            "could not re-read subscribers on %s after drain request: %s",
            node_name, e,
        )
    if subscribers:
        log.info(
            "drain requested on %s (cycle %s); awaiting ack from %s",
            node_name, token, sorted(subscribers),
        )
    return DrainCycle(token, sorted(subscribers))


def await_workload_acks(
    api: KubeApi,
    node_name: str,
    timeout_s: float,
    poll_interval_s: float = DEFAULT_ACK_POLL_INTERVAL_S,
    token: str = "",
) -> list[str]:
    """Wait (bounded) until every subscriber label carries THIS cycle's ack.

    Returns the list of laggards (empty on full ack). Subscribers that
    unregister mid-wait (their pod finished) count as done.

    A bare legacy ``acked`` (pre-token subscriber, versioned with the
    training image rather than the manager DaemonSet) is accepted too so a
    manager upgrade doesn't turn every skewed job into a full-timeout
    laggard; only those subscribers keep the r4-size stale-ack window, and
    only until their image catches up."""
    expected = ack_value(token)
    state: dict = {"laggards": [], "legacy_warned": False}

    def all_acked() -> bool:
        labels = node_labels(api.get_node(node_name))
        subs = subscriber_labels_of(labels)
        if not state["legacy_warned"] and any(
            v == ACKED for v in subs.values()
        ):
            log.warning(
                "subscriber(s) %s acked with the pre-token value — "
                "upgrade their image for cycle-scoped acks",
                sorted(k for k, v in subs.items() if v == ACKED),
            )
            state["legacy_warned"] = True
        state["laggards"] = sorted(
            k for k, v in subs.items() if v not in (expected, ACKED)
        )
        return not state["laggards"]

    if retry_mod.poll_until(all_acked, timeout_s, poll_interval_s):
        return []
    log.warning(
        "drain ack timeout on %s: %s did not checkpoint in %.0fs — "
        "proceeding anyway", node_name, state["laggards"], timeout_s,
    )
    return state["laggards"]


def clear_drain_request(api: KubeApi, node_name: str) -> None:
    """Withdraw the drain request (after re-admission). Best-effort."""
    try:
        api.patch_node_labels(node_name, {
            DRAIN_REQUESTED_LABEL: None,
            DRAIN_DEADLINE_LABEL: None,
        })
    except KubeApiError as e:
        log.warning("could not clear drain request on %s: %s", node_name, e)


# ---------------------------------------------------------------------------
# Workload side
# ---------------------------------------------------------------------------


class DrainSubscriber:
    """The training job's side of the handshake.

    Run :meth:`start` from the job process (a daemon thread polls the node);
    ``on_drain`` is invoked — once per drain cycle — when the manager
    requests a drain, and must return only after the job's state is durably
    checkpointed. ``on_resume`` (optional) fires when the request clears.

        sub = DrainSubscriber(api, node, "llama-train", on_drain=ckpt.save_now)
        sub.start()
        ...
        sub.stop()      # unregisters
    """

    def __init__(
        self,
        api: KubeApi,
        node_name: str,
        job_name: str,
        on_drain: Callable[[], None],
        on_resume: Callable[[], None] | None = None,
        poll_interval_s: float = DEFAULT_ACK_POLL_INTERVAL_S,
        idle_poll_interval_s: float | None = None,
    ) -> None:
        self.api = api
        self.node_name = node_name
        self.label = subscriber_label(job_name)
        self.on_drain = on_drain
        self.on_resume = on_resume
        self.poll_interval_s = poll_interval_s
        # Idle polls only need to notice a NEW request, which tolerates
        # seconds of lag — back off so a fleet of subscribers doesn't hit
        # the apiserver at full drain-poll rate around the clock.
        self.idle_poll_interval_s = (
            idle_poll_interval_s
            if idle_poll_interval_s is not None
            else IDLE_POLL_MULTIPLIER * poll_interval_s
        )
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        self._acked_token: str | None = None
        self._drain_requested = False
        # The deadline hint of the current drain cycle (None on a normal
        # drain): read before on_drain fires so a checkpoint callback can
        # size itself to a preemption fast-drain's hard window.
        self.drain_deadline_s: float | None = None

    def register(self) -> None:
        self.api.patch_node_labels(self.node_name, {self.label: ACTIVE})

    def unregister(self) -> None:
        try:
            self.api.patch_node_labels(self.node_name, {self.label: None})
        except KubeApiError as e:
            log.warning("could not unregister %s: %s", self.label, e)

    def check_once(self) -> bool:
        """One poll step; returns True if the current cycle is acked.

        The cycle is identified by the token in the drain-request label:
        ``_acked_token`` tracks which cycle OUR checkpoint served, so a new
        request (fresh token — e.g. after a crash-restart of the manager)
        re-runs the callback (checkpointing twice is safe; not
        checkpointing is not), while re-polling one cycle is idempotent.
        """
        labels = node_labels(self.api.get_node(self.node_name))
        token = request_token(labels.get(DRAIN_REQUESTED_LABEL))
        self._drain_requested = token is not None
        try:
            self.drain_deadline_s = (
                float(labels[DRAIN_DEADLINE_LABEL])
                if token is not None and DRAIN_DEADLINE_LABEL in labels
                else None
            )
        except (TypeError, ValueError):
            self.drain_deadline_s = None
        if token is None:
            if self._acked_token is not None:
                # Clear the cycle only AFTER on_resume succeeds: a failing
                # resume callback leaves _acked_token set, so the next poll
                # really does retry it (run()'s catch-all promises that).
                if self.on_resume is not None:
                    self.on_resume()
                self._acked_token = None
            return False
        if self._acked_token == token and labels.get(self.label) == ack_value(token):
            return True
        # Drain requested and we have not acked this cycle: checkpoint,
        # then ack with the cycle's token. A callback failure leaves us
        # un-acked — the manager's bounded wait will proceed without us and
        # the failure is loud here.
        self.on_drain()
        self.api.patch_node_labels(self.node_name, {self.label: ack_value(token)})
        self._acked_token = token
        log.info(
            "drain ack published for %s on %s (cycle %s)",
            self.label, self.node_name, token,
        )
        return True

    def run(self) -> None:
        self.register()
        try:
            while not self._stop.is_set():
                try:
                    self.check_once()
                except KubeApiError as e:
                    log.warning("drain subscriber poll failed: %s", e)
                except Exception:  # noqa: BLE001 - callback failures
                    # A failing on_drain (disk hiccup mid-checkpoint…) must
                    # not kill the subscriber thread: we stay registered and
                    # un-acked, and the next poll retries the checkpoint.
                    # (Un-acked is safe — the manager's bounded wait
                    # proceeds without us at worst.)
                    log.exception(
                        "drain callback failed; retrying next poll"
                    )
                self._stop.wait(
                    self.poll_interval_s
                    if self._drain_requested
                    else self.idle_poll_interval_s
                )
        finally:
            self.unregister()

    def start(self) -> None:
        self._thread = threading.Thread(
            target=self.run, daemon=True, name=f"drain-sub-{self.label}"
        )
        self._thread.start()

    def stop(self, timeout_s: float = 10.0) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=timeout_s)
