"""Workload drain handshake: let live training jobs checkpoint before the
node's TPU runtime is bounced.

No reference counterpart (the reference's drain only pauses operator
components and waits for THEIR pods, gpu_operator_eviction.py:185-207; the
workloads a reset disrupts are invisible to its protocol). On TPUs the gap
bites harder: a CC transition restarts the runtime under every pod on the
host, so a live training job loses unsaved state unless it snapshots first
(BASELINE.json configs[3] — rolling reconfig under live ResNet-50 training).

Protocol, carried on node labels like everything else in this system:

1. A training job registers a subscriber label
   ``drain-subscriber.tpu-cc.gke.io/<job> = active`` on its node
   (:class:`DrainSubscriber`, typically from a sidecar thread).
2. The manager, before pausing components, sets
   ``cloud.google.com/tpu-cc.drain = requested`` and resets every
   subscriber label to ``active`` in the same patch (stale acks from a
   previous cycle can never satisfy this cycle's wait).
3. The subscriber sees the request, runs its ``on_drain`` callback
   (checkpoint via :class:`~tpu_cc_manager.parallel.checkpoint
   .TrainCheckpointer`), then flips its label to ``acked``.
4. The manager waits — bounded, CC_DRAIN_ACK_TIMEOUT_S — for every
   subscriber to ack, then proceeds with the normal component drain.
   Timeout proceeds with a warning (the reference's lenient-drain policy,
   SURVEY.md §8.5): a wedged job must not be able to veto a security
   transition forever.
5. After re-admission the drain request label is cleared; subscribers see
   that and may resume (restore + continue, or simply let the pod restart
   and restore on boot).
"""

from __future__ import annotations

import logging
import threading
import time
from typing import Callable

from tpu_cc_manager.kubeclient.api import KubeApi, KubeApiError, node_labels
from tpu_cc_manager.labels import label_safe

log = logging.getLogger(__name__)

DRAIN_REQUESTED_LABEL = "cloud.google.com/tpu-cc.drain"
DRAIN_REQUESTED = "requested"
SUBSCRIBER_PREFIX = "drain-subscriber.tpu-cc.gke.io/"
ACTIVE = "active"
ACKED = "acked"

DEFAULT_ACK_POLL_INTERVAL_S = 2.0


def subscriber_label(job_name: str) -> str:
    return SUBSCRIBER_PREFIX + label_safe(job_name)


def subscriber_labels_of(labels: dict[str, str]) -> dict[str, str]:
    """The subscriber entries among a node's labels."""
    return {
        k: v for k, v in labels.items() if k.startswith(SUBSCRIBER_PREFIX)
    }


# ---------------------------------------------------------------------------
# Manager side
# ---------------------------------------------------------------------------


def request_drain(api: KubeApi, node_name: str) -> list[str]:
    """Publish the drain request and reset every subscriber to ``active``.

    Returns the subscriber label keys that must ack this cycle. One
    merge-patch: no window where the request is visible with a stale ack.
    """
    subscribers = subscriber_labels_of(node_labels(api.get_node(node_name)))
    patch: dict[str, str] = {DRAIN_REQUESTED_LABEL: DRAIN_REQUESTED}
    patch.update({k: ACTIVE for k in subscribers})
    api.patch_node_labels(node_name, patch)
    if subscribers:
        log.info(
            "drain requested on %s; awaiting ack from %s",
            node_name, sorted(subscribers),
        )
    return sorted(subscribers)


def await_workload_acks(
    api: KubeApi,
    node_name: str,
    timeout_s: float,
    poll_interval_s: float = DEFAULT_ACK_POLL_INTERVAL_S,
) -> list[str]:
    """Wait (bounded) until every subscriber label reads ``acked``.

    Returns the list of laggards (empty on full ack). Subscribers that
    unregister mid-wait (their pod finished) count as done."""
    deadline = time.monotonic() + timeout_s
    while True:
        labels = node_labels(api.get_node(node_name))
        laggards = sorted(
            k for k, v in subscriber_labels_of(labels).items() if v != ACKED
        )
        if not laggards:
            return []
        if time.monotonic() >= deadline:
            log.warning(
                "drain ack timeout on %s: %s did not checkpoint in %.0fs — "
                "proceeding anyway", node_name, laggards, timeout_s,
            )
            return laggards
        time.sleep(poll_interval_s)


def clear_drain_request(api: KubeApi, node_name: str) -> None:
    """Withdraw the drain request (after re-admission). Best-effort."""
    try:
        api.patch_node_labels(node_name, {DRAIN_REQUESTED_LABEL: None})
    except KubeApiError as e:
        log.warning("could not clear drain request on %s: %s", node_name, e)


# ---------------------------------------------------------------------------
# Workload side
# ---------------------------------------------------------------------------


class DrainSubscriber:
    """The training job's side of the handshake.

    Run :meth:`start` from the job process (a daemon thread polls the node);
    ``on_drain`` is invoked — once per drain cycle — when the manager
    requests a drain, and must return only after the job's state is durably
    checkpointed. ``on_resume`` (optional) fires when the request clears.

        sub = DrainSubscriber(api, node, "llama-train", on_drain=ckpt.save_now)
        sub.start()
        ...
        sub.stop()      # unregisters
    """

    def __init__(
        self,
        api: KubeApi,
        node_name: str,
        job_name: str,
        on_drain: Callable[[], None],
        on_resume: Callable[[], None] | None = None,
        poll_interval_s: float = DEFAULT_ACK_POLL_INTERVAL_S,
    ) -> None:
        self.api = api
        self.node_name = node_name
        self.label = subscriber_label(job_name)
        self.on_drain = on_drain
        self.on_resume = on_resume
        self.poll_interval_s = poll_interval_s
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        self._acked_this_cycle = False

    def register(self) -> None:
        self.api.patch_node_labels(self.node_name, {self.label: ACTIVE})

    def unregister(self) -> None:
        try:
            self.api.patch_node_labels(self.node_name, {self.label: None})
        except KubeApiError as e:
            log.warning("could not unregister %s: %s", self.label, e)

    def check_once(self) -> bool:
        """One poll step; returns True if this cycle is acked.

        The manager resets our label to ``active`` when it opens a cycle,
        so ``_acked_this_cycle`` tracks OUR work while the label tracks the
        cycle: a second request after a crash-restart of the manager re-runs
        the callback (checkpointing twice is safe; not checkpointing is not).
        """
        labels = node_labels(self.api.get_node(self.node_name))
        requested = labels.get(DRAIN_REQUESTED_LABEL) == DRAIN_REQUESTED
        ours = labels.get(self.label)
        if not requested:
            if self._acked_this_cycle:
                self._acked_this_cycle = False
                if self.on_resume is not None:
                    self.on_resume()
            return False
        if ours == ACKED and self._acked_this_cycle:
            return True
        # Drain requested and we have not acked this cycle: checkpoint,
        # then ack. A callback failure leaves us un-acked — the manager's
        # bounded wait will proceed without us and the failure is loud here.
        self.on_drain()
        self.api.patch_node_labels(self.node_name, {self.label: ACKED})
        self._acked_this_cycle = True
        log.info("drain ack published for %s on %s", self.label, self.node_name)
        return True

    def run(self) -> None:
        self.register()
        try:
            while not self._stop.is_set():
                try:
                    self.check_once()
                except KubeApiError as e:
                    log.warning("drain subscriber poll failed: %s", e)
                self._stop.wait(self.poll_interval_s)
        finally:
            self.unregister()

    def start(self) -> None:
        self._thread = threading.Thread(
            target=self.run, daemon=True, name=f"drain-sub-{self.label}"
        )
        self._thread.start()

    def stop(self, timeout_s: float = 10.0) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=timeout_s)
