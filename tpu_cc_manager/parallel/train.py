"""Sharded train-step construction for the smoke models.

Builds (state, step_fn) pairs where the state is initialized *sharded*
(params never materialize replicated on one host) and the step is a single
pjit-compiled function: forward, loss, grad, optimizer update — XLA inserts
the psum/reduce-scatter collectives implied by the shardings.
"""

from __future__ import annotations

from functools import partial
from typing import Any

import jax
import jax.numpy as jnp
import optax
from flax.training import train_state
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from tpu_cc_manager.models.llama import LlamaConfig, LlamaModel
from tpu_cc_manager.parallel.sharding import batch_sharding, logical_state_sharding


class TrainState(train_state.TrainState):
    """flax TrainState (params + optax state + step)."""


def cross_entropy(logits: jnp.ndarray, targets: jnp.ndarray) -> jnp.ndarray:
    logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
    tgt = jax.nn.one_hot(targets, logits.shape[-1], dtype=jnp.float32)
    return -jnp.mean(jnp.sum(logp * tgt, axis=-1))


def make_llama_train_state(
    cfg: LlamaConfig,
    mesh: Mesh,
    learning_rate: float = 3e-4,
    seed: int = 0,
) -> tuple[TrainState, Any]:
    """Sharded-init Llama TrainState + its sharding pytree."""
    import flax.linen as nn

    model = LlamaModel(cfg)
    sample = jnp.zeros((1, 8), jnp.int32)
    tx = optax.adamw(learning_rate, weight_decay=0.01)

    def boxed_init(rng):
        variables = model.init(rng, sample)
        return TrainState.create(apply_fn=model.apply, params=variables["params"], tx=tx)

    # Shapes only (keeps the flax Partitioned metadata), derive mesh
    # shardings from it, then run the real init already-sharded — parameters
    # never materialize replicated (jit with out_shardings shards the init
    # computation itself).
    abstract = jax.eval_shape(boxed_init, jax.random.PRNGKey(seed))
    shardings = logical_state_sharding(abstract, mesh)
    with mesh:
        state = jax.jit(
            lambda rng: nn.unbox(boxed_init(rng)), out_shardings=shardings
        )(jax.random.PRNGKey(seed))
    return state, shardings


def make_llama_train_step(cfg: LlamaConfig, mesh: Mesh, state_shardings):
    """One pjit-compiled next-token-prediction training step."""
    data_sharding = batch_sharding(mesh)

    @partial(
        jax.jit,
        in_shardings=(state_shardings, data_sharding),
        out_shardings=(state_shardings, NamedSharding(mesh, P())),
        donate_argnums=(0,),
    )
    def train_step(state: TrainState, tokens: jnp.ndarray):
        inputs, targets = tokens[:, :-1], tokens[:, 1:]

        def loss_fn(params):
            logits, _ = state.apply_fn({"params": params}, inputs)
            return cross_entropy(logits, targets)

        loss, grads = jax.value_and_grad(loss_fn)(state.params)
        return state.apply_gradients(grads=grads), loss

    return train_step
