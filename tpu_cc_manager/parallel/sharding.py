"""Logical-axis → mesh-axis sharding rules.

Models annotate parameters with *logical* axis names
(``nn.with_logical_partitioning`` in models/llama.py); the rules here map
those names onto the mesh axes of parallel/mesh.py. This indirection is the
idiomatic flax/pjit pattern: the model is written once, and dp/fsdp/tp
layouts are a table change, not a model change.
"""

from __future__ import annotations

import flax.linen as nn
import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

# logical axis -> mesh axis (None = replicated along that logical axis).
LOGICAL_AXIS_RULES = (
    ("batch", ("dcn", "dp", "fsdp")),  # global batch over all data axes
    ("seq", None),                      # sequence sharding arrives with ring attention (ops/)
    ("embed", "fsdp"),                  # ZeRO-style weight sharding
    ("heads", "tp"),
    ("kv_heads", "tp"),
    ("mlp", "tp"),
    ("vocab", "tp"),
    ("layers", None),                   # scan axis stays replicated
)


def mesh_sharding(mesh: Mesh, *spec) -> NamedSharding:
    return NamedSharding(mesh, P(*spec))


def logical_state_sharding(tree, mesh: Mesh):
    """Pytree of NamedShardings for a pytree carrying flax logical metadata
    (a boxed params tree / TrainState from ``jax.eval_shape`` over a boxed
    init). Structure of the result matches the *unboxed* tree, so it can be
    passed straight to ``jit(..., out_shardings=...)`` of an unboxing init.
    Leaves without metadata are replicated."""
    logical_specs = nn.get_partition_spec(tree)
    shardings = nn.logical_to_mesh_sharding(logical_specs, mesh, LOGICAL_AXIS_RULES)
    # logical_to_mesh_sharding leaves bare P()/None for unboxed leaves; wrap
    # everything as NamedSharding for a uniform out_shardings tree.
    return jax.tree.map(
        lambda s: s if isinstance(s, NamedSharding) else NamedSharding(mesh, s or P()),
        shardings,
        is_leaf=lambda x: isinstance(x, (NamedSharding, P)) or x is None,
    )


def batch_sharding(mesh: Mesh) -> NamedSharding:
    """Input-batch sharding: leading axis over all data axes."""
    return NamedSharding(mesh, P(("dcn", "dp", "fsdp")))
