"""Parallelism: meshes, sharding rules, train steps, checkpoint, multi-slice.

No reference counterpart as software (SURVEY.md §2 "Parallelism strategies":
the reference has none) — but the reference's one fabric-wide invariant,
PPCIe's stage-all/reset-all atomicity over NVLink, maps onto the structures
here: the ICI mesh axes are the slice fabric, the 'dcn' axis is the
inter-slice data-parallel path (BASELINE.json configs[4]), and
jax.distributed is the coordination bootstrap (SURVEY.md §5).
"""

from tpu_cc_manager.parallel.mesh import MeshSpec, make_mesh
from tpu_cc_manager.parallel.sharding import (
    LOGICAL_AXIS_RULES,
    logical_state_sharding,
    mesh_sharding,
)

__all__ = [
    "MeshSpec",
    "make_mesh",
    "LOGICAL_AXIS_RULES",
    "logical_state_sharding",
    "mesh_sharding",
]
