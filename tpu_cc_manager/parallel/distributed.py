"""Multi-host / multi-slice coordination bootstrap.

The reference's "distributed backend" is implicit (PPCIe's fabric-wide
stage/reset invariant; SURVEY.md §5): there is no NCCL/MPI to port. The
TPU-native equivalents here are:

- ``bootstrap()``: ``jax.distributed.initialize`` from the env GKE TPU
  pods carry (the NCCL-bootstrap analogue) — coordinator address from the
  JobSet/TPU env, process count/id from TPU worker env;
- ``verify_dcn_mesh()``: a one-psum health check across the 'dcn' axis,
  used after a slice bounces (CC reconfig) to prove the DCN data-parallel
  mesh re-formed before training resumes (BASELINE.json configs[4]);
- quote exchange helpers for cross-slice attestation live in
  ccmanager/multislice.py and use these primitives.
"""

from __future__ import annotations

import logging
import os

import jax

log = logging.getLogger(__name__)


def _env_int(*names: str, default: int | None = None) -> int | None:
    for n in names:
        v = os.environ.get(n)
        if v not in (None, ""):
            try:
                return int(v)
            except ValueError:
                continue
    return default


def bootstrap(timeout_s: int = 300) -> dict:
    """Initialize jax.distributed from the environment, idempotently.

    Recognized env (first match wins):
    - coordinator: JAX_COORDINATOR_ADDRESS, MEGASCALE_COORDINATOR_ADDRESS,
      or TPU_WORKER_HOSTNAMES[0] (GKE TPU podslice convention) + port 8476;
    - process count: JAX_NUM_PROCESSES, else len(TPU_WORKER_HOSTNAMES);
    - process id: JAX_PROCESS_ID, TPU_WORKER_ID.

    Single-process (no env) is a no-op. Returns a summary dict for logs.
    """
    num = _env_int("JAX_NUM_PROCESSES")
    hostnames = [
        h for h in os.environ.get("TPU_WORKER_HOSTNAMES", "").split(",") if h
    ]
    if num is None and len(hostnames) > 1:
        num = len(hostnames)
    if not num or num <= 1:
        log.info("distributed bootstrap: single process, nothing to do")
        return {"processes": 1, "initialized": False}

    pid = _env_int("JAX_PROCESS_ID", "TPU_WORKER_ID", default=0)
    coordinator = (
        os.environ.get("JAX_COORDINATOR_ADDRESS")
        or os.environ.get("MEGASCALE_COORDINATOR_ADDRESS")
        or (f"{hostnames[0]}:8476" if hostnames else None)
    )
    if coordinator is None:
        raise RuntimeError(
            "multi-process env detected but no coordinator address "
            "(set JAX_COORDINATOR_ADDRESS)"
        )
    jax.distributed.initialize(
        coordinator_address=coordinator,
        num_processes=num,
        process_id=pid,
        initialization_timeout=timeout_s,
    )
    log.info(
        "jax.distributed initialized: coordinator=%s process %d/%d "
        "local_devices=%d global_devices=%d",
        coordinator, pid, num, jax.local_device_count(), jax.device_count(),
    )
    return {"processes": num, "process_id": pid, "initialized": True}


def verify_dcn_mesh(mesh) -> bool:
    """Prove the data-parallel mesh is live end-to-end: an all-reduce of
    ones over every data axis must equal the number of participants.

    Run after a slice returns from a CC bounce and before training resumes
    — a half-formed DCN mesh hangs or mis-reduces here instead of corrupting
    gradients silently."""
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P

    data_axes = ("dcn", "dp", "fsdp")
    n = 1
    for a in data_axes:
        n *= mesh.shape.get(a, 1)
    ones = jax.device_put(
        jnp.ones((n,), jnp.float32), NamedSharding(mesh, P(data_axes))
    )
    total = jax.jit(
        lambda x: jnp.sum(x), out_shardings=NamedSharding(mesh, P())
    )(ones)
    ok = int(total) == n
    (log.info if ok else log.error)(
        "DCN mesh verification: expected %d, got %d -> %s", n, int(total), ok
    )
    return ok
