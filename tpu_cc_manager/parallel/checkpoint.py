"""Checkpoint/resume for training across CC reconfigurations.

New component with no reference counterpart (SURVEY.md §5 "Checkpoint /
resume: none in the reference"): the rolling-reconfig scenario
(BASELINE.json configs[3]) drains nodes out from under a live ResNet-50/
Llama training job, so the job must snapshot before the drain and restore
after re-admission. Orbax-backed; restores respect the target's shardings
(arrays come back already distributed on the mesh).
"""

from __future__ import annotations

import logging

import orbax.checkpoint as ocp

log = logging.getLogger(__name__)


class TrainCheckpointer:
    """Thin orbax CheckpointManager wrapper for TrainState pytrees."""

    def __init__(self, directory: str, max_to_keep: int = 3) -> None:
        self.manager = ocp.CheckpointManager(
            directory,
            options=ocp.CheckpointManagerOptions(
                max_to_keep=max_to_keep, create=True
            ),
        )

    def save(self, step: int, state, wait: bool = True) -> None:
        self.manager.save(step, args=ocp.args.StandardSave(state))
        if wait:
            self.manager.wait_until_finished()
        log.info("checkpoint saved at step %d", step)

    def latest_step(self) -> int | None:
        return self.manager.latest_step()

    def restore(self, abstract_state, step: int | None = None):
        """Restore into the structure/shardings of ``abstract_state``
        (typically ``jax.eval_shape`` of the init, with shardings attached
        via ``jax.tree.map(lambda s, sh: jax.ShapeDtypeStruct(s.shape,
        s.dtype, sharding=sh), ...)``)."""
        step = self.latest_step() if step is None else step
        if step is None:
            raise FileNotFoundError("no checkpoint to restore")
        state = self.manager.restore(step, args=ocp.args.StandardRestore(abstract_state))
        log.info("checkpoint restored from step %d", step)
        return state

    def close(self) -> None:
        self.manager.close()
