"""Device meshes: slice-aware axis layout.

Axis convention (outermost first):

- ``dcn``  inter-slice data parallelism over the data-center network
  (multi-slice, BASELINE.json configs[4]); size 1 on a single slice.
- ``dp``   intra-slice data parallelism over ICI.
- ``fsdp`` parameter sharding over ICI (ZeRO-style); merged into dp-like
  usage — kept as its own axis so weight shards and batch shards can scale
  independently.
- ``sp``   sequence/context parallelism over ICI (ring attention,
  ops/ring_attention.py): long sequences sharded across devices, K/V
  shards streamed with ppermute; size 1 unless running long-context.
- ``tp``   tensor parallelism (attention heads / MLP) over the fastest ICI
  dimension.

The scaling-book recipe: put tensor-parallel collectives on the
innermost (fastest) mesh dimension, data-parallel reductions on outer
dimensions, and never let a collective cross DCN unless the axis is 'dcn'.
"""

from __future__ import annotations

import dataclasses
import logging
import math

import jax
import numpy as np
from jax.experimental import mesh_utils
from jax.sharding import Mesh

log = logging.getLogger(__name__)

AXES = ("dcn", "dp", "fsdp", "sp", "tp")


@dataclasses.dataclass(frozen=True)
class MeshSpec:
    """Sizes for each mesh axis; -1 on dp means 'absorb remaining devices'."""

    dcn: int = 1
    dp: int = -1
    fsdp: int = 1
    sp: int = 1
    tp: int = 1

    def resolve(self, n_devices: int) -> dict[str, int]:
        fixed = self.dcn * self.fsdp * self.sp * self.tp
        dp = self.dp
        if dp == -1:
            if n_devices % fixed:
                raise ValueError(
                    f"{n_devices} devices not divisible by dcn*fsdp*sp*tp={fixed}"
                )
            dp = n_devices // fixed
        total = fixed * dp
        if total != n_devices:
            raise ValueError(
                f"mesh {self} needs {total} devices, have {n_devices}"
            )
        return {"dcn": self.dcn, "dp": dp, "fsdp": self.fsdp, "sp": self.sp, "tp": self.tp}


def make_mesh(spec: MeshSpec | None = None, devices=None) -> Mesh:
    """Build a Mesh over ``devices`` (default: all) with the AXES layout.

    On multi-slice TPU deployments, uses hybrid mesh construction so the
    'dcn' axis maps to slice boundaries (collectives over every other axis
    stay on ICI). Elsewhere (single slice, CPU test meshes) a plain
    contiguous mesh is used.
    """
    spec = spec or MeshSpec()
    devices = list(devices if devices is not None else jax.devices())
    sizes = spec.resolve(len(devices))
    shape = tuple(sizes[a] for a in AXES)

    if sizes["dcn"] > 1:
        try:
            dev_array = mesh_utils.create_hybrid_device_mesh(
                mesh_shape=shape[1:],
                dcn_mesh_shape=(sizes["dcn"], 1, 1, 1),
                devices=devices,
            )
        except (ValueError, AssertionError) as e:
            # CPU test meshes have no slice topology; fall back to contiguous.
            log.debug("hybrid mesh unavailable (%s); using contiguous mesh", e)
            dev_array = np.asarray(devices).reshape(shape)
    else:
        try:
            dev_array = mesh_utils.create_device_mesh(shape, devices=devices)
        except (ValueError, AssertionError):
            dev_array = np.asarray(devices).reshape(shape)
    dev_array = np.asarray(dev_array).reshape(shape)
    mesh = Mesh(dev_array, AXES)
    log.info("mesh: %s over %d devices", {a: sizes[a] for a in AXES}, len(devices))
    return mesh


def default_spec_for(n_devices: int, want_tp: bool = True) -> MeshSpec:
    """A sensible mesh for n devices: largest power-of-two tp up to 4 that
    divides the device count (ICI-local), rest data-parallel."""
    tp = 1
    if want_tp:
        for candidate in (4, 2):
            if n_devices % candidate == 0 and n_devices > candidate:
                tp = candidate
                break
    dp = n_devices // tp
    return MeshSpec(dcn=1, dp=dp, fsdp=1, tp=tp)


def pad_batch_to(batch: int, mesh: Mesh) -> int:
    """Smallest batch >= requested divisible by the mesh's data axes."""
    denom = math.prod(mesh.shape[a] for a in ("dcn", "dp", "fsdp"))
    return ((batch + denom - 1) // denom) * denom
