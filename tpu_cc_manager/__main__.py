"""``python -m tpu_cc_manager`` runs the node agent (the container
entrypoint; reference analogue: ``python3 /app/main.py``,
Dockerfile.distroless:70)."""

import sys

from tpu_cc_manager.ccmanager.cli import main

sys.exit(main())
