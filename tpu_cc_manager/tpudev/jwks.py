"""RS256 JWT verification against a JWKS — pure standard library.

The TPU VM backend's attestation evidence is a GCE instance-identity JWT
(tpudev/tpuvm.py); trusting it requires verifying its RSASSA-PKCS1-v1_5 /
SHA-256 signature against Google's published JWKS. *Verification* (unlike
signing) needs only one modular exponentiation and a constant-time byte
comparison, so this module is stdlib-only and the distroless production
image (deployments/container/Dockerfile.distroless) carries no crypto
dependency. Tests generate throwaway RSA keypairs with the ``cryptography``
package, which is a test-only dependency.

Key material comes from, in order:

1. an operator-provided offline JWKS file (``CC_GOOGLE_JWKS_FILE``) — the
   air-gapped / egress-less path; the DaemonSet can mount one fetched at
   deploy time,
2. a cached copy from a previous fetch (``CC_JWKS_CACHE_FILE``),
3. a live fetch of ``GOOGLE_JWKS_URL`` (written back to the cache).

No key material at all is a verification *failure*, not a skip — the
reference's device layer never reports success without querying the device
(reference main.py:524-528); the attestation layer holds the same line.
"""

from __future__ import annotations

import base64
import hashlib
import hmac
import json
import logging
import os
import time
import urllib.error
import urllib.request

log = logging.getLogger(__name__)

GOOGLE_JWKS_URL = "https://www.googleapis.com/oauth2/v3/certs"
# Both spellings are documented for GCE instance-identity tokens.
GOOGLE_ISSUERS = ("https://accounts.google.com", "accounts.google.com")

JWKS_FILE_ENV = "CC_GOOGLE_JWKS_FILE"
JWKS_CACHE_ENV = "CC_JWKS_CACHE_FILE"
DEFAULT_CACHE_FILE = "/var/lib/tpu-cc-manager/jwks-cache.json"
CACHE_TTL_S = 6 * 3600.0

# DER prefix of DigestInfo for SHA-256 (RFC 8017 §9.2 note 1).
_SHA256_DIGESTINFO = bytes.fromhex(
    "3031300d060960864801650304020105000420"
)


class JwksError(Exception):
    """Signature verification failed or no usable key material."""


def _b64url_decode(seg: str) -> bytes:
    return base64.urlsafe_b64decode(seg + "=" * (-len(seg) % 4))


def _b64url_to_int(seg: str) -> int:
    return int.from_bytes(_b64url_decode(seg), "big")


def _emsa_pkcs1_v15_sha256(message: bytes, em_len: int) -> bytes:
    """EMSA-PKCS1-v1_5 encoding of SHA-256(message) (RFC 8017 §9.2)."""
    t = _SHA256_DIGESTINFO + hashlib.sha256(message).digest()
    if em_len < len(t) + 11:
        raise JwksError("RSA modulus too short for SHA-256 signature")
    ps = b"\xff" * (em_len - len(t) - 3)
    return b"\x00\x01" + ps + b"\x00" + t


def _candidate_keys(jwks: dict, kid: str | None) -> list[dict]:
    keys = [k for k in jwks.get("keys", []) if k.get("kty") == "RSA"]
    if kid is not None:
        matched = [k for k in keys if k.get("kid") == kid]
        # An unknown kid falls back to trying every RSA key: Google rotates
        # keys, and a slightly stale JWKS with the right key under a new kid
        # should still verify rather than hard-fail on metadata.
        return matched or keys
    return keys


def verify_rs256(token: str, jwks: dict) -> dict:
    """Verify an RS256 JWT against a JWKS; return the claims on success.

    Raises :class:`JwksError` on a malformed token, a non-RS256 algorithm,
    or a signature that verifies under none of the JWKS's RSA keys.
    """
    parts = token.split(".")
    if len(parts) != 3:
        raise JwksError("token is not a three-part JWT")
    try:
        header = json.loads(_b64url_decode(parts[0]))
        claims = json.loads(_b64url_decode(parts[1]))
        signature = _b64url_decode(parts[2])
    except Exception as e:  # noqa: BLE001 - any decode failure is the finding
        raise JwksError(f"JWT undecodable: {e}") from e
    if header.get("alg") != "RS256":
        raise JwksError(f"unsupported JWT alg {header.get('alg')!r}")
    signing_input = f"{parts[0]}.{parts[1]}".encode("ascii")
    keys = _candidate_keys(jwks, header.get("kid"))
    if not keys:
        raise JwksError("JWKS contains no RSA keys")
    s = int.from_bytes(signature, "big")
    for key in keys:
        try:
            n = _b64url_to_int(key["n"])
            e = _b64url_to_int(key["e"])
        except (KeyError, ValueError):
            continue
        k = (n.bit_length() + 7) // 8
        if len(signature) != k or s >= n:
            continue
        em = pow(s, e, n).to_bytes(k, "big")
        if hmac.compare_digest(em, _emsa_pkcs1_v15_sha256(signing_input, k)):
            return claims
    raise JwksError("signature verifies under no JWKS key")


def load_jwks(
    offline_file: str | None = None,
    cache_file: str | None = None,
    url: str = GOOGLE_JWKS_URL,
    fetch_timeout_s: float = 5.0,
) -> dict | None:
    """Load key material: offline file > fresh cache > live fetch > stale
    cache. Returns None when nothing is available (the caller fails closed).
    """
    offline_file = offline_file or os.environ.get(JWKS_FILE_ENV)
    if offline_file:
        try:
            with open(offline_file, "r", encoding="utf-8") as f:
                return json.load(f)
        except FileNotFoundError:
            # The offline file is OPTIONAL provisioning (the DaemonSet sets
            # the path unconditionally); absence falls through to
            # cache/fetch.
            log.info(
                "offline JWKS file %s not present; falling back to "
                "cache/fetch", offline_file,
            )
        except (OSError, json.JSONDecodeError) as e:
            log.error("configured JWKS file %s unreadable: %s", offline_file, e)
            # A file that EXISTS but is broken should not fall through to
            # the network: surface the misconfiguration.
            return None

    cache_file = cache_file or os.environ.get(JWKS_CACHE_ENV, DEFAULT_CACHE_FILE)
    cached: dict | None = None
    try:
        with open(cache_file, "r", encoding="utf-8") as f:
            payload = json.load(f)
        cached = payload.get("jwks")
        if time.time() - float(payload.get("fetched_at", 0)) < CACHE_TTL_S:
            return cached
    except (OSError, ValueError):
        cached = None

    try:
        with urllib.request.urlopen(url, timeout=fetch_timeout_s) as resp:
            jwks = json.loads(resp.read().decode("utf-8"))
    except (urllib.error.URLError, OSError, ValueError, TimeoutError) as e:
        if cached is not None:
            log.warning("JWKS fetch failed (%s); using stale cache", e)
            return cached
        log.error("JWKS fetch failed and no cache/offline file: %s", e)
        return None
    try:
        os.makedirs(os.path.dirname(cache_file), exist_ok=True)
        tmp = cache_file + ".tmp"
        with open(tmp, "w", encoding="utf-8") as f:
            json.dump({"fetched_at": time.time(), "jwks": jwks}, f)
        os.replace(tmp, cache_file)
    except OSError as e:
        log.warning("could not write JWKS cache %s: %s", cache_file, e)
    return jwks
