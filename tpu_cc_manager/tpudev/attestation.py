"""Attestation verification for reconfigured slices.

New subsystem — no reference counterpart (SURVEY.md §0(b): "libtpu / TPU VM
runtime based CC+attestation toggle"). After a CC transition commits, the
reconciler asks the backend for a quote bound to a fresh nonce and verifies
it here before declaring the node ready. In ``devtools`` mode the policy is
relaxed: problems are logged, not fatal (the reference's devtools is a GPU
debug mode; the TPU analogue is a debug attestation policy, labels.py).

Verifier dispatch is by quote ``platform``:
- ``fake``  — HMAC with the shared test key (tpudev/fake.py). Rejected
  outright unless the caller explicitly allows fake quotes (the manager
  does so only when the operator selected the fake device layer) — a forged
  ``platform="fake"`` quote must never verify in production.
- ``tpuvm`` — GCE instance-identity JWT (tpudev/tpuvm.py): structural
  checks (audience carries the nonce, not expired), issuer must be Google,
  and the RS256 signature is verified against Google's JWKS
  (tpudev/jwks.py: offline file > cache > live fetch). Missing key
  material fails closed.
"""

from __future__ import annotations

import base64
import hashlib
import hmac
import json
import logging
import secrets
import time

from tpu_cc_manager.obs import trace as obs_trace
from tpu_cc_manager.tpudev import jwks
from tpu_cc_manager.tpudev.contract import AttestationQuote, TpuError

log = logging.getLogger(__name__)

REQUIRED_MEASUREMENTS = ("accelerator_type", "runtime_digest", "cc_mode")


class AttestationError(TpuError):
    """Quote failed verification (fatal outside devtools policy)."""


def fresh_nonce() -> str:
    return secrets.token_hex(16)


def _check_fake_signature(quote: AttestationQuote) -> list[str]:
    from tpu_cc_manager.tpudev.fake import sign_fake_quote

    expected = sign_fake_quote(quote.slice_id, quote.nonce, quote.mode, quote.measurements)
    if not hmac.compare_digest(expected, quote.signature):
        return ["fake quote HMAC mismatch"]
    return []


def _decode_jwt_segment(seg: str) -> dict:
    pad = "=" * (-len(seg) % 4)
    return json.loads(base64.urlsafe_b64decode(seg + pad))


def _check_tpuvm_signature(quote: AttestationQuote) -> list[str]:
    """Verify a GCE instance-identity JWT carried in ``signature``:
    structure, nonce binding, expiry, Google issuer, and the RS256
    signature against Google's JWKS (tpudev/jwks.py). No key material at
    all is a failure — a quote that *cannot* be checked must not pass."""
    problems = []
    parts = quote.signature.split(".")
    if len(parts) != 3:
        return ["tpuvm quote is not a JWT"]
    try:
        header = _decode_jwt_segment(parts[0])
        claims = _decode_jwt_segment(parts[1])
    except Exception as e:  # noqa: BLE001 - any decode failure is the finding
        return [f"tpuvm quote JWT undecodable: {e}"]
    if header.get("alg") != "RS256":
        problems.append(f"unexpected JWT alg {header.get('alg')!r}")
    aud = claims.get("aud")
    if not aud:
        # No audience means no nonce binding at all — a replayed token would
        # sail through; treat as a failure, not a skip.
        problems.append("JWT has no audience claim (nonce unbound)")
    elif quote.nonce not in str(aud):
        problems.append("JWT audience does not carry the nonce")
    exp = claims.get("exp")
    if isinstance(exp, (int, float)) and exp < time.time():
        problems.append("JWT expired")
    if claims.get("iss") not in jwks.GOOGLE_ISSUERS:
        problems.append(f"unexpected JWT issuer {claims.get('iss')!r}")
    keyset = jwks.load_jwks()
    if keyset is None:
        problems.append(
            "no JWKS key material for signature verification (set "
            f"{jwks.JWKS_FILE_ENV} or allow egress to {jwks.GOOGLE_JWKS_URL}); "
            "failing closed"
        )
    else:
        try:
            jwks.verify_rs256(quote.signature, keyset)
        except jwks.JwksError as e:
            problems.append(f"JWT signature verification failed: {e}")
    return problems


_SIGNATURE_CHECKS = {
    "fake": _check_fake_signature,
    "tpuvm": _check_tpuvm_signature,
}


def _check_tsm_binding(quote: AttestationQuote, nonce: str) -> list[str]:
    """When the quote claims a TEE guest report (measurements.tsm_provider
    != "none"), the per-host evidence must carry the report and the report
    itself must embed the nonce-derived challenge: both SEV-SNP attestation
    reports and TDX quotes copy the configfs-tsm ``inblob`` verbatim into
    their signed report_data field, so the 32 random challenge bytes must
    appear inside the outblob. A producer-supplied hash would not do — it
    is derivable from the public nonce alone, so a stale outblob could ride
    along under a fresh JWT. Full certificate-chain validation of the
    outblob signature (AMD/Intel roots) is the relying party's job; this
    check decides what the manager can decide offline: presence + the
    challenge being inside the signed blob."""
    provider = quote.measurements.get("tsm_provider", "none")
    if provider in ("none", "unavailable"):
        return []
    evidence = quote.host_evidence
    outblob_b64 = evidence.get("tsm_outblob_b64")
    if not outblob_b64:
        return [f"tsm_provider={provider!r} claimed but no guest report attached"]
    try:
        outblob = base64.b64decode(outblob_b64, validate=True)
    except Exception:  # noqa: BLE001 - undecodable evidence is the finding
        return ["tsm guest report is not valid base64"]
    expected_inblob = hashlib.sha256(f"tpu-cc-manager/{nonce}".encode()).digest()
    if expected_inblob not in outblob:
        return [
            "tsm report is not bound to this nonce (nonce-derived challenge "
            "not present in the signed report_data)"
        ]
    return []


def quote_problems(
    quote: AttestationQuote,
    nonce: str,
    expected_mode: str,
    expected_slice_id: str | None = None,
    allow_fake: bool = False,
) -> list[str]:
    """All the checks of :func:`verify_quote`, returned as a problem list
    with no policy attached — the shared core for the local verify phase
    (raise/log per devtools policy) and for pool peer-verification, which
    aggregates problems across nodes (ccmanager/multislice.py)."""
    problems: list[str] = []
    if quote.platform == "fake" and not allow_fake:
        problems.append(
            "fake-platform quote rejected: the fake device layer is not in "
            "use (select --tpu-backend=fake for dry-runs)"
        )
    if quote.nonce != nonce:
        problems.append(f"nonce mismatch: sent {nonce}, quote has {quote.nonce}")
    if quote.mode != expected_mode:
        problems.append(f"mode mismatch: expected {expected_mode}, quote says {quote.mode}")
    if expected_slice_id is not None and quote.slice_id != expected_slice_id:
        problems.append(
            f"slice mismatch: expected {expected_slice_id}, quote says {quote.slice_id}"
        )
    for key in REQUIRED_MEASUREMENTS:
        if key not in quote.measurements:
            problems.append(f"missing measurement {key!r}")
    problems.extend(_check_tsm_binding(quote, nonce))
    checker = _SIGNATURE_CHECKS.get(quote.platform)
    if checker is None:
        problems.append(f"unknown quote platform {quote.platform!r}")
    else:
        problems.extend(checker(quote))
    return problems


def verify_quote(
    quote: AttestationQuote,
    nonce: str,
    expected_mode: str,
    expected_slice_id: str | None = None,
    debug_policy: bool = False,
    allow_fake: bool = False,
) -> list[str]:
    """Verify a quote; returns the (possibly empty) problem list.

    Raises AttestationError on any problem unless ``debug_policy`` is set
    (devtools mode), in which case problems are logged and returned.

    ``allow_fake`` admits ``platform="fake"`` quotes (HMAC with the shared
    test key). The manager enables it only when the operator explicitly
    selected the fake device layer; everywhere else a fake-platform quote
    is an attack, not a test.
    """
    with obs_trace.span(
        "attest.verify",
        platform=quote.platform, mode=quote.mode, slice=quote.slice_id,
    ) as sp:
        problems = quote_problems(
            quote, nonce, expected_mode,
            expected_slice_id=expected_slice_id, allow_fake=allow_fake,
        )
        sp.set_attribute("problems", len(problems))
    if problems:
        if debug_policy:
            for p in problems:
                log.warning("attestation (devtools policy, non-fatal): %s", p)
        else:
            raise AttestationError("; ".join(problems))
    else:
        log.info(
            "attestation verified: slice=%s mode=%s digest=%s",
            quote.slice_id,
            quote.mode,
            quote.measurements.get("runtime_digest", "")[:12],
        )
    return problems


def serialize_quote(quote: AttestationQuote) -> str:
    """Compact JSON of the full quote — signature included — for transport
    in a node annotation, so PEERS can re-verify the platform signature
    instead of trusting a self-published digest label
    (ccmanager/multislice.py; the reference's read-truth-back principle,
    /root/reference/main.py:524-528)."""
    return json.dumps(
        {
            "slice_id": quote.slice_id,
            "nonce": quote.nonce,
            "mode": quote.mode,
            "measurements": quote.measurements,
            "signature": quote.signature,
            "platform": quote.platform,
            "host_evidence": quote.host_evidence,
        },
        sort_keys=True, separators=(",", ":"),
    )


def deserialize_quote(data: str) -> AttestationQuote:
    """Inverse of :func:`serialize_quote`. Raises AttestationError on any
    shape problem — an unparseable published quote is an attestation
    failure, not a crash."""
    try:
        obj = json.loads(data)
        return AttestationQuote(
            slice_id=str(obj["slice_id"]),
            nonce=str(obj["nonce"]),
            mode=str(obj["mode"]),
            measurements={str(k): str(v) for k, v in obj["measurements"].items()},
            signature=str(obj["signature"]),
            platform=str(obj["platform"]),
            host_evidence={
                str(k): str(v)
                for k, v in (obj.get("host_evidence") or {}).items()
            },
        )
    except (ValueError, KeyError, TypeError, AttributeError) as e:
        raise AttestationError(f"undeserializable quote: {e}") from e


def quote_digest(quote: AttestationQuote) -> str:
    """Short stable digest of a quote, for logs and cross-slice comparison
    (multi-slice DP verifies every slice attests the same runtime digest
    before re-forming the DCN mesh, ccmanager/multislice.py).

    Deliberately excludes ``slice_id``: the digest is the pool-wide "same
    runtime, same mode" fingerprint, and two healthy slices of one DP pool
    must produce EQUAL digests. Per-slice identity is checked separately by
    ``verify_quote(expected_slice_id=...)``."""
    msg = json.dumps(
        {"mode": quote.mode, "m": quote.measurements},
        sort_keys=True,
    ).encode()
    return hashlib.sha256(msg).hexdigest()[:16]
