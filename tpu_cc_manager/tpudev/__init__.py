"""TPU device layer: the CC/attestation contract and its backends.

This layer replaces the reference's external gpu-admin-tools dependency
(SURVEY.md §1 L1) — the contract the control loop consumes is re-created for
TPU slices in :mod:`contract`, with a fully featured fake in :mod:`fake`
(SURVEY.md §4 calls the reference's missing fake backend its biggest gap) and
a real TPU VM backend in :mod:`tpuvm`.
"""

from tpu_cc_manager.tpudev.contract import (
    AttestationQuote,
    SliceTopology,
    TpuCcBackend,
    TpuChip,
    TpuError,
)

__all__ = [
    "AttestationQuote",
    "SliceTopology",
    "TpuCcBackend",
    "TpuChip",
    "TpuError",
]


def load_backend(name: str, **kwargs) -> TpuCcBackend:
    """Backend factory: ``fake`` or ``tpuvm`` (reference picks its device
    library at image build time, Dockerfile.distroless:22; we pick at runtime
    via --tpu-backend / TPU_CC_BACKEND so the kind dry-run needs no hardware).

    The fake backend's topology is env-configurable
    (``TPU_CC_FAKE_{NUM_CHIPS,NUM_HOSTS,HOST_INDEX,SLICE_ID}``) so
    multi-host slice flows — the commit barrier above all — can be driven
    end-to-end by separate agent processes (hack/demo_multihost.sh)."""
    if name == "fake":
        import os

        from tpu_cc_manager.tpudev.fake import FakeTpuBackend

        env = os.environ
        if "TPU_CC_FAKE_NUM_CHIPS" in env:
            kwargs.setdefault("num_chips", int(env["TPU_CC_FAKE_NUM_CHIPS"]))
        if "TPU_CC_FAKE_NUM_HOSTS" in env:
            kwargs.setdefault("num_hosts", int(env["TPU_CC_FAKE_NUM_HOSTS"]))
        if "TPU_CC_FAKE_HOST_INDEX" in env:
            kwargs.setdefault("host_index", int(env["TPU_CC_FAKE_HOST_INDEX"]))
        if "TPU_CC_FAKE_SLICE_ID" in env:
            kwargs.setdefault("slice_id", env["TPU_CC_FAKE_SLICE_ID"])
        return FakeTpuBackend(**kwargs)
    if name == "tpuvm":
        from tpu_cc_manager.tpudev.tpuvm import TpuVmBackend

        return TpuVmBackend(**kwargs)
    raise ValueError(f"unknown TPU backend {name!r} (expected 'fake' or 'tpuvm')")
