"""Fake TPU backend: in-memory chips with configurable latency and faults.

This is the test double the reference never had (SURVEY.md §4). It implements
the full contract with:

- configurable chip count / capability flags (mixed-capability test cases,
  reference main.py:237-240),
- staged-vs-committed mode tracking so tests can assert the
  stage-all/reset-all ordering (reference main.py:502-519),
- attestation quotes HMAC-signed with a shared test key, verified by
  :mod:`tpu_cc_manager.tpudev.attestation`,
- fault injection: fail on stage/reset/wait/attest once or always,
- latency knobs so bench.py can model realistic reset/boot times —
  scalar (one whole-set latency, the legacy shape) or per-chip lists, so
  the parallel-reset pipeline's speedup is measurable and deterministic
  in the simulated bench (per-chip work fans out across a bounded pool,
  each chip in its own ``reset.chip`` obs span).
"""

from __future__ import annotations

import hashlib
import hmac
import json
import time
from concurrent.futures import ThreadPoolExecutor

from tpu_cc_manager.labels import MODE_OFF
from tpu_cc_manager.obs import trace as obs_trace
from tpu_cc_manager.tpudev.contract import (
    AttestationQuote,
    HealthProbe,
    SliceTopology,
    TpuCcBackend,
    TpuChip,
    TpuError,
    raise_pool_errors,
    reset_parallelism,
)
from tpu_cc_manager.utils import locks as locks_mod

# Shared secret for fake quotes; the verifier uses the same constant.
FAKE_ATTESTATION_KEY = b"tpu-cc-manager-fake-attestation-key"


def sign_fake_quote(slice_id: str, nonce: str, mode: str, measurements: dict) -> str:
    msg = json.dumps(
        {"slice_id": slice_id, "nonce": nonce, "mode": mode, "m": measurements},
        sort_keys=True,
    ).encode()
    return hmac.new(FAKE_ATTESTATION_KEY, msg, hashlib.sha256).hexdigest()


class FakeTpuBackend(TpuCcBackend):
    def __init__(
        self,
        num_chips: int = 4,
        chip_type: str = "v5p",
        accelerator_type: str = "v5p-8",
        num_hosts: int = 1,
        host_index: int = 0,
        slice_id: str = "fake-slice-0",
        cc_supported: bool | list[bool] = True,
        slice_cc_supported: bool | list[bool] = True,
        initial_mode: str = MODE_OFF,
        reset_latency_s: float | list[float] = 0.0,
        boot_latency_s: float | list[float] = 0.0,
        reset_parallelism_override: int | None = None,
    ) -> None:
        def flags(spec, n):
            return list(spec) if isinstance(spec, list) else [spec] * n

        cc_flags = flags(cc_supported, num_chips)
        slice_flags = flags(slice_cc_supported, num_chips)
        self._chips = tuple(
            TpuChip(
                index=i,
                device_path=f"/dev/accel{i}",
                chip_type=chip_type,
                cc_supported=cc_flags[i],
                slice_cc_supported=slice_flags[i],
            )
            for i in range(num_chips)
        )
        self._topology = SliceTopology(
            slice_id=slice_id,
            accelerator_type=accelerator_type,
            num_hosts=num_hosts,
            host_index=host_index,
            chips=self._chips,
        )
        self._lock = locks_mod.make_lock("fake-backend")
        self.committed: dict[int, str] = {c.index: initial_mode for c in self._chips}
        self.staged: dict[int, str] = {}
        self.booted: dict[int, bool] = {c.index: True for c in self._chips}
        # Scalar = one latency for the whole set (legacy); a list is
        # per-chip (index-aligned), independently configurable so the
        # parallel-reset speedup is measurable deterministically.
        self.reset_latency_s = reset_latency_s
        self.boot_latency_s = boot_latency_s
        # None -> CC_RESET_PARALLELISM (default 4); only per-chip reset
        # latencies fan out — a scalar keeps the legacy single sleep.
        self.reset_parallelism_override = reset_parallelism_override
        # Brownout (gray failure, faults/plan.py seed_brownout): every
        # reset/boot wall is multiplied by this factor while > 1 — the
        # node fails SLOW, not stop, and probe_runtime_health stays
        # healthy by construction (that is what makes it gray).
        self.brownout_factor = 1.0
        self._boot_done_at: dict[int, float] = {}
        # Fault injection: map op name -> remaining failure count (-1 = always).
        self.fail: dict[str, int] = {}
        # Ordered op log for ordering assertions: (op, payload).
        self.op_log: list[tuple[str, object]] = []
        # The committed runtime environment, mirroring TpuVmBackend's
        # EnvironmentFile semantics (devtools commits debug flags): tests
        # assert the backend-visible difference between modes here.
        self.runtime_env: dict[str, str] = {}
        # Runtime-health watchdog controls: tests (and the chaos soak) flip
        # ``healthy`` to drive demote→restore cycles; ``health_tier``
        # mimics whichever probe tier the scenario wants reported.
        self.healthy = True
        self.health_tier = "probe-cmd"
        # Preemption-notice control (spot/preemptible chaos): set by tests
        # or FaultPlan.schedule_preemption; the manager's preemption
        # monitor reads it through the contract's preemption_notice().
        self.preempted = False

    # ---- fault injection helpers ----------------------------------------

    def fail_next(self, op: str, times: int = 1) -> None:
        self.fail[op] = times

    def _maybe_fail(self, op: str) -> None:
        n = self.fail.get(op, 0)
        if n:
            if n > 0:
                self.fail[op] = n - 1
            raise TpuError(f"injected fault in {op}")

    # ---- contract --------------------------------------------------------

    def discover(self) -> SliceTopology:
        self._maybe_fail("discover")
        self.op_log.append(("discover", None))
        return self._topology

    def query_cc_mode(self, chip: TpuChip) -> str:
        self._maybe_fail("query")
        with self._lock:
            return self.committed[chip.index]

    def stage_cc_mode(self, chips: tuple[TpuChip, ...], mode: str) -> None:
        self._maybe_fail("stage")
        with self._lock:
            for chip in chips:
                self.staged[chip.index] = mode
            self.op_log.append(("stage", (tuple(c.index for c in chips), mode)))

    def clear_staged(self, chips: tuple[TpuChip, ...]) -> None:
        self._maybe_fail("clear_staged")
        with self._lock:
            for chip in chips:
                self.staged.pop(chip.index, None)
            self.op_log.append(
                ("clear_staged", tuple(c.index for c in chips))
            )

    def set_brownout(self, factor: float) -> None:
        """Arm (factor > 1) or clear (factor = 1) a brownout: inflate
        every reset/boot wall while leaving health probes green — the
        seeded gray-failure scenario the fail-slow detector exists
        for."""
        self.brownout_factor = max(1.0, float(factor))

    def _latency_for(self, spec: float | list[float], index: int) -> float:
        """Per-chip latency from a scalar-or-list spec (lists are
        index-aligned; a short list repeats its last value), scaled by
        the brownout factor while one is armed."""
        if isinstance(spec, (list, tuple)):
            if not spec:
                return 0.0
            base = float(spec[index] if index < len(spec) else spec[-1])
        else:
            base = float(spec)
        return base * self.brownout_factor

    def _reset_one_chip(self, chip: TpuChip) -> None:
        """One chip's share of a per-chip reset: its own fault point, its
        own latency, its own span — and its own committed promotion, so a
        crash mid-pool leaves untouched chips still staged (crash-as-retry
        re-applies exactly those)."""
        self._maybe_fail(f"reset.chip{chip.index}")
        with obs_trace.span("reset.chip", chip=chip.index):
            delay = self._latency_for(self.reset_latency_s, chip.index)
            if delay:
                time.sleep(delay)
            with self._lock:
                if chip.index in self.staged:
                    self.committed[chip.index] = self.staged.pop(chip.index)
                self.booted[chip.index] = False
                self._boot_done_at[chip.index] = time.monotonic() + (
                    self._latency_for(self.boot_latency_s, chip.index)
                )
                self.op_log.append(("reset.chip", chip.index))

    def reset(self, chips: tuple[TpuChip, ...]) -> None:
        self._maybe_fail("reset")
        if isinstance(self.reset_latency_s, (list, tuple)):
            # Per-chip latencies fan out across a bounded worker pool
            # (contract: pending state for every chip is already durable —
            # the manager staged all chips before calling reset — and each
            # chip promotes only after its own work finishes).
            workers = self.reset_parallelism_override or reset_parallelism()
            with ThreadPoolExecutor(
                max_workers=max(1, min(workers, len(chips)))
            ) as pool:
                futures = [
                    pool.submit(
                        obs_trace.in_current_context(self._reset_one_chip, c)
                    )
                    for c in chips
                ]
            raise_pool_errors(
                [f.exception() for f in futures if f.exception()]
            )
            self._finish_reset(chips)
            return
        scalar_wall = self._latency_for(self.reset_latency_s, 0)
        if scalar_wall:
            time.sleep(scalar_wall)
        with self._lock:
            now = time.monotonic()
            for chip in chips:
                if chip.index in self.staged:
                    self.committed[chip.index] = self.staged.pop(chip.index)
                self.booted[chip.index] = False
                self._boot_done_at[chip.index] = now + self._latency_for(
                    self.boot_latency_s, chip.index
                )
        self._finish_reset(chips)

    def _finish_reset(self, chips: tuple[TpuChip, ...]) -> None:
        """Shared reset epilogue (runtime env + the whole-set op-log entry
        ordering tests key on)."""
        with self._lock:
            modes = sorted(set(self.committed.values()))
            if len(modes) == 1:
                from tpu_cc_manager.tpudev.tpuvm import runtime_env_for_mode

                self.runtime_env = {
                    k: v
                    for k, _, v in (
                        line.partition("=")
                        for line in runtime_env_for_mode(modes[0]).splitlines()
                        if "=" in line
                    )
                }
            self.op_log.append(("reset", tuple(c.index for c in chips)))

    def wait_ready(self, chips: tuple[TpuChip, ...], timeout_s: float) -> None:
        self._maybe_fail("wait_ready")
        deadline = time.monotonic() + timeout_s
        for chip in chips:
            while True:
                with self._lock:
                    ready_at = self._boot_done_at.get(chip.index, 0.0)
                    if time.monotonic() >= ready_at:
                        self.booted[chip.index] = True
                        break
                if time.monotonic() >= deadline:
                    raise TpuError(f"chip {chip.index} did not become ready")
                time.sleep(0.01)
        self.op_log.append(("wait_ready", tuple(c.index for c in chips)))

    def restart_runtime(self) -> None:
        """Distinct remediation op (vs ``reset``) so chaos plans can arm
        terminal faults per ladder rung and tests can assert which rung
        ran."""
        self._maybe_fail("restart_runtime")
        with self._lock:
            now = time.monotonic()
            for chip in self._chips:
                self.booted[chip.index] = False
                self._boot_done_at[chip.index] = now + self._latency_for(
                    self.boot_latency_s, chip.index
                )
            self.op_log.append(
                ("restart_runtime", tuple(c.index for c in self._chips))
            )

    def set_preempted(self, preempted: bool = True) -> None:
        """Arm (or clear) the platform preemption notice — the injectable
        fake counterpart of the GCE metadata server's ``instance/
        preempted`` flag flipping to TRUE."""
        with self._lock:
            self.preempted = preempted

    def preemption_notice(self) -> bool:
        self._maybe_fail("preemption_notice")
        with self._lock:
            return self.preempted

    def probe_runtime_health(self) -> HealthProbe:
        self._maybe_fail("probe")
        with self._lock:
            return HealthProbe(
                self.health_tier, self.healthy,
                "fake probe " + ("healthy" if self.healthy else "unhealthy"),
            )

    def fetch_attestation(self, nonce: str) -> AttestationQuote:
        self._maybe_fail("attest")
        with self._lock:
            modes = sorted(set(self.committed.values()))
            mode = modes[0] if len(modes) == 1 else "mixed"
            measurements = {
                "accelerator_type": self._topology.accelerator_type,
                "num_chips": str(len(self._chips)),
                "runtime_digest": hashlib.sha256(b"fake-tpu-runtime").hexdigest(),
                "cc_mode": mode,
            }
        sig = sign_fake_quote(self._topology.slice_id, nonce, mode, measurements)
        self.op_log.append(("attest", nonce))
        return AttestationQuote(
            slice_id=self._topology.slice_id,
            nonce=nonce,
            mode=mode,
            measurements=measurements,
            signature=sig,
            platform="fake",
        )
