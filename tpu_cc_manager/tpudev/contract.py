"""The device-layer contract the control loop consumes.

This is the TPU re-creation of the interface the reference consumes from
gpu-admin-tools (SURVEY.md §1 L1: find_gpus, query/set cc & ppcie mode,
reset_with_os, wait_for_boot, GpuError), redesigned around the one structural
difference between the two fabrics: **a TPU slice is the unit of CC state,
not a chip**. GPUs are staged per-device and reset per-device (with PPCIe as
a special fabric-atomic mode, reference main.py:317-391); an ICI-connected
TPU slice must always be staged together and reset together, so fabric
atomicity is structural here — ``reset`` takes the whole chip set and there
is no per-chip reset at all.

Second addition with no reference counterpart: attestation. A CC transition
on TPU is only trustworthy if the post-reset slice produces a verifiable
quote, so ``fetch_attestation`` is part of the contract and the verify phase
checks it (SURVEY.md §3.4 "TPU mapping").
"""

from __future__ import annotations

import abc
import os
from dataclasses import dataclass, field

# Bounded worker-pool width for backends that fan per-chip reset work out
# in parallel (tpuvm's per-chip reset commands, the fake's per-chip
# latencies). The pool is bounded — a 8-chip host must not spawn 8
# concurrent device commands against a driver that serializes them anyway
# — and 1 restores the fully serial walk.
DEFAULT_RESET_PARALLELISM = 4
RESET_PARALLELISM_ENV = "CC_RESET_PARALLELISM"


def reset_parallelism(default: int = DEFAULT_RESET_PARALLELISM) -> int:
    """The configured per-chip reset fan-out width (>=1)."""
    try:
        value = int(os.environ.get(RESET_PARALLELISM_ENV, "") or default)
    except ValueError:
        value = default
    return max(1, value)


def raise_pool_errors(errors: list, what: str = "per-chip reset") -> None:
    """Re-raise the worker errors from a per-chip pool with the right
    type: a BaseException that is not an Exception (a modeled SIGKILL in
    tests) must unwind as a CRASH — never laundered into a catchable
    device error; device errors aggregate into ONE TpuError naming every
    failed worker (an operator fixing only errors[0]'s chip and retrying
    into the next failure, one bounce at a time, is the failure mode this
    exists to avoid)."""
    if not errors:
        return
    for e in errors:
        if not isinstance(e, Exception):
            raise e  # crash model: unwind first, diagnosis is moot
    if len(errors) == 1 and isinstance(errors[0], TpuError):
        raise errors[0]
    detail = "; ".join(str(e)[:256] for e in errors)
    raise TpuError(f"{what} failed on {len(errors)} worker(s): {detail}")


class TpuError(Exception):
    """Device-layer failure (reference analogue: GpuError, main.py:40).

    The control loop catches this, labels the node ``failed``, and keeps
    watching (reference main.py:531-538)."""


# Runtime-health probe tiers, strongest signal first. The rank (value) is
# exported as a metric by the watchdog so a fleet silently degraded to the
# weakest probe (bare device-node existence — nodes persist across a wedged
# runtime) is visible, not implicit.
HEALTH_TIER_STRENGTH = {
    "health-port": 4,   # the runtime's own liveness port answers
    "probe-cmd": 3,     # operator-supplied probe command exits 0
    "systemd": 2,       # the runtime unit reports ActiveState=active
    "device-node": 1,   # the device nodes merely exist
    "none": 0,          # no probe available at all
}


@dataclass(frozen=True)
class HealthProbe:
    """One runtime-health probe result: which tier answered, its verdict,
    and a human-readable detail for events/logs."""

    tier: str
    healthy: bool
    detail: str = ""

    @property
    def strength(self) -> int:
        return HEALTH_TIER_STRENGTH.get(self.tier, 0)


@dataclass(frozen=True)
class TpuChip:
    """One TPU chip as seen from this host."""

    index: int                 # host-local chip index
    device_path: str           # e.g. /dev/accel0 or /dev/vfio/…
    chip_type: str             # "v5e" | "v5p" | "v6e" | …
    cc_supported: bool         # chip+platform can run confidential workloads
    slice_cc_supported: bool   # chip can join a multi-host slice-wide CC domain

    @property
    def name(self) -> str:
        return f"{self.chip_type}:{self.device_path}"


@dataclass(frozen=True)
class SliceTopology:
    """The ICI domain this host belongs to (the NVLink-fabric analogue)."""

    slice_id: str              # stable id of the ICI domain
    accelerator_type: str      # e.g. "v5p-32"
    num_hosts: int             # hosts in the slice (1 for single-host types)
    host_index: int            # this host's position in the slice
    chips: tuple[TpuChip, ...] = field(default_factory=tuple)

    @property
    def is_multi_host(self) -> bool:
        return self.num_hosts > 1

    def cc_capable_chips(self) -> tuple[TpuChip, ...]:
        return tuple(c for c in self.chips if c.cc_supported)

    def slice_cc_capable_chips(self) -> tuple[TpuChip, ...]:
        return tuple(c for c in self.chips if c.slice_cc_supported)


@dataclass(frozen=True)
class AttestationQuote:
    """Evidence that the slice booted into the reported CC mode.

    ``measurements`` carries the platform's POOL-COMPARABLE claims (mode,
    runtime digest, libtpu version…): every healthy host of one pool must
    produce identical values, and :func:`attestation.quote_digest` hashes
    them for the cross-slice equality check (ccmanager/multislice.py).

    ``host_evidence`` carries PER-HOST facts (systemd activation stamp,
    configfs-tsm guest report) that would break cross-host digest equality
    — excluded from the digest, still available to the verifier.

    ``signature`` binds the caller's nonce.
    """

    slice_id: str
    nonce: str
    mode: str
    measurements: dict[str, str]
    signature: str
    platform: str  # "fake" | "tpuvm"
    host_evidence: dict[str, str] = field(default_factory=dict)


class TpuCcBackend(abc.ABC):
    """What the reconciler calls. All methods may raise TpuError.

    Call sequence for a mode change (reference phases at main.py:449-542,
    restructured for slice atomicity):

        topo = discover()
        stage_cc_mode(chips, mode)    # write desired mode, no disruption yet
        reset(chips)                  # commit: whole-chip-set reset
        wait_ready(chips, timeout)    # runtime back up
        query_cc_mode(chip) == mode   # verify, per chip
        fetch_attestation(nonce)      # verify the platform agrees
    """

    @abc.abstractmethod
    def discover(self) -> SliceTopology:
        """Enumerate this host's chips and slice membership
        (reference analogue: find_gpus(), main.py:144-155)."""

    @abc.abstractmethod
    def query_cc_mode(self, chip: TpuChip) -> str:
        """Current committed CC mode of a chip: on|off|devtools|slice
        (reference analogue: query_cc_mode, main.py:441)."""

    @abc.abstractmethod
    def stage_cc_mode(self, chips: tuple[TpuChip, ...], mode: str) -> None:
        """Stage a mode on a set of chips without committing it. Staging is
        batched (all chips in one call) because TPU CC config is a slice
        property (reference analogue: per-gpu set_cc_mode, main.py:511,
        batched by the caller)."""

    def clear_staged(self, chips: tuple[TpuChip, ...]) -> None:
        """Withdraw a staged-but-uncommitted mode from ``chips`` — the
        rollback half of ``stage_cc_mode``. The intent-journal replayer
        (ccmanager/intent_journal.py) calls this when a crash interrupted
        a transition BEFORE its reset: nothing disruptive ran, so the
        clean recovery is to roll the staging back rather than re-drive a
        transition the desired label may no longer want. Idempotent; the
        default is a no-op for backends whose staging has no durable
        side effects."""

    @abc.abstractmethod
    def reset(self, chips: tuple[TpuChip, ...]) -> None:
        """Commit staged modes by resetting the chip set together. The whole
        set goes down at once — fabric atomicity is structural (reference
        analogue: the reset-all loop, main.py:514-519 / :362-368).

        Implementations with per-chip reset work may fan it out across a
        bounded worker pool (:func:`reset_parallelism`,
        CC_RESET_PARALLELISM) PROVIDED the crash ordering is preserved:
        the pending/"resetting" markers for every chip land durably
        before ANY chip's disruptive work starts, and no chip promotes to
        committed until its own reset verifiably finished — a crash
        anywhere still reads "resetting" for every touched chip and
        crash-as-retry re-applies. Per-chip workers should open their own
        obs span (``reset.chip``) so the bench can compare the pipeline's
        wall time against the serial-equivalent sum."""

    @abc.abstractmethod
    def wait_ready(self, chips: tuple[TpuChip, ...], timeout_s: float) -> None:
        """Block until the runtime is healthy on every chip, or raise
        TpuError (reference analogue: wait_for_boot, main.py:523)."""

    @abc.abstractmethod
    def fetch_attestation(self, nonce: str) -> AttestationQuote:
        """Produce a quote for the slice's current state bound to ``nonce``.
        New capability — no reference counterpart (SURVEY.md §0(b))."""

    def prepare_attestation(self) -> None:
        """Warm whatever ``fetch_attestation`` can precompute without the
        post-reset runtime state (the tpuvm backend hashes an O(100 MB)
        libtpu into its measured-file memo here). The manager overlaps
        this with the wait-ready poll so the attest phase after boot pays
        only the nonce-bound work. Advisory: failures must be swallowed
        by callers, and the quote fetched later must not depend on this
        having run. Default: nothing to warm."""

    def probe_runtime_health(self) -> HealthProbe:
        """One health probe using the strongest tier this backend has
        available (see HEALTH_TIER_STRENGTH). Consumed by the runtime-health
        watchdog between reconciles; ``wait_ready`` implementations should
        share the same probe so "ready" and "still healthy" can never
        disagree on methodology. Default: no probe capability."""
        return HealthProbe(tier="none", healthy=True, detail="no probe available")

    def restart_runtime(self) -> None:
        """Restart the TPU runtime WITHOUT changing the committed mode —
        the remediation ladder's rung above a device re-reset
        (ccmanager/remediation.py). Default: a reset of the discovered
        chip set with nothing staged, which for the tpuvm backend IS the
        runtime-restart commit path and leaves the committed mode
        untouched. May raise TpuError."""
        self.reset(self.discover().chips)

    def preemption_notice(self) -> bool:
        """Whether the platform has signaled IMMINENT preemption of this
        VM (spot/preemptible reclaim). On GCE the signal is the metadata
        server's ``instance/preempted`` flag, delivered with a hard
        termination deadline far shorter than the normal 300 s drain
        budget — the manager's preemption monitor polls this and runs the
        fast-drain + handoff path (drain/evict.py fast_drain_components,
        ccmanager/manager.py) instead of the full drain. Default: never
        preempted (on-demand hosts, test backends)."""
        return False
