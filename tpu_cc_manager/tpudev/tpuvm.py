"""Real TPU VM backend.

The reference's device layer flips PCI config bits and resets the GPU
(gpu-admin-tools; SURVEY.md §1 L1). TPUs expose no user-visible equivalent,
so this backend follows the design SURVEY.md §7.2 prescribes: the CC mode is
carried as *runtime configuration* (persisted in a state dir), committed by
**restarting the TPU runtime** for the whole host at once, verified by
runtime health + a platform attestation (GCE instance-identity JWT from the
metadata server; on SEV-SNP/TDX hosts the VM-level evidence is implicit in
the platform's confidential-VM identity claims).

Everything environment-touching is injectable (commands, paths, metadata
URL) so the backend is unit-testable on any machine; on a non-TPU host
``discover`` raises TpuError and the CLI tells the operator to use
``--tpu-backend=fake``.
"""

from __future__ import annotations

import glob
import json
import logging
import os
import socket
import subprocess
import sys
import time
import urllib.error
import urllib.request

from concurrent.futures import ThreadPoolExecutor

from tpu_cc_manager.labels import MODE_OFF, VALID_MODES
from tpu_cc_manager.obs import trace as obs_trace
from tpu_cc_manager.tpudev.contract import (
    AttestationQuote,
    HealthProbe,
    SliceTopology,
    TpuCcBackend,
    TpuChip,
    TpuError,
    raise_pool_errors,
    reset_parallelism,
)
from tpu_cc_manager.utils import retry as retry_mod

log = logging.getLogger(__name__)

METADATA_URL = "http://metadata.google.internal/computeMetadata/v1"
DEFAULT_STATE_DIR = "/var/lib/tpu-cc-manager"
# Restarting the runtime is the commit point (the reset_with_os analogue,
# reference main.py:519). Overridable for non-systemd hosts.
DEFAULT_RESET_CMD = ["systemctl", "restart", "tpu-runtime"]
DEFAULT_HEALTH_PROBE_CMD = None  # None -> health_port / systemd / device-node probe
# Cross-check that the reset actually bounced the runtime: the reference's
# device layer reads truth back from the hardware (reset_with_os +
# wait_for_boot query the device, main.py:519-528); the systemd unit's
# monotonic activation timestamp is this backend's equivalent ground truth.
DEFAULT_SHOW_CMD = [
    "systemctl", "show", "tpu-runtime",
    "--property=ActiveState,ActiveEnterTimestampMonotonic",
]

# Files whose content IS the runtime's identity: the libtpu library the
# runtime loads, its systemd unit, and its environment/config files. Their
# hashes form the attested runtime digest — change any of them and the
# digest provably changes (the reference reads truth back from the device,
# main.py:524-528; this is the TPU equivalent of measuring what actually
# runs rather than what the manager believes). Overridable via
# CC_RUNTIME_MEASURE_PATHS (colon-separated globs).
DEFAULT_MEASURE_GLOBS = [
    "/lib/systemd/system/tpu-runtime.service",
    "/etc/systemd/system/tpu-runtime.service",
    "/etc/systemd/system/tpu-runtime.service.d/*.conf",
    "/etc/default/tpu-runtime",
    "/lib/libtpu.so",
    "/usr/lib/libtpu.so",
    "/usr/lib/tpu/libtpu.so",
    "/usr/share/tpu/libtpu*.so",
]
MEASURE_PATHS_ENV = "CC_RUNTIME_MEASURE_PATHS"

# The runtime environment file the mode is carried in (an EnvironmentFile=
# of the runtime unit). ``devtools`` stages debug/trace flags here — the
# backend-visible difference from ``on`` — committed by the runtime restart
# like any mode change. The file is on DEFAULT_MEASURE_GLOBS, so a devtools
# runtime attests a DIFFERENT runtime digest than a production-CC runtime
# (the reference's devtools is a real hardware mode, main.py:214-263; the
# TPU analogue is a measurably distinct runtime configuration). Disabled
# when unset: tests and non-systemd hosts must not write /etc.
RUNTIME_ENV_FILE_ENV = "CC_RUNTIME_ENV_FILE"

_MODE_RUNTIME_ENV = {
    "devtools": (
        "TPU_MIN_LOG_LEVEL=0\n"
        "TPU_STDERR_LOG_LEVEL=0\n"
        "TPU_VMODULE=tpu_configuration=2,tpu_driver=1\n"
    ),
}


def runtime_env_for_mode(mode: str) -> str:
    """Content of the runtime EnvironmentFile for a committed mode."""
    return (
        "# Managed by tpu-cc-manager; rewritten on every CC mode commit.\n"
        f"TPU_CC_MODE={mode}\n" + _MODE_RUNTIME_ENV.get(mode, "")
    )


# configfs-tsm: the kernel's TSM report interface inside TDX/SEV-SNP guests
# (kernel >= 6.7). mkdir a report dir, write the nonce-derived challenge to
# ``inblob``, read the signed ``outblob`` back — a REAL guest report from
# the CPU's security processor, alongside the metadata-server JWT.
DEFAULT_TSM_ROOT = "/sys/kernel/config/tsm/report"
TSM_ROOT_ENV = "CC_TSM_ROOT"

# Optional per-chip reset command (space-separated template; ``{device}``
# and ``{index}`` substitute per chip). When set, the commit point is one
# command PER CHIP fanned out across a bounded worker pool
# (CC_RESET_PARALLELISM) instead of the host-global runtime restart —
# for runtimes whose chips expose individual reset entry points (vfio
# unbind/rebind, per-accel reset nodes). Crash ordering is preserved:
# pending markers for EVERY chip land durably before any chip's command
# runs, and committed promotion happens only after all succeed. The
# command's EXIT STATUS is the authority that the chip actually reset
# (there is no host-global activation stamp to cross-check on this
# path) — point it at something that fails when the reset did not take,
# not at a fire-and-forget trigger. Incompatible with
# CC_RUNTIME_ENV_FILE (host-global mode env needs a host-global
# restart; reset() refuses the combination loudly). Unset (the default)
# keeps the host-global restart + activation-stamp cross-check exactly
# as before.
PER_CHIP_RESET_CMD_ENV = "CC_RESET_PER_CHIP_CMD"

# The distroless container image ships no systemctl/nsenter; host commands
# run through a Python chroot into the host rootfs mounted at this path
# (deployments/manifests/daemonset.yaml mounts / as /host with
# HostToContainer propagation; the pod is privileged, so CAP_SYS_CHROOT is
# present). Unset = run commands directly (bare-metal / test usage).
HOST_ROOT_ENV = "CC_HOST_ROOT"


def _host_path(path: str) -> str:
    """Prefix a host path with CC_HOST_ROOT when running containerized
    (identity otherwise) — the one place the host/container path mapping
    for file access lives (command execution maps via host_wrap)."""
    return os.environ.get(HOST_ROOT_ENV, "") + path


def host_wrap(cmd: list[str], host_root: str | None = None) -> list[str]:
    """Wrap a command to execute inside the host rootfs when CC_HOST_ROOT
    (or ``host_root``) is set; identity otherwise. The wrapper chroots and
    then REPLACES itself with the command (execvp) — the wrapper process
    IS the command, so the caller's capture/timeout/kill semantics reach
    the real command instead of orphaning a grandchild."""
    root = host_root if host_root is not None else os.environ.get(HOST_ROOT_ENV)
    if not root or not cmd:
        return list(cmd)
    return [
        sys.executable, "-c",
        "import os,sys;"
        "os.chroot(sys.argv[1]);os.chdir('/');"
        "os.execvp(sys.argv[2], sys.argv[2:])",
        root, *cmd,
    ]


def classify_subprocess_error(e: BaseException) -> retry_mod.Classification | None:
    """Transient-vs-permanent verdict for host device commands (systemctl
    restart & co). A missing binary never improves with repetition; a
    non-zero exit or a timeout plausibly does (dbus hiccup, a unit mid-
    restart), and gets exactly the one classified retry the policy allows."""
    if isinstance(e, retry_mod.CircuitOpenError):
        return retry_mod.Classification(False, "circuit-open")
    if isinstance(e, FileNotFoundError):
        return retry_mod.Classification(False, "not-found")
    if isinstance(e, subprocess.TimeoutExpired):
        return retry_mod.Classification(True, "timeout")
    if isinstance(e, subprocess.CalledProcessError):
        return retry_mod.Classification(True, f"rc-{e.returncode}")
    if isinstance(e, OSError):
        return retry_mod.Classification(True, "os-error")
    return None

# chips per host by generation (v4/v5p: 4 chips/host; v5e/v6e: up to 8).
_CHIPS_PER_HOST = {"v4": 4, "v5p": 4, "v5e": 8, "v6e": 8}
# cores per chip: megacore generations report 1 core/chip to accelerator-type
# counts on v5e/v6e; v4/v5p accelerator-type counts are TensorCores (2/chip).
_CORES_PER_CHIP = {"v4": 2, "v5p": 2, "v5e": 1, "v6e": 1}


def parse_accelerator_type(accel: str) -> tuple[str, int, int]:
    """``v5p-32`` -> (generation, total_chips, num_hosts)."""
    try:
        gen, _, count = accel.partition("-")
        cores = int(count)
    except ValueError as e:
        raise TpuError(f"unparseable accelerator type {accel!r}") from e
    gen = gen.lower()
    if gen.startswith("v5lite"):
        gen = "v5e"
    cores_per_chip = _CORES_PER_CHIP.get(gen, 2)
    chips = max(1, cores // cores_per_chip)
    per_host = _CHIPS_PER_HOST.get(gen, 4)
    hosts = max(1, (chips + per_host - 1) // per_host)
    return gen, chips, hosts


class TpuVmBackend(TpuCcBackend):
    def __init__(
        self,
        state_dir: str = DEFAULT_STATE_DIR,
        reset_cmd: list[str] | None = None,
        health_probe_cmd: list[str] | None = DEFAULT_HEALTH_PROBE_CMD,
        show_cmd: list[str] | None = None,
        health_port: int | None = None,
        metadata_url: str = METADATA_URL,
        device_glob: str = "/dev/accel*",
        vfio_glob: str = "/dev/vfio/[0-9]*",
        measure_globs: list[str] | None = None,
        tsm_root: str | None = None,
        runtime_env_file: str | None = None,
        cc_guest_devices: tuple[str, ...] = ("/dev/tdx_guest", "/dev/sev-guest"),
        per_chip_reset_cmd: list[str] | None = None,
    ) -> None:
        self.state_dir = state_dir
        self.reset_cmd = host_wrap(reset_cmd or list(DEFAULT_RESET_CMD))
        if per_chip_reset_cmd is None:
            env = os.environ.get(PER_CHIP_RESET_CMD_ENV)
            per_chip_reset_cmd = env.split() if env else None
        # Template, host-wrapped at run time (after {device}/{index}
        # substitution); None keeps the host-global restart commit path.
        self.per_chip_reset_cmd = per_chip_reset_cmd
        self.health_probe_cmd = (
            host_wrap(health_probe_cmd) if health_probe_cmd else health_probe_cmd
        )
        # show_cmd=[] (or CC_RUNTIME_SHOW_CMD="") disables the systemd
        # cross-checks on non-systemd hosts; None means the default.
        if show_cmd is None:
            env = os.environ.get("CC_RUNTIME_SHOW_CMD")
            show_cmd = env.split() if env is not None else list(DEFAULT_SHOW_CMD)
        self.show_cmd = host_wrap(show_cmd) if show_cmd else show_cmd
        if health_port is None:
            health_port = int(os.environ.get("CC_RUNTIME_HEALTH_PORT", "0")) or None
        self.health_port = health_port
        self.metadata_url = metadata_url
        self.device_glob = device_glob
        self.vfio_glob = vfio_glob
        # The activation stamp is a HOST fact, but query_cc_mode is per-chip
        # (contract parity): a short-TTL memo keeps an idempotency sweep
        # over N chips at one subprocess instead of N. Set to 0 to disable
        # (tests that rewrite the injected show output mid-flow do).
        self.stamp_cache_ttl_s = 0.5
        self._stamp_cache: tuple[float, tuple[str, int] | None] | None = None
        if measure_globs is None:
            env = os.environ.get(MEASURE_PATHS_ENV)
            measure_globs = env.split(":") if env else list(DEFAULT_MEASURE_GLOBS)
        self.measure_globs = measure_globs
        if tsm_root is None:
            # Like the measured files, the host's configfs is only visible
            # under CC_HOST_ROOT when running containerized.
            tsm_root = _host_path(os.environ.get(TSM_ROOT_ENV, DEFAULT_TSM_ROOT))
        self.tsm_root = tsm_root
        # (size, mtime_ns) -> sha256 memo per path: libtpu is O(100 MB) and
        # re-attestation happens on every idempotent sweep.
        self._file_hash_cache: dict[str, tuple[tuple[int, int], str]] = {}
        if runtime_env_file is None:
            runtime_env_file = os.environ.get(RUNTIME_ENV_FILE_ENV) or None
        # A HOST path (CC_HOST_ROOT-prefixed at write time); None disables.
        self.runtime_env_file = runtime_env_file
        # Confidential-VM guest device nodes (TDX/SEV-SNP surface these
        # inside a CC VM); injectable so multi-host tests can model
        # CC-capable hosts without kernel support on the test box.
        self.cc_guest_devices = tuple(cc_guest_devices)
        # Device-command path protection: one classified retry per command
        # (utils/retry.py; a dbus hiccup should not fail a whole reconcile)
        # behind a breaker so a host whose systemctl keeps failing fails
        # fast instead of stacking 120 s command timeouts every reconcile.
        self.retry_policy = retry_mod.RetryPolicy(
            max_attempts=2, base_delay_s=1.0, max_delay_s=5.0
        )
        self.breaker = retry_mod.CircuitBreaker(
            "device-cmd", failure_threshold=4, recovery_time_s=60.0
        )
        # Whether the configured health port has EVER answered: until it
        # has, a refused connection means "this runtime build has no
        # liveness port" (the manifest defaults the env on) and the probe
        # falls through to the next tier instead of failing the whole
        # fleet closed; once seen, refusal means the runtime is down.
        self._health_port_seen = False

    # ---- metadata / persistence helpers ---------------------------------

    def _metadata(self, path: str, default: str | None = None) -> str | None:
        req = urllib.request.Request(
            f"{self.metadata_url}/{path}", headers={"Metadata-Flavor": "Google"}
        )
        try:
            with urllib.request.urlopen(req, timeout=2) as resp:
                return resp.read().decode("utf-8").strip()
        except (urllib.error.URLError, OSError, TimeoutError):
            return default

    def _state_path(self, name: str) -> str:
        return os.path.join(self.state_dir, name)

    def _read_state(self, name: str) -> dict:
        try:
            with open(self._state_path(name), "r", encoding="utf-8") as f:
                return json.load(f)
        except FileNotFoundError:
            return {}
        except (OSError, json.JSONDecodeError) as e:
            raise TpuError(f"corrupt device state file {name}: {e}") from e

    def _write_state(self, name: str, payload: dict) -> None:
        os.makedirs(self.state_dir, exist_ok=True)
        tmp = self._state_path(name) + ".tmp"
        with open(tmp, "w", encoding="utf-8") as f:
            json.dump(payload, f)
        os.replace(tmp, self._state_path(name))

    # ---- device-command path --------------------------------------------

    def _run_device_cmd(
        self, cmd: list[str], *, op: str, timeout: float
    ) -> subprocess.CompletedProcess:
        """Run a host command with one classified retry (transient rc /
        timeout / OS error) behind the device-command breaker. Permanent
        failures (missing binary) and exhausted retries propagate the
        original subprocess exception so callers keep their error mapping.
        """
        def attempt() -> subprocess.CompletedProcess:
            # Gated PER ATTEMPT: a transient failure on attempt 1 can open
            # the circuit mid-ladder, and attempt 2 must then fail fast
            # instead of running another (up to 120 s) command against the
            # known-bad path. CircuitOpenError classifies permanent.
            self.breaker.before_call()
            try:
                return subprocess.run(
                    cmd, check=True, capture_output=True, timeout=timeout
                )
            except BaseException as e:
                verdict = classify_subprocess_error(e)
                if verdict is not None and verdict.transient:
                    self.breaker.record_failure()
                else:
                    # Permanent (missing binary) says nothing about the
                    # command path's health — release a held half-open
                    # probe slot so the breaker can't wedge on it.
                    self.breaker.record_permanent()
                raise

        result = self.retry_policy.call(
            attempt, op=op, classify=classify_subprocess_error
        )
        self.breaker.record_success()
        return result

    # ---- runtime ground truth (systemd) ---------------------------------

    def _runtime_stamp(self, fresh: bool = False) -> tuple[str, int] | None:
        """(ActiveState, ActiveEnterTimestampMonotonic µs) of the runtime
        unit, or None when the probe is disabled/unavailable. The monotonic
        activation timestamp is the backend's ground truth for "the runtime
        actually restarted" — state files alone can never disagree with the
        manager that wrote them.

        ``fresh`` bypasses the short-TTL memo — the reset pre/post stamps
        must never see a cached value."""
        if not self.show_cmd:
            return None
        if not fresh and self._stamp_cache is not None:
            cached_at, value = self._stamp_cache
            if time.monotonic() - cached_at < self.stamp_cache_ttl_s:
                return value
        try:
            out = subprocess.run(
                self.show_cmd, capture_output=True, timeout=10, check=True
            ).stdout.decode("utf-8", "replace")
        except (OSError, subprocess.SubprocessError):
            return None
        state: str | None = None
        ts: int | None = None
        for line in out.splitlines():
            key, _, value = line.partition("=")
            if key == "ActiveState":
                state = value.strip()
            elif key == "ActiveEnterTimestampMonotonic":
                try:
                    ts = int(value.strip())
                except ValueError:
                    pass
        # ts stays None when the property is absent/garbled: "no timestamp"
        # must read as probe-unavailable, not as 0 — a show_cmd that emits
        # only ActiveState would otherwise fail every restart cross-check.
        result = (
            None
            if state is None and ts is None
            else (state or "unknown", ts)
        )
        self._stamp_cache = (time.monotonic(), result)
        return result

    def _host_is_confidential(self) -> bool:
        return any(os.path.exists(p) for p in self.cc_guest_devices)

    # ---- contract --------------------------------------------------------

    def discover(self) -> SliceTopology:
        device_paths = sorted(glob.glob(self.device_glob)) or sorted(
            glob.glob(self.vfio_glob)
        )
        accel = (
            os.environ.get("TPU_ACCELERATOR_TYPE")
            or self._metadata("instance/attributes/accelerator-type")
        )
        if not device_paths and not accel:
            raise TpuError(
                "no TPU devices found (no /dev/accel*, no accelerator-type "
                "metadata) — not a TPU VM? use --tpu-backend=fake for dry-runs"
            )
        accel = accel or f"v5e-{len(device_paths)}"
        gen, total_chips, num_hosts = parse_accelerator_type(accel)
        worker_id = int(
            os.environ.get("TPU_WORKER_ID")
            or self._metadata("instance/attributes/agent-worker-number", "0")
            or 0
        )
        slice_id = (
            os.environ.get("TPU_SLICE_ID")
            or self._metadata("instance/attributes/tpu-env-slice-id")
            or f"{accel}-{self._metadata('instance/id', 'local')}"
        )
        # Confidential support: the VM itself must be confidential. Probe the
        # same host signals the reference probes for TDX/SEV-SNP
        # (main.py:80-103), which surface inside a CC VM as /dev/tdx_guest or
        # /dev/sev-guest.
        host_cc = self._host_is_confidential()
        if not device_paths:
            # Multi-host slices schedule one worker per host; synthesize this
            # host's chip share when the device nodes are containerized away.
            per_host = max(1, total_chips // num_hosts)
            device_paths = [f"/dev/accel{i}" for i in range(per_host)]
        chips = tuple(
            TpuChip(
                index=i,
                device_path=p,
                chip_type=gen,
                cc_supported=host_cc,
                slice_cc_supported=host_cc and num_hosts > 1,
            )
            for i, p in enumerate(device_paths)
        )
        return SliceTopology(
            slice_id=str(slice_id),
            accelerator_type=accel,
            num_hosts=num_hosts,
            host_index=worker_id,
            chips=chips,
        )

    def query_cc_mode(self, chip: TpuChip) -> str:
        pending = self._read_state("pending.json")
        if str(chip.index) in pending:
            # A reset started but never finished (crash / failed restart):
            # the true hardware mode is unknown, so report a value that can
            # never satisfy an idempotency check.
            return "resetting"
        committed = self._read_state("committed.json")
        mode = committed.get(str(chip.index), committed.get("*", MODE_OFF))
        if mode not in VALID_MODES:
            return MODE_OFF
        if mode != MODE_OFF:
            # External-restart detection: if the runtime's activation stamp
            # no longer matches the one recorded at commit time, something
            # other than this manager bounced the runtime — the committed
            # mode can no longer be trusted, so report an in-between state
            # that fails every idempotency check and forces a full re-apply
            # (re-commit + re-attest).
            recorded = self._read_state("runtime.json").get("enter_ts")
            if recorded:
                current = self._runtime_stamp()
                if (
                    current is not None
                    and current[1] is not None
                    and current[1] != recorded
                ):
                    log.warning(
                        "TPU runtime restarted outside the manager "
                        "(activation stamp %d != committed %d); reporting "
                        "'resetting'", current[1], recorded,
                    )
                    return "resetting"
        return mode

    def stage_cc_mode(self, chips: tuple[TpuChip, ...], mode: str) -> None:
        staged = self._read_state("staged.json")
        for chip in chips:
            staged[str(chip.index)] = mode
        self._write_state("staged.json", staged)
        log.info("staged mode=%s on %d chip(s)", mode, len(chips))

    def clear_staged(self, chips: tuple[TpuChip, ...]) -> None:
        """Roll a staged-but-never-reset mode back out of staged.json (the
        intent-journal replayer's pre-reset rollback). Idempotent — chips
        that never staged are skipped — and leaves committed/pending state
        untouched, so query_cc_mode keeps reporting hardware truth."""
        staged = self._read_state("staged.json")
        dropped = [
            k for k in (str(c.index) for c in chips) if staged.pop(k, None)
        ]
        if dropped:
            self._write_state("staged.json", staged)
            log.info("cleared staged mode on %d chip(s)", len(dropped))

    def reset(self, chips: tuple[TpuChip, ...]) -> None:
        if self.per_chip_reset_cmd and self.runtime_env_file:
            # The two mechanisms are incompatible by construction: the
            # committed mode rides in a HOST-GLOBAL runtime
            # EnvironmentFile that only a host-global runtime restart
            # applies — per-chip commands would promote committed.json
            # while the running runtime still holds the old mode env.
            # Refuse before touching any state (a stable misconfiguration
            # must not mint 'resetting' markers).
            raise TpuError(
                "CC_RESET_PER_CHIP_CMD is incompatible with "
                "CC_RUNTIME_ENV_FILE: the mode env file is host-global and "
                "only a host-global runtime restart applies it; unset one"
            )
        staged = self._read_state("staged.json")
        pending = {}
        for chip in chips:
            key = str(chip.index)
            if key in staged:
                pending[key] = staged.pop(key)
        # Crash-safety ordering: mark the transition *pending* before the
        # disruptive restart, and only promote to committed after the restart
        # succeeds. A crash or restart failure leaves pending.json behind, and
        # query_cc_mode reports "resetting" for those chips — which can never
        # equal a desired mode, so the retrying reconcile re-runs the full
        # apply instead of trusting a commit that never happened
        # (crash-as-retry safety, SURVEY.md §7(c)).
        self._write_state("pending.json", pending)
        self._write_state("staged.json", staged)
        self._write_runtime_env(pending)
        if self.per_chip_reset_cmd:
            # Per-chip commit path: the pending markers above are already
            # durable for EVERY chip (a crash anywhere below reads
            # "resetting" and crash-as-retry re-applies), so the chip
            # commands may fan out across the bounded pool.
            self._reset_per_chip(chips, pending)
            return
        pre_stamp = self._runtime_stamp(fresh=True)
        log.info("restarting TPU runtime: %s", " ".join(self.reset_cmd))
        try:
            self._run_device_cmd(self.reset_cmd, op="tpuvm.reset", timeout=120)
        except FileNotFoundError as e:
            raise TpuError(f"reset command not found: {e}") from e
        except subprocess.TimeoutExpired as e:
            raise TpuError(f"reset command timed out: {e}") from e
        except subprocess.CalledProcessError as e:
            raise TpuError(
                f"reset command failed rc={e.returncode}: "
                f"{(e.stderr or b'').decode('utf-8', 'replace')[:256]}"
            ) from e
        except retry_mod.CircuitOpenError as e:
            # Crash-as-retry semantics preserved: pending markers stay,
            # query reports 'resetting', the retrying reconcile re-applies
            # once the breaker's recovery window passes.
            raise TpuError(f"device-command path unavailable: {e}") from e
        # Cross-check the restart actually happened: a reset command that
        # exits 0 without bouncing the runtime (wrong unit name, masked
        # unit, no-op wrapper) must not promote pending -> committed. The
        # pending markers stay behind, so query_cc_mode reports 'resetting'
        # and the reconcile retries instead of trusting a commit that never
        # happened.
        post_stamp = self._runtime_stamp(fresh=True)
        if (
            pre_stamp is not None
            and post_stamp is not None
            and pre_stamp[1] is not None
            and post_stamp[1] is not None
            and post_stamp[1] <= pre_stamp[1]
        ):
            raise TpuError(
                "reset command succeeded but the TPU runtime did not "
                f"restart (ActiveEnterTimestampMonotonic {post_stamp[1]} "
                f"not newer than {pre_stamp[1]})"
            )
        committed = self._read_state("committed.json")
        committed.update(pending)
        self._write_state("committed.json", committed)
        # Record the post-restart stamp; when the probe was unavailable,
        # CLEAR the record rather than leave a stale one — a stale stamp
        # would make the next query_cc_mode falsely report an external
        # restart and fail a healthy reconcile.
        self._write_state(
            "runtime.json",
            {"active_state": post_stamp[0], "enter_ts": post_stamp[1]}
            if post_stamp is not None and post_stamp[1]
            else {},
        )
        self._write_state("pending.json", {})

    def _reset_one_chip_cmd(self, chip: TpuChip) -> None:
        """One chip's reset command, in its own span (the bench reads the
        per-chip spans back to compare pipeline wall vs serial sum)."""
        cmd = host_wrap([
            part.replace("{device}", chip.device_path)
                .replace("{index}", str(chip.index))
            for part in self.per_chip_reset_cmd
        ])
        with obs_trace.span("reset.chip", chip=chip.index) as sp:
            sp.set_attribute("device", chip.device_path)
            try:
                self._run_device_cmd(
                    cmd, op=f"tpuvm.reset.chip{chip.index}", timeout=120
                )
            except FileNotFoundError as e:
                raise TpuError(f"per-chip reset command not found: {e}") from e
            except subprocess.TimeoutExpired as e:
                raise TpuError(f"per-chip reset timed out: {e}") from e
            except subprocess.CalledProcessError as e:
                raise TpuError(
                    f"per-chip reset of {chip.name} failed rc={e.returncode}: "
                    f"{(e.stderr or b'').decode('utf-8', 'replace')[:256]}"
                ) from e
            except retry_mod.CircuitOpenError as e:
                raise TpuError(f"device-command path unavailable: {e}") from e

    def _reset_per_chip(
        self, chips: tuple[TpuChip, ...], pending: dict[str, str]
    ) -> None:
        """Fan the per-chip reset commands out across a bounded worker
        pool. Committed promotion happens only after EVERY chip's command
        succeeded — any failure leaves the pending markers behind, so
        query_cc_mode keeps reporting 'resetting' for the whole staged set
        and the retrying reconcile re-applies from a clean stage (the same
        crash-as-retry contract as the host-global restart)."""
        workers = max(1, min(reset_parallelism(), len(chips)))
        log.info(
            "resetting %d chip(s) via per-chip commands (%d worker(s))",
            len(chips), workers,
        )
        with ThreadPoolExecutor(max_workers=workers) as pool:
            futures = [
                pool.submit(
                    obs_trace.in_current_context(self._reset_one_chip_cmd, c)
                )
                for c in chips
            ]
        raise_pool_errors([f.exception() for f in futures if f.exception()])
        committed = self._read_state("committed.json")
        committed.update(pending)
        self._write_state("committed.json", committed)
        # The runtime unit did not restart on this path; record the
        # CURRENT activation stamp (when available) so the external-
        # restart cross-check in query_cc_mode compares against fresh
        # truth instead of a stale pre-reset record.
        stamp = self._runtime_stamp(fresh=True)
        self._write_state(
            "runtime.json",
            {"active_state": stamp[0], "enter_ts": stamp[1]}
            if stamp is not None and stamp[1]
            else {},
        )
        self._write_state("pending.json", {})

    def _write_runtime_env(self, pending: dict[str, str]) -> None:
        """Write the runtime EnvironmentFile for the mode being committed —
        BEFORE the restart, so the restarting runtime picks it up. This is
        where ``devtools`` becomes backend-visible: its env carries debug/
        trace flags (labels.py mode table). A write failure fails the reset
        (pending markers stay, query reports 'resetting', the reconcile
        retries) — committing a mode whose runtime config didn't land would
        attest a runtime that isn't configured as claimed."""
        if not self.runtime_env_file or not pending:
            return
        modes = sorted(set(pending.values()))
        if len(modes) != 1:
            # The manager stages one mode per apply, so mixed pending modes
            # mean a caller bug or corrupted pending state. The runtime env
            # is host-global — silently writing one chip's mode (or 'off')
            # would commit a runtime config that doesn't match what half the
            # chips staged, then attest it. Refuse instead; pending markers
            # stay and the reconcile retries from a clean stage.
            raise TpuError(
                f"mixed modes staged across chips: {modes}; refusing to "
                "write a single host-global runtime env"
            )
        mode = modes[0]
        path = _host_path(self.runtime_env_file)
        try:
            os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
            tmp = path + ".tmp"
            with open(tmp, "w", encoding="utf-8") as f:
                f.write(runtime_env_for_mode(mode))
            os.replace(tmp, path)
        except OSError as e:
            raise TpuError(f"could not write runtime env {path}: {e}") from e
        log.info("runtime env staged for mode=%s at %s", mode, path)

    def wait_ready(self, chips: tuple[TpuChip, ...], timeout_s: float) -> None:
        if not retry_mod.poll_until(
            lambda: self._probe_healthy(chips), timeout_s, 1.0
        ):
            raise TpuError(f"TPU runtime not healthy after {timeout_s:.0f}s")

    def _probe_healthy(self, chips: tuple[TpuChip, ...]) -> bool:
        return self.probe_runtime_health(chips).healthy

    def _probe_cmd_verdict(self) -> HealthProbe:
        try:
            rc = subprocess.run(
                self.health_probe_cmd, capture_output=True, timeout=10
            ).returncode
            return HealthProbe("probe-cmd", rc == 0, f"probe rc={rc}")
        except (OSError, subprocess.TimeoutExpired) as e:
            return HealthProbe("probe-cmd", False, f"probe failed: {e}")

    def probe_runtime_health(
        self, chips: tuple[TpuChip, ...] | None = None
    ) -> HealthProbe:
        """Layered health probe, strongest AVAILABLE tier first (contract
        HEALTH_TIER_STRENGTH): runtime health port (TCP) > explicit probe
        command > systemd ActiveState + device nodes > device nodes alone.
        Bare device-node existence is the weakest signal (nodes persist
        across a wedged runtime) and is only the last resort — the watchdog
        exports the active tier so that fallback is visible, never silent.
        """
        if self.health_port:
            port_up = True
            try:
                with socket.create_connection(
                    ("127.0.0.1", self.health_port), timeout=2
                ):
                    pass
            except OSError as e:
                if not self._health_port_seen:
                    # Never answered since process start: the runtime
                    # build most likely exposes no liveness port (the
                    # manifest defaults CC_RUNTIME_HEALTH_PORT on), which
                    # must read as tier-unavailable, not fleet-wide
                    # unhealthy. Fall through to the next tier.
                    log.debug(
                        "health port %d never answered; treating the tier "
                        "as unavailable: %s", self.health_port, e,
                    )
                    port_up = None
                else:
                    return HealthProbe(
                        "health-port", False, f"port {self.health_port}: {e}"
                    )
            if port_up:
                self._health_port_seen = True
                # A bare TCP accept can come straight from the kernel
                # backlog of a wedged process; when the operator ALSO
                # supplied a probe command, it still runs as the
                # application-level second opinion and both must pass (the
                # port alone must not mask a wedge the command would
                # catch).
                if self.health_probe_cmd is not None:
                    cmd = self._probe_cmd_verdict()
                    return HealthProbe(
                        "health-port",
                        cmd.healthy,
                        f"port {self.health_port} answers; {cmd.detail}",
                    )
                return HealthProbe(
                    "health-port", True, f"port {self.health_port} answers"
                )
        if self.health_probe_cmd is not None:
            return self._probe_cmd_verdict()
        device_paths = (
            [c.device_path for c in chips]
            if chips is not None
            else sorted(glob.glob(self.device_glob))
            or sorted(glob.glob(self.vfio_glob))
        )
        nodes_present = bool(device_paths) and all(
            os.path.exists(p) for p in device_paths
        )
        stamp = self._runtime_stamp()
        if stamp is not None:
            if stamp[0] not in ("active", "unknown"):
                return HealthProbe(
                    "systemd", False, f"runtime unit {stamp[0]}"
                )
            return HealthProbe(
                "systemd",
                nodes_present,
                f"runtime unit {stamp[0]}; device nodes "
                + ("present" if nodes_present else "MISSING"),
            )
        return HealthProbe(
            "device-node",
            nodes_present,
            "device nodes " + ("present" if nodes_present else "missing")
            + " (weakest probe tier — configure CC_RUNTIME_HEALTH_PORT or a "
            "probe command)",
        )

    def preemption_notice(self) -> bool:
        """GCE preemption signal: the metadata server flips
        ``instance/preempted`` to TRUE when the VM has been scheduled for
        reclaim (spot/preemptible), leaving a hard termination deadline
        (~30 s) far below the normal 300 s drain budget. An unreachable
        metadata server reads as NOT preempted — the notice is an
        optimization of a death we cannot veto, so a flaky metadata path
        must never trigger a spurious fast-drain."""
        value = self._metadata("instance/preempted", default="FALSE")
        return (value or "").strip().upper() == "TRUE"

    def prepare_attestation(self) -> None:
        """Warm the measured-file hash memo (libtpu is O(100 MB)) so the
        post-boot attest phase pays only the nonce-bound metadata fetch.
        The manager overlaps this with wait_ready; any failure is
        irrelevant — fetch_attestation re-hashes whatever is missing."""
        self._measured_files()

    def fetch_attestation(self, nonce: str) -> AttestationQuote:
        committed = self._read_state("committed.json")
        modes = sorted(set(committed.values())) or [MODE_OFF]
        mode = modes[0] if len(modes) == 1 else "mixed"
        topo = self.discover()
        # GCE instance-identity JWT bound to the nonce via the audience.
        jwt = self._metadata(
            f"instance/service-accounts/default/identity"
            f"?audience=tpu-cc-manager/{nonce}&format=full"
        )
        if jwt is None:
            raise TpuError(
                "metadata server unreachable: cannot fetch instance identity "
                "for attestation"
            )
        tsm = self._tsm_report(nonce)
        files = self._measured_files()  # one glob/stat sweep per quote
        measurements = {
            "accelerator_type": topo.accelerator_type,
            "num_chips": str(len(topo.chips)),
            "runtime_digest": self._runtime_digest(files),
            "libtpu_version": self._libtpu_version(files),
            "runtime_files": str(len(files)),
            "cc_mode": mode,
            "confidential_vm": str(self._host_is_confidential()).lower(),
            # Pool-comparable: every host of one confidential pool runs the
            # same TEE provider (or none).
            "tsm_provider": tsm["provider"] if tsm else "none",
        }
        # Per-host evidence: excluded from the cross-host quote digest
        # (quote_digest hashes measurements only) but carried for the
        # verifier — the activation stamp pins WHEN this runtime instance
        # came up, the TSM outblob is the CPU security processor's signed
        # report over the nonce-derived challenge.
        host_evidence: dict[str, str] = {}
        stamp = self._runtime_stamp()
        if stamp is not None:
            host_evidence["runtime_active_state"] = stamp[0]
            if stamp[1] is not None:
                host_evidence["runtime_active_enter_ts"] = str(stamp[1])
        if tsm:
            host_evidence["tsm_outblob_b64"] = tsm["outblob_b64"]
            host_evidence["tsm_inblob_sha256"] = tsm["inblob_sha256"]
        return AttestationQuote(
            slice_id=topo.slice_id,
            nonce=nonce,
            mode=mode,
            measurements=measurements,
            signature=jwt,
            platform="tpuvm",
            host_evidence=host_evidence,
        )

    def _hash_file(self, path: str) -> str | None:
        """sha256 of a file, memoized on (size, mtime_ns)."""
        import hashlib

        try:
            st = os.stat(path)
        except OSError:
            return None
        key = (st.st_size, st.st_mtime_ns)
        cached = self._file_hash_cache.get(path)
        if cached is not None and cached[0] == key:
            return cached[1]
        h = hashlib.sha256()
        try:
            with open(path, "rb") as f:
                for chunk in iter(lambda: f.read(1 << 20), b""):
                    h.update(chunk)
        except OSError:
            return None
        digest = h.hexdigest()
        self._file_hash_cache[path] = (key, digest)
        return digest

    def _measured_files(self) -> dict[str, str]:
        """path -> content sha256 for every existing measured file."""
        out: dict[str, str] = {}
        root = os.environ.get(HOST_ROOT_ENV, "")
        for pattern in self.measure_globs:
            # Measured paths are host paths; inside the container the host
            # rootfs is mounted at CC_HOST_ROOT.
            for path in sorted(glob.glob(_host_path(pattern))):
                digest = self._hash_file(path)
                if digest is not None:
                    # Record under the host-visible path so digests compare
                    # equal across containerized and bare-metal agents.
                    out[path[len(root):] if root else path] = digest
        return out

    def _libtpu_version(self, files: dict[str, str] | None = None) -> str:
        """Identity of the libtpu the RUNTIME loads: the measured host
        library's hash first — the manager container's own pip-installed
        libtpu is a different artifact (present for the smoke workload) and
        must not masquerade as the runtime's, nor change the pool digest on
        a container image roll. The package version is only a fallback for
        bare-metal installs where the manager's environment IS the runtime
        environment (no measurable library file)."""
        if files is None:
            files = self._measured_files()
        for path in sorted(files):
            if "libtpu" in os.path.basename(path):
                return f"sha256:{files[path][:12]}"
        try:
            from importlib import metadata

            for dist in ("libtpu", "libtpu-nightly"):
                try:
                    return metadata.version(dist)
                except metadata.PackageNotFoundError:
                    continue
        except Exception:  # noqa: BLE001 - version is best-effort identity
            pass
        return "unknown"

    def _runtime_digest(self, files: dict[str, str] | None = None) -> str:
        """Digest of the runtime's actual identity: the measured file set
        (libtpu library, unit file, runtime config). Equal across hosts
        running the same runtime; provably different when the runtime
        binary or its config changes. Deliberately does NOT hash the
        manager's own state files — a digest of committed.json would attest
        the manager's beliefs, not the runtime (VERDICT r3 weak #2)."""
        import hashlib

        if files is None:
            files = self._measured_files()
        h = hashlib.sha256()
        for path in sorted(files):
            h.update(path.encode())
            h.update(b"\0")
            h.update(files[path].encode())
            h.update(b"\n")
        if not files:
            # No measurable runtime files (non-standard install): fall back
            # to a constant-per-host-image marker rather than an empty hash
            # masquerading as a measurement.
            h.update(b"unmeasured-runtime")
        return h.hexdigest()

    # ---- configfs-tsm guest evidence ------------------------------------

    def _tsm_report(self, nonce: str) -> dict[str, str] | None:
        """Fetch a guest report from configfs-tsm, challenge-bound to the
        nonce. Returns {provider, outblob_b64, inblob_sha256} or None when
        the interface is unavailable (non-confidential VM or pre-6.7
        kernel). The report directory name is fixed so tests can pre-seed
        outblob/provider in an injected tsm_root."""
        import base64
        import hashlib

        root = self.tsm_root
        if not root or not os.path.isdir(root):
            return None
        report_dir = os.path.join(root, "tpu-cc-manager")
        # TSM inblob is a <=64-byte challenge; bind it to the nonce.
        inblob = hashlib.sha256(f"tpu-cc-manager/{nonce}".encode()).digest()
        try:
            try:
                os.mkdir(report_dir)
            except FileExistsError:
                pass  # leftover dir from a crashed fetch (or a test seed)
            with open(os.path.join(report_dir, "inblob"), "wb") as f:
                f.write(inblob)
            with open(os.path.join(report_dir, "outblob"), "rb") as f:
                outblob = f.read()
            provider = "unknown"
            try:
                with open(os.path.join(report_dir, "provider"), "r",
                          encoding="utf-8") as f:
                    provider = f.read().strip() or "unknown"
            except OSError:
                pass
        except OSError as e:
            log.warning("configfs-tsm report unavailable: %s", e)
            return None
        if not outblob:
            return None
        return {
            "provider": provider,
            "outblob_b64": base64.b64encode(outblob).decode("ascii"),
            "inblob_sha256": hashlib.sha256(inblob).hexdigest(),
        }
