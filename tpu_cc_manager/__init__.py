"""tpu-cc-manager: a TPU-native confidential-computing control plane for GKE.

Built from scratch with the capabilities of NVIDIA's k8s-cc-manager
(reference: /root/reference, see SURVEY.md): a per-node DaemonSet agent that

1. watches the desired-state node label ``cloud.google.com/tpu-cc.mode``
   (reference analogue: ``nvidia.com/cc.mode``, main.py:62),
2. drains TPU device-plugin / workload pods via a label pause protocol
   (reference: gpu_operator_eviction.py:131-214),
3. flips the whole ICI-connected TPU slice into/out of confidential-computing
   mode with stage-all/reset-all/verify-all atomicity (the TPU analogue of the
   reference's fabric-atomic PPCIe flow, main.py:317-391),
4. fetches and verifies a slice attestation quote (new; no reference
   counterpart),
5. validates the reconfigured slice end-to-end with an in-tree JAX/XLA smoke
   workload (new; no reference counterpart),
6. re-admits the drained components (reference:
   gpu_operator_eviction.py:217-259) and reports actual state through node
   labels (reference: gpu_operator_eviction.py:262-295).

Package layout:

- ``kubeclient/``  minimal Kubernetes REST client (stdlib only) + fake server
- ``tpudev/``      TPU device layer: CC backend contract, fake + TPU VM impls
- ``drain/``       pause/unpause label algebra, eviction, state reporting
- ``ccmanager/``   the reconciler, watch loop, rolling orchestrator, CLI
- ``smoke/``       JAX validation workloads (matmul, Llama, ResNet-50)
- ``models/``      flax model definitions used by the smoke workloads
- ``parallel/``    mesh / sharding / checkpoint / multi-slice DP over DCN
- ``ops/``         pallas TPU kernels for the smoke-model hot paths
- ``utils/``       logging, phase metrics, config
"""

from tpu_cc_manager.version import __version__

__all__ = ["__version__"]
