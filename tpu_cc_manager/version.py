"""Single source of version truth (build machinery reads this via
``python -c "from tpu_cc_manager.version import __version__"``; the container
Makefile pins the same value in deployments/container/versions.mk, mirroring
the reference's versions.mk:15)."""

__version__ = "0.3.0"
