"""Minimal Kubernetes client, stdlib-only.

The reference depends on the external ``kubernetes`` Python package
(requirements.txt) for four things: node GET, node label PATCH, pod LIST, and
a node WATCH stream. This package implements exactly that surface in-tree:

- :mod:`tpu_cc_manager.kubeclient.api` — the ``KubeApi`` interface and types,
- :mod:`tpu_cc_manager.kubeclient.rest` — a real client over the apiserver
  REST API (in-cluster service account or kubeconfig),
- :mod:`tpu_cc_manager.kubeclient.fake` — an in-memory apiserver for tests
  and dry-runs (the reference has no fake backend; SURVEY.md §4 calls that
  out as its biggest testing gap).

Deliberate divergence from the reference: label writes use a JSON merge-patch
against ``metadata.labels`` only, instead of the reference's racy full-object
read-modify-write ``patch_node(node_name, node)``
(gpu_operator_eviction.py:165-170; SURVEY.md §8.3).
"""

from tpu_cc_manager.kubeclient.api import KubeApi, KubeApiError, WatchEvent

__all__ = ["KubeApi", "KubeApiError", "WatchEvent"]
