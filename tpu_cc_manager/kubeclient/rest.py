"""Real apiserver client over stdlib http.client + ssl.

Replaces the reference's external ``kubernetes`` package dependency
(requirements.txt, main.py:129-140). Supports the same two auth paths, in the
same order of preference: in-cluster service-account config, then kubeconfig
fallback (reference main.py:131-140).

Only the four verbs the control plane needs are implemented (see
:mod:`tpu_cc_manager.kubeclient.api`); the watch uses the apiserver's
streaming JSON-lines protocol with server-side timeoutSeconds, matching the
reference's ``watch.Watch().stream(..., timeout_seconds=300)`` behavior
(main.py:622-632).
"""

from __future__ import annotations

import base64
import http.client
import json
import logging
import os
import ssl
import tempfile
import urllib.parse
import urllib.request
from dataclasses import dataclass
from typing import Iterator, Mapping

from tpu_cc_manager.kubeclient.api import (
    RETRYABLE_STATUS,
    KubeApi,
    KubeApiError,
    WatchEvent,
    classify_kube_error,
)
from tpu_cc_manager.utils import retry as retry_mod

log = logging.getLogger(__name__)

SERVICEACCOUNT_DIR = "/var/run/secrets/kubernetes.io/serviceaccount"


@dataclass
class ClusterConfig:
    """Where the apiserver is and how to authenticate to it."""

    server: str  # e.g. https://10.0.0.1:443
    token: str | None = None
    ca_file: str | None = None
    client_cert_file: str | None = None
    client_key_file: str | None = None
    insecure_skip_tls_verify: bool = False

    @classmethod
    def in_cluster(cls) -> "ClusterConfig":
        """Service-account config, present in every pod with a mounted SA."""
        host = os.environ.get("KUBERNETES_SERVICE_HOST")
        port = os.environ.get("KUBERNETES_SERVICE_PORT", "443")
        token_path = os.path.join(SERVICEACCOUNT_DIR, "token")
        ca_path = os.path.join(SERVICEACCOUNT_DIR, "ca.crt")
        if not host or not os.path.exists(token_path):
            raise KubeApiError(None, "not running in-cluster")
        with open(token_path, "r", encoding="utf-8") as f:
            token = f.read().strip()
        return cls(
            server=f"https://{host}:{port}",
            token=token,
            ca_file=ca_path if os.path.exists(ca_path) else None,
        )

    @classmethod
    def from_kubeconfig(
        cls, path: str | None = None, context: str | None = None,
    ) -> "ClusterConfig":
        """Parse one context of a kubeconfig file — ``context`` names it,
        None means the file's current-context. Per-region federation
        shards (``--regions r1=ctx1,...``) select their cluster this way
        from a single shared kubeconfig.

        Supports token, client-certificate(-data)/client-key(-data), and
        insecure-skip-tls-verify — the auth shapes kind and GKE emit.
        """
        import yaml  # baked into the image; only needed on this path

        path = path or os.environ.get("KUBECONFIG") or os.path.expanduser("~/.kube/config")
        with open(path, "r", encoding="utf-8") as f:
            cfg = yaml.safe_load(f) or {}

        def by_name(section: str, name: str) -> dict:
            for item in cfg.get(section) or []:
                if item.get("name") == name:
                    return item.get(section.rstrip("s")) or {}
            raise KubeApiError(None, f"kubeconfig: {section} entry {name!r} not found")

        ctx_name = context or cfg.get("current-context")
        if not ctx_name:
            raise KubeApiError(None, "kubeconfig: no current-context")
        ctx = by_name("contexts", ctx_name)
        cluster = by_name("clusters", ctx.get("cluster", ""))
        user = by_name("users", ctx.get("user", ""))

        def materialize(data_key: str, file_key: str, src: dict) -> str | None:
            if src.get(file_key):
                return src[file_key]
            data = src.get(data_key)
            if not data:
                return None
            f = tempfile.NamedTemporaryFile(
                prefix="tpucc-kubeconfig-", suffix=".pem", delete=False
            )
            f.write(base64.b64decode(data))
            f.close()
            return f.name

        return cls(
            server=cluster.get("server", ""),
            token=user.get("token"),
            ca_file=materialize("certificate-authority-data", "certificate-authority", cluster),
            client_cert_file=materialize("client-certificate-data", "client-certificate", user),
            client_key_file=materialize("client-key-data", "client-key", user),
            insecure_skip_tls_verify=bool(cluster.get("insecure-skip-tls-verify")),
        )

    @classmethod
    def load(
        cls, kubeconfig: str | None = None, context: str | None = None,
    ) -> "ClusterConfig":
        """In-cluster first, kubeconfig fallback (reference main.py:129-140).
        A named ``context`` skips the in-cluster probe outright: asking
        for a specific cluster and silently getting the local one is
        exactly the cross-region mixup per-region contexts exist to
        prevent."""
        if context:
            cfg = cls.from_kubeconfig(kubeconfig, context=context)
            log.info(
                "using kubeconfig at %s (context %s)",
                kubeconfig or "<default>", context,
            )
            return cfg
        try:
            cfg = cls.in_cluster()
            log.info("using in-cluster kubernetes configuration")
            return cfg
        except KubeApiError:
            cfg = cls.from_kubeconfig(kubeconfig)
            log.info("using kubeconfig at %s", kubeconfig or "<default>")
            return cfg


class RestKube(KubeApi):
    # Transient statuses worth one more try on the non-watch verbs; a watch
    # stream has its own reconnect loop in the caller (manager.py) and is
    # never retried here. (Kept as a class attribute for compatibility;
    # classification itself lives in kubeclient.api.classify_kube_error.)
    RETRYABLE_STATUS = RETRYABLE_STATUS
    # Caller-side policies collapse to one attempt against this client
    # (kubeclient.api.caller_retry_attempts): the ladder lives HERE.
    retries_internally = True

    def __init__(
        self,
        config: ClusterConfig,
        retry_attempts: int = 3,
        retry_base_delay_s: float = 0.5,
        retry_policy: retry_mod.RetryPolicy | None = None,
        breaker: retry_mod.CircuitBreaker | None = None,
        metrics=None,
    ):
        # Per-verb apiserver request accounting
        # (tpu_cc_apiserver_requests_total{verb}): every HTTP round trip
        # this client performs — retries included, since each one lands on
        # the apiserver — so the exported QPS is what the server actually
        # absorbs, not the logical call rate.
        from tpu_cc_manager.utils import metrics as metrics_mod

        self.metrics = metrics if metrics is not None else metrics_mod.REGISTRY
        self.config = config
        self.retry_attempts = max(1, retry_attempts)
        self.retry_base_delay_s = retry_base_delay_s
        # The shared backoff policy (full jitter, Retry-After honoring);
        # injectable for tests/chaos. max_attempts rides per-call so the
        # legacy retry_attempts knob keeps working.
        self.retry_policy = retry_policy or retry_mod.RetryPolicy(
            max_attempts=self.retry_attempts,
            base_delay_s=retry_base_delay_s,
            max_delay_s=30.0,
        )
        # One breaker per client instance: a flapping apiserver fails fast
        # after the threshold instead of absorbing every caller's full
        # retry ladder. Generous threshold — the watch loop's own
        # consecutive-error cap (10) should normally fire first.
        self.breaker = breaker or retry_mod.CircuitBreaker(
            "apiserver", failure_threshold=12, recovery_time_s=15.0
        )
        self._ssl_ctx = self._build_ssl_context(config)

    @staticmethod
    def _build_ssl_context(config: ClusterConfig) -> ssl.SSLContext | None:
        if not config.server.startswith("https"):
            return None
        ctx = ssl.create_default_context(cafile=config.ca_file)
        if config.insecure_skip_tls_verify:
            ctx.check_hostname = False
            ctx.verify_mode = ssl.CERT_NONE
        if config.client_cert_file:
            ctx.load_cert_chain(config.client_cert_file, config.client_key_file)
        return ctx

    # ---- low-level HTTP --------------------------------------------------

    def _open(self, method: str, path: str, query: dict | None = None,
              body: bytes | None = None, content_type: str | None = None,
              read_timeout: float = 30.0):
        url = self.config.server.rstrip("/") + path
        if query:
            url += "?" + urllib.parse.urlencode(query)
        req = urllib.request.Request(url, data=body, method=method)
        if self.config.token:
            req.add_header("Authorization", f"Bearer {self.config.token}")
        if content_type:
            req.add_header("Content-Type", content_type)
        req.add_header("Accept", "application/json")
        try:
            return urllib.request.urlopen(req, timeout=read_timeout, context=self._ssl_ctx)
        except urllib.error.HTTPError as e:
            detail = ""
            try:
                detail = e.read().decode("utf-8", "replace")[:512]
            except Exception:
                pass
            raise KubeApiError(
                e.code,
                f"{method} {path}: {detail or e.reason}",
                retry_after_s=retry_mod.parse_retry_after(
                    e.headers.get("Retry-After") if e.headers else None
                ),
            ) from e
        except (urllib.error.URLError, OSError, TimeoutError) as e:
            raise KubeApiError(None, f"{method} {path}: {e}") from e

    _VERB_OF_METHOD = {
        "GET": "get", "PATCH": "patch", "PUT": "update",
        "POST": "create", "DELETE": "delete",
    }

    def _request_json(self, method: str, path: str, query: dict | None = None,
                      body: dict | None = None, content_type: str | None = None,
                      verb: str | None = None) -> dict:
        """One apiserver round trip through the shared retry policy
        (utils/retry.py: full jitter, Retry-After honoring) behind the
        apiserver circuit breaker. Only idempotent verbs (GET, label
        merge-patch) are retried — enforced here, not just documented, so a
        future non-idempotent route (e.g. a POST eviction) cannot silently
        inherit retry-after-ambiguous-failure. Client-side errors (4xx)
        propagate immediately — a 404/409 will not improve with
        repetition."""
        raw = json.dumps(body).encode() if body is not None else None
        retryable_verb = method in ("GET", "PATCH")
        counted_verb = verb or self._VERB_OF_METHOD.get(method, method.lower())

        def attempt() -> dict:
            try:
                self.breaker.before_call()
            except retry_mod.CircuitOpenError as e:
                # Same exception surface as any other transport failure
                # (callers already handle KubeApiError(None)) — but marked
                # so the classifier treats it as PERMANENT: sleeping
                # through a retry ladder against a known-open circuit
                # would defeat the fail-fast the breaker exists for.
                err = KubeApiError(None, str(e))
                err.circuit_open = True
                raise err from e
            # Counted only once the request demonstrably REACHED the
            # apiserver — a 2xx response, an HTTP error status, or a
            # failure while reading a response that started arriving. A
            # circuit-open refusal or connect-level failure (refused,
            # DNS, timeout: KubeApiError with status None) never got
            # there, and counting it would export phantom server QPS at
            # full retry speed during an outage — the exact signal the
            # metric's HELP text tells operators to read as real load.
            try:
                resp_cm = self._open(method, path, query, raw, content_type)
            except KubeApiError as e:
                if e.status is not None:
                    self.metrics.record_apiserver_request(counted_verb)
                verdict = classify_kube_error(e)
                if verdict is not None and verdict.transient:
                    self.breaker.record_failure()
                else:
                    # A definitive 4xx proves the apiserver is answering.
                    self.breaker.record_success()
                raise
            self.metrics.record_apiserver_request(counted_verb)
            try:
                with resp_cm as resp:
                    result = json.loads(resp.read().decode("utf-8"))
            except (OSError, ValueError, http.client.HTTPException) as e:
                # Failures AFTER the connection opened (reset mid-body,
                # IncompleteRead on a truncated stream, garbled JSON) are
                # transport flakes too: wrap them so the retry policy and
                # breaker see them instead of a raw exception escaping
                # both.
                self.breaker.record_failure()
                raise KubeApiError(
                    None, f"{method} {path}: response read failed: {e}"
                ) from e
            self.breaker.record_success()
            return result

        return self.retry_policy.call(
            attempt,
            op=f"kube.{method.lower()}",
            classify=classify_kube_error,
            max_attempts=self.retry_attempts if retryable_verb else 1,
        )

    # ---- KubeApi ---------------------------------------------------------

    def get_node(self, name: str) -> dict:
        return self._request_json("GET", f"/api/v1/nodes/{name}")

    def patch_node_labels(self, name: str, labels: Mapping[str, str | None]) -> dict:
        return self._request_json(
            "PATCH",
            f"/api/v1/nodes/{name}",
            body={"metadata": {"labels": dict(labels)}},
            content_type="application/merge-patch+json",
        )

    def patch_node_annotations(
        self, name: str, annotations: Mapping[str, str | None]
    ) -> dict:
        return self._request_json(
            "PATCH",
            f"/api/v1/nodes/{name}",
            body={"metadata": {"annotations": dict(annotations)}},
            content_type="application/merge-patch+json",
        )

    def patch_node_taints(
        self, name: str, add: list[dict], remove_keys: list[str]
    ) -> dict:
        """Read-modify-write of ``spec.taints`` (a list, so a merge-patch
        replaces it wholesale — the RMW keeps foreign taints intact). A
        concurrent writer between the GET and the PATCH loses its edit to
        ours; acceptable for the quarantine taint, whose only writers are
        this agent and the operator CLI, and the patch is idempotent."""
        node = self.get_node(name)
        taints = list((node.get("spec") or {}).get("taints") or [])
        doomed = set(remove_keys) | {t.get("key") for t in add}
        taints = [t for t in taints if t.get("key") not in doomed]
        taints.extend(dict(t) for t in add)
        return self._request_json(
            "PATCH",
            f"/api/v1/nodes/{name}",
            body={"spec": {"taints": taints}},
            content_type="application/merge-patch+json",
        )

    def list_nodes(self, label_selector: str | None = None) -> list[dict]:
        query: dict = {}
        if label_selector:
            query["labelSelector"] = label_selector
        return self._request_json(
            "GET", "/api/v1/nodes", query, verb="list"
        ).get("items", [])

    def list_nodes_page(
        self,
        label_selector: str | None = None,
        limit: int | None = None,
        continue_token: str | None = None,
    ) -> dict:
        """One chunk of the apiserver's paginated LIST protocol. The
        ``continue`` token is served from a consistent snapshot server-
        side; an expired token answers 410, which
        ``list_nodes_chunked``'s callers treat as "restart the listing"
        (the informer relist path)."""
        query: dict = {}
        if label_selector:
            query["labelSelector"] = label_selector
        if limit:
            query["limit"] = str(int(limit))
        if continue_token:
            query["continue"] = continue_token
        return self._request_json("GET", "/api/v1/nodes", query, verb="list")

    def list_pods(self, namespace: str, label_selector: str | None = None,
                  field_selector: str | None = None) -> list[dict]:
        query: dict = {}
        if label_selector:
            query["labelSelector"] = label_selector
        if field_selector:
            query["fieldSelector"] = field_selector
        return self._request_json(
            "GET", f"/api/v1/namespaces/{namespace}/pods", query, verb="list"
        ).get("items", [])

    def create_event(self, namespace: str, event: dict) -> dict:
        return self._request_json(
            "POST",
            f"/api/v1/namespaces/{namespace}/events",
            body=event,
            content_type="application/json",
        )

    # Lease verbs (coordination.k8s.io/v1). GET retries like any read;
    # POST/PUT/DELETE run exactly one attempt (the idempotent-verb gate in
    # _request_json): a PUT retried after an ambiguous first attempt would
    # 409 against its own write, and the lease renew loop is itself the
    # retry layer.

    @staticmethod
    def _lease_path(namespace: str, name: str | None = None) -> str:
        path = f"/apis/coordination.k8s.io/v1/namespaces/{namespace}/leases"
        return f"{path}/{name}" if name else path

    def get_lease(self, namespace: str, name: str) -> dict:
        return self._request_json("GET", self._lease_path(namespace, name))

    def create_lease(self, namespace: str, name: str, spec: dict) -> dict:
        return self._request_json(
            "POST",
            self._lease_path(namespace),
            body={
                "apiVersion": "coordination.k8s.io/v1",
                "kind": "Lease",
                "metadata": {"name": name, "namespace": namespace},
                "spec": dict(spec),
            },
            content_type="application/json",
        )

    def update_lease(self, namespace: str, name: str, lease: dict) -> dict:
        return self._request_json(
            "PUT",
            self._lease_path(namespace, name),
            body=lease,
            content_type="application/json",
        )

    def delete_lease(self, namespace: str, name: str) -> None:
        self._request_json("DELETE", self._lease_path(namespace, name))

    def self_subject_access_review(
        self, verb: str, resource: str, namespace: str | None = None
    ) -> bool:
        """Ask the apiserver whether THIS identity may perform verb on
        resource (SelfSubjectAccessReview). Used by ``tpu-cc-ctl
        rbac-check`` to prove the DaemonSet RBAC covers every verb the
        agent needs before a rollout, instead of discovering a 403 mid-
        drain. POST, so never retried (the idempotent-verb gate in
        _request_json); SSAR is cheap and the caller just re-runs."""
        attrs: dict = {"verb": verb, "resource": resource}
        if namespace:
            attrs["namespace"] = namespace
        resp = self._request_json(
            "POST",
            "/apis/authorization.k8s.io/v1/selfsubjectaccessreviews",
            body={
                "apiVersion": "authorization.k8s.io/v1",
                "kind": "SelfSubjectAccessReview",
                "spec": {"resourceAttributes": attrs},
            },
            content_type="application/json",
        )
        return bool(resp.get("status", {}).get("allowed", False))

    def watch_nodes(self, name: str, resource_version: str | None = None,
                    timeout_seconds: int = 300) -> Iterator[WatchEvent]:
        query = {
            "watch": "true",
            "fieldSelector": f"metadata.name={name}",
            "timeoutSeconds": str(timeout_seconds),
            # Bookmarks keep the tracked resourceVersion fresh on quiet
            # nodes, so reconnects don't 410-expire after etcd compaction;
            # the manager's loop handles the BOOKMARK event type.
            "allowWatchBookmarks": "true",
        }
        if resource_version:
            query["resourceVersion"] = resource_version
        return self._watch("/api/v1/nodes", query, timeout_seconds)

    def watch_nodes_pool(
        self,
        label_selector: str | None = None,
        resource_version: str | None = None,
        timeout_seconds: int = 300,
    ) -> Iterator[WatchEvent]:
        query = {
            "watch": "true",
            "timeoutSeconds": str(timeout_seconds),
            "allowWatchBookmarks": "true",
        }
        if label_selector:
            query["labelSelector"] = label_selector
        if resource_version:
            query["resourceVersion"] = resource_version
        return self._watch("/api/v1/nodes", query, timeout_seconds)

    def _watch(self, path: str, query: dict,
               timeout_seconds: int) -> Iterator[WatchEvent]:
        # Client-side read timeout a bit above the server-side one so the
        # server closes first in the normal case. Counted only once the
        # connect succeeded (or the server answered an HTTP error): a
        # refused connect never reached the apiserver.
        try:
            resp = self._open(
                "GET", path, query, read_timeout=timeout_seconds + 15
            )
        except KubeApiError as e:
            if e.status is not None:
                self.metrics.record_apiserver_request("watch")
            raise
        self.metrics.record_apiserver_request("watch")
        try:
            while True:
                try:
                    line = resp.readline()
                except (OSError, TimeoutError) as e:
                    raise KubeApiError(None, f"watch stream: {e}") from e
                if not line:
                    return  # server closed (timeout elapsed)
                line = line.strip()
                if not line:
                    continue
                try:
                    payload = json.loads(line)
                except json.JSONDecodeError as e:
                    raise KubeApiError(None, f"watch stream: bad JSON frame: {e}") from e
                yield WatchEvent(payload.get("type", "ERROR"), payload.get("object") or {})
        finally:
            resp.close()
