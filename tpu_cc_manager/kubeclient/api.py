"""KubeApi: the exact apiserver surface the manager needs, as an interface.

Reference analogue: the subset of kubernetes.client.CoreV1Api used by
main.py:129-140/580-684 and gpu_operator_eviction.py (read_node, patch_node,
list_namespaced_pod, watch.Watch). Defining it as an interface lets tests and
bench.py swap in the in-memory fake (SURVEY.md §4 test plan, step 2).
"""

from __future__ import annotations

import abc
from dataclasses import dataclass
from typing import Iterator, Mapping


class KubeApiError(Exception):
    """Apiserver error with an HTTP status, mirroring ApiException.status
    (the reference branches on 410 Gone at main.py:670)."""

    def __init__(self, status: int | None, reason: str = ""):
        super().__init__(f"kube api error status={status} reason={reason}")
        self.status = status
        self.reason = reason


@dataclass
class WatchEvent:
    """One event from a watch stream: type ∈ ADDED|MODIFIED|DELETED|BOOKMARK|ERROR,
    object is the raw (JSON-decoded) Kubernetes object."""

    type: str
    object: dict


def node_annotations(node: dict) -> dict:
    """metadata.annotations of a node object (never None)."""
    return (node.get("metadata") or {}).get("annotations") or {}


def node_labels(node: dict) -> dict:
    """Labels of a node dict ({} if unset)."""
    return (node.get("metadata") or {}).get("labels") or {}


def resource_version(obj: dict) -> str:
    return str((obj.get("metadata") or {}).get("resourceVersion") or "")


class KubeApi(abc.ABC):
    """Typed facade over the apiserver operations the control plane performs."""

    @abc.abstractmethod
    def get_node(self, name: str) -> dict:
        """GET /api/v1/nodes/{name}. Raises KubeApiError (404 if absent)."""

    @abc.abstractmethod
    def patch_node_labels(self, name: str, labels: Mapping[str, str | None]) -> dict:
        """JSON merge-patch {"metadata": {"labels": labels}} onto the node.

        A ``None`` value deletes the label (merge-patch semantics). Returns
        the patched node. This deliberately never writes anything but labels
        (SURVEY.md §8.3)."""

    def patch_node_annotations(
        self, name: str, annotations: Mapping[str, str | None]
    ) -> dict:
        """JSON merge-patch {"metadata": {"annotations": ...}} onto the node.

        Annotations carry payloads too large for label values (the signed
        attestation quote, ccmanager/multislice.py); a ``None`` value
        deletes. Optional capability — the default raises KubeApiError so
        callers degrade cleanly on clients without it."""
        raise KubeApiError(
            None, "annotation patching not supported by this client"
        )

    @abc.abstractmethod
    def list_nodes(self, label_selector: str | None = None) -> list[dict]:
        """GET /api/v1/nodes, optionally filtered by an equality label
        selector ("k=v" or "k" presence, comma-separated)."""

    @abc.abstractmethod
    def list_pods(
        self,
        namespace: str,
        label_selector: str | None = None,
        field_selector: str | None = None,
    ) -> list[dict]:
        """GET /api/v1/namespaces/{ns}/pods with optional selectors.

        The manager uses label_selector="app=<component>" plus
        field_selector="spec.nodeName=<node>" while polling the drain
        (reference gpu_operator_eviction.py:185-207)."""

    @abc.abstractmethod
    def watch_nodes(
        self,
        name: str,
        resource_version: str | None = None,
        timeout_seconds: int = 300,
    ) -> Iterator[WatchEvent]:
        """Watch a single node (field selector metadata.name=<name>).

        Yields WatchEvents until the server-side timeout elapses, then
        returns. Transport errors raise KubeApiError; a stale
        resourceVersion raises KubeApiError(410) either immediately or as an
        ERROR event translated by the caller (reference main.py:622-638)."""

    def create_event(self, namespace: str, event: dict) -> dict:
        """POST a core/v1 Event (``kubectl describe node`` visibility).

        Optional capability — the default raises, and callers must treat
        emission as best-effort (events are operator convenience, never
        control-plane state). Not retried on failure: POST is not
        idempotent and a lost event is acceptable."""
        raise KubeApiError(None, "event creation not supported by this client")

    def self_subject_access_review(
        self, verb: str, resource: str, namespace: str | None = None
    ) -> bool:
        """Whether THIS identity may perform verb on resource (SSAR).

        Optional capability with a clean failure mode (``tpu-cc-ctl
        rbac-check`` reports it instead of crashing on AttributeError);
        RestKube implements the real apiserver call."""
        raise KubeApiError(
            None, "SelfSubjectAccessReview not supported by this client"
        )
