"""KubeApi: the exact apiserver surface the manager needs, as an interface.

Reference analogue: the subset of kubernetes.client.CoreV1Api used by
main.py:129-140/580-684 and gpu_operator_eviction.py (read_node, patch_node,
list_namespaced_pod, watch.Watch). Defining it as an interface lets tests and
bench.py swap in the in-memory fake (SURVEY.md §4 test plan, step 2).
"""

from __future__ import annotations

import abc
from dataclasses import dataclass
from typing import Iterator, Mapping


class KubeApiError(Exception):
    """Apiserver error with an HTTP status, mirroring ApiException.status
    (the reference branches on 410 Gone at main.py:670).

    ``retry_after_s`` carries a server-directed minimum backoff (a 429's
    ``Retry-After`` header) for the shared retry policy to honor."""

    def __init__(
        self,
        status: int | None,
        reason: str = "",
        retry_after_s: float | None = None,
    ):
        super().__init__(f"kube api error status={status} reason={reason}")
        self.status = status
        self.reason = reason
        self.retry_after_s = retry_after_s


# Transient statuses worth another try on idempotent verbs; status=None is
# a transport-level failure (connection reset, timeout) and equally
# transient. 410 Gone is NOT here: it is a protocol signal (resync), not a
# flake.
RETRYABLE_STATUS = (429, 500, 502, 503, 504)


def classify_kube_error(e: BaseException) -> "retry_mod.Classification | None":
    """Shared transient-vs-permanent verdict for apiserver failures, used
    by every call site that retries through utils/retry.py. A 4xx (other
    than 429) will not improve with repetition; anything transport-level
    or throttling/5xx-shaped will plausibly clear."""
    from tpu_cc_manager.utils import retry as retry_mod

    if not isinstance(e, KubeApiError):
        return None
    if getattr(e, "circuit_open", False):
        # The client's breaker is open: retrying cannot help until the
        # recovery window passes — fail fast, as the breaker intends.
        return retry_mod.Classification(False, "circuit-open")
    if e.status is None:
        return retry_mod.Classification(True, "connection", e.retry_after_s)
    if e.status == 429:
        return retry_mod.Classification(True, "throttled", e.retry_after_s)
    if e.status in RETRYABLE_STATUS:
        return retry_mod.Classification(True, f"http-{e.status}", e.retry_after_s)
    return retry_mod.Classification(False, f"http-{e.status}")


@dataclass
class WatchEvent:
    """One event from a watch stream: type ∈ ADDED|MODIFIED|DELETED|BOOKMARK|ERROR,
    object is the raw (JSON-decoded) Kubernetes object."""

    type: str
    object: dict


def node_annotations(node: dict) -> dict:
    """metadata.annotations of a node object (never None)."""
    return (node.get("metadata") or {}).get("annotations") or {}


def node_labels(node: dict) -> dict:
    """Labels of a node dict ({} if unset)."""
    return (node.get("metadata") or {}).get("labels") or {}


def resource_version(obj: dict) -> str:
    return str((obj.get("metadata") or {}).get("resourceVersion") or "")


def is_lease_unsupported(e: BaseException) -> bool:
    """Whether an error is the KubeApi default's lease-unsupported marker
    (as opposed to a real apiserver failure): callers use it to degrade to
    an unfenced rollout on minimal clients while still surfacing genuine
    lease errors."""
    return (
        isinstance(e, KubeApiError)
        and e.status is None
        and KubeApi.LEASE_UNSUPPORTED in (e.reason or "")
    )


def is_pool_watch_unsupported(e: BaseException) -> bool:
    """Whether an error is the KubeApi default's pool-watch-unsupported
    marker: the informer cache uses it to fail construction loudly (a
    cache that silently never updates would be worse than no cache)."""
    return (
        isinstance(e, KubeApiError)
        and e.status is None
        and KubeApi.POOL_WATCH_UNSUPPORTED in (e.reason or "")
    )


def caller_retry_attempts(api: "KubeApi", default: int = 3) -> int:
    """How many attempts a CALLER-side retry policy should make against
    ``api``: 1 when the client already retries transients internally
    (RestKube), ``default`` otherwise (fakes, chaos wrappers). Prevents the
    nested-ladder amplification where a caller's 3 attempts each expand
    into the client's 3 — up to 9 HTTP requests per logical call against
    an apiserver that is already degraded."""
    return 1 if getattr(api, "retries_internally", False) else default


def list_nodes_chunked(
    api: "KubeApi", label_selector: str | None = None,
    limit: int | None = None,
) -> tuple[list[dict], str]:
    """Full listing through the chunked-list protocol: pages of ``limit``
    via ``list_nodes_page`` until the continue token runs dry. Returns
    (items, resourceVersion-of-the-listing) — the rv is what a follow-up
    watch resumes from, which is why the informer cache uses this instead
    of plain ``list_nodes`` (whose return type carries no rv)."""
    items: list[dict] = []
    token: str | None = None
    rv = ""
    while True:
        page = api.list_nodes_page(
            label_selector, limit=limit, continue_token=token
        )
        items.extend(page.get("items") or [])
        meta = page.get("metadata") or {}
        rv = str(meta.get("resourceVersion") or rv)
        token = meta.get("continue") or None
        if not token:
            return items, rv


class KubeApi(abc.ABC):
    """Typed facade over the apiserver operations the control plane performs."""

    #: True when this client retries transient failures internally; caller-
    #: side policies consult caller_retry_attempts() so exactly ONE backoff
    #: ladder runs per logical call.
    retries_internally = False

    POOL_WATCH_UNSUPPORTED = "pool watch not supported by this client"

    @abc.abstractmethod
    def get_node(self, name: str) -> dict:
        """GET /api/v1/nodes/{name}. Raises KubeApiError (404 if absent)."""

    @abc.abstractmethod
    def patch_node_labels(self, name: str, labels: Mapping[str, str | None]) -> dict:
        """JSON merge-patch {"metadata": {"labels": labels}} onto the node.

        A ``None`` value deletes the label (merge-patch semantics). Returns
        the patched node. This deliberately never writes anything but labels
        (SURVEY.md §8.3)."""

    def patch_node_annotations(
        self, name: str, annotations: Mapping[str, str | None]
    ) -> dict:
        """JSON merge-patch {"metadata": {"annotations": ...}} onto the node.

        Annotations carry payloads too large for label values (the signed
        attestation quote, ccmanager/multislice.py); a ``None`` value
        deletes. Optional capability — the default raises KubeApiError so
        callers degrade cleanly on clients without it."""
        raise KubeApiError(
            None, "annotation patching not supported by this client"
        )

    def patch_node_taints(
        self, name: str, add: list[dict], remove_keys: list[str]
    ) -> dict:
        """Add/remove taints on the node's ``spec.taints``.

        ``add`` entries are taint dicts ({key, value, effect}); existing
        taints with the same key are replaced, and ``remove_keys`` are
        deleted. Taints are a LIST in the node spec, so implementations do
        a read-modify-write and replace the whole list in one merge-patch
        — same ``patch nodes`` RBAC verb as the label writes. Used by
        quarantine (ccmanager/remediation.py) to fence workloads off a
        condemned node with ``NoSchedule``. Optional capability — the
        default raises so callers degrade cleanly."""
        raise KubeApiError(None, "taint patching not supported by this client")

    @abc.abstractmethod
    def list_nodes(self, label_selector: str | None = None) -> list[dict]:
        """GET /api/v1/nodes, optionally filtered by an equality label
        selector ("k=v" or "k" presence, comma-separated)."""

    def list_nodes_page(
        self,
        label_selector: str | None = None,
        limit: int | None = None,
        continue_token: str | None = None,
    ) -> dict:
        """One page of GET /api/v1/nodes with ``limit``/``continue``
        chunking, returned NodeList-shaped: ``{"items": [...], "metadata":
        {"resourceVersion": ..., "continue": ...}}``. An absent/empty
        ``metadata.continue`` ends the listing. The default degrades to a
        single unchunked page through :meth:`list_nodes` (minimal clients
        keep working; they just pay the one big response a real 10k-node
        listing would chunk)."""
        if continue_token:
            # The default never hands out a token, so receiving one back
            # means the caller mixed clients mid-listing.
            raise KubeApiError(
                410, "continue token not recognized by this client"
            )
        return {
            "items": self.list_nodes(label_selector),
            "metadata": {"resourceVersion": ""},
        }

    @abc.abstractmethod
    def list_pods(
        self,
        namespace: str,
        label_selector: str | None = None,
        field_selector: str | None = None,
    ) -> list[dict]:
        """GET /api/v1/namespaces/{ns}/pods with optional selectors.

        The manager uses label_selector="app=<component>" plus
        field_selector="spec.nodeName=<node>" while polling the drain
        (reference gpu_operator_eviction.py:185-207)."""

    @abc.abstractmethod
    def watch_nodes(
        self,
        name: str,
        resource_version: str | None = None,
        timeout_seconds: int = 300,
    ) -> Iterator[WatchEvent]:
        """Watch a single node (field selector metadata.name=<name>).

        Yields WatchEvents until the server-side timeout elapses, then
        returns. Transport errors raise KubeApiError; a stale
        resourceVersion raises KubeApiError(410) either immediately or as an
        ERROR event translated by the caller (reference main.py:622-638)."""

    def watch_nodes_pool(
        self,
        label_selector: str | None = None,
        resource_version: str | None = None,
        timeout_seconds: int = 300,
    ) -> Iterator[WatchEvent]:
        """Watch EVERY node matching a label selector (one stream for a
        whole pool — the informer cache's transport,
        ccmanager/informer.py).

        Same event contract as :meth:`watch_nodes` (ADDED/MODIFIED/
        DELETED/BOOKMARK/ERROR, 410 on a stale resourceVersion), plus the
        real apiserver's selector-scoping rule: an object that STOPS
        matching the selector is delivered as DELETED — the cache must
        drop it, not keep serving its stale last-matching state. Optional
        capability: the default raises the POOL_WATCH_UNSUPPORTED marker
        so callers can degrade to polling listings."""
        raise KubeApiError(None, self.POOL_WATCH_UNSUPPORTED)

    def create_event(self, namespace: str, event: dict) -> dict:
        """POST a core/v1 Event (``kubectl describe node`` visibility).

        Optional capability — the default raises, and callers must treat
        emission as best-effort (events are operator convenience, never
        control-plane state). Not retried on failure: POST is not
        idempotent and a lost event is acceptable."""
        raise KubeApiError(None, "event creation not supported by this client")

    # -- coordination.k8s.io/v1 Leases ---------------------------------
    #
    # The single-writer primitive for fleet-scale operations: the rolling
    # orchestrator holds a Lease while it flips a pool, with the rollout
    # record checkpointed into the Lease's annotations so a successor can
    # resume (ccmanager/rollout_state.py). All four verbs are OPTIONAL
    # capabilities (the defaults raise the LEASE_UNSUPPORTED marker) so
    # minimal clients degrade to an unfenced legacy rollout instead of
    # crashing. ``update_lease`` is the optimistic-concurrency hinge:
    # implementations MUST reject a stale ``metadata.resourceVersion``
    # with 409 Conflict — that CAS is what makes the fencing token
    # trustworthy.

    LEASE_UNSUPPORTED = "lease operations not supported by this client"

    def get_lease(self, namespace: str, name: str) -> dict:
        """GET a coordination.k8s.io/v1 Lease (404 if absent)."""
        raise KubeApiError(None, self.LEASE_UNSUPPORTED)

    def create_lease(self, namespace: str, name: str, spec: dict) -> dict:
        """POST a new Lease with the given ``spec`` (holderIdentity,
        leaseDurationSeconds, acquireTime, renewTime, leaseTransitions).
        Raises 409 AlreadyExists when the Lease exists — the loser of a
        create race must observe the winner, never overwrite it."""
        raise KubeApiError(None, self.LEASE_UNSUPPORTED)

    def update_lease(self, namespace: str, name: str, lease: dict) -> dict:
        """PUT the full Lease object back. ``lease`` must carry the
        ``metadata.resourceVersion`` the caller read; a mismatch with the
        stored object raises 409 Conflict (optimistic concurrency — the
        compare-and-swap every lease transition and rollout checkpoint
        rides on). Never retried internally: a retry after an ambiguous
        first attempt would 409 against its own write."""
        raise KubeApiError(None, self.LEASE_UNSUPPORTED)

    def delete_lease(self, namespace: str, name: str) -> None:
        """DELETE a Lease (404 if absent) — the operator's force-release
        escape hatch for a wedged rollout lease."""
        raise KubeApiError(None, self.LEASE_UNSUPPORTED)

    def self_subject_access_review(
        self, verb: str, resource: str, namespace: str | None = None
    ) -> bool:
        """Whether THIS identity may perform verb on resource (SSAR).

        Optional capability with a clean failure mode (``tpu-cc-ctl
        rbac-check`` reports it instead of crashing on AttributeError);
        RestKube implements the real apiserver call."""
        raise KubeApiError(
            None, "SelfSubjectAccessReview not supported by this client"
        )
