"""In-memory fake apiserver implementing KubeApi.

Used by unit/integration tests and by bench.py's no-cluster dry-run
(BASELINE.json configs[0]). The reference project has no fake backend at all
(SURVEY.md §4) — this is the deliberate fix.

Features beyond a dumb store, each needed by a specific test scenario:

- monotonically increasing resourceVersions with a watch event log,
- configurable "compaction" so old resourceVersions raise 410 Gone
  (exercises the resync path, reference main.py:670-682),
- injectable transport errors / ERROR events on the watch stream
  (exercises the consecutive-error cap, reference main.py:659-668),
- reactors: callbacks fired after each node label patch, used to emulate the
  operator controller that deletes component pods when it sees the paused
  label (the reference relies on the external GPU operator for this,
  gpu_operator_eviction.py:185-207).
"""

from __future__ import annotations

import copy
import threading
import time
from typing import Callable, Iterator, Mapping

from tpu_cc_manager.kubeclient.api import KubeApi, KubeApiError, WatchEvent


def _match_label_selector(labels: Mapping[str, str], selector: str | None) -> bool:
    if not selector:
        return True
    for term in selector.split(","):
        term = term.strip()
        if not term:
            continue
        if "=" in term:
            k, _, v = term.partition("=")
            if labels.get(k.strip()) != v.strip():
                return False
        elif labels.get(term) is None:
            return False
    return True


def _match_pod_field_selector(pod: dict, selector: str | None) -> bool:
    if not selector:
        return True
    for term in selector.split(","):
        k, _, v = term.partition("=")
        k, v = k.strip(), v.strip()
        if k == "spec.nodeName":
            if (pod.get("spec") or {}).get("nodeName") != v:
                return False
        elif k == "metadata.name":
            if (pod.get("metadata") or {}).get("name") != v:
                return False
        elif k == "status.phase":
            if (pod.get("status") or {}).get("phase") != v:
                return False
        else:
            raise KubeApiError(400, f"unsupported field selector {k!r}")
    return True


class FakeKube(KubeApi):
    def __init__(self) -> None:
        self._lock = threading.Condition()
        self._rv = 0
        self._compacted_before = 0  # rvs strictly below this are 410-Gone
        self._nodes: dict[str, dict] = {}
        self._pods: dict[tuple[str, str], dict] = {}  # (namespace, name) -> pod
        self._leases: dict[tuple[str, str], dict] = {}  # (namespace, name)
        self._node_events: list[tuple[int, WatchEvent]] = []
        # In-flight chunked listings: continue tokens serve from the
        # snapshot taken at the FIRST page (like the real apiserver's
        # etcd-revision-pinned continuation), never the live store — a
        # node changing between pages must not shift the sort and drop a
        # neighbor from the listing. token -> (pages' items, listing rv).
        self._page_snapshots: dict[str, tuple[list[dict], str]] = {}
        self._page_snapshot_seq = 0
        self._watch_faults: list[Exception | WatchEvent] = []
        self._patch_reactors: list[Callable[[str, dict], None]] = []
        # Counters some tests assert on.
        self.patch_calls = 0
        self.list_pod_calls = 0
        # Per-verb request accounting, apiserver-side (what a real
        # apiserver's QPS dashboard would show): the scale harness
        # (hack/scale_bench.py) reads this to prove the informer refactor
        # turned O(pool) listings into O(changes) watch traffic.
        self.request_counts: dict[str, int] = {}
        # Events emitted via create_event, in order (tests assert on them).
        self.events: list[dict] = []

    def _count(self, verb: str) -> None:
        # Caller need not hold the lock; GIL-atomic enough for counters
        # read only after the workload quiesces.
        self.request_counts[verb] = self.request_counts.get(verb, 0) + 1

    # ---- test harness helpers -------------------------------------------

    def add_node(self, name: str, labels: dict | None = None) -> dict:
        with self._lock:
            self._rv += 1
            node = {
                "kind": "Node",
                "metadata": {
                    "name": name,
                    "labels": dict(labels or {}),
                    "resourceVersion": str(self._rv),
                },
            }
            self._nodes[name] = node
            self._record_event("ADDED", node)
            return copy.deepcopy(node)

    def add_pod(
        self,
        namespace: str,
        name: str,
        node_name: str,
        labels: dict | None = None,
        phase: str = "Running",
    ) -> dict:
        with self._lock:
            self._rv += 1
            pod = {
                "kind": "Pod",
                "metadata": {
                    "name": name,
                    "namespace": namespace,
                    "labels": dict(labels or {}),
                    "resourceVersion": str(self._rv),
                },
                "spec": {"nodeName": node_name},
                "status": {"phase": phase},
            }
            self._pods[(namespace, name)] = pod
            return copy.deepcopy(pod)

    def delete_pod(self, namespace: str, name: str) -> None:
        with self._lock:
            self._pods.pop((namespace, name), None)

    def delete_pods_matching(self, namespace: str, label_selector: str) -> int:
        """Emulates the operator controller reacting to a paused label."""
        with self._lock:
            doomed = [
                key
                for key, pod in self._pods.items()
                if key[0] == namespace
                and _match_label_selector((pod["metadata"].get("labels") or {}), label_selector)
            ]
            for key in doomed:
                del self._pods[key]
            return len(doomed)

    def delete_node(self, name: str) -> None:
        """Harness helper modeling a cluster-autoscaler scale-down: the
        Node object disappears and watchers get a DELETED event (GETs
        404, listings drop it) — exactly what a real apiserver serves
        when the autoscaler deletes a node mid-rollout."""
        with self._lock:
            node = self._nodes.pop(name, None)
            if node is None:
                return
            self._rv += 1
            node["metadata"]["resourceVersion"] = str(self._rv)
            self._record_event("DELETED", node)

    def add_patch_reactor(self, fn: Callable[[str, dict], None]) -> None:
        """fn(node_name, patched_node) runs (outside the lock) after each
        patch_node_labels call."""
        self._patch_reactors.append(fn)

    def inject_watch_fault(self, fault: Exception | WatchEvent) -> None:
        """Next watch_nodes call raises/yields this before streaming events."""
        self._watch_faults.append(fault)

    def compact(self) -> None:
        """Forget watch history: watches from older rvs now get 410 Gone."""
        with self._lock:
            self._compacted_before = self._rv + 1
            self._node_events.clear()

    def set_node_label(self, name: str, key: str, value: str | None) -> dict:
        """Out-of-band label write (e.g. 'the user edits the desired mode')."""
        return self.patch_node_labels(name, {key: value}, _count=False)

    # ---- KubeApi ---------------------------------------------------------

    def get_node(self, name: str) -> dict:
        self._count("get")
        with self._lock:
            node = self._nodes.get(name)
            if node is None:
                raise KubeApiError(404, f"node {name} not found")
            return copy.deepcopy(node)

    def patch_node_labels(
        self, name: str, labels: Mapping[str, str | None], _count: bool = True
    ) -> dict:
        if _count:
            self._count("patch")
        with self._lock:
            node = self._nodes.get(name)
            if node is None:
                raise KubeApiError(404, f"node {name} not found")
            if _count:
                self.patch_calls += 1
            current = node["metadata"].setdefault("labels", {})
            for k, v in labels.items():
                if v is None:
                    current.pop(k, None)
                else:
                    current[k] = str(v)
            self._rv += 1
            node["metadata"]["resourceVersion"] = str(self._rv)
            self._record_event("MODIFIED", node)
            snapshot = copy.deepcopy(node)
        for reactor in self._patch_reactors:
            reactor(name, snapshot)
        return snapshot

    def patch_node_annotations(
        self, name: str, annotations: Mapping[str, str | None]
    ) -> dict:
        self._count("patch")
        with self._lock:
            node = self._nodes.get(name)
            if node is None:
                raise KubeApiError(404, f"node {name} not found")
            current = node["metadata"].setdefault("annotations", {})
            for k, v in annotations.items():
                if v is None:
                    current.pop(k, None)
                else:
                    current[k] = str(v)
            self._rv += 1
            node["metadata"]["resourceVersion"] = str(self._rv)
            self._record_event("MODIFIED", node)
            return copy.deepcopy(node)

    def patch_node_taints(
        self, name: str, add: list[dict], remove_keys: list[str]
    ) -> dict:
        self._count("patch")
        with self._lock:
            node = self._nodes.get(name)
            if node is None:
                raise KubeApiError(404, f"node {name} not found")
            taints = list((node.get("spec") or {}).get("taints") or [])
            doomed = set(remove_keys) | {t.get("key") for t in add}
            taints = [t for t in taints if t.get("key") not in doomed]
            taints.extend(copy.deepcopy(dict(t)) for t in add)
            node.setdefault("spec", {})["taints"] = taints
            self._rv += 1
            node["metadata"]["resourceVersion"] = str(self._rv)
            self._record_event("MODIFIED", node)
            return copy.deepcopy(node)

    def list_nodes(self, label_selector: str | None = None) -> list[dict]:
        self._count("list")
        with self._lock:
            return [
                copy.deepcopy(n)
                for n in self._nodes.values()
                if _match_label_selector(n["metadata"].get("labels") or {}, label_selector)
            ]

    def list_nodes_page(
        self,
        label_selector: str | None = None,
        limit: int | None = None,
        continue_token: str | None = None,
    ) -> dict:
        """Chunked listing with real ``limit``/``continue`` semantics:
        the first page snapshots the name-sorted matching set and the
        token walks THAT snapshot (the real apiserver serves continues
        from the first page's etcd revision) — a label flip between pages
        cannot shift the sort and drop a neighbor from the listing. Every
        page reports the snapshot's resourceVersion so an informer can
        watch from the listing it built its cache from. An unknown or
        malformed token answers 410 Expired (client restarts the
        listing)."""
        self._count("list")
        with self._lock:
            if continue_token:
                snap = self._page_snapshots.get(continue_token)
                if snap is None:
                    raise KubeApiError(
                        410,
                        f"continue token {continue_token!r} expired",
                    )
                matching, rv, offset = (
                    snap[0], snap[1], int(continue_token.split(":")[-1])
                )
            else:
                matching = [
                    copy.deepcopy(n)
                    for _, n in sorted(self._nodes.items())
                    if _match_label_selector(
                        n["metadata"].get("labels") or {}, label_selector
                    )
                ]
                rv = str(self._rv)
                offset = 0
            end = offset + limit if limit else len(matching)
            items = [copy.deepcopy(n) for n in matching[offset:end]]
            meta: dict = {"resourceVersion": rv}
            if continue_token:
                del self._page_snapshots[continue_token]
            if end < len(matching):
                self._page_snapshot_seq += 1
                token = f"{self._page_snapshot_seq}:{end}"
                self._page_snapshots[token] = (matching, rv)
                meta["continue"] = token
                # Abandoned paginations must not pin snapshots forever.
                while len(self._page_snapshots) > 8:
                    oldest = next(iter(self._page_snapshots))
                    del self._page_snapshots[oldest]
            return {"kind": "NodeList", "items": items, "metadata": meta}

    def list_pods(
        self,
        namespace: str,
        label_selector: str | None = None,
        field_selector: str | None = None,
    ) -> list[dict]:
        self._count("list")
        with self._lock:
            self.list_pod_calls += 1
            return [
                copy.deepcopy(p)
                for (ns, _), p in self._pods.items()
                if ns == namespace
                and _match_label_selector(p["metadata"].get("labels") or {}, label_selector)
                and _match_pod_field_selector(p, field_selector)
            ]

    def create_event(self, namespace: str, event: dict) -> dict:
        self._count("create")
        with self._lock:
            self.events.append({"namespace": namespace, **copy.deepcopy(event)})
            return copy.deepcopy(event)

    # Lease verbs with honest optimistic concurrency: update_lease does a
    # real resourceVersion compare-and-swap (409 on mismatch), because the
    # rollout lease's fencing guarantee is only as strong as that CAS.

    def get_lease(self, namespace: str, name: str) -> dict:
        self._count("get")
        with self._lock:
            lease = self._leases.get((namespace, name))
            if lease is None:
                raise KubeApiError(404, f"lease {namespace}/{name} not found")
            return copy.deepcopy(lease)

    def create_lease(self, namespace: str, name: str, spec: dict) -> dict:
        self._count("create")
        with self._lock:
            if (namespace, name) in self._leases:
                raise KubeApiError(
                    409, f"lease {namespace}/{name} already exists"
                )
            self._rv += 1
            lease = {
                "apiVersion": "coordination.k8s.io/v1",
                "kind": "Lease",
                "metadata": {
                    "name": name,
                    "namespace": namespace,
                    "resourceVersion": str(self._rv),
                },
                "spec": copy.deepcopy(dict(spec)),
            }
            self._leases[(namespace, name)] = lease
            return copy.deepcopy(lease)

    def update_lease(self, namespace: str, name: str, lease: dict) -> dict:
        self._count("update")
        with self._lock:
            stored = self._leases.get((namespace, name))
            if stored is None:
                raise KubeApiError(404, f"lease {namespace}/{name} not found")
            sent_rv = (lease.get("metadata") or {}).get("resourceVersion")
            if str(sent_rv) != stored["metadata"]["resourceVersion"]:
                raise KubeApiError(
                    409,
                    f"lease {namespace}/{name}: resourceVersion conflict "
                    f"(sent {sent_rv}, stored "
                    f"{stored['metadata']['resourceVersion']})",
                )
            self._rv += 1
            updated = copy.deepcopy(lease)
            updated["metadata"]["resourceVersion"] = str(self._rv)
            updated["metadata"]["name"] = name
            updated["metadata"]["namespace"] = namespace
            self._leases[(namespace, name)] = updated
            return copy.deepcopy(updated)

    def delete_lease(self, namespace: str, name: str) -> None:
        self._count("delete")
        with self._lock:
            if self._leases.pop((namespace, name), None) is None:
                raise KubeApiError(404, f"lease {namespace}/{name} not found")

    def self_subject_access_review(
        self, verb: str, resource: str, namespace: str | None = None
    ) -> bool:
        """Grants everything unless the test narrows it via ``rbac_rules``
        (a dict of (verb, resource) -> bool set on the instance)."""
        rules = getattr(self, "rbac_rules", None)
        if rules is None:
            return True
        return bool(rules.get((verb, resource), False))

    def watch_nodes(
        self,
        name: str,
        resource_version: str | None = None,
        timeout_seconds: int = 300,
    ) -> Iterator[WatchEvent]:
        self._count("watch")
        if self._watch_faults:
            fault = self._watch_faults.pop(0)
            if isinstance(fault, Exception):
                raise fault
            yield fault
            return
        start_rv = int(resource_version) if resource_version else 0
        with self._lock:
            if start_rv and start_rv < self._compacted_before - 1:
                raise KubeApiError(410, "resourceVersion too old")
        deadline = time.monotonic() + timeout_seconds
        cursor = start_rv
        while True:
            with self._lock:
                if cursor < getattr(self, "_dropped_below_rv", 0):
                    raise KubeApiError(
                        410, "watch history compacted past the cursor"
                    )
                pending = [
                    ev
                    for rv, ev in self._node_events
                    if rv > cursor and ev.object["metadata"]["name"] == name
                ]
                if pending:
                    cursor = max(
                        int(ev.object["metadata"]["resourceVersion"]) for ev in pending
                    )
                else:
                    remaining = deadline - time.monotonic()
                    if remaining <= 0:
                        return
                    self._lock.wait(timeout=min(remaining, 0.05))
                    continue
            for ev in pending:
                yield copy.deepcopy(ev)

    def watch_nodes_pool(
        self,
        label_selector: str | None = None,
        resource_version: str | None = None,
        timeout_seconds: int = 300,
    ) -> Iterator[WatchEvent]:
        """Selector-scoped pool watch with the real apiserver's view
        semantics: a node whose labels stop matching the selector is
        delivered as DELETED (the cache must drop it), one that starts
        matching arrives as its change event. ``in_view`` reconstructs
        which nodes the caller's listing (at ``resource_version``) could
        see, from the retained event log plus the nodes unchanged since."""
        self._count("watch")
        if self._watch_faults:
            fault = self._watch_faults.pop(0)
            if isinstance(fault, Exception):
                raise fault
            yield fault
            return
        start_rv = int(resource_version) if resource_version else 0
        in_view: set[str] = set()
        with self._lock:
            if start_rv and start_rv < self._compacted_before - 1:
                raise KubeApiError(410, "resourceVersion too old")
            for name, node in self._nodes.items():
                if int(node["metadata"]["resourceVersion"]) <= start_rv and (
                    _match_label_selector(
                        node["metadata"].get("labels") or {}, label_selector
                    )
                ):
                    in_view.add(name)
            for rv, ev in self._node_events:
                if rv > start_rv:
                    break
                name = ev.object["metadata"]["name"]
                if ev.type != "DELETED" and _match_label_selector(
                    ev.object["metadata"].get("labels") or {}, label_selector
                ):
                    in_view.add(name)
                else:
                    in_view.discard(name)
        deadline = time.monotonic() + timeout_seconds
        cursor = start_rv
        while True:
            with self._lock:
                if cursor < getattr(self, "_dropped_below_rv", 0):
                    raise KubeApiError(
                        410, "watch history compacted past the cursor"
                    )
                pending = [ev for rv, ev in self._node_events if rv > cursor]
                if pending:
                    cursor = max(
                        int(ev.object["metadata"]["resourceVersion"])
                        for ev in pending
                    )
                else:
                    remaining = deadline - time.monotonic()
                    if remaining <= 0:
                        return
                    self._lock.wait(timeout=min(remaining, 0.05))
                    continue
            for ev in pending:
                name = ev.object["metadata"]["name"]
                matches = ev.type != "DELETED" and _match_label_selector(
                    ev.object["metadata"].get("labels") or {}, label_selector
                )
                if matches:
                    yield copy.deepcopy(
                        WatchEvent(
                            "ADDED" if name not in in_view else ev.type,
                            ev.object,
                        )
                    )
                    in_view.add(name)
                elif name in in_view:
                    in_view.discard(name)
                    yield copy.deepcopy(WatchEvent("DELETED", ev.object))

    # ---- internals -------------------------------------------------------

    def _record_event(self, etype: str, node: dict) -> None:
        # Caller holds the lock.
        self._node_events.append((self._rv, WatchEvent(etype, copy.deepcopy(node))))
        if len(self._node_events) > 4096:
            # Remember the newest DROPPED rv: a watcher whose cursor is
            # below it may have missed events, and (like a real apiserver
            # whose history was compacted out from under a slow watcher)
            # must get 410 Gone and relist — never a silent gap. Found
            # while scaling to 10k nodes, where a busy fleet can outrun a
            # momentarily-stalled watch reader.
            self._dropped_below_rv = max(
                getattr(self, "_dropped_below_rv", 0),
                self._node_events[2047][0],
            )
            del self._node_events[:2048]
        self._lock.notify_all()
