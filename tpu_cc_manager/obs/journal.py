"""Span journal: bounded in-process record of finished spans.

The reference has no instrumentation beyond log lines (SURVEY.md §5); the
north-star metric here is a latency budget (per-node drain→CC-on→ready
< 90 s, BASELINE.md), and when a rollout blows it the flat per-phase gauges
cannot say *which slice, which retry, which handshake* ate the time. The
journal is the other half of the tracing subsystem (obs/trace.py): every
finished span lands in a thread-safe ring buffer (bounded — the agent is a
long-lived DaemonSet pod) and, when ``CC_TRACE_FILE`` is set, is appended
as one JSON line to a size-bounded JSONL file, so a post-mortem has the
span stream even after the ring rolled over.

Consumers:

- ``/tracez`` and ``/statusz`` (ccmanager/metrics_server.py) serve the ring
  and the in-flight set over HTTP;
- bench.py reads the journal to report per-phase histograms instead of
  single-run totals;
- operators tail the JSONL file (same shape as the HTTP payloads).
"""

from __future__ import annotations

import collections
import json
import logging
import os
from typing import TYPE_CHECKING, Iterable

from tpu_cc_manager.utils import locks as locks_mod

if TYPE_CHECKING:  # pragma: no cover - import cycle guard (trace imports us)
    from tpu_cc_manager.obs.trace import Span

log = logging.getLogger(__name__)

TRACE_FILE_ENV = "CC_TRACE_FILE"
TRACE_FILE_MAX_BYTES_ENV = "CC_TRACE_FILE_MAX_BYTES"
DEFAULT_CAPACITY = 2048
# One rotation (file -> file.1) keeps disk usage bounded at ~2x this.
DEFAULT_MAX_FILE_BYTES = 8 * 1024 * 1024


class Journal:
    """Thread-safe bounded record of spans, optionally mirrored to JSONL.

    ``trace_file=None`` (the default) reads :data:`TRACE_FILE_ENV` at
    construction; pass ``trace_file=""`` to force the file sink off
    regardless of the environment.
    """

    def __init__(
        self,
        capacity: int = DEFAULT_CAPACITY,
        trace_file: str | None = None,
        max_file_bytes: int | None = None,
    ) -> None:
        self._lock = locks_mod.make_lock("obs.journal")
        self._finished: collections.deque[dict] = collections.deque(  # cclint: guarded-by(_lock)
            maxlen=max(1, capacity)
        )
        # span_id -> live Span, for the /statusz in-flight tree.
        self._active: dict[str, "Span"] = {}  # cclint: guarded-by(_lock)
        if trace_file is None:
            trace_file = os.environ.get(TRACE_FILE_ENV, "")
        self.trace_file = trace_file or None
        if max_file_bytes is None:
            raw = os.environ.get(TRACE_FILE_MAX_BYTES_ENV, "")
            try:
                max_file_bytes = int(raw) if raw else DEFAULT_MAX_FILE_BYTES
            except ValueError:
                # Observability must never take the agent down: a malformed
                # size (e.g. "8M") degrades to the default, loudly.
                log.warning(
                    "invalid %s=%r; using default %d",
                    TRACE_FILE_MAX_BYTES_ENV, raw, DEFAULT_MAX_FILE_BYTES,
                )
                max_file_bytes = DEFAULT_MAX_FILE_BYTES
        self.max_file_bytes = max_file_bytes
        self._file_bytes = 0  # cclint: guarded-by(_lock)
        if self.trace_file and os.path.exists(self.trace_file):
            try:
                self._file_bytes = os.path.getsize(self.trace_file)
            except OSError:
                self._file_bytes = 0

    # ------------------------------------------------------------------
    # Recording (called by obs/trace.py)
    # ------------------------------------------------------------------

    def span_started(self, span: "Span") -> None:
        with self._lock:
            self._active[span.span_id] = span

    def span_finished(self, span: "Span") -> None:
        entry = span.to_dict()
        with self._lock:
            self._active.pop(span.span_id, None)
            self._finished.append(entry)
        if self.trace_file:
            self._write_jsonl(entry)

    def _write_jsonl(self, entry: dict) -> None:
        """Append one JSON line, rotating file -> file.1 at the size cap.

        Best-effort: the journal is observability, and neither a full disk
        nor an unserializable span attribute may fail a reconcile."""
        try:
            line = json.dumps(entry, sort_keys=True, default=str) + "\n"
            data = line.encode()
            with self._lock:
                if (
                    self.max_file_bytes > 0
                    and self._file_bytes + len(data) > self.max_file_bytes
                    and self._file_bytes > 0
                ):
                    os.replace(self.trace_file, self.trace_file + ".1")
                    self._file_bytes = 0
                with open(self.trace_file, "a", encoding="utf-8") as f:
                    f.write(line)
                self._file_bytes += len(data)
        except (OSError, TypeError, ValueError) as e:
            log.debug("trace journal write failed (non-fatal): %s", e)

    # ------------------------------------------------------------------
    # Reading (metrics_server.py, bench.py, tests)
    # ------------------------------------------------------------------

    def spans(
        self, trace_id: str | None = None, limit: int | None = None
    ) -> list[dict]:
        """Finished spans, oldest first, optionally filtered by trace and
        bounded to the newest ``limit``."""
        with self._lock:
            out = list(self._finished)
        if trace_id is not None:
            out = [s for s in out if s["trace_id"] == trace_id]
        if limit is not None and limit >= 0:
            out = out[-limit:]
        return out

    def active_spans(self) -> list[dict]:
        """In-flight (started, unfinished) spans as dicts."""
        with self._lock:
            live = list(self._active.values())
        return [s.to_dict() for s in live]

    def trace_ids(self) -> list[str]:
        """Distinct trace ids in the ring, oldest first."""
        seen: dict[str, None] = {}
        for s in self.spans():
            seen.setdefault(s["trace_id"], None)
        return list(seen)

    def span_tree(self, spans: Iterable[dict]) -> list[dict]:
        """Nest a flat span list into parent→children trees (roots
        returned; orphans whose parent is outside the list become roots
        too, so a partially-rolled-over trace still renders)."""
        nodes = {s["span_id"]: {**s, "children": []} for s in spans}
        roots: list[dict] = []
        for node in nodes.values():
            parent = nodes.get(node.get("parent_id") or "")
            if parent is not None:
                parent["children"].append(node)
            else:
                roots.append(node)
        return roots

    def phase_durations(
        self, names: Iterable[str] | None = None
    ) -> dict[str, list[float]]:
        """name -> [seconds, ...] across every finished span (bench.py's
        per-phase histogram input). ``names`` filters to the given set."""
        wanted = set(names) if names is not None else None
        out: dict[str, list[float]] = {}
        for s in self.spans():
            if wanted is not None and s["name"] not in wanted:
                continue
            out.setdefault(s["name"], []).append(s["seconds"])
        return out

    def clear(self) -> None:
        with self._lock:
            self._finished.clear()
            self._active.clear()


#: Process-wide default journal (the agent's; bench/tests build their own).
JOURNAL = Journal()
