"""In-process tracing: spans with trace ids, nesting, and status.

Deliberately tiny — OpenTelemetry is not in this image, and the control
plane needs exactly four things the stdlib gives for free:

- a **trace id** minted once per reconcile (or rollout) and shared by every
  span under it, so a drain handshake in ``drain/`` correlates with the
  reset/attest it triggered in ``ccmanager/manager.py``;
- **parent/child nesting** via a :mod:`contextvars` context variable, so a
  phase span opened in ``utils/metrics.py`` automatically parents the
  barrier/attestation/smoke sub-spans opened layers below it;
- **attributes and status** (ok / error + message) per span;
- a **journal** of finished spans (obs/journal.py) that ``/tracez`` and
  bench.py read.

Context propagation: ``contextvars`` flow through generators and async
code, but NOT into ``threading.Thread`` targets. Code that fans work out
to threads under one trace wraps the target with :func:`in_current_context`
(the rolling orchestrator's wave threads do exactly this, so sharded-wave
spans nest under the rollout root).

Cross-PROCESS propagation: a root span may be opened with an explicit
``parent=(trace_id, span_id)`` — the remote-parent contract the rolling
orchestrator uses to stitch its rollout trace to each node agent's
reconcile trace. The orchestrator stamps
:func:`format_parent`'s ``<trace>.<span>`` value into the desired-mode
patch (labels.ROLLOUT_TRACE_LABEL, dot-separated because label values
cannot carry ``:``), the agent parses it back with :func:`parse_parent`
and opens its reconcile root under it, and ``/tracez?trace_id=`` then
renders ONE causal tree from ``ctl rollout`` down through each node's
drain/reset/smoke.
"""

from __future__ import annotations

import contextlib
import contextvars
import secrets
import time
from dataclasses import dataclass, field
from typing import Callable

from tpu_cc_manager.obs import journal as journal_mod

#: Current span for this execution context (None outside any trace).
_CURRENT: contextvars.ContextVar["Span | None"] = contextvars.ContextVar(
    "tpu_cc_current_span", default=None
)

STATUS_IN_PROGRESS = "in_progress"
STATUS_OK = "ok"
STATUS_ERROR = "error"


def new_id() -> str:
    """128-bit trace / 64-bit span ids are overkill for one node agent;
    64 random bits keep the labels and log lines short."""
    return secrets.token_hex(8)


@dataclass
class Span:
    name: str
    trace_id: str
    span_id: str
    parent_id: str | None = None
    start_monotonic: float = 0.0
    end_monotonic: float | None = None
    start_ts: float = 0.0  # wall clock, for cross-process correlation
    attributes: dict = field(default_factory=dict)
    status: str = STATUS_IN_PROGRESS
    error: str | None = None
    # The journal this span reports to; children inherit it from their
    # parent so one reconcile's whole tree lands in one journal.
    journal: "journal_mod.Journal | None" = field(
        default=None, repr=False, compare=False
    )

    @property
    def seconds(self) -> float:
        end = (
            self.end_monotonic
            if self.end_monotonic is not None
            else time.monotonic()
        )
        return max(0.0, end - self.start_monotonic)

    def set_attribute(self, key: str, value) -> None:
        self.attributes[key] = value

    def to_dict(self) -> dict:
        try:
            attributes = dict(self.attributes)
        except RuntimeError:  # live span mutated while /statusz serializes
            attributes = {}
        return {
            "name": self.name,
            "trace_id": self.trace_id,
            "span_id": self.span_id,
            "parent_id": self.parent_id,
            "start_ts": round(self.start_ts, 3),
            "seconds": round(self.seconds, 6),
            "status": self.status,
            "error": self.error,
            "attributes": attributes,
        }


def current_span() -> Span | None:
    return _CURRENT.get()


def current_trace_id() -> str | None:
    span = _CURRENT.get()
    return span.trace_id if span is not None else None


def current_span_id() -> str | None:
    span = _CURRENT.get()
    return span.span_id if span is not None else None


def format_parent(s: Span) -> str:
    """``<trace_id>.<span_id>``: the label-safe wire form of a span's
    identity (dot, not colon — k8s label values reject ``:``)."""
    return f"{s.trace_id}.{s.span_id}"


def parse_parent(value: str | None) -> tuple[str, str] | None:
    """Parse a :func:`format_parent` value back to (trace_id, span_id);
    None for absent/garbled input — a stitching hint must never fail a
    reconcile."""
    if not value:
        return None
    parts = value.split(".")
    if len(parts) != 2 or not all(parts):
        return None
    return parts[0], parts[1]


@contextlib.contextmanager
def span(
    name: str,
    journal: "journal_mod.Journal | None" = None,
    root: bool = False,
    parent: tuple[str, str] | None = None,
    **attributes,
):
    """Open a span under the current one (or a new root trace).

    - nested under :func:`current_span` unless ``root=True``;
    - ``parent=(trace_id, span_id)`` adopts a REMOTE parent (only
      meaningful with ``root=True``): the span joins that trace instead
      of minting its own — cross-process stitching;
    - ``journal`` defaults to the parent's journal, then the process-wide
      :data:`~tpu_cc_manager.obs.journal.JOURNAL`;
    - an escaping exception marks the span ``error`` (message recorded) and
      propagates.
    """
    ambient = None if root else _CURRENT.get()
    if ambient is not None:
        trace_id = ambient.trace_id
        parent_id = ambient.span_id
        if journal is None:
            journal = ambient.journal
    elif parent is not None:
        trace_id, parent_id = parent
    else:
        trace_id = new_id()
        parent_id = None
    if journal is None:
        journal = journal_mod.JOURNAL
    s = Span(
        name=name,
        trace_id=trace_id,
        span_id=new_id(),
        parent_id=parent_id,
        start_monotonic=time.monotonic(),
        start_ts=time.time(),
        attributes=dict(attributes),
        journal=journal,
    )
    journal.span_started(s)
    token = _CURRENT.set(s)
    try:
        yield s
        if s.status == STATUS_IN_PROGRESS:
            s.status = STATUS_OK
    except BaseException as e:
        s.status = STATUS_ERROR
        s.error = f"{type(e).__name__}: {e}"
        raise
    finally:
        s.end_monotonic = time.monotonic()
        _CURRENT.reset(token)
        journal.span_finished(s)


def root_span(
    name: str,
    journal: "journal_mod.Journal | None" = None,
    parent: tuple[str, str] | None = None,
    **attributes,
):
    """A new root trace, ignoring any ambient span — one reconcile, one
    rollout, one pool verification each get their own trace id. With
    ``parent`` the root joins a REMOTE trace instead (the agent adopting
    the orchestrator's rollout trace)."""
    return span(name, journal=journal, root=True, parent=parent, **attributes)


def in_current_context(fn: Callable, *args, **kwargs) -> Callable[[], object]:
    """Bind ``fn(*args, **kwargs)`` to a snapshot of the caller's context.

    ``threading.Thread`` targets do NOT inherit contextvars; pass the
    returned thunk as the thread target and spans opened inside the thread
    nest under the caller's current span."""
    ctx = contextvars.copy_context()
    return lambda: ctx.run(fn, *args, **kwargs)
