"""Rollout flight recorder: an append-only JSONL timeline of every
orchestrator decision.

The rolling orchestrator (ccmanager/rolling.py) makes dozens of
decisions per rollout — plan computed, surge picks, wave/window
open+close, per-node desired-patch/converged/failed/retired/adopted,
budget spend and halt, lease takeover and fence, resume — and before
this module the only durable record was the end-of-run summary. When
wave 3 halts at 02:00 the summary says *that* it halted; the flight
recorder says *why*, in order, with the rollout generation, wave id and
trace id stamped on every event, and it survives the orchestrator dying
mid-window: a successor's ``--resume`` appends to the SAME file, so one
timeline spans the crash.

Write discipline: one JSON object per line, flushed per event. A kill
can tear at most the final line; :func:`read_events` tolerates exactly
that (an unparseable tail line is counted, never fatal) and fails no
reader. Like the span journal, recording is best-effort — observability
must never halt a rollout.

Consumers:

- ``tpu-cc-ctl rollout-timeline`` renders the timeline and the
  exactly-once reconstruction (docs/observability.md);
- ``/rolloutz`` (ccmanager/metrics_server.py) serves the live
  recorder's snapshot during a rollout;
- ``hack/chaos_soak.sh`` asserts zero torn lines after seeded kills
  (the OBS_SUMMARY line).
"""

from __future__ import annotations

import collections
import json
import logging
import os
import tempfile
import time

from tpu_cc_manager.labels import label_safe
from tpu_cc_manager.utils import locks as locks_mod

log = logging.getLogger(__name__)

FLIGHT_DIR_ENV = "CC_FLIGHT_DIR"

#: Event names the recorder emits (ccmanager/rolling.py + ctl.py). Kept
#: here as the schema's single source so the timeline renderer and the
#: docs table cannot drift from the writers.
EVENT_LEASE_ACQUIRED = "lease-acquired"
EVENT_RESUME = "resume"
EVENT_PLAN = "plan"
EVENT_QUARANTINE_SKIP = "quarantine-skip"
EVENT_GROUP_SKIPPED = "group-skipped"
EVENT_SURGE_PICK = "surge-pick"
EVENT_WINDOW_OPEN = "window-open"
EVENT_WINDOW_CLOSE = "window-close"
EVENT_NODE_DESIRED = "node-desired-patch"
EVENT_NODE_CONVERGED = "node-converged"
EVENT_NODE_FAILED = "node-failed"
EVENT_NODE_RETIRED = "node-retired-deleted"
EVENT_NODE_ADOPTED = "node-adopted"
EVENT_BUDGET_CHARGE = "budget-charge"
EVENT_HALT = "halt"
EVENT_FENCED = "fenced"
EVENT_COMPLETE = "complete"
#: SLO pacing (ccmanager/rolling.py slo_gate): the gate paused the next
#: wave at a boundary / the window recovered and the wave resumed / the
#: burn outlasted the pause budget and the rollout halted like the
#: failure budget does. Every pacing decision is journaled.
EVENT_SLO_PAUSED = "slo-paused"
EVENT_SLO_RESUMED = "slo-resumed"
EVENT_SLO_HALT = "slo-halt"
#: Zero-bounce spares (ccmanager/rolling.py prestage): one event per
#: surge spare whose agent reported a completed pre-staged flip (the
#: annotation record's seconds ride along) BEFORE its flip window
#: opened — the timeline's explanation of a surge window that converged
#: in ~drain+readmit time.
EVENT_SPARE_PRESTAGED = "spare-prestaged"
#: Federated rollouts (ccmanager/federation.py): one event per
#: wave-boundary exchange with the parent record — region, the global
#: spend size folded back, and the parent status at that instant. The
#: stitched cross-region timeline uses these to show WHEN each region
#: learned of a sibling's budget charges or a global halt.
EVENT_FEDERATION_SYNC = "federation-sync"
#: Parent-plane partition tolerance (ccmanager/federation.py escrow):
#: journaled once per outage edge. ``parent-offline`` fires when a
#: shard's boundary syncs have hit transport errors past
#: CC_FEDERATION_OFFLINE_GRACE_S and it enters degraded mode (waves now
#: charge strictly against the local escrow); ``parent-reconnect`` fires
#: when the next sync lands and the dark spend reconciles exactly-once
#: into the parent. The stitched timeline uses the pair to bracket how
#: long each region ran autonomously.
EVENT_PARENT_OFFLINE = "parent-offline"
EVENT_PARENT_RECONNECT = "parent-reconnect"
#: Continuous prestage (ccmanager/rolling.py continuous_prestage, record
#: v7): the capacity-ledger lifecycle of one REGULAR node prestaged
#: ahead of its flip window. ``reserved`` journals the headroom charge
#: (durable before the node is touched), ``armed`` the PRESTAGE request
#: landing, ``held`` the agent's completed hidden flip adopted at the
#: window, ``invalidated`` a stale/never-held entry downgraded to the
#: full flip path, ``released`` the charge settling (outcome rides
#: along: converged/degraded/aborted), and ``paused`` a maintenance
#: pass that skipped its top-up on SLO burn — prestage pauses, the
#: wave never does.
EVENT_PRESTAGE_RESERVED = "prestage-reserved"
EVENT_PRESTAGE_ARMED = "prestage-armed"
EVENT_PRESTAGE_HELD = "prestage-held"
EVENT_PRESTAGE_INVALIDATED = "prestage-invalidated"
EVENT_PRESTAGE_RELEASED = "prestage-released"
EVENT_PRESTAGE_PAUSED = "prestage-paused"
#: Fail-slow containment (obs/failslow.py + ccmanager/rolling.py):
#: ``failslow-verdict`` journals one concluded peer-relative verdict
#: (node, verdict, deviation ride along) at the boundary where the
#: orchestrator recorded it — BEFORE acting, behind the
#: ``failslow-vetted`` crash point, so a successor resumes the same
#: verdict instead of re-deriving it. ``straggler-skipped`` fires when
#: an await gives up on nodes converging beyond the peer-relative
#: straggler wall: charged to the failure budget and skipped, instead
#: of stretching every window to node_timeout_s.
EVENT_FAILSLOW_VERDICT = "failslow-verdict"
EVENT_STRAGGLER_SKIPPED = "straggler-skipped"

#: Node-terminal events: the exactly-once reconstruction keys on these
#: (a node converges/fails/retires once per rollout, crash+resume
#: included — the record's done map and the idempotency skip guarantee
#: it; a duplicate here is a real double-bounce).
NODE_TERMINAL_EVENTS = (
    EVENT_NODE_CONVERGED,
    EVENT_NODE_FAILED,
    EVENT_NODE_RETIRED,
)


def flight_dir() -> str:
    """Where rollout flight files live: ``CC_FLIGHT_DIR``, defaulting to
    a stable per-host temp subdirectory (the orchestrator is a CLI, not
    a pod — a crash+``--resume`` on the same host must find the same
    file)."""
    return os.environ.get(FLIGHT_DIR_ENV) or os.path.join(
        tempfile.gettempdir(), "tpu-cc-flight"
    )


def flight_path_for(selector: str) -> str:
    """Deterministic flight-file path for a pool selector, so a resumed
    rollout appends to the interrupted one's timeline without any flag
    plumbing."""
    return os.path.join(
        flight_dir(), f"rollout-{label_safe(selector, max_len=120)}.jsonl"
    )


class FlightRecorder:
    """Append-only JSONL event sink for one rollout timeline.

    ``generation`` and ``trace_id`` are stamped on every event once set
    (the lease generation at construction/adoption, the trace id when
    the rollout root span opens). Thread-safe: wave threads record
    concurrently. Every append is flushed so a SIGKILL tears at most
    the in-progress final line.
    """

    def __init__(
        self,
        path: str,
        generation: int | None = None,
        trace_id: str | None = None,
    ) -> None:
        self.path = path
        self.generation = generation
        self.trace_id = trace_id
        self._lock = locks_mod.make_lock("obs.flight")
        self._seq = 0  # cclint: guarded-by(_lock)
        self.events_written = 0  # cclint: guarded-by(_lock)
        self._failed = False  # cclint: guarded-by(_lock)
        # /rolloutz serves from memory: the recorder wrote (or loaded at
        # init) every event itself, so a scrape never re-reads and
        # re-parses the whole file — O(limit) per poll however long the
        # rollout ran. read_events() stays the cross-process reader
        # (ctl rollout-timeline).
        self._recent: collections.deque[dict] = collections.deque(  # cclint: guarded-by(_lock)
            maxlen=256
        )
        self._loaded = 0  # cclint: guarded-by(_lock)
        self._torn_at_load = 0  # cclint: guarded-by(_lock)
        try:
            os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
            # Continue a predecessor's sequence so one file stays
            # globally ordered across a crash+resume.
            if os.path.exists(path):
                events, torn = read_events(path)
                if events:
                    self._seq = max(e.get("seq", 0) for e in events)
                self._recent.extend(events)
                self._loaded = len(events)
                self._torn_at_load = torn
        except OSError as e:
            log.warning("flight recorder init failed (non-fatal): %s", e)

    def set_trace(self, trace_id: str) -> None:
        self.trace_id = trace_id

    def set_generation(self, generation: int | None) -> None:
        self.generation = generation

    def record(self, event: str, **fields) -> None:
        """Append one event. Best-effort: a full disk degrades the
        recorder (one warning), never the rollout."""
        entry = {
            "event": event,
            "ts": round(time.time(), 3),
            "gen": self.generation,
            "trace_id": self.trace_id,
        }
        entry.update(fields)
        with self._lock:
            self._seq += 1
            entry["seq"] = self._seq
            try:
                line = json.dumps(entry, sort_keys=True, default=str)
                with open(self.path, "a", encoding="utf-8") as f:
                    f.write(line + "\n")
                    f.flush()
                self.events_written += 1
                self._recent.append(json.loads(line))
                self._failed = False
            except (OSError, TypeError, ValueError) as e:
                if not self._failed:
                    log.warning(
                        "flight recorder write failed (non-fatal, "
                        "degrading): %s", e,
                    )
                self._failed = True

    def snapshot(self, limit: int = 64) -> dict:
        """The live payload ``/rolloutz`` serves — from memory, so a
        poller scraping every few seconds costs O(limit), not a re-read
        of the whole (growing) file."""
        with self._lock:
            written = self.events_written
            seq = self._seq
            loaded = self._loaded
            torn = self._torn_at_load
            recent = list(self._recent)
        return {
            "enabled": True,
            "path": self.path,
            "generation": self.generation,
            "trace_id": self.trace_id,
            "events_written": written,
            "last_seq": seq,
            "events_in_file": loaded + written,
            "torn_lines": torn,
            "recent": recent[-max(0, limit):],
        }


def read_events(path: str) -> tuple[list[dict], int]:
    """Every parseable event in ``path`` (file order) plus the count of
    torn/garbled lines skipped. A missing file is an empty timeline, not
    an error — the readers (ctl, /rolloutz) run before, during and after
    rollouts alike."""
    events: list[dict] = []
    torn = 0
    try:
        with open(path, encoding="utf-8") as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    obj = json.loads(line)
                except ValueError:
                    torn += 1
                    continue
                if isinstance(obj, dict) and obj.get("event"):
                    events.append(obj)
                else:
                    torn += 1
    except OSError:
        return [], 0
    return events, torn


def _order_key(value) -> tuple:
    """Type-stable sort key for wave/window ids: numeric ids first in
    numeric order, then string ids ("surge", "adopt") alphabetically,
    then absent — int and str never compare directly."""
    if isinstance(value, (int, float)) and not isinstance(value, bool):
        return (0, value, "")
    if value is None:
        return (2, 0, "")
    return (1, 0, str(value))


def reconstruct(events: list[dict]) -> dict:
    """Collapse a (possibly crash-spanning) event stream into the
    exactly-once view an operator asks for: one outcome per node, one
    row per wave/window, the halts and resumes in order.

    The raw stream is kept honest by the writers (a resumed rollout
    skips done groups on the record's say-so, so terminal node events
    genuinely happen once); this function VERIFIES that — any node with
    two terminal events is surfaced in ``duplicates`` instead of being
    silently merged."""
    nodes: dict[str, dict] = {}
    duplicates: list[dict] = []
    windows: dict[tuple, dict] = {}
    halts: list[dict] = []
    slo_pauses = 0
    resumes: list[dict] = []
    generations: list[int] = []
    plan: dict | None = None
    adopted: list[str] = []
    surged: list[str] = []
    prestaged: list[str] = []
    prestage: dict = {
        "reserved": [], "armed": [], "held": [], "invalidated": [],
        "released": {}, "paused": 0,
    }
    for e in events:
        ev = e.get("event")
        gen = e.get("gen")
        if gen is not None and gen not in generations:
            generations.append(gen)
        if ev == EVENT_PLAN and plan is None:
            plan = e
        elif ev == EVENT_RESUME:
            resumes.append(e)
        elif ev in (EVENT_HALT, EVENT_SLO_HALT):
            halts.append(e)
        elif ev == EVENT_SLO_PAUSED:
            slo_pauses += 1
        elif ev == EVENT_SURGE_PICK:
            surged.extend(e.get("nodes") or [])
        elif ev == EVENT_SPARE_PRESTAGED:
            prestaged.append(e.get("node"))
        elif ev == EVENT_PRESTAGE_RESERVED:
            prestage["reserved"].append(e.get("node"))
        elif ev == EVENT_PRESTAGE_ARMED:
            prestage["armed"].append(e.get("node"))
        elif ev == EVENT_PRESTAGE_HELD:
            prestage["held"].append(e.get("node"))
        elif ev == EVENT_PRESTAGE_INVALIDATED:
            prestage["invalidated"].append(e.get("node"))
        elif ev == EVENT_PRESTAGE_RELEASED:
            outcome = e.get("outcome") or "released"
            prestage["released"][outcome] = (
                prestage["released"].get(outcome, 0) + 1
            )
        elif ev == EVENT_PRESTAGE_PAUSED:
            prestage["paused"] += 1
        elif ev == EVENT_NODE_ADOPTED:
            adopted.append(e.get("node"))
        elif ev in (EVENT_WINDOW_OPEN, EVENT_WINDOW_CLOSE):
            key = (e.get("wave"), e.get("window"))
            w = windows.setdefault(
                key, {"wave": e.get("wave"), "window": e.get("window")}
            )
            if ev == EVENT_WINDOW_OPEN:
                w["opened_ts"] = e.get("ts")
                w["groups"] = e.get("groups")
            else:
                w["closed_ts"] = e.get("ts")
                w["seconds"] = e.get("seconds")
                w["failed"] = e.get("failed")
        elif ev in NODE_TERMINAL_EVENTS:
            name = e.get("node")
            entry = {
                "outcome": ev,
                "state": e.get("state"),
                "wave": e.get("wave"),
                "gen": gen,
                "ts": e.get("ts"),
                "skipped": bool(e.get("skipped")),
            }
            prev = nodes.get(name)
            if prev is None:
                nodes[name] = entry
            elif entry["skipped"] or prev["skipped"]:
                # A crash between the terminal event and its checkpoint
                # makes the successor re-verify the group; its skipped
                # terminal MERGES with the real one (prefer the real
                # drive) — that is a re-observation, not a re-bounce.
                if prev["skipped"] and not entry["skipped"]:
                    nodes[name] = entry
            elif prev["outcome"] != EVENT_NODE_CONVERGED:
                # A re-drive of a FAILED (or retired-then-reappeared)
                # node is the DESIGNED resume path — the operator re-ran
                # the rollout on purpose and rolling.py re-drives
                # not-done groups. The later outcome supersedes;
                # `redriven` keeps the history visible.
                entry["redriven"] = True
                nodes[name] = entry
            else:
                # Two REAL drives of a CONVERGED node: the double bounce
                # the exactly-once guarantee forbids. Surface, never
                # merge.
                duplicates.append(e)
    return {
        "plan": {
            "mode": (plan or {}).get("mode"),
            "groups": (plan or {}).get("groups"),
            "nodes": (plan or {}).get("nodes"),
        } if plan else None,
        "generations": generations,
        "resumes": len(resumes),
        # Wave ids mix ints (shards) and strings ("surge"/"adopt"), so
        # the sort key must never compare across types: rank by kind
        # first, then within it.
        "windows": [windows[k] for k in sorted(
            windows, key=lambda k: (_order_key(k[0]), _order_key(k[1]))
        )],
        "nodes": nodes,
        "adopted": sorted(n for n in adopted if n),
        "surged": sorted(set(surged)),
        "prestaged": sorted({n for n in prestaged if n}),
        # Continuous-prestage ledger accounting, crash-spanning: a
        # resumed rollout's adoption re-journals nothing, so reserved −
        # (invalidated + released) should read the live in-transition
        # count and a COMPLETE timeline balances to zero.
        "prestage": prestage if (
            prestage["reserved"] or prestage["paused"]
            or prestage["released"]
        ) else None,
        "halts": halts,
        "slo_pauses": slo_pauses,
        "duplicate_node_events": duplicates,
    }


def _stitch_identity(event: dict) -> str:
    """Content identity of an event, independent of which stream carried
    it (the ``stream`` tag a stitch adds is excluded). Two streams can
    legitimately carry the SAME event — e.g. a gateway scraping a node's
    /rolloutz and a shard file on disk — and a stitch must not double it."""
    return json.dumps(
        {k: v for k, v in event.items() if k != "stream"},
        sort_keys=True, default=str,
    )


def stitch_timelines(
    streams: list[list[dict]], labels: list[str] | None = None
) -> list[dict]:
    """Merge N shard/region flight-recorder streams into ONE federated
    timeline, seq-consistent across the fleet.

    Within a stream events are already totally ordered by ``seq``
    (continued across crash+resume by the recorder). Across streams
    there is no shared sequence, so the stitch orders by what IS shared:
    the lease generation first (a gen-N event globally precedes gen-N+1
    — the fence guarantees no overlap), then the wall-clock ``ts``
    within a generation, with (stream, seq) as the deterministic
    tiebreak. ``gen`` uses the type-stable :func:`_order_key` so a
    pre-lease ``None`` generation sorts after numbered ones it trails
    in no stream.

    Each stitched event carries a ``stream`` tag (the label or index of
    its source) so the federated timeline stays attributable; exact
    duplicates appearing in multiple streams collapse to one event.
    Torn tails were already dropped per stream by :func:`read_events` —
    this function only ever sees parseable events, however ragged the
    shard files' endings.
    """
    tagged: list[tuple[tuple, dict]] = []
    seen: set[str] = set()
    for idx, stream in enumerate(streams):
        label = labels[idx] if labels and idx < len(labels) else str(idx)
        for event in stream:
            identity = _stitch_identity(event)
            if identity in seen:
                continue
            seen.add(identity)
            merged = dict(event)
            merged["stream"] = label
            tagged.append((
                (
                    _order_key(event.get("gen")),
                    event.get("ts") or 0,
                    idx,
                    event.get("seq") or 0,
                ),
                merged,
            ))
    tagged.sort(key=lambda pair: pair[0])
    return [event for _, event in tagged]


def stitch_files(paths: list[str]) -> tuple[list[dict], int]:
    """Stitch N flight files (``ctl rollout-timeline --stitch``): the
    federated timeline plus the total torn-line count across shards."""
    streams: list[list[dict]] = []
    labels: list[str] = []
    torn_total = 0
    for path in paths:
        events, torn = read_events(path)
        streams.append(events)
        labels.append(os.path.basename(path))
        torn_total += torn
    return stitch_timelines(streams, labels=labels), torn_total


def render_timeline(events: list[dict], torn: int = 0) -> str:
    """Human timeline for ``tpu-cc-ctl rollout-timeline``: one line per
    event in file order, then the reconstruction summary."""
    lines: list[str] = []
    for e in events:
        ts = time.strftime("%H:%M:%S", time.localtime(e.get("ts") or 0))
        wave = e.get("wave")
        where = f" wave={wave}" if wave is not None else ""
        window = e.get("window")
        if window is not None:
            where += f" window={window}"
        detail = {
            k: v for k, v in e.items()
            if k not in (
                "event", "ts", "seq", "gen", "trace_id", "wave", "window",
            ) and v is not None
        }
        lines.append(
            f"{ts} gen={e.get('gen')}{where} {e.get('event'):<22} "
            + (json.dumps(detail, sort_keys=True) if detail else "")
        )
    rec = reconstruct(events)
    lines.append("")
    plan = rec["plan"] or {}
    lines.append(
        f"reconstruction: mode={plan.get('mode')} "
        f"groups={plan.get('groups')} nodes={plan.get('nodes')} "
        f"generations={rec['generations']} resumes={rec['resumes']}"
    )
    for w in rec["windows"]:
        lines.append(
            f"  wave {w.get('wave')} window {w.get('window')}: "
            f"groups={w.get('groups')} seconds={w.get('seconds')} "
            f"failed={w.get('failed') or '-'}"
        )
    for name in sorted(rec["nodes"]):
        n = rec["nodes"][name]
        lines.append(
            f"  node {name}: {n['outcome']} (state={n.get('state')}, "
            f"gen={n.get('gen')})"
        )
    for h in rec["halts"]:
        lines.append(f"  HALT: {h.get('reason')} (gen={h.get('gen')})")
    if rec["duplicate_node_events"]:
        lines.append(
            f"  WARNING: {len(rec['duplicate_node_events'])} duplicate "
            "node event(s) — a node was driven twice"
        )
    if torn:
        lines.append(f"  WARNING: {torn} torn/garbled line(s) skipped")
    return "\n".join(lines)
