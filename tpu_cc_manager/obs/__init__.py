"""Observability: reconcile tracing (spans + journal).

``obs.trace`` mints trace/span ids and nests spans through a contextvar;
``obs.journal`` records finished spans to a bounded ring and an optional
JSONL file (``CC_TRACE_FILE``). The metrics endpoint layer
(ccmanager/metrics_server.py) serves both at ``/tracez`` and ``/statusz``.
"""

from tpu_cc_manager.obs.journal import JOURNAL, Journal
from tpu_cc_manager.obs.trace import (
    Span,
    current_span,
    current_span_id,
    current_trace_id,
    in_current_context,
    root_span,
    span,
)

__all__ = [
    "JOURNAL",
    "Journal",
    "Span",
    "current_span",
    "current_span_id",
    "current_trace_id",
    "in_current_context",
    "root_span",
    "span",
]
