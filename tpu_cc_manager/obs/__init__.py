"""Observability: reconcile tracing (spans + journal), the rollout
flight recorder, and serving-SLO evaluation.

``obs.trace`` mints trace/span ids and nests spans through a contextvar
(with cross-process parents for orchestrator→agent stitching);
``obs.journal`` records finished spans to a bounded ring and an optional
JSONL file (``CC_TRACE_FILE``); ``obs.flight`` journals every rolling-
orchestrator decision to an append-only JSONL timeline (``tpu-cc-ctl
rollout-timeline`` / ``/rolloutz``); ``obs.slo`` computes rolling-window
p99 and error-budget burn for the serving layer. The metrics endpoint
layer (ccmanager/metrics_server.py) serves traces at ``/tracez`` and
``/statusz`` and the flight recorder at ``/rolloutz``.
"""

from tpu_cc_manager.obs.flight import FlightRecorder
from tpu_cc_manager.obs.journal import JOURNAL, Journal
from tpu_cc_manager.obs.slo import SloEvaluator
from tpu_cc_manager.obs.trace import (
    Span,
    current_span,
    current_span_id,
    current_trace_id,
    in_current_context,
    root_span,
    span,
)

__all__ = [
    "JOURNAL",
    "FlightRecorder",
    "Journal",
    "SloEvaluator",
    "Span",
    "current_span",
    "current_span_id",
    "current_trace_id",
    "in_current_context",
    "root_span",
    "span",
]
