"""Fleet observability plane: metrics federation + the capacity ledger.

PR 11 gave every node agent a flight recorder, SLO telemetry and
``/metrics``/``/statusz``/``/rolloutz`` endpoints — per node. At 10k+
nodes nobody scrapes 10k endpoints by hand: this module is the layer
above, the standard Prometheus-federation / hierarchical-collection
pattern applied to the ``tpu_cc_*`` families:

- :class:`FleetGateway` scrapes every agent (informer-discovered or
  injected targets, bounded worker pool, per-node scrape deadline on
  the shared :class:`~tpu_cc_manager.utils.retry.RetryPolicy`), marks
  nodes **stale** instead of silently omitting them, and serves the
  merged truth at fleet ``/metrics`` + ``/fleetz``;
- :func:`merge_expositions` is the merge engine: histogram families
  merge bucket-wise with exact ``_sum``/``_count`` conservation (the
  fixed bucket sets in utils/metrics.py guarantee mergeable bounds),
  counters and gauges sum label-preserving (``sum by`` over the full
  label set), HELP/TYPE pairing survives federation (the exposition
  lint runs over the MERGED text too — lint/expo.py);
- the fleet p99 is computed through ``obs/slo.py`` :func:`~tpu_cc_manager.obs.slo.merge_p99`
  over per-node latency shards reconstructed from the serve histogram;
- the **capacity ledger**: per-node headroom judged from
  ``hbm_bw_util``, serve queue depth, prestage-in-progress and
  quarantine/offline state, rolled into ``tpu_cc_fleet_headroom_nodes``
  — the signal ROADMAP item 2's prestage pacer and item 4's per-class
  admission gate consume.

Staleness contract (the fleet-scale bug this kills): a dead agent's
cached exposition must not be merged as live forever. Each sweep a
node either scrapes fresh (and its ``/statusz`` ``snapshot_ts`` must
ADVANCE — a frozen timestamp means a proxy replayed a stale body), or
its age grows; at ``stale_after_sweeps`` (default 2) the node leaves
the rollups but stays LISTED in ``/fleetz`` with its error — absence
of evidence is surfaced, never silent.

Server form: ``hack/obs_gateway.py`` (CLI, informer-discovered
targets). In-process form: construct with :func:`local_target`
fetchers — what tests, ``scale_bench.py`` and ``serve_bench.py`` do.
"""

from __future__ import annotations

import heapq
import http.server
import json
import logging
import threading
import time
import urllib.request
from urllib.parse import parse_qs, urlparse

from tpu_cc_manager.obs import flight as flight_mod
from tpu_cc_manager.obs import slo as slo_mod
from tpu_cc_manager.utils import locks as locks_mod
from tpu_cc_manager.utils import retry as retry_mod

log = logging.getLogger(__name__)

#: The per-node serve-latency histogram the fleet p99 is pooled from.
SERVE_HIST_FAMILY = "tpu_cc_serve_request_seconds"

#: Families the capacity ledger reads per node (utils/metrics.py).
HBM_FAMILY = "tpu_cc_hbm_bw_util"
QUEUE_FAMILY = "tpu_cc_serve_queue_depth"
PRESTAGE_FAMILY = "tpu_cc_prestage_in_progress"
QUARANTINE_FAMILY = "tpu_cc_quarantined"
CONNECTED_FAMILY = "tpu_cc_apiserver_connected"

_HIST_SUFFIXES = ("_bucket", "_sum", "_count")


# ---------------------------------------------------------------------------
# Exposition parsing / rendering (text format, the subset the agents emit)
# ---------------------------------------------------------------------------


def _unescape_label_value(raw: str) -> str:
    out: list[str] = []
    i = 0
    while i < len(raw):
        c = raw[i]
        if c == "\\" and i + 1 < len(raw):
            out.append({"\\": "\\", '"': '"', "n": "\n"}.get(
                raw[i + 1], raw[i + 1]
            ))
            i += 2
        else:
            out.append(c)
            i += 1
    return "".join(out)


def _escape_label_value(value: str) -> str:
    return (
        value.replace("\\", "\\\\").replace('"', '\\"').replace("\n", "\\n")
    )


def _parse_label_body(raw: str) -> tuple[tuple[str, str], ...] | None:
    """``k="v",...`` -> ordered (k, v) pairs; None when malformed."""
    pairs: list[tuple[str, str]] = []
    i, n = 0, len(raw)
    while i < n:
        eq = raw.find("=", i)
        if eq < 0 or eq + 1 >= n or raw[eq + 1] != '"':
            return None
        name = raw[i:eq]
        j = eq + 2
        chars: list[str] = []
        while j < n:
            c = raw[j]
            if c == "\\":
                if j + 1 >= n:
                    return None
                chars.append(raw[j:j + 2])
                j += 2
            elif c == '"':
                break
            else:
                chars.append(c)
                j += 1
        else:
            return None
        pairs.append((name, _unescape_label_value("".join(chars))))
        i = j + 1
        if i < n:
            if raw[i] != ",":
                return None
            i += 1
    return tuple(pairs)


class ParsedExposition:
    """One scrape, parsed: family HELP/TYPE in declaration order plus
    every sample as ``(series, ordered-labels, value)``."""

    def __init__(self) -> None:
        self.helps: dict[str, str] = {}
        self.types: dict[str, str] = {}
        self.family_order: list[str] = []
        # (series name, ordered (k, v) pairs, float value) in file order.
        self.samples: list[tuple[str, tuple[tuple[str, str], ...], float]] = []
        self.unparseable = 0

    def family_of(self, series: str) -> str:
        for suffix in _HIST_SUFFIXES:
            if series.endswith(suffix):
                base = series[: -len(suffix)]
                if self.types.get(base) in ("histogram", "summary"):
                    return base
        return series

    def series_values(self, family: str) -> list[tuple[dict, float]]:
        """Samples of one (non-histogram) family as (labels, value)."""
        return [
            (dict(labels), value)
            for series, labels, value in self.samples
            if series == family
        ]


def parse_exposition(text: str) -> ParsedExposition:
    """Parse a Prometheus text exposition (the agents' own renders are
    always well-formed; garbled lines are counted, never fatal — the
    gateway must keep serving the rest of a partially-broken scrape)."""
    parsed = ParsedExposition()
    for line in text.splitlines():
        line = line.strip()
        if not line:
            continue
        if line.startswith("#"):
            parts = line.split(None, 3)
            if len(parts) >= 3 and parts[1] in ("HELP", "TYPE"):
                name = parts[2]
                if name not in parsed.helps and name not in parsed.types:
                    parsed.family_order.append(name)
                if parts[1] == "HELP":
                    parsed.helps.setdefault(
                        name, parts[3] if len(parts) > 3 else ""
                    )
                else:
                    parsed.types.setdefault(
                        name, parts[3].strip() if len(parts) > 3 else ""
                    )
            continue
        brace = line.find("{")
        if brace >= 0:
            close = line.rfind("}")
            if close < brace:
                parsed.unparseable += 1
                continue
            name = line[:brace]
            labels = _parse_label_body(line[brace + 1:close])
            rest = line[close + 1:].split()
        else:
            fields = line.split()
            if len(fields) < 2:
                parsed.unparseable += 1
                continue
            name = fields[0]
            labels = ()
            rest = fields[1:]
        if labels is None or not rest:
            parsed.unparseable += 1
            continue
        try:
            value = float(rest[0].replace("Inf", "inf"))
        except ValueError:
            parsed.unparseable += 1
            continue
        parsed.samples.append((name, labels, value))
    return parsed


def _format_value(value: float) -> str:
    if value == int(value) and abs(value) < 1e15:
        return "%d" % int(value)
    return "%.6f" % value


def _render_sample(
    series: str, labels: tuple[tuple[str, str], ...], value: float
) -> str:
    if not labels:
        return f"{series} {_format_value(value)}"
    body = ",".join(
        f'{k}="{_escape_label_value(v)}"' for k, v in labels
    )
    return f"{series}{{{body}}} {_format_value(value)}"


def merge_expositions(scrapes: dict[str, str]) -> str:
    """Merge N agents' expositions into one fleet exposition.

    Counters and gauges **sum by their full label set** (label-
    preserving: per-node families carry a ``node`` label and stay per
    node; unlabeled control-plane families sum across agents, so e.g.
    the merged ``tpu_cc_quarantined`` counts quarantined agents).
    Histogram series merge the same way — identical fixed bucket bounds
    (utils/metrics.py) make bucket-wise summation exact, so bucket
    cumulativeness and ``_sum``/``_count`` conservation hold by
    construction. HELP/TYPE come from the first scrape declaring the
    family and are emitted ONCE, before the family's first sample, so
    the pairing the exposition lint enforces survives federation.
    """
    family_order: list[str] = []
    helps: dict[str, str] = {}
    types: dict[str, str] = {}
    # (series, sorted-label-key) -> [ordered labels, summed value]
    merged: dict[tuple, list] = {}
    per_family_series: dict[str, list[tuple]] = {}

    for _node in sorted(scrapes):
        parsed = parse_exposition(scrapes[_node])
        for fam in parsed.family_order:
            if fam not in helps and fam not in types:
                family_order.append(fam)
            if fam in parsed.helps:
                helps.setdefault(fam, parsed.helps[fam])
            if fam in parsed.types:
                types.setdefault(fam, parsed.types[fam])
        for series, labels, value in parsed.samples:
            key = (series, tuple(sorted(labels)))
            entry = merged.get(key)
            if entry is None:
                merged[key] = [labels, value]
                fam = parsed.family_of(series)
                per_family_series.setdefault(fam, []).append(key)
            else:
                entry[1] += value

    lines: list[str] = []
    for fam in family_order:
        series_keys = per_family_series.pop(fam, [])
        if fam in helps:
            lines.append(f"# HELP {fam} {helps[fam]}")
        if fam in types:
            lines.append(f"# TYPE {fam} {types[fam]}")
        for key in series_keys:
            labels, value = merged[key]
            lines.append(_render_sample(key[0], labels, value))
    # Families sampled without any HELP/TYPE declaration (shouldn't
    # happen with our agents, but a federation layer must not drop data).
    for fam, series_keys in per_family_series.items():
        for key in series_keys:
            labels, value = merged[key]
            lines.append(_render_sample(key[0], labels, value))
    return "\n".join(lines) + "\n" if lines else ""


# ---------------------------------------------------------------------------
# Fleet p99 through obs/slo.merge_p99
# ---------------------------------------------------------------------------


def histogram_shard(
    parsed: ParsedExposition, family: str = SERVE_HIST_FAMILY
) -> list[float]:
    """One node's latency samples reconstructed from its histogram
    buckets (each observation represented by its bucket's upper bound;
    the +Inf overflow by the top finite bound — the standard pooled-
    histogram approximation). Ascending, ready for merge_p99."""
    series = family + "_bucket"
    by_set: dict[tuple, list[tuple[float, float]]] = {}
    for name, labels, value in parsed.samples:
        if name != series:
            continue
        lab = dict(labels)
        le_raw = lab.pop("le", None)
        if le_raw is None:
            continue
        try:
            le = float("inf") if le_raw == "+Inf" else float(le_raw)
        except ValueError:
            continue
        by_set.setdefault(tuple(sorted(lab.items())), []).append((le, value))
    out: list[float] = []
    for buckets in by_set.values():
        buckets.sort()
        prev = 0.0
        top_finite = max(
            (le for le, _ in buckets if le != float("inf")), default=None
        )
        for le, cumulative in buckets:
            delta = int(max(0.0, cumulative - prev))
            prev = cumulative
            if delta <= 0:
                continue
            rep = le if le != float("inf") else top_finite
            if rep is None:
                continue
            out.extend([rep] * delta)
    out.sort()
    return out


def fleet_p99(shards: list[list[float]]) -> float | None:
    """p99 of the pooled per-node latency shards, via obs/slo.py
    ``merge_p99``: the first N-1 ascending shards are linearly merged
    into one union, then merge_p99 folds in the last — so the fleet
    number and the single-node number share ONE percentile
    implementation (nearest-rank, tests/test_slo.py)."""
    nonempty = [s for s in shards if s]
    if not nonempty:
        return None
    if len(nonempty) == 1:
        return slo_mod.percentile(nonempty[0], 0.99)
    union = list(heapq.merge(*nonempty[:-1]))
    return slo_mod.merge_p99(union, nonempty[-1])


# ---------------------------------------------------------------------------
# Scrape targets
# ---------------------------------------------------------------------------


def http_target(base_url: str, timeout_s: float = 2.0):
    """Fetcher for a real agent endpoint: ``fetch(path) -> text``."""
    base = base_url.rstrip("/")

    def fetch(path: str) -> str:
        with urllib.request.urlopen(base + path, timeout=timeout_s) as resp:
            return resp.read().decode()

    return fetch


def local_target(
    registry,
    flight=None,
    version: str | None = None,
    clock=time.monotonic,
):
    """In-process twin of an agent's debug endpoints — what tests and
    the benches hand the gateway instead of URLs. Serves the same three
    paths from live objects: ``/metrics`` renders the registry,
    ``/statusz`` carries the monotonic ``snapshot_ts`` + agent version
    the staleness check reads, ``/rolloutz`` snapshots the flight
    recorder."""
    if version is None:
        from tpu_cc_manager.version import __version__ as version

    def fetch(path: str) -> str:
        if path in ("", "/metrics"):
            return registry.render_prometheus()
        if path == "/statusz":
            return json.dumps({
                "agent_version": version,
                "snapshot_ts": round(clock(), 6),
            })
        if path == "/rolloutz":
            payload = (
                flight.snapshot() if flight is not None
                else {"enabled": False}
            )
            return json.dumps(payload)
        raise ValueError(f"local target: unknown path {path!r}")

    return fetch


def targets_from_nodes(nodes: list[dict], port: int) -> dict[str, str]:
    """Informer-discovered scrape endpoints: node name -> base URL,
    address preference InternalIP > ExternalIP > Hostname > name (the
    same resolution ``ctl node-debug`` uses)."""
    out: dict[str, str] = {}
    for node in nodes:
        name = (node.get("metadata") or {}).get("name")
        if not name:
            continue
        addresses = (node.get("status") or {}).get("addresses") or []
        by_type = {
            a.get("type"): a.get("address")
            for a in addresses if a.get("address")
        }
        addr = (
            by_type.get("InternalIP")
            or by_type.get("ExternalIP")
            or by_type.get("Hostname")
            or name
        )
        out[name] = f"http://{addr}:{port}"
    return out


# ---------------------------------------------------------------------------
# The gateway
# ---------------------------------------------------------------------------


def _classify_scrape(exc: BaseException) -> retry_mod.Classification:
    # Every scrape failure is transient from the fleet's seat — the
    # per-node deadline (policy.deadline_s) bounds how long one slow or
    # dead agent can hold a worker; staleness handles persistence.
    return retry_mod.Classification(True, type(exc).__name__.lower())


class FleetGateway:
    """Scrape-merge-serve loop over a fleet of agent endpoints.

    ``targets`` maps node name -> base URL (scraped over HTTP) or a
    ``fetch(path) -> text`` callable (in-process). Thread-safe;
    :meth:`scrape_once` is one full sweep (bounded worker pool,
    per-node deadline), :meth:`serve` exposes the merged results, and
    :meth:`run` loops sweeps until ``stop`` is set.
    """

    def __init__(
        self,
        targets: dict | None = None,
        interval_s: float = 5.0,
        scrape_deadline_s: float = 2.0,
        stale_after_sweeps: int = 2,
        workers: int = 8,
        hbm_ceiling: float = 0.9,
        max_queue_depth: int = 16,
        slow_scrape_s: float | None = None,
        clock=time.monotonic,
    ) -> None:
        self.interval_s = float(interval_s)
        self.scrape_deadline_s = float(scrape_deadline_s)
        # A scrape that SUCCEEDS but takes this long is a gray signal:
        # the agent is alive (not dead, not stale) yet something on the
        # node is dragging — surfaced as scrape_slow in /fleetz and
        # excluded from the headroom ledger, but NOT from the rollups
        # (its telemetry is real; a fail-slow vetter needs it).
        self.slow_scrape_s = (
            float(slow_scrape_s)
            if slow_scrape_s is not None
            else self.scrape_deadline_s / 2.0
        )
        self.stale_after_sweeps = max(1, int(stale_after_sweeps))
        self.workers = max(1, int(workers))
        self.hbm_ceiling = float(hbm_ceiling)
        self.max_queue_depth = int(max_queue_depth)
        self.clock = clock
        self._lock = locks_mod.make_lock("obs.fleet")
        self._targets: dict[str, object] = {}  # cclint: guarded-by(_lock)
        self._scrapes: dict[str, dict] = {}  # cclint: guarded-by(_lock)
        self._sweep = 0  # cclint: guarded-by(_lock)
        self._scrape_errors_total = 0  # cclint: guarded-by(_lock)
        self._last_sweep_seconds: float | None = None  # cclint: guarded-by(_lock)
        self._merged_text = ""  # cclint: guarded-by(_lock)
        self._ledger: dict[str, dict] = {}  # cclint: guarded-by(_lock)
        if targets:
            self.set_targets(targets)

    # -- target management (informer refresh path) -------------------------

    def _normalize(self, target):
        if callable(target):
            return target
        return http_target(str(target), timeout_s=self.scrape_deadline_s)

    def set_targets(self, targets: dict) -> None:
        """Replace the target set (the informer-refresh path: nodes that
        left the pool drop out of the ledger with their scrapes)."""
        normalized = {
            name: self._normalize(t) for name, t in targets.items()
        }
        with self._lock:
            self._targets = normalized
            for gone in set(self._scrapes) - set(normalized):
                del self._scrapes[gone]

    def add_target(self, name: str, target) -> None:
        with self._lock:
            self._targets[name] = self._normalize(target)

    def remove_target(self, name: str) -> None:
        with self._lock:
            self._targets.pop(name, None)
            self._scrapes.pop(name, None)

    def target_names(self) -> list[str]:
        with self._lock:
            return sorted(self._targets)

    # -- one sweep ---------------------------------------------------------

    def _scrape_node(self, name: str, fetch, prev: dict | None) -> dict:
        policy = retry_mod.RetryPolicy(
            max_attempts=2,
            base_delay_s=0.05,
            max_delay_s=0.25,
            deadline_s=self.scrape_deadline_s,
            clock=self.clock if callable(self.clock) else time.monotonic,
        )

        def fetch_all() -> dict:
            metrics_text = fetch("/metrics")
            try:
                statusz = json.loads(fetch("/statusz"))
            except (ValueError, TypeError):
                statusz = {}
            try:
                rolloutz = json.loads(fetch("/rolloutz"))
            except (ValueError, TypeError):
                rolloutz = {}
            return {
                "metrics_text": metrics_text,
                "statusz": statusz if isinstance(statusz, dict) else {},
                "rolloutz": rolloutz if isinstance(rolloutz, dict) else {},
            }

        clock = self.clock if callable(self.clock) else time.monotonic
        t0 = clock()
        try:
            got = policy.call(
                fetch_all, op=f"fleet.scrape.{name}",
                classify=_classify_scrape,
            )
        except Exception as e:  # noqa: BLE001 - a dead agent is data, not a crash
            return {"ok": False, "error": f"{type(e).__name__}: {e}"}
        scrape_seconds = clock() - t0
        snapshot_ts = got["statusz"].get("snapshot_ts")
        prev_ts = prev.get("snapshot_ts") if prev else None
        if (
            snapshot_ts is not None
            and prev_ts is not None
            and snapshot_ts == prev_ts
        ):
            # The scrape "succeeded" but time did not advance on the
            # agent: a cached/replayed body. Dead node wearing a live
            # exposition — exactly the staleness bug /statusz's
            # monotonic snapshot_ts exists to catch. Compared against
            # the last KNOWN timestamp (not just the last OK sweep),
            # else a frozen agent flip-flops ok/fail and never ages
            # out. A DECREASED timestamp is an agent restart
            # (monotonic clock reset) and is accepted as fresh.
            return {
                "ok": False,
                "error": "snapshot-ts-not-advancing",
                "snapshot_ts": snapshot_ts,
            }
        return {
            "ok": True,
            "error": None,
            "metrics_text": got["metrics_text"],
            "snapshot_ts": snapshot_ts,
            "agent_version": got["statusz"].get("agent_version"),
            "rollout_recent": got["rolloutz"].get("recent") or [],
            "rollout_torn": got["rolloutz"].get("torn_lines") or 0,
            # Slow-but-successful is a DISTINCT verdict from dead: the
            # agent answered (telemetry stays in the rollups) but took
            # long enough that the node itself is suspect.
            "scrape_seconds": round(scrape_seconds, 4),
            "slow": scrape_seconds >= self.slow_scrape_s,
        }

    def scrape_once(self) -> dict:
        """One full-fleet sweep: scrape every target through the worker
        pool, refresh staleness, rebuild the merged exposition and the
        capacity ledger. Returns the ``/fleetz`` payload."""
        t0 = time.monotonic()
        with self._lock:
            targets = dict(self._targets)
            prevs = {
                name: dict(scrape)
                for name, scrape in self._scrapes.items()
            }
            sweep = self._sweep + 1
        results: dict[str, dict] = {}
        results_lock = threading.Lock()
        work = list(targets.items())
        cursor = [0]

        def worker() -> None:
            while True:
                with results_lock:
                    if cursor[0] >= len(work):
                        return
                    name, fetch = work[cursor[0]]
                    cursor[0] += 1
                row = self._scrape_node(name, fetch, prevs.get(name))
                with results_lock:
                    results[name] = row

        threads = [
            threading.Thread(target=worker, daemon=True, name=f"fleet-{i}")
            for i in range(min(self.workers, max(1, len(work))))
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join()

        with self._lock:
            self._sweep = sweep
            for name, row in results.items():
                prev = self._scrapes.get(name) or {}
                if row["ok"]:
                    row["last_ok_sweep"] = sweep
                    self._scrapes[name] = row
                else:
                    self._scrape_errors_total += 1
                    kept = dict(prev)
                    kept["ok"] = False
                    kept["error"] = row["error"]
                    kept.setdefault("last_ok_sweep", 0)
                    self._scrapes[name] = kept
            self._rebuild_locked()
            self._last_sweep_seconds = round(time.monotonic() - t0, 4)
        return self.fleetz()

    # -- merge + ledger (under lock) ---------------------------------------

    def _stale_locked(self, scrape: dict) -> bool:  # cclint: requires(_lock)
        age = self._sweep - scrape.get("last_ok_sweep", 0)
        return age >= self.stale_after_sweeps

    def _rebuild_locked(self) -> None:  # cclint: requires(_lock)
        live: dict[str, str] = {}
        ledger: dict[str, dict] = {}
        shards: list[list[float]] = []
        for name, scrape in self._scrapes.items():
            stale = self._stale_locked(scrape)
            text = scrape.get("metrics_text")
            entry: dict = {
                "stale": stale,
                "error": scrape.get("error"),
                "agent_version": scrape.get("agent_version"),
                "snapshot_ts": scrape.get("snapshot_ts"),
                "age_sweeps": self._sweep - scrape.get("last_ok_sweep", 0),
                "scrape_slow": bool(scrape.get("slow")),
                "scrape_seconds": scrape.get("scrape_seconds"),
            }
            if text is not None and not stale:
                live[name] = text
                parsed = parse_exposition(text)
                entry.update(self._headroom(parsed))
                shards.append(histogram_shard(parsed))
                burns = slo_mod.parse_serve_slo_text(text)
                if burns:
                    fastest = burns[min(burns)]
                    entry["slo_burn"] = fastest.get("burn_rate")
                    entry["slo_p99_s"] = fastest.get("p99_s")
                if entry["scrape_slow"]:
                    # Slow != dead: the telemetry stays in the rollups
                    # (the fail-slow vetter needs the suspect's own
                    # samples), but its capacity is phantom — the
                    # prestage pacer must not spend it.
                    entry["has_headroom"] = False
            else:
                entry["has_headroom"] = False
            ledger[name] = entry
        merged = merge_expositions(live)
        p99 = fleet_p99(shards)
        n_stale = sum(1 for e in ledger.values() if e["stale"])
        n_slow = sum(
            1 for e in ledger.values()
            if e.get("scrape_slow") and not e["stale"]
        )
        n_headroom = sum(
            1 for e in ledger.values() if e.get("has_headroom")
        )
        lines = [merged.rstrip("\n")] if merged else []
        lines += [
            "# HELP tpu_cc_fleet_nodes Scrape targets known to the fleet "
            "gateway (informer-discovered agent endpoints).",
            "# TYPE tpu_cc_fleet_nodes gauge",
            "tpu_cc_fleet_nodes %d" % len(self._scrapes),
            "# HELP tpu_cc_fleet_nodes_stale Targets whose scrape has "
            "been failing (or whose snapshot_ts stopped advancing) for "
            "stale_after_sweeps sweeps — listed in /fleetz, excluded "
            "from the rollups.",
            "# TYPE tpu_cc_fleet_nodes_stale gauge",
            "tpu_cc_fleet_nodes_stale %d" % n_stale,
            "# HELP tpu_cc_fleet_nodes_slow Targets whose scrape "
            "SUCCEEDED but ran past slow_scrape_s — alive-but-dragging "
            "gray signal: kept in the rollups, excluded from the "
            "headroom ledger, surfaced per node as scrape_slow in "
            "/fleetz.",
            "# TYPE tpu_cc_fleet_nodes_slow gauge",
            "tpu_cc_fleet_nodes_slow %d" % n_slow,
            "# HELP tpu_cc_fleet_headroom_nodes The capacity ledger: "
            "nodes with serving headroom (fresh scrape, not quarantined"
            "/offline/prestaging, hbm_bw_util under the ceiling, queue "
            "under the bound) — what the prestage pacer consumes.",
            "# TYPE tpu_cc_fleet_headroom_nodes gauge",
            "tpu_cc_fleet_headroom_nodes %d" % n_headroom,
            "# HELP tpu_cc_fleet_scrape_errors_total Failed per-node "
            "scrapes since gateway start (deadline, refused, frozen "
            "snapshot_ts), cumulative.",
            "# TYPE tpu_cc_fleet_scrape_errors_total counter",
            "tpu_cc_fleet_scrape_errors_total %d"
            % self._scrape_errors_total,
        ]
        if p99 is not None:
            lines += [
                "# HELP tpu_cc_fleet_serve_p99_seconds Fleet-pooled "
                "p99 serving latency (per-node histogram shards merged "
                "through obs/slo.py merge_p99).",
                "# TYPE tpu_cc_fleet_serve_p99_seconds gauge",
                "tpu_cc_fleet_serve_p99_seconds %.6f" % p99,
            ]
        self._merged_text = "\n".join(lines) + "\n"
        self._ledger = ledger

    def _headroom(self, parsed: ParsedExposition) -> dict:
        hbm = max(
            (v for _, v in parsed.series_values(HBM_FAMILY)), default=None
        )
        queue = sum(
            v for _, v in parsed.series_values(QUEUE_FAMILY)
        )
        prestaging = any(
            v > 0 for _, v in parsed.series_values(PRESTAGE_FAMILY)
        )
        quarantined = any(
            v > 0 for _, v in parsed.series_values(QUARANTINE_FAMILY)
        )
        connected = parsed.series_values(CONNECTED_FAMILY)
        offline = bool(connected) and all(v == 0 for _, v in connected)
        return {
            "hbm_bw_util": hbm,
            "queue_depth": int(queue),
            "prestage_in_progress": prestaging,
            "quarantined": quarantined,
            "offline": offline,
            "has_headroom": bool(
                not quarantined
                and not offline
                and not prestaging
                and (hbm is None or hbm < self.hbm_ceiling)
                and queue <= self.max_queue_depth
            ),
        }

    # -- read side ---------------------------------------------------------

    def metrics_text(self) -> str:
        with self._lock:
            return self._merged_text

    def fleetz(self) -> dict:
        with self._lock:
            ledger = {
                name: dict(entry)
                for name, entry in sorted(self._ledger.items())
            }
            sweep = self._sweep
            errors = self._scrape_errors_total
            sweep_seconds = self._last_sweep_seconds
        stale = sorted(n for n, e in ledger.items() if e["stale"])
        # Slow-but-successful is reported apart from dead/stale: a gray
        # node's telemetry is still live (rollups keep it) but its
        # capacity is not trusted — operators need to see which is which.
        slow = sorted(
            n for n, e in ledger.items()
            if e.get("scrape_slow") and not e["stale"]
        )
        burns = [
            e["slo_burn"] for e in ledger.values()
            if e.get("slo_burn") is not None
        ]
        return {
            "sweep": sweep,
            "sweep_seconds": sweep_seconds,
            "interval_s": self.interval_s,
            "stale_after_sweeps": self.stale_after_sweeps,
            "nodes": ledger,
            "fleet": {
                "nodes": len(ledger),
                "stale": len(stale),
                "stale_nodes": stale,
                "slow": len(slow),
                "slow_nodes": slow,
                "headroom_nodes": sum(
                    1 for e in ledger.values() if e.get("has_headroom")
                ),
                "max_slo_burn": max(burns) if burns else None,
                "scrape_errors_total": errors,
            },
        }

    def stitched_rollout(self) -> dict:
        """The federated rollout view (``/fleetz?rollout=``): every
        node's ``/rolloutz`` recent-event stream stitched into one
        seq-consistent timeline (obs/flight.py) plus its exactly-once
        reconstruction."""
        with self._lock:
            streams = {
                name: list(scrape.get("rollout_recent") or [])
                for name, scrape in sorted(self._scrapes.items())
            }
            torn = sum(
                scrape.get("rollout_torn") or 0
                for scrape in self._scrapes.values()
            )
        nonempty = {n: s for n, s in streams.items() if s}
        events = flight_mod.stitch_timelines(
            list(nonempty.values()), labels=list(nonempty)
        )
        return {
            "streams": len(nonempty),
            "events": len(events),
            "torn_lines": torn,
            "reconstruction": flight_mod.reconstruct(events),
        }

    # -- serving -----------------------------------------------------------

    def serve(
        self, port: int, bind: str = "127.0.0.1"
    ) -> http.server.ThreadingHTTPServer:
        """Serve fleet ``/metrics``, ``/fleetz`` (``?rollout=`` for the
        stitched timeline) and ``/healthz`` on ``bind:port`` (port 0 =
        ephemeral; read it back off ``server_address``)."""
        gateway = self

        class Handler(http.server.BaseHTTPRequestHandler):
            def do_GET(self):  # noqa: N802 - http.server API
                url = urlparse(self.path)
                path = url.path.rstrip("/")
                content_type = "application/json"
                if path in ("", "/metrics"):
                    body = gateway.metrics_text().encode()
                    content_type = "text/plain; version=0.0.4"
                    code = 200
                elif path == "/healthz":
                    body = b"ok\n"
                    content_type = "text/plain"
                    code = 200
                elif path == "/fleetz":
                    # keep_blank_values: the documented form is the
                    # bare `?rollout=` flag, which parse_qs otherwise
                    # drops.
                    query = parse_qs(url.query, keep_blank_values=True)
                    payload = gateway.fleetz()
                    if "rollout" in query:
                        payload["rollout"] = gateway.stitched_rollout()
                    body = (json.dumps(payload, indent=1) + "\n").encode()
                    code = 200
                else:
                    body = b"not found\n"
                    content_type = "text/plain"
                    code = 404
                self.send_response(code)
                self.send_header("Content-Type", content_type)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def log_message(self, fmt, *fmtargs):  # quiet access logs
                log.debug("fleet http: " + fmt, *fmtargs)

        server = http.server.ThreadingHTTPServer((bind, port), Handler)
        thread = threading.Thread(
            target=server.serve_forever, name="fleet-gateway", daemon=True
        )
        thread.start()
        log.info(
            "fleet gateway listening on %s:%d",
            bind, server.server_address[1],
        )
        return server

    def run(self, stop: threading.Event | None = None) -> None:
        """Sweep loop: scrape, then wait out the interval (stop-aware,
        via the sanctioned retry.wait — a kill between sweeps returns
        immediately)."""
        stop = stop or threading.Event()
        while not stop.is_set():
            try:
                self.scrape_once()
            except Exception:  # noqa: BLE001 - the loop must outlive one bad sweep
                log.exception("fleet sweep failed; continuing")
            if retry_mod.wait(self.interval_s, stop):
                return
