"""Peer-relative fail-slow (gray-failure) vetting.

Every failure the manager survives elsewhere is fail-stop or fail-dark;
production fleets lose far more SLO to *gray* failures — nodes that pass
every watchdog probe while serving 10x latency (Huang et al., HotOS'17;
Gunawi et al., "Fail-Slow at Scale", FAST'18). A gray node cannot be
caught by an absolute threshold (load moves the whole fleet's latency
together) nor by its own health probe (green by definition), so this
module judges each node *against its peers*:

- **samples**: per-node request latencies, fed either directly
  (:meth:`FailslowVetter.observe`, the ServeHarness path) or scraped
  (:meth:`FailslowVetter.ingest_exposition` deltas the cumulative
  ``tpu_cc_serve_request_seconds_sum``/``_count`` families between
  calls — the FleetGateway-rollup path);
- **vetting window**: each :meth:`FailslowVetter.vet` call closes one
  window; a node's window statistic is its sample **median** (robust to
  a stray tail) and the fleet baseline is the **median of the per-node
  medians** (robust to the suspect itself dragging the mean);
- **hysteresis**: a node must deviate beyond ``threshold`` x fleet for
  ``min_windows`` CONSECUTIVE windows to be confirmed (one bad window
  is weather), and a confirmed node must recover below
  ``clear_threshold`` for ``clear_windows`` consecutive windows to be
  cleared (flapping is not recovery);
- **false-positive bound**: with the default ``threshold`` of 2.0, a
  healthy homogeneous fleet under ±20 % latency jitter can reach a
  peer ratio of at most 1.2/0.8 = 1.5 — strictly inside the threshold,
  so no strike is ever possible from jitter alone
  (tests/test_failslow.py holds this to a 200-trial seeded property
  test). ``min_peers`` floors the jury: below it there is no fleet to
  be relative to, and the vetter abstains rather than guess.

Verdicts are **re-concluding**: while a node stays confirmed, every
further deviant window emits another confirmed verdict under a fresh
monotonic id. That is what lets the consumer escalate — the remediation
ladder turns verdict #1 into a runtime restart and verdict #2 into a
quarantine (``reason=fail-slow``) — while the ids keep journaled
exactly-once acting trivial (ccmanager/rolling.py ``failslow-vetted``
crash point: the successor resumes acting from the record by id, never
double-quarantining).
"""

from __future__ import annotations

import os
import re
import statistics
import time

from tpu_cc_manager.utils import locks as locks_mod

VERDICT_CONFIRMED = "confirmed"
VERDICT_CLEARED = "cleared"

FAILSLOW_WINDOW_S_ENV = "CC_FAILSLOW_WINDOW_S"
FAILSLOW_THRESHOLD_ENV = "CC_FAILSLOW_THRESHOLD"
FAILSLOW_MIN_WINDOWS_ENV = "CC_FAILSLOW_MIN_WINDOWS"
FAILSLOW_MIN_PEERS_ENV = "CC_FAILSLOW_MIN_PEERS"
FAILSLOW_CLEAR_WINDOWS_ENV = "CC_FAILSLOW_CLEAR_WINDOWS"

#: Exposition families the scrape-fed path deltas (per-node cumulative
#: latency sum and completion count, exported by utils/metrics.py).
_SUM_RE = re.compile(
    r'^tpu_cc_serve_request_seconds_sum\{node="([^"]*)"\}\s+([0-9.eE+-]+)\s*$',
    re.MULTILINE,
)
_COUNT_RE = re.compile(
    r'^tpu_cc_serve_request_seconds_count\{node="([^"]*)"\}\s+([0-9.eE+-]+)\s*$',
    re.MULTILINE,
)


class FailslowVetter:
    """Thread-safe peer-relative outlier vetter.

    Feed per-node latencies with :meth:`observe` (or scrape deltas with
    :meth:`ingest_exposition`); the caller paces the windows by calling
    :meth:`vet` once per ``window_s`` — each call closes the current
    window, judges every participating node against the fleet median,
    and appends any verdicts to the non-draining :meth:`concluded` list
    (monotonic ids, so consumers dedup by id). ``clock`` is injectable
    for deterministic tests.
    """

    def __init__(
        self,
        window_s: float = 5.0,
        threshold: float = 2.0,
        clear_threshold: float = 1.3,
        min_windows: int = 2,
        clear_windows: int = 2,
        min_peers: int = 3,
        min_samples: int = 3,
        metrics=None,
        clock=time.monotonic,
    ) -> None:
        if threshold <= 1.0:
            raise ValueError("threshold must be > 1.0")
        if clear_threshold > threshold:
            raise ValueError("clear_threshold must be <= threshold")
        self.window_s = float(window_s)
        self.threshold = float(threshold)
        self.clear_threshold = float(clear_threshold)
        self.min_windows = max(1, int(min_windows))
        self.clear_windows = max(1, int(clear_windows))
        self.min_peers = max(2, int(min_peers))
        self.min_samples = max(1, int(min_samples))
        self.metrics = metrics
        self.clock = clock
        self._lock = locks_mod.make_lock("obs.failslow")
        self._window: dict[str, list[float]] = {}  # cclint: guarded-by(_lock)
        self._strikes: dict[str, int] = {}  # cclint: guarded-by(_lock)
        self._clear_streak: dict[str, int] = {}  # cclint: guarded-by(_lock)
        self._confirmed: set[str] = set()  # cclint: guarded-by(_lock)
        self._suspect: set[str] = set()  # cclint: guarded-by(_lock)
        self._deviation: dict[str, float] = {}  # cclint: guarded-by(_lock)
        self._concluded: list[dict] = []  # cclint: guarded-by(_lock)
        self._next_id = 1  # cclint: guarded-by(_lock)
        self.windows_vetted = 0  # cclint: guarded-by(_lock)
        # Last cumulative (sum, count) per node the scrape path saw.
        self._scrape_prev: dict[str, tuple[float, float]] = {}  # cclint: guarded-by(_lock)

    @classmethod
    def from_env(cls, **kwargs) -> "FailslowVetter":
        """Build from the CC_FAILSLOW_* env knobs (docs/operations.md
        env table); explicit kwargs win over the environment."""
        env = {
            "window_s": float(os.environ.get(FAILSLOW_WINDOW_S_ENV, "5.0")),
            "threshold": float(os.environ.get(FAILSLOW_THRESHOLD_ENV, "2.0")),
            "min_windows": int(os.environ.get(FAILSLOW_MIN_WINDOWS_ENV, "2")),
            "min_peers": int(os.environ.get(FAILSLOW_MIN_PEERS_ENV, "3")),
            "clear_windows": int(
                os.environ.get(FAILSLOW_CLEAR_WINDOWS_ENV, "2")
            ),
        }
        env.update(kwargs)
        return cls(**env)

    # -- feeding -----------------------------------------------------------

    def observe(self, node: str, seconds: float) -> None:
        """Fold one completed request's latency into the current
        window's per-node sample set (the ServeHarness feeds every
        completion through here from the driver's on_complete)."""
        with self._lock:
            self._window.setdefault(node, []).append(
                max(0.0, float(seconds))
            )

    def ingest_exposition(self, text: str) -> int:
        """Scrape-fed path: delta the cumulative per-node
        ``tpu_cc_serve_request_seconds_sum``/``_count`` families against
        the previous call and fold each node's interval MEAN latency in
        as one weighted window sample per completed request (capped at
        ``min_samples`` — the mean already summarizes the interval).
        Returns how many nodes contributed. First call only primes the
        cumulative baseline (a cumulative counter's first read is not a
        rate)."""
        sums = {n: float(v) for n, v in _SUM_RE.findall(text)}
        counts = {n: float(v) for n, v in _COUNT_RE.findall(text)}
        contributed = 0
        with self._lock:
            for node, count in counts.items():
                total = sums.get(node)
                if total is None:
                    continue
                prev = self._scrape_prev.get(node)
                self._scrape_prev[node] = (total, count)
                if prev is None:
                    continue
                d_sum = total - prev[0]
                d_count = count - prev[1]
                if d_count <= 0 or d_sum < 0:
                    continue  # counter reset or idle interval
                mean = d_sum / d_count
                reps = min(self.min_samples, int(d_count))
                self._window.setdefault(node, []).extend([mean] * reps)
                contributed += 1
        return contributed

    # -- vetting -----------------------------------------------------------

    def vet(self) -> list[dict]:
        """Close the current vetting window and judge it. Returns the
        verdicts newly concluded by THIS call (also appended to
        :meth:`concluded`): ``{"id", "node", "verdict", "deviation"}``.
        Abstains (returns []) when fewer than ``min_peers`` nodes
        produced ``min_samples`` samples — strikes neither advance nor
        reset without a fleet to be relative to."""
        new: list[dict] = []
        with self._lock:
            window, self._window = self._window, {}
            self.windows_vetted += 1
            medians = {
                n: statistics.median(s)
                for n, s in window.items()
                if len(s) >= self.min_samples
            }
            if len(medians) < self.min_peers:
                return []
            fleet = statistics.median(medians.values())
            if fleet <= 0:
                return []
            for node, med in sorted(medians.items()):
                ratio = med / fleet
                self._deviation[node] = ratio
                if node in self._confirmed:
                    if ratio <= self.clear_threshold:
                        streak = self._clear_streak.get(node, 0) + 1
                        self._clear_streak[node] = streak
                        if streak >= self.clear_windows:
                            self._confirmed.discard(node)
                            self._suspect.discard(node)
                            self._strikes[node] = 0
                            self._clear_streak[node] = 0
                            new.append(self._conclude_locked(
                                node, VERDICT_CLEARED, ratio
                            ))
                    else:
                        self._clear_streak[node] = 0
                        if ratio >= self.threshold:
                            # Re-conclude: still deviant while
                            # confirmed — a fresh verdict id lets the
                            # consumer's ladder escalate.
                            new.append(self._conclude_locked(
                                node, VERDICT_CONFIRMED, ratio
                            ))
                    continue
                if ratio >= self.threshold:
                    strikes = self._strikes.get(node, 0) + 1
                    self._strikes[node] = strikes
                    self._suspect.add(node)
                    if strikes >= self.min_windows:
                        self._confirmed.add(node)
                        self._clear_streak[node] = 0
                        new.append(self._conclude_locked(
                            node, VERDICT_CONFIRMED, ratio
                        ))
                else:
                    self._strikes[node] = 0
                    self._suspect.discard(node)
            self._export_locked(medians)
        return new

    def _conclude_locked(self, node, verdict, ratio) -> dict:  # cclint: requires(_lock)
        entry = {
            "id": self._next_id,
            "node": node,
            "verdict": verdict,
            "deviation": round(ratio, 4),
        }
        self._next_id += 1
        self._concluded.append(entry)
        if self.metrics is not None:
            self.metrics.record_failslow_verdict(node, verdict)
        # Bound memory across a long soak; consumers dedup by id and
        # have long since acted on anything this old.
        if len(self._concluded) > 256:
            del self._concluded[: len(self._concluded) - 256]
        return entry

    def _export_locked(self, medians) -> None:  # cclint: requires(_lock)
        if self.metrics is None:
            return
        for node in medians:
            self.metrics.set_failslow_suspect(
                node, node in self._suspect or node in self._confirmed
            )
            self.metrics.set_failslow_deviation(
                node, self._deviation.get(node, 1.0)
            )

    # -- reading -----------------------------------------------------------

    def suspects(self) -> set[str]:
        """Nodes currently under suspicion (>= 1 strike) or confirmed —
        the set the serve driver de-weights and the prestage headroom
        gate excludes while vetting runs."""
        with self._lock:
            return set(self._suspect) | set(self._confirmed)

    def confirmed(self) -> set[str]:
        with self._lock:
            return set(self._confirmed)

    def concluded(self) -> list[dict]:
        """Every verdict concluded so far (non-draining, ids monotonic):
        the poll contract for the rolling orchestrator's journaled
        exactly-once acting — reading never consumes, so a successor
        resuming after a SIGKILL sees the same list."""
        with self._lock:
            return [dict(e) for e in self._concluded]

    def deviation(self, node: str) -> float | None:
        with self._lock:
            return self._deviation.get(node)


def publish_suspect_labels(api, added, removed) -> None:
    """Best-effort label publication for the ``ctl status`` SUSPECT
    column: mark newly suspected nodes, clear recovered ones. Failures
    are swallowed — suspicion labels are operator telemetry, never
    control flow (the record journal, not the label, is authoritative
    for acting)."""
    from tpu_cc_manager.labels import FAILSLOW_SUSPECT_LABEL

    for name in added:
        try:
            api.patch_node_labels(name, {FAILSLOW_SUSPECT_LABEL: "true"})
        except Exception:
            pass
    for name in removed:
        try:
            api.patch_node_labels(name, {FAILSLOW_SUSPECT_LABEL: None})
        except Exception:
            pass
