"""Windowed serving-SLO evaluation: rolling p99 and error-budget burn.

ROADMAP item 1 (SLO-paced rollouts) needs the orchestrator to ask one
question while a pool flips under live traffic: *is the user-visible SLO
holding right now?* This module is that answer's single implementation —
the TrafficDriver feeds it every completion (and every loss), it keeps a
bounded rolling window of samples, and both consumers read the same
numbers:

- ``tpu_cc_serve_slo_p99_seconds`` / ``tpu_cc_serve_error_budget_burn``
  metric gauges (utils/metrics.py, exported per window), and
- :meth:`SloEvaluator.snapshot` — the Python contract a latency-gated
  rollout will poll at wave boundaries (``breached()`` is the halt
  predicate, shaped like the failure budget's).

Definitions (the SRE-workbook shapes, kept deliberately boring):

- **p99**: the 99th-percentile latency of the samples inside the
  window (nearest-rank on the sorted list).
- **error rate**: failed samples / all samples in the window.
- **burn rate**: error rate / error budget — 1.0 means the budget is
  being spent exactly as provisioned; a burn of 14 on a short window is
  the classic page-now threshold.

The math is conservation-friendly on purpose (tests/test_slo.py holds
it to property tests): error *counts* over a window equal the sum over
any split of that window, p99 is monotone under added slow requests,
and an empty window reports ``None`` p99 with zero burn rather than
inventing a number.
"""

from __future__ import annotations

import bisect
import collections
import time

from tpu_cc_manager.utils import locks as locks_mod

#: Default rolling windows (seconds): a fast window for paging-speed
#: reaction and a slow one for pacing decisions.
DEFAULT_WINDOWS_S = (30.0, 300.0)

#: Default error budget: 99.9 % of requests succeed.
DEFAULT_ERROR_BUDGET = 1e-3

#: Bound on retained samples; beyond this the OLDEST samples are
#: dropped (the windows are time-bounded anyway — this is the memory
#: backstop for a driver pushing 100k+ rps through a long soak).
DEFAULT_MAX_SAMPLES = 200_000


def percentile(sorted_vals: list[float], q: float) -> float | None:
    """Nearest-rank percentile of an ascending list (None when empty)."""
    if not sorted_vals:
        return None
    idx = min(
        len(sorted_vals) - 1, max(0, round(q * (len(sorted_vals) - 1)))
    )
    return sorted_vals[idx]


def breach_verdict(
    burn_rate: float,
    p99_s: float | None,
    max_burn_rate: float,
    p99_target_s: float | None,
) -> bool:
    """THE breach predicate — the single comparison both gate forms
    share (:meth:`SloEvaluator.breached` in-process,
    :func:`breached_from_metrics_text` over a remote scrape), so the
    thresholds can never drift between them: burn above budget, or p99
    above target when both exist (a None p99 is no evidence, never a
    breach)."""
    if burn_rate > max_burn_rate:
        return True
    return (
        p99_target_s is not None
        and p99_s is not None
        and p99_s > p99_target_s
    )


class SloEvaluator:
    """Thread-safe rolling-window SLO evaluator.

    ``observe(latency_s, ok=...)`` records one finished request;
    ``snapshot()`` reports per-window p99 / error rate / burn rate /
    goodput; ``breached(...)`` is the boolean the pacing loop polls.
    ``clock`` is injectable for deterministic tests.
    """

    def __init__(
        self,
        windows_s: tuple[float, ...] = DEFAULT_WINDOWS_S,
        error_budget: float = DEFAULT_ERROR_BUDGET,
        p99_target_s: float | None = None,
        max_samples: int = DEFAULT_MAX_SAMPLES,
        clock=time.monotonic,
    ) -> None:
        if not windows_s:
            raise ValueError("at least one window is required")
        if error_budget <= 0:
            raise ValueError("error_budget must be > 0")
        self.windows_s = tuple(sorted(float(w) for w in windows_s))
        self.error_budget = float(error_budget)
        self.p99_target_s = p99_target_s
        self.max_samples = max(1, int(max_samples))
        self.clock = clock
        self._lock = locks_mod.make_lock("obs.slo")
        # (t, latency_s, ok) in arrival order; pruned past the longest
        # window on every observe.
        self._samples: collections.deque[tuple[float, float, bool]] = (  # cclint: guarded-by(_lock)
            collections.deque()
        )
        self._total = 0  # cclint: guarded-by(_lock)
        self._errors_total = 0  # cclint: guarded-by(_lock)

    # -- recording ---------------------------------------------------------

    def observe(
        self, latency_s: float, ok: bool = True, now: float | None = None
    ) -> None:
        t = self.clock() if now is None else now
        with self._lock:
            self._samples.append((t, max(0.0, float(latency_s)), bool(ok)))
            self._total += 1
            if not ok:
                self._errors_total += 1
            self._prune(t)

    def observe_error(self, now: float | None = None) -> None:
        """A request that never completed (lost / deadline-dead): all
        error, no meaningful latency."""
        self.observe(0.0, ok=False, now=now)

    def _prune(self, now: float) -> None:  # cclint: requires(_lock)
        horizon = now - self.windows_s[-1]
        while self._samples and (
            self._samples[0][0] < horizon
            or len(self._samples) > self.max_samples
        ):
            self._samples.popleft()

    # -- reading -----------------------------------------------------------

    def counts_between(self, t0: float, t1: float) -> tuple[int, int]:
        """(samples, errors) with ``t0 <= t < t1`` — the conservation
        primitive: counts over a window equal the sum over any split of
        it (tests/test_slo.py)."""
        with self._lock:
            total = errors = 0
            for t, _lat, ok in self._samples:
                if t0 <= t < t1:
                    total += 1
                    if not ok:
                        errors += 1
            return total, errors

    def stats(
        self, window_s: float | None = None, now: float | None = None
    ) -> dict:
        """One window's readout. ``window_s`` defaults to the fastest
        configured window."""
        if window_s is None:
            window_s = self.windows_s[0]
        t = self.clock() if now is None else now
        horizon = t - window_s
        with self._lock:
            # Samples arrive in clock order, so walking from the newest
            # end and stopping at the horizon costs O(window), not
            # O(everything retained) — this runs on the driver's
            # dispatch thread every ladder tick, and the retention
            # backstop is 200k samples. (An out-of-order straggler
            # stamped older than the window's newest sample may be
            # missed by the early stop — acceptable for telemetry;
            # counts_between keeps the exact full scan.)
            in_window = []
            for s in reversed(self._samples):
                if s[0] < horizon:
                    break
                in_window.append(s)
        lats = sorted(lat for _, lat, ok in in_window if ok)
        count = len(in_window)
        errors = sum(1 for _, _, ok in in_window if not ok)
        error_rate = (errors / count) if count else 0.0
        p99 = percentile(lats, 0.99)
        return {
            "window_s": window_s,
            "count": count,
            "errors": errors,
            "ok": count - errors,
            "error_rate": error_rate,
            # An empty window burns nothing: no evidence is not bad
            # evidence (the pacing loop must not halt a rollout because
            # traffic paused).
            "burn_rate": error_rate / self.error_budget,
            "p99_s": p99,
            "p50_s": percentile(lats, 0.50),
            "goodput_rps": (count - errors) / window_s if window_s else 0.0,
        }

    def snapshot(self, now: float | None = None) -> dict:
        """Every configured window's stats plus lifetime totals — the
        poll contract for the latency-gated rollout AND the payload the
        serve metrics export."""
        with self._lock:
            total, errors_total = self._total, self._errors_total
        return {
            "error_budget": self.error_budget,
            "p99_target_s": self.p99_target_s,
            "windows": [
                self.stats(w, now=now) for w in self.windows_s
            ],
            "total": total,
            "errors_total": errors_total,
        }

    def breached(
        self,
        max_burn_rate: float = 1.0,
        window_s: float | None = None,
        now: float | None = None,
        p99_target_s: float | None = None,
    ) -> bool:
        """True when the SLO is being violated over ``window_s``: the
        burn rate exceeds ``max_burn_rate``, or the window p99 exceeds
        the target (``p99_target_s`` argument, falling back to the
        evaluator's configured target). The halt predicate a
        latency-gated rollout checks at wave boundaries, same shape as
        the failure budget's — and its ONLY implementation: callers
        (ServeHarness's in-process gate, the remote
        ``breached_from_metrics_text``) must not re-derive it."""
        target = p99_target_s if p99_target_s is not None else self.p99_target_s
        s = self.stats(window_s, now=now)
        return breach_verdict(
            s["burn_rate"], s["p99_s"], max_burn_rate, target
        )


#: Exposition families the remote gate reads. One sample line looks like
#: ``tpu_cc_serve_error_budget_burn{window="30"} 1.500000``.
_GAUGE_RE_TMPL = r'^%s\{window="([^"]+)"\}\s+([0-9.eE+-]+)\s*$'


def parse_serve_slo_text(text: str) -> dict[float, dict[str, float]]:
    """Parse the ``tpu_cc_serve_slo_p99_seconds`` /
    ``tpu_cc_serve_error_budget_burn`` gauges out of a Prometheus
    exposition scrape: window seconds -> {"p99_s": ..., "burn_rate":
    ...}. A window exporting only burn (empty window: no invented p99)
    yields no ``p99_s`` key — the same no-sample contract the local
    evaluator keeps."""
    import re

    out: dict[float, dict[str, float]] = {}
    for family, key in (
        ("tpu_cc_serve_slo_p99_seconds", "p99_s"),
        ("tpu_cc_serve_error_budget_burn", "burn_rate"),
    ):
        pat = re.compile(_GAUGE_RE_TMPL % re.escape(family), re.MULTILINE)
        for window, value in pat.findall(text):
            try:
                w = float(window)
                v = float(value)
            except ValueError:
                continue
            out.setdefault(w, {})[key] = v
    return out


def parse_serve_offered_rps(text: str) -> float | None:
    """Parse the ``tpu_cc_serve_offered_rps`` gauge (no labels, unlike
    the windowed SLO gauges) out of an exposition scrape — the input
    the continuous-prestage headroom gate converts into knee slack
    (rolling.headroom_gate_from_source). None when the pool exports no
    offered-rate gauge: no evidence of slack."""
    import re

    m = re.search(
        r"^tpu_cc_serve_offered_rps\s+([0-9.eE+-]+)\s*$",
        text, re.MULTILINE,
    )
    if m is None:
        return None
    try:
        return float(m.group(1))
    except ValueError:
        return None


def breached_from_metrics_text(
    text: str,
    max_burn_rate: float = 1.0,
    p99_target_s: float | None = None,
    window_s: float | None = None,
) -> bool:
    """The remote twin of :meth:`SloEvaluator.breached`, judged from a
    scraped ``/metrics`` payload (a serving pool's live exposition)
    instead of an in-process evaluator — what ``tpu-cc-ctl rollout
    --slo-source`` polls at wave boundaries. ``window_s`` selects one
    exported window (default: the fastest exported). A scrape with no
    serve SLO gauges at all reads as NOT breached — no evidence is not
    bad evidence, same as the empty-window rule."""
    windows = parse_serve_slo_text(text)
    if not windows:
        return False
    if window_s is not None:
        stats = windows.get(float(window_s))
        if stats is None:
            return False
    else:
        stats = windows[min(windows)]
    return breach_verdict(
        stats.get("burn_rate", 0.0), stats.get("p99_s"),
        max_burn_rate, p99_target_s,
    )


def merge_p99(sorted_a: list[float], sorted_b: list[float]) -> float | None:
    """p99 of the union of two ascending latency lists (no re-sort of
    the inputs' concatenation beyond a linear merge) — the helper the
    monotonicity property tests exercise: p99(A ∪ slow_extras) >=
    p99(A)."""
    if not sorted_a:
        return percentile(sorted_b, 0.99)
    if not sorted_b:
        return percentile(sorted_a, 0.99)
    merged = list(sorted_a)
    for v in sorted_b:
        bisect.insort(merged, v)
    return percentile(merged, 0.99)
