"""Checker 1: annotated lock discipline — now interprocedural.

The agent is a thread soup — watch loop, watchdog, preemption monitor,
informer, renewer, wave drivers, pipeline workers — and every shared
field they touch is supposed to be lock-guarded. The convention:

- A shared field declares its lock at its ``__init__`` assignment::

      self._nodes = {}  # cclint: guarded-by(_cond)

- Everywhere else in the class, the field may only be touched inside a
  lexical ``with self._cond:`` block, or in a method whose callers hold
  the lock::

      def _rebuild(self):  # cclint: requires(_cond)

- ``__init__`` itself is exempt (no concurrency before construction
  finishes), and a deliberate lock-free access can carry
  ``# cclint: unlocked-ok(<reason>)`` on its line.

v1 trusted two things it could not see; v2 checks them through the
class call graph:

- **``requires`` is verified, not trusted**: every same-class call site
  of a ``requires(L)`` method must hold L (lexically, or via its own
  ``requires``). A bare ``self.method`` reference to a ``requires``
  method (a thread target, a callback) is a finding — the thread that
  eventually calls it holds nothing.
- **unannotated private helpers are checked against their callers'
  lock context**: a ``_helper`` touching a guarded field outside a
  ``with`` is clean when every same-class call site provably holds the
  lock (one level of context — a chain of helpers needs ``requires``
  on the middle links), and a finding that names the lock-free caller
  otherwise. Public methods keep the strict lexical rule: external
  callers are invisible to the engine.

Lexical scoping stays deliberately conservative: a closure defined
inside a ``with`` block may run after the lock is released, so nested
``def`` / ``lambda`` bodies start with no held locks (they may
re-acquire, or declare ``requires`` on the nested def). Calls made
inside such closures count as lock-free call sites for the same reason.
"""

from __future__ import annotations

import ast
from dataclasses import dataclass, field

from tpu_cc_manager.lint.base import Finding, LintContext, SourceFile

CHECKER = "locks"


def _self_attr(node: ast.AST) -> str | None:
    """``self.<attr>`` -> attr name, else None."""
    if (
        isinstance(node, ast.Attribute)
        and isinstance(node.value, ast.Name)
        and node.value.id == "self"
    ):
        return node.attr
    return None


def _with_locks(node: ast.With) -> set[str]:
    """Lock names acquired by ``with self.<lock>[, ...]:`` items."""
    locks: set[str] = set()
    for item in node.items:
        attr = _self_attr(item.context_expr)
        if attr is not None:
            locks.add(attr)
    return locks


def _requires_of(fn: ast.FunctionDef, src: SourceFile) -> set[str]:
    """Locks a ``# cclint: requires(<lock>)`` annotation on the def's
    signature lines declares held by every caller."""
    sig_end = fn.body[0].lineno if fn.body else fn.lineno
    out: set[str] = set()
    for ln in range(fn.lineno, sig_end + 1):
        for d, arg in src.annotations.get(ln, ()):
            if d == "requires":
                out.update(a.strip() for a in arg.split(",") if a.strip())
    return out


def _guarded_fields(cls: ast.ClassDef, src: SourceFile) -> dict[str, str]:
    """field -> lock, from ``guarded-by`` annotations on ``__init__``
    assignments (or class-body assignments)."""
    guarded: dict[str, str] = {}

    def scan_stmt(stmt: ast.stmt) -> None:
        targets: list[ast.expr] = []
        if isinstance(stmt, ast.Assign):
            targets = stmt.targets
        elif isinstance(stmt, (ast.AnnAssign, ast.AugAssign)):
            targets = [stmt.target]
        else:
            return
        arg = src.annotation(
            stmt.lineno, "guarded-by", span_end=stmt.end_lineno
        )
        if arg is None:
            return
        for t in targets:
            attr = _self_attr(t)
            if attr is not None:
                guarded[attr] = arg.strip()

    for node in cls.body:
        if isinstance(node, ast.FunctionDef) and node.name == "__init__":
            for stmt in ast.walk(node):
                if isinstance(stmt, ast.stmt):
                    scan_stmt(stmt)
    return guarded


@dataclass
class _Access:
    """One guarded-field touch: where, and what was lexically held."""

    attr: str
    line: int
    held: frozenset


@dataclass
class _CallSite:
    """One ``self.m(...)`` call (or bare ``self.m`` reference) with the
    lexically-held lock set at that point."""

    method: str
    line: int
    held: frozenset
    caller: str
    is_call: bool  # False: bare reference (thread target / callback)


@dataclass
class _MethodFacts:
    accesses: list[_Access] = field(default_factory=list)
    calls: list[_CallSite] = field(default_factory=list)


class _MethodWalker:
    """Walks one method body tracking the lexically-held lock set,
    collecting guarded-field accesses and same-class call sites."""

    def __init__(
        self,
        src: SourceFile,
        cls_name: str,
        method: str,
        guarded: dict[str, str],
        method_names: set[str],
        facts: _MethodFacts,
    ) -> None:
        self.src = src
        self.cls_name = cls_name
        self.method = method
        self.guarded = guarded
        self.method_names = method_names
        self.facts = facts

    def walk(self, node: ast.AST, held: frozenset) -> None:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            # A nested def runs later, possibly lock-free: reset to its
            # own declared requirements.
            inner = frozenset(_requires_of(node, self.src))
            for child in node.body:
                self.walk(child, inner)
            return
        if isinstance(node, ast.Lambda):
            self.walk(node.body, frozenset())
            return
        if isinstance(node, (ast.With, ast.AsyncWith)):
            acquired = _with_locks(node)
            for item in node.items:
                self.walk(item.context_expr, held)
            for child in node.body:
                self.walk(child, held | acquired)
            return
        attr = _self_attr(node)
        if attr is not None:
            if attr in self.guarded:
                self.facts.accesses.append(
                    _Access(attr, node.lineno, held)
                )
            elif attr in self.method_names:
                # A bare self.m reference; ast.Call sites are recorded
                # below (the Call's func is this same Attribute — mark
                # it a call there and skip the double record here).
                self.facts.calls.append(
                    _CallSite(
                        attr, node.lineno, held, self.method, is_call=False
                    )
                )
            # Still walk the value chain (e.g. self._nodes[k].foo).
        if isinstance(node, ast.Call):
            callee = _self_attr(node.func)
            if callee is not None and callee in self.method_names:
                self.facts.calls.append(
                    _CallSite(
                        callee, node.lineno, held, self.method, is_call=True
                    )
                )
                # Walk args with the current held set; skip re-recording
                # the func attribute as a bare reference.
                for child in list(node.args) + [
                    kw.value for kw in node.keywords
                ]:
                    self.walk(child, held)
                return
        for child in ast.iter_child_nodes(node):
            self.walk(child, held)


def _is_private(name: str) -> bool:
    return name.startswith("_") and not (
        name.startswith("__") and name.endswith("__")
    )


def check(ctx: LintContext) -> list[Finding]:
    findings: list[Finding] = []
    for src in ctx.files:
        for cls in [
            n for n in ast.walk(src.tree) if isinstance(n, ast.ClassDef)
        ]:
            guarded = _guarded_fields(cls, src)
            methods = {
                n.name: n
                for n in cls.body
                if isinstance(n, ast.FunctionDef)
            }
            if not guarded and not any(
                _requires_of(m, src) for m in methods.values()
            ):
                continue
            requires = {
                name: frozenset(_requires_of(m, src))
                for name, m in methods.items()
            }
            facts: dict[str, _MethodFacts] = {}
            for name, m in methods.items():
                # __init__ is walked too: its field accesses are exempt
                # (no concurrency before construction finishes) and are
                # filtered in _judge_class, but the call sites and bare
                # references it records are NOT — a thread target built
                # in __init__ (`Thread(target=self._run)`) outlives
                # construction and runs holding nothing.
                mf = _MethodFacts()
                walker = _MethodWalker(
                    src, cls.name, name, guarded, set(methods), mf
                )
                for stmt in m.body:
                    walker.walk(stmt, requires[name])
                facts[name] = mf
            findings.extend(
                _judge_class(src, cls.name, guarded, requires, facts)
            )
    return findings


def _judge_class(
    src: SourceFile,
    cls_name: str,
    guarded: dict[str, str],
    requires: dict[str, frozenset],
    facts: dict[str, _MethodFacts],
) -> list[Finding]:
    findings: list[Finding] = []

    # Call sites of each method, across the class.
    sites: dict[str, list[_CallSite]] = {}
    for mf in facts.values():
        for cs in mf.calls:
            sites.setdefault(cs.method, []).append(cs)

    def waived(line: int) -> bool:
        return src.annotation(line, "unlocked-ok") is not None

    # -- requires() is verified against every visible call site ----------
    for name, req in requires.items():
        if not req:
            continue
        for cs in sites.get(name, ()):  # same-class call sites only
            if waived(cs.line):
                continue
            if cs.caller == "__init__" and cs.is_call:
                # A direct call during construction runs single-threaded;
                # the lock protects nothing yet. (A bare reference from
                # __init__ — a thread target — is still checked below.)
                continue
            if not cs.is_call:
                findings.append(
                    Finding(
                        checker=CHECKER,
                        path=src.relpath,
                        line=cs.line,
                        message=(
                            f"self.{name} (requires("
                            f"{', '.join(sorted(req))})) escapes as a bare "
                            f"reference in {cls_name}.{cs.caller} — a "
                            "thread target or callback runs it holding "
                            "nothing; acquire inside, or waive with "
                            "`# cclint: unlocked-ok(reason)`"
                        ),
                        symbol=f"{cls_name}.{cs.caller}",
                        detail=f"ref-{name}",
                    )
                )
                continue
            missing = req - cs.held
            if missing:
                findings.append(
                    Finding(
                        checker=CHECKER,
                        path=src.relpath,
                        line=cs.line,
                        message=(
                            f"{cls_name}.{cs.caller} calls self.{name}() "
                            f"without holding {', '.join(sorted(missing))} "
                            f"which it declares requires() — take the "
                            "lock at the call site (or annotate the "
                            "caller's own requires)"
                        ),
                        symbol=f"{cls_name}.{cs.caller}",
                        detail=f"call-{name}",
                    )
                )

    # -- guarded-field accesses, with caller-context inference ------------
    for name, mf in facts.items():
        if name == "__init__":
            continue  # no concurrency before construction finishes
        for acc in mf.accesses:
            lock = guarded[acc.attr]
            if lock in acc.held or waived(acc.line):
                continue
            # Lock-free lexically. A private helper is saved by its
            # callers when every same-class call site holds the lock
            # and the method never escapes as a bare reference. A direct
            # call from __init__ counts as held (single-threaded).
            caller_sites = sites.get(name, [])
            lockfree_caller = next(
                (
                    cs for cs in caller_sites
                    if not cs.is_call
                    or (lock not in cs.held and cs.caller != "__init__")
                ),
                None,
            )
            if (
                _is_private(name)
                and caller_sites
                and lockfree_caller is None
            ):
                continue  # proven through every caller
            via = ""
            if lockfree_caller is not None and lockfree_caller.is_call:
                via = (
                    f" (called lock-free from {cls_name}."
                    f"{lockfree_caller.caller} line {lockfree_caller.line})"
                )
            elif lockfree_caller is not None:
                via = (
                    f" (escapes as a bare reference in {cls_name}."
                    f"{lockfree_caller.caller} line {lockfree_caller.line})"
                )
            findings.append(
                Finding(
                    checker=CHECKER,
                    path=src.relpath,
                    line=acc.line,
                    message=(
                        f"self.{acc.attr} is guarded-by({lock}) but "
                        f"accessed outside `with self.{lock}:` in "
                        f"{cls_name}.{name}{via}"
                    ),
                    symbol=f"{cls_name}.{name}",
                    detail=acc.attr,
                )
            )
    return findings
