"""Checker 1: annotated lock discipline.

The agent is a thread soup — watch loop, watchdog, preemption monitor,
informer, renewer, wave drivers, pipeline workers — and every shared
field they touch is supposed to be lock-guarded. The convention this
checker enforces:

- A shared field declares its lock at its ``__init__`` assignment::

      self._nodes = {}  # cclint: guarded-by(_cond)

- Everywhere else in the class, the field may only be touched inside a
  ``with self._cond:`` block (lexically), or in a method that declares
  its callers hold the lock::

      def _rebuild(self):  # cclint: requires(_cond)

- ``__init__`` itself is exempt (no concurrency before construction
  finishes), and a deliberate lock-free access can carry
  ``# cclint: unlocked-ok(<reason>)`` on its line.

Lexical scoping is deliberately conservative: a closure defined inside a
``with`` block may run after the lock is released, so nested ``def`` /
``lambda`` bodies start with no held locks (they may re-acquire, or
declare ``requires`` on the nested def).
"""

from __future__ import annotations

import ast

from tpu_cc_manager.lint.base import Finding, LintContext, SourceFile

CHECKER = "locks"


def _self_attr(node: ast.AST) -> str | None:
    """``self.<attr>`` -> attr name, else None."""
    if (
        isinstance(node, ast.Attribute)
        and isinstance(node.value, ast.Name)
        and node.value.id == "self"
    ):
        return node.attr
    return None


def _with_locks(node: ast.With) -> set[str]:
    """Lock names acquired by ``with self.<lock>[, ...]:`` items."""
    locks: set[str] = set()
    for item in node.items:
        attr = _self_attr(item.context_expr)
        if attr is not None:
            locks.add(attr)
    return locks


def _requires_of(fn: ast.FunctionDef, src: SourceFile) -> set[str]:
    """Locks a ``# cclint: requires(<lock>)`` annotation on the def's
    signature lines declares held by every caller."""
    sig_end = fn.body[0].lineno if fn.body else fn.lineno
    out: set[str] = set()
    for ln in range(fn.lineno, sig_end + 1):
        for d, arg in src.annotations.get(ln, ()):
            if d == "requires":
                out.update(a.strip() for a in arg.split(",") if a.strip())
    return out


def _guarded_fields(cls: ast.ClassDef, src: SourceFile) -> dict[str, str]:
    """field -> lock, from ``guarded-by`` annotations on ``__init__``
    assignments (or class-body assignments)."""
    guarded: dict[str, str] = {}

    def scan_stmt(stmt: ast.stmt) -> None:
        targets: list[ast.expr] = []
        if isinstance(stmt, ast.Assign):
            targets = stmt.targets
        elif isinstance(stmt, (ast.AnnAssign, ast.AugAssign)):
            targets = [stmt.target]
        else:
            return
        arg = src.annotation(
            stmt.lineno, "guarded-by", span_end=stmt.end_lineno
        )
        if arg is None:
            return
        for t in targets:
            attr = _self_attr(t)
            if attr is not None:
                guarded[attr] = arg.strip()

    for node in cls.body:
        if isinstance(node, ast.FunctionDef) and node.name == "__init__":
            for stmt in ast.walk(node):
                if isinstance(stmt, ast.stmt):
                    scan_stmt(stmt)
    return guarded


class _MethodWalker:
    """Walks one method body tracking the lexically-held lock set."""

    def __init__(
        self,
        src: SourceFile,
        cls_name: str,
        method: str,
        guarded: dict[str, str],
        findings: list[Finding],
    ) -> None:
        self.src = src
        self.cls_name = cls_name
        self.method = method
        self.guarded = guarded
        self.findings = findings

    def walk(self, node: ast.AST, held: frozenset[str]) -> None:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            # A nested def runs later, possibly lock-free: reset to its
            # own declared requirements.
            inner = frozenset(_requires_of(node, self.src))
            for child in node.body:
                self.walk(child, inner)
            return
        if isinstance(node, ast.Lambda):
            self.walk(node.body, frozenset())
            return
        if isinstance(node, (ast.With, ast.AsyncWith)):
            acquired = _with_locks(node)
            for item in node.items:
                self.walk(item.context_expr, held)
            for child in node.body:
                self.walk(child, held | acquired)
            return
        attr = _self_attr(node)
        if attr is not None and attr in self.guarded:
            lock = self.guarded[attr]
            if lock not in held and self.src.annotation(
                node.lineno, "unlocked-ok"
            ) is None:
                self.findings.append(
                    Finding(
                        checker=CHECKER,
                        path=self.src.relpath,
                        line=node.lineno,
                        message=(
                            f"self.{attr} is guarded-by({lock}) but accessed "
                            f"outside `with self.{lock}:` in "
                            f"{self.cls_name}.{self.method}"
                        ),
                        symbol=f"{self.cls_name}.{self.method}",
                        detail=attr,
                    )
                )
            # Still walk the value chain (e.g. self._nodes[k].foo).
        for child in ast.iter_child_nodes(node):
            self.walk(child, held)


def check(ctx: LintContext) -> list[Finding]:
    findings: list[Finding] = []
    for src in ctx.files:
        for cls in [
            n for n in ast.walk(src.tree) if isinstance(n, ast.ClassDef)
        ]:
            guarded = _guarded_fields(cls, src)
            if not guarded:
                continue
            for fn in cls.body:
                if not isinstance(fn, ast.FunctionDef) or fn.name == "__init__":
                    continue
                held = frozenset(_requires_of(fn, src))
                walker = _MethodWalker(
                    src, cls.name, fn.name, guarded, findings
                )
                for stmt in fn.body:
                    walker.walk(stmt, held)
    return findings
