"""Checker 7: crash-point coverage — kill-at suites provably keep pace.

The kill-at-every-crash-point suites are this repo's strongest safety
evidence: the orchestrator names its crash points
(``self._crash_point("window-boundary")`` →
``FaultPlan.decide_orchestrator_kill``), the node pipeline marks journal
phases (``intents.mark(txn, PHASE_RESET)``), and the suites kill at each
one and prove the successor converges. That evidence rots silently: a
new crash point or phase mark added without a test is exactly the
crash ordering nobody ever exercised.

This checker closes the loop in both directions:

- **orphaned point** — a crash-point string passed to
  ``_crash_point(...)`` / ``decide_orchestrator_kill(...)`` in the
  package, or a journal phase passed to ``mark(...)``, that no test
  under ``tests/`` references (as the string literal, or as the
  ``PHASE_*`` constant name) fails the build. Waive a deliberately
  untested point with ``# cclint: crash-point-ok(<reason>)`` on the
  package line.
- **stale point** — a point name that only tests reference: a string in
  a test module's ``*CRASH_POINTS*`` declaration list, or a literal
  passed to ``decide_orchestrator_kill``/``_crash_point`` from a test,
  that no longer exists in the package. Dead coverage reads as
  coverage; it's a finding at the test line.

Tests claim coverage by *naming the literal* (a module-level
``ROLLING_CRASH_POINTS = [...]`` list that a runtime assertion ties to
the package's canonical tuple is the idiom — see
``tests/test_rollout_resume.py``). Dynamic constructions (f-strings,
index loops without names) are invisible to the static half on purpose:
the coverage contract is that the names are spelled out somewhere a
reviewer and this checker can both read.
"""

from __future__ import annotations

import ast
import re

from tpu_cc_manager.lint.base import Finding, LintContext, SourceFile

CHECKER = "crashpoints"

#: Package functions whose first string argument names a crash point.
POINT_SINKS = ("_crash_point", "decide_orchestrator_kill")

#: Journal phase-mark sinks: second argument is the phase.
MARK_SINKS = ("mark", "_journal_mark")

#: Test-side declaration lists the stale check reads.
_DECL_RE = re.compile(r"CRASH_POINTS?")


def _call_sink_name(call: ast.Call) -> str | None:
    fn = call.func
    if isinstance(fn, ast.Name):
        return fn.id
    if isinstance(fn, ast.Attribute):
        return fn.attr
    return None


def _phase_constants(files: list[SourceFile]) -> dict[str, str]:
    """PHASE_* constant name -> string value, from module-level
    assignments anywhere in the package (intent_journal.py today)."""
    out: dict[str, str] = {}
    for src in files:
        for node in src.tree.body:
            if (
                isinstance(node, ast.Assign)
                and len(node.targets) == 1
                and isinstance(node.targets[0], ast.Name)
                and node.targets[0].id.startswith("PHASE_")
                and isinstance(node.value, ast.Constant)
                and isinstance(node.value.value, str)
            ):
                out[node.targets[0].id] = node.value.value
    return out


def _package_points(
    files: list[SourceFile], phase_consts: dict[str, str]
) -> dict[str, tuple[SourceFile, int, frozenset[str]]]:
    """point-key -> (src, line, accepted test tokens). Crash points are
    keyed by their literal; phase marks accept either the constant name
    or its value."""
    out: dict[str, tuple[SourceFile, int, frozenset[str]]] = {}
    value_to_const = {v: k for k, v in phase_consts.items()}
    for src in files:
        for node in ast.walk(src.tree):
            if not isinstance(node, ast.Call):
                continue
            sink = _call_sink_name(node)
            if sink in POINT_SINKS:
                args = node.args
                if args and isinstance(args[0], ast.Constant) and isinstance(
                    args[0].value, str
                ):
                    point = args[0].value
                    out.setdefault(
                        point, (src, node.lineno, frozenset((point,)))
                    )
            elif sink in MARK_SINKS and len(node.args) >= 2:
                phase = node.args[1]
                name = value = None
                if isinstance(phase, ast.Attribute) and phase.attr.startswith(
                    "PHASE_"
                ):
                    name = phase.attr
                    value = phase_consts.get(name)
                elif isinstance(phase, ast.Name) and phase.id.startswith(
                    "PHASE_"
                ):
                    name = phase.id
                    value = phase_consts.get(name)
                elif isinstance(phase, ast.Constant) and isinstance(
                    phase.value, str
                ):
                    value = phase.value
                    name = value_to_const.get(value)
                tokens = frozenset(t for t in (name, value) if t)
                if tokens:
                    key = value or name
                    out.setdefault(key, (src, node.lineno, tokens))
    return out


def _test_tokens(test_files: list[SourceFile]) -> set[str]:
    """Everything a test can reference a point by: every string literal
    plus every PHASE_*-shaped identifier."""
    out: set[str] = set()
    for src in test_files:
        for node in ast.walk(src.tree):
            if isinstance(node, ast.Constant) and isinstance(node.value, str):
                out.add(node.value)
            elif isinstance(node, ast.Attribute) and node.attr.startswith(
                "PHASE_"
            ):
                out.add(node.attr)
            elif isinstance(node, ast.Name) and node.id.startswith("PHASE_"):
                out.add(node.id)
    return out


def _test_claims(
    test_files: list[SourceFile],
) -> list[tuple[SourceFile, int, str]]:
    """(src, line, point) for every test-side point *claim*: entries of
    ``*CRASH_POINTS*`` declaration lists and literals passed to the
    point sinks from tests."""
    out: list[tuple[SourceFile, int, str]] = []
    for src in test_files:
        for node in src.tree.body:
            if (
                isinstance(node, ast.Assign)
                and len(node.targets) == 1
                and isinstance(node.targets[0], ast.Name)
                and _DECL_RE.search(node.targets[0].id)
                and isinstance(node.value, (ast.List, ast.Tuple, ast.Set))
            ):
                for elt in node.value.elts:
                    if isinstance(elt, ast.Constant) and isinstance(
                        elt.value, str
                    ):
                        out.append((src, elt.lineno, elt.value))
        for node in ast.walk(src.tree):
            if isinstance(node, ast.Call) and _call_sink_name(
                node
            ) in POINT_SINKS:
                if node.args and isinstance(
                    node.args[0], ast.Constant
                ) and isinstance(node.args[0].value, str):
                    out.append((src, node.lineno, node.args[0].value))
    return out


def check(ctx: LintContext) -> list[Finding]:
    findings: list[Finding] = []
    phase_consts = _phase_constants(ctx.files)
    points = _package_points(ctx.files, phase_consts)
    tokens = _test_tokens(ctx.test_files)

    # -- orphaned: package point no test names ----------------------------
    for key, (src, line, accepted) in sorted(points.items()):
        if accepted & tokens:
            continue
        if src.annotation(line, "crash-point-ok") is not None:
            continue
        findings.append(
            Finding(
                checker=CHECKER,
                path=src.relpath,
                line=line,
                message=(
                    f"crash point {key!r} has no kill-at test under "
                    "tests/ naming it — add it to the suite's "
                    "*_CRASH_POINTS list (and exercise it), or waive "
                    "with `# cclint: crash-point-ok(reason)`"
                ),
                symbol="orphaned-point",
                detail=key,
            )
        )

    # -- stale: test claim the package no longer makes --------------------
    known: set[str] = set()
    for _, (_, _, accepted) in points.items():
        known |= accepted
    # Phase constants remain claimable even where a mark site also
    # accepts them by value.
    known |= set(phase_consts) | set(phase_consts.values())
    for src, line, point in _test_claims(ctx.test_files):
        if point in known:
            continue
        findings.append(
            Finding(
                checker=CHECKER,
                path=src.relpath,
                line=line,
                message=(
                    f"test references crash point {point!r} which no "
                    "package code declares — dead coverage reads as "
                    "coverage; drop it or fix the name"
                ),
                symbol="stale-point",
                detail=point,
            )
        )
    return findings
