"""Checker 6: fenced-write taint — no raw apiserver writes inside a
rollout lease bracket.

PR 4's contract: the rollout lease is a single-writer fence. Once
``RolloutLease.acquire()`` succeeds, every apiserver WRITE the
orchestrator performs must flow through ``FencedKube``, whose per-write
validity check turns a lost lease into ``RolloutFenced`` instead of a
silent write into a pool a successor now owns. A raw-client write
reachable inside the bracket bypasses the CAS fencing — the exact bug
class that lets two orchestrators flip the same pool.

Two rules:

- **self-fencing classes** (``RollingReconfigurator``: ``__init__``
  wraps its client in ``FencedKube`` when a lease is present): every
  write-method call anywhere in the class must go through ``self.api``
  — the one attribute the wrap covers. A write through any other
  receiver (a stashed raw client, a fresh constructor) is a finding.
- **lease brackets** (any function that constructs a ``RolloutLease``
  and acquires it — ``ctl.py`` today): from ``lease.acquire()`` to
  ``lease.release()`` (may-analysis over the CFG — if ANY path reaches
  the write with the bracket open, it's a finding), a write-method call
  on the raw client, or a call handing the raw client to a function or
  constructor that (transitively) writes through that parameter, is an
  error. Handing the client to a self-fencing class WITH the lease is
  the sanctioned pattern; the lease machinery itself
  (``rollout_state.py``) is the fence, not a client of it.

Resolution limits are the engine's (lint/flow.py): cross-module calls
resolve by unique name, dynamic dispatch doesn't resolve and degrades
to a finding, ``# cclint: unfenced-ok(<reason>)`` waives a line.
"""

from __future__ import annotations

import ast

from tpu_cc_manager.lint import flow
from tpu_cc_manager.lint.base import Finding, LintContext, SourceFile

CHECKER = "fenced"

#: KubeApi methods that mutate apiserver state. Reads may bypass the
#: fence (a stale read is safe; a stale write is the bug).
WRITE_METHODS = frozenset((
    "patch_node_labels",
    "patch_node_annotations",
    "patch_node_taints",
    "create_event",
    "create_lease",
    "update_lease",
    "delete_lease",
    "delete_node",
))

#: The lease machinery itself — its writes ARE the fence.
MECHANISM_FILES = ("tpu_cc_manager/ccmanager/rollout_state.py",)


def _write_call(call: ast.Call) -> str | None:
    fn = call.func
    if isinstance(fn, ast.Attribute) and fn.attr in WRITE_METHODS:
        return fn.attr
    return None


def _is_fencedkube_call(call: ast.Call) -> bool:
    kn = flow.call_name(call)
    return kn is not None and kn[1] == "FencedKube"


def _is_self_attr(expr: ast.expr, attr: str) -> bool:
    return (
        isinstance(expr, ast.Attribute)
        and expr.attr == attr
        and isinstance(expr.value, ast.Name)
        and expr.value.id == "self"
    )


class _PackageIndex:
    """Name-keyed package map for the cross-module hops this checker
    needs (constructor calls in ctl.py resolve classes in rolling.py).
    Duplicate names across modules resolve to nothing — conservative."""

    def __init__(self, files: list) -> None:
        self.functions: dict[str, tuple[SourceFile, ast.FunctionDef]] = {}
        self.classes: dict[str, tuple[SourceFile, ast.ClassDef]] = {}
        fn_dupes: set[str] = set()
        cls_dupes: set[str] = set()
        for src in files:
            for node in src.tree.body:
                if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    if node.name in self.functions:
                        fn_dupes.add(node.name)
                    self.functions[node.name] = (src, node)
                elif isinstance(node, ast.ClassDef):
                    if node.name in self.classes:
                        cls_dupes.add(node.name)
                    self.classes[node.name] = (src, node)
        for name in fn_dupes:
            self.functions.pop(name, None)
        for name in cls_dupes:
            self.classes.pop(name, None)
        self._writes_memo: dict[tuple[str, str], set[str]] = {}

    # -- summaries: which params does a callee write through? --------------

    def fn_writes_through(self, name: str) -> set[str]:
        """Param names of module-level function ``name`` through which a
        write-method call is reachable (transitive, name-resolved)."""
        key = ("fn", name)
        if key in self._writes_memo:
            return self._writes_memo[key]
        self._writes_memo[key] = set()  # recursion guard
        entry = self.functions.get(name)
        if entry is None:
            return set()
        src, node = entry
        params = _param_names(node)
        out = self._writes_in_body(node, set(params))
        self._writes_memo[key] = out
        return out

    def cls_writes_through(self, name: str) -> set[str]:
        """__init__ param names of class ``name`` through which a write
        is reachable: written directly in __init__, or stored on self
        and written by any method."""
        key = ("cls", name)
        if key in self._writes_memo:
            return self._writes_memo[key]
        self._writes_memo[key] = set()
        entry = self.classes.get(name)
        if entry is None:
            return set()
        src, cls = entry
        init = next(
            (
                n for n in cls.body
                if isinstance(n, ast.FunctionDef) and n.name == "__init__"
            ),
            None,
        )
        if init is None:
            return set()
        params = set(_param_names(init)) - {"self"}
        out = self._writes_in_body(init, params)
        # Param stored to a self attribute some method writes through.
        stored: dict[str, str] = {}  # attr -> param
        for node in ast.walk(init):
            if (
                isinstance(node, ast.Assign)
                and len(node.targets) == 1
                and isinstance(node.targets[0], ast.Attribute)
                and isinstance(node.targets[0].value, ast.Name)
                and node.targets[0].value.id == "self"
                and isinstance(node.value, ast.Name)
                and node.value.id in params
            ):
                stored[node.targets[0].attr] = node.value.id
        if stored:
            for method in cls.body:
                if not isinstance(method, ast.FunctionDef):
                    continue
                for call in flow.iter_calls(method):
                    m = _write_call(call)
                    if m is None:
                        continue
                    recv = call.func.value
                    if (
                        isinstance(recv, ast.Attribute)
                        and isinstance(recv.value, ast.Name)
                        and recv.value.id == "self"
                        and recv.attr in stored
                    ):
                        out.add(stored[recv.attr])
        self._writes_memo[key] = out
        return out

    def _writes_in_body(self, fn: ast.AST, params: set[str]) -> set[str]:
        out: set[str] = set()
        for call in flow.iter_calls(fn):
            m = _write_call(call)
            if m is not None:
                recv = call.func.value
                if isinstance(recv, ast.Name) and recv.id in params:
                    out.add(recv.id)
                continue
            kn = flow.call_name(call)
            if kn is None:
                continue
            _, name = kn
            through = self.fn_writes_through(name) | self.cls_writes_through(
                name
            )
            if not through:
                continue
            entry = self.functions.get(name) or self.classes.get(name)
            callee = _callable_def(entry)
            if callee is None:
                continue
            bound = _bind(callee, call)
            for p in through:
                arg = bound.get(p)
                if isinstance(arg, ast.Name) and arg.id in params:
                    out.add(arg.id)
        return out

    def is_self_fencing(self, name: str) -> bool:
        entry = self.classes.get(name)
        if entry is None:
            return False
        _, cls = entry
        for node in cls.body:
            if isinstance(node, ast.FunctionDef) and node.name == "__init__":
                return any(
                    _is_fencedkube_call(c) for c in flow.iter_calls(node)
                )
        return False


def _callable_def(entry):
    """The FunctionDef bound by a call to this name: the function
    itself, or a class's __init__."""
    if entry is None:
        return None
    _, node = entry
    if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
        return node
    for item in node.body:
        if isinstance(item, ast.FunctionDef) and item.name == "__init__":
            return item
    return None


def _param_names(fn) -> list[str]:
    a = fn.args
    return [p.arg for p in a.posonlyargs + a.args] + [
        p.arg for p in a.kwonlyargs
    ]


def _bind(fn, call: ast.Call) -> dict[str, ast.expr]:
    params = _param_names(fn)
    if params and params[0] in ("self", "cls"):
        params = params[1:]
    bound: dict[str, ast.expr] = {}
    for i, arg in enumerate(call.args):
        if isinstance(arg, ast.Starred):
            break
        if i < len(params):
            bound[params[i]] = arg
    for kw in call.keywords:
        if kw.arg is not None:
            bound[kw.arg] = kw.value
    return bound


def check(ctx: LintContext) -> list[Finding]:
    files = [f for f in ctx.files if f.relpath not in MECHANISM_FILES]
    index = _PackageIndex(files)
    findings: list[Finding] = []
    for src in files:
        for cls in [
            n for n in ast.walk(src.tree) if isinstance(n, ast.ClassDef)
        ]:
            if index.is_self_fencing(cls.name):
                findings.extend(_check_self_fencing_class(src, cls))
        findings.extend(_check_brackets(src, index))
    return findings


def _check_self_fencing_class(src: SourceFile, cls: ast.ClassDef) -> list[Finding]:
    findings: list[Finding] = []
    for method in cls.body:
        if not isinstance(method, ast.FunctionDef):
            continue
        for call in flow.iter_calls(method):
            m = _write_call(call)
            if m is None:
                continue
            recv = call.func.value
            if _is_self_attr(recv, "api"):
                continue
            line = call.lineno
            if src.annotation(
                line, "unfenced-ok",
                span_end=getattr(call, "end_lineno", line),
            ) is not None:
                continue
            findings.append(
                Finding(
                    checker=CHECKER,
                    path=src.relpath,
                    line=line,
                    message=(
                        f"{cls.name}.{method.name} calls .{m}() on "
                        f"{ast.unparse(recv)!r} — {cls.name} fences its "
                        "writes through self.api (FencedKube); a write "
                        "through any other client bypasses the lease CAS"
                    ),
                    symbol=f"{cls.name}.{method.name}",
                    detail=m,
                )
            )
    return findings


def _check_brackets(src: SourceFile, index: _PackageIndex) -> list[Finding]:
    findings: list[Finding] = []
    for fi_node, qualname in _functions_with_qualnames(src.tree):
        lease_vars = _lease_vars(fi_node)
        if not lease_vars:
            continue
        raw_names = _raw_client_names(fi_node, lease_vars)
        if not raw_names:
            continue
        findings.extend(
            _check_one_bracket(
                src, fi_node, qualname, lease_vars, raw_names, index
            )
        )
    return findings


def _functions_with_qualnames(tree: ast.Module):
    for node in tree.body:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            yield node, node.name
        elif isinstance(node, ast.ClassDef):
            for item in node.body:
                if isinstance(item, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    yield item, f"{node.name}.{item.name}"


def _lease_vars(fn) -> set[str]:
    """Names assigned from ``RolloutLease(...)`` in this function."""
    out: set[str] = set()
    for node in ast.walk(fn):
        if (
            isinstance(node, ast.Assign)
            and len(node.targets) == 1
            and isinstance(node.targets[0], ast.Name)
            and isinstance(node.value, ast.Call)
        ):
            kn = flow.call_name(node.value)
            if kn is not None and kn[1] == "RolloutLease":
                out.add(node.targets[0].id)
    return out


def _raw_client_names(fn, lease_vars: set[str]) -> set[str]:
    """The raw-client names of this function: whatever was handed to the
    RolloutLease constructor, plus an ``api`` parameter by convention."""
    raw: set[str] = set()
    for node in ast.walk(fn):
        if isinstance(node, ast.Call):
            kn = flow.call_name(node)
            if kn is not None and kn[1] == "RolloutLease" and node.args:
                first = node.args[0]
                if isinstance(first, ast.Name):
                    raw.add(first.id)
    for p in _param_names(fn):
        if p == "api":
            raw.add(p)
    return raw


def _check_one_bracket(
    src: SourceFile,
    fn,
    qualname: str,
    lease_vars: set[str],
    raw_names: set[str],
    index: _PackageIndex,
) -> list[Finding]:
    cfg = flow.build_cfg(fn)

    def lease_method_call(stmt, method_names) -> bool:
        for call in flow.stmt_calls(stmt):
            f = call.func
            if (
                isinstance(f, ast.Attribute)
                and f.attr in method_names
                and isinstance(f.value, ast.Name)
                and f.value.id in lease_vars
            ):
                return True
        return False

    # May-analysis: in-bracket if any path from an acquire reaches here
    # without passing a release.
    in_bracket: dict[int, bool] = {cfg.entry.idx: False}
    work = [cfg.entry.idx]
    while work:
        idx = work.pop()
        node = cfg.nodes[idx]
        state = in_bracket.get(idx, False)
        if node.stmt is not None:
            if lease_method_call(node.stmt, ("acquire",)):
                state = True
            if lease_method_call(node.stmt, ("release",)):
                state = False
        for s in node.succs:
            new = in_bracket.get(s, False) or state
            if new != in_bracket.get(s, False) or s not in in_bracket:
                in_bracket[s] = new
                work.append(s)

    findings: list[Finding] = []
    for node in cfg.nodes:
        if node.stmt is None or not in_bracket.get(node.idx, False):
            continue
        calls = list(flow.stmt_calls(node.stmt))
        # A closure/lambda DEFINED inside the bracket most plausibly
        # runs inside it (callbacks, hooks): scan its whole body too —
        # conservative, and the hole a callback-shaped bypass would
        # otherwise walk through.
        for sub in ast.walk(node.stmt):
            if isinstance(
                sub, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)
            ):
                calls.extend(flow.iter_calls(sub))
        unique: dict[int, ast.Call] = {}
        for c in calls:
            unique.setdefault(id(c), c)
        for call in unique.values():
            finding = _classify_bracket_call(
                src, qualname, call, raw_names, lease_vars, index
            )
            if finding is not None:
                findings.append(finding)
    return findings


def _classify_bracket_call(
    src: SourceFile,
    qualname: str,
    call: ast.Call,
    raw_names: set[str],
    lease_vars: set[str],
    index: _PackageIndex,
) -> Finding | None:
    line = call.lineno

    def waived() -> bool:
        return src.annotation(
            line, "unfenced-ok", span_end=getattr(call, "end_lineno", line)
        ) is not None

    m = _write_call(call)
    if m is not None:
        recv = call.func.value
        if isinstance(recv, ast.Name) and recv.id in raw_names:
            if waived():
                return None
            return Finding(
                checker=CHECKER,
                path=src.relpath,
                line=line,
                message=(
                    f"raw-client write .{m}() on {recv.id!r} inside the "
                    f"rollout lease bracket in {qualname} — route it "
                    "through FencedKube (or hand the client+lease to the "
                    "self-fencing orchestrator)"
                ),
                symbol=qualname,
                detail=m,
            )
        return None
    kn = flow.call_name(call)
    if kn is None:
        return None
    _, name = kn
    if name == "FencedKube":
        return None
    passes_raw = [
        a for a in list(call.args)
        + [kw.value for kw in call.keywords]
        if isinstance(a, ast.Name) and a.id in raw_names
    ]
    if not passes_raw:
        return None
    passes_lease = any(
        isinstance(a, ast.Name) and a.id in lease_vars
        for a in list(call.args) + [kw.value for kw in call.keywords]
    )
    if passes_lease and index.is_self_fencing(name):
        return None  # the sanctioned handoff: client + lease to a wrapper
    through = index.fn_writes_through(name) | index.cls_writes_through(name)
    if not through:
        return None
    entry = index.functions.get(name) or index.classes.get(name)
    callee = _callable_def(entry)
    if callee is None:
        return None
    bound = _bind(callee, call)
    for p in through:
        arg = bound.get(p)
        if isinstance(arg, ast.Name) and arg.id in raw_names:
            if waived():
                return None
            return Finding(
                checker=CHECKER,
                path=src.relpath,
                line=line,
                message=(
                    f"{qualname} hands the raw client to {name}() inside "
                    f"the lease bracket, and {name} writes through that "
                    "parameter — fence it (FencedKube) or pass the lease "
                    "so the callee self-fences"
                ),
                symbol=qualname,
                detail=name,
            )
    return None
