"""Checker 5: contract-surface drift.

The agent's outward contract is spread across four surfaces that only
stay consistent by discipline: the ``CC_*`` environment variables the
code reads, the env table in ``docs/operations.md``, the env the
DaemonSet manifest sets, the metric families the registry emits (which
must be exercised by the exposition lint's seeded render and documented),
and the ``cloud.google.com/tpu-cc.*`` / ``tpu-cc.gke.io`` label and
annotation keys — which must all come from ``labels.py`` (one module
owns the wire names) rather than inline literals.

Four sub-checks:

- **env-undocumented** — a ``CC_*`` env read anywhere in the package that
  does not appear in the docs/operations.md env table;
- **env-unread** — a ``CC_*`` env the daemonset sets that nothing reads
  (manifest drift: a typo'd or retired knob silently configuring nothing);
- **metric-drift** — a ``tpu_cc_*`` family declared in utils/metrics.py
  that the seeded exposition-lint render never emits (unseeded: a
  registry regression in that family would pass CI) or that no docs
  page mentions;
- **label-literal** — an inline ``cloud.google.com/tpu-cc*`` /
  ``tpu-cc.gke.io`` string outside labels.py (docstrings exempt).
"""

from __future__ import annotations

import ast
import re

from tpu_cc_manager.lint.base import Finding, LintContext

CHECKER = "surface"

ENV_RE = re.compile(r"^CC_[A-Z0-9_]+$")
DOCS_ENV_PATH = "docs/operations.md"
DAEMONSET_PATH = "deployments/manifests/daemonset.yaml"
METRICS_PATH = "tpu_cc_manager/utils/metrics.py"
LABELS_PATH = "tpu_cc_manager/labels.py"
DOC_PATHS = ("docs/observability.md", "docs/operations.md")
LABEL_PREFIXES = ("cloud.google.com/tpu-cc", "tpu-cc.gke.io")
_FAMILY_RE = re.compile(r"#\s(?:HELP|TYPE)\s(tpu_cc_[a-z0-9_]+)")
_DAEMONSET_ENV_RE = re.compile(r"-\s*name:\s*(CC_[A-Z0-9_]+)\b")


def _env_name_of(call: ast.Call) -> str | None:
    """The literal env name of an ``os.environ.get``/``os.getenv`` call
    (or None)."""
    fn = call.func
    is_env_get = (
        isinstance(fn, ast.Attribute)
        and fn.attr == "get"
        and isinstance(fn.value, ast.Attribute)
        and fn.value.attr == "environ"
    )
    is_getenv = isinstance(fn, ast.Attribute) and fn.attr == "getenv"
    if not (is_env_get or is_getenv) or not call.args:
        return None
    first = call.args[0]
    if isinstance(first, ast.Constant) and isinstance(first.value, str):
        return first.value
    return None


def _env_reads(ctx: LintContext) -> dict[str, tuple[str, int]]:
    """CC_* env name -> first (path, line) that reads it. Covers
    ``os.environ.get``, ``os.getenv``, ``os.environ[...]`` and env names
    bound to module constants ending in ``_ENV`` (the
    ``os.environ.get(OFFLINE_GRACE_ENV, ...)`` idiom)."""
    reads: dict[str, tuple[str, int]] = {}
    for src in ctx.files:
        for node in ast.walk(src.tree):
            name: str | None = None
            if isinstance(node, ast.Call):
                name = _env_name_of(node)
            elif (
                isinstance(node, ast.Subscript)
                and isinstance(node.value, ast.Attribute)
                and node.value.attr == "environ"
                and isinstance(node.slice, ast.Constant)
                and isinstance(node.slice.value, str)
            ):
                name = node.slice.value
            elif (
                isinstance(node, ast.Assign)
                and len(node.targets) == 1
                and isinstance(node.targets[0], ast.Name)
                and node.targets[0].id.endswith("_ENV")
                and isinstance(node.value, ast.Constant)
                and isinstance(node.value.value, str)
            ):
                name = node.value.value
            if name and ENV_RE.match(name):
                reads.setdefault(name, (src.relpath, node.lineno))
    return reads


def _docstring_constants(tree: ast.Module) -> set[int]:
    """ids of Constant nodes that are docstrings (exempt from the
    label-literal rule — documentation may name the wire keys)."""
    out: set[int] = set()
    for node in ast.walk(tree):
        if isinstance(
            node, (ast.Module, ast.ClassDef, ast.FunctionDef, ast.AsyncFunctionDef)
        ):
            body = getattr(node, "body", [])
            if (
                body
                and isinstance(body[0], ast.Expr)
                and isinstance(body[0].value, ast.Constant)
                and isinstance(body[0].value.value, str)
            ):
                out.add(id(body[0].value))
    return out


def check(
    ctx: LintContext, seeded_render_text: str | None = None
) -> list[Finding]:
    findings: list[Finding] = []

    # -- env reads vs the docs table ------------------------------------
    docs = ctx.read_text(DOCS_ENV_PATH)
    reads = _env_reads(ctx)
    if docs is not None:
        for name in sorted(reads):
            if name not in docs:
                path, line = reads[name]
                findings.append(
                    Finding(
                        checker=CHECKER,
                        path=path,
                        line=line,
                        message=(
                            f"env {name} is read here but missing from the "
                            f"{DOCS_ENV_PATH} env table"
                        ),
                        symbol="env-undocumented",
                        detail=name,
                    )
                )

    # -- daemonset env vs code reads ------------------------------------
    daemonset = ctx.read_text(DAEMONSET_PATH)
    if daemonset is not None:
        for i, line_text in enumerate(daemonset.splitlines(), start=1):
            m = _DAEMONSET_ENV_RE.search(line_text)
            if m and m.group(1) not in reads:
                findings.append(
                    Finding(
                        checker=CHECKER,
                        path=DAEMONSET_PATH,
                        line=i,
                        message=(
                            f"daemonset sets {m.group(1)} but nothing in "
                            "the package reads it (manifest drift)"
                        ),
                        symbol="env-unread",
                        detail=m.group(1),
                    )
                )

    # -- metric families: seeded + documented ---------------------------
    metrics_src = ctx.file(METRICS_PATH)
    if metrics_src is not None:
        families = sorted(set(_FAMILY_RE.findall(metrics_src.source)))
        seeded_text = (
            seeded_render_text if seeded_render_text is not None
            else seeded_render()
        )
        doc_text = "\n".join(ctx.read_text(p) or "" for p in DOC_PATHS)
        for family in families:
            if seeded_text is not None and family not in seeded_text:
                findings.append(
                    Finding(
                        checker=CHECKER,
                        path=METRICS_PATH,
                        line=1,
                        message=(
                            f"metric family {family} is never emitted by "
                            "the exposition lint's seeded registry render "
                            "(lint/expo.py _seeded_registry_text) — seed it"
                        ),
                        symbol="metric-unseeded",
                        detail=family,
                    )
                )
            if doc_text and family not in doc_text:
                findings.append(
                    Finding(
                        checker=CHECKER,
                        path=METRICS_PATH,
                        line=1,
                        message=(
                            f"metric family {family} is documented in "
                            f"neither of {', '.join(DOC_PATHS)}"
                        ),
                        symbol="metric-undocumented",
                        detail=family,
                    )
                )

    # -- inline label-key literals --------------------------------------
    for src in ctx.files:
        if src.relpath == LABELS_PATH or src.relpath.startswith(
            "tpu_cc_manager/lint/"
        ):
            # labels.py owns the wire names; the lint package holds the
            # prefixes as checker data, not as wire usage.
            continue
        docstrings = _docstring_constants(src.tree)
        for node in ast.walk(src.tree):
            if (
                isinstance(node, ast.Constant)
                and isinstance(node.value, str)
                and any(p in node.value for p in LABEL_PREFIXES)
                and id(node) not in docstrings
            ):
                findings.append(
                    Finding(
                        checker=CHECKER,
                        path=src.relpath,
                        line=node.lineno,
                        message=(
                            f"inline label-key literal {node.value!r} — "
                            "wire names come from labels.py, import the "
                            "constant instead"
                        ),
                        symbol="label-literal",
                        detail=node.value[:60],
                    )
                )
    return findings


def seeded_render() -> str | None:
    """The exposition lint's seeded live-registry render (None if the
    registry cannot be imported — fixture contexts in unit tests). The
    driver calls this once and shares the text between this checker and
    the exposition pass."""
    try:
        from tpu_cc_manager.lint import expo

        return expo._seeded_registry_text()
    except Exception:  # pragma: no cover - import-environment dependent
        return None
