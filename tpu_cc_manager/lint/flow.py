"""Flow machinery for the v2 checkers: per-function CFGs and a
package-wide call graph (stdlib ``ast`` only).

The v1 checkers were lexical — an allowlist of call sites, a ``with``
block visible inside one function. The invariants they guard span call
chains (the transition intent begins in ``_apply_with_eviction`` and the
reset it journals runs two frames deeper) and threads (a helper touched
lock-free from one of its three callers). This module gives checkers the
two structures those proofs need:

- :func:`build_cfg` — a statement-granularity control-flow graph per
  function, with branch-polarity labels on ``if`` edges (so analyses can
  refine ``x is None`` tests), exception edges from ``try`` bodies to
  their handlers, and return-through-``finally`` threading.
- :class:`CallIndex` — resolution of ``self.method(...)`` calls to
  methods of the same class and bare-name calls to functions of the same
  module, in both directions (callees of f / call sites of f).

Documented limitations (see docs/cclint.md):

- **Dynamic dispatch is unresolved.** ``self.m()`` resolves only within
  the lexical class; inherited/overridden methods, ``getattr``, bound
  references passed around, and cross-module calls are not followed.
  Analyses must degrade to "unknown" (and findings) there, never to
  silent cleanliness.
- **Exception edges are approximate.** Any statement in a ``try`` body
  may jump to any of its handlers; exceptions raised inside handlers,
  ``else`` or ``finally`` blocks propagate straight to the exceptional
  exit. A ``return`` inside ``try/finally`` runs the innermost
  ``finally`` body before exiting (outer finallies are not chained).
- **Paths are merged, not enumerated.** The CFG supports dataflow over
  paths (dominance-style must/may facts), not path-sensitive predicates
  beyond single ``if <name> [is [not] None]`` refinements.
"""

from __future__ import annotations

import ast
from dataclasses import dataclass, field


@dataclass(eq=False)
class Node:
    """One CFG node: a statement (or ExceptHandler), or a synthetic
    entry/exit. ``branch`` labels this node's outgoing edges with a
    polarity ("true"/"false") when the node is a conditional test.
    Identity semantics (``eq=False``): hashable, one object per node."""

    idx: int
    stmt: ast.AST | None
    kind: str = "stmt"  # entry | exit | raise-exit | stmt | handler
    succs: set[int] = field(default_factory=set)
    preds: set[int] = field(default_factory=set)
    branch: dict[int, str] = field(default_factory=dict)


class CFG:
    """Statement-level control-flow graph of one function body.

    ``exit`` joins every normal completion (explicit returns, implicit
    end-of-body) — after any ``finally`` bodies on the way out.
    ``raise_exit`` joins escaping exceptions and is where crash-exempt
    paths end (a modeled SIGKILL is a BaseException; the journal
    contract's "non-crash exits" are exactly the edges into ``exit``).
    """

    def __init__(self, fn: ast.AST) -> None:
        self.fn = fn
        self.nodes: list[Node] = []
        self.entry = self._new(None, "entry")
        self.exit = self._new(None, "exit")
        self.raise_exit = self._new(None, "raise-exit")

    def _new(self, stmt: ast.AST | None, kind: str = "stmt") -> Node:
        n = Node(len(self.nodes), stmt, kind)
        self.nodes.append(n)
        return n


class _Builder:
    def __init__(self, fn: ast.AST) -> None:
        self.cfg = CFG(fn)
        # (break-target collector, continue-target node) per active loop.
        self.loop_stack: list[tuple[set[Node], Node]] = []
        # Innermost-first: each entry collects the Return nodes that must
        # run this finally body before reaching exit.
        self.finally_stack: list[dict] = []
        # Nodes with a pending polarity for their NEXT outgoing edge
        # (the implicit false-edge of an if without an else).
        self._pending_label: dict[int, str] = {}

    def _link(self, a: Node, b: Node) -> None:
        a.succs.add(b.idx)
        b.preds.add(a.idx)
        lbl = self._pending_label.get(a.idx)
        if lbl is not None and b.idx not in a.branch:
            a.branch[b.idx] = lbl

    def _link_all(self, preds: set[Node], b: Node) -> None:
        for a in preds:
            self._link(a, b)

    def build(self) -> CFG:
        frontier = self._body(self.cfg.fn.body, {self.cfg.entry})
        self._link_all(frontier, self.cfg.exit)
        return self.cfg

    def _body(self, stmts: list[ast.stmt], preds: set[Node]) -> set[Node]:
        frontier = set(preds)
        for stmt in stmts:
            if not frontier:
                # Unreachable code after return/raise/break: still build
                # nodes (a checker may want to look at them) but leave
                # them disconnected.
                frontier = set()
            frontier = self._stmt(stmt, frontier)
        return frontier

    def _stmt(self, stmt: ast.stmt, preds: set[Node]) -> set[Node]:
        cfg = self.cfg
        if isinstance(stmt, ast.If):
            node = cfg._new(stmt)
            self._link_all(preds, node)
            before = set(node.succs)
            body_frontier = self._body(stmt.body, {node})
            for s in node.succs - before:
                node.branch[s] = "true"
            if stmt.orelse:
                before2 = set(node.succs)
                else_frontier = self._body(stmt.orelse, {node})
                for s in node.succs - before2:
                    node.branch.setdefault(s, "false")
                return body_frontier | else_frontier
            # No else: the fall-through edge (created by our caller when
            # it links the next statement) carries the false polarity.
            self._pending_label[node.idx] = "false"
            return body_frontier | {node}
        if isinstance(stmt, (ast.While, ast.For, ast.AsyncFor)):
            node = cfg._new(stmt)
            self._link_all(preds, node)
            breaks: set[Node] = set()
            self.loop_stack.append((breaks, node))
            body_frontier = self._body(stmt.body, {node})
            self.loop_stack.pop()
            self._link_all(body_frontier, node)  # back edge
            else_frontier = (
                self._body(stmt.orelse, {node}) if stmt.orelse else {node}
            )
            return else_frontier | breaks
        if isinstance(stmt, (ast.With, ast.AsyncWith)):
            node = cfg._new(stmt)
            self._link_all(preds, node)
            return self._body(stmt.body, {node})
        if isinstance(stmt, ast.Try):
            return self._try(stmt, preds)
        if isinstance(stmt, ast.Return):
            node = cfg._new(stmt)
            self._link_all(preds, node)
            if self.finally_stack:
                self.finally_stack[-1]["returns"].add(node)
            else:
                self._link(node, cfg.exit)
            return set()
        if isinstance(stmt, ast.Raise):
            node = cfg._new(stmt)
            self._link_all(preds, node)
            # Raise reaches the enclosing handlers via the body-node ->
            # handler edges added by _try; if none catch, it escapes.
            self._link(node, cfg.raise_exit)
            return set()
        if isinstance(stmt, ast.Break):
            node = cfg._new(stmt)
            self._link_all(preds, node)
            if self.loop_stack:
                self.loop_stack[-1][0].add(node)
            return set()
        if isinstance(stmt, ast.Continue):
            node = cfg._new(stmt)
            self._link_all(preds, node)
            if self.loop_stack:
                self._link(node, self.loop_stack[-1][1])
            return set()
        # Plain statement (assign, expr, assert, nested def, ...).
        node = cfg._new(stmt)
        self._link_all(preds, node)
        return {node}

    def _try(self, stmt: ast.Try, preds: set[Node]) -> set[Node]:
        cfg = self.cfg
        if stmt.finalbody:
            self.finally_stack.append({"returns": set()})
        start = len(cfg.nodes)
        body_frontier = self._body(stmt.body, preds)
        body_nodes = [
            n for n in cfg.nodes[start:] if n.kind in ("stmt", "handler")
        ]
        handler_frontiers: list[set[Node]] = []
        handler_entries: list[Node] = []
        for h in stmt.handlers:
            hn = cfg._new(h, "handler")
            handler_entries.append(hn)
            handler_frontiers.append(self._body(h.body, {hn}))
        # An exception may arise at any statement of the body (including
        # ones inside nested structures — over-approximation) and jump to
        # any handler; which handler matches is type-dependent and
        # unresolved here.
        for bn in body_nodes:
            for hn in handler_entries:
                self._link(bn, hn)
        else_frontier = (
            self._body(stmt.orelse, body_frontier)
            if stmt.orelse else body_frontier
        )
        merged = set(else_frontier)
        for f in handler_frontiers:
            merged |= f
        if stmt.finalbody:
            info = self.finally_stack.pop()
            fin_preds = merged | info["returns"]
            fin_frontier = self._body(stmt.finalbody, fin_preds)
            if info["returns"]:
                # Paths that entered the finally via a return leave the
                # function after it. (They also share the fall-through
                # edge to the next statement — a path over-approximation;
                # must-analyses stay sound, may-analyses stay complete.)
                for n in fin_frontier:
                    self._link(n, cfg.exit)
            return fin_frontier
        return merged


def build_cfg(fn: ast.AST) -> CFG:
    """CFG of a FunctionDef/AsyncFunctionDef body."""
    return _Builder(fn).build()


# ---------------------------------------------------------------------------
# Call resolution
# ---------------------------------------------------------------------------


def call_name(call: ast.Call) -> tuple[str, str] | None:
    """(kind, name) of a call: ("self", m) for ``self.m(...)``,
    ("bare", f) for ``f(...)``, ("attr", a) for ``<expr>.a(...)``;
    None for anything else (subscripts, lambdas, ...)."""
    fn = call.func
    if isinstance(fn, ast.Name):
        return ("bare", fn.id)
    if isinstance(fn, ast.Attribute):
        if isinstance(fn.value, ast.Name) and fn.value.id == "self":
            return ("self", fn.attr)
        return ("attr", fn.attr)
    return None


@dataclass(eq=False)
class FunctionInfo:
    """One function/method in the package, with enough context to
    resolve its intra-class and intra-module calls. Identity semantics
    (``eq=False``): one object per definition, hashable, comparable
    with ``is``."""

    src: object  # lint.base.SourceFile
    node: ast.FunctionDef | ast.AsyncFunctionDef
    cls: ast.ClassDef | None  # enclosing class (methods) or None
    qualname: str  # Class.method or function

    @property
    def params(self) -> list[str]:
        a = self.node.args
        names = [p.arg for p in a.posonlyargs + a.args]
        if self.cls is not None and names and names[0] in ("self", "cls"):
            names = names[1:]
        names += [p.arg for p in a.kwonlyargs]
        return names

    def param_default(self, name: str) -> ast.expr | None:
        """The default expression of parameter ``name`` (None if it has
        no default)."""
        a = self.node.args
        pos = a.posonlyargs + a.args
        # defaults align with the tail of pos
        for p, d in zip(pos[len(pos) - len(a.defaults):], a.defaults):
            if p.arg == name:
                return d
        for p, d in zip(a.kwonlyargs, a.kw_defaults):
            if p.arg == name and d is not None:
                return d
        return None

    def bind_args(self, call: ast.Call) -> dict[str, ast.expr]:
        """Map parameter name -> argument expression for ``call``
        (best-effort positional/keyword binding; *args/**kwargs are
        ignored — a checker sees those params as unresolved)."""
        params = self.params
        bound: dict[str, ast.expr] = {}
        for i, arg in enumerate(call.args):
            if isinstance(arg, ast.Starred):
                break
            if i < len(params):
                bound[params[i]] = arg
        for kw in call.keywords:
            if kw.arg is not None:
                bound[kw.arg] = kw.value
        return bound


class CallIndex:
    """Both directions of the package call graph, at the resolution the
    engine supports: ``self.m(...)`` within the lexical class and bare
    ``f(...)`` within the module."""

    def __init__(self, files: list) -> None:
        self.functions: dict[tuple[str, str], FunctionInfo] = {}
        # (relpath, qualname) of caller -> list of (callee FunctionInfo, Call)
        self._files = files
        for src in files:
            self._index_file(src)

    def _index_file(self, src) -> None:
        for node in src.tree.body:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                fi = FunctionInfo(src, node, None, node.name)
                self.functions[(src.relpath, node.name)] = fi
            elif isinstance(node, ast.ClassDef):
                for item in node.body:
                    if isinstance(item, (ast.FunctionDef, ast.AsyncFunctionDef)):
                        q = f"{node.name}.{item.name}"
                        self.functions[(src.relpath, q)] = FunctionInfo(
                            src, item, node, q
                        )

    def resolve(self, caller: FunctionInfo, call: ast.Call) -> FunctionInfo | None:
        """The FunctionInfo a call resolves to, or None (dynamic
        dispatch, cross-module, builtin — the documented blind spots)."""
        kn = call_name(call)
        if kn is None:
            return None
        kind, name = kn
        if kind == "self" and caller.cls is not None:
            return self.functions.get(
                (caller.src.relpath, f"{caller.cls.name}.{name}")
            )
        if kind == "bare":
            return self.functions.get((caller.src.relpath, name))
        return None

    def call_sites(self, target: FunctionInfo) -> list[tuple[FunctionInfo, ast.Call]]:
        """Every resolvable call site of ``target`` in the package:
        (caller, call) pairs. Same resolution limits as :meth:`resolve`."""
        out: list[tuple[FunctionInfo, ast.Call]] = []
        for fi in self.functions.values():
            if fi.src.relpath != target.src.relpath:
                continue
            for call in iter_calls(fi.node):
                if self.resolve(fi, call) is target:
                    out.append((fi, call))
        return out


def iter_calls(fn: ast.AST):
    """Every ast.Call in a function body, including ones inside nested
    defs/lambdas/comprehensions (a call site in a closure is still a
    call site)."""
    for node in ast.walk(fn):
        if isinstance(node, ast.Call):
            yield node


def stmt_calls(stmt: ast.AST):
    """Calls belonging to exactly one CFG node: for a compound statement
    (if/while/for/with/try) only the header expressions — its body
    statements are their own CFG nodes — and never inside nested
    function bodies (those run later, under their own analysis)."""

    def walk(node: ast.AST):
        for child in ast.iter_child_nodes(node):
            if isinstance(
                child,
                (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda, ast.stmt),
            ):
                continue
            if isinstance(child, ast.Call):
                yield child
            yield from walk(child)

    roots: list[ast.AST] = []
    if isinstance(stmt, (ast.If, ast.While)):
        roots = [stmt.test]
    elif isinstance(stmt, (ast.For, ast.AsyncFor)):
        roots = [stmt.target, stmt.iter]
    elif isinstance(stmt, (ast.With, ast.AsyncWith)):
        roots = [item.context_expr for item in stmt.items]
    elif isinstance(stmt, ast.Try):
        roots = []
    elif isinstance(
        stmt, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)
    ):
        roots = []
    else:
        roots = [stmt]
    for root in roots:
        if isinstance(root, ast.Call):
            yield root
        yield from walk(root)
