"""Checker 4: journal-before-reset.

PR 5's contract: every hardware-effecting operation journals an intent
(``intent_journal.begin`` fsync'd to disk) BEFORE the first disruptive
step, so a SIGKILL at any point replays to exactly-one-reset-per-chip.
A new call site that resets chips or bounces the runtime without the
write-ahead intent silently reopens the double-reset window — so direct
calls to ``<...>.backend.reset(...)`` / ``<...>.backend.restart_runtime()``
are only legal at the allowlisted, journal-bracketed sites below.

The device layer itself (``tpudev/``) is out of scope: a backend
composing its own primitives (the contract's default ``restart_runtime``
delegating to ``reset``) is inside the bracket its caller journaled.
"""

from __future__ import annotations

import ast

from tpu_cc_manager.lint.base import Finding, LintContext, qualname_of

CHECKER = "journal"

EXCLUDED_DIRS = ("tpu_cc_manager/tpudev/",)

#: fingerprint -> why this call site is legal. Adding a site here is a
#: reviewed act: the new caller must journal an intent first (or prove it
#: runs inside an existing bracket).
ALLOWLIST: dict[str, str] = {
    # The phased transition: _begin_transition_intent ran (write-ahead,
    # before the drain on the pipelined path) and the reset phase is
    # marked on the txn immediately before the call.
    "journal:tpu_cc_manager/ccmanager/manager.py:CCManager._apply_direct:reset": (
        "inside the journaled transition bracket (PHASE_RESET marked)"
    ),
    # Remediation ladder rungs journal a KIND_REMEDIATION intent before
    # the hardware action (RemediationLadder._journal_hardware_intent).
    "journal:tpu_cc_manager/ccmanager/remediation.py:RemediationLadder._device_reset:reset": (
        "journaled via _journal_hardware_intent (KIND_REMEDIATION intent)"
    ),
    "journal:tpu_cc_manager/ccmanager/remediation.py:RemediationLadder._runtime_restart:restart_runtime": (
        "journaled via _journal_hardware_intent (KIND_REMEDIATION intent)"
    ),
}


def _is_backend_hw_call(call: ast.Call) -> str | None:
    """``<expr>.backend.reset(...)`` / ``.restart_runtime(...)`` (or a
    bare ``backend.<op>(...)``) -> the op name, else None."""
    fn = call.func
    if not isinstance(fn, ast.Attribute) or fn.attr not in (
        "reset", "restart_runtime"
    ):
        return None
    base = fn.value
    if isinstance(base, ast.Name) and base.id == "backend":
        return fn.attr
    if isinstance(base, ast.Attribute) and base.attr == "backend":
        return fn.attr
    return None


def check(ctx: LintContext) -> list[Finding]:
    findings: list[Finding] = []
    for src in ctx.files:
        if src.relpath.startswith(EXCLUDED_DIRS):
            continue
        stack: list[ast.AST] = []

        def visit(node: ast.AST) -> None:
            is_scope = isinstance(
                node, (ast.ClassDef, ast.FunctionDef, ast.AsyncFunctionDef)
            )
            if is_scope:
                stack.append(node)
            if isinstance(node, ast.Call):
                op = _is_backend_hw_call(node)
                if op is not None:
                    symbol = qualname_of(stack)
                    f = Finding(
                        checker=CHECKER,
                        path=src.relpath,
                        line=node.lineno,
                        message=(
                            f"backend.{op} in {symbol} is not an "
                            "allowlisted journaled call site — journal an "
                            "intent first, then add the site to "
                            "lint/journal.py ALLOWLIST with its bracket"
                        ),
                        symbol=symbol,
                        detail=op,
                    )
                    if f.fingerprint not in ALLOWLIST:
                        findings.append(f)
            for child in ast.iter_child_nodes(node):
                visit(child)
            if is_scope:
                stack.pop()

        visit(src.tree)
    return findings
