"""Checker 4: journal-before-reset — a dominance proof, not an allowlist.

PR 5's contract: every hardware-effecting operation journals an intent
(``intent_journal.begin``, fsync'd to disk) BEFORE the first disruptive
step, so a SIGKILL at any point replays to exactly-one-reset-per-chip —
and the intent is closed (commit/abort) on every non-crash exit, so
replay never resolves an intent the code already resolved.

v1 enforced the lexical shadow of this: a reviewed allowlist of call
sites. v2 proves the bracket on the control-flow graph (lint/flow.py):

- **begin-dominates-reset**: every ``backend.reset`` /
  ``backend.restart_runtime`` call must have an intent-begin on every
  CFG path from the function entry to the call. The proof is
  interprocedural: a journal token received as a parameter carries its
  callers' proof (``_apply_direct(txn=...)`` is proven through
  ``_apply_with_eviction``'s write-ahead begin plus the
  ``if txn is None: txn = begin()`` merge), and begin/close wrappers
  (``_journal_begin``, ``_journal_hardware_intent``) are discovered from
  the call graph, not hardcoded.
- **close-postdominates-exit**: a token begun in a function must be
  closed — or returned to the caller, who is then checked — on every
  path into the normal exit. Crash exits (escaping BaseException, bare
  ``raise``) are exempt: an OPEN intent at a crash is exactly the record
  replay recovers from.

Degradation is loud: a token that reaches a hardware call as "maybe
journaled" (one caller proven, one not; dynamic dispatch; a *args call
the binder can't see) is a finding. Waivers, in escalating order of
reviewer attention: ``# cclint: journal-ok(<reason>)`` on the hardware
call line, ``# cclint: intent-open-ok(<reason>)`` on a begin whose
token deliberately stays open (none needed today), and the ALLOWLIST
below — the waiver of last resort, now empty; adding an entry means the
engine cannot see a bracket a human has re-verified.

The device layer (``tpudev/``) is out of scope — a backend composing
its own primitives runs inside its caller's bracket — and the journal
implementation itself (``intent_journal.py``) is the mechanism, not a
client.
"""

from __future__ import annotations

import ast

from tpu_cc_manager.lint import flow
from tpu_cc_manager.lint.base import (
    Finding,
    LintContext,
    SourceFile,
    qualname_of,
)

CHECKER = "journal"

EXCLUDED_DIRS = ("tpu_cc_manager/tpudev/",)
EXCLUDED_FILES = ("tpu_cc_manager/ccmanager/intent_journal.py",)

#: fingerprint -> reason. The waiver of LAST resort: an entry asserts a
#: human re-verified a bracket the flow engine cannot prove. Prefer
#: making the bracket provable (thread the token, begin unconditionally)
#: or a `# cclint: journal-ok(reason)` line waiver.
ALLOWLIST: dict[str, str] = {}

# Token states (powerset lattice; merge = union). Open tokens carry the
# statically-visible intent KIND ("open:<kind>", "open:?" when the kind
# is not a literal at the begin site): a drain-bracket token must not
# prove a hardware call — replay of a drain intent readmits components,
# it does not resolve a reset.
OPEN_PREFIX = "open:"
NONE = "none"      # literal None
CLOSED = "closed"  # committed/aborted, or ownership handed off
OTHER = "other"    # anything the engine can't classify

#: Intent kinds whose replay does NOT cover hardware effects.
NON_HW_KINDS = ("drain",)


def _is_open(value: str) -> bool:
    return value.startswith(OPEN_PREFIX)


def _open_state_of(call: ast.Call) -> frozenset:
    """The token state a begin call produces: open, tagged with the
    first literal string argument when there is one (the kind for the
    primitive and for the pass-through wrappers; an unrelated literal
    only matters if it collides with a non-hardware kind name, which is
    the conservative direction)."""
    kind = "?"
    if call.args:
        first = call.args[0]
        if isinstance(first, ast.Constant) and isinstance(first.value, str):
            kind = first.value
    return frozenset((f"{OPEN_PREFIX}{kind}",))


def _proves_hw(state: frozenset) -> bool:
    """A token state proves a hardware bracket when it is definitely
    open (no path where it is None/closed/unknown) and its kind is not
    a known non-hardware bracket."""
    if len(state) != 1:
        return False
    (value,) = state
    return _is_open(value) and value[len(OPEN_PREFIX):] not in NON_HW_KINDS


def _is_backend_hw_call(call: ast.Call) -> str | None:
    """``<expr>.backend.reset(...)`` / ``.restart_runtime(...)`` (or a
    bare ``backend.<op>(...)``) -> the op name, else None."""
    fn = call.func
    if not isinstance(fn, ast.Attribute) or fn.attr not in (
        "reset", "restart_runtime"
    ):
        return None
    base = fn.value
    if isinstance(base, ast.Name) and base.id == "backend":
        return fn.attr
    if isinstance(base, ast.Attribute) and base.attr == "backend":
        return fn.attr
    return None


def _chain_names(expr: ast.expr) -> set[str]:
    """Attribute/Name identifiers along a dotted chain."""
    out: set[str] = set()
    while isinstance(expr, ast.Attribute):
        out.add(expr.attr)
        expr = expr.value
    if isinstance(expr, ast.Name):
        out.add(expr.id)
    return out


def _is_begin_primitive(call: ast.Call) -> bool:
    """``<...intents...>.begin(...)`` — the IntentJournal write-ahead."""
    fn = call.func
    return (
        isinstance(fn, ast.Attribute)
        and fn.attr == "begin"
        and "intents" in _chain_names(fn.value)
    )


def _close_primitive_arg(call: ast.Call) -> str | None:
    """The token variable a ``<...intents...>.commit/.abort(tok, ...)``
    call closes (None when not a close primitive or the arg isn't a
    plain name)."""
    fn = call.func
    if (
        isinstance(fn, ast.Attribute)
        and fn.attr in ("commit", "abort")
        and "intents" in _chain_names(fn.value)
        and call.args
        and isinstance(call.args[0], ast.Name)
    ):
        return call.args[0].id
    return None


class _Engine:
    """Per-context analysis state: summaries (beginners/closers), memoized
    per-function dataflow, and demand-driven parameter token states."""

    def __init__(self, ctx: LintContext) -> None:
        self.files = [
            f for f in ctx.files
            if not f.relpath.startswith(EXCLUDED_DIRS)
            and f.relpath not in EXCLUDED_FILES
        ]
        self.index = flow.CallIndex(self.files)
        self.beginners: set[flow.FunctionInfo] = set()
        self.closers: dict[flow.FunctionInfo, set[str]] = {}
        self._compute_summaries()
        self._analysis: dict[flow.FunctionInfo, dict[int, dict]] = {}
        self._param_memo: dict[tuple[flow.FunctionInfo, str], frozenset] = {}
        self._param_inflight: set[tuple[flow.FunctionInfo, str]] = set()
        self._token_param_memo: dict[flow.FunctionInfo, set[str]] = {}
        self._cfgs: dict[flow.FunctionInfo, flow.CFG] = {}

    # -- summaries ---------------------------------------------------------

    def _compute_summaries(self) -> None:
        """Fixpoint over the call graph: a *beginner* returns an intent
        token it began (``return self.intents.begin(...)`` directly, or
        a variable assigned from a begin); a *closer* closes one of its
        parameters on some path (close calls are unconditional in spirit
        — runtime journal-unavailable guards don't demote a closer)."""
        changed = True
        while changed:
            changed = False
            for fi in self.index.functions.values():
                if fi not in self.beginners and self._scan_beginner(fi):
                    self.beginners.add(fi)
                    changed = True
                closed = self._scan_closer(fi)
                if closed - self.closers.get(fi, set()):
                    self.closers[fi] = self.closers.get(fi, set()) | closed
                    changed = True

    def _is_begin_call(self, caller: flow.FunctionInfo, call: ast.Call) -> bool:
        if _is_begin_primitive(call):
            return True
        target = self.index.resolve(caller, call)
        return target is not None and target in self.beginners

    def _closed_params_of_call(
        self, caller: flow.FunctionInfo, call: ast.Call
    ) -> list[str]:
        """Token VARIABLE names in ``caller`` that this call closes."""
        out: list[str] = []
        prim = _close_primitive_arg(call)
        if prim is not None:
            out.append(prim)
        target = self.index.resolve(caller, call)
        if target is not None and target in self.closers:
            bound = target.bind_args(call)
            for param in self.closers[target]:
                arg = bound.get(param)
                if isinstance(arg, ast.Name):
                    out.append(arg.id)
        return out

    def _scan_beginner(self, fi: flow.FunctionInfo) -> bool:
        begun_vars: set[str] = set()
        for node in ast.walk(fi.node):
            if (
                isinstance(node, ast.Assign)
                and len(node.targets) == 1
                and isinstance(node.targets[0], ast.Name)
                and isinstance(node.value, ast.Call)
                and self._is_begin_call(fi, node.value)
            ):
                begun_vars.add(node.targets[0].id)
        for node in ast.walk(fi.node):
            if isinstance(node, ast.Return) and node.value is not None:
                if isinstance(node.value, ast.Call) and self._is_begin_call(
                    fi, node.value
                ):
                    return True
                if (
                    isinstance(node.value, ast.Name)
                    and node.value.id in begun_vars
                ):
                    return True
        return False

    def _scan_closer(self, fi: flow.FunctionInfo) -> set[str]:
        params = set(fi.params)
        closed: set[str] = set()
        for call in flow.iter_calls(fi.node):
            for name in self._closed_params_of_call(fi, call):
                if name in params:
                    closed.add(name)
        return closed

    # -- token-relevant parameters ----------------------------------------

    def _token_params(self, fi: flow.FunctionInfo) -> set[str]:
        """Parameters that can carry a journal token: passed onward into
        a close/mark primitive or a callee's token parameter (one level
        of the call graph per fixpoint round is enough in practice)."""
        if fi in self._token_param_memo:
            return self._token_param_memo[fi]
        self._token_param_memo[fi] = set()  # recursion guard
        out = self._token_params_uncached(fi)
        self._token_param_memo[fi] = out
        return out

    def _token_params_uncached(self, fi: flow.FunctionInfo) -> set[str]:
        params = set(fi.params)
        out: set[str] = set()
        for call in flow.iter_calls(fi.node):
            names: list[str] = []
            prim = _close_primitive_arg(call)
            if prim is not None:
                names.append(prim)
            fn = call.func
            if (
                isinstance(fn, ast.Attribute)
                and fn.attr == "mark"
                and "intents" in _chain_names(fn.value)
                and call.args
                and isinstance(call.args[0], ast.Name)
            ):
                names.append(call.args[0].id)
            target = self.index.resolve(fi, call)
            if target is not None:
                bound = target.bind_args(call)
                for p in self.closers.get(target, set()) | (
                    self._token_params(target) if target is not fi else set()
                ):
                    arg = bound.get(p)
                    if isinstance(arg, ast.Name):
                        names.append(arg.id)
            out.update(n for n in names if n in params)
        return out

    # -- parameter token state (interprocedural) ---------------------------

    def param_state(self, fi: flow.FunctionInfo, param: str) -> frozenset:
        key = (fi, param)
        if key in self._param_memo:
            return self._param_memo[key]
        if key in self._param_inflight:
            # Recursion along the call graph: conservative, never proven.
            return frozenset((OTHER,))
        self._param_inflight.add(key)
        try:
            sites = self.index.call_sites(fi)
            if not sites:
                state: frozenset = frozenset((OTHER,))
            else:
                state = frozenset()
                for caller, call in sites:
                    bound = fi.bind_args(call)
                    arg = bound.get(param)
                    if arg is None:
                        default = fi.param_default(param)
                        state |= self._expr_state_static(default)
                    else:
                        state |= self._arg_state_at(caller, call, arg)
                if not state:
                    state = frozenset((OTHER,))
            self._param_memo[key] = state
            return state
        finally:
            self._param_inflight.discard(key)

    def _expr_state_static(self, expr: ast.expr | None) -> frozenset:
        if expr is None:
            return frozenset((OTHER,))
        if isinstance(expr, ast.Constant) and expr.value is None:
            return frozenset((NONE,))
        return frozenset((OTHER,))

    def _arg_state_at(
        self, caller: flow.FunctionInfo, call: ast.Call, arg: ast.expr
    ) -> frozenset:
        """The token state of ``arg`` at ``call``'s statement in the
        caller, from the caller's own dataflow."""
        if isinstance(arg, ast.Call) and self._is_begin_call(caller, arg):
            return _open_state_of(arg)
        if isinstance(arg, ast.Constant) and arg.value is None:
            return frozenset((NONE,))
        if not isinstance(arg, ast.Name):
            return frozenset((OTHER,))
        analysis = self.analyze(caller)
        cfg = self._cfg(caller)
        for node in cfg.nodes:
            if node.stmt is None:
                continue
            found = any(c is call for c in flow.stmt_calls(node.stmt))
            if found:
                env = analysis.get(node.idx)
                if env is None:
                    return frozenset((OTHER,))
                return env.get(arg.id, frozenset((OTHER,)))
        return frozenset((OTHER,))

    # -- per-function dataflow --------------------------------------------

    def _cfg(self, fi: flow.FunctionInfo) -> flow.CFG:
        if fi not in self._cfgs:
            self._cfgs[fi] = flow.build_cfg(fi.node)
        return self._cfgs[fi]

    def analyze(self, fi: flow.FunctionInfo) -> dict[int, dict]:
        """IN-state (var -> frozenset) per CFG node index, to fixpoint."""
        if fi in self._analysis:
            return self._analysis[fi]
        # Publish the (empty) in-progress result so self-recursive
        # shapes terminate with conservative answers.
        self._analysis[fi] = {}
        cfg = self._cfg(fi)
        entry_env: dict[str, frozenset] = {}
        for p in self._token_params(fi):
            entry_env[p] = self.param_state(fi, p)
        in_states: dict[int, dict] = {cfg.entry.idx: entry_env}
        out_states: dict[int, dict] = {}
        work = [cfg.entry.idx]
        iters = 0
        limit = 50 * max(1, len(cfg.nodes))
        while work and iters < limit:
            iters += 1
            idx = work.pop()
            node = cfg.nodes[idx]
            env_in = in_states.get(idx, {})
            env_out = self._transfer(fi, node, dict(env_in))
            out_states[idx] = env_out
            for s in node.succs:
                succ_env = self._refine(node, s, env_out)
                merged = self._merge(in_states.get(s), succ_env)
                if merged != in_states.get(s):
                    in_states[s] = merged
                    work.append(s)
        self._analysis[fi] = in_states
        return in_states

    @staticmethod
    def _merge(a: dict | None, b: dict) -> dict:
        if a is None:
            return dict(b)
        out = dict(a)
        for k, v in b.items():
            if k in out:
                out[k] = out[k] | v
            else:
                # Unbound on the already-merged paths: could be anything
                # there. Same for the symmetric case below.
                out[k] = v | frozenset((OTHER,))
        for k in out:
            if k not in b:
                out[k] = out[k] | frozenset((OTHER,))
        return out

    def _transfer(
        self, fi: flow.FunctionInfo, node: flow.Node, env: dict
    ) -> dict:
        stmt = node.stmt
        if stmt is None or node.kind == "handler":
            return env
        # Close calls anywhere in the statement resolve their token.
        for call in flow.stmt_calls(stmt):
            for name in self._closed_params_of_call(fi, call):
                env[name] = frozenset((CLOSED,))
        targets: list[ast.expr] = []
        value: ast.expr | None = None
        if isinstance(stmt, ast.Assign):
            targets, value = stmt.targets, stmt.value
        elif isinstance(stmt, ast.AnnAssign) and stmt.value is not None:
            targets, value = [stmt.target], stmt.value
        if value is not None:
            state = self._value_state(fi, value, env)
            for t in targets:
                if isinstance(t, ast.Name):
                    env[t.id] = state
                else:
                    # Tuple/attribute/subscript targets: any plain name
                    # inside is rebound to something we can't classify.
                    for sub in ast.walk(t):
                        if isinstance(sub, ast.Name):
                            env[sub.id] = frozenset((OTHER,))
        # Other rebinding forms (loop targets, `with ... as x`): the
        # bound names stop being classifiable tokens.
        rebinders: list[ast.expr] = []
        if isinstance(stmt, (ast.For, ast.AsyncFor)):
            rebinders.append(stmt.target)
        if isinstance(stmt, (ast.With, ast.AsyncWith)):
            rebinders.extend(
                item.optional_vars for item in stmt.items
                if item.optional_vars is not None
            )
        for target in rebinders:
            for sub in ast.walk(target):
                if isinstance(sub, ast.Name):
                    env[sub.id] = frozenset((OTHER,))
        if isinstance(stmt, ast.Return) and isinstance(stmt.value, ast.Name):
            # Returning the token hands ownership to the caller, whose
            # own analysis takes over (beginner summaries).
            env[stmt.value.id] = frozenset((CLOSED,))
        return env

    def _value_state(
        self, fi: flow.FunctionInfo, value: ast.expr, env: dict
    ) -> frozenset:
        if isinstance(value, ast.Constant) and value.value is None:
            return frozenset((NONE,))
        if isinstance(value, ast.Name):
            return env.get(value.id, frozenset((OTHER,)))
        if isinstance(value, ast.Call) and self._is_begin_call(fi, value):
            return _open_state_of(value)
        return frozenset((OTHER,))

    @staticmethod
    def _refine(node: flow.Node, succ: int, env: dict) -> dict:
        """Apply single-variable None-ness refinement along a labeled
        ``if`` edge."""
        polarity = node.branch.get(succ)
        if polarity is None or not isinstance(node.stmt, ast.If):
            return env
        test = node.stmt.test
        var, none_if_true = None, None
        if (
            isinstance(test, ast.Compare)
            and isinstance(test.left, ast.Name)
            and len(test.ops) == 1
            and len(test.comparators) == 1
            and isinstance(test.comparators[0], ast.Constant)
            and test.comparators[0].value is None
        ):
            if isinstance(test.ops[0], ast.Is):
                var, none_if_true = test.left.id, True
            elif isinstance(test.ops[0], ast.IsNot):
                var, none_if_true = test.left.id, False
        elif isinstance(test, ast.Name):
            var, none_if_true = test.id, False  # truthy -> not None
        elif (
            isinstance(test, ast.UnaryOp)
            and isinstance(test.op, ast.Not)
            and isinstance(test.operand, ast.Name)
        ):
            var, none_if_true = test.operand.id, True
        if var is None or var not in env:
            return env
        keep_none = none_if_true == (polarity == "true")
        out = dict(env)
        if keep_none:
            out[var] = env[var] & frozenset((NONE,)) or frozenset((NONE,))
        else:
            out[var] = env[var] - frozenset((NONE,)) or env[var]
        return out


def _functions_of(src: SourceFile, index: flow.CallIndex):
    return [
        fi for fi in index.functions.values() if fi.src is src
    ]


def check(ctx: LintContext) -> list[Finding]:
    engine = _Engine(ctx)
    findings: list[Finding] = []
    for src in engine.files:
        proven_ids: set[int] = set()
        for fi in _functions_of(src, engine.index):
            findings.extend(_check_function(engine, src, fi, proven_ids))
        # Coverage backstop: hardware calls the flow engine could not
        # even SEE — module level, nested defs/lambdas (closures run
        # later, possibly outside any bracket), class bodies. These
        # degrade to findings, never to silent cleanliness.
        findings.extend(_check_unanalyzed(src, proven_ids))
    return findings


def _check_unanalyzed(src: SourceFile, proven_ids: set[int]) -> list[Finding]:
    findings: list[Finding] = []
    stack: list[ast.AST] = []

    def visit(node: ast.AST) -> None:
        is_scope = isinstance(
            node, (ast.ClassDef, ast.FunctionDef, ast.AsyncFunctionDef)
        )
        if is_scope:
            stack.append(node)
        if isinstance(node, ast.Call) and id(node) not in proven_ids:
            op = _is_backend_hw_call(node)
            if op is not None and src.annotation(
                node.lineno, "journal-ok",
                span_end=getattr(node, "end_lineno", node.lineno),
            ) is None:
                symbol = qualname_of(stack)
                f = Finding(
                    checker=CHECKER,
                    path=src.relpath,
                    line=node.lineno,
                    message=(
                        f"backend.{op} in {symbol} sits where the flow "
                        "engine cannot prove a journal bracket (module "
                        "level, or a closure that runs later) — move it "
                        "into a journaled method, or waive with "
                        "`# cclint: journal-ok(reason)`"
                    ),
                    symbol=symbol,
                    detail=op,
                )
                if f.fingerprint not in ALLOWLIST:
                    findings.append(f)
        for child in ast.iter_child_nodes(node):
            visit(child)
        if is_scope:
            stack.pop()

    visit(src.tree)
    return findings


def _check_function(
    engine: _Engine, src: SourceFile, fi: flow.FunctionInfo,
    proven_ids: set[int],
) -> list[Finding]:
    hw_stmts: list[tuple[flow.Node, str]] = []
    begun_stmts: list[tuple[ast.stmt, str]] = []
    cfg = engine._cfg(fi)
    for node in cfg.nodes:
        if node.stmt is None or node.kind == "handler":
            continue
        for call in flow.stmt_calls(node.stmt):
            op = _is_backend_hw_call(call)
            if op is not None:
                hw_stmts.append((node, op))
                # Seen by the flow analysis: the backstop pass must not
                # double-report it (whatever the verdict here).
                proven_ids.add(id(call))
        if isinstance(node.stmt, ast.Assign) and isinstance(
            node.stmt.value, ast.Call
        ) and engine._is_begin_call(fi, node.stmt.value):
            for t in node.stmt.targets:
                if isinstance(t, ast.Name):
                    begun_stmts.append((node.stmt, t.id))
    if not hw_stmts and not begun_stmts:
        return []
    analysis = engine.analyze(fi)
    findings: list[Finding] = []

    # -- begin-dominates-reset --------------------------------------------
    for node, op in hw_stmts:
        env = analysis.get(node.idx)
        proven = env is not None and any(
            _proves_hw(state) for state in env.values()
        )
        if proven:
            continue
        stmt = node.stmt
        if src.annotation(
            stmt.lineno, "journal-ok", span_end=stmt.end_lineno
        ) is not None:
            continue
        f = Finding(
            checker=CHECKER,
            path=src.relpath,
            line=stmt.lineno,
            message=(
                f"backend.{op} in {fi.qualname} is not dominated by an "
                "intent-begin journal write on every path — journal the "
                "intent first (intent_journal.begin / a begin wrapper), "
                "or thread the caller's token so the engine can prove "
                "the bracket"
            ),
            symbol=fi.qualname,
            detail=op,
        )
        if f.fingerprint not in ALLOWLIST:
            findings.append(f)

    # -- close-postdominates-exit -----------------------------------------
    exit_env = analysis.get(cfg.exit.idx)
    for stmt, var in begun_stmts:
        if exit_env is None:
            continue  # no normal exit reaches — raise-only function
        state = exit_env.get(var, frozenset())
        if not any(_is_open(v) for v in state):
            continue
        if src.annotation(
            stmt.lineno, "intent-open-ok", span_end=stmt.end_lineno
        ) is not None:
            continue
        f = Finding(
            checker=CHECKER,
            path=src.relpath,
            line=stmt.lineno,
            message=(
                f"intent begun here ({var}) may still be open on a "
                f"non-crash exit of {fi.qualname} — close it "
                "(commit/abort) on every return path, or annotate "
                "`# cclint: intent-open-ok(reason)` if replay is the "
                "designed owner"
            ),
            symbol=fi.qualname,
            detail=f"open-{var}",
        )
        if f.fingerprint not in ALLOWLIST:
            findings.append(f)
    return findings
