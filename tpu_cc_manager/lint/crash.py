"""Checker 3: a crash stays a crash.

Every kill-at-every-crash-point suite (rollout resume, pipeline,
intent-journal replay) models SIGKILL as a ``BaseException`` that is NOT
an ``Exception`` — the whole methodology collapses if any cleanup path
quietly swallows it. So: a handler that can catch ``BaseException``
(bare ``except:``, ``except BaseException``, or a tuple containing it)
must contain a ``raise`` on its own level (nested function bodies don't
count — they run later, if at all).

Worker-thread trampolines that capture the exception to re-raise at
``join()`` are the legitimate exception; they declare themselves with
``# cclint: crash-ok(<reason>)`` on the ``except`` line.
"""

from __future__ import annotations

import ast

from tpu_cc_manager.lint.base import Finding, LintContext, qualname_of

CHECKER = "crash"


def _catches_base(handler: ast.ExceptHandler) -> bool:
    t = handler.type
    if t is None:
        return True  # bare except:
    if isinstance(t, ast.Name) and t.id == "BaseException":
        return True
    if isinstance(t, ast.Tuple):
        return any(
            isinstance(e, ast.Name) and e.id == "BaseException" for e in t.elts
        )
    return False


def _contains_raise(body: list[ast.stmt]) -> bool:
    """A ``raise`` reachable at the handler's own level (not inside a
    nested def/lambda, which executes later if ever)."""
    for stmt in body:
        for node in ast.walk(stmt):
            if isinstance(
                node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)
            ):
                continue
            if isinstance(node, ast.Raise):
                # ast.walk descends into nested defs too; re-verify by
                # checking the raise isn't under one.
                if not _under_nested_def(stmt, node):
                    return True
    return False


def _under_nested_def(root: ast.stmt, target: ast.AST) -> bool:
    """Whether ``target`` sits inside a function/lambda nested in
    ``root``."""

    def search(node: ast.AST, in_def: bool) -> bool | None:
        if node is target:
            return in_def
        nested = in_def or isinstance(
            node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)
        )
        for child in ast.iter_child_nodes(node):
            got = search(child, nested)
            if got is not None:
                return got
        return None

    return bool(search(root, False))


def check(ctx: LintContext) -> list[Finding]:
    findings: list[Finding] = []
    for src in ctx.files:
        stack: list[ast.AST] = []

        def visit(node: ast.AST) -> None:
            is_scope = isinstance(
                node, (ast.ClassDef, ast.FunctionDef, ast.AsyncFunctionDef)
            )
            if is_scope:
                stack.append(node)
            if isinstance(node, ast.ExceptHandler) and _catches_base(node):
                if not _contains_raise(node.body) and src.annotation(
                    node.lineno, "crash-ok"
                ) is None:
                    symbol = qualname_of(stack)
                    caught = "bare except" if node.type is None else "BaseException"
                    findings.append(
                        Finding(
                            checker=CHECKER,
                            path=src.relpath,
                            line=node.lineno,
                            message=(
                                f"{caught} handler in {symbol} never "
                                "re-raises — modeled SIGKILL must escape "
                                "every cleanup path (annotate "
                                "`# cclint: crash-ok(reason)` for a "
                                "re-raise-at-join trampoline)"
                            ),
                            symbol=symbol,
                        )
                    )
            for child in ast.iter_child_nodes(node):
                visit(child)
            if is_scope:
                stack.pop()

        visit(src.tree)
    return findings
