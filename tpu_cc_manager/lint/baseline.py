"""Baseline: explicit, reasoned grandfathering of known violations.

``.cclint-baseline.json`` (repo root, committed) maps finding
fingerprints to one-line reasons. Fingerprints are line-independent
(``checker:path:symbol[:detail]``) so ordinary edits don't churn the
file; one entry covers every finding sharing its fingerprint (e.g. three
simulated-latency sleeps in one method).

A finding without an entry fails the build. An entry without a finding
is *stale* — and since v2 that is a HARD error too: the fix and the
entry deletion belong to the same change (`--write-baseline` regenerates
the file, preserving hand-written reasons and pruning fixed entries, so
shedding the grandfathering is one command, not an edit race).
"""

from __future__ import annotations

import json
import os

from tpu_cc_manager.lint.base import Finding

BASELINE_FILE = ".cclint-baseline.json"


def load(root: str, path: str | None = None) -> dict[str, str]:
    """fingerprint -> reason; empty when the file doesn't exist."""
    full = path or os.path.join(root, BASELINE_FILE)
    try:
        with open(full, "r", encoding="utf-8") as f:
            data = json.load(f)
    except FileNotFoundError:
        return {}
    entries = data.get("entries", [])
    return {e["fingerprint"]: e.get("reason", "") for e in entries}


def save(root: str, findings: list[Finding], path: str | None = None) -> str:
    """Write a baseline grandfathering every current finding (reasons
    stubbed TODO — each must be hand-edited to a real justification)."""
    full = path or os.path.join(root, BASELINE_FILE)
    existing = load(root, path)
    seen: dict[str, str] = {}
    for f in findings:
        seen.setdefault(
            f.fingerprint, existing.get(f.fingerprint, "TODO: justify")
        )
    payload = {
        "comment": (
            "cclint grandfathered violations. Every entry needs a one-line "
            "reason; remove entries as the violations are fixed. "
            "Regenerate skeleton: python -m tpu_cc_manager.lint "
            "--write-baseline"
        ),
        "entries": [
            {"fingerprint": fp, "reason": reason}
            for fp, reason in sorted(seen.items())
        ],
    }
    with open(full, "w", encoding="utf-8") as f:
        json.dump(payload, f, indent=2)
        f.write("\n")
    return full


def split(
    findings: list[Finding], baseline: dict[str, str]
) -> tuple[list[Finding], list[Finding], list[str]]:
    """(new, grandfathered, stale-fingerprints)."""
    new: list[Finding] = []
    old: list[Finding] = []
    hit: set[str] = set()
    for f in findings:
        if f.fingerprint in baseline:
            old.append(f)
            hit.add(f.fingerprint)
        else:
            new.append(f)
    stale = sorted(set(baseline) - hit)
    return new, old, stale
