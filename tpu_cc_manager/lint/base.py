"""Shared plumbing for the cclint checkers.

Each source file is parsed once into a :class:`SourceFile` (AST +
per-line ``# cclint:`` annotation map, extracted with :mod:`tokenize` so
annotations inside strings don't count), and every checker receives the
same :class:`LintContext`. Findings carry a line number for humans and a
line-independent ``fingerprint`` for the baseline — line numbers drift
with every edit, so grandfathering keys on
``checker:relpath:symbol[:detail]`` instead.
"""

from __future__ import annotations

import ast
import io
import os
import re
import tokenize
from dataclasses import dataclass, field

#: Annotation grammar: ``# cclint: <directive>(<arg>)`` with an optional
#: free-text tail. Multiple directives per line are legal (rare).
_ANNOTATION_RE = re.compile(
    r"#\s*cclint:\s*(?P<directive>[a-z-]+)\s*\(\s*(?P<arg>[^)]*?)\s*\)"
)


@dataclass
class Finding:
    """One violation: where it is, which contract, and a stable identity."""

    checker: str
    path: str  # repo-relative
    line: int
    message: str
    symbol: str  # enclosing scope or offending name — fingerprint input
    detail: str = ""  # extra fingerprint disambiguation (e.g. env name)

    @property
    def fingerprint(self) -> str:
        parts = [self.checker, self.path, self.symbol]
        if self.detail:
            parts.append(self.detail)
        return ":".join(parts)

    def to_dict(self) -> dict:
        return {
            "checker": self.checker,
            "path": self.path,
            "line": self.line,
            "message": self.message,
            "fingerprint": self.fingerprint,
        }


class SourceFile:
    """One parsed source file: AST, raw lines, and cclint annotations."""

    def __init__(self, root: str, relpath: str) -> None:
        self.relpath = relpath
        self.abspath = os.path.join(root, relpath)
        with open(self.abspath, "r", encoding="utf-8") as f:
            self.source = f.read()
        self.lines = self.source.splitlines()
        self.tree = ast.parse(self.source, filename=relpath)
        # line -> [(directive, arg), ...], from real comment tokens only.
        self.annotations: dict[int, list[tuple[str, str]]] = {}
        try:
            tokens = tokenize.generate_tokens(io.StringIO(self.source).readline)
            for tok in tokens:
                if tok.type == tokenize.COMMENT:
                    for m in _ANNOTATION_RE.finditer(tok.string):
                        self.annotations.setdefault(tok.start[0], []).append(
                            (m.group("directive"), m.group("arg"))
                        )
        except tokenize.TokenError:
            pass  # the ast.parse above would have raised on real breakage

    def annotation(
        self, line: int, directive: str, *, span_end: int | None = None
    ) -> str | None:
        """The argument of ``directive`` on ``line`` (or any line through
        ``span_end`` — a multi-line statement's comment may sit on any of
        its physical lines); None when absent."""
        for ln in range(line, (span_end or line) + 1):
            for d, arg in self.annotations.get(ln, ()):
                if d == directive:
                    return arg
        return None


@dataclass
class LintContext:
    """Everything a checker may look at. ``root`` is the repo root;
    ``files`` covers ``tpu_cc_manager/**/*.py`` and ``test_files``
    covers ``tests/**/*.py`` (the crash-point coverage and test-wait
    checkers read the suite; the package checkers never do)."""

    root: str
    files: list[SourceFile] = field(default_factory=list)
    test_files: list[SourceFile] = field(default_factory=list)

    def file(self, relpath: str) -> SourceFile | None:
        for f in self.files + self.test_files:
            if f.relpath == relpath:
                return f
        return None

    def read_text(self, relpath: str) -> str | None:
        """A non-Python contract surface (docs, manifests); None when the
        file does not exist."""
        path = os.path.join(self.root, relpath)
        try:
            with open(path, "r", encoding="utf-8") as f:
                return f.read()
        except OSError:
            return None


def package_files(root: str, package_dir: str = "tpu_cc_manager") -> list[str]:
    """Repo-relative paths of every package source file, sorted."""
    out: list[str] = []
    for dirpath, dirnames, filenames in os.walk(os.path.join(root, package_dir)):
        dirnames[:] = [d for d in dirnames if d != "__pycache__"]
        for name in sorted(filenames):
            if name.endswith(".py"):
                out.append(
                    os.path.relpath(os.path.join(dirpath, name), root)
                )
    return sorted(out)


def build_context(root: str) -> LintContext:
    ctx = LintContext(root=root)
    for relpath in package_files(root):
        ctx.files.append(SourceFile(root, relpath))
    for relpath in package_files(root, package_dir="tests"):
        ctx.test_files.append(SourceFile(root, relpath))
    return ctx


def qualname_of(stack: list[ast.AST]) -> str:
    """Dotted class/function path for the innermost scopes in ``stack``
    (module level -> ``<module>``)."""
    names = [
        n.name
        for n in stack
        if isinstance(n, (ast.ClassDef, ast.FunctionDef, ast.AsyncFunctionDef))
    ]
    return ".".join(names) if names else "<module>"
