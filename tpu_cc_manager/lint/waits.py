"""Checker 2: no ad-hoc waits.

PR 2's invariant: every wait in the agent rides ``utils/retry.py``
(jittered backoff ladders, ``poll_until`` deadlines, stop-aware waits) so
nothing sleeps unjittered, uninterruptible, or unaccounted. A direct
``time.sleep`` call anywhere outside ``utils/retry.py`` itself (and the
fault-injection layer, whose job is to simulate slowness) is an error.

References that merely *name* the function (``sleep=time.sleep`` default
arguments) are not calls and are fine.
"""

from __future__ import annotations

import ast

from tpu_cc_manager.lint.base import Finding, LintContext, qualname_of

CHECKER = "waits"

ALLOWED_FILES = ("tpu_cc_manager/utils/retry.py",)
ALLOWED_DIRS = ("tpu_cc_manager/faults/",)


def _is_time_sleep(call: ast.Call, from_time_names: set[str]) -> bool:
    fn = call.func
    if (
        isinstance(fn, ast.Attribute)
        and fn.attr == "sleep"
        and isinstance(fn.value, ast.Name)
        and fn.value.id == "time"
    ):
        return True
    return isinstance(fn, ast.Name) and fn.id in from_time_names


def check(ctx: LintContext) -> list[Finding]:
    findings: list[Finding] = []
    for src in ctx.files:
        if src.relpath in ALLOWED_FILES or src.relpath.startswith(ALLOWED_DIRS):
            continue
        # Names bound by `from time import sleep [as x]`.
        from_time: set[str] = set()
        for node in ast.walk(src.tree):
            if isinstance(node, ast.ImportFrom) and node.module == "time":
                for alias in node.names:
                    if alias.name == "sleep":
                        from_time.add(alias.asname or alias.name)

        stack: list[ast.AST] = []

        def visit(node: ast.AST) -> None:
            is_scope = isinstance(
                node, (ast.ClassDef, ast.FunctionDef, ast.AsyncFunctionDef)
            )
            if is_scope:
                stack.append(node)
            if isinstance(node, ast.Call) and _is_time_sleep(node, from_time):
                symbol = qualname_of(stack)
                findings.append(
                    Finding(
                        checker=CHECKER,
                        path=src.relpath,
                        line=node.lineno,
                        message=(
                            f"time.sleep in {symbol} — waits must ride "
                            "utils/retry.py (poll_until / RetryPolicy / "
                            "stop-aware wait)"
                        ),
                        symbol=symbol,
                    )
                )
            for child in ast.iter_child_nodes(node):
                visit(child)
            if is_scope:
                stack.pop()

        visit(src.tree)
    return findings
