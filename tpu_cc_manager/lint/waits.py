"""Checker 2: no ad-hoc waits.

PR 2's invariant: every wait in the agent rides ``utils/retry.py``
(jittered backoff ladders, ``poll_until`` deadlines, stop-aware waits) so
nothing sleeps unjittered, uninterruptible, or unaccounted. A direct
``time.sleep`` call anywhere outside ``utils/retry.py`` itself (and the
fault-injection layer, whose job is to simulate slowness) is an error.

The suite is covered too: an ad-hoc ``time.sleep`` in a test is the
flake factory — a fixed delay that races the scheduler on a loaded box.
Tests should ride ``retry.poll_until`` (wait for the condition, bounded)
or an event; a sleep that genuinely IS the test (simulated latency, a
real-clock lease TTL that must lapse) carries
``# cclint: test-sleep-ok(<reason>)`` on its line. The waiver is only
honored under ``tests/`` — package code has no such escape.

References that merely *name* the function (``sleep=time.sleep`` default
arguments) are not calls and are fine.
"""

from __future__ import annotations

import ast

from tpu_cc_manager.lint.base import Finding, LintContext, SourceFile, qualname_of

CHECKER = "waits"

ALLOWED_FILES = ("tpu_cc_manager/utils/retry.py",)
ALLOWED_DIRS = ("tpu_cc_manager/faults/",)


def _is_time_sleep(call: ast.Call, from_time_names: set[str]) -> bool:
    fn = call.func
    if (
        isinstance(fn, ast.Attribute)
        and fn.attr == "sleep"
        and isinstance(fn.value, ast.Name)
        and fn.value.id in ("time", "_time")
    ):
        return True
    return isinstance(fn, ast.Name) and fn.id in from_time_names


def _check_file(src: SourceFile, in_tests: bool) -> list[Finding]:
    findings: list[Finding] = []
    # Names bound by `from time import sleep [as x]`.
    from_time: set[str] = set()
    for node in ast.walk(src.tree):
        if isinstance(node, ast.ImportFrom) and node.module == "time":
            for alias in node.names:
                if alias.name == "sleep":
                    from_time.add(alias.asname or alias.name)

    stack: list[ast.AST] = []

    def visit(node: ast.AST) -> None:
        is_scope = isinstance(
            node, (ast.ClassDef, ast.FunctionDef, ast.AsyncFunctionDef)
        )
        if is_scope:
            stack.append(node)
        if isinstance(node, ast.Call) and _is_time_sleep(node, from_time):
            # The waiver may sit on the call line, or on the line above
            # it when that line is a pure comment (an honest reason
            # rarely fits beside an indented call) — a waiver trailing
            # another statement never bleeds onto the next sleep.
            end = getattr(node, "end_lineno", node.lineno)
            waived = in_tests and src.annotation(
                node.lineno, "test-sleep-ok", span_end=end
            ) is not None
            if in_tests and not waived and node.lineno >= 2:
                above = src.lines[node.lineno - 2].strip()
                if above.startswith("#"):
                    waived = src.annotation(
                        node.lineno - 1, "test-sleep-ok"
                    ) is not None
            if not waived:
                symbol = qualname_of(stack)
                hint = (
                    "waits must ride utils/retry.py (poll_until / "
                    "RetryPolicy / stop-aware wait)"
                    if not in_tests else
                    "a fixed test sleep is the flake factory — "
                    "poll_until the condition, or waive with "
                    "`# cclint: test-sleep-ok(reason)` when the delay "
                    "IS the test"
                )
                findings.append(
                    Finding(
                        checker=CHECKER,
                        path=src.relpath,
                        line=node.lineno,
                        message=f"time.sleep in {symbol} — {hint}",
                        symbol=symbol,
                    )
                )
        for child in ast.iter_child_nodes(node):
            visit(child)
        if is_scope:
            stack.pop()

    visit(src.tree)
    return findings


def check(ctx: LintContext) -> list[Finding]:
    findings: list[Finding] = []
    for src in ctx.files:
        if src.relpath in ALLOWED_FILES or src.relpath.startswith(ALLOWED_DIRS):
            continue
        findings.extend(_check_file(src, in_tests=False))
    for src in ctx.test_files:
        findings.extend(_check_file(src, in_tests=True))
    return findings
