"""cclint: contract-aware static analysis for this repo's safety invariants.

Robustness work accumulated safety contracts that lived only as prose in
CHANGES.md and reviewer memory. Each checker here machine-checks one of
them over the package's own source (stdlib ``ast`` only). v2 upgraded
the engine from per-file lexical checks to flow-aware analysis:
:mod:`tpu_cc_manager.lint.flow` builds a per-function CFG and resolves
the intra-class/intra-module call graph, so the checkers prove the
invariants where they actually live — across call chains and threads.

``locks``
    Shared fields annotated ``# cclint: guarded-by(<lock>)`` at their
    ``__init__`` assignment may only be touched inside a
    ``with self.<lock>:`` block elsewhere in the class, or in a method
    annotated ``# cclint: requires(<lock>)`` — and ``requires`` is now
    VERIFIED at every same-class call site, bare references of
    ``requires`` methods (thread targets) are findings, and an
    unannotated private helper is judged by its callers' lock context.
``waits``
    ``time.sleep`` outside ``utils/retry.py`` / ``faults/`` is an error —
    every wait rides the shared retry/backoff layer (the PR 2
    invariant). Now covers ``tests/`` too (the ad-hoc test sleep is the
    flake factory), with ``# cclint: test-sleep-ok(<reason>)`` waivers.
``crash``
    A handler that can catch ``BaseException`` (bare ``except:`` or
    explicit) must re-raise it; the kill-at-every-crash-point suites
    depend on modeled SIGKILL escaping every cleanup path. A handler that
    intentionally captures (worker threads re-raising at join) carries
    ``# cclint: crash-ok(<reason>)``.
``journal``
    Journal typestate, proven on the CFG: every ``backend.reset`` /
    ``backend.restart_runtime`` must be dominated by an intent-begin
    write on every path (interprocedurally — tokens carry their callers'
    proof), and a begun intent must be closed on every non-crash exit.
    The old reviewed allowlist survives only as a waiver of last resort
    (currently empty).
``fenced``
    Fenced-write taint: once a ``RolloutLease`` is acquired, every
    apiserver write must flow through ``FencedKube`` — a raw-client
    write reachable inside the lease bracket (including through a
    callee) is the CAS-bypass bug class, and a finding.
``crashpoints``
    Crash-point coverage: every named orchestrator crash point and
    journal phase mark must be named by at least one kill-at test under
    ``tests/``, and point names only tests still reference are stale.
``surface``
    Contract-surface drift: every ``CC_*`` env read must appear in the
    docs/operations.md env table, every ``CC_*`` env the daemonset sets
    must be read somewhere in code, every emitted metric family must be
    seeded through the exposition lint's live-registry render and
    documented, and every ``cloud.google.com/tpu-cc.*`` /
    ``tpu-cc.gke.io`` label/annotation key must come from ``labels.py``,
    never an inline literal.

The driver (``python -m tpu_cc_manager.lint``) runs every checker plus
the Prometheus exposition lint (:mod:`tpu_cc_manager.lint.expo`, the
former ``hack/check_metrics_lint.py`` — the old entrypoint remains as a
shim), emits human or ``--json`` output (plus a ``--changed-only
<git-ref>`` fast review mode), and compares findings against the
committed baseline (``.cclint-baseline.json``): grandfathered
violations are explicit, each with a reason; any NEW finding — or any
STALE baseline entry — fails the build. The static passes pair with an
opt-in runtime lock-order checker (``CC_LOCKCHECK=1``,
:mod:`tpu_cc_manager.utils.locks`).
"""

from tpu_cc_manager.lint.base import Finding, LintContext  # noqa: F401
