"""cclint: contract-aware static analysis for this repo's safety invariants.

Eight PRs of robustness work accumulated safety contracts that lived only
as prose in CHANGES.md and reviewer memory. Each checker here machine-
checks one of them, over the package's own source (stdlib ``ast`` only):

``locks``
    Shared fields annotated ``# cclint: guarded-by(<lock>)`` at their
    ``__init__`` assignment may only be touched inside a
    ``with self.<lock>:`` block elsewhere in the class (or in a method
    annotated ``# cclint: requires(<lock>)``, whose callers hold it).
``waits``
    ``time.sleep`` outside ``utils/retry.py`` / ``faults/`` is an error —
    every wait rides the shared retry/backoff layer (the PR 2 invariant).
``crash``
    A handler that can catch ``BaseException`` (bare ``except:`` or
    explicit) must re-raise it; the kill-at-every-crash-point suites
    depend on modeled SIGKILL escaping every cleanup path. A handler that
    intentionally captures (worker threads re-raising at join) carries
    ``# cclint: crash-ok(<reason>)``.
``journal``
    Direct calls to ``backend.reset`` / ``backend.restart_runtime``
    outside the allowlisted journaled call sites are an error — every
    hardware-effecting operation journals an intent first (PR 5).
``surface``
    Contract-surface drift: every ``CC_*`` env read must appear in the
    docs/operations.md env table, every ``CC_*`` env the daemonset sets
    must be read somewhere in code, every emitted metric family must be
    seeded through the exposition lint's live-registry render and
    documented, and every ``cloud.google.com/tpu-cc.*`` /
    ``tpu-cc.gke.io`` label/annotation key must come from ``labels.py``,
    never an inline literal.

The driver (``python -m tpu_cc_manager.lint``) runs every checker plus
the Prometheus exposition lint (:mod:`tpu_cc_manager.lint.expo`, the
former ``hack/check_metrics_lint.py`` — the old entrypoint remains as a
shim), emits human or ``--json`` output, and compares findings against
the committed baseline (``.cclint-baseline.json``): grandfathered
violations are explicit, each with a reason, and any NEW finding fails
the build. The static passes pair with an opt-in runtime lock-order
checker (``CC_LOCKCHECK=1``, :mod:`tpu_cc_manager.utils.locks`).
"""

from tpu_cc_manager.lint.base import Finding, LintContext  # noqa: F401
