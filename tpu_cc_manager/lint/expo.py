"""Prometheus exposition-format lint for the agent's /metrics output.

A scraper that rejects one malformed line drops the WHOLE scrape, so a
regression in MetricsRegistry.render_prometheus (an unescaped label value,
a histogram bucket out of order, a sample with no TYPE) silently blinds
the fleet. This lint validates the invariants a real Prometheus parser
enforces, plus the histogram contract promtool checks:

- sample lines parse: ``name{label="value",...} value`` with valid metric
  and label names, and label values using only the three legal escapes
  (``\\``, ``\"``, ``\n``);
- every sampled metric family has exactly one # HELP and one # TYPE,
  declared before its first sample;
- histogram families: ``le`` parses as a float or ``+Inf``, bucket counts
  are non-decreasing as ``le`` increases (cumulative), the ``+Inf`` bucket
  exists, and ``_count`` equals the ``+Inf`` bucket per label set.

Run modes (the cclint driver runs the seeded mode as part of
``python -m tpu_cc_manager.lint``; ``hack/check_metrics_lint.py`` remains
as a standalone shim over this module):

  python3 hack/check_metrics_lint.py                # lint a seeded live registry
  python3 hack/check_metrics_lint.py --url URL      # lint a live /metrics scrape
  python3 hack/check_metrics_lint.py --file PATH    # lint a saved exposition

Also imported by tests/test_metrics_lint.py as a fast tier-1 check.
"""

from __future__ import annotations

import argparse
import re
import sys

_METRIC_NAME_RE = re.compile(r"^[a-zA-Z_:][a-zA-Z0-9_:]*$")
_LABEL_NAME_RE = re.compile(r"[a-zA-Z_][a-zA-Z0-9_]*")
# A sample line: name, optional {labels}, value, optional timestamp.
_SAMPLE_RE = re.compile(
    r"^(?P<name>[a-zA-Z_:][a-zA-Z0-9_:]*)"
    r"(?:\{(?P<labels>.*)\})?"
    r"\s+(?P<value>\S+)(?:\s+(?P<ts>-?\d+))?$"
)
_HIST_SUFFIXES = ("_bucket", "_sum", "_count")


def _parse_labels(raw: str, line_no: int, problems: list[str]) -> dict | None:
    """Parse a {..} label body with exposition-format escaping rules."""
    labels: dict[str, str] = {}
    i = 0
    n = len(raw)
    while i < n:
        m = _LABEL_NAME_RE.match(raw[i:])
        eq = raw.find("=", i)
        if eq < 0 or m is None or i + m.end() != eq:
            problems.append(f"line {line_no}: bad label name at offset {i}: {raw[i:]!r}")
            return None
        name = raw[i:eq]
        if eq + 1 >= n or raw[eq + 1] != '"':
            problems.append(f"line {line_no}: label {name} value not quoted")
            return None
        j = eq + 2
        value_chars: list[str] = []
        while j < n:
            c = raw[j]
            if c == "\\":
                if j + 1 >= n or raw[j + 1] not in ('\\', '"', 'n'):
                    problems.append(
                        f"line {line_no}: label {name}: illegal escape "
                        f"{raw[j:j+2]!r} (only \\\\, \\\" and \\n are legal)"
                    )
                    return None
                value_chars.append({"\\": "\\", '"': '"', "n": "\n"}[raw[j + 1]])
                j += 2
            elif c == '"':
                break
            elif c == "\n":
                problems.append(f"line {line_no}: label {name}: raw newline in value")
                return None
            else:
                value_chars.append(c)
                j += 1
        else:
            problems.append(f"line {line_no}: label {name}: unterminated value")
            return None
        labels[name] = "".join(value_chars)
        i = j + 1  # past closing quote
        if i < n:
            if raw[i] != ",":
                problems.append(
                    f"line {line_no}: expected ',' between labels, got {raw[i]!r}"
                )
                return None
            i += 1
    return labels


def _family(name: str, types: dict[str, str]) -> str:
    """The declared family a sample belongs to (histogram/summary samples
    use suffixed series names)."""
    for suffix in _HIST_SUFFIXES + ("_total",):
        base = name[: -len(suffix)] if name.endswith(suffix) else None
        if base and types.get(base) in ("histogram", "summary"):
            return base
    return name


def lint(text: str) -> list[str]:
    """All exposition-format problems found in ``text`` (empty = clean)."""
    problems: list[str] = []
    helps: dict[str, int] = {}
    types: dict[str, str] = {}
    # family -> label-set-minus-le (as sorted tuple) -> [(le, count)]
    buckets: dict[str, dict[tuple, list[tuple[float, float]]]] = {}
    counts: dict[str, dict[tuple, float]] = {}
    sampled_families: dict[str, int] = {}

    for line_no, line in enumerate(text.splitlines(), start=1):
        if not line.strip():
            continue
        if line.startswith("#"):
            parts = line.split(None, 3)
            if len(parts) >= 3 and parts[1] in ("HELP", "TYPE"):
                name = parts[2]
                if not _METRIC_NAME_RE.match(name):
                    problems.append(f"line {line_no}: bad metric name {name!r}")
                    continue
                if parts[1] == "HELP":
                    if name in helps:
                        problems.append(
                            f"line {line_no}: duplicate HELP for {name} "
                            f"(first at line {helps[name]})"
                        )
                    helps[name] = line_no
                else:
                    if name in types:
                        problems.append(f"line {line_no}: duplicate TYPE for {name}")
                    if name in sampled_families:
                        problems.append(
                            f"line {line_no}: TYPE for {name} after its first "
                            f"sample (line {sampled_families[name]})"
                        )
                    types[name] = (parts[3].strip() if len(parts) > 3 else "")
            # other comments are legal and ignored
            continue
        m = _SAMPLE_RE.match(line)
        if m is None:
            problems.append(f"line {line_no}: unparseable sample: {line!r}")
            continue
        name = m.group("name")
        raw_labels = m.group("labels")
        labels = (
            _parse_labels(raw_labels, line_no, problems)
            if raw_labels is not None and raw_labels != ""
            else {}
        )
        if labels is None:
            continue
        try:
            value = float(m.group("value"))
        except ValueError:
            if m.group("value") not in ("+Inf", "-Inf", "NaN"):
                problems.append(
                    f"line {line_no}: unparseable value {m.group('value')!r}"
                )
                continue
            value = float(m.group("value").replace("Inf", "inf"))
        family = _family(name, types)
        sampled_families.setdefault(family, line_no)
        if types.get(family) == "histogram":
            key = tuple(sorted((k, v) for k, v in labels.items() if k != "le"))
            if name.endswith("_bucket"):
                le_raw = labels.get("le")
                if le_raw is None:
                    problems.append(f"line {line_no}: histogram bucket without le label")
                    continue
                try:
                    le = float("inf") if le_raw == "+Inf" else float(le_raw)
                except ValueError:
                    problems.append(f"line {line_no}: unparseable le {le_raw!r}")
                    continue
                buckets.setdefault(family, {}).setdefault(key, []).append((le, value))
            elif name.endswith("_count"):
                counts.setdefault(family, {})[key] = value

    for family in sorted(sampled_families):
        if family not in helps:
            problems.append(f"metric family {family} has samples but no # HELP")
        if family not in types:
            problems.append(f"metric family {family} has samples but no # TYPE")

    for family, by_labels in sorted(buckets.items()):
        for key, series in sorted(by_labels.items()):
            ordered = sorted(series)
            if [b for b, _ in ordered] != [b for b, _ in series]:
                problems.append(
                    f"{family}{dict(key)}: buckets not emitted in increasing le order"
                )
            les = [le for le, _ in ordered]
            if len(les) != len(set(les)):
                problems.append(f"{family}{dict(key)}: duplicate le bounds")
            vals = [v for _, v in ordered]
            if any(later < earlier for earlier, later in zip(vals, vals[1:])):
                problems.append(
                    f"{family}{dict(key)}: bucket counts are not cumulative "
                    f"(non-monotonic): {vals}"
                )
            if not les or les[-1] != float("inf"):
                problems.append(f"{family}{dict(key)}: missing +Inf bucket")
            else:
                count = counts.get(family, {}).get(key)
                if count is not None and count != vals[-1]:
                    problems.append(
                        f"{family}{dict(key)}: _count {count} != +Inf bucket {vals[-1]}"
                    )
    return problems


def _seeded_registry_text() -> str:
    """Render a live registry exercised through the real phase/finish path
    — including awkward label values — so the lint checks what the agent
    actually serves, not a synthetic fixture. The cclint surface checker
    (lint/surface.py) additionally requires every family declared in
    utils/metrics.py to appear in this render, so a new family cannot
    ship unseeded."""
    from tpu_cc_manager.utils.metrics import MetricsRegistry

    registry = MetricsRegistry()
    for mode in ("on", 'odd"mode\nwith\\escapes'):
        m = registry.start(mode)
        for phase in ("drain", "reset", "wait_ready"):
            with m.phase(phase):
                pass
        m.finish("ok")
    m = registry.start("off")
    m.result = "failed"
    m.finish("failed")
    registry.record_failure("attestation-failed")
    registry.record_failure('weird"reason')
    registry.record_retry("kube.get", "throttled")
    registry.record_retry("tpuvm.reset", 'odd"reason\nhere')
    registry.set_breaker_state("apiserver", "half_open")
    registry.set_breaker_state("device-cmd", "closed")
    registry.set_health_tier("device-node", 1, healthy=False)
    # Failure-containment families (ccmanager/remediation.py + slice
    # fencing), awkward outcome value included.
    registry.set_quarantined(True)
    registry.record_remediation_step("device-reset", "ok")
    registry.record_remediation_step("quarantine", 'odd"outcome')
    registry.record_barrier_fenced()
    # Crash-safe rollout families (ccmanager/rollout_state.py).
    registry.record_rollout_resume()
    registry.record_lease_transition()
    registry.record_lease_transition()
    registry.record_fenced_write()
    # Federated rollout families (ccmanager/federation.py).
    registry.record_federation_sync("ok")
    registry.record_federation_sync('odd"outcome\nhere')
    registry.record_federation_fence("parent-generation")
    registry.record_federation_fence('odd"reason\nhere')
    registry.set_federation_budget_spent(7)
    # Parent-plane partition tolerance (escrowed degraded mode).
    registry.set_federation_offline_seconds(12.5)
    registry.set_federation_escrow(3, 1)
    # Apiserver-outage autonomy families (ccmanager/intent_journal.py).
    registry.set_apiserver_connected(False)
    registry.set_offline_seconds(93.5)
    registry.record_journal_replay("completed")
    registry.record_journal_replay("rolled-back")
    registry.record_journal_replay('odd"outcome\nhere')
    registry.record_deferred_patch()
    # Fleet-scale orchestration family (kubeclient per-verb accounting).
    registry.record_apiserver_request("list")
    registry.record_apiserver_request("watch")
    registry.record_apiserver_request('odd"verb')
    # Fleet-churn families (preemption fast-drain + autoscaler interplay).
    registry.record_preemption("handoff")
    registry.record_preemption("clean")
    registry.record_preemption('odd"outcome')
    registry.record_node_adoption(3)
    registry.set_fast_drain_seconds(1.234)
    # Pipelined-transition families (overlap gauge + smoke fast path).
    registry.set_phase_overlap_seconds(22.5)
    registry.record_smoke_fastpath("hit")
    registry.record_smoke_fastpath("miss")
    registry.record_smoke_fastpath('odd"outcome\nhere')
    # Live serving telemetry (serve/ + obs/slo.py), awkward node name
    # included.
    registry.observe_serve_request("serve-node-0", 0.042)
    registry.observe_serve_request("serve-node-0", 0.180)
    registry.observe_serve_request('odd"node\nname', 1.5)
    registry.set_serve_queue_depth("serve-node-0", 7)
    registry.set_serve_inflight("serve-node-0", 4)
    registry.record_serve_outcome("serve-node-0", "completed", 2)
    registry.record_serve_outcome("serve-node-0", "bounced")
    registry.record_serve_outcome("serve-node-0", "requeued")
    registry.record_serve_outcome("serve-node-0", "shed", 2)
    registry.record_serve_outcome('odd"node', 'odd"outcome')
    registry.record_serve_lost(1)
    registry.record_serve_deadline_miss("serve-node-0", 3)
    registry.record_serve_deadline_miss('odd"node\nname')
    registry.set_serve_offered_rps(997.25)
    registry.record_slo_pause()
    registry.set_serve_goodput(812.5)
    registry.set_serve_slo(30.0, 0.059, 0.2)
    registry.set_serve_slo(300.0, None, 0.0)  # empty window: no p99
    # Zero-bounce flip families (serve/ handoff + ccmanager prestage).
    registry.record_serve_handoff("accepted", 3)
    registry.record_serve_handoff("fallback")
    registry.record_serve_handoff('odd"outcome')
    registry.set_spare_prestage_seconds(31.3)
    # Capacity-ledger inputs (obs/fleet.py headroom judgment).
    registry.set_serve_hbm_bw_util("serve-node-0", 0.73)
    registry.set_serve_hbm_bw_util('odd"node\nname', 0.99)
    registry.set_prestage_in_progress(True)
    # Continuous-prestage ledger families (ccmanager/rolling.py
    # continuous_prestage, record v7), awkward outcome value included.
    registry.set_prestage_reserved(2)
    registry.set_prestage_headroom_nodes(1)
    registry.record_prestage("reserved")
    registry.record_prestage("armed")
    registry.record_prestage("held")
    registry.record_prestage("converged")
    registry.record_prestage("invalidated")
    registry.record_prestage("degraded")
    registry.record_prestage("paused")
    registry.record_prestage('odd"outcome\nhere')
    # Fail-slow vetting families (obs/failslow.py peer-relative
    # gray-failure detection), hostile node/verdict labels included.
    registry.set_failslow_suspect("serve-node-0", True)
    registry.set_failslow_suspect('odd"node\nname', False)
    registry.set_failslow_deviation("serve-node-0", 3.4142)
    registry.set_failslow_deviation('odd"node\nname', 0.98)
    registry.record_failslow_verdict("serve-node-0", "confirmed")
    registry.record_failslow_verdict("serve-node-0", "cleared")
    registry.record_failslow_verdict('odd"node\nname', 'odd"verdict')
    return registry.render_prometheus()


def _seeded_fleet_text() -> str:
    """The fleet gateway's MERGED exposition over seeded per-node
    registries — what ``obs/fleet.py`` actually serves at fleet
    ``/metrics``. Two full seeded agents plus one partial-overlap agent
    (different node names, a subset of families) exercise the merge's
    HELP/TYPE dedup, label-preserving summation and histogram
    conservation, then the fleet's own ``tpu_cc_fleet_*`` families are
    appended by the gateway's rebuild — so federation regressions fail
    the same lint the per-agent render does."""
    from tpu_cc_manager.obs import fleet as fleet_mod
    from tpu_cc_manager.utils.metrics import MetricsRegistry

    partial = MetricsRegistry()
    partial.observe_serve_request("fleet-node-2", 0.021)
    partial.observe_serve_request("fleet-node-2", 2.75)
    partial.set_serve_queue_depth("fleet-node-2", 1)
    partial.set_serve_hbm_bw_util("fleet-node-2", 0.42)
    partial.record_serve_outcome("fleet-node-2", "completed", 5)
    gateway = fleet_mod.FleetGateway(targets={
        "agent-a": fleet_mod.local_target(_SeededRegistry()),
        "agent-b": fleet_mod.local_target(_SeededRegistry()),
        "agent-c": fleet_mod.local_target(partial),
    })
    gateway.scrape_once()
    return gateway.metrics_text()


class _SeededRegistry:
    """Duck-typed registry whose render IS the seeded exposition — so
    the fleet seed reuses _seeded_registry_text verbatim (hostile label
    values included) without re-driving the setters."""

    def render_prometheus(self) -> str:
        return _seeded_registry_text()


def main(argv: list[str] | None = None) -> int:
    parser = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    source = parser.add_mutually_exclusive_group()
    source.add_argument("--url", help="scrape this /metrics URL and lint it")
    source.add_argument("--file", help="lint a saved exposition file")
    source.add_argument(
        "--fleet", action="store_true",
        help="lint the fleet gateway's MERGED exposition over seeded "
        "per-node registries (obs/fleet.py federation)",
    )
    args = parser.parse_args(argv)

    if args.url:
        import urllib.request

        with urllib.request.urlopen(args.url, timeout=10) as resp:
            text = resp.read().decode()
    elif args.file:
        with open(args.file, encoding="utf-8") as f:
            text = f.read()
    elif args.fleet:
        text = _seeded_fleet_text()
    else:
        text = _seeded_registry_text()

    problems = lint(text)
    for p in problems:
        print(f"LINT: {p}", file=sys.stderr)
    print(
        f"checked {len(text.splitlines())} lines: "
        + ("OK" if not problems else f"{len(problems)} problem(s)")
    )
    return 1 if problems else 0


if __name__ == "__main__":
    sys.exit(main())
