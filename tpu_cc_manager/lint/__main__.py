"""cclint driver: ``python -m tpu_cc_manager.lint``.

Runs every contract checker over the package plus the Prometheus
exposition lint's seeded live-registry render, filters findings through
the committed baseline, and exits non-zero on anything new. ``--json``
emits the machine-readable report CI archives.
"""

from __future__ import annotations

import argparse
import json
import os
import re
import sys
import time

from tpu_cc_manager.lint import base, baseline as baseline_mod, expo
from tpu_cc_manager.lint import crash, journal, locks, surface, waits
from tpu_cc_manager.lint.base import Finding

CHECKERS = (locks, waits, crash, journal, surface)


def _repo_root() -> str:
    """The repo root: the directory holding the tpu_cc_manager package."""
    return os.path.dirname(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    )


def run(root: str, skip_expo: bool = False) -> list[Finding]:
    ctx = base.build_context(root)
    # One seeded render serves both the surface checker's metric-unseeded
    # sub-check and the exposition pass below.
    seeded = surface.seeded_render()
    findings: list[Finding] = []
    for checker in CHECKERS:
        if checker is surface:
            findings.extend(surface.check(ctx, seeded_render_text=seeded))
        else:
            findings.extend(checker.check(ctx))
    if not skip_expo and seeded is not None:
        for problem in expo.lint(seeded):
            findings.append(
                Finding(
                    checker="expo",
                    path="tpu_cc_manager/utils/metrics.py",
                    line=1,
                    message=f"exposition lint: {problem}",
                    symbol="exposition",
                    # Fingerprints are line-independent by design; the
                    # problem text leads with the exposition line number,
                    # which shifts whenever a family is added.
                    detail=re.sub(r"^line \d+:\s*", "", problem)[:80],
                )
            )
    findings.sort(key=lambda f: (f.path, f.line, f.checker))
    return findings


def main(argv: list[str] | None = None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m tpu_cc_manager.lint",
        description="contract-aware static analysis (see docs/cclint.md)",
    )
    parser.add_argument("--root", default=None, help="repo root (default: auto)")
    parser.add_argument(
        "--baseline", default=None, help=f"baseline path (default: <root>/{baseline_mod.BASELINE_FILE})"
    )
    parser.add_argument(
        "--json", action="store_true", help="machine-readable report on stdout"
    )
    parser.add_argument(
        "--write-baseline", action="store_true",
        help="grandfather every current finding (reasons stubbed TODO)",
    )
    parser.add_argument(
        "--skip-expo", action="store_true",
        help="skip the Prometheus exposition lint pass",
    )
    args = parser.parse_args(argv)

    root = args.root or _repo_root()
    started = time.monotonic()
    findings = run(root, skip_expo=args.skip_expo)
    if args.write_baseline:
        path = baseline_mod.save(root, findings, args.baseline)
        print(f"wrote {len(set(f.fingerprint for f in findings))} entries to {path}")
        return 0
    known = baseline_mod.load(root, args.baseline)
    new, grandfathered, stale = baseline_mod.split(findings, known)
    elapsed = time.monotonic() - started

    if args.json:
        print(
            json.dumps(
                {
                    "ok": not new,
                    "elapsed_s": round(elapsed, 3),
                    "new": [f.to_dict() for f in new],
                    "grandfathered": [f.to_dict() for f in grandfathered],
                    "stale_baseline_entries": stale,
                },
                indent=2,
            )
        )
    else:
        for f in new:
            print(f"{f.path}:{f.line}: [{f.checker}] {f.message}")
            print(f"    fingerprint: {f.fingerprint}")
        for fp in stale:
            print(f"stale baseline entry (no longer found): {fp}")
        print(
            f"cclint: {len(new)} new, {len(grandfathered)} grandfathered, "
            f"{len(stale)} stale baseline entr{'y' if len(stale) == 1 else 'ies'} "
            f"({elapsed:.2f}s)"
        )
        if new:
            print(
                "fix the findings, or (deliberate keeps only) add baseline "
                f"entries with reasons to {baseline_mod.BASELINE_FILE}"
            )
    return 1 if new else 0


if __name__ == "__main__":
    sys.exit(main())
