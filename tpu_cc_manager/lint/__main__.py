"""cclint driver: ``python -m tpu_cc_manager.lint``.

Runs every contract checker over the package (plus the kill-at suites
under ``tests/`` for the checkers that read them), executes the
Prometheus exposition lint's seeded live-registry render, filters
findings through the committed baseline, and exits non-zero on anything
new — or on a STALE baseline entry: an entry whose violation is gone is
debt that must be deleted in the same change that fixed it.

``--json`` emits the machine-readable report CI archives; the default
text output is shaped for the GitHub problem matcher
(``.github/cclint-problem-matcher.json``), so findings surface as PR
annotations. ``--changed-only <git-ref>`` is the fast review mode: the
ANALYSIS still runs whole-package (the interprocedural checkers need
the full call graph), but only findings in files changed since
``<git-ref>`` are reported — stale-baseline detection stays global.
"""

from __future__ import annotations

import argparse
import json
import os
import re
import subprocess
import sys
import time

from tpu_cc_manager.lint import base, baseline as baseline_mod, expo
from tpu_cc_manager.lint import (
    crash,
    crashpoints,
    fenced,
    journal,
    locks,
    surface,
    waits,
)
from tpu_cc_manager.lint.base import Finding

CHECKERS = (locks, waits, crash, journal, fenced, crashpoints, surface)


def _repo_root() -> str:
    """The repo root: the directory holding the tpu_cc_manager package."""
    return os.path.dirname(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    )


def run(root: str, skip_expo: bool = False) -> list[Finding]:
    ctx = base.build_context(root)
    # One seeded render serves both the surface checker's metric-unseeded
    # sub-check and the exposition pass below.
    seeded = surface.seeded_render()
    findings: list[Finding] = []
    for checker in CHECKERS:
        if checker is surface:
            findings.extend(surface.check(ctx, seeded_render_text=seeded))
        else:
            findings.extend(checker.check(ctx))
    if not skip_expo and seeded is not None:
        for problem in expo.lint(seeded):
            findings.append(
                Finding(
                    checker="expo",
                    path="tpu_cc_manager/utils/metrics.py",
                    line=1,
                    message=f"exposition lint: {problem}",
                    symbol="exposition",
                    # Fingerprints are line-independent by design; the
                    # problem text leads with the exposition line number,
                    # which shifts whenever a family is added.
                    detail=re.sub(r"^line \d+:\s*", "", problem)[:80],
                )
            )
    findings.sort(key=lambda f: (f.path, f.line, f.checker))
    return findings


def changed_files(root: str, ref: str) -> set[str] | None:
    """Repo-relative paths changed since ``ref`` (committed diff plus
    untracked files); None when git cannot answer — the caller falls
    back to full reporting rather than silently reporting nothing."""
    try:
        diff = subprocess.run(
            ["git", "-C", root, "diff", "--name-only", ref],
            capture_output=True, text=True, timeout=30, check=True,
        )
        untracked = subprocess.run(
            ["git", "-C", root, "ls-files", "--others",
             "--exclude-standard"],
            capture_output=True, text=True, timeout=30, check=True,
        )
    except (OSError, subprocess.SubprocessError):
        return None
    out = set()
    for line in diff.stdout.splitlines() + untracked.stdout.splitlines():
        line = line.strip()
        if line:
            out.add(line)
    return out


def main(argv: list[str] | None = None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m tpu_cc_manager.lint",
        description="contract-aware static analysis (see docs/cclint.md)",
    )
    parser.add_argument("--root", default=None, help="repo root (default: auto)")
    parser.add_argument(
        "--baseline", default=None, help=f"baseline path (default: <root>/{baseline_mod.BASELINE_FILE})"
    )
    parser.add_argument(
        "--json", action="store_true", help="machine-readable report on stdout"
    )
    parser.add_argument(
        "--json-file", metavar="PATH", default=None,
        help="also write the machine-readable report to PATH (one "
        "analysis run serves both the annotated text output and the "
        "archived report)",
    )
    parser.add_argument(
        "--write-baseline", action="store_true",
        help="grandfather every current finding (existing reasons are "
        "preserved; new entries get TODO stubs to hand-edit; entries "
        "whose violations are gone are pruned)",
    )
    parser.add_argument(
        "--skip-expo", action="store_true",
        help="skip the Prometheus exposition lint pass",
    )
    parser.add_argument(
        "--changed-only", metavar="GIT_REF", default=None,
        help="fast review mode: report only findings in files changed "
        "since GIT_REF (full analysis still runs; stale-baseline "
        "detection stays global)",
    )
    args = parser.parse_args(argv)

    root = args.root or _repo_root()
    started = time.monotonic()
    findings = run(root, skip_expo=args.skip_expo)
    if args.write_baseline:
        path = baseline_mod.save(root, findings, args.baseline)
        print(f"wrote {len(set(f.fingerprint for f in findings))} entries to {path}")
        return 0
    known = baseline_mod.load(root, args.baseline)
    new, grandfathered, stale = baseline_mod.split(findings, known)
    scoped = None
    if args.changed_only is not None:
        scoped = changed_files(root, args.changed_only)
        if scoped is None:
            print(
                f"--changed-only: git diff against {args.changed_only!r} "
                "failed; reporting everything", file=sys.stderr,
            )
        else:
            new = [f for f in new if f.path in scoped]
    elapsed = time.monotonic() - started

    ok = not new and not stale
    report = json.dumps(
        {
            "ok": ok,
            "elapsed_s": round(elapsed, 3),
            "changed_only": args.changed_only,
            "new": [f.to_dict() for f in new],
            "grandfathered": [f.to_dict() for f in grandfathered],
            "stale_baseline_entries": stale,
        },
        indent=2,
    )
    if args.json_file:
        with open(args.json_file, "w", encoding="utf-8") as fh:
            fh.write(report + "\n")
    if args.json:
        print(report)
    else:
        for f in new:
            print(f"{f.path}:{f.line}: [{f.checker}] {f.message}")
            print(f"    fingerprint: {f.fingerprint}")
        for fp in stale:
            # Same shape the problem matcher parses; the baseline file
            # is where the fix goes.
            print(
                f"{baseline_mod.BASELINE_FILE}:1: [baseline] stale entry "
                f"{fp} — its violation is fixed; delete the entry"
            )
        print(
            f"cclint: {len(new)} new, {len(grandfathered)} grandfathered, "
            f"{len(stale)} stale baseline entr{'y' if len(stale) == 1 else 'ies'} "
            f"({elapsed:.2f}s)"
            + (f" [changed-only vs {args.changed_only}]" if scoped is not None else "")
        )
        if new:
            print(
                "fix the findings, or (deliberate keeps only) add baseline "
                f"entries with reasons to {baseline_mod.BASELINE_FILE}"
            )
        if stale:
            print(
                "stale baseline entries are a HARD error: delete them from "
                f"{baseline_mod.BASELINE_FILE} (the violations they "
                "grandfathered are gone)"
            )
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
