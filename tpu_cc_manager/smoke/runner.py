"""Smoke-workload registry and runners.

Each workload module exposes ``run(**kwargs) -> dict`` returning at least
``{"ok": bool, "workload": str}`` plus workload-specific measurements
(tflops, tokens_per_sec, mfu…). The manager invokes workloads through
``run_workload_subprocess`` so the TPU is acquired and released by a child
process, never by the long-lived agent.
"""

from __future__ import annotations

import importlib
import json
import logging
import os
import shutil
import subprocess
import sys
import tempfile
import time

from tpu_cc_manager.obs import trace as obs_trace
from tpu_cc_manager.utils import retry as retry_mod

log = logging.getLogger(__name__)

WORKLOADS = {
    "matmul": "tpu_cc_manager.smoke.matmul",
    "llama": "tpu_cc_manager.smoke.llama_infer",
    "resnet": "tpu_cc_manager.smoke.resnet_train",
}


class SmokeError(Exception):
    """Workload failed — treated like a device verification failure."""


class SmokeConfigError(SmokeError, ValueError):
    """Bad workload PARAMETERS (non-dividing pallas blocks, unknown size
    name): a user misconfiguration, reported as the structured JSON error
    line — distinct from runtime defects, whose tracebacks must survive.
    Also a ValueError: in-process callers validating parameters (tests,
    bench) keep the stdlib-idiomatic contract."""


# ---------------------------------------------------------------------------
# Two-phase COMPILE→DISPATCH warmup gate
# ---------------------------------------------------------------------------
# A CC flip's ~20 s wait_ready boot-wait and the smoke's compile span are
# both serial, device-free stretches — the gate lets the manager overlap
# them: the smoke subprocess is launched while the runtime is still
# booting, does everything up to (but not including) its first device
# dispatch, then BLOCKS until the parent releases the gate — which the
# manager does only after wait_ready returned and attestation passed, so
# no device work ever runs on an unready or unattested runtime.

#: Path of the gate file; its EXISTENCE releases dispatch. Set by the
#: parent (SmokeWarmup) in the child's environment; unset = no gate.
DISPATCH_GATE_ENV = "CC_SMOKE_DISPATCH_GATE"
#: Pid of the process that owns the gate. If it dies before releasing,
#: the child exits instead of waiting out the timeout as an orphan — a
#: SIGKILLed manager must not leave warmup subprocesses behind.
GATE_PARENT_PID_ENV = "CC_SMOKE_GATE_PARENT_PID"
#: Upper bound on the gate wait (seconds); a gate never released within
#: it fails the workload loudly rather than hanging the child forever.
GATE_TIMEOUT_ENV = "CC_SMOKE_GATE_TIMEOUT_S"

DEFAULT_GATE_TIMEOUT_S = 600.0
GATE_POLL_S = 0.05
_COMPILED_SUFFIX = ".compiled"


def compiled_sentinel(gate_path: str) -> str:
    """Sentinel file the child touches when its COMPILE phase is done
    (imports, model build, AOT compiles) and it is about to block on the
    gate — the parent reads its mtime as the compile-span end."""
    return gate_path + _COMPILED_SUFFIX


def _pid_alive(pid: int) -> bool:
    try:
        os.kill(pid, 0)
    except ProcessLookupError:
        return False
    except PermissionError:
        return True
    return True


def await_dispatch_gate(compile_fns: tuple = ()) -> bool:
    """Workload-side gate: called at the COMPILE→DISPATCH boundary (after
    imports and setup, strictly before the first device dispatch).

    No-op (returns False) unless the parent armed the gate via
    ``CC_SMOKE_DISPATCH_GATE``. Otherwise: run ``compile_fns`` (advisory
    AOT compiles — with the persistent XLA cache on, the dispatch-path
    recompile is a disk hit), touch the compiled sentinel, then block
    until the gate file appears. Raises :class:`SmokeError` — the child
    exits with the one-JSON-line failure — when the gate times out or
    the parent pid named in ``CC_SMOKE_GATE_PARENT_PID`` died without
    releasing (orphan protection: a SIGKILLed manager's warmup child
    must terminate itself, never dispatch, and never linger)."""
    gate = os.environ.get(DISPATCH_GATE_ENV)
    if not gate:
        return False
    for fn in compile_fns:
        try:
            fn()
        except Exception as e:  # noqa: BLE001 - AOT warm is advisory
            log.warning("warmup AOT compile failed (advisory): %s", e)
    try:
        with open(compiled_sentinel(gate), "w", encoding="utf-8") as f:
            f.write(str(os.getpid()))
    except OSError as e:
        log.warning("could not touch compiled sentinel for %s: %s", gate, e)
    try:
        timeout_s = float(
            os.environ.get(GATE_TIMEOUT_ENV) or DEFAULT_GATE_TIMEOUT_S
        )
    except ValueError:
        timeout_s = DEFAULT_GATE_TIMEOUT_S
    parent = os.environ.get(GATE_PARENT_PID_ENV, "")
    parent_pid = int(parent) if parent.isdigit() else None
    state = {"orphan": False}

    def released_or_orphaned() -> bool:
        if os.path.exists(gate):
            return True
        if parent_pid is not None and not _pid_alive(parent_pid):
            state["orphan"] = True
            return True
        return False

    opened = retry_mod.poll_until(
        released_or_orphaned, timeout_s, GATE_POLL_S
    )
    if state["orphan"]:
        raise SmokeError(
            f"dispatch gate abandoned: parent pid {parent_pid} is gone — "
            "exiting instead of dispatching as an orphan"
        )
    if not opened:
        raise SmokeError(
            f"dispatch gate {gate} not released within {timeout_s:.0f}s"
        )
    return True


def run_workload(name: str, **kwargs) -> dict:
    """Run a workload in-process (tests, bench)."""
    if name not in WORKLOADS:
        raise SmokeError(f"unknown smoke workload {name!r} (have {sorted(WORKLOADS)})")
    with obs_trace.span("smoke.run", workload=name) as sp:
        mod = importlib.import_module(WORKLOADS[name])
        result = mod.run(**kwargs)
        sp.set_attribute("backend", result.get("backend"))
        if not result.get("ok"):
            raise SmokeError(f"workload {name} reported failure: {result}")
    return result


def _subprocess_cmd_env(
    name: str,
    force_cpu: bool,
    extra_args: list[str] | None,
    extra_env: dict[str, str] | None,
) -> tuple[list[str], dict[str, str] | None]:
    """The shared ``python -m tpu_cc_manager.smoke`` command + child env
    (one place, so the blocking and warmup spawns can never diverge)."""
    if name not in WORKLOADS:
        raise SmokeError(f"unknown smoke workload {name!r} (have {sorted(WORKLOADS)})")
    env = None
    if force_cpu or extra_env:
        env = dict(os.environ)
        if force_cpu:
            env.pop("PALLAS_AXON_POOL_IPS", None)
            env["JAX_PLATFORMS"] = "cpu"
        if extra_env:
            env.update(extra_env)
    cmd = [sys.executable, "-m", "tpu_cc_manager.smoke", "--workload", name]
    if extra_args:
        cmd.extend(extra_args)
    return cmd, env


def _parse_smoke_stdout(
    name: str, stdout: str, returncode: int, stderr: str
) -> dict:
    """Parse the final JSON line of a smoke child's stdout; raises
    :class:`SmokeError` unless the child exited 0 with an ok result."""
    last_json = None
    for line in stdout.splitlines():
        line = line.strip()
        if line.startswith("{"):
            try:
                last_json = json.loads(line)
            except json.JSONDecodeError:
                continue
    if returncode != 0:
        raise SmokeError(
            f"workload {name} exited rc={returncode}: "
            f"{(stderr or '')[-512:]}"
        )
    if not last_json or not last_json.get("ok"):
        raise SmokeError(f"workload {name} produced no passing result: {last_json}")
    return last_json


def run_workload_subprocess(
    name: str,
    timeout_s: float = 900.0,
    force_cpu: bool = False,
    cwd: str | None = None,
    extra_args: list[str] | None = None,
    extra_env: dict[str, str] | None = None,
) -> dict:
    """Run a workload as ``python -m tpu_cc_manager.smoke`` and parse the
    final JSON line from its stdout.

    ``force_cpu`` pins the child to the CPU backend (and strips the image's
    TPU-tunnel trigger variable) — the bench scripts use it when the
    accelerator failed preflight. ``extra_env`` overlays the child's
    environment (the cold/warm compilation-cache bench points
    JAX_COMPILATION_CACHE_DIR at its own directory this way). This is the
    single subprocess-smoke contract; bench.py and bench_ab.py import it
    rather than keeping copies in sync.
    """
    cmd, env = _subprocess_cmd_env(name, force_cpu, extra_args, extra_env)
    log.info("running smoke workload: %s", " ".join(cmd))
    with obs_trace.span(
        "smoke.subprocess", workload=name, force_cpu=force_cpu
    ) as sp:
        try:
            proc = subprocess.run(
                cmd, capture_output=True, timeout=timeout_s, text=True,
                env=env, cwd=cwd,
            )
        except subprocess.TimeoutExpired as e:
            raise SmokeError(f"workload {name} timed out after {timeout_s:.0f}s") from e
        last_json = _parse_smoke_stdout(
            name, proc.stdout, proc.returncode, proc.stderr or ""
        )
        sp.set_attribute("backend", last_json.get("backend"))
    log.info("smoke workload %s passed: %s", name, last_json)
    return last_json


class SmokeWarmup:
    """Parent-side handle on a two-phase smoke subprocess.

    The child is spawned immediately with the dispatch gate armed: it
    runs its COMPILE phase (interpreter start, jax import, model build,
    advisory AOT compiles) concurrently with whatever the caller is
    doing — the manager starts it alongside ``wait_ready`` so the boot
    wait absorbs the compile span — and then blocks. :meth:`release`
    opens the gate (the manager calls it only after the runtime is ready
    AND attestation passed); :meth:`result` joins the child and returns
    the parsed result with the warmup timing folded in; :meth:`cancel`
    kills the child on any path where its dispatch must never run
    (fast-path hit, verify failure, pipeline unwinding). A parent that
    dies without any of these (real SIGKILL) is covered child-side: the
    gate wait watches the parent pid and exits instead of orphaning
    (:func:`await_dispatch_gate`).

    Timing fields injected into the result dict:

    - ``warmup_compile_s`` — spawn → compiled-sentinel (the span a serial
      pipeline would have paid inside its smoke phase);
    - ``warmup_overlap_s`` — the part of that span that ran before
      :meth:`release` (what the overlap actually saved; the remainder, if
      any, still shows up inside the caller's smoke phase);
    - ``warmup_dispatch_s`` — release → exit.
    """

    def __init__(
        self,
        name: str,
        timeout_s: float = 900.0,
        force_cpu: bool = False,
        cwd: str | None = None,
        extra_args: list[str] | None = None,
        extra_env: dict[str, str] | None = None,
        gate_timeout_s: float | None = None,
    ) -> None:
        cmd, env = _subprocess_cmd_env(name, force_cpu, extra_args, extra_env)
        if env is None:
            env = dict(os.environ)
        self.name = name
        self._timeout_s = timeout_s
        self._tmp = tempfile.mkdtemp(prefix="tpu-cc-smoke-gate-")
        self._gate = os.path.join(self._tmp, "dispatch-gate")
        env[DISPATCH_GATE_ENV] = self._gate
        env[GATE_PARENT_PID_ENV] = str(os.getpid())
        if gate_timeout_s is not None:
            env[GATE_TIMEOUT_ENV] = str(gate_timeout_s)
        self._stdout_path = os.path.join(self._tmp, "stdout")
        self._stderr_path = os.path.join(self._tmp, "stderr")
        log.info("starting warmup smoke (gated dispatch): %s", " ".join(cmd))
        # File-backed stdio: no pipe to drain, so the parent never blocks
        # on child output and a killed parent can't wedge the child on a
        # full pipe either.
        try:
            with open(self._stdout_path, "w", encoding="utf-8") as out, open(
                self._stderr_path, "w", encoding="utf-8"
            ) as err:
                self._proc = subprocess.Popen(
                    cmd, stdout=out, stderr=err, env=env, cwd=cwd, text=True,
                )
        except BaseException:
            # A failed spawn (fork/exec pressure) must not strand the
            # gate directory — the caller degrades to the sync smoke and
            # would never reach cancel()/result() on this handle.
            shutil.rmtree(self._tmp, ignore_errors=True)
            raise
        self._t0 = time.monotonic()
        # Wall-clock twin of _t0: the compiled sentinel's mtime is wall
        # time, so EVERY span compared against it must be wall-clock too
        # (mixing in monotonic deltas would let an NTP step inflate
        # warmup_overlap_s and every downstream overlap_saved_s claim).
        # Monotonic time is used only for the subprocess timeout budget.
        self._t0_wall = time.time()
        self._released_at: float | None = None
        self._released_wall: float | None = None
        self._done = False

    @property
    def gate_path(self) -> str:
        return self._gate

    def compiled_after_s(self) -> float | None:
        """Seconds from spawn to the child's compiled sentinel (None while
        the COMPILE phase is still running or the sentinel never landed)."""
        try:
            mtime = os.path.getmtime(compiled_sentinel(self._gate))
        except OSError:
            return None
        return max(0.0, mtime - self._t0_wall)

    def died_during_warmup(self) -> bool:
        """True when the child exited before the gate was ever released —
        a warmup-infrastructure failure (e.g. the backend client choking
        on a mid-boot runtime), NOT a smoke verdict. The caller should
        fall back to the synchronous smoke instead of failing the flip on
        a run the serial path would have passed."""
        return self._released_at is None and self._proc.poll() is not None

    def release(self) -> None:
        """Open the dispatch gate. Idempotent; the caller must have
        established safe-to-dispatch (runtime ready, attestation passed)."""
        if self._released_at is not None:
            return
        with open(self._gate, "w", encoding="utf-8") as f:
            f.write("released")
        self._released_at = time.monotonic()
        self._released_wall = time.time()

    def cancel(self, reason: str = "") -> None:
        """Kill the child (no dispatch must run). Safe on any state —
        a child that already exited is just reaped."""
        if self._done:
            return
        self._done = True
        if self._proc.poll() is None:
            log.info(
                "cancelling warmup smoke %s%s", self.name,
                f" ({reason})" if reason else "",
            )
            self._proc.kill()
        try:
            self._proc.wait(timeout=10)
        except subprocess.TimeoutExpired:  # pragma: no cover - kill() sent
            log.warning("warmup smoke %s did not reap after kill", self.name)
        shutil.rmtree(self._tmp, ignore_errors=True)

    def result(self) -> dict:
        """Join the released child and return its parsed result (raises
        :class:`SmokeError` exactly like ``run_workload_subprocess``)."""
        if self._released_at is None:
            self.release()
        remaining = max(1.0, self._timeout_s - (time.monotonic() - self._t0))
        try:
            rc = self._proc.wait(timeout=remaining)
        except subprocess.TimeoutExpired as e:
            self.cancel("timeout")
            raise SmokeError(
                f"workload {self.name} timed out after {self._timeout_s:.0f}s"
            ) from e
        # All three spans on the WALL clock, like the sentinel mtime they
        # are compared against — one clock, so a step skews measurements
        # proportionally instead of letting min(wall, monotonic) pick an
        # inflated bound. (Measurement only; gate control flow never
        # depends on these.)
        compile_s = self.compiled_after_s()
        released_delta = max(0.0, self._released_wall - self._t0_wall)
        dispatch_s = max(0.0, time.time() - self._released_wall)
        try:
            with open(self._stdout_path, encoding="utf-8") as f:
                stdout = f.read()
            with open(self._stderr_path, encoding="utf-8") as f:
                stderr = f.read()
        except OSError:
            stdout, stderr = "", ""
        self._done = True
        shutil.rmtree(self._tmp, ignore_errors=True)
        last_json = _parse_smoke_stdout(self.name, stdout, rc, stderr)
        last_json["warmup_compile_s"] = (
            round(compile_s, 3) if compile_s is not None else None
        )
        # Only the pre-release part of the compile span was actually
        # hidden by the overlap; compile work after release shows up in
        # the caller's (timed) smoke phase and must not be double-counted
        # as saved. A missing sentinel (the child's write failed) means
        # the span is UNKNOWN: claim zero, never the maximum — an
        # inflated overlap would overstate every downstream
        # overlap_saved_s number.
        overlap = 0.0 if compile_s is None else min(
            compile_s, released_delta
        )
        last_json["warmup_overlap_s"] = round(max(0.0, overlap), 3)
        last_json["warmup_dispatch_s"] = round(dispatch_s, 3)
        log.info("warmup smoke %s passed: %s", self.name, last_json)
        return last_json

    def release_and_result(self) -> dict:
        self.release()
        return self.result()
