"""Smoke-workload registry and runners.

Each workload module exposes ``run(**kwargs) -> dict`` returning at least
``{"ok": bool, "workload": str}`` plus workload-specific measurements
(tflops, tokens_per_sec, mfu…). The manager invokes workloads through
``run_workload_subprocess`` so the TPU is acquired and released by a child
process, never by the long-lived agent.
"""

from __future__ import annotations

import importlib
import json
import logging
import subprocess
import sys

from tpu_cc_manager.obs import trace as obs_trace

log = logging.getLogger(__name__)

WORKLOADS = {
    "matmul": "tpu_cc_manager.smoke.matmul",
    "llama": "tpu_cc_manager.smoke.llama_infer",
    "resnet": "tpu_cc_manager.smoke.resnet_train",
}


class SmokeError(Exception):
    """Workload failed — treated like a device verification failure."""


class SmokeConfigError(SmokeError, ValueError):
    """Bad workload PARAMETERS (non-dividing pallas blocks, unknown size
    name): a user misconfiguration, reported as the structured JSON error
    line — distinct from runtime defects, whose tracebacks must survive.
    Also a ValueError: in-process callers validating parameters (tests,
    bench) keep the stdlib-idiomatic contract."""


def run_workload(name: str, **kwargs) -> dict:
    """Run a workload in-process (tests, bench)."""
    if name not in WORKLOADS:
        raise SmokeError(f"unknown smoke workload {name!r} (have {sorted(WORKLOADS)})")
    with obs_trace.span("smoke.run", workload=name) as sp:
        mod = importlib.import_module(WORKLOADS[name])
        result = mod.run(**kwargs)
        sp.set_attribute("backend", result.get("backend"))
        if not result.get("ok"):
            raise SmokeError(f"workload {name} reported failure: {result}")
    return result


def run_workload_subprocess(
    name: str,
    timeout_s: float = 900.0,
    force_cpu: bool = False,
    cwd: str | None = None,
    extra_args: list[str] | None = None,
    extra_env: dict[str, str] | None = None,
) -> dict:
    """Run a workload as ``python -m tpu_cc_manager.smoke`` and parse the
    final JSON line from its stdout.

    ``force_cpu`` pins the child to the CPU backend (and strips the image's
    TPU-tunnel trigger variable) — the bench scripts use it when the
    accelerator failed preflight. ``extra_env`` overlays the child's
    environment (the cold/warm compilation-cache bench points
    JAX_COMPILATION_CACHE_DIR at its own directory this way). This is the
    single subprocess-smoke contract; bench.py and bench_ab.py import it
    rather than keeping copies in sync.
    """
    if name not in WORKLOADS:
        raise SmokeError(f"unknown smoke workload {name!r} (have {sorted(WORKLOADS)})")
    env = None
    if force_cpu or extra_env:
        import os

        env = dict(os.environ)
        if force_cpu:
            env.pop("PALLAS_AXON_POOL_IPS", None)
            env["JAX_PLATFORMS"] = "cpu"
        if extra_env:
            env.update(extra_env)
    cmd = [sys.executable, "-m", "tpu_cc_manager.smoke", "--workload", name]
    if extra_args:
        cmd.extend(extra_args)
    log.info("running smoke workload: %s", " ".join(cmd))
    with obs_trace.span(
        "smoke.subprocess", workload=name, force_cpu=force_cpu
    ) as sp:
        try:
            proc = subprocess.run(
                cmd, capture_output=True, timeout=timeout_s, text=True,
                env=env, cwd=cwd,
            )
        except subprocess.TimeoutExpired as e:
            raise SmokeError(f"workload {name} timed out after {timeout_s:.0f}s") from e
        last_json = None
        for line in proc.stdout.splitlines():
            line = line.strip()
            if line.startswith("{"):
                try:
                    last_json = json.loads(line)
                except json.JSONDecodeError:
                    continue
        if proc.returncode != 0:
            raise SmokeError(
                f"workload {name} exited rc={proc.returncode}: "
                f"{(proc.stderr or '')[-512:]}"
            )
        if not last_json or not last_json.get("ok"):
            raise SmokeError(f"workload {name} produced no passing result: {last_json}")
        sp.set_attribute("backend", last_json.get("backend"))
    log.info("smoke workload %s passed: %s", name, last_json)
    return last_json
