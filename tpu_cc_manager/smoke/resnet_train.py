"""ResNet-50 training smoke workload: data-parallel train steps, MFU.

BASELINE.json configs[3] ("v5p-32: rolling CC reconfig under live JAX
ResNet-50 training"). The smoke proves the slice trains: synthetic
fixed-label batch, a few SGD steps, loss must strictly decrease and stay
finite; throughput (images/sec) and an MFU estimate are reported so the
north-star "≤3% MFU loss CC-on vs CC-off" is measurable by running the
same workload in both modes (BASELINE.md).
"""

from __future__ import annotations

import time
from functools import partial


from tpu_cc_manager.utils.tpu_info import generation_for
from tpu_cc_manager.utils.tpu_info import peak_flops_per_chip as _peak_flops_per_device


def run(size: str | None = None, batch: int | None = None, steps: int = 6,
        seed: int = 0) -> dict:
    import jax
    import jax.numpy as jnp
    import optax
    from flax.training import train_state
    from jax.sharding import NamedSharding, PartitionSpec as P

    from tpu_cc_manager.models.resnet import ResNet50, ResNetTiny
    from tpu_cc_manager.parallel.mesh import MeshSpec, make_mesh

    backend = jax.default_backend()
    n_dev = len(jax.devices())
    if size is None:
        size = "tiny" if backend == "cpu" else "resnet50"
    if size == "resnet50":
        model, image_size, num_classes = ResNet50(), 224, 1000
        default_batch = 64 * n_dev
    else:
        model, image_size, num_classes = ResNetTiny(), 32, 10
        default_batch = 8 * n_dev
    batch = batch or default_batch
    if batch % n_dev:
        from tpu_cc_manager.smoke.runner import SmokeConfigError

        raise SmokeConfigError(
            f"batch {batch} must divide evenly over {n_dev} device(s)"
        )

    mesh = make_mesh(MeshSpec(dcn=1, dp=-1, fsdp=1, tp=1))
    repl = NamedSharding(mesh, P())
    data_sharding = NamedSharding(mesh, P(("dcn", "dp", "fsdp")))

    class State(train_state.TrainState):
        batch_stats: dict

    # COMPILE→DISPATCH boundary (see smoke/runner.py): model/config build
    # above is host-side; the key generation and device_put below are the
    # first device work. Under a warmup gate the child blocks here until
    # dispatch releases.
    from tpu_cc_manager.smoke.runner import await_dispatch_gate

    await_dispatch_gate()
    key = jax.random.PRNGKey(seed)
    images = jax.device_put(
        jax.random.normal(key, (batch, image_size, image_size, 3), jnp.float32),
        data_sharding,
    )
    labels = jax.device_put(
        jax.random.randint(key, (batch,), 0, num_classes), data_sharding
    )

    def init_fn(rng):
        variables = model.init(rng, jnp.zeros((1, image_size, image_size, 3)), train=False)
        tx = optax.sgd(0.1, momentum=0.9)
        return State.create(
            apply_fn=model.apply,
            params=variables["params"],
            batch_stats=variables["batch_stats"],
            tx=tx,
        )

    with mesh:
        state = jax.jit(init_fn, out_shardings=repl)(key)

        # One loss definition shared by the train step and the oracle's
        # eval, so the oracle always compares the metric being optimized.
        def _loss(apply_fn, params, batch_stats, images, labels):
            logits, mutated = apply_fn(
                {"params": params, "batch_stats": batch_stats},
                images, train=True, mutable=["batch_stats"],
            )
            onehot = jax.nn.one_hot(labels, logits.shape[-1])
            loss = -jnp.mean(jnp.sum(jax.nn.log_softmax(logits) * onehot, axis=-1))
            return loss, mutated["batch_stats"]

        def train_step_impl(state, images, labels):
            def loss_fn(params):
                return _loss(
                    state.apply_fn, params, state.batch_stats, images, labels
                )

            (loss, new_stats), grads = jax.value_and_grad(loss_fn, has_aux=True)(
                state.params
            )
            state = state.apply_gradients(grads=grads)
            return state.replace(batch_stats=new_stats), loss


        # Multi-step chains compiled as whole programs: a per-step host
        # readback of the loss would put one dispatch+RTT per step inside
        # the clock — through a tunnelled chip that overhead exceeds the
        # step itself. Two programs total: a traced-length fori_loop chain
        # (one executable serves every chain length, for both training and
        # timing) and a cheap forward-only loss eval for the
        # oracle's before/after comparison.
        from jax import lax

        @partial(jax.jit, donate_argnums=(0,))
        def train_n(state, images, labels, n):
            return lax.fori_loop(
                0, n, lambda _, s: train_step_impl(s, images, labels)[0], state
            )

        @jax.jit
        def eval_loss(state, images, labels):
            return _loss(
                state.apply_fn, state.params, state.batch_stats, images, labels
            )[0]

        # Correctness oracle: loss after `steps` SGD steps must be finite
        # and strictly below the initial loss.
        loss_first = float(eval_loss(state, images, labels))
        state = train_n(state, images, labels, steps)
        loss_last = float(eval_loss(state, images, labels))
        losses = [loss_first, loss_last]

        # Differential timing (as in smoke/matmul.py): median T(4N) - median
        # T(N) cancels constant dispatch + readback overhead, leaving 3N
        # steps of pure device time. Sync via a host readback of state.step
        # (data-dependent on the whole chain) — on the tunnel backend
        # block_until_ready can return before work retires.
        import statistics

        def _timed(n: int, reps: int = 3) -> float:
            nonlocal state
            state = train_n(state, images, labels, n)
            int(state.step)
            times = []
            for _ in range(reps):
                t0 = time.perf_counter()
                state = train_n(state, images, labels, n)
                int(state.step)
                times.append(time.perf_counter() - t0)
            return statistics.median(times)

        diff = _timed(4 * steps) - _timed(steps)
        timing_valid = diff > 0
        dt = diff / (3 * steps) if timing_valid else None

    # FLOPs from the compiled executable when XLA reports them, else the
    # textbook 4.1 GFLOPs/image fwd x3 for fwd+bwd.
    try:
        lowered = jax.jit(train_step_impl).lower(state, images, labels)
        try:
            flops = lowered.cost_analysis()["flops"]
        except (KeyError, TypeError, NotImplementedError):
            flops = lowered.compile().cost_analysis()["flops"]
    except Exception:  # noqa: BLE001 - cost analysis is best-effort
        per_image = 4.1e9 if size == "resnet50" else 5e7
        flops = 3 * per_image * batch

    mfu = (
        flops / dt / (_peak_flops_per_device() * n_dev)
        if backend == "tpu" and timing_valid
        else 0.0
    )
    finite = all(l == l and abs(l) != float("inf") for l in losses)
    decreasing = losses[-1] < losses[0]
    return {
        "ok": bool(finite and decreasing),
        "workload": "resnet",
        "model": size,
        "backend": backend,
        "generation": generation_for(backend),
        "devices": n_dev,
        "batch": batch,
        "timing_valid": bool(timing_valid),
        "seconds_per_step": round(dt, 4) if timing_valid else None,
        "images_per_sec": round(batch / dt, 1) if timing_valid else None,
        "mfu": round(mfu, 4),
        "loss_first": round(losses[0], 4),
        "loss_last": round(losses[-1], 4),
    }


if __name__ == "__main__":
    import json

    print(json.dumps(run()))
