"""Llama inference smoke workload: prefill + greedy decode, tokens/sec.

BASELINE.json configs[2] ("v5p-8: drain→CC-on→re-admit, JAX Llama-2 7B
inference") and configs[4] (Llama-3-8B DP over DCN). As a smoke it must be
fast *and* an actual correctness oracle:

- sharded init over all visible devices (tp over heads when >1 device);
- one compiled prefill (full prompt into the KV cache) + one compiled
  decode step re-used for every generated token (static shapes);
- oracle: teacher-forced cached decode must reproduce the no-cache full
  forward's argmax sequence exactly — this catches wrong cache indexing,
  mask or RoPE bugs, the classic CC-mode-flip failure being "numerics
  changed after runtime restart".
"""

from __future__ import annotations

import time


def _pick_config(size: str | None):
    from tpu_cc_manager.models.llama import LlamaConfig

    import jax

    if size is None:
        size = "tiny" if jax.default_backend() == "cpu" else "500m"
    table = {
        "tiny": LlamaConfig.tiny,
        "500m": LlamaConfig.smoke_500m,
        "llama2-7b": LlamaConfig.llama2_7b,
        "llama3-8b": LlamaConfig.llama3_8b,
    }
    if size not in table:
        raise ValueError(f"unknown llama smoke size {size!r} (have {sorted(table)})")
    return size, table[size]()


def run(
    size: str | None = None,
    batch: int = 4,
    prompt_len: int = 32,
    decode_len: int = 32,
    seed: int = 0,
) -> dict:
    import flax.linen as nn
    import jax
    import jax.numpy as jnp

    from tpu_cc_manager.models.llama import LlamaModel
    from tpu_cc_manager.parallel.mesh import default_spec_for, make_mesh
    from tpu_cc_manager.parallel.sharding import logical_state_sharding

    size, cfg = _pick_config(size)
    n_dev = len(jax.devices())
    mesh = make_mesh(default_spec_for(n_dev, want_tp=n_dev > 1))
    model = LlamaModel(cfg)
    max_len = prompt_len + decode_len

    key = jax.random.PRNGKey(seed)
    prompt = jax.random.randint(key, (batch, prompt_len), 0, cfg.vocab_size)

    def boxed_init(rng):
        return model.init(rng, jnp.zeros((1, 8), jnp.int32))

    abstract = jax.eval_shape(boxed_init, key)
    shardings = logical_state_sharding(abstract, mesh)
    with mesh:
        variables = jax.jit(lambda r: nn.unbox(boxed_init(r)), out_shardings=shardings)(key)

        def prefill(variables, prompt, cache):
            logits, cache = model.apply(variables, prompt, cache=cache, position=0)
            return jnp.argmax(logits[:, -1], axis=-1), cache

        def decode_step(variables, token, cache, position):
            logits, cache = model.apply(
                variables, token[:, None], cache=cache, position=position
            )
            return jnp.argmax(logits[:, 0], axis=-1), cache

        prefill = jax.jit(prefill, donate_argnums=(2,))
        decode_step = jax.jit(decode_step, donate_argnums=(2,))

        # --- correctness oracle (tiny lengths, cache vs no-cache) --------
        oracle_len = min(8, prompt_len)
        full_logits, _ = jax.jit(model.apply)(variables, prompt[:, :oracle_len])
        expected = jnp.argmax(full_logits, axis=-1)
        cache = model.init_cache(batch, max_len)
        got = []
        for i in range(oracle_len):
            tok, cache = decode_step(variables, prompt[:, i], cache, i)
            got.append(tok)
        got = jnp.stack(got, axis=1)
        oracle_ok = bool(jnp.array_equal(got, expected))

        # --- timed run ---------------------------------------------------
        cache = model.init_cache(batch, max_len)
        tok, cache = prefill(variables, prompt, cache)
        tok.block_until_ready()
        t0 = time.perf_counter()
        position = prompt_len
        for _ in range(decode_len):
            tok, cache = decode_step(variables, tok, cache, position)
            position += 1
        tok.block_until_ready()
        dt = time.perf_counter() - t0

    tokens_per_sec = batch * decode_len / dt
    return {
        "ok": oracle_ok,
        "workload": "llama",
        "model": size,
        "backend": jax.default_backend(),
        "devices": n_dev,
        "params": cfg.param_count(),
        "batch": batch,
        "decode_len": decode_len,
        "tokens_per_sec": round(tokens_per_sec, 2),
        "ms_per_token": round(1e3 * dt / decode_len, 3),
        "oracle_ok": oracle_ok,
    }


if __name__ == "__main__":
    import json

    print(json.dumps(run()))
