"""Llama inference smoke workload: prefill + greedy decode, tokens/sec.

BASELINE.json configs[2] ("v5p-8: drain→CC-on→re-admit, JAX Llama-2 7B
inference") and configs[4] (Llama-3-8B DP over DCN). As a smoke it must be
fast *and* an actual correctness oracle:

- sharded init over all visible devices (tp over heads when >1 device);
- one compiled prefill (full prompt into the KV cache) + the ENTIRE greedy
  decode as one compiled ``lax.scan`` over static-length steps — the loop
  lives on device, so generating N tokens costs one dispatch, not N
  host round trips (tens of ms each through a tunnelled chip);
- oracle: teacher-forced cached decode (also a scan) must reproduce the
  no-cache full forward's argmax sequence exactly — this catches wrong
  cache indexing, mask or RoPE bugs, the classic CC-mode-flip failure
  being "numerics changed after runtime restart".
"""

from __future__ import annotations

import time

from tpu_cc_manager.utils.tpu_info import (
    generation_for,
    peak_flops_per_chip,
    peak_hbm_bytes_per_chip,
)


def _pick_config(size: str | None):
    import jax
    import jax.numpy as jnp

    from tpu_cc_manager.models.llama import LlamaConfig

    if size is None:
        size = "tiny" if jax.default_backend() == "cpu" else "500m"
    table = {
        "tiny": LlamaConfig.tiny,
        "500m": LlamaConfig.smoke_500m,
        "llama3.2-1b": LlamaConfig.llama3_2_1b,
        "llama3.2-3b": LlamaConfig.llama3_2_3b,
        "llama2-7b": LlamaConfig.llama2_7b,
        "llama3-8b": LlamaConfig.llama3_8b,
        "llama3.1-8b": LlamaConfig.llama3_1_8b,
    }
    if size not in table:
        from tpu_cc_manager.smoke.runner import SmokeConfigError

        raise SmokeConfigError(
            f"unknown llama smoke size {size!r} (have {sorted(table)})"
        )
    # Inference-only workload: bf16 parameter storage. Decode reads every
    # weight every step, so tokens/s is bounded by param bytes — bf16
    # doubles it and is what fits the 7B configs on one chip.
    return size, table[size](param_dtype=jnp.bfloat16)


def run(
    size: str | None = None,
    batch: int = 4,
    prompt_len: int = 32,
    decode_len: int = 32,
    seed: int = 0,
    cache_position_offset: int = 0,
) -> dict:
    """``cache_position_offset`` is a test-only fault hook: it shifts every
    cached-decode position by the given amount, emulating the classic
    off-by-one cache-indexing bug. tests/test_smoke.py proves the decode
    oracle FAILS when it is non-zero — an oracle that can't catch the bug
    it exists for is decoration."""
    import flax.linen as nn
    import jax
    import jax.numpy as jnp

    from tpu_cc_manager.models.llama import LlamaModel
    from tpu_cc_manager.parallel.mesh import default_spec_for, make_mesh
    from tpu_cc_manager.parallel.sharding import logical_state_sharding

    size, cfg = _pick_config(size)
    n_dev = len(jax.devices())
    mesh = make_mesh(default_spec_for(n_dev, want_tp=n_dev > 1))
    model = LlamaModel(cfg)
    max_len = prompt_len + decode_len

    def boxed_init(rng):
        return model.init(rng, jnp.zeros((1, 8), jnp.int32))

    # Shape/sharding derivation from ABSTRACT values: nothing above the
    # gate dispatches a computation (eval_shape only traces).
    key_aval = jax.eval_shape(lambda: jax.random.PRNGKey(seed))
    abstract = jax.eval_shape(boxed_init, key_aval)
    shardings = logical_state_sharding(abstract, mesh)
    # COMPILE→DISPATCH boundary (see smoke/runner.py): imports, config
    # and shape/sharding derivation above are host-side; the key/prompt
    # generation and jitted init below are the first device dispatches.
    # Under a warmup gate the child blocks here until the manager
    # releases dispatch.
    from tpu_cc_manager.smoke.runner import await_dispatch_gate

    await_dispatch_gate()
    key = jax.random.PRNGKey(seed)
    prompt = jax.random.randint(key, (batch, prompt_len), 0, cfg.vocab_size)
    with mesh:
        variables = jax.jit(lambda r: nn.unbox(boxed_init(r)), out_shardings=shardings)(key)

        from functools import partial

        from jax import lax

        def prefill(variables, prompt, cache):
            logits, cache = model.apply(variables, prompt, cache=cache, position=0)
            return jnp.argmax(logits[:, -1], axis=-1), cache

        prefill = jax.jit(prefill, donate_argnums=(2,))

        def step(variables, token, cache, position):
            logits, cache = model.apply(
                variables, token[:, None], cache=cache,
                position=position + cache_position_offset,
            )
            return jnp.argmax(logits[:, 0], axis=-1), cache

        # Teacher-forced scan: feed the given tokens, emit each step's argmax.
        @partial(jax.jit, donate_argnums=(2,))
        def teacher_forced(variables, tokens, cache):
            def body(carry, tok):
                cache, pos = carry
                out, cache = step(variables, tok, cache, pos)
                return (cache, pos + 1), out

            (_, _), outs = lax.scan(body, (cache, jnp.int32(0)), tokens.T)
            return outs.T

        # Greedy chain: each step feeds its own argmax forward, the whole
        # loop lives on device. The step count is a TRACED fori_loop bound
        # so every chain length shares one executable (an extra remote
        # compile costs seconds through a tunnelled chip). Only the final
        # token is returned — the timed runs need a sync value, not the
        # transcript. No cache donation: the timed runs below re-use the
        # post-prefill cache across repetitions.
        @jax.jit
        def greedy_decode_n(variables, tok, cache, position, n):
            def body(_, carry):
                tok, cache, pos = carry
                ntok, cache = step(variables, tok, cache, pos)
                return (ntok, cache, pos + 1)

            tok, cache, _ = lax.fori_loop(
                0, n, body, (tok, cache, jnp.int32(position))
            )
            return tok

        # The decode oracles compare the cached path against a no-cache
        # forward. The cached path always uses the einsum attention, while
        # the no-cache path defaults to the flash kernel on TPU — two
        # kernels whose (MXU-precision) logit differences can flip argmax
        # at near-ties. Cache-position correctness must be isolated from
        # kernel choice, so the oracle forwards pin the einsum path; the
        # flash kernel is checked separately below with a numeric
        # tolerance on the logits.
        import dataclasses

        model_ref = LlamaModel(dataclasses.replace(cfg, use_flash=False))

        # Margin-aware argmax agreement: exact argmax equality across two
        # differently-shaped reductions is brittle on TPU — f32 summation
        # order differs between the cached (padded-buffer) and no-cache
        # attention, and a near-tie can flip the argmax with both paths
        # mathematically correct. Accept a produced token when its
        # reference logit is within ``rel_margin`` of the row max: numeric
        # jitter is O(1e-3·scale); a genuine cache/RoPE/mask bug moves
        # logits by O(scale) and still fails (proven by the seeded
        # off-by-one test, tests/test_smoke.py).
        def argmax_agrees(ref_logits, got, rel_margin=1e-2) -> bool:
            scale = jnp.max(jnp.abs(ref_logits))
            top = jnp.max(ref_logits, axis=-1)
            gotv = jnp.take_along_axis(
                ref_logits, got[..., None], axis=-1
            )[..., 0]
            return bool(jnp.all(top - gotv <= rel_margin * scale))

        # --- oracle 1: teacher-forced cached prefix vs no-cache ----------
        oracle_len = min(8, prompt_len)
        full_logits, _ = jax.jit(model_ref.apply)(
            variables, prompt[:, :oracle_len]
        )
        cache = model.init_cache(batch, max_len)
        got = teacher_forced(variables, prompt[:, :oracle_len], cache)
        oracle_ok = argmax_agrees(full_logits, got)

        # --- oracle 2: the WHOLE greedy decode transcript ----------------
        # Decode ``decode_len`` tokens through the cache, then teacher-force
        # the produced transcript through the no-cache forward and demand
        # argmax agreement at EVERY generated position. A cache-position
        # bug past the first few steps (which oracle 1's short prefix would
        # miss) shifts RoPE phases / attention spans and breaks agreement.
        oracle_decode = max(1, min(decode_len, cfg.max_seq_len - prompt_len))
        cache = model.init_cache(batch, prompt_len + oracle_decode)
        tok0, cache = prefill(variables, prompt, cache)

        @partial(jax.jit, donate_argnums=(2,))
        def greedy_transcript(variables, tok, cache, position):
            def body(carry, _):
                tok, cache, pos = carry
                ntok, cache = step(variables, tok, cache, pos)
                return (ntok, cache, pos + 1), ntok

            _, toks = lax.scan(
                body, (tok, cache, jnp.int32(position)), None,
                length=oracle_decode - 1,
            )
            return toks.T  # (batch, oracle_decode - 1)

        if oracle_decode > 1:
            rest = greedy_transcript(variables, tok0, cache, prompt_len)
            gen = jnp.concatenate([tok0[:, None], rest], axis=1)
        else:
            gen = tok0[:, None]
        # Feed prompt + all-but-last generated token; the no-cache argmax
        # from position prompt_len-1 on must reproduce the transcript.
        x = jnp.concatenate([prompt, gen[:, :-1]], axis=1)
        nocache_logits, _ = jax.jit(model_ref.apply)(variables, x)
        transcript_ok = argmax_agrees(nocache_logits[:, prompt_len - 1 :], gen)
        oracle_ok = oracle_ok and transcript_ok

        # --- oracle 3: flash-kernel numeric consistency ------------------
        # When the default no-cache path uses the pallas flash kernel, its
        # logits must agree with the einsum path within MXU precision —
        # a relative tolerance, not argmax equality.
        kernel_rel_err = None
        if cfg.resolved_use_flash():
            flash_logits, _ = jax.jit(model.apply)(variables, x)
            scale = float(jnp.max(jnp.abs(nocache_logits))) + 1e-6
            kernel_rel_err = float(
                jnp.max(jnp.abs(flash_logits - nocache_logits))
            ) / scale
            oracle_ok = oracle_ok and kernel_rel_err < 5e-2

        # --- timed run ---------------------------------------------------
        # Differential timing, as in smoke/matmul.py: median T(hi steps) -
        # median T(lo steps) cancels the constant dispatch + readback
        # overhead (~0.1 s through a tunnelled chip, which would otherwise
        # swamp a short decode), leaving hi-lo steps of pure device time.
        # Sync via a host readback — on the tunnel backend
        # block_until_ready can return before the work is truly retired.
        # The long chain stays within cfg.max_seq_len: positions past the
        # RoPE phase table would silently clamp to the last row.
        import statistics

        hi = min(4 * decode_len, cfg.max_seq_len - prompt_len)
        lo = max(1, hi // 4)
        cache = model.init_cache(batch, prompt_len + hi)
        tok, cache = prefill(variables, prompt, cache)

        def _sync(x):
            return float(jnp.sum(x[:1]))

        def _timed_call(thunk, reps: int = 3) -> float:
            """Warmup + median-of-reps wall time of ``thunk`` (which must
            sync via a host readback — on the tunnel backend
            block_until_ready can return before the work retires). One
            helper for decode AND prefill so the two numbers can never
            follow different timing methodologies."""
            thunk()
            times = []
            for _ in range(reps):
                t0 = time.perf_counter()
                thunk()
                times.append(time.perf_counter() - t0)
            return statistics.median(times)

        def _timed(steps: int) -> float:
            return _timed_call(
                lambda: _sync(
                    greedy_decode_n(variables, tok, cache, prompt_len, steps)
                )
            )

        diff = _timed(hi) - _timed(lo)
        timing_valid = diff > 0 and hi > lo
        per_step = diff / (hi - lo) if timing_valid else None
        dt = per_step * decode_len if timing_valid else None

        # --- prefill throughput ------------------------------------------
        # Decode is bandwidth-bound (every weight read per token); PREFILL
        # is the MXU-bound half of inference — the whole prompt in one
        # batched forward — so its utilization is reported as MFU, the
        # honest denominator for "is the matmul path healthy". Same
        # differential-timing trick: two prompt lengths, the difference
        # cancels dispatch + readback overhead. Lengths are fixed (not the
        # oracle's prompt_len) so the measurement has enough tokens to
        # register against a fast MXU.
        p_hi = min(512, cfg.max_seq_len // 2)
        p_lo = max(16, p_hi // 4)
        prefill_tokens_per_sec = None
        if p_hi > p_lo:
            pf_prompt = jax.random.randint(
                key, (batch, p_hi), 0, cfg.vocab_size
            )

            @jax.jit  # no donation: caches are re-used across timed reps
            def prefill_timed(variables, prompt, cache):
                logits, _ = model.apply(
                    variables, prompt, cache=cache, position=0
                )
                return jnp.argmax(logits[:, -1], axis=-1)

            pf_cache_hi = model.init_cache(batch, p_hi)
            pf_cache_lo = model.init_cache(batch, p_lo)
            pf_short = pf_prompt[:, :p_lo]
            pf_diff = (
                _timed_call(
                    lambda: _sync(prefill_timed(variables, pf_prompt, pf_cache_hi))
                )
                - _timed_call(
                    lambda: _sync(prefill_timed(variables, pf_short, pf_cache_lo))
                )
            )
            if pf_diff > 0:
                prefill_tokens_per_sec = batch * (p_hi - p_lo) / pf_diff

    tokens_per_sec = batch * decode_len / dt if timing_valid else None

    # Utilization accounting. Decode FLOPs/token ≈ 2·params (each weight
    # participates in one MAC per token); MFU on decode is structurally low
    # because the workload is BANDWIDTH-bound — every bf16 weight is read
    # once per step whatever the batch — so the honest utilization metric
    # is HBM bandwidth: bytes/step ≈ 2·params (bf16), vs the public peak
    # (utils/tpu_info.py). Both ride along; only on-TPU numbers are
    # meaningful, so CPU runs report None.
    backend = jax.default_backend()
    generation = generation_for(backend)
    mfu = hbm_util = prefill_mfu = None
    if timing_valid and generation is not None:
        flops_per_sec = 2.0 * cfg.param_count() * tokens_per_sec
        mfu = flops_per_sec / (peak_flops_per_chip() * n_dev)
        # HBM traffic per decode STEP: the full bf16 weight set once
        # (shared by the whole batch) plus each sequence's KV-cache read
        # at its current context length. Counting weights alone (the r4
        # accounting) under-reports traffic — and so over-states the
        # remaining headroom — as batch or context grows; the KV term is
        # what the batch ladder trades against weight amortization.
        steps_per_sec = tokens_per_sec / batch
        weight_bytes = 2.0 * cfg.param_count()
        # The KV read is the FULL ALLOCATED cache, not the logical context:
        # the cache buffer is allocated at prompt_len + hi up front and the
        # padded-buffer attention streams the whole buffer (masked) every
        # step. Counting the logical-midpoint context (the r5 accounting)
        # under-reported traffic and so over-stated remaining headroom;
        # with the allocated length, hbm_bw_util reflects the bytes the
        # HBM actually moves (useful-traffic utilization is bounded above
        # by it).
        #
        # Headroom semantics for consumers (the serve/ batch ladder reads
        # this number): hbm_bw_util models ONLY the weight + KV streams,
        # so it is a lower bound on the bandwidth the chip actually
        # achieves (activations, logits and any re-reads ride on top) —
        # a ladder treating (ceiling − hbm_bw_util) as headroom must keep
        # its ceiling below 1.0. And because each sequence is charged its
        # full allocated, padded+masked buffer rather than its logical
        # context, the modeled marginal cost of one more sequence is the
        # worst case — the ladder's per-step headroom read is explicitly
        # conservative, never optimistic.
        alloc_ctx = prompt_len + hi
        kv_bytes_per_seq = (
            cfg.n_layers * 2 * cfg.n_kv_heads * cfg.head_dim * alloc_ctx * 2.0
        )
        bytes_per_sec = steps_per_sec * (
            weight_bytes + batch * kv_bytes_per_seq
        )
        hbm_util = bytes_per_sec / (peak_hbm_bytes_per_chip() * n_dev)
    if prefill_tokens_per_sec is not None and generation is not None:
        prefill_mfu = (
            2.0 * cfg.param_count() * prefill_tokens_per_sec
            / (peak_flops_per_chip() * n_dev)
        )
    return {
        "ok": oracle_ok,
        "workload": "llama",
        "model": size,
        "backend": backend,
        "generation": generation,
        "devices": n_dev,
        "params": cfg.param_count(),
        "batch": batch,
        "decode_len": decode_len,
        "timing_valid": bool(timing_valid),
        "tokens_per_sec": round(tokens_per_sec, 2) if timing_valid else None,
        "ms_per_token": round(1e3 * dt / decode_len, 3) if timing_valid else None,
        "mfu": round(mfu, 4) if mfu is not None else None,
        "hbm_bw_util": round(hbm_util, 4) if hbm_util is not None else None,
        # How the KV term was counted, recorded in the artifact so ladder
        # rows from different accounting eras can't be compared blindly.
        "hbm_bw_accounting": "weights+allocated-kv",
        # hbm_bw_util models only the weight+KV streams over the full
        # allocated (padded+masked) cache: a useful-traffic LOWER bound
        # on achieved bandwidth — batch ladders reading it as headroom
        # are conservative by construction (see the accounting comment).
        "hbm_bw_util_lower_bound": True,
        "prefill_tokens_per_sec": (
            round(prefill_tokens_per_sec, 2)
            if prefill_tokens_per_sec is not None else None
        ),
        "prefill_mfu": (
            round(prefill_mfu, 4) if prefill_mfu is not None else None
        ),
        "oracle_ok": oracle_ok,
        "transcript_ok": transcript_ok,
        "transcript_positions": int(oracle_decode),
        "flash_kernel_rel_err": (
            round(kernel_rel_err, 6) if kernel_rel_err is not None else None
        ),
    }


if __name__ == "__main__":
    import json

    print(json.dumps(run()))
