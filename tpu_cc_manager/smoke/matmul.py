"""Matmul smoke workload: prove the slice multiplies correctly and fast.

BASELINE.json configs[1] ("libtpu CC toggle + JAX matmul smoke test").
TPU-first design notes:

- bf16 operands, f32 accumulation (``preferred_element_type``) — the MXU's
  native contraction;
- square tiles sized to keep the MXU busy (4096 on accelerators, small on
  CPU test runs);
- sharded over all visible devices with a 1-D mesh so the same code
  exercises 1 chip or a full slice (collectives ride ICI via XLA);
- numerics oracle: a deterministic low-rank construction whose product is
  known in closed form, checked with bf16-appropriate tolerance, plus a
  f64-free checksum — no host-side reference matmul at full size.
"""

from __future__ import annotations

import time
from functools import partial


def run(size: int | None = None, iters: int | None = None, seed: int = 0,
        kernel: str = "xla", blocks: tuple[int, int, int] | None = None) -> dict:
    """kernel='xla' uses jnp.matmul (stock compiler); kernel='pallas' uses
    the Mosaic tiled kernel (ops/matmul.py) — single-device only, used to
    prove custom-kernel compilation works on a reconfigured slice.
    ``blocks`` overrides the pallas (block_m, block_n, block_k) tiling for
    one-command on-chip tuning sweeps."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    devices = jax.devices()
    backend = jax.default_backend()
    if kernel == "pallas":
        devices = devices[:1]  # the Mosaic kernel is single-device
    if size is None:
        size = 4096 if backend == "tpu" else 256
    if iters is None:
        # Long enough that the T(4N)-T(N) differential (~3N iters of device
        # time) dwarfs dispatch/readback jitter — short chains can report
        # above-peak TFLOPs on a noisy transport.
        iters = 64 if backend == "tpu" else 4
    # Round to a multiple of (128 * device count) — keeps every shard aligned
    # to the MXU/VPU lane width after sharding.
    n_dev = len(devices)
    size = max(128 * n_dev, (size // (128 * n_dev)) * (128 * n_dev))

    mesh = Mesh(devices, ("x",))
    row_sharding = NamedSharding(mesh, P("x", None))
    repl = NamedSharding(mesh, P())

    # Operands are generated ON device, inlined into each program (two
    # compiled programs total): a host-side random.normal + device_put would
    # push 2×size² bf16 through the (possibly tunnelled) host↔device link,
    # and a separate generator program would be a third remote compile —
    # each costs seconds through a tunnel. Regenerating per call costs ~one
    # chain iteration.
    def gen_operands(key):
        k1, k2 = jax.random.split(key)
        a = jax.random.normal(k1, (size, size), dtype=jnp.bfloat16)
        b = jax.random.normal(k2, (size, size), dtype=jnp.bfloat16)
        return a, b

    # One product definition shared by the numerics path and the timed
    # chain, so kernel dispatch and block sizing can't diverge.
    if kernel == "pallas":
        from tpu_cc_manager.ops.matmul import default_blocks, tiled_matmul

        if blocks is None:
            # The measured per-generation table (ops/matmul.py), clamped
            # to divide this problem size.
            from tpu_cc_manager.utils.tpu_info import generation_for

            blocks = default_blocks(generation_for(backend), size)
        from tpu_cc_manager.smoke.runner import SmokeConfigError

        if any(b < 1 for b in blocks):
            raise SmokeConfigError(f"pallas blocks {blocks} must be positive")
        # Clamp to the (rounded) problem size — tiled_matmul does the same,
        # and the result JSON must report the EFFECTIVE tiling or a sweep
        # comparing clamped configs would mislabel identical kernels.
        blocks = tuple(min(b, size) for b in blocks)
        bm, bn, bk = blocks
        if size % bm or size % bn or size % bk:
            raise SmokeConfigError(
                f"pallas blocks {blocks} must divide the problem size {size}"
            )

        def product(x, y):
            return tiled_matmul(x, y, block_m=bm, block_n=bn, block_k=bk)

    else:

        def product(x, y):
            return jnp.matmul(x, y, preferred_element_type=jnp.float32)

    # Timed loop: dependency-chained inside ONE jitted fori_loop so the
    # iterations are provably sequential on-device — independent identical
    # dispatches can overlap (or be elided) in an async stream and report
    # impossible TFLOP/s. The per-iter renormalisation keeps bf16 from
    # overflowing across the chain and costs O(n²) against the O(n³) matmul.
    from jax import lax

    # `iters` is a TRACED argument (fori_loop lowers to while_loop), so one
    # compiled program serves every chain length — on a tunnelled device each
    # extra remote compile costs seconds, dwarfing the while- vs scan-loop
    # difference for 4096³ matmul bodies.
    @partial(jax.jit, out_shardings=row_sharding)
    def mm_chain(key, iters):
        a, b = gen_operands(key)
        a = jax.lax.with_sharding_constraint(a, row_sharding)
        b = jax.lax.with_sharding_constraint(b, repl)
        # Barrier: without it XLA can recompute the RNG inside different
        # fusions, one consumer seeing pre-bf16-rounding values — the
        # identity oracle would then compare two different "a"s.
        a, b = jax.lax.optimization_barrier((a, b))

        def body(_, acc):
            # Constant renorm: rows of acc@b grow by ~sqrt(n) for unit
            # Gaussian operands, so a fixed 1/sqrt(n) keeps the chain
            # bounded without a max-reduction (fuses into the matmul).
            prod = product(acc, b)
            return (prod * jnp.float32(1.0 / size**0.5)).astype(jnp.bfloat16)

        return lax.fori_loop(0, iters, body, a)

    # Sync via a host readback of a scalar that depends on the whole result:
    # on the tunnel backend block_until_ready can return before the work is
    # truly retired, but a device→host value cannot exist early.
    def _sync(x):
        return float(jnp.sum(x[:1, :1]))

    # Differential timing: median T(4N) - median T(N) cancels the constant
    # dispatch + readback overhead (tens of ms of RTT through a tunnelled
    # device, and noisy), leaving 3N iters of pure device time.
    import statistics

    def _timed(n: int, reps: int = 3) -> float:
        _sync(mm_chain(key, n))  # compile + warm
        times = []
        for _ in range(reps):
            t0 = time.perf_counter()
            _sync(mm_chain(key, n))
            times.append(time.perf_counter() - t0)
        return statistics.median(times)

    # COMPILE→DISPATCH boundary: everything above is host-side build
    # (client init + tracing, no computation dispatched); everything
    # below executes on the device. Under a warmup gate
    # (CC_SMOKE_DISPATCH_GATE, set by the manager while wait_ready runs)
    # the AOT compile of the timed chain happens NOW — overlapped with
    # the runtime boot, from an ABSTRACT key so nothing dispatches — and
    # execution blocks until the manager releases dispatch (runtime
    # ready + attestation passed). Without the gate this is a no-op.
    from tpu_cc_manager.smoke.runner import await_dispatch_gate

    key_aval = jax.eval_shape(lambda: jax.random.PRNGKey(seed))
    await_dispatch_gate(compile_fns=(
        lambda: mm_chain.lower(key_aval, iters).compile(),
    ))
    key = jax.random.PRNGKey(seed)

    diff = _timed(4 * iters, reps=5) - _timed(iters, reps=5)
    # A non-positive differential means overhead variance swamped 3N iters
    # of device time: the numerics verdict stands, but the throughput
    # measurement is invalid and must not be reported as a number.
    timing_valid = diff > 0
    dt = diff / (3 * iters) if timing_valid else None
    tflops = 2 * size**3 / dt / 1e12 if timing_valid else None
    mfu = None
    if timing_valid and backend == "tpu":
        from tpu_cc_manager.utils.tpu_info import peak_flops_per_chip

        mfu = round(tflops * 1e12 / (peak_flops_per_chip() * n_dev), 4)

    # Numerics: identity sanity (A @ I == A within bf16 cast error) plus a
    # row-sum cross-check of the product under test: (A·B) @ 1 == A @ (B @ 1).
    # One fused jitted program: the product, the on-device identity matrix
    # (no size² host transfer), and all three checks come back as scalars in
    # a single dispatch instead of ~eight op-by-op round trips.
    @jax.jit
    def numerics(key):
        a, b = gen_operands(key)
        a = jax.lax.with_sharding_constraint(a, row_sharding)
        b = jax.lax.with_sharding_constraint(b, repl)
        # Barrier: without it XLA can recompute the RNG inside different
        # fusions, one consumer seeing pre-bf16-rounding values — the
        # identity oracle would then compare two different "a"s.
        a, b = jax.lax.optimization_barrier((a, b))
        out = product(a, b)
        eye = jnp.eye(size, dtype=jnp.bfloat16)
        ident_err = jnp.max(jnp.abs(product(a, eye) - a.astype(jnp.float32)))
        ones = jnp.ones((size, 1), dtype=jnp.float32)
        lhs = jnp.matmul(out, ones)
        rhs = jnp.matmul(
            a.astype(jnp.float32), jnp.matmul(b.astype(jnp.float32), ones)
        )
        scale = jnp.max(jnp.abs(rhs))
        return ident_err, jnp.max(jnp.abs(lhs - rhs)), scale

    ident_err_v, rowsum_err_v, scale_v = numerics(key)
    ident_err = float(ident_err_v)
    rowsum_rel_err = float(rowsum_err_v) / (float(scale_v) + 1e-6)
    # bf16 has ~8 mantissa bits; row-sum of `size` products loses a few more.
    ok = ident_err <= 1e-6 and rowsum_rel_err <= 2e-2

    from tpu_cc_manager.utils.tpu_info import generation_for

    return {
        "ok": bool(ok),
        "workload": "matmul",
        "kernel": kernel,
        "blocks": list(blocks) if kernel == "pallas" else None,
        "backend": backend,
        "generation": generation_for(backend),
        "devices": n_dev,
        "size": size,
        "timing_valid": bool(timing_valid),
        "seconds_per_iter": dt,
        "tflops": round(tflops, 2) if tflops is not None else None,
        "mfu": mfu,
        "ident_err": ident_err,
        "rowsum_rel_err": rowsum_rel_err,
    }


if __name__ == "__main__":
    import json

    print(json.dumps(run()))
